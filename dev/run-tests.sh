#!/usr/bin/env bash
# Test runner (ref pyzoo/dev/run-pytests: suite sharding per heavy
# dependency set). One env here — jax+torch coexist — so sharding is by
# subsystem for parallel CI lanes and fail isolation; every lane runs on
# the virtual 8-device CPU mesh (tests/conftest.py).
#
#   dev/run-tests.sh              # everything
#   dev/run-tests.sh core         # one lane
#   dev/run-tests.sh smoke        # fast pre-push subset (<5 min, 1 core)
#   Lanes: smoke core data keras models zouwu automl serving interop
#          examples telemetry fleet resilience zoolint kernels chaos
#          scheduling sharded decode observability
set -euo pipefail
cd "$(dirname "$0")/.."

lane="${1:-all}"

run() { echo "== pytest $*"; python -m pytest -q "$@"; }

# zoolint: AST-based static analysis (docs/zoolint.md) — hot-path
# wall-clock/sync, jit recompile hazards, unlocked cross-thread writes,
# metric/env-var catalog drift. Replaces the old time.time() grep: the
# shipped tree must be clean (modulo dev/zoolint-baseline.json and
# inline "# zoolint: disable=RULE"), and the seeded-violation fixture
# must FAIL — a passing fixture means the linter itself regressed.
lint_zoolint() {
  echo "== zoolint: analytics_zoo_tpu (interprocedural + dataflow passes)"
  python -m analytics_zoo_tpu.analysis analytics_zoo_tpu --timing
  echo "== zoolint: stale-baseline check (warning only)"
  python -m analytics_zoo_tpu.analysis analytics_zoo_tpu --prune-baseline
  echo "== zoolint: seeded-violation fixture (must fail)"
  if fixture_out="$(python -m analytics_zoo_tpu.analysis --no-baseline \
       tests/fixtures/zoolint 2>&1)"; then
    echo "zoolint passed the seeded-violation fixture — linter regressed" >&2
    exit 1
  fi
  # every whole-program / path-sensitive rule must fire on its seeded
  # fixture by id — a non-zero exit from the per-file rules alone is
  # not good enough
  for rule in cross-thread-unlocked-state lock-order-inversion \
              blocking-under-lock thread-leak \
              record-ack-leak lock-release-path span-pairing \
              tainted-host-sync shape-dependent-branch-in-jit \
              kv-page-leak; do
    if ! grep -q "$rule" <<<"$fixture_out"; then
      echo "zoolint fixture never tripped $rule — rule regressed" >&2
      exit 1
    fi
  done
  # the workflow-annotation format must carry the new findings too
  gh_out="$(python -m analytics_zoo_tpu.analysis --no-baseline \
       --format=github tests/fixtures/zoolint 2>&1 || true)"
  for rule in record-ack-leak tainted-host-sync; do
    if ! grep -q "^::error .*$rule" <<<"$gh_out"; then
      echo "zoolint --format=github lost the $rule annotation" >&2
      exit 1
    fi
  done
  echo "== zoolint: docs/concurrency.md drift check"
  owndir="$(mktemp -d)"
  python -m analytics_zoo_tpu.analysis analytics_zoo_tpu \
    --ownership-report "$owndir/concurrency.md" >/dev/null
  if ! diff -q docs/concurrency.md "$owndir/concurrency.md" >/dev/null || \
     ! diff -q docs/concurrency.json "$owndir/concurrency.json" >/dev/null; then
    echo "docs/concurrency.md is stale — regenerate with:" >&2
    echo "  python -m analytics_zoo_tpu.analysis analytics_zoo_tpu \\" >&2
    echo "    --ownership-report docs/concurrency.md" >&2
    exit 1
  fi
  rm -rf "$owndir"
}

case "$lane" in
  lint)     lint_zoolint ;;
  zoolint)  lint_zoolint ;;
  # fast cross-subsystem sweep for the edit loop: serving end-to-end,
  # the dispatch pipeline, estimator, inference + quantize, attention
  # ops — everything marked slow stays out
  smoke)    lint_zoolint
            run -m "not slow" tests/test_pipeline_io.py \
                tests/test_serving.py tests/test_inference_net.py \
                tests/test_estimator.py tests/test_attention.py ;;
  core)     run tests/test_context.py tests/test_estimator.py \
                tests/test_estimator_edge.py tests/test_estimator_factories.py \
                tests/test_attention.py tests/test_pipeline.py tests/test_moe.py ;;
  # data plane (ISSUE 12): pooled shard executor, vectorized Friesian
  # kernels with bitwise legacy parity, tiered bounded-residency
  # pipeline, streaming prefetch — then a tiny recsys pipeline measure
  # gating the never-slower transform dispatch (docs/data_plane.md)
  data)     run tests/test_data.py tests/test_native_store.py \
                tests/test_feature.py tests/test_friesian.py \
                tests/test_friesian_parity.py tests/test_data_plane.py \
                tests/test_image3d_parquet.py tests/test_elastic_search.py \
                tests/test_tfrecord.py
            echo "== recsys pipeline smoke (never-slower transform dispatch)"
            JAX_PLATFORMS=cpu python - <<'PY'
import bench
bench.RECSYS_ROWS, bench.RECSYS_SHARDS = 1500, 4
bench.RECSYS_USERS, bench.RECSYS_ITEMS = 60, 40
bench.RECSYS_BATCH = 128
out = bench.measure_recsys_pipeline()
assert out["recsys_pipeline_samples_per_sec"] > 0, out
assert out["friesian_transform_speedup"] >= 1.0, out
print(f"recsys OK: {out['recsys_pipeline_samples_per_sec']} samples/s "
      f"(data included), transform speedup "
      f"{out['friesian_transform_speedup']}x "
      f"[{out['recsys_transform_mode']}]")
PY
            ;;
  keras)    run tests/test_keras.py tests/test_keras_layers_golden.py \
                tests/test_keras2_multihost.py tests/test_nnframes_autograd.py ;;
  models)   run tests/test_model_zoo.py tests/test_recommendation.py \
                tests/test_text_bert.py tests/test_gan.py ;;
  zouwu)    run tests/test_zouwu.py tests/test_autots.py \
                tests/test_stats_forecast.py ;;
  automl)   run tests/test_automl.py ;;
  serving)  run tests/test_serving.py tests/test_inference_net.py \
                tests/test_onnx.py tests/test_openvino.py \
                tests/test_encryption.py ;;
  interop)  run tests/test_inference_net.py tests/test_onnx.py \
                tests/test_openvino.py ;;
  examples) run tests/test_examples.py ;;
  # observability: unit tests, then an armed bench smoke that must leave
  # a flight-recorder postmortem (the dump path CI would rely on after a
  # wedged TPU round is exercised on every lane run, not just on wedges)
  telemetry) lint_zoolint
            run -m "not slow" tests/test_telemetry.py tests/test_profiling.py
            echo "== bench --smoke telemetry (flight recorder armed)"
            frdir="$(mktemp -d)"
            ZOO_FLIGHT_RECORDER=1 ZOO_FLIGHT_RECORDER_DIR="$frdir" \
              JAX_PLATFORMS=cpu python bench.py --smoke telemetry \
              > "$frdir/smoke.json"
            python - "$frdir" <<'PY'
import glob, json, sys
frdir = sys.argv[1]
rec = json.load(open(frdir + "/smoke.json"))
assert rec["mode"] == "smoke" and "telemetry" in rec, rec.keys()
assert "bench_regression" in rec, "regression gate missing from record"
dumps = glob.glob(frdir + "/flightrec_*.json")
assert dumps, "armed smoke left no flight-recorder dump"
d = json.load(open(dumps[0]))
assert d["kind"] == "zoo_flight_recorder" and d["spans"], d.get("kind")
assert rec.get("flight_recorder") in dumps, "record does not point at dump"
# compile-ahead serve path (ISSUE 5): after the ladder warmup the burst
# must cross at least one bucket-growth boundary with ZERO recompiles —
# a stall-free swap onto an already-AOT-compiled rung
assert rec.get("serving_post_warmup_recompiles") == 0, \
    f"serve path recompiled after warmup: {rec.get('serving_post_warmup_recompiles')}"
assert rec.get("serving_bucket_growth", 0) >= 1, \
    f"burst never crossed a bucket boundary: {rec.get('serving_bucket_growth')}"
assert rec.get("serving_cold_start_seconds", -1) >= 0, \
    "cold-start metric missing from smoke record"
print(f"flight recorder OK: {len(d['spans'])} spans in {dumps[0]}")
print(f"compile-ahead OK: growth={rec['serving_bucket_growth']} "
      f"recompiles=0 cold_start={rec['serving_cold_start_seconds']}s")
PY
            ;;
  # pallas kernels + autotuner (ISSUE 8): flash/embedding-bag parity on
  # the CPU interpreter, then a smoke proving the autotune dispatch NEVER
  # picks a config slower than the numerics-reference fallback — the
  # invariant that turns a kernel regression into a fallback, not a perf
  # bug (lint first: new kernels must be zoolint-clean, and the catalog
  # cross-check must know the zoo_autotune_* metrics)
  kernels)  lint_zoolint
            run -m "not slow" tests/test_autotune.py \
                tests/test_embedding_bag.py tests/test_attention.py \
                tests/test_paged_attention.py
            echo "== autotune never-slower smoke"
            JAX_PLATFORMS=cpu ZOO_PALLAS_INTERPRET=1 python - <<'PY'
import os, tempfile
os.environ["ZOO_AUTOTUNE_CACHE"] = os.path.join(tempfile.mkdtemp(),
                                                "autotune.json")
os.environ["ZOO_AUTOTUNE_ITERS"] = "2"
import jax.numpy as jnp
from analytics_zoo_tpu.ops import autotune
rec = autotune.tune_attention(1, 64, 2, 64, dtype=jnp.float32,
                              causal=True)
assert rec["best"] is not None, rec["errors"]
# the dispatch invariant: the kernel only engages when its measured time
# BEAT the blockwise reference — use_kernel=True with best>=reference
# would mean the autotuner can select a slower config
if rec["use_kernel"]:
    assert rec["best_ms"] < rec["reference_ms"], rec
else:
    assert rec["best_ms"] >= rec["reference_ms"], rec
print(f"autotune OK: best={rec['best']} {rec['best_ms']}ms "
      f"ref={rec['reference_ms']}ms use_kernel={rec['use_kernel']}")
PY
            ;;
  # fleet observability (ISSUE 6): snapshot merge algebra, replica
  # registry + SLO burn units, and the two-replica federation smoke
  # (subprocess engines, one broker, merged /metrics?scope=fleet). The
  # seeded race fixture must trip the whole-program ownership rule: a
  # heartbeater-style helper-method write the per-file rule can't see.
  fleet)    run -m "not slow" tests/test_fleet.py
            echo "== zoolint: seeded heartbeater race must fire"
            drift="$(python -m analytics_zoo_tpu.analysis --no-baseline \
                       tests/fixtures/zoolint 2>&1 || true)"
            if ! grep "cross-thread-unlocked-state" <<<"$drift" | \
                 grep -q "fleet/bad_shared_state.py"; then
              echo "ownership rule missed the seeded heartbeater race" >&2
              exit 1
            fi
            ;;
  # wedge resilience (ISSUE 7): fault injector, backend supervisor,
  # checkpoint fallback, fit auto-resume, serving failover — then an
  # armed bench smoke whose built-in wedge drill must leave a
  # backend-wedged postmortem AND a completed CPU failover on the record
  resilience) run -m "not slow" tests/test_resilience.py
            echo "== bench --smoke resilience (wedge drill armed)"
            frdir="$(mktemp -d)"
            ZOO_FLIGHT_RECORDER=1 ZOO_FLIGHT_RECORDER_DIR="$frdir" \
              JAX_PLATFORMS=cpu python bench.py --smoke resilience \
              > "$frdir/smoke.json"
            python - "$frdir" <<'PY'
import glob, json, sys
frdir = sys.argv[1]
rec = json.load(open(frdir + "/smoke.json"))
assert rec["mode"] == "smoke", rec.keys()
# the drill's wedge completed a measured failover: every record served,
# drain->first-CPU-result latency on the (lower-better-gated) record
assert rec.get("serving_failover_seconds", -1) >= 0, \
    f"no completed failover on record: {rec.get('serving_failover_seconds')}"
assert rec.get("serving_failover_episodes", 0) >= 1, \
    "supervisor never entered wedged during the drill"
# the supervisor wedge verdict left exactly one latched postmortem
dumps = [p for p in glob.glob(frdir + "/flightrec_*.json")
         if json.load(open(p)).get("reason") == "backend-wedged"]
assert len(dumps) == 1, f"expected 1 backend-wedged dump, got {len(dumps)}"
print(f"failover OK: {rec['serving_failover_seconds']}s "
      f"episodes={rec['serving_failover_episodes']} dump={dumps[0]}")
PY
            ;;
  # multi-replica delivery contract (ISSUE 9): lease/XCLAIM semantics on
  # both broker backends, client reconnect retry, orphan detection, and
  # the 2-replica SIGKILL chaos drill (slow-marked, runs here) — then a
  # bench smoke gating the scaling floor and replica-kill failover. The
  # seeded zoolint fixture must flag an undeclared zoo_serving_* family:
  # a quiet drift check on the new delivery metrics means the linter
  # regressed, not that the tree is clean.
  chaos)    run tests/test_multi_replica.py
            echo "== zoolint: drift must flag undeclared zoo_serving_* names"
            drift="$(python -m analytics_zoo_tpu.analysis --no-baseline \
                       tests/fixtures/zoolint 2>&1 || true)"
            if ! grep -q "zoo_serving_redelivered_bogus_total" <<<"$drift"; then
              echo "catalog drift missed the seeded zoo_serving_* violation" >&2
              exit 1
            fi
            # the chaos drills' kill paths hang on leaked non-daemon
            # threads — the seeded leak must trip the lifecycle rule
            if ! grep "thread-leak" <<<"$drift" | \
                 grep -q "chaos/bad_thread_leak.py"; then
              echo "zoolint missed the seeded non-daemon thread leak" >&2
              exit 1
            fi
            echo "== bench --smoke chaos (replica-kill drill + scaling floor)"
            outdir="$(mktemp -d)"
            ZOO_FLIGHT_RECORDER_DIR="$outdir" \
              JAX_PLATFORMS=cpu python bench.py --smoke chaos \
              > "$outdir/smoke.json"
            python - "$outdir" <<'PY'
import json, sys
rec = json.load(open(sys.argv[1] + "/smoke.json"))
assert rec["mode"] == "smoke", rec.keys()
# consumer-group fan-out really scales: 2 replicas on one stream must
# beat one by the acceptance floor (sleep-dominated duck model, so the
# ratio is host-independent)
scaling = rec.get("serving_replica_scaling", 0.0)
assert scaling >= 1.5, f"2-replica scaling below floor: {scaling}"
# the SIGKILL drill completed: zero loss is asserted inside the measure;
# the record must carry the (lower-better-gated) failover latency and a
# visible redelivery in exactly one reclaim sweep
fo = rec.get("serving_replica_failover_seconds", -1)
assert fo >= 0, f"no completed replica-kill failover on record: {fo}"
assert rec.get("serving_replica_kill_redelivered", 0) >= 1, \
    "kill drill recorded no redelivery"
assert rec.get("serving_replica_lease_reclaims", 0) == 1, \
    f"expected one reclaim sweep: {rec.get('serving_replica_lease_reclaims')}"
print(f"chaos OK: scaling={scaling} failover={fo}s "
      f"redelivered={rec['serving_replica_kill_redelivered']} "
      f"sweeps={rec['serving_replica_lease_reclaims']}")
PY
            ;;
  # SLO-aware continuous batching (ISSUE 10): priority lanes on both
  # broker backends, weighted-deficit scheduling, deadline expiry,
  # admission control, the lane/lease SIGKILL drill (slow-marked, runs
  # here) — then a mixed-traffic bench smoke gating interactive p99
  # under a batch-lane flood. The seeded zoolint fixture must flag an
  # undeclared per-lane metric: a quiet drift check on the scheduling
  # metrics means the linter regressed, not that the tree is clean.
  scheduling) run tests/test_priority.py
            echo "== zoolint: drift must flag undeclared lane metrics/knobs"
            drift="$(python -m analytics_zoo_tpu.analysis --no-baseline \
                       tests/fixtures/zoolint 2>&1 || true)"
            if ! grep -q "zoo_serving_lane_depth_bogus" <<<"$drift"; then
              echo "catalog drift missed the seeded per-lane metric" >&2
              exit 1
            fi
            if ! grep -q "ZOO_SERVING_MAX_WAIT_BOGUS_MS" <<<"$drift"; then
              echo "catalog drift missed the seeded scheduling env var" >&2
              exit 1
            fi
            # a scheduler sleeping under a contended lock stalls every
            # submitter; a cross-file ABBA pair deadlocks under load —
            # both seeded races must trip the whole-program lock rules
            if ! grep "blocking-under-lock" <<<"$drift" | \
                 grep -q "scheduling/bad_blocking.py"; then
              echo "zoolint missed the seeded sleep-under-lock" >&2
              exit 1
            fi
            if ! grep "lock-order-inversion" <<<"$drift" | \
                 grep -q "scheduling/"; then
              echo "zoolint missed the seeded cross-file lock inversion" >&2
              exit 1
            fi
            echo "== bench --smoke scheduling (batch-lane flood drill)"
            outdir="$(mktemp -d)"
            ZOO_FLIGHT_RECORDER_DIR="$outdir" \
              JAX_PLATFORMS=cpu python bench.py --smoke scheduling \
              > "$outdir/smoke.json"
            python - "$outdir" <<'PY'
import json, sys
rec = json.load(open(sys.argv[1] + "/smoke.json"))
assert rec["mode"] == "smoke", rec.keys()
# interactive p99 stayed within budget while the batch lane was flooded
# (zero loss + zero expiries are asserted inside the measure)
p99 = rec.get("serving_p99_interactive_ms", -1)
budget = rec.get("serving_interactive_budget_ms", 0)
assert 0 <= p99 <= budget, \
    f"interactive p99 {p99}ms blew the {budget}ms budget under flood"
rps = rec.get("serving_priority_records_per_sec", 0)
assert rps > 0, "mixed-traffic drill recorded no throughput"
assert rec.get("serving_priority_flood_records", 0) > 0, \
    "drill ran without a batch-lane flood"
print(f"scheduling OK: interactive p99={p99}ms (budget {budget}ms) "
      f"mixed throughput={rps} rec/s")
PY
            ;;
  # sharded executor seam + bucketed decode (ISSUE 14): dispatch
  # equivalence and recompile-flat warm rungs on the forced 8-device
  # mesh, bitwise rung-padding parity, the end-to-end generate flow —
  # then the sharded/decode bench measures at smoke size. The seeded
  # zoolint fixture must flag undeclared zoo_shard_* / zoo_decode_*
  # names: a quiet drift check on the new families means the linter
  # regressed, not that the tree is clean.
  sharded)  XLA_FLAGS="--xla_force_host_platform_device_count=8" \
              run -m "not slow" tests/test_generation.py \
              tests/test_sharded_serving.py
            echo "== zoolint: drift must flag undeclared shard/decode names"
            drift="$(python -m analytics_zoo_tpu.analysis --no-baseline \
                       tests/fixtures/zoolint 2>&1 || true)"
            for name in zoo_shard_hbm_bogus_bytes \
                        zoo_decode_steps_bogus_total \
                        ZOO_SERVING_DECODE_BOGUS_SEQ; do
              if ! grep -q "$name" <<<"$drift"; then
                echo "catalog drift missed the seeded $name violation" >&2
                exit 1
              fi
            done
            echo "== bench sharded/decode smoke (8 forced host devices)"
            JAX_PLATFORMS=cpu \
              XLA_FLAGS="--xla_force_host_platform_device_count=8" \
              python - <<'PY'
import bench
bench.SERVE_BATCH, bench.SERVE_HIDDEN = 8, 32
bench.DECODE_BATCH, bench.DECODE_STEPS, bench.DECODE_HIDDEN = 4, 8, 16
sh = bench.measure_serving_sharded()
# the tentpole's proof obligations: every device carries a strict
# fraction of the model, and a post-warmup burst crossing a bucket
# growth boundary never recompiles
assert sh.get("serving_sharded_n_shards") == 8, sh
assert 0 < sh["serving_sharded_max_shard_fraction"] < 1.0, sh
assert sh["serving_sharded_post_warmup_recompiles"] == 0, sh
assert sh["serving_sharded_bucket_growth"] >= 1, sh
assert sh["serving_sharded_records_per_sec"] > 0, sh
dec = bench.measure_decode()
assert dec["decode_tokens_per_sec"] > 0, dec
assert dec["decode_post_warmup_recompiles"] == 0, dec
print(f"sharded OK: {sh['serving_sharded_records_per_sec']} rec/s "
      f"max_shard_fraction={sh['serving_sharded_max_shard_fraction']} "
      f"growth={sh['serving_sharded_bucket_growth']} recompiles=0")
print(f"decode OK: {dec['decode_tokens_per_sec']} tok/s "
      f"p99={dec['decode_p99_ms']}ms recompiles=0")
PY
            ;;
  # step-level continuous batching + paged KV + speculative decode
  # (ISSUE 16): scheduler parity/spec units, the sampling contract, the
  # kv-page-leak dataflow rule — the seeded allocator leaks must fire by
  # file — then a bench smoke gating the interleaved-streams speedup,
  # the self-draft accept ratio at exactly 1.0, and interactive p99
  # under a live decode flood.
  decode)   run -m "not slow" tests/test_decode_scheduler.py \
                tests/test_generation.py tests/test_zoolint_dataflow.py
            echo "== zoolint: seeded kv page leaks must fire"
            drift="$(python -m analytics_zoo_tpu.analysis --no-baseline \
                       tests/fixtures/zoolint 2>&1 || true)"
            if [ "$(grep "kv-page-leak" <<<"$drift" | \
                    grep -c "serving/bad_kv_page_leak.py")" -ne 2 ]; then
              echo "zoolint missed a seeded kv page leak" >&2
              exit 1
            fi
            # the paged-table fixture holds exactly ONE leak (the guard
            # raise) — its clean twin must stay silent
            if [ "$(grep "kv-page-leak" <<<"$drift" | \
                    grep -c "serving/bad_paged_table_leak.py")" -ne 1 ]; then
              echo "zoolint missed the seeded paged-table leak" >&2
              exit 1
            fi
            echo "== zoolint: drift must flag undeclared paged/kv names"
            for name in zoo_paged_attn_bogus_total zoo_kv_quant_bogus_bytes \
                        ZOO_KV_BOGUS_DTYPE; do
              if ! grep -q "$name" <<<"$drift"; then
                echo "catalog drift missed the seeded $name violation" >&2
                exit 1
              fi
            done
            echo "== bench decode smoke (continuous batching + spec + mixed)"
            JAX_PLATFORMS=cpu python - <<'PY'
import bench
bench.DECODE_BATCH, bench.DECODE_STEPS, bench.DECODE_HIDDEN = 4, 8, 16
bench.MIXED_FLOOD, bench.MIXED_INT, bench.MIXED_STEPS = 6, 6, 8
dec = bench.measure_decode()
# interleaving N streams through one scheduler must beat draining them
# serially (both run the same warmed executables — the delta is pure
# step-sharing), and the self-drafted speculative pass accepts every
# token (bitwise identity vs plain greedy is asserted inside)
assert dec["decode_concurrent_speedup"] >= 1.0, dec
assert dec["decode_spec_accept_ratio"] == 1.0, dec
assert dec["decode_post_warmup_recompiles"] == 0, dec
# the paged-attention verdict is never-slower by construction (a losing
# measurement dispatches the gather fallback and reports 1.0), and the
# paged run's outputs are asserted bitwise against plain decode inside
assert dec["decode_paged_attn_speedup"] >= 1.0, dec
assert dec["decode_kv_bytes_per_seq"] > 0, dec
mix = bench.measure_decode_mixed()
p99, budget = (mix["decode_mixed_interactive_p99_ms"],
               mix["decode_mixed_interactive_budget_ms"])
assert 0 <= p99 <= budget, mix
print(f"decode OK: concurrent speedup "
      f"{dec['decode_concurrent_speedup']}x "
      f"accept_ratio={dec['decode_spec_accept_ratio']} "
      f"paged={dec['decode_paged_attn_speedup']}x "
      f"kv_bytes/seq={dec['decode_kv_bytes_per_seq']}")
print(f"mixed OK: interactive p99={p99}ms (budget {budget}ms) "
      f"preemptions={mix['decode_mixed_preemptions_total']}")
PY
            ;;
  # metric history + cost attribution (ISSUE 17): the windowed store's
  # quantile/rate algebra, exemplar->/trace links, fleet window merge,
  # the end-to-end cost drill (slow-marked, runs here) — then the bench
  # history drill scraping /metrics/history mid-flood. The seeded
  # zoolint fixture must flag an undeclared zoo_ts_* name: a quiet
  # drift check on the new families means the linter regressed.
  observability) run tests/test_timeseries.py
            echo "== zoolint: drift must flag undeclared history names"
            drift="$(python -m analytics_zoo_tpu.analysis --no-baseline \
                       tests/fixtures/zoolint 2>&1 || true)"
            for name in zoo_ts_points_bogus ZOO_TS_BOGUS_TICK_S; do
              if ! grep -q "$name" <<<"$drift"; then
                echo "catalog drift missed the seeded $name violation" >&2
                exit 1
              fi
            done
            echo "== bench metric-history smoke (flood + mid-drill scrape)"
            JAX_PLATFORMS=cpu python - <<'PY'
import bench
bench.HIST_FLOOD, bench.HIST_GEN = 48, 2
# the measure itself asserts ramp -> sustain -> recover on the lane
# depth ring, a mid-drill non-empty scrape, >= 1 exemplar resolving on
# /trace, and encode+generate request-cost settlement
h = bench.measure_metric_history()
assert h["history_lane_depth_peak"] > 0, h
assert h["history_ring_points"] >= 3, h
assert h["history_exemplar_links"] >= 1, h
assert h["history_records_per_sec"] > 0, h
print(f"history OK: peak={h['history_lane_depth_peak']} "
      f"points={h['history_ring_points']} "
      f"p99(60s)={h['history_p99_60s_ms']}ms "
      f"exemplars={h['history_exemplar_links']}")
PY
            ;;
  release)  bash "$(dirname "$0")/release.sh" ;;
  all)      lint_zoolint
            run tests/ ;;
  *) echo "unknown lane: $lane" >&2; exit 2 ;;
esac
