#!/usr/bin/env bash
# Release build (ref scripts/ + pyzoo packaging): sdist + wheel into dist/,
# then an import smoke test of the built wheel in a scratch venv-less
# PYTHONPATH check. No network needed (--no-build-isolation uses the
# host's setuptools).
set -euo pipefail
cd "$(dirname "$0")/.."

rm -rf build dist *.egg-info
python -m pip wheel --no-deps --no-build-isolation -w dist . >/dev/null
WHEEL=$(ls dist/*.whl)
echo "built: $WHEEL"

# smoke: the wheel must import standalone — run from INSIDE the unpack dir
# (cwd on sys.path would otherwise shadow it with the repo checkout and
# make the check vacuous) and assert the native sources shipped
SMOKE=$(mktemp -d)
python -m zipfile -e "$WHEEL" "$SMOKE"
(cd "$SMOKE" && python - <<'PY'
import os
import analytics_zoo_tpu
import analytics_zoo_tpu.keras, analytics_zoo_tpu.learn, analytics_zoo_tpu.serving
root = os.path.dirname(analytics_zoo_tpu.__file__)
assert root.startswith(os.getcwd()), f"imported {root}, not the wheel"
for rel in ("serving/native/zbroker.cpp", "data/native/zstore.cpp"):
    assert os.path.exists(os.path.join(root, rel)), f"wheel missing {rel}"
print("wheel import OK (incl. native sources):", root)
PY
)
rm -rf "$SMOKE"
echo "release artifacts in dist/"
