"""Cluster Serving container entrypoint (ref cluster-serving start scripts:
boot Redis + Flink job + HTTP frontend; here: native broker + engine +
frontend from one config.yaml)."""

import os
import signal
import sys
import threading

from analytics_zoo_tpu.inference import InferenceModel
from analytics_zoo_tpu.serving import (
    Broker, ClusterServing, FrontEnd, ServingConfig,
)


def main(config_path: str = "config.yaml") -> int:
    cfg = ServingConfig.load(config_path)
    model = InferenceModel().load(cfg.model_path)
    broker = None
    if cfg.broker_host in ("127.0.0.1", "localhost", "0.0.0.0"):
        broker = Broker.launch(port=cfg.broker_port)
        b_host, b_port = "127.0.0.1", broker.port
    else:
        # reference Redis semantics: data.src names an EXISTING shared
        # broker — connect, don't launch a shadow one
        b_host, b_port = cfg.broker_host, cfg.broker_port
    serving = ClusterServing(
        model, b_port, batch_size=cfg.batch_size, broker_host=b_host,
        image_preprocess=cfg.build_image_preprocess()).start()
    front = FrontEnd(broker_port=b_port, broker_host=b_host,
                     host=os.environ.get("BIND_HOST", "0.0.0.0"),
                     port=int(os.environ.get("HTTP_PORT", "8080"))).start()
    print(f"serving up: broker {b_host}:{b_port} http :{front.port}",
          flush=True)
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    serving.stop()
    if broker is not None:
        broker.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main(*sys.argv[1:]))
