"""Unit tests for the whole-program half of zoolint: call-graph
construction, thread-root inference, runs-on propagation, lock tracking
through helper methods (must-held), cross-file lock-cycle detection, and
the generated ownership report's drift check against docs/."""

import json
import os
import textwrap

import pytest

from analytics_zoo_tpu.analysis import analyze_paths, build_project
from analytics_zoo_tpu.analysis import ownership
from analytics_zoo_tpu.analysis.core import build_model_for_paths

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _project(**sources):
    """build_project from dedented keyword sources; ``pkg_mod`` becomes
    ``pkg/mod.py``."""
    return build_project({
        name.replace("__", "/") + ".py": textwrap.dedent(src)
        for name, src in sources.items()
    })


# ------------------------------------------------------------- call graph

def test_call_graph_cross_module_edges():
    m = _project(
        app__worker="""
        def helper():
            return 1

        def run():
            return helper()
        """,
        app__main="""
        from app.worker import run

        def entry():
            return run()
        """,
    )
    assert "app.worker.helper" in m.edges["app.worker.run"]
    assert "app.worker.run" in m.edges["app.main.entry"]
    assert "app.main.entry" in m.incoming["app.worker.run"]


def test_call_graph_method_edges_via_self():
    m = _project(
        app__svc="""
        class Svc:
            def _step(self):
                pass

            def run(self):
                self._step()
        """,
    )
    assert "app.svc.Svc._step" in m.edges["app.svc.Svc.run"]


# ------------------------------------------------------------ thread roots

def test_thread_root_inferred_from_spawn():
    m = _project(
        app__eng="""
        import threading

        class Engine:
            def start(self):
                self._t = threading.Thread(
                    target=self._run, name="zoo-serve", daemon=True)
                self._t.start()

            def _run(self):
                pass
        """,
    )
    assert "zoo-serve" in m.roots
    root = m.roots["zoo-serve"]
    assert root.kind == "thread"
    assert root.entries == ["app.eng.Engine._run"]


def test_executor_submit_and_atexit_roots():
    m = _project(
        app__pool="""
        import atexit
        from concurrent.futures import ThreadPoolExecutor

        def task():
            pass

        def _cleanup():
            pass

        def go():
            ex = ThreadPoolExecutor(max_workers=2)
            ex.submit(task)
            atexit.register(_cleanup)
        """,
    )
    kinds = {r.kind for r in m.roots.values()}
    assert "executor" in kinds
    assert "atexit" in kinds


def test_pytest_only_roots_excluded():
    m = _project(
        tests__test_x="""
        import threading

        def test_spawns():
            t = threading.Thread(target=print)
            t.start()
            t.join()
        """,
    )
    assert all(r.kind == "main" for r in m.roots.values())


# --------------------------------------------------------- runs-on

def test_runs_on_propagates_through_calls():
    m = _project(
        app__eng="""
        import threading

        def leaf():
            pass

        def loop():
            leaf()

        class Engine:
            def start(self):
                threading.Thread(target=loop, name="zoo-w").start()
        """,
    )
    assert "zoo-w" in m.runs_on["app.eng.loop"]
    assert "zoo-w" in m.runs_on["app.eng.leaf"]
    # start() itself runs on main, not on the thread it spawns
    assert "zoo-w" not in m.runs_on.get("app.eng.Engine.start", frozenset())


def test_atexit_root_folds_into_main_for_runs_on():
    m = _project(
        app__ctx="""
        import atexit

        def _shutdown():
            pass

        atexit.register(_shutdown)
        """,
    )
    # listed as a root for the ownership report ...
    assert any(r.kind == "atexit" for r in m.roots.values())
    # ... but attributed to main for race purposes (atexit handlers run
    # sequentially on the main thread)
    assert m.runs_on["app.ctx._shutdown"] == frozenset({"main"})


# ------------------------------------------------- must-held via helpers

def test_lock_tracked_through_helper_method():
    m = _project(
        app__st="""
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def _bump_locked(self):
                self.n += 1

            def add(self):
                with self._lock:
                    self._bump_locked()

            def sub(self):
                with self._lock:
                    self._bump_locked()
        """,
    )
    held = m.must_held["app.st.Store._bump_locked"]
    assert any("_lock" in h for h in held)


def test_must_held_empty_when_one_caller_is_unlocked():
    m = _project(
        app__st="""
        import threading

        class Store:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def _bump(self):
                self.n += 1

            def add(self):
                with self._lock:
                    self._bump()

            def racy(self):
                self._bump()
        """,
    )
    assert m.must_held["app.st.Store._bump"] == frozenset()


# ------------------------------------------------ cross-file lock cycles

def test_cross_file_lock_cycle_detected(tmp_path):
    (tmp_path / "locksmod.py").write_text(textwrap.dedent("""
        import threading

        LOCK_A = threading.Lock()
        LOCK_B = threading.Lock()

        def forward():
            with LOCK_A:
                with LOCK_B:
                    pass
    """))
    (tmp_path / "other.py").write_text(textwrap.dedent("""
        from locksmod import LOCK_A, LOCK_B

        def backward():
            with LOCK_B:
                with LOCK_A:
                    pass
    """))
    fs = analyze_paths([str(tmp_path)], root=str(tmp_path))
    assert "lock-order-inversion" in {f.rule for f in fs}


def test_same_file_abba_left_to_per_file_rule(tmp_path):
    (tmp_path / "abba.py").write_text(textwrap.dedent("""
        import threading

        LOCK_A = threading.Lock()
        LOCK_B = threading.Lock()

        def fwd():
            with LOCK_A:
                with LOCK_B:
                    pass

        def bwd():
            with LOCK_B:
                with LOCK_A:
                    pass
    """))
    fs = analyze_paths([str(tmp_path)], root=str(tmp_path))
    rules = {f.rule for f in fs}
    assert "lock-order" in rules
    assert "lock-order-inversion" not in rules


# ------------------------------------------------------ ownership report

def test_ownership_report_structure():
    m = _project(
        app__eng="""
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def start(self):
                threading.Thread(
                    target=self._run, name="zoo-serve",
                    daemon=True).start()

            def _run(self):
                with self._lock:
                    self.count += 1
        """,
    )
    rep = ownership.build_report(m)
    assert rep["version"] == ownership.REPORT_SCHEMA_VERSION
    rids = [r["root"] for r in rep["roots"]]
    assert rids[0] == "main"
    assert "zoo-serve" in rids


def test_concurrency_doc_has_no_drift(tmp_path):
    """docs/concurrency.md must match a fresh regeneration — the same
    check dev/run-tests.sh runs in the zoolint lane."""
    model = build_model_for_paths(
        [os.path.join(REPO, "analytics_zoo_tpu")], root=REPO, jobs=2)
    md = tmp_path / "concurrency.md"
    ownership.write_report(model, str(md))
    committed = os.path.join(REPO, "docs", "concurrency.md")
    assert md.read_text() == open(committed).read(), \
        "docs/concurrency.md is stale; regenerate with " \
        "`python -m analytics_zoo_tpu.analysis analytics_zoo_tpu " \
        "--ownership-report docs/concurrency.md`"
    with open(os.path.join(REPO, "docs", "concurrency.json")) as fh:
        js = json.load(fh)
    assert js["version"] == ownership.REPORT_SCHEMA_VERSION
    root_ids = " ".join(r["root"] for r in js["roots"])
    for expected in ("zoo-fleet-heartbeat", "zoo-replica-supervisor",
                     "zoo-warmup-estimator"):
        assert expected in root_ids
