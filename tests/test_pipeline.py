"""Pipeline parallelism tests on the virtual 8-device mesh."""

import numpy as np
import pytest

from analytics_zoo_tpu.parallel import mesh as mesh_lib
from analytics_zoo_tpu.parallel.pipeline import (
    PipelinedMLP, PipelinedTransformerLM, gpipe, pack_stage_params,
    stack_stage_params,
)


@pytest.fixture
def pipe_mesh():
    mesh = mesh_lib.build_mesh(axes=(mesh_lib.DATA_AXIS, mesh_lib.PIPE_AXIS),
                               shape=[2, 4])
    yield mesh


def _ref_forward(stages_w, stages_b, h):
    import numpy as np
    for w, b in zip(stages_w, stages_b):
        h = np.tanh(h @ w + b)
    return h


class TestGpipe:
    def test_matches_sequential_execution(self, pipe_mesh):
        import jax.numpy as jnp
        rng = np.random.RandomState(0)
        S, hidden, batch = 4, 8, 16
        ws = [rng.randn(hidden, hidden).astype(np.float32) * 0.3
              for _ in range(S)]
        bs = [rng.randn(hidden).astype(np.float32) * 0.1 for _ in range(S)]
        stacked = stack_stage_params(
            [{"w": w, "b": b} for w, b in zip(ws, bs)])
        x = rng.randn(batch, hidden).astype(np.float32)

        def stage_fn(p, h):
            return jnp.tanh(h @ p["w"] + p["b"])

        got = np.asarray(gpipe(stage_fn, stacked, x, mesh=pipe_mesh,
                               n_microbatches=4))
        want = _ref_forward(ws, bs, x)
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_single_microbatch_and_many(self, pipe_mesh):
        import jax.numpy as jnp
        rng = np.random.RandomState(1)
        stacked = stack_stage_params(
            [{"w": rng.randn(4, 4).astype(np.float32) * 0.3}
             for _ in range(4)])
        x = rng.randn(8, 4).astype(np.float32)

        def stage_fn(p, h):
            return jnp.tanh(h @ p["w"])

        # batch 8 over dp2 → 4 rows per dp group; M must divide that
        outs = [np.asarray(gpipe(stage_fn, stacked, x, mesh=pipe_mesh,
                                 n_microbatches=m)) for m in (1, 2, 4)]
        np.testing.assert_allclose(outs[0], outs[1], atol=1e-5)
        np.testing.assert_allclose(outs[0], outs[2], atol=1e-5)

    def test_gradients_flow_through_pipeline(self, pipe_mesh):
        import jax
        import jax.numpy as jnp
        rng = np.random.RandomState(2)
        stacked = stack_stage_params(
            [{"w": rng.randn(4, 4).astype(np.float32) * 0.3}
             for _ in range(4)])
        x = rng.randn(8, 4).astype(np.float32)

        def stage_fn(p, h):
            return jnp.tanh(h @ p["w"])

        def loss(params):
            out = gpipe(stage_fn, params, x, mesh=pipe_mesh,
                        n_microbatches=2)
            return (out ** 2).mean()

        g = jax.grad(loss)(stacked)
        gw = np.asarray(g["w"])
        assert gw.shape == (4, 4, 4)
        # every stage receives signal
        for s in range(4):
            assert np.abs(gw[s]).max() > 1e-8, f"stage {s} got zero grad"

    def test_batch_not_divisible_raises(self, pipe_mesh):
        import jax.numpy as jnp
        stacked = stack_stage_params(
            [{"w": np.eye(4, dtype=np.float32)} for _ in range(4)])
        with pytest.raises(ValueError, match="divisible"):
            gpipe(lambda p, h: h @ p["w"], stacked,
                  np.zeros((10, 4), np.float32), mesh=pipe_mesh,
                  n_microbatches=4)

    def test_wrong_stage_count_raises(self, pipe_mesh):
        stacked = stack_stage_params(
            [{"w": np.eye(4, dtype=np.float32)} for _ in range(3)])
        with pytest.raises(ValueError, match="pipe size"):
            gpipe(lambda p, h: h @ p["w"], stacked,
                  np.zeros((8, 4), np.float32), mesh=pipe_mesh,
                  n_microbatches=2)

    def test_no_pipe_axis_raises(self):
        mesh = mesh_lib.build_mesh(axes=(mesh_lib.DATA_AXIS,), shape=[8])
        stacked = stack_stage_params(
            [{"w": np.eye(4, dtype=np.float32)} for _ in range(4)])
        with pytest.raises(ValueError, match="pipe"):
            gpipe(lambda p, h: h @ p["w"], stacked,
                  np.zeros((8, 4), np.float32), mesh=mesh, n_microbatches=2)


class TestPipelinedTraining:
    def test_estimator_trains_pipelined_mlp(self, orca_ctx):
        """End-to-end pp training through Estimator.from_fn with the
        stacked stage params sharded over the pipe axis."""
        from analytics_zoo_tpu.learn.estimator import Estimator

        mesh = mesh_lib.build_mesh(
            axes=(mesh_lib.DATA_AXIS, mesh_lib.PIPE_AXIS), shape=[2, 4])
        model = PipelinedMLP(hidden=8, out_dim=2, n_stages=4,
                             n_microbatches=2, mesh=mesh)
        import jax
        rng = np.random.RandomState(0)
        x = rng.randn(64, 4).astype(np.float32)
        y = (x.sum(1) > 0).astype(np.int32)
        params = model.init(jax.random.PRNGKey(0), x[:2])
        est = Estimator.from_fn(
            apply_fn=model.apply, params=params,
            loss="sparse_categorical_crossentropy_logits",
            optimizer="adam", strategy="dp2,pp4",
            param_rules=model.param_rules())
        h1 = est.fit((x, y), epochs=1, batch_size=16)
        h2 = est.fit((x, y), epochs=8, batch_size=16)
        assert h2["loss"][-1] < h1["loss"][0]
        # the stacked stage weights really live sharded over pipe
        w = est._state["params"]["stages"]["w"]
        assert "pipe" in str(w.sharding.spec), w.sharding.spec

class TestHeterogeneousPipeline:
    """gpipe_hetero: embedding / blocks / head INSIDE the schedule."""

    def _model_and_data(self, mesh, seq=8, vocab=17):
        import jax
        model = PipelinedTransformerLM(
            vocab=vocab, d_model=16, n_heads=2, d_ff=32, seq_len=seq,
            n_stages=4, n_microbatches=2, mesh=mesh)
        rng = np.random.RandomState(0)
        tokens = rng.randint(0, vocab, (16, seq)).astype(np.int32)
        params = model.init(jax.random.PRNGKey(1), tokens[:2])
        return model, params, tokens

    def test_matches_sequential_execution(self, pipe_mesh):
        """The pipelined forward must equal running the same heterogeneous
        stages one after another on one device."""
        model, params, tokens = self._model_and_data(pipe_mesh)
        got = np.asarray(model.apply(params, tokens))
        want = np.asarray(model.apply_sequential(params, tokens))
        assert got.shape == (16, 8, 17)
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    @pytest.mark.slow  # ~14s: pipeline-parallel grads vs sequential
    def test_gradients_match_sequential(self, pipe_mesh):
        import jax
        import jax.numpy as jnp
        model, params, tokens = self._model_and_data(pipe_mesh)
        targets = np.roll(tokens, -1, axis=1)

        def loss_pipe(p):
            logits = model.apply(p, tokens)
            lp = jax.nn.log_softmax(logits)
            return -jnp.take_along_axis(lp, targets[..., None], -1).mean()

        def loss_seq(p):
            logits = model.apply_sequential(p, tokens)
            lp = jax.nn.log_softmax(logits)
            return -jnp.take_along_axis(lp, targets[..., None], -1).mean()

        g_pipe = jax.grad(loss_pipe)(params)["pipe"]
        g_seq = jax.grad(loss_seq)(params)["pipe"]
        np.testing.assert_allclose(np.asarray(g_pipe), np.asarray(g_seq),
                                   rtol=5e-3, atol=5e-5)

    def test_estimator_trains_hetero_lm_dp_pp(self, orca_ctx):
        """dp2 x pp4 language-model training end-to-end through the
        Estimator; the packed stage matrix is sharded over pipe."""
        import jax
        from analytics_zoo_tpu.learn.estimator import Estimator

        mesh = mesh_lib.build_mesh(
            axes=(mesh_lib.DATA_AXIS, mesh_lib.PIPE_AXIS), shape=[2, 4])
        model, params, tokens = self._model_and_data(mesh)
        targets = np.roll(tokens, -1, axis=1)

        est = Estimator.from_fn(
            apply_fn=model.apply, params=params,
            loss="sparse_categorical_crossentropy_logits",
            optimizer="adam", strategy="dp2,pp4",
            param_rules=model.param_rules())
        h1 = est.fit((tokens, targets), epochs=2, batch_size=16)
        h2 = est.fit((tokens, targets), epochs=10, batch_size=16)
        assert h2["loss"][-1] < h1["loss"][0]
        packed = est._state["params"]["pipe"]
        assert "pipe" in str(packed.sharding.spec), packed.sharding.spec

    def test_pack_stage_params_roundtrip(self):
        from jax.flatten_util import ravel_pytree
        stages = [{"a": np.arange(4, dtype=np.float32)},
                  {"b": np.ones((2, 3), np.float32), "c": np.zeros(2, np.float32)},
                  {"d": np.full((5,), 2.0, np.float32)}]
        packed, unravels, sizes = pack_stage_params(stages)
        assert packed.shape == (3, 8)
        for s, st in enumerate(stages):
            rec = unravels[s](packed[s][:sizes[s]])
            flat0, _ = ravel_pytree(st)
            flat1, _ = ravel_pytree(rec)
            np.testing.assert_allclose(np.asarray(flat1), np.asarray(flat0))
