import warnings

import numpy as np
import pytest


def test_init_orca_context_local():
    from analytics_zoo_tpu import init_orca_context, OrcaContext
    ctx = init_orca_context(cluster_mode="local")
    assert ctx.num_devices == 8
    assert OrcaContext.get_context() is ctx
    assert ctx.mesh.axis_names == ("data",)


def test_legacy_spark_kwargs_warn():
    from analytics_zoo_tpu import init_orca_context
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        init_orca_context(cluster_mode="local", cores=4, memory="2g")
    assert any("ignored" in str(x.message) for x in w)


def test_orca_context_knobs():
    from analytics_zoo_tpu import OrcaContext
    OrcaContext.shard_size = 100
    assert OrcaContext.shard_size == 100
    OrcaContext.train_data_store = "disk_4"
    assert OrcaContext.train_data_store == "DISK_4"
    with pytest.raises(AssertionError):
        OrcaContext.pandas_read_backend = "spark"
    OrcaContext.shard_size = None
    OrcaContext.train_data_store = "DRAM"


def test_mesh_build_and_global_batch():
    from analytics_zoo_tpu import init_orca_context
    from analytics_zoo_tpu.parallel.mesh import build_mesh, local_batch_to_global
    init_orca_context(cluster_mode="local")
    mesh = build_mesh(axes=("data", "model"), shape=(4, -1))
    assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {"data": 4, "model": 2}
    x = np.arange(32, dtype=np.float32).reshape(8, 4)
    gx = local_batch_to_global({"x": x}, mesh)["x"]
    assert gx.shape == (8, 4)
    np.testing.assert_allclose(np.asarray(gx), x)


def test_strategy_parse_and_specs():
    from jax.sharding import PartitionSpec as P
    from analytics_zoo_tpu import init_orca_context
    from analytics_zoo_tpu.parallel.strategy import ShardingStrategy

    init_orca_context(cluster_mode="local")
    s = ShardingStrategy.parse("dp2,tp4")
    assert s.axis_names() == ("data", "model")
    mesh = s.build_mesh()
    assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {"data": 2, "model": 4}
    assert s.batch_spec(2) == P("data", None)

    s2 = ShardingStrategy.parse("dp")
    m2 = s2.build_mesh()
    assert dict(zip(m2.axis_names, m2.devices.shape)) == {"data": 8}

    s3 = ShardingStrategy.parse("dp2,fsdp4")
    m3 = s3.build_mesh()
    assert s3.batch_spec(2) == P(("data", "fsdp"), None)
    assert s3.param_spec("dense/kernel", (16, 8), m3) == P("fsdp", None)

    tp = ShardingStrategy.parse("tp8", param_rules=[(r"kernel$", (None, "model"))])
    mtp = tp.build_mesh()
    assert tp.param_spec("layers_0/dense/kernel", (4, 16), mtp) == P(None, "model")
    assert tp.param_spec("layers_0/dense/bias", (16,), mtp) == P()
