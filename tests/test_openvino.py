"""OpenVINO IR importer (the reference's load_openvino path, ref
pyzoo/zoo/pipeline/inference/inference_model.py:69): IR xml+bin parsed
directly and translated to jax. Tests hand-build IR files (the same
strategy as the ONNX wire-format tests) and compare against numpy/torch."""

import os

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from analytics_zoo_tpu.net.openvino_net import (  # noqa: E402
    OpenVINONet, openvino_to_jax, parse_ir,
)


class _IRBuilder:
    """Hand-build an IR xml + weight bin."""

    def __init__(self):
        self.layers = []
        self.edges = []
        self.bin = b""
        self._id = 0

    def _dims(self, shape):
        return "".join(f"<dim>{d}</dim>" for d in shape)

    def layer(self, type_, attrs=None, n_in=0, out_shape=(),
              version="opset1"):
        lid = self._id
        self._id += 1
        attr_s = ""
        if attrs:
            attr_s = "<data " + " ".join(
                f'{k}="{v}"' for k, v in attrs.items()) + "/>"
        in_s = ""
        if n_in:
            ports = "".join(
                f'<port id="{i}">{self._dims(())}</port>'
                for i in range(n_in))
            in_s = f"<input>{ports}</input>"
        out_s = ""
        if type_ != "Result":
            out_s = (f'<output><port id="{n_in}" precision="FP32">'
                     f"{self._dims(out_shape)}</port></output>")
        self.layers.append(
            f'<layer id="{lid}" name="l{lid}" type="{type_}" '
            f'version="{version}">{attr_s}{in_s}{out_s}</layer>')
        return lid, n_in  # (id, first output port index)

    def const(self, arr):
        arr = np.ascontiguousarray(arr)
        off = len(self.bin)
        self.bin += arr.tobytes()
        et = {np.dtype(np.float32): "f32", np.dtype(np.int64): "i64",
              np.dtype(np.int32): "i32"}[arr.dtype]
        return self.layer(
            "Const",
            {"element_type": et, "offset": off, "size": arr.nbytes,
             "shape": ",".join(str(d) for d in arr.shape)},
            n_in=0, out_shape=arr.shape)

    def edge(self, src, dst, dst_port):
        (sid, sport) = src
        (did, _) = dst
        self.edges.append(
            f'<edge from-layer="{sid}" from-port="{sport}" '
            f'to-layer="{did}" to-port="{dst_port}"/>')

    def build(self):
        xml = ("<net name=\"t\" version=\"10\"><layers>"
               + "".join(self.layers) + "</layers><edges>"
               + "".join(self.edges) + "</edges></net>")
        return xml.encode(), self.bin

    def write(self, tmp_path, stem="model"):
        xml, binb = self.build()
        xp = os.path.join(str(tmp_path), f"{stem}.xml")
        bp = os.path.join(str(tmp_path), f"{stem}.bin")
        with open(xp, "wb") as f:
            f.write(xml)
        with open(bp, "wb") as f:
            f.write(binb)
        return xp, bp


def _mlp_ir(w1, b1, w2, b2):
    """Parameter → MatMul → Add → ReLU → MatMul → Add → SoftMax → Result"""
    b = _IRBuilder()
    inp = b.layer("Parameter", {"shape": f"1,{w1.shape[0]}",
                                "element_type": "f32"},
                  out_shape=(1, w1.shape[0]))
    cw1 = b.const(w1)
    cb1 = b.const(b1)
    cw2 = b.const(w2)
    cb2 = b.const(b2)
    mm1 = b.layer("MatMul", {"transpose_a": "false",
                             "transpose_b": "false"}, 2, (1, w1.shape[1]))
    add1 = b.layer("Add", None, 2, (1, w1.shape[1]))
    relu = b.layer("ReLU", None, 1, (1, w1.shape[1]))
    mm2 = b.layer("MatMul", None, 2, (1, w2.shape[1]))
    add2 = b.layer("Add", None, 2, (1, w2.shape[1]))
    sm = b.layer("SoftMax", {"axis": "1"}, 1, (1, w2.shape[1]))
    res = b.layer("Result", None, 1)
    b.edge(inp, mm1, 0)
    b.edge(cw1, mm1, 1)
    b.edge(mm1, add1, 0)
    b.edge(cb1, add1, 1)
    b.edge(add1, relu, 0)
    b.edge(relu, mm2, 0)
    b.edge(cw2, mm2, 1)
    b.edge(mm2, add2, 0)
    b.edge(cb2, add2, 1)
    b.edge(add2, sm, 0)
    b.edge(sm, res, 0)
    return b


class TestOpenVINOImport:
    def test_mlp_matches_numpy(self, orca_ctx, tmp_path):
        rs = np.random.RandomState(0)
        w1 = rs.randn(6, 8).astype(np.float32)
        b1 = rs.randn(8).astype(np.float32)
        w2 = rs.randn(8, 3).astype(np.float32)
        b2 = rs.randn(3).astype(np.float32)
        xp, bp = _mlp_ir(w1, b1, w2, b2).write(tmp_path)
        net = OpenVINONet(xp, bp)
        x = rs.randn(4, 6).astype(np.float32)
        got = net.predict(x)
        h = np.maximum(x @ w1 + b1, 0)
        logits = h @ w2 + b2
        e = np.exp(logits - logits.max(1, keepdims=True))
        np.testing.assert_allclose(got, e / e.sum(1, keepdims=True),
                                   rtol=1e-5, atol=1e-6)

    def test_conv_bn_pool_matches_torch(self, orca_ctx, tmp_path):
        torch.manual_seed(0)
        conv = torch.nn.Conv2d(3, 4, 3, stride=1, padding=1)
        bn = torch.nn.BatchNorm2d(4)
        bn.train()(conv(torch.randn(8, 3, 8, 8)))  # prime running stats
        conv.eval()
        bn.eval()

        b = _IRBuilder()
        inp = b.layer("Parameter", {"shape": "2,3,8,8",
                                    "element_type": "f32"},
                      out_shape=(2, 3, 8, 8))
        cw = b.const(conv.weight.detach().numpy())
        cb = b.const(conv.bias.detach().numpy().reshape(1, 4, 1, 1))
        cv = b.layer("Convolution",
                     {"strides": "1,1", "pads_begin": "1,1",
                      "pads_end": "1,1", "dilations": "1,1",
                      "auto_pad": "explicit"}, 2, (2, 4, 8, 8))
        addb = b.layer("Add", None, 2, (2, 4, 8, 8))
        g = b.const(bn.weight.detach().numpy())
        beta = b.const(bn.bias.detach().numpy())
        mean = b.const(bn.running_mean.detach().numpy())
        var = b.const(bn.running_var.detach().numpy())
        bnl = b.layer("BatchNormInference", {"eps": str(bn.eps)}, 5,
                      (2, 4, 8, 8), version="opset5")  # data-first order
        mp = b.layer("MaxPool", {"kernel": "2,2", "strides": "2,2",
                                 "pads_begin": "0,0", "pads_end": "0,0"},
                     1, (2, 4, 4, 4))
        res = b.layer("Result", None, 1)
        b.edge(inp, cv, 0)
        b.edge(cw, cv, 1)
        b.edge(cv, addb, 0)
        b.edge(cb, addb, 1)
        b.edge(addb, bnl, 0)
        b.edge(g, bnl, 1)
        b.edge(beta, bnl, 2)
        b.edge(mean, bnl, 3)
        b.edge(var, bnl, 4)
        b.edge(bnl, mp, 0)
        b.edge(mp, res, 0)
        xp, bp = b.write(tmp_path)
        net = OpenVINONet(xp, bp)

        x = np.random.RandomState(1).randn(2, 3, 8, 8).astype(np.float32)
        with torch.no_grad():
            want = torch.nn.functional.max_pool2d(
                bn(conv(torch.from_numpy(x))), 2).numpy()
        np.testing.assert_allclose(net.predict(x), want, rtol=1e-4,
                                   atol=1e-4)

    def test_reshape_reduce_and_static_consts(self, orca_ctx, tmp_path):
        """Integer consts (Reshape targets, ReduceMean axes) stay static
        under jit."""
        b = _IRBuilder()
        inp = b.layer("Parameter", {"shape": "2,3,4", "element_type": "f32"},
                      out_shape=(2, 3, 4))
        axes = b.const(np.array([2], np.int64))
        rm = b.layer("ReduceMean", {"keep_dims": "false"}, 2, (2, 3))
        shape = b.const(np.array([3, 2], np.int64))
        rs_ = b.layer("Reshape", {"special_zero": "false"}, 2, (3, 2))
        res = b.layer("Result", None, 1)
        b.edge(inp, rm, 0)
        b.edge(axes, rm, 1)
        b.edge(rm, rs_, 0)
        b.edge(shape, rs_, 1)
        b.edge(rs_, res, 0)
        xp, bp = b.write(tmp_path)
        net = OpenVINONet(xp, bp)
        x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
        np.testing.assert_allclose(net.predict(x),
                                   x.mean(2).reshape(3, 2), rtol=1e-6)

    def test_unsupported_layer_raises(self, orca_ctx, tmp_path):
        b = _IRBuilder()
        inp = b.layer("Parameter", {"shape": "1,4", "element_type": "f32"},
                      out_shape=(1, 4))
        bad = b.layer("NonMaxSuppression", None, 1, (1, 4))
        res = b.layer("Result", None, 1)
        b.edge(inp, bad, 0)
        b.edge(bad, res, 0)
        xp, bp = b.write(tmp_path)
        net = OpenVINONet(xp, bp, jit=False)
        with pytest.raises(NotImplementedError, match="NonMaxSuppression"):
            net.predict(np.zeros((1, 4), np.float32))

    def test_inference_model_load_openvino(self, orca_ctx, tmp_path):
        """The reference entry point: InferenceModel.load_openvino(xml,
        bin) then predict (ref inference_model.py:69)."""
        from analytics_zoo_tpu.inference import InferenceModel
        rs = np.random.RandomState(2)
        w1 = rs.randn(5, 7).astype(np.float32)
        b1 = rs.randn(7).astype(np.float32)
        w2 = rs.randn(7, 2).astype(np.float32)
        b2 = rs.randn(2).astype(np.float32)
        xp, bp = _mlp_ir(w1, b1, w2, b2).write(tmp_path)
        im = InferenceModel().load_openvino(xp, bp, batch_size=4)
        x = rs.randn(3, 5).astype(np.float32)
        out = im.predict(x)
        assert out.shape == (3, 2)
        np.testing.assert_allclose(out.sum(1), 1.0, rtol=1e-5)

    def test_net_load_openvino_facade(self, orca_ctx, tmp_path):
        from analytics_zoo_tpu.net import Net
        rs = np.random.RandomState(3)
        xp, bp = _mlp_ir(rs.randn(4, 4).astype(np.float32),
                         np.zeros(4, np.float32),
                         rs.randn(4, 2).astype(np.float32),
                         np.zeros(2, np.float32)).write(tmp_path)
        net = Net.load_openvino(xp, bp)
        assert net.predict(np.zeros((1, 4), np.float32)).shape == (1, 2)

    def test_batchnorm_opset1_input_order(self, orca_ctx, tmp_path):
        """opset1 BatchNormInference wires (gamma, beta, data, mean, var)
        — data is NOT first (the order changed in opset5)."""
        b = _IRBuilder()
        inp = b.layer("Parameter", {"shape": "2,3,4,4",
                                    "element_type": "f32"},
                      out_shape=(2, 3, 4, 4))
        rs = np.random.RandomState(4)
        gamma = rs.rand(3).astype(np.float32) + 0.5
        beta = rs.randn(3).astype(np.float32)
        mean = rs.randn(3).astype(np.float32)
        var = rs.rand(3).astype(np.float32) + 0.5
        cg, cb2 = b.const(gamma), b.const(beta)
        cm, cv2 = b.const(mean), b.const(var)
        bnl = b.layer("BatchNormInference", {"eps": "1e-5"}, 5,
                      (2, 3, 4, 4), version="opset1")
        res = b.layer("Result", None, 1)
        b.edge(cg, bnl, 0)     # opset1: gamma first
        b.edge(cb2, bnl, 1)
        b.edge(inp, bnl, 2)    # data third
        b.edge(cm, bnl, 3)
        b.edge(cv2, bnl, 4)
        b.edge(bnl, res, 0)
        xp, bp = b.write(tmp_path)
        net = OpenVINONet(xp, bp)
        x = rs.randn(2, 3, 4, 4).astype(np.float32)
        sh = (1, 3, 1, 1)
        want = (x - mean.reshape(sh)) * gamma.reshape(sh) \
            / np.sqrt(var.reshape(sh) + 1e-5) + beta.reshape(sh)
        np.testing.assert_allclose(net.predict(x), want, rtol=1e-4,
                                   atol=1e-5)

    def test_multi_input_ir_through_inference_model(self, orca_ctx,
                                                    tmp_path):
        """Two Parameter layers: InferenceModel must honor the IR's real
        input count (tuple inputs reach apply in order)."""
        from analytics_zoo_tpu.inference import InferenceModel
        b = _IRBuilder()
        a = b.layer("Parameter", {"shape": "2,3", "element_type": "f32"},
                    out_shape=(2, 3))
        c = b.layer("Parameter", {"shape": "2,3", "element_type": "f32"},
                    out_shape=(2, 3))
        add = b.layer("Add", None, 2, (2, 3))
        res = b.layer("Result", None, 1)
        b.edge(a, add, 0)
        b.edge(c, add, 1)
        b.edge(add, res, 0)
        xp, bp = b.write(tmp_path)
        im = InferenceModel().load_openvino(xp, bp)
        x1 = np.ones((2, 3), np.float32)
        x2 = np.full((2, 3), 2.0, np.float32)
        np.testing.assert_allclose(im.predict((x1, x2)),
                                   np.full((2, 3), 3.0))

    def test_unsqueeze_negative_axes(self, orca_ctx, tmp_path):
        """Negative Unsqueeze axes index the OUTPUT rank: (3,) with axes
        [-2,-1] → (3, 1, 1)."""
        b = _IRBuilder()
        inp = b.layer("Parameter", {"shape": "3", "element_type": "f32"},
                      out_shape=(3,))
        ax = b.const(np.array([-2, -1], np.int64))
        un = b.layer("Unsqueeze", None, 2, (3, 1, 1))
        res = b.layer("Result", None, 1)
        b.edge(inp, un, 0)
        b.edge(ax, un, 1)
        b.edge(un, res, 0)
        xp, bp = b.write(tmp_path)
        net = OpenVINONet(xp, bp)
        out = net.predict(np.arange(3, dtype=np.float32))
        assert out.shape == (3, 1, 1)

    def test_gather_batch_dims_attr_axis(self, orca_ctx, tmp_path):
        # batch_dims via the attrs-only (2-input) Gather spelling
        b = _IRBuilder()
        inp = b.layer("Parameter", {"shape": "2,4", "element_type": "f32"},
                      out_shape=(2, 4))
        idx = b.const(np.array([[0], [1]], np.int64))
        g = b.layer("Gather", {"batch_dims": "1", "axis": "1"}, 2, (2, 1),
                    version="opset8")
        res = b.layer("Result", None, 1)
        b.edge(inp, g, 0)
        b.edge(idx, g, 1)
        b.edge(g, res, 0)
        xp, bp = b.write(tmp_path)
        net = OpenVINONet(xp, bp, jit=False)
        x = np.arange(8, dtype=np.float32).reshape(2, 4)
        got = np.asarray(net.predict(x))
        np.testing.assert_allclose(got, np.array([[0.], [5.]]))

    def test_dangling_subgraph_ignored_when_results_exist(self, orca_ctx,
                                                          tmp_path):
        """A disconnected unsupported layer must not break a model whose
        actual outputs are fully supported."""
        b = _IRBuilder()
        inp = b.layer("Parameter", {"shape": "2,3", "element_type": "f32"},
                      out_shape=(2, 3))
        relu = b.layer("ReLU", None, 1, (2, 3))
        res = b.layer("Result", None, 1)
        # dangling: an unsupported layer reachable from NO Result
        b.layer("NonMaxSuppression", None, 0, (1,))
        b.edge(inp, relu, 0)
        b.edge(relu, res, 0)
        xp, bp = b.write(tmp_path)
        net = OpenVINONet(xp, bp)
        x = np.array([[-1.0, 0.0, 2.0]] * 2, np.float32)
        np.testing.assert_allclose(net.predict(x), np.maximum(x, 0))


class TestRealToolIRFeatures:
    """Attribute variants real model-optimizer exports use (VERDICT r4
    weak #3: ceil-mode pooling, auto_pad, Gather batch_dims) — each
    checked numerically against torch."""

    def _conv_ir(self, w, in_shape, pool_attrs=None, conv_attrs=None,
                 pool_type="MaxPool", out_spatial=None):
        b = _IRBuilder()
        n, c, h, wd = in_shape
        inp = b.layer("Parameter", {"shape": ",".join(map(str, in_shape)),
                                    "element_type": "f32"},
                      out_shape=in_shape)
        cw = b.const(w)
        conv = b.layer("Convolution", conv_attrs or
                       {"strides": "1,1", "pads_begin": "0,0",
                        "pads_end": "0,0", "dilations": "1,1"},
                       2, ())
        last = conv
        if pool_attrs is not None:
            pool = b.layer(pool_type, pool_attrs, 1, ())
            b.edge(conv, pool, 0)
            last = pool
        res = b.layer("Result", None, 1)
        b.edge(inp, conv, 0)
        b.edge(cw, conv, 1)
        b.edge(last, res, 0)
        return b

    def test_ceil_mode_maxpool_matches_torch(self, orca_ctx, tmp_path):
        import torch.nn.functional as F
        rng = np.random.RandomState(0)
        x = rng.randn(1, 3, 11, 11).astype(np.float32)
        w = rng.randn(4, 3, 3, 3).astype(np.float32) * 0.2
        b = self._conv_ir(
            w, (1, 3, 11, 11),
            pool_attrs={"kernel": "3,3", "strides": "2,2",
                        "pads_begin": "0,0", "pads_end": "0,0",
                        "rounding_type": "ceil"})
        xp, bp = b.write(tmp_path)
        net = OpenVINONet(xp, bp)
        got = np.asarray(net.predict(x))
        with torch.no_grad():
            want = F.max_pool2d(
                F.conv2d(torch.tensor(x), torch.tensor(w)),
                3, 2, ceil_mode=True).numpy()
        assert got.shape == want.shape, (got.shape, want.shape)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_ceil_mode_avgpool_exclude_pad(self, orca_ctx, tmp_path):
        import torch.nn.functional as F
        rng = np.random.RandomState(1)
        x = rng.randn(1, 2, 7, 7).astype(np.float32)
        w = rng.randn(2, 2, 1, 1).astype(np.float32)
        b = self._conv_ir(
            w, (1, 2, 7, 7), pool_type="AvgPool",
            pool_attrs={"kernel": "3,3", "strides": "2,2",
                        "pads_begin": "0,0", "pads_end": "0,0",
                        "rounding_type": "ceil", "exclude-pad": "true"})
        xp, bp = b.write(tmp_path)
        got = np.asarray(OpenVINONet(xp, bp).predict(x))
        with torch.no_grad():
            # torch count_include_pad=False == IR exclude-pad=true
            want = F.avg_pool2d(
                F.conv2d(torch.tensor(x), torch.tensor(w)), 3, 2,
                ceil_mode=True, count_include_pad=False).numpy()
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_auto_pad_same_upper_conv(self, orca_ctx, tmp_path):
        import torch.nn.functional as F
        rng = np.random.RandomState(2)
        x = rng.randn(1, 3, 8, 8).astype(np.float32)
        w = rng.randn(5, 3, 3, 3).astype(np.float32) * 0.2
        b = self._conv_ir(
            w, (1, 3, 8, 8),
            conv_attrs={"strides": "1,1", "auto_pad": "same_upper",
                        "dilations": "1,1"})
        xp, bp = b.write(tmp_path)
        got = np.asarray(OpenVINONet(xp, bp).predict(x))
        with torch.no_grad():
            want = F.conv2d(torch.tensor(x), torch.tensor(w),
                            padding=1).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_gather_batch_dims(self, orca_ctx, tmp_path):
        b = _IRBuilder()
        data = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
        idx = np.array([[2, 0], [1, 1]], np.int64)
        inp = b.layer("Parameter", {"shape": "2,3,4",
                                    "element_type": "f32"},
                      out_shape=(2, 3, 4))
        ci = b.const(idx)
        cax = b.const(np.array(1, np.int64).reshape(()))
        g = b.layer("Gather", {"batch_dims": "1"}, 3, (2, 2, 4),
                    version="opset8")
        res = b.layer("Result", None, 1)
        b.edge(inp, g, 0)
        b.edge(ci, g, 1)
        b.edge(cax, g, 2)
        b.edge(g, res, 0)
        xp, bp = b.write(tmp_path)
        got = np.asarray(OpenVINONet(xp, bp).predict(data))
        want = np.stack([data[i][idx[i]] for i in range(2)])
        np.testing.assert_allclose(got, want)

    def test_ceil_clamp_window_fully_in_padding(self, orca_ctx, tmp_path):
        """kernel=2 stride=2 pads 1/1 ceil on width 3: the last ceil
        window starts entirely in padding — torch drops it (shape 2, not
        3, no -inf column)."""
        import torch.nn.functional as F
        rng = np.random.RandomState(3)
        x = rng.randn(1, 2, 3, 3).astype(np.float32)
        w = rng.randn(2, 2, 1, 1).astype(np.float32)
        b = self._conv_ir(
            w, (1, 2, 3, 3),
            pool_attrs={"kernel": "2,2", "strides": "2,2",
                        "pads_begin": "1,1", "pads_end": "1,1",
                        "rounding_type": "ceil"})
        xp, bp = b.write(tmp_path)
        got = np.asarray(OpenVINONet(xp, bp).predict(x))
        with torch.no_grad():
            want = F.max_pool2d(
                F.conv2d(torch.tensor(x), torch.tensor(w)), 2, 2,
                padding=1, ceil_mode=True).numpy()
        assert got.shape == want.shape, (got.shape, want.shape)
        assert np.isfinite(got).all()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_ceil_avgpool_include_pad_divisor(self, orca_ctx, tmp_path):
        """AvgPool ceil + exclude-pad=false: divisor clips to input +
        explicit pads (torch count_include_pad=True), NOT the full
        kernel."""
        import torch.nn.functional as F
        rng = np.random.RandomState(4)
        x = rng.randn(1, 2, 7, 7).astype(np.float32)
        w = rng.randn(2, 2, 1, 1).astype(np.float32)
        b = self._conv_ir(
            w, (1, 2, 7, 7), pool_type="AvgPool",
            pool_attrs={"kernel": "3,3", "strides": "2,2",
                        "pads_begin": "0,0", "pads_end": "0,0",
                        "rounding_type": "ceil", "exclude-pad": "false"})
        xp, bp = b.write(tmp_path)
        got = np.asarray(OpenVINONet(xp, bp).predict(x))
        with torch.no_grad():
            want = F.avg_pool2d(
                F.conv2d(torch.tensor(x), torch.tensor(w)), 3, 2,
                ceil_mode=True, count_include_pad=True).numpy()
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
