"""TFRecord/tf.Example wire-format tests (ref TFBytesDataset ingestion,
tf_dataset.py:915 — here parsed natively, no TF)."""

import numpy as np
import pytest

from analytics_zoo_tpu.data.tfrecord import (
    encode_example, parse_example, read_tfrecords,
    read_tfrecords_as_shards, write_tfrecords,
)


def _records(n=7):
    rng = np.random.RandomState(0)
    return [{
        "image": rng.rand(12).astype(np.float32),
        "label": np.asarray([i % 3], np.int64),
        "name": f"rec{i}".encode(),
    } for i in range(n)]


class TestTFRecord:
    def test_example_roundtrip(self):
        rec = _records(1)[0]
        parsed = parse_example(encode_example(rec))
        np.testing.assert_allclose(parsed["image"], rec["image"], rtol=1e-6)
        assert parsed["label"].tolist() == [0]
        assert parsed["name"] == [b"rec0"]

    def test_negative_and_bool_ints(self):
        parsed = parse_example(encode_example(
            {"v": np.asarray([-5, 3], np.int64),
             "b": np.asarray([True, False])}))
        assert parsed["v"].tolist() == [-5, 3]
        assert parsed["b"].tolist() == [1, 0]

    def test_file_roundtrip(self, tmp_path):
        recs = _records()
        p = str(tmp_path / "data.tfrecord")
        assert write_tfrecords(p, recs) == len(recs)
        back = read_tfrecords(p)
        assert len(back) == len(recs)
        for a, b in zip(back, recs):
            np.testing.assert_allclose(a["image"], b["image"], rtol=1e-6)
            assert a["label"].tolist() == b["label"].tolist()

    def test_directory_read_and_shards(self, tmp_path):
        write_tfrecords(str(tmp_path / "a.tfrecord"), _records(3))
        write_tfrecords(str(tmp_path / "b.tfrecord"), _records(4))
        shards = read_tfrecords_as_shards(str(tmp_path), num_shards=2)
        collected = shards.collect()
        assert sum(len(s) for s in collected) == 7

    def test_crc_detects_corruption(self, tmp_path):
        p = str(tmp_path / "x.tfrecord")
        write_tfrecords(p, _records(2))
        raw = bytearray(open(p, "rb").read())
        # flip a bit in the LAST record's payload CRC: framing stays intact,
        # so the CRC check is the only thing standing between us and garbage
        raw[-1] ^= 0xFF
        open(p, "wb").write(bytes(raw))
        with pytest.raises(IOError):
            read_tfrecords(p)
        assert len(read_tfrecords(p, verify_crc=False)) == 2

    def test_truncated_file_raises(self, tmp_path):
        p = str(tmp_path / "t.tfrecord")
        write_tfrecords(p, _records(2))
        raw = open(p, "rb").read()
        open(p, "wb").write(raw[:-6])
        with pytest.raises(IOError):
            read_tfrecords(p, verify_crc=False)

    def test_feeds_estimator_dataset(self, tmp_path, orca_ctx):
        from analytics_zoo_tpu.data.dataset import ShardedDataset
        p = str(tmp_path / "train.tfrecord")
        write_tfrecords(p, _records(32))
        shards = read_tfrecords_as_shards(p, num_shards=2)
        packed = shards.transform_shard(lambda recs: {
            "x": np.stack([r["image"] for r in recs]),
            "y": np.stack([int(r["label"][0]) for r in recs]),
        })
        ds = ShardedDataset.from_xshards(packed)
        x, y, mask = next(iter(ds.iter_batches(batch_size=8)))
        assert np.asarray(x).shape == (8, 12)
        assert np.asarray(y).shape == (8,)
