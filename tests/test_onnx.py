"""ONNX loader tests (ref pyzoo/test/zoo/pipeline/api/onnx tests).

No ``onnx`` package exists in this environment, so the test ENCODES ONNX
ModelProto bytes by hand following the public onnx.proto wire format —
the loader must parse the spec, not a mirror of itself — and checks the
translated jax graph numerically against numpy/torch references.
"""

import struct

import numpy as np
import pytest

from analytics_zoo_tpu.net import Net, ONNXNet, onnx_to_jax


# ---------------------------------------------------------- proto encoder

def _varint(v: int) -> bytes:
    v &= (1 << 64) - 1  # negatives: 10-byte two's complement per protobuf
    out = b""
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _tag(field: int, wire: int) -> bytes:
    return _varint((field << 3) | wire)


def _len_field(field: int, payload: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(payload)) + payload


def _int_field(field: int, v: int) -> bytes:
    return _tag(field, 0) + _varint(v)


def _float_field(field: int, v: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", v)


def tensor_proto(name: str, arr: np.ndarray) -> bytes:
    dtype_code = {np.dtype("float32"): 1, np.dtype("int32"): 6,
                  np.dtype("int64"): 7}[arr.dtype]
    out = b""
    for d in arr.shape:
        out += _int_field(1, d)
    out += _int_field(2, dtype_code)
    out += _len_field(8, name.encode())
    out += _len_field(9, arr.tobytes())          # raw_data
    return out


def attr_int(name: str, v: int) -> bytes:
    return _len_field(1, name.encode()) + _int_field(4, v) \
        + _int_field(20, 2)                      # type = INT


def attr_ints(name: str, vals) -> bytes:
    out = _len_field(1, name.encode())
    for v in vals:
        out += _int_field(8, v)
    return out + _int_field(20, 7)               # type = INTS


def attr_float(name: str, v: float) -> bytes:
    return _len_field(1, name.encode()) + _tag(3, 5) \
        + struct.pack("<f", v) + _int_field(20, 1)


def node(op: str, inputs, outputs, attrs=()) -> bytes:
    out = b""
    for i in inputs:
        out += _len_field(1, i.encode())
    for o in outputs:
        out += _len_field(2, o.encode())
    out += _len_field(4, op.encode())
    for a in attrs:
        out += _len_field(5, a)
    return out


def value_info(name: str) -> bytes:
    return _len_field(1, name.encode())


def model_proto(nodes, initializers, inputs, outputs) -> bytes:
    graph = b""
    for n in nodes:
        graph += _len_field(1, n)
    graph += _len_field(2, b"g")
    for t in initializers:
        graph += _len_field(5, t)
    for i in inputs:
        graph += _len_field(11, value_info(i))
    for o in outputs:
        graph += _len_field(12, value_info(o))
    return _int_field(1, 8) + _len_field(7, graph)   # ir_version + graph


# ---------------------------------------------------------------- tests

class TestOnnxMLP:
    def _mlp_bytes(self, w1, b1, w2, b2):
        nodes = [
            node("Gemm", ["x", "w1", "b1"], ["h"]),
            node("Relu", ["h"], ["a"]),
            node("Gemm", ["a", "w2", "b2"], ["y"],
                 attrs=[attr_float("alpha", 1.0)]),
            node("Softmax", ["y"], ["p"], attrs=[attr_int("axis", -1)]),
        ]
        inits = [tensor_proto("w1", w1), tensor_proto("b1", b1),
                 tensor_proto("w2", w2), tensor_proto("b2", b2)]
        return model_proto(nodes, inits, ["x", "w1", "b1", "w2", "b2"],
                           ["p"])

    def test_mlp_matches_numpy(self, orca_ctx, tmp_path):
        rng = np.random.RandomState(0)
        w1 = rng.randn(4, 8).astype(np.float32)
        b1 = rng.randn(8).astype(np.float32)
        w2 = rng.randn(8, 3).astype(np.float32)
        b2 = rng.randn(3).astype(np.float32)
        data = self._mlp_bytes(w1, b1, w2, b2)

        x = rng.randn(5, 4).astype(np.float32)
        h = np.maximum(x @ w1 + b1, 0)
        logits = h @ w2 + b2
        e = np.exp(logits - logits.max(-1, keepdims=True))
        want = e / e.sum(-1, keepdims=True)

        net = ONNXNet(data)
        np.testing.assert_allclose(net.predict(x), want, atol=1e-5)
        # params surfaced as a real pytree (trainable downstream)
        assert set(net.params) == {"w1", "b1", "w2", "b2"}

        # file path + Net.load_onnx entry point
        p = str(tmp_path / "m.onnx")
        with open(p, "wb") as fh:
            fh.write(data)
        np.testing.assert_allclose(Net.load_onnx(p).predict(x), want,
                                   atol=1e-5)

    def test_gemm_transB_and_matmul_add(self, orca_ctx):
        rng = np.random.RandomState(1)
        w = rng.randn(3, 4).astype(np.float32)   # transB: y = x @ w.T
        b = rng.randn(3).astype(np.float32)
        nodes = [node("Gemm", ["x", "w", "b"], ["g"],
                      attrs=[attr_int("transB", 1)]),
                 node("MatMul", ["g", "m"], ["mm"]),
                 node("Add", ["mm", "c"], ["y"])]
        m = rng.randn(3, 2).astype(np.float32)
        c = rng.randn(2).astype(np.float32)
        data = model_proto(
            nodes, [tensor_proto("w", w), tensor_proto("b", b),
                    tensor_proto("m", m), tensor_proto("c", c)],
            ["x", "w", "b", "m", "c"], ["y"])
        x = rng.randn(6, 4).astype(np.float32)
        want = (x @ w.T + b) @ m + c
        np.testing.assert_allclose(ONNXNet(data).predict(x), want,
                                   atol=1e-5)


class TestOnnxConvNet:
    def test_conv_pool_bn_matches_torch(self, orca_ctx):
        torch = pytest.importorskip("torch")
        import torch.nn.functional as F

        rng = np.random.RandomState(2)
        w = rng.randn(5, 3, 3, 3).astype(np.float32) * 0.3
        b = rng.randn(5).astype(np.float32)
        scale = rng.rand(5).astype(np.float32) + 0.5
        bias = rng.randn(5).astype(np.float32)
        mean = rng.randn(5).astype(np.float32)
        var = rng.rand(5).astype(np.float32) + 0.5

        nodes = [
            node("Conv", ["x", "w", "b"], ["c"],
                 attrs=[attr_ints("kernel_shape", [3, 3]),
                        attr_ints("strides", [1, 1]),
                        attr_ints("pads", [1, 1, 1, 1])]),
            node("BatchNormalization",
                 ["c", "scale", "bias", "mean", "var"], ["n"],
                 attrs=[attr_float("epsilon", 1e-5)]),
            node("Relu", ["n"], ["r"]),
            node("MaxPool", ["r"], ["p"],
                 attrs=[attr_ints("kernel_shape", [2, 2]),
                        attr_ints("strides", [2, 2])]),
            node("GlobalAveragePool", ["p"], ["gap"]),
            node("Flatten", ["gap"], ["y"], attrs=[attr_int("axis", 1)]),
        ]
        inits = [tensor_proto("w", w), tensor_proto("b", b),
                 tensor_proto("scale", scale), tensor_proto("bias", bias),
                 tensor_proto("mean", mean), tensor_proto("var", var)]
        data = model_proto(nodes, inits,
                           ["x", "w", "b", "scale", "bias", "mean", "var"],
                           ["y"])

        x = rng.randn(2, 3, 8, 8).astype(np.float32)
        tx = torch.from_numpy(x)
        t = F.conv2d(tx, torch.from_numpy(w), torch.from_numpy(b),
                     padding=1)
        t = F.batch_norm(t, torch.from_numpy(mean), torch.from_numpy(var),
                         torch.from_numpy(scale), torch.from_numpy(bias),
                         training=False, eps=1e-5)
        t = F.max_pool2d(F.relu(t), 2)
        want = t.mean(dim=(2, 3)).numpy()
        np.testing.assert_allclose(ONNXNet(data).predict(x), want,
                                   rtol=1e-4, atol=1e-4)


class TestOnnxSemantics:
    def test_omitted_zero_attr_and_variadic_sum(self, orca_ctx):
        """proto3 omits i=0 on the wire: an axis=0 attribute arrives as
        name+type only and must decode as 0, not None; Sum takes any number
        of inputs."""
        axis0 = _len_field(1, b"axis") + _int_field(20, 2)  # type=INT, no i
        nodes = [node("Concat", ["x", "x"], ["c"], attrs=[axis0]),
                 node("Sum", ["c", "c", "c"], ["y"])]
        data = model_proto(nodes, [], ["x"], ["y"])
        x = np.arange(6, dtype=np.float32).reshape(2, 3)
        want = 3 * np.concatenate([x, x], axis=0)
        np.testing.assert_allclose(ONNXNet(data).predict(x), want)

    def test_flatten_is_always_2d(self, orca_ctx):
        data = model_proto([node("Flatten", ["x"], ["y"],
                                 attrs=[attr_int("axis", 2)])],
                           [], ["x"], ["y"])
        x = np.arange(24, dtype=np.float32).reshape(2, 3, 2, 2)
        out = ONNXNet(data).predict(x)
        assert out.shape == (6, 4)
        np.testing.assert_allclose(out, x.reshape(6, 4))

    def test_avgpool_excludes_padding_by_default(self, orca_ctx):
        torch = pytest.importorskip("torch")
        import torch.nn.functional as F
        data = model_proto(
            [node("AveragePool", ["x"], ["y"],
                  attrs=[attr_ints("kernel_shape", [2, 2]),
                         attr_ints("strides", [2, 2]),
                         attr_ints("pads", [1, 1, 1, 1])])],
            [], ["x"], ["y"])
        x = np.random.RandomState(3).randn(1, 2, 4, 4).astype(np.float32)
        want = F.avg_pool2d(torch.from_numpy(x), 2, 2, padding=1,
                            count_include_pad=False).numpy()
        np.testing.assert_allclose(ONNXNet(data).predict(x), want,
                                   atol=1e-5)

    def test_conv_auto_pad_same_upper(self, orca_ctx):
        torch = pytest.importorskip("torch")
        import torch.nn.functional as F
        rng = np.random.RandomState(4)
        w = rng.randn(4, 3, 3, 3).astype(np.float32) * 0.3
        auto = _len_field(1, b"auto_pad") + _len_field(5, b"SAME_UPPER") \
            + _int_field(20, 3)
        data = model_proto(
            [node("Conv", ["x", "w"], ["y"],
                  attrs=[attr_ints("kernel_shape", [3, 3]), auto])],
            [tensor_proto("w", w)], ["x", "w"], ["y"])
        x = rng.randn(2, 3, 7, 7).astype(np.float32)
        want = F.conv2d(torch.from_numpy(x), torch.from_numpy(w),
                        padding="same").numpy()
        got = ONNXNet(data).predict(x)
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


class TestOnnxErrors:
    def test_unknown_op_raises(self, orca_ctx):
        data = model_proto([node("FancyOp", ["x"], ["y"])], [], ["x"],
                           ["y"])
        with pytest.raises(NotImplementedError, match="FancyOp"):
            ONNXNet(data).predict(np.zeros((1, 2), np.float32))

    def test_not_onnx_raises(self):
        with pytest.raises(ValueError, match="ModelProto"):
            onnx_to_jax(_int_field(3, 7))

class TestOnnxExtendedOps:
    def _run1(self, nodes, inits, in_names, x, n_out=1):
        data = model_proto(nodes, inits, in_names, ["y"])
        return ONNXNet(data).predict(x)

    def test_elementwise_unary_chain(self, orca_ctx):
        # y = -(sqrt(exp(log(abs(x)+1)))) through a single graph
        nodes = [
            node("Abs", ["x"], ["a"]),
            node("Add", ["a", "one"], ["a1"]),
            node("Log", ["a1"], ["l"]),
            node("Exp", ["l"], ["e"]),
            node("Sqrt", ["e"], ["s"]),
            node("Neg", ["s"], ["y"]),
        ]
        inits = [tensor_proto("one", np.float32(1.0).reshape(()))]
        x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
        want = -np.sqrt(np.abs(x) + 1.0)
        np.testing.assert_allclose(
            self._run1(nodes, inits, ["x", "one"], x), want,
            rtol=1e-5, atol=1e-5)

    def test_leaky_elu_clip_pow(self, orca_ctx):
        nodes = [
            node("LeakyRelu", ["x"], ["lr"],
                 attrs=[attr_float("alpha", 0.2)]),
            node("Elu", ["lr"], ["el"], attrs=[attr_float("alpha", 0.5)]),
            node("Clip", ["el", "lo", "hi"], ["cl"]),
            node("Pow", ["cl", "two"], ["y"]),
        ]
        inits = [tensor_proto("lo", np.float32(-0.4).reshape(())),
                 tensor_proto("hi", np.float32(0.9).reshape(())),
                 tensor_proto("two", np.float32(2.0).reshape(()))]
        x = np.random.RandomState(1).randn(2, 5).astype(np.float32)
        lr = np.where(x >= 0, x, 0.2 * x)
        el = np.where(lr >= 0, lr, 0.5 * (np.exp(lr) - 1.0))
        want = np.clip(el, -0.4, 0.9) ** 2
        np.testing.assert_allclose(
            self._run1(nodes, inits, ["x", "lo", "hi", "two"], x), want,
            rtol=1e-5, atol=1e-5)

    def test_clip_attr_form(self, orca_ctx):
        nodes = [node("Clip", ["x"], ["y"],
                      attrs=[attr_float("min", -0.5),
                             attr_float("max", 0.5)])]
        x = np.random.RandomState(2).randn(8).astype(np.float32)
        np.testing.assert_allclose(self._run1(nodes, [], ["x"], x),
                                   np.clip(x, -0.5, 0.5), atol=1e-6)

    def test_reduce_pad_where_expand(self, orca_ctx):
        nodes = [
            node("ReduceMean", ["x"], ["m"],
                 attrs=[attr_ints("axes", [1]), attr_int("keepdims", 1)]),
            node("Expand", ["m", "shape"], ["me"]),
            node("Where", ["cond", "x", "me"], ["w"]),
            node("Pad", ["w", "pads"], ["p"]),
            node("ReduceSum", ["p"], ["y"],
                 attrs=[attr_ints("axes", [0, 1]),
                        attr_int("keepdims", 0)]),
        ]
        rng = np.random.RandomState(3)
        x = rng.randn(3, 4).astype(np.float32)
        cond = (rng.rand(3, 4) > 0.5)
        inits = [tensor_proto("shape", np.asarray([3, 4], np.int64)),
                 tensor_proto("cond", cond.astype(np.int32)),
                 tensor_proto("pads", np.asarray([1, 0, 0, 2], np.int64))]
        m = x.mean(1, keepdims=True)
        w = np.where(cond, x, np.broadcast_to(m, x.shape))
        p = np.pad(w, [(1, 0), (0, 2)])
        want = p.sum()
        got = self._run1(nodes, inits, ["x", "shape", "cond", "pads"], x)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_cast_and_slice_both_opsets(self, orca_ctx):
        nodes = [
            node("Cast", ["x"], ["c"], attrs=[attr_int("to", 6)]),  # int32
            node("Cast", ["c"], ["f"], attrs=[attr_int("to", 1)]),  # float32
            node("Slice", ["f", "starts", "ends", "axes", "steps"], ["y"]),
        ]
        x = (np.arange(24, dtype=np.float32) + 0.7).reshape(4, 6)
        inits = [tensor_proto("starts", np.asarray([1, 0], np.int64)),
                 tensor_proto("ends", np.asarray([4, 6], np.int64)),
                 tensor_proto("axes", np.asarray([0, 1], np.int64)),
                 tensor_proto("steps", np.asarray([1, 2], np.int64))]
        want = np.floor(x).astype(np.float32)[1:4, 0:6:2]
        got = self._run1(nodes, inits,
                         ["x", "starts", "ends", "axes", "steps"], x)
        np.testing.assert_allclose(got, want, atol=1e-6)
        # attr form (opset<10)
        nodes = [node("Slice", ["x"], ["y"],
                      attrs=[attr_ints("starts", [0, 2]),
                             attr_ints("ends", [2, 5]),
                             attr_ints("axes", [0, 1])])]
        np.testing.assert_allclose(self._run1(nodes, [], ["x"], x),
                                   x[0:2, 2:5], atol=1e-6)

    def test_pad_with_traced_float_value(self, orca_ctx):
        """A float initializer as Pad's constant value must work under
        jit (it lands in params and is traced)."""
        nodes = [node("Pad", ["x", "pads", "cv"], ["y"])]
        inits = [tensor_proto("pads", np.asarray([0, 1, 0, 1], np.int64)),
                 tensor_proto("cv", np.float32(-2.5).reshape(()))]
        x = np.random.RandomState(5).randn(2, 3).astype(np.float32)
        got = self._run1(nodes, inits, ["x", "pads", "cv"], x)
        np.testing.assert_allclose(
            got, np.pad(x, [(0, 0), (1, 1)], constant_values=-2.5),
            atol=1e-6)

    def test_reduce_sum_noop_with_empty_axes(self, orca_ctx):
        nodes = [node("ReduceSum", ["x"], ["y"],
                      attrs=[attr_int("noop_with_empty_axes", 1)])]
        x = np.random.RandomState(6).randn(3, 2).astype(np.float32)
        np.testing.assert_allclose(self._run1(nodes, [], ["x"], x), x,
                                   atol=1e-6)
