"""HF-format BERT import parity vs the REAL transformers implementation
(installed in this image) — the mapping is checked against the canonical
source, not a hand twin (ref bert_estimator.py init_checkpoint flow)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

from analytics_zoo_tpu.text.bert import BertConfig, BertModule  # noqa: E402
from analytics_zoo_tpu.text.hf_import import hf_bert_params  # noqa: E402


SMALL = dict(vocab=97, hidden_size=32, n_block=2, n_head=2,
             intermediate_size=64, max_position_len=48)


def _hf_model():
    cfg = transformers.BertConfig(
        vocab_size=SMALL["vocab"], hidden_size=SMALL["hidden_size"],
        num_hidden_layers=SMALL["n_block"],
        num_attention_heads=SMALL["n_head"],
        intermediate_size=SMALL["intermediate_size"],
        max_position_embeddings=SMALL["max_position_len"],
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        hidden_act="gelu")
    torch.manual_seed(0)
    return transformers.BertModel(cfg).eval()


def _zoo_config():
    return BertConfig(hidden_drop=0.0, attn_drop=0.0, **SMALL)


class TestHFBertImport:
    def test_sequence_and_pooled_parity(self, orca_ctx):
        """Imported weights reproduce transformers' last_hidden_state AND
        pooler_output, including a ragged attention mask."""
        import jax

        hf = _hf_model()
        cfg = _zoo_config()
        params = hf_bert_params(hf, cfg)
        module = BertModule(cfg)

        rng = np.random.RandomState(0)
        ids = rng.randint(0, SMALL["vocab"], (2, 16)).astype(np.int32)
        seg = (rng.rand(2, 16) < 0.5).astype(np.int32)
        mask = np.ones((2, 16), np.int32)
        mask[0, 11:] = 0                       # padded tail
        mask[1, 14:] = 0

        seq, pooled = module.apply(
            {"params": params}, ids, seg, mask, train=False,
            rngs={"dropout": jax.random.PRNGKey(0)})
        with torch.no_grad():
            out = hf(input_ids=torch.tensor(ids.astype(np.int64)),
                     token_type_ids=torch.tensor(seg.astype(np.int64)),
                     attention_mask=torch.tensor(mask.astype(np.int64)))
        # compare only the VALID positions: inside padding HF still
        # attends (it masks keys, not queries) but those outputs are
        # meaningless downstream
        for b in range(2):
            n = int(mask[b].sum())
            np.testing.assert_allclose(
                np.asarray(seq)[b, :n], out.last_hidden_state[b, :n],
                atol=2e-5)
        np.testing.assert_allclose(np.asarray(pooled), out.pooler_output,
                                   atol=2e-5)

    def test_bert_for_classification_dict_accepted(self, orca_ctx):
        """BertForSequenceClassification dicts (keys under 'bert.') load
        too — the common artifact shape on model hubs."""
        hf = _hf_model()
        sd = {"bert." + k: v for k, v in hf.state_dict().items()}
        sd["classifier.weight"] = torch.zeros(2, 32)   # extra head keys
        params = hf_bert_params(sd, _zoo_config())
        np.testing.assert_allclose(
            params["word_embeddings"]["embedding"],
            hf.state_dict()["embeddings.word_embeddings.weight"].numpy())

    def test_task_estimator_load_hf(self, orca_ctx):
        """BERTClassifier.load_hf: encoder replaced, head kept, predict
        runs; a config mismatch raises a shape error."""
        from analytics_zoo_tpu.text.estimators import BERTClassifier

        hf = _hf_model()
        clf = BERTClassifier(num_classes=3, config=_zoo_config(),
                             seq_len=16)
        clf.load_hf(hf.state_dict())
        est = clf.estimator
        got = np.asarray(
            est.adapter.params["bert"]["word_embeddings"]["embedding"])
        np.testing.assert_allclose(
            got, hf.state_dict()["embeddings.word_embeddings.weight"],
            rtol=1e-6)
        ids = np.zeros((2, 16), np.int32)
        probs = clf.predict(ids)
        assert np.asarray(probs).shape == (2, 3)

        wrong = BERTClassifier(
            num_classes=3, seq_len=16,
            config=BertConfig(hidden_drop=0.0, attn_drop=0.0,
                              vocab=97, hidden_size=16, n_block=2,
                              n_head=2, intermediate_size=64,
                              max_position_len=48))
        with pytest.raises(ValueError, match="config mismatch|shape"):
            wrong.load_hf(hf.state_dict())
