"""Native tiered blob store tests (analog of ref feature/pmem tests +
FeatureSet DISK_n specs)."""

import numpy as np
import pytest

from analytics_zoo_tpu.data.native_store import (
    NativeBlobStore, NativeShardStore, load_native_lib,
)

pytestmark = pytest.mark.skipif(load_native_lib() is None,
                                reason="no native toolchain")


class TestBlobStore:
    def test_put_get_roundtrip(self):
        store = NativeBlobStore(capacity_bytes=1 << 20)
        try:
            blobs = [bytes([i]) * (100 + i) for i in range(10)]
            ids = [store.put(b) for b in blobs]
            for i, b in zip(ids, blobs):
                assert store.get(i) == b
            assert store.count == 10
        finally:
            store.close()

    def test_eviction_under_capacity_pressure(self):
        # capacity fits ~3 of the 10 blobs: older ones spill, reads fault
        # them back in and still return the right bytes
        store = NativeBlobStore(capacity_bytes=3 * 10_000)
        try:
            blobs = [np.random.RandomState(i).bytes(10_000)
                     for i in range(10)]
            ids = [store.put(b) for b in blobs]
            assert store.resident_bytes <= 3 * 10_000
            for i, b in zip(ids, blobs):
                assert store.get(i) == b
            stats = store.stats
            assert stats["misses"] > 0, "expected disk faults under pressure"
            assert stats["hits"] + stats["misses"] == 10
        finally:
            store.close()

    def test_prefetch_stages_blobs(self):
        import time
        store = NativeBlobStore(capacity_bytes=2 * 10_000)
        try:
            blobs = [np.random.RandomState(i).bytes(10_000)
                     for i in range(6)]
            ids = [store.put(b) for b in blobs]
            store.prefetch(ids[:2])
            deadline = time.time() + 5
            while time.time() < deadline:
                if store.get(ids[0]) == blobs[0]:
                    break
                time.sleep(0.01)
            assert store.get(ids[1]) == blobs[1]
        finally:
            store.close()

    def test_empty_blob(self):
        store = NativeBlobStore(capacity_bytes=1000)
        try:
            i = store.put(b"")
            assert store.get(i) == b""
        finally:
            store.close()

    def test_unknown_blob_raises(self):
        store = NativeBlobStore(capacity_bytes=1000)
        try:
            with pytest.raises(KeyError):
                store.get(12345)
        finally:
            store.close()


class TestNativeShardStore:
    def test_shard_roundtrip_with_spill(self):
        rng = np.random.RandomState(0)
        shards = [{"x": rng.randn(100, 8).astype(np.float32),
                   "y": rng.randint(0, 2, 100)} for _ in range(6)]
        store = NativeShardStore(shards, keep_fraction_denom=3)
        assert len(store) == 6
        for i in range(6):
            got = store.get(i)
            np.testing.assert_array_equal(got["x"], shards[i]["x"])
            np.testing.assert_array_equal(got["y"], shards[i]["y"])

    def test_xshards_native_tier(self):
        from analytics_zoo_tpu.data.shard import HostXShards
        rng = np.random.RandomState(1)
        records = [{"x": rng.randn(50, 4)} for _ in range(8)]
        xs = HostXShards(records, tier="NATIVE_4")
        assert xs.tier.startswith("NATIVE")
        out = xs.transform_shard(lambda s: {"x": s["x"] * 2}).collect()
        for rec, o in zip(records, out):
            np.testing.assert_allclose(o["x"], rec["x"] * 2)

    def test_context_tier_setting(self):
        from analytics_zoo_tpu.common.context import OrcaContext
        old = OrcaContext.train_data_store
        try:
            OrcaContext.train_data_store = "NATIVE_2"
            assert OrcaContext.train_data_store == "NATIVE_2"
            with pytest.raises(AssertionError):
                OrcaContext.train_data_store = "PMEM"
        finally:
            OrcaContext.train_data_store = old

    def test_training_from_native_tier(self, orca_ctx):
        """End-to-end: Estimator.fit over a NATIVE-tier dataset."""
        from analytics_zoo_tpu.keras.models import Sequential
        from analytics_zoo_tpu.keras.layers import Dense
        from analytics_zoo_tpu.common.context import OrcaContext

        old = OrcaContext.train_data_store
        try:
            OrcaContext.train_data_store = "NATIVE_2"
            rng = np.random.RandomState(0)
            x = rng.randn(128, 4).astype(np.float32)
            y = (x.sum(1) > 0).astype(np.int32)
            m = Sequential()
            m.add(Dense(8, input_shape=(4,), activation="relu"))
            m.add(Dense(2, activation="softmax"))
            m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
            h = m.fit(x, y, batch_size=32, nb_epoch=2)
            assert all(np.isfinite(v) for v in h["loss"])
        finally:
            OrcaContext.train_data_store = old
