"""Bitwise parity: vectorized/parallel data plane vs the legacy path.

Every fast body in friesian/feature/table.py is gated on
``ZOO_DATA_VECTORIZE`` and claims to reproduce the legacy row-wise output
*bitwise* (values and dtypes). These tests run each transform twice — once
under ``ZOO_DATA_VECTORIZE=0 ZOO_DATA_WORKERS=0`` (legacy kernels, serial
executor) and once under the fast/parallel default — and compare cell for
cell, including the documented edge cases: the empty-history-in-a-
nested-column flat-pad quirk, seq_len truncation of nested lists, int64
mask dtype stability, and ``_shard_seed`` RNG reproducibility across
executor modes.
"""

import os

import numpy as np
import pandas as pd

from analytics_zoo_tpu.friesian.feature import FeatureTable

LEGACY = {"ZOO_DATA_VECTORIZE": "0", "ZOO_DATA_WORKERS": "0"}
FAST = {"ZOO_DATA_VECTORIZE": "1", "ZOO_DATA_WORKERS": "4"}


def _under(env, fn):
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        return fn()
    finally:
        for k, v in old.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _both(build):
    """Run ``build`` under the legacy and the fast env; return both."""
    return _under(LEGACY, build), _under(FAST, build)


def assert_cells_equal(a: pd.DataFrame, b: pd.DataFrame):
    """Cell-wise bitwise comparison tolerant of list-vs-ndarray packaging
    (fast pad/mask emit ndarray rows, legacy emits lists — by design)."""
    assert list(a.columns) == list(b.columns)
    assert len(a) == len(b)
    for c in a.columns:
        for i, (x, y) in enumerate(zip(a[c].tolist(), b[c].tolist())):
            xa, ya = np.asarray(x), np.asarray(y)
            assert xa.shape == ya.shape, (c, i, x, y)
            assert xa.dtype == ya.dtype, (c, i, xa.dtype, ya.dtype)
            assert np.array_equal(xa, ya), (c, i, x, y)


def hist_df():
    return pd.DataFrame({
        "user": [1, 1, 2, 2, 3, 3],
        "flat": [[1, 2], [3], [], [4, 5, 6, 7, 8], [9], []],
        "nested": [[[1, 2], [3, 4]], [[5, 6]], [],
                   [[7, 8], [9, 10], [11, 12]], [[13, 14]], []],
    })


# ------------------------------------------------------------- pad / mask

def test_pad_parity_flat_and_nested():
    def build():
        t = FeatureTable.from_pandas(hist_df(), 3)
        return t.pad(["flat", "nested"], seq_len=4).to_pandas()
    legacy, fast = _both(build)
    assert_cells_equal(legacy, fast)
    # the quirk: an empty cell in the *nested* column pads flat, not
    # (seq_len, inner) — both paths must keep it
    for df in (legacy, fast):
        empty = df[df["user"] == 2].iloc[0]["nested"]
        assert np.asarray(empty).shape == (4,)
        assert np.asarray(empty).tolist() == [0, 0, 0, 0]


def test_pad_parity_truncates_nested_lists():
    def build():
        t = FeatureTable.from_pandas(hist_df(), 2)
        return t.pad(["flat", "nested"], seq_len=2).to_pandas()
    legacy, fast = _both(build)
    assert_cells_equal(legacy, fast)
    long_nested = fast[fast["user"] == 2].iloc[1]["nested"]
    assert np.asarray(long_nested).shape == (2, 2)
    assert np.asarray(long_nested).tolist() == [[7, 8], [9, 10]]
    long_flat = fast[fast["user"] == 2].iloc[1]["flat"]
    assert np.asarray(long_flat).tolist() == [4, 5]


def test_pad_ragged_inner_falls_back_rowwise():
    # ragged inner widths can't rectangular-fill; both paths must agree
    df = pd.DataFrame({"h": [[[1, 2], [3]], [[4]], []]})

    def build():
        return FeatureTable.from_pandas(df, 1).pad("h", 3).to_pandas()
    legacy, fast = _both(build)
    for x, y in zip(legacy["h"], fast["h"]):
        assert [list(map(int, np.atleast_1d(r))) if hasattr(r, "__len__")
                else r for r in x] == \
               [list(map(int, np.atleast_1d(r))) if hasattr(r, "__len__")
                else r for r in y]


def test_mask_parity_and_int64_dtype():
    def build():
        t = FeatureTable.from_pandas(hist_df(), 3)
        return t.mask(["flat", "nested"], seq_len=3).to_pandas()
    legacy, fast = _both(build)
    assert_cells_equal(legacy, fast)
    for df in (legacy, fast):
        for cell in df["flat_mask"]:
            assert np.asarray(cell).dtype == np.int64
    assert np.asarray(fast["flat_mask"].iloc[3]).tolist() == [1, 1, 1]
    assert np.asarray(fast["flat_mask"].iloc[2]).tolist() == [0, 0, 0]


def test_mask_pad_and_add_length_parity():
    def build():
        t = FeatureTable.from_pandas(hist_df(), 2)
        t = t.mask_pad(padding_cols=["flat"], mask_cols=["flat"], seq_len=4)
        return t.add_length("nested").to_pandas()
    legacy, fast = _both(build)
    assert_cells_equal(legacy, fast)
    assert fast["nested_length"].tolist() == [2, 1, 0, 3, 1, 0]
    assert fast["nested_length"].dtype == np.int64


# ---------------------------------------------------------- add_feature

def test_add_feature_parity_scalar_list_mixed():
    df = pd.DataFrame({"item": [1, 2, 3],
                       "hist": [[1, 2], [2, 9], []]})
    lk = pd.DataFrame({"item": [1, 2, 3], "cat": [7, 8, 9]})

    def build():
        t = FeatureTable.from_pandas(df, 2)
        lookup = FeatureTable.from_pandas(lk, 1)
        return t.add_feature(["item", "hist"], lookup,
                             default_value=0).to_pandas()
    legacy, fast = _both(build)
    assert_cells_equal(legacy, fast)
    assert fast["item_feature"].tolist() == [7, 8, 9]
    # unseen key 9 -> default 0; empty history -> empty feature list
    assert fast["hist_feature"].tolist() == [[7, 8], [8, 0], []]


def test_add_feature_duplicate_keys_last_wins():
    df = pd.DataFrame({"item": [1, 1, 2]})
    lk = pd.DataFrame({"item": [1, 2, 1], "cat": [7, 8, 70]})

    def build():
        t = FeatureTable.from_pandas(df, 1)
        lookup = FeatureTable.from_pandas(lk, 1)
        return t.add_feature(["item"], lookup, default_value=-1).to_pandas()
    legacy, fast = _both(build)
    assert_cells_equal(legacy, fast)
    assert fast["item_feature"].tolist() == [70, 70, 8]


# ----------------------------------------------- aggregations (map-reduce)

def cat_df():
    return pd.DataFrame({
        "user": np.arange(12),
        "price": [1.0, np.nan, 3.0, 4.0, 5.0, np.nan,
                  2.0, 8.0, 1.5, 0.5, 7.0, 6.0],
        "cat": ["a", "b", "a", "c", "a", None, "b", "c", "d", "b", "a", "d"],
    })


def test_gen_string_idx_parity_including_ties():
    def build():
        t = FeatureTable.from_pandas(cat_df(), 3)
        [idx] = t.gen_string_idx("cat")
        return idx.to_dict()
    legacy, fast = _both(build)
    # "b" (3) vs "c"/"d" (2 each): exact id assignment must match, ties
    # broken by first appearance in both paths
    assert legacy == fast
    assert fast["a"] == 1

    def build_limited():
        t = FeatureTable.from_pandas(cat_df(), 3)
        [idx] = t.gen_string_idx("cat", freq_limit=3)
        return idx.to_dict()
    legacy, fast = _both(build_limited)
    assert legacy == fast == {"a": 1, "b": 2}


def test_normalize_median_distinct_size_parity():
    def build():
        t = FeatureTable.from_pandas(cat_df(), 3)
        normed = t.fill_median("price").normalize(["price"]).to_pandas()
        med = t.median("price").to_pandas()
        dup = FeatureTable.from_pandas(
            pd.concat([cat_df(), cat_df()], ignore_index=True), 4)
        return (normed["price"].to_numpy(), med["median"].iloc[0],
                dup.distinct().size(), t.size())
    legacy, fast = _both(build)
    np.testing.assert_array_equal(legacy[0], fast[0])
    assert legacy[1] == fast[1]
    assert legacy[2] == fast[2] == 12
    assert legacy[3] == fast[3] == 12


def test_add_hist_seq_parity():
    df = pd.DataFrame({
        "user": [1, 1, 1, 2, 2, 3, 3, 3, 3],
        "item": [10, 11, 12, 10, 13, 11, 14, 15, 16],
        "time": [1, 2, 3, 1, 2, 1, 2, 3, 4],
    })

    def canon(out):
        out = out.sort_values(["user", "time"]).reset_index(drop=True)
        out["item_hist_seq"] = out["item_hist_seq"].map(list)
        return out

    def build():
        t = FeatureTable.from_pandas(df, 3)
        return canon(t.add_hist_seq("user", ["item"], sort_col="time",
                                    min_len=1, max_len=2).to_pandas())
    legacy, fast = _both(build)
    # the fast path reshuffles by user, so compare canonicalized content
    assert_cells_equal(legacy, fast)
    assert fast[fast["user"] == 3]["item_hist_seq"].tolist() == \
        [[11], [11, 14], [14, 15]]


# ------------------------------------------------------------ arrays + rng

def test_to_sharded_arrays_parity():
    df = pd.DataFrame({"user": np.arange(8), "item": np.arange(8) * 2,
                       "label": [0, 1] * 4})

    def build():
        t = FeatureTable.from_pandas(df, 3)
        return t.to_sharded_arrays(["user", "item"], "label").collect()
    legacy, fast = _both(build)
    assert len(legacy) == len(fast)
    for a, b in zip(legacy, fast):
        assert len(a["x"]) == len(b["x"]) == 2
        for xa, xb in zip(a["x"], b["x"]):
            assert xa.dtype == xb.dtype
            np.testing.assert_array_equal(xa, xb)
        assert a["y"].dtype == b["y"].dtype
        np.testing.assert_array_equal(a["y"], b["y"])


def test_negative_sampling_reproducible_across_executors():
    df = pd.DataFrame({"user": np.arange(20) % 5,
                       "item": (np.arange(20) * 3) % 50 + 1})

    def build():
        t = FeatureTable.from_pandas(df, 4)
        return t.add_negative_samples(item_size=50, neg_num=2).to_pandas()
    legacy, fast = _both(build)
    # _shard_seed depends only on shard content: serial-legacy, parallel,
    # and a parallel rerun must all draw identical negatives in order
    fast2 = _under(FAST, build)
    pd.testing.assert_frame_equal(legacy, fast)
    pd.testing.assert_frame_equal(fast, fast2)


def test_add_neg_hist_seq_reproducible_across_executors():
    df = pd.DataFrame({
        "user": [1, 1, 1, 2, 2],
        "item": [10, 11, 12, 10, 13],
        "time": [1, 2, 3, 1, 2],
    })

    def build():
        t = FeatureTable.from_pandas(df, 2)
        out = t.add_hist_seq("user", ["item"], min_len=1, max_len=4)
        out = out.add_neg_hist_seq(30, "item_hist_seq", neg_num=2)
        d = out.to_pandas().sort_values(["user", "time"]
                                        ).reset_index(drop=True)
        d["item_hist_seq"] = d["item_hist_seq"].map(list)
        d["neg_item_hist_seq"] = d["neg_item_hist_seq"].map(
            lambda nn: [list(n) for n in nn])
        return d
    legacy, fast = _both(build)
    fast2 = _under(FAST, build)
    assert fast.equals(fast2)
    # neg draws are seeded from shard content; the reshuffling fast
    # add_hist_seq regroups rows into different shards, so only shape
    # invariants (not draws) are comparable across modes
    for d in (legacy, fast):
        assert all(len(nn) == 2 for nn in d["neg_item_hist_seq"])
        assert all(len(nn[0]) == len(h) for nn, h in
                   zip(d["neg_item_hist_seq"], d["item_hist_seq"]))
