"""Real 2-process multi-host training (VERDICT r3 missing #1).

The reference's whole purpose is multi-node training (ref
pyzoo/zoo/orca/learn/tf2/tf_runner.py:281-318 builds a real multi-worker
ring; pyzoo/zoo/orca/learn/mpi/mpi_estimator.py:28 launches real
processes).  Here we launch TWO real Python processes, each with 4 virtual
CPU devices, connected by ``jax.distributed.initialize`` + gloo
collectives, and assert the distributed ``JaxEstimator.fit`` loss history
matches a single-process run on the same global batches — the end-to-end
proof that ``ShardedDataset``'s per-process batch slicing plus
``jax.make_array_from_process_local_data`` reconstruct the exact global
computation.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLE = os.path.join(REPO, "examples", "multihost_launch.py")

EPOCHS = 2
BATCH = 32


import functools


def _launch(strategy):
    """Run the 2-process example with a strategy; return the parsed
    MULTIHOST_RESULT."""
    proc = subprocess.run(
        [sys.executable, EXAMPLE, "--num-processes", "2",
         "--epochs", str(EPOCHS), "--batch-size", str(BATCH),
         "--strategy", strategy],
        capture_output=True, text=True, timeout=800, cwd=REPO,
        env=dict(os.environ))
    assert proc.returncode == 0, (
        f"multihost launch ({strategy}) failed:\n"
        f"stdout:\n{proc.stdout[-3000:]}\nstderr:\n{proc.stderr[-2000:]}")
    line = next(l for l in proc.stdout.splitlines()
                if l.startswith("MULTIHOST_RESULT "))
    return json.loads(line[len("MULTIHOST_RESULT "):])


@functools.lru_cache(maxsize=1)
def _single_process_reference():
    """Same model/data/optimizer as the example's workers, full dataset,
    run in-process on the conftest 8-device CPU mesh (memoized — both
    comparison tests share one run)."""
    sys.path.insert(0, os.path.join(REPO, "examples"))
    import multihost_launch as mh
    from analytics_zoo_tpu import init_orca_context

    init_orca_context(cluster_mode="local")
    x, y = mh.make_data()
    est = mh.build_estimator(x.shape[1])
    hist = est.fit((x, y), epochs=EPOCHS, batch_size=BATCH, shuffle=False)
    return hist["loss"]


def test_two_process_fit_matches_single_process():
    result = _launch("dp")

    assert result["process_count"] == 2
    assert result["global_devices"] == 8
    assert len(result["loss"]) == EPOCHS
    # training must actually make progress
    assert result["loss"][-1] < result["loss"][0]

    ref_loss = _single_process_reference()
    # Same global batch sets (block-interleaved split), so the histories
    # agree up to reduction-order float error.
    np.testing.assert_allclose(result["loss"], ref_loss, rtol=0, atol=2e-4)


def test_local_rows_partition_is_exact():
    """The block-interleave split covers each global batch exactly once."""
    sys.path.insert(0, os.path.join(REPO, "examples"))
    import multihost_launch as mh

    n, B, P = 256, 32, 2
    parts = [mh.local_rows(n, B, p, P) for p in range(P)]
    h = B // P
    for p, rows in enumerate(parts):
        assert len(rows) == n // P
        # k-th local chunk of process p == global rows [k*B+p*h, k*B+(p+1)*h)
        for k in range(n // B):
            np.testing.assert_array_equal(
                rows[k * h:(k + 1) * h],
                np.arange(k * B + p * h, k * B + (p + 1) * h))
    together = np.sort(np.concatenate(parts))
    np.testing.assert_array_equal(together, np.arange(n))


def test_two_process_fsdp_matches_dp():
    """Parameter-sharded training across REAL processes: strategy "fsdp"
    spans the full 8-device axis ACROSS both hosts (4 devices each), so
    every parameter/optimizer shard group crosses the process boundary —
    its all-gather/reduce-scatter rides the cross-process fabric (a
    dp2,fsdp4 layout would keep fsdp intra-process and prove nothing).
    The loss history must match plain dp (same math, different layout)."""
    result = _launch("fsdp")
    assert result["strategy"] == "fsdp"
    assert result["loss"][-1] < result["loss"][0]
    ref_loss = _single_process_reference()
    np.testing.assert_allclose(result["loss"], ref_loss, rtol=0, atol=2e-4)


def _launch_ex(*args):
    """Run the launcher with extra args; return parsed MULTIHOST_RESULT."""
    proc = subprocess.run(
        [sys.executable, EXAMPLE, "--epochs", str(EPOCHS),
         "--batch-size", str(BATCH)] + list(args),
        capture_output=True, text=True, timeout=800, cwd=REPO,
        env=dict(os.environ))
    assert proc.returncode == 0, (
        f"multihost launch {args} failed:\n"
        f"stdout:\n{proc.stdout[-3000:]}\nstderr:\n{proc.stderr[-2000:]}")
    line = next(l for l in proc.stdout.splitlines()
                if l.startswith("MULTIHOST_RESULT "))
    return json.loads(line[len("MULTIHOST_RESULT "):])


def test_four_process_dp_matches_single_process():
    """Beyond the 2-process minimum (VERDICT r4 weak #6): FOUR real
    processes x 2 virtual devices each — same global math."""
    result = _launch_ex("--num-processes", "4", "--local-devices", "2",
                        "--strategy", "dp")
    assert result["process_count"] == 4
    assert result["global_devices"] == 8
    ref_loss = _single_process_reference()
    np.testing.assert_allclose(result["loss"], ref_loss, rtol=0, atol=2e-4)


def test_two_process_tp_spans_processes():
    """Tensor parallelism ACROSS the process boundary: strategy tp8 puts
    every Megatron shard group over all 8 devices of both hosts (a
    dp2,tp4 layout would keep tp intra-process and prove nothing); the
    batch is process-replicated (ShardingStrategy.batch_feed_fraction ==
    1.0, each host feeds the full batch). Same math as dp."""
    result = _launch_ex("--num-processes", "2", "--strategy", "tp8")
    assert result["strategy"] == "tp8"
    ref_loss = _single_process_reference()
    np.testing.assert_allclose(result["loss"], ref_loss, rtol=0, atol=2e-4)


def test_two_process_pipeline_spans_processes():
    """Pipeline parallelism across processes: 8 stages over 2 hosts — the
    stage-3 -> stage-4 microbatch handoff crosses the process boundary.
    Compared against the SAME PipelinedMLP on a single-process 8-device
    mesh."""
    result = _launch_ex("--num-processes", "2", "--strategy", "pp")
    assert result["loss"][-1] < result["loss"][0]

    sys.path.insert(0, os.path.join(REPO, "examples"))
    import multihost_launch as mh
    from analytics_zoo_tpu import init_orca_context
    init_orca_context(cluster_mode="local")
    x, y = mh.make_data()
    est = mh.build_pipeline_estimator(x.shape[1], 8)
    ref = est.fit((x, y), epochs=EPOCHS, batch_size=BATCH, shuffle=False)
    np.testing.assert_allclose(result["loss"], ref["loss"], rtol=0,
                               atol=2e-4)


def test_two_process_streaming_feed_matches():
    """Multihost fed from StreamingShardedDataset (the DiskFeatureSet
    analog): each worker streams its own shard windows; same losses as
    the in-memory feed."""
    result = _launch_ex("--num-processes", "2", "--strategy", "dp",
                        "--data", "streaming")
    assert result["data_mode"] == "streaming"
    ref_loss = _single_process_reference()
    np.testing.assert_allclose(result["loss"], ref_loss, rtol=0, atol=2e-4)


def test_non_process_major_batch_layout_refused():
    """A strategy whose batch axes don't span the processes (e.g.
    "tp4,dp2": model-major mesh, every data index local to each host)
    must be REFUSED — feeding local slices there would give cross-process
    replicas different rows and silently wrong gradients."""
    proc = subprocess.run(
        [sys.executable, EXAMPLE, "--num-processes", "2",
         "--epochs", "1", "--batch-size", str(BATCH),
         "--strategy", "tp4,dp2"],
        capture_output=True, text=True, timeout=800, cwd=REPO,
        env=dict(os.environ))
    assert proc.returncode != 0
    assert "do not span the processes" in proc.stdout + proc.stderr
