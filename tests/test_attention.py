import numpy as np
import pytest


def _qkv(b=2, s=32, h=2, d=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: rng.normal(size=(b, s, h, d)).astype(np.float32)
    return mk(), mk(), mk()


def _reference(q, k, v, causal=False):
    import jax.numpy as jnp
    from analytics_zoo_tpu.ops.attention import _reference_attention
    return np.asarray(_reference_attention(jnp.asarray(q), jnp.asarray(k),
                                           jnp.asarray(v), causal=causal))


def test_blockwise_matches_reference(orca_ctx):
    from analytics_zoo_tpu.ops.flash_attention import blockwise_attention
    q, k, v = _qkv()
    for causal in (False, True):
        ref = _reference(q, k, v, causal)
        out = np.asarray(blockwise_attention(q, k, v, causal=causal, block_k=8))
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_blockwise_ragged_seq(orca_ctx):
    from analytics_zoo_tpu.ops.flash_attention import blockwise_attention
    q, k, v = _qkv(s=20)  # not a multiple of block_k
    ref = _reference(q, k, v, True)
    out = np.asarray(blockwise_attention(q, k, v, causal=True, block_k=8))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_blockwise_grad_matches(orca_ctx):
    import jax
    import jax.numpy as jnp
    from analytics_zoo_tpu.ops.flash_attention import blockwise_attention
    from analytics_zoo_tpu.ops.attention import _reference_attention
    q, k, v = _qkv(b=1, s=16, h=1, d=4)

    def loss_block(q, k, v):
        return blockwise_attention(q, k, v, causal=True, block_k=8).sum()

    def loss_ref(q, k, v):
        return _reference_attention(jnp.asarray(q), jnp.asarray(k),
                                    jnp.asarray(v), causal=True).sum()

    g1 = jax.grad(loss_block, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)


def test_ring_attention_matches_full(orca_ctx):
    from analytics_zoo_tpu.parallel.strategy import ShardingStrategy
    from analytics_zoo_tpu.parallel.mesh import place_on_mesh
    from analytics_zoo_tpu.ops.ring_attention import ring_attention
    from jax.sharding import PartitionSpec as P

    s = ShardingStrategy.parse("dp2,sp4")
    mesh = s.build_mesh()
    q, k, v = _qkv(b=4, s=32, h=2, d=8)
    spec_fn = lambda a: P("data", "seq", None, None)
    gq, gk, gv = (place_on_mesh(t, mesh, spec_fn) for t in (q, k, v))

    for causal in (False, True):
        out = np.asarray(ring_attention(gq, gk, gv, mesh=mesh, causal=causal,
                                        batch_axis="data"))
        ref = _reference(q, k, v, causal)
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_ring_attention_seq_only_mesh(orca_ctx):
    from analytics_zoo_tpu.parallel.strategy import ShardingStrategy
    from analytics_zoo_tpu.parallel.mesh import place_on_mesh
    from analytics_zoo_tpu.ops.ring_attention import ring_attention
    from jax.sharding import PartitionSpec as P

    s = ShardingStrategy.parse("sp8")
    mesh = s.build_mesh()
    q, k, v = _qkv(b=2, s=64, h=2, d=8, seed=3)
    spec_fn = lambda a: P(None, "seq", None, None)
    gq, gk, gv = (place_on_mesh(t, mesh, spec_fn) for t in (q, k, v))
    out = np.asarray(ring_attention(gq, gk, gv, mesh=mesh, causal=True))
    ref = _reference(q, k, v, True)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_ring_attention_grad(orca_ctx):
    """Ring attention must be differentiable (it sits inside train steps)."""
    import jax
    from analytics_zoo_tpu.parallel.strategy import ShardingStrategy
    from analytics_zoo_tpu.parallel.mesh import place_on_mesh
    from analytics_zoo_tpu.ops.ring_attention import ring_attention
    from analytics_zoo_tpu.ops.attention import _reference_attention
    from jax.sharding import PartitionSpec as P
    import jax.numpy as jnp

    s = ShardingStrategy.parse("sp4")
    mesh = s.build_mesh(devices=jax.devices()[:4])
    q, k, v = _qkv(b=1, s=16, h=1, d=4, seed=5)
    spec_fn = lambda a: P(None, "seq", None, None)
    gq, gk, gv = (place_on_mesh(t, mesh, spec_fn) for t in (q, k, v))

    g1 = jax.grad(lambda q, k, v: ring_attention(
        q, k, v, mesh=mesh, causal=False).sum(), argnums=(0, 1, 2))(gq, gk, gv)
    g2 = jax.grad(lambda q, k, v: _reference_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)).sum(),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)


def test_flash_kernel_interpret_mode(orca_ctx):
    """Pallas kernel numerics vs reference, in interpret mode on CPU."""
    import jax.experimental.pallas as pl
    from analytics_zoo_tpu.ops import flash_attention as fa
    import functools
    import jax

    q, k, v = _qkv(b=1, s=256, h=2, d=128, seed=7)
    # run the pallas_call in interpret mode by monkeypatching pallas_call
    orig = pl.pallas_call
    try:
        pl.pallas_call = functools.partial(orig, interpret=True)
        out = np.asarray(fa._flash_fwd(q, k, v, causal=True,
                                       block_q=128, block_k=128))
    finally:
        pl.pallas_call = orig
    ref = _reference(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-4)


def test_flash_backward_kernel_interpret_mode(orca_ctx):
    """FlashAttention-2 backward kernels (dq + dk/dv over the saved
    logsumexp) vs the blockwise vjp, interpret mode on CPU — exact in
    fp32, bf16-rounding otherwise. Also checks the lse the forward
    saves."""
    import functools
    import jax
    import jax.numpy as jnp
    import jax.experimental.pallas as pl
    from analytics_zoo_tpu.ops import flash_attention as fa

    orig = pl.pallas_call
    try:
        pl.pallas_call = functools.partial(orig, interpret=True)
        for causal in (False, True):
            q, k, v = _qkv(b=2, s=256, h=2, d=128, seed=11 + causal)
            g = np.asarray(jax.random.normal(
                jax.random.PRNGKey(3), (2, 256, 2, 128)), np.float32)

            # call the kernels DIRECTLY: flash_attention's vjp would
            # silently fall back to the blockwise reference on a broken
            # kernel, making the comparison vacuous
            out, lse = fa._flash_fwd(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v), causal=causal,
                                     block_q=128, block_k=128,
                                     return_lse=True)
            gf = fa._flash_bwd(jnp.asarray(q), jnp.asarray(k),
                               jnp.asarray(v), out, lse, jnp.asarray(g),
                               causal, 128, 128)

            def f_block(q, k, v):
                return (fa.blockwise_attention(
                    jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                    causal=causal) * jnp.asarray(g)).sum()

            gb = jax.grad(f_block, argnums=(0, 1, 2))(q, k, v)
            for name, a, b in zip("qkv", gf, gb):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-4,
                    err_msg=f"d{name} causal={causal}")
            # the saved lse must equal the true logsumexp of scaled scores
            scores = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(128)
            if causal:
                mask = np.tril(np.ones((256, 256), bool))
                scores = np.where(mask[None, None], scores, -1e30)
            ref_lse = np.log(np.exp(
                scores - scores.max(-1, keepdims=True)).sum(-1))                 + scores.max(-1)
            np.testing.assert_allclose(
                np.asarray(lse).reshape(2, 2, 256),
                ref_lse.astype(np.float32), rtol=1e-4, atol=1e-4)
    finally:
        pl.pallas_call = orig


def test_flash_head_dim_64_parity(orca_ctx, monkeypatch):
    """head_dim 64 (the BERT class) packs into the 128 lane: forward
    parity vs the reference, full and causal, plus a ragged sequence
    (s % block != 0 — the padded tail k-block must mask to −∞, ISSUE 8
    satellite). Runs via ZOO_PALLAS_INTERPRET so the real kernel bodies
    execute on CPU, exercising the same knob docs/kernels.md documents."""
    import jax.numpy as jnp
    from analytics_zoo_tpu.ops import flash_attention as fa

    monkeypatch.setenv("ZOO_PALLAS_INTERPRET", "1")
    for s, causal in ((256, False), (256, True), (200, True), (40, False)):
        q, k, v = _qkv(b=1, s=s, h=2, d=64, seed=17 + s)
        out = np.asarray(fa.flash_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal,
            128, 128))
        ref = _reference(q, k, v, causal)
        np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-4,
                                   err_msg=f"s={s} causal={causal}")


def test_flash_head_dim_64_backward(orca_ctx, monkeypatch):
    """FA-2 backward kernels at head_dim 64, aligned AND ragged seq: the
    kernels are called directly (the custom_vjp would silently fall back
    to blockwise on a broken kernel, making the comparison vacuous).
    Padded lse rows carry +1e30 so padded-row p is exactly 0 — grads for
    real rows must match the blockwise vjp."""
    import jax
    import jax.numpy as jnp
    from analytics_zoo_tpu.ops import flash_attention as fa

    monkeypatch.setenv("ZOO_PALLAS_INTERPRET", "1")
    for s, causal in ((256, True), (200, False), (200, True)):
        q, k, v = _qkv(b=1, s=s, h=2, d=64, seed=29 + s)
        g = np.asarray(jax.random.normal(
            jax.random.PRNGKey(31), (1, s, 2, 64)), np.float32)
        out, lse = fa._flash_fwd(jnp.asarray(q), jnp.asarray(k),
                                 jnp.asarray(v), causal=causal,
                                 block_q=128, block_k=128,
                                 return_lse=True)
        gf = fa._flash_bwd(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                           out, lse, jnp.asarray(g), causal, 128, 128)

        def f_block(q, k, v):
            return (fa.blockwise_attention(q, k, v, causal=causal)
                    * jnp.asarray(g)).sum()

        gb = jax.grad(f_block, argnums=(0, 1, 2))(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        for name, a, b in zip("qkv", gf, gb):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=5e-4,
                err_msg=f"d{name} s={s} causal={causal}")


def test_flash_cross_attention_ragged_kv(orca_ctx, monkeypatch):
    """Cross-attention with sq < sk and a ragged kv length (the KV-cache
    decode shape): the causal offset comes from the ORIGINAL lengths —
    bottom-right alignment must not shift when the tail k-block pads.
    sq stays <= sk: a causal query with ZERO visible keys is degenerate
    (every implementation emits a different 'uniform' placeholder)."""
    import jax.numpy as jnp
    from analytics_zoo_tpu.ops import flash_attention as fa

    monkeypatch.setenv("ZOO_PALLAS_INTERPRET", "1")
    rng = np.random.default_rng(41)
    q = rng.normal(size=(1, 16, 2, 64)).astype(np.float32)
    k = rng.normal(size=(1, 24, 2, 64)).astype(np.float32)
    v = rng.normal(size=(1, 24, 2, 64)).astype(np.float32)
    for causal in (False, True):
        out = np.asarray(fa.flash_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal, 16, 16))
        ref = _reference(q, k, v, causal)
        np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-4,
                                   err_msg=f"causal={causal}")


def test_default_use_flash_relaxed(orca_ctx):
    """head_dim 64 and ragged seq no longer disqualify a shape (the
    kernels pad internally); the remaining exclusions are economic:
    sub-block sequences and head dims past 512. Off-TPU always False."""
    import jax
    from analytics_zoo_tpu.ops.flash_attention import default_use_flash

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    # CPU test env: the gate must still say no (pallas needs the TPU)
    assert default_use_flash(2048, 64) == on_tpu
    assert default_use_flash(2000, 64) == on_tpu   # ragged seq eligible
    assert not default_use_flash(64, 64)           # shorter than a block
    assert not default_use_flash(2048, 1024)       # VMEM pressure


def test_ring_flash_composition(orca_ctx):
    """ring_attention(use_flash=True): each resident block runs the
    pallas kernels and ring steps merge via logsumexp (the lse cotangent
    flows through flash_attention_with_lse's backward). Forward AND
    gradients must match blockwise over the full sequence."""
    import functools
    import jax
    import jax.numpy as jnp
    import jax.experimental.pallas as pl
    from jax.sharding import NamedSharding, PartitionSpec as P
    from analytics_zoo_tpu.parallel.strategy import ShardingStrategy
    from analytics_zoo_tpu.ops.ring_attention import ring_attention
    from analytics_zoo_tpu.ops.flash_attention import blockwise_attention

    mesh = ShardingStrategy.parse("sp8").build_mesh()
    key = jax.random.PRNGKey(0)
    B, S, H, D = 1, 1024, 1, 128
    q, k, v = (np.asarray(jax.random.normal(kk, (B, S, H, D)), np.float32)
               for kk in jax.random.split(key, 3))
    sh = NamedSharding(mesh, P(None, "seq", None, None))
    gq, gk, gv = (jax.device_put(a, sh) for a in (q, k, v))
    g = np.asarray(jax.random.normal(jax.random.PRNGKey(9),
                                     (B, S, H, D)), np.float32)

    orig = pl.pallas_call
    try:
        pl.pallas_call = functools.partial(orig, interpret=True)
        for causal in (False, True):
            out = np.asarray(ring_attention(gq, gk, gv, mesh=mesh,
                                            causal=causal, use_flash=True))
            ref = np.asarray(blockwise_attention(
                jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                causal=causal))
            np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)
            gr = jax.grad(lambda q, k, v: (ring_attention(
                q, k, v, mesh=mesh, causal=causal, use_flash=True)
                * jnp.asarray(g)).sum(), argnums=(0, 1, 2))(gq, gk, gv)
            gb = jax.grad(lambda q, k, v: (blockwise_attention(
                q, k, v, causal=causal) * jnp.asarray(g)).sum(),
                argnums=(0, 1, 2))(jnp.asarray(q), jnp.asarray(k),
                                   jnp.asarray(v))
            for name, a, b in zip("qkv", gr, gb):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=1e-3, atol=5e-4,
                    err_msg=f"d{name} causal={causal}")
    finally:
        pl.pallas_call = orig


class TestCausalCrossLength:
    """Regression: causal mask must be bottom-right aligned (KV-cache decode
    semantics) in every implementation, not just _reference_attention."""

    def test_blockwise_matches_reference_when_sq_ne_sk(self):
        import numpy as np
        import jax
        from analytics_zoo_tpu.ops.attention import _reference_attention
        from analytics_zoo_tpu.ops.flash_attention import blockwise_attention

        rng = jax.random.PRNGKey(0)
        kq, kk, kv = jax.random.split(rng, 3)
        q = jax.random.normal(kq, (1, 4, 2, 8))
        k = jax.random.normal(kk, (1, 8, 2, 8))
        v = jax.random.normal(kv, (1, 8, 2, 8))
        ref = _reference_attention(q, k, v, causal=True)
        blk = blockwise_attention(q, k, v, causal=True, block_k=4)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(blk),
                                   rtol=2e-5, atol=2e-5)


def test_ulysses_flash_composition(orca_ctx):
    """ulysses_attention(use_flash=True): per-device full attention runs
    the pallas kernels after the seq->head all-to-all; fwd + grads match
    the einsum path."""
    import functools
    import jax
    import jax.numpy as jnp
    import jax.experimental.pallas as pl
    from jax.sharding import NamedSharding, PartitionSpec as P
    from analytics_zoo_tpu.parallel.strategy import ShardingStrategy
    from analytics_zoo_tpu.ops.ulysses import ulysses_attention

    mesh = ShardingStrategy.parse("sp2").build_mesh()
    key = jax.random.PRNGKey(1)
    B, S, H, D = 1, 256, 2, 128
    q, k, v = (np.asarray(jax.random.normal(kk, (B, S, H, D)), np.float32)
               for kk in jax.random.split(key, 3))
    sh = NamedSharding(mesh, P(None, "seq", None, None))
    gq, gk, gv = (jax.device_put(a, sh) for a in (q, k, v))
    g = np.asarray(jax.random.normal(jax.random.PRNGKey(5),
                                     (B, S, H, D)), np.float32)

    orig = pl.pallas_call
    try:
        pl.pallas_call = functools.partial(orig, interpret=True)
        for causal in (False, True):
            out = np.asarray(ulysses_attention(gq, gk, gv, mesh=mesh,
                                               causal=causal,
                                               use_flash=True))
            ref = np.asarray(ulysses_attention(gq, gk, gv, mesh=mesh,
                                               causal=causal,
                                               use_flash=False))
            np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)
            gr = jax.grad(lambda q, k, v: (ulysses_attention(
                q, k, v, mesh=mesh, causal=causal, use_flash=True)
                * jnp.asarray(g)).sum(), argnums=(0, 1, 2))(gq, gk, gv)
            gb = jax.grad(lambda q, k, v: (ulysses_attention(
                q, k, v, mesh=mesh, causal=causal, use_flash=False)
                * jnp.asarray(g)).sum(), argnums=(0, 1, 2))(gq, gk, gv)
            for name, a, b in zip("qkv", gr, gb):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=1e-3, atol=5e-4,
                    err_msg=f"d{name} causal={causal}")
    finally:
        pl.pallas_call = orig


def test_ulysses_matches_full(orca_ctx):
    """All-to-all sequence parallelism: sequence-sharded q/k/v through two
    all-to-alls + local full attention must equal single-device
    attention."""
    from analytics_zoo_tpu.parallel.strategy import ShardingStrategy
    from analytics_zoo_tpu.parallel.mesh import place_on_mesh
    from analytics_zoo_tpu.ops.ulysses import ulysses_attention
    from jax.sharding import PartitionSpec as P

    s = ShardingStrategy.parse("dp2,sp4")
    mesh = s.build_mesh()
    q, k, v = _qkv(b=4, s=32, h=4, d=8)   # heads divisible by sp=4
    spec_fn = lambda a: P("data", "seq", None, None)  # noqa: E731
    gq, gk, gv = (place_on_mesh(t, mesh, spec_fn) for t in (q, k, v))

    for causal in (False, True):
        out = np.asarray(ulysses_attention(gq, gk, gv, mesh=mesh,
                                           causal=causal,
                                           batch_axis="data"))
        ref = _reference(q, k, v, causal)
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_ulysses_grad_matches(orca_ctx):
    import jax
    import jax.numpy as jnp
    from analytics_zoo_tpu.parallel.strategy import ShardingStrategy
    from analytics_zoo_tpu.parallel.mesh import place_on_mesh
    from analytics_zoo_tpu.ops.ulysses import ulysses_attention
    from analytics_zoo_tpu.ops.attention import _reference_attention
    from jax.sharding import PartitionSpec as P

    s = ShardingStrategy.parse("sp4")
    mesh = s.build_mesh()
    q, k, v = _qkv(b=2, s=16, h=4, d=4)
    spec_fn = lambda a: P(None, "seq", None, None)  # noqa: E731
    gq, gk, gv = (place_on_mesh(t, mesh, spec_fn) for t in (q, k, v))

    def loss_u(q, k, v):
        return ulysses_attention(q, k, v, mesh=mesh, causal=True).sum()

    def loss_ref(q, k, v):
        return _reference_attention(jnp.asarray(q), jnp.asarray(k),
                                    jnp.asarray(v), causal=True).sum()

    gu = jax.grad(loss_u, argnums=(0, 1, 2))(gq, gk, gv)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gu, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)


def test_ulysses_validates_divisibility(orca_ctx):
    from analytics_zoo_tpu.parallel.strategy import ShardingStrategy
    from analytics_zoo_tpu.ops.ulysses import ulysses_attention

    s = ShardingStrategy.parse("sp4")
    mesh = s.build_mesh()
    q, k, v = _qkv(b=2, s=16, h=3, d=4)   # 3 heads % 4 != 0
    with pytest.raises(ValueError, match="divide"):
        ulysses_attention(q, k, v, mesh=mesh)


class TestSelfAttentionFlag:
    """AttentionModule.self_attention: the packed-QKV path must be
    forceable — the ``kv_in is q_in`` identity fallback does not survive
    transforms that rebind arguments (checkpoint/vmap hand the module two
    distinct tracers for the same value)."""

    def _setup(self, **kw):
        import jax
        from analytics_zoo_tpu.ops.attention import AttentionModule
        m = AttentionModule(num_heads=2, head_dim=8, **kw)
        x = np.random.default_rng(5).normal(
            size=(2, 16, 32)).astype(np.float32)
        params = m.init(jax.random.PRNGKey(0), x)
        return m, params, x

    @staticmethod
    def _n_dots(fn, *args):
        import jax
        return str(jax.make_jaxpr(fn)(*args)).count("dot_general")

    def test_flag_survives_argument_rebinding(self, orca_ctx):
        import jax  # noqa: F401
        m, params, x = self._setup()
        forced, _, _ = self._setup(self_attention=True)
        # identity fallback: a DISTINCT array for the same value silently
        # demotes to three projection matmuls (+2 dot_generals)
        packed = self._n_dots(lambda a: m.apply(params, a), x)
        demoted = self._n_dots(lambda a, b: m.apply(params, a, b), x,
                               x.copy())
        assert demoted == packed + 2
        # the explicit flag keeps the fused matmul through the rebinding
        still_packed = self._n_dots(
            lambda a, b: forced.apply(params, a, b), x, x.copy())
        assert still_packed == packed
        # and the result is bit-identical to plain self-attention
        np.testing.assert_array_equal(
            np.asarray(forced.apply(params, x, x.copy())),
            np.asarray(m.apply(params, x)))

    def test_flag_false_forces_separate_projections(self, orca_ctx):
        m, params, x = self._setup()
        off, _, _ = self._setup(self_attention=False)
        packed = self._n_dots(lambda a: m.apply(params, a), x)
        unpacked = self._n_dots(lambda a: off.apply(params, a), x)
        assert unpacked == packed + 2
        # both formulations compute the same attention (same params, the
        # packed concat is exact) — numerics agree to float tolerance
        np.testing.assert_allclose(np.asarray(off.apply(params, x)),
                                   np.asarray(m.apply(params, x)),
                                   rtol=1e-5, atol=1e-6)
