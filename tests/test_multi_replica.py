"""Multi-replica serving delivery contract (ISSUE 9): consumer-group
broker semantics (lease-based XCLAIM redelivery, per-consumer XPENDING),
BrokerClient transparent reconnect retry, fleet orphan detection,
graceful-drain/deregister ordering, engine idempotence under
redelivery, and the 2-replica SIGKILL chaos drill (slow-marked — the
``chaos`` lane in dev/run-tests.sh runs it)."""

import json
import time
import urllib.request

import numpy as np
import pytest

from analytics_zoo_tpu.common import fleet, resilience, telemetry
from analytics_zoo_tpu.serving import (
    Broker, ClusterServing, InputQueue, OutputQueue,
)
from analytics_zoo_tpu.serving.broker import BrokerClient, build_native_broker


BACKENDS = ["python"] + (["native"] if build_native_broker() else [])

STREAM, GROUP = "serving_stream", "serving"


@pytest.fixture(params=BACKENDS)
def broker(request):
    b = Broker.launch(backend=request.param)
    yield b
    b.stop()


def _counter(family, label=None):
    """Current value of a registry counter from the global snapshot (0.0
    when the family has never been touched)."""
    fam = telemetry.snapshot().get(family, {})
    if not isinstance(fam, dict):
        return float(fam or 0.0)
    if label is None:
        # unlabeled counters snapshot as {"": v} or a bare number
        return float(next(iter(fam.values()), 0.0))
    return float(fam.get(label, 0.0))


# ------------------------------------------------- broker lease semantics

class TestLeaseSemantics:
    def test_xclaim_never_steals_claimer_own_lease(self, broker):
        c = broker.client()
        for i in range(3):
            c.xadd("s", f"cDA{i}=")
        assert len(c.xreadgroup("g", "c0", "s", 10)) == 3
        # idle 0 qualifies every entry, but c0 owns them: nothing moves
        assert c.xclaim("s", "g", "c0", 0, 10) == []
        assert c.xpending_detail("s", "g") == {"c0": 3}
        # a DIFFERENT consumer takes all three; ownership transfers
        got = c.xclaim("s", "g", "c1", 0, 10)
        assert [e[0] for e in got] == [1, 2, 3]
        assert c.xpending_detail("s", "g") == {"c1": 3}

    def test_xclaim_on_acked_entries_is_noop(self, broker):
        c = broker.client()
        for i in range(2):
            c.xadd("s", "YQ==")
        got = c.xreadgroup("g", "c0", "s", 10)
        for eid, _ in got:
            assert c.xack("s", "g", eid) == 1
        assert c.xpending("s", "g") == 0
        assert c.xclaim("s", "g", "c1", 0, 10) == []
        assert c.xpending_detail("s", "g") == {}

    def test_lease_expiry_boundary(self, broker):
        c = broker.client()
        c.xadd("s", "YQ==")
        c.xreadgroup("g", "c0", "s", 1)
        # lease still fresh: a long min_idle refuses the claim
        assert c.xclaim("s", "g", "c1", 60_000, 10) == []
        time.sleep(0.25)
        got = c.xclaim("s", "g", "c1", 200, 10)
        assert [e[0] for e in got] == [1]
        # claiming REFRESHED the lease clock: the original owner cannot
        # immediately claim it back with the same idle threshold
        assert c.xclaim("s", "g", "c0", 200, 10) == []
        time.sleep(0.25)
        assert [e[0] for e in c.xclaim("s", "g", "c0", 200, 10)] == [1]

    def test_xpending_detail_per_consumer(self, broker):
        c = broker.client()
        for i in range(5):
            c.xadd("s", "YQ==")
        a = c.xreadgroup("g", "c0", "s", 3)
        c.xreadgroup("g", "c1", "s", 2)
        assert c.xpending_detail("s", "g") == {"c0": 3, "c1": 2}
        assert c.xpending("s", "g") == 5
        c.xack("s", "g", a[0][0])
        assert c.xpending_detail("s", "g") == {"c0": 2, "c1": 2}

    @pytest.mark.parametrize("backend", BACKENDS)
    def test_hash_ttl_never_evicts_pending_delivery_entries(self, backend):
        """The result-hash TTL reaps uncollected RESULTS only: stream
        entries under an un-acked delivery must survive any TTL so a
        crashed consumer's records stay claimable."""
        b = Broker.launch(backend=backend, hash_ttl_ms=150)
        try:
            c = b.client()
            for i in range(3):
                c.xadd("s", f"cGF5{i}")
            c.xreadgroup("g", "c0", "s", 10)
            c.hset("h", "k", "dg==")
            time.sleep(0.6)
            c.hset("h", "poke", "dg==")       # trigger amortized eviction
            assert c.hget("h", "k") is None    # TTL demonstrably live
            assert c.xlen("s") == 3            # stream untouched
            got = c.xclaim("s", "g", "c1", 0, 10)
            assert [payload for _, payload in got] == \
                ["cGF50", "cGF51", "cGF52"]
            # only a full ack cycle releases the entries
            for eid, _ in got:
                c.xack("s", "g", eid)
            assert c.xlen("s") == 0
        finally:
            b.stop()


# ------------------------------------------------- client reconnect retry

class TestClientReconnect:
    def test_idempotent_reads_survive_broker_restart(self):
        b1 = Broker.launch(backend="python")
        port = b1.port
        c = BrokerClient(port=port)
        try:
            assert c.ping()
            c.xadd("s", "YQ==")
            before = _counter("zoo_broker_reconnects_total")
            b1.stop()
            b2 = Broker.launch(backend="python", port=port)
            try:
                # XLEN rides the transparent reconnect+resend path; the
                # restarted broker is empty, and the generation bump tells
                # id-keyed callers their world was reset
                assert c.xlen("s") == 0
                assert c.generation == 1
                assert _counter("zoo_broker_reconnects_total") == before + 1
            finally:
                b2.stop()
        finally:
            c.close()

    def test_xadd_is_never_transparently_resent(self):
        b1 = Broker.launch(backend="python")
        port = b1.port
        c = BrokerClient(port=port)
        try:
            assert c.ping()
            b1.stop()
            b2 = Broker.launch(backend="python", port=port)
            try:
                # a resend after an ambiguous failure could duplicate the
                # record, so the error must surface to the caller
                with pytest.raises((ConnectionError, OSError)):
                    c.xadd("s", "YQ==")
                fresh = BrokerClient(port=port)
                try:
                    assert fresh.xlen("s") == 0
                finally:
                    fresh.close()
            finally:
                b2.stop()
        finally:
            c.close()


# ------------------------------------------------- fleet orphan detection

def test_replica_supervisor_detects_and_reports_orphans(broker):
    c = broker.client()
    for i in range(4):
        c.xadd(STREAM, "YQ==")
    # "deadbeef" took four deliveries and then vanished — no heartbeat
    c.xreadgroup(GROUP, "deadbeef", STREAM, 10)
    reg = fleet.ReplicaRegistry("127.0.0.1", broker.port)
    now = time.time()
    reg.publish(fleet.ReplicaInfo(replica_id="live-1", started_at=now,
                                  last_heartbeat=now))
    fired = []
    sup = fleet.ReplicaSupervisor(
        reg, STREAM, group=GROUP, broker_port=broker.port,
        own_replica_id="live-1", on_orphans=fired.append)
    snap = sup.sweep()
    assert snap["live"] == 1 and snap["replicas"] == ["live-1"]
    assert snap["pending_per_replica"] == {"deadbeef": 4}
    assert snap["orphan_entries"] == 4
    assert fired == [4]
    assert _counter("zoo_serving_orphan_entries",
                    f"stream={STREAM}") == 4.0
    # once a live consumer claims the leases, the next sweep is clean
    assert len(c.xclaim(STREAM, GROUP, "live-1", 0, 10)) == 4
    snap2 = sup.sweep()
    assert snap2["orphan_entries"] == 0 and snap2["sweeps"] == 2
    assert fired == [4]                      # callback fired only once
    assert sup.snapshot() == snap2


# ------------------------------------------- engine-level delivery contract

class _Duck:
    """Doubler whose first predict may stall — the 'slow replica' whose
    lease expires mid-batch."""

    def __init__(self, first_sleep_s=0.0):
        self.first_sleep_s = first_sleep_s
        self._calls = 0

    def predict(self, x):
        self._calls += 1
        if self._calls == 1 and self.first_sleep_s:
            time.sleep(self.first_sleep_s)
        return np.asarray(x) * 2.0


def test_slow_batch_redelivery_is_idempotent_and_single_sweep():
    """Replica A takes one batch and stalls past its lease; replica B's
    reclaim sweep must redeliver the WHOLE batch in exactly one sweep,
    and A's late finish (duplicate result writes + double-acks) must be
    harmless — every record answered, pending drained to zero."""
    n = 4
    redelivered0 = _counter("zoo_serving_redelivered_total",
                            f"stream={STREAM}")
    reclaims0 = _counter("zoo_serving_lease_reclaims_total",
                         f"stream={STREAM}")
    with Broker.launch(backend="python") as b:
        in_q = InputQueue(port=b.port)
        out_q = OutputQueue(port=b.port)
        # backlog FIRST: A's initial read then takes the whole batch in
        # one delivery, so its stalled lease covers all n records
        uris = in_q.enqueue_batch(
            (f"rd{i}", {"x": np.full(3, i, np.float32)})
            for i in range(n))
        eng_a = ClusterServing(_Duck(first_sleep_s=1.2), b.port,
                               batch_size=n, max_batch_size=n,
                               consumer="repA", claim_min_idle_ms=300,
                               reclaim_interval_s=30.0)
        eng_a.start()
        try:
            # wait until A holds the whole batch, THEN bring up B so the
            # only way B gets work is through lease reclamation
            c = b.client()
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if c.xpending_detail(STREAM, GROUP).get("repA") == n:
                    break
                time.sleep(0.02)
            assert c.xpending_detail(STREAM, GROUP) == {"repA": n}
            eng_b = ClusterServing(_Duck(), b.port, batch_size=n,
                                   max_batch_size=n, consumer="repB",
                                   claim_min_idle_ms=300,
                                   reclaim_interval_s=0.1)
            eng_b.start()
            try:
                res = out_q.query_many(uris, timeout=30.0)
                assert all(v is not None for v in res.values())
                for i in range(n):
                    np.testing.assert_allclose(
                        res[f"rd{i}"], np.full(3, 2.0 * i, np.float32))
                # the batch was redelivered in ONE sweep
                assert _counter("zoo_serving_redelivered_total",
                                f"stream={STREAM}") == redelivered0 + n
                assert _counter("zoo_serving_lease_reclaims_total",
                                f"stream={STREAM}") == reclaims0 + 1
                # A's late duplicate finish drains without residue
                deadline = time.monotonic() + 10.0
                while time.monotonic() < deadline and \
                        c.xpending(STREAM, GROUP):
                    time.sleep(0.05)
                assert c.xpending(STREAM, GROUP) == 0
            finally:
                eng_b.stop()
        finally:
            eng_a.stop()


def test_graceful_stop_acks_all_deliveries_before_deregister():
    """stop() ordering contract: the final drain flushes and acks every
    in-flight delivery BEFORE the heartbeat record is removed, so a peer
    supervisor can never classify drain work as orphaned."""
    with Broker.launch(backend="python") as b:
        eng = ClusterServing(_Duck(), b.port, batch_size=4,
                             max_batch_size=4)
        eng.start()
        try:
            in_q = InputQueue(port=b.port)
            out_q = OutputQueue(port=b.port)
            uris = in_q.enqueue_batch(
                (f"gs{i}", {"x": np.full(3, i, np.float32)})
                for i in range(12))
            res = out_q.query_many(uris, timeout=30.0)
            assert all(v is not None for v in res.values())
        finally:
            eng.stop()
        c = b.client()
        assert c.xpending(STREAM, GROUP) == 0
        reg = fleet.ReplicaRegistry("127.0.0.1", b.port)
        assert all(r.replica_id != eng.replica_id for r in reg.list())


# --------------------------------------------------- SIGKILL chaos drill

@pytest.mark.slow
def test_two_replica_sigkill_chaos_drill():
    """Acceptance (ISSUE 9): two subprocess replicas share one stream;
    SIGKILL one mid-stream through the ``kill@replica`` fault seam. Zero
    records lost, everything acked, the survivor's ``/healthz`` fleet
    view drops to 1 live replica, redelivery lands in exactly one
    lease-reclaim sweep. The victim's predict is wedged (long sleep) so
    its whole in-flight window was delivered within a few ms — one sweep
    reclaims it all, deterministically."""
    n = 64
    env = {"ZOO_SERVING_LEASE_MS": "300", "ZOO_SERVING_RECLAIM_S": "0.25",
           "ZOO_FLEET_HEARTBEAT_S": "0.25", "ZOO_FLEET_STALE_S": "1.0"}

    def snap_metric(port, family):
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics?format=snapshot",
                    timeout=2.0) as r:
                snap = json.loads(r.read().decode())
        except Exception:
            return 0.0
        fam = snap.get(family, {})
        if not isinstance(fam, dict):
            return float(fam or 0.0)
        return float(fam.get(f"stream={STREAM}", 0.0))

    rng = np.random.default_rng(5)
    payloads = rng.standard_normal((n, 4)).astype(np.float32)
    with resilience.fault_drill("kill@replica:1", cpu_fallback=False), \
            Broker.launch(backend="python") as broker:
        victim = resilience.ServingReplicaProc(
            broker.port, batch_size=4, predict_sleep_ms=60_000.0,
            env_extra=env)
        survivor = resilience.ServingReplicaProc(
            broker.port, batch_size=4, predict_sleep_ms=2.0,
            env_extra=env)
        try:
            in_q = InputQueue(port=broker.port)
            out_q = OutputQueue(port=broker.port)
            uris = list(in_q.enqueue_batch(
                (f"ch{i}", {"x": payloads[i]}) for i in range(n)))
            # let the wedged victim fill its in-flight window, then the
            # seam fires on the drill's first checkpoint
            time.sleep(0.3)
            assert resilience.maybe_kill_replica(victim)
            assert not victim.alive
            res = out_q.query_many(uris, timeout=60.0)
            missing = [u for u, v in res.items() if v is None]
            assert not missing, f"{len(missing)} records lost after kill"
            # every delivery acked (late duplicate acks are no-ops)
            c = broker.client()
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline and c.xpending(STREAM, GROUP):
                time.sleep(0.1)
            assert c.xpending(STREAM, GROUP) == 0
            # redelivery is visible on the survivor, in exactly one sweep
            assert snap_metric(survivor.http_port,
                               "zoo_serving_redelivered_total") >= 1.0
            assert snap_metric(survivor.http_port,
                               "zoo_serving_lease_reclaims_total") == 1.0
            # the fleet view converges to one live replica
            deadline = time.monotonic() + 20.0
            live = None
            while time.monotonic() < deadline:
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{survivor.http_port}"
                            "/healthz", timeout=2.0) as r:
                        hz = json.loads(r.read().decode())
                    live = hz.get("fleet", {}).get("replicas")
                    if live == 1:
                        break
                except Exception:
                    pass
                time.sleep(0.25)
            assert live == 1, f"fleet view never dropped to 1 live: {live}"
        finally:
            survivor.stop()
            victim.stop()
