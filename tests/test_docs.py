"""Doc-test lane: every ```python block in docs/*.md actually executes.

The reference shipped a docs site whose snippets routinely rotted
(docs/docs/ProgrammingGuide); here the guides ARE tests — each document's
python blocks run top-to-bottom in one namespace in a fresh subprocess on
the virtual CPU mesh. Blocks marked ``<!-- doctest: skip -->`` on the line
directly above the fence are skipped (e.g. TPU-pod-only or
network-dependent snippets).
"""

import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DOCS = os.path.join(REPO, "docs")

_FENCE = re.compile(
    r"(?P<skip><!--\s*doctest:\s*skip\s*-->\s*\n)?```python\n(?P<body>.*?)```",
    re.S)

_PRELUDE = """
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax
jax.config.update("jax_platforms", "cpu")
# cwd stays the test tmpdir so snippets writing relative paths (ckpts/,
# tb_logs/) land there, never in the repo checkout
import sys
sys.path.insert(0, {repo!r})
"""


# docs whose snippets train real models for minutes on 1 CPU core — run
# them in the full lane only, not in tier-1/smoke (model-zoo ~100s,
# zouwu ~12s measured)
_SLOW_DOCS = {"model-zoo.md", "zouwu.md"}


def _doc_files():
    docs = sorted(f for f in os.listdir(DOCS)
                  if f.endswith(".md") and f not in ("BERT_MFU.md",
                                                     "INT8_CEILING.md"))
    return [pytest.param(d, marks=pytest.mark.slow) if d in _SLOW_DOCS
            else d for d in docs]


def extract_blocks(path):
    text = open(path).read()
    out = []
    for m in _FENCE.finditer(text):
        if not m.group("skip"):
            out.append(m.group("body"))
    return out


@pytest.mark.parametrize("doc", _doc_files())
def test_doc_snippets_execute(doc, tmp_path):
    blocks = extract_blocks(os.path.join(DOCS, doc))
    if not blocks:
        pytest.skip(f"{doc} has no python blocks")
    script = _PRELUDE.format(repo=REPO) + "\n\n".join(blocks)
    p = tmp_path / "doc_snippets.py"
    p.write_text(script)
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["DOCTEST_TMPDIR"] = str(tmp_path)
    proc = subprocess.run([sys.executable, str(p)], capture_output=True,
                          text=True, timeout=1200, env=env,
                          cwd=str(tmp_path))
    assert proc.returncode == 0, (
        f"{doc} snippets failed:\n--- stdout ---\n{proc.stdout[-3000:]}\n"
        f"--- stderr ---\n{proc.stderr[-3000:]}")


def test_observability_catalog_matches_code():
    """Metric/env-var catalog drift (docs/observability.md vs the actual
    registrations and env reads) fails tier-1, not just the zoolint lane.
    zoolint's project-scope catalog rules are the single implementation —
    this test is just their pytest face (docs/zoolint.md)."""
    from analytics_zoo_tpu.analysis import catalog_drift
    findings = catalog_drift(root=REPO)
    assert findings == [], "\n".join(f.format() for f in findings)
