"""Mixed-precision dtype policy (keras/policy.py): bf16 compute, fp32
params, snapshotted at layer construction."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


@pytest.fixture
def orca_ctx():
    import analytics_zoo_tpu as zoo
    return zoo.init_orca_context(cluster_mode="local")


class TestDtypePolicy:
    def test_default_is_float32(self):
        from analytics_zoo_tpu.keras import policy
        assert policy.dtype_policy() == "float32"
        assert policy.compute_dtype() is None

    def test_unknown_policy_rejected(self):
        from analytics_zoo_tpu.keras import policy
        with pytest.raises(ValueError, match="unknown dtype policy"):
            policy.set_dtype_policy("float16")

    def test_scope_restores(self):
        from analytics_zoo_tpu.keras import policy
        with policy.policy_scope("mixed_bfloat16"):
            assert policy.compute_dtype() == jnp.bfloat16
        assert policy.compute_dtype() is None

    def test_snapshot_at_construction(self, orca_ctx):
        """A layer built under the policy keeps bf16 compute even after
        the policy is reset; a layer built outside stays fp32."""
        from analytics_zoo_tpu.keras import layers as zl, policy
        with policy.policy_scope("mixed_bfloat16"):
            inside = zl.Dense(4, input_shape=(8,))
        outside = zl.Dense(4, input_shape=(8,))
        assert inside.compute_dtype == jnp.bfloat16
        assert outside.compute_dtype is None

    def test_mixed_model_params_stay_fp32_outputs_bf16(self, orca_ctx):
        from analytics_zoo_tpu.keras import Sequential, policy
        from analytics_zoo_tpu.keras import layers as zl
        with policy.policy_scope("mixed_bfloat16"):
            m = Sequential()
            m.add(zl.Conv2D(8, 3, 3, activation="relu",
                            input_shape=(12, 12, 3)))
            m.add(zl.BatchNormalization())
            m.add(zl.Flatten())
            m.add(zl.Dense(4))
        est = m._ensure_estimator()
        params = est.adapter.params
        kinds = {np.asarray(p).dtype for p in jax.tree_util.tree_leaves(
            params) if np.issubdtype(np.asarray(p).dtype, np.floating)}
        assert kinds == {np.dtype("float32")}, kinds
        x = np.random.default_rng(0).standard_normal(
            (2, 12, 12, 3)).astype(np.float32)
        out = est.adapter.module.apply(
            {"params": est.adapter.params, **est.adapter.model_state}, x)
        assert out.dtype == jnp.bfloat16

    def test_mixed_model_trains(self, orca_ctx):
        """Loss decreases under the bf16 policy (fp32 loss tail via the
        _f32 upcast in learn/losses.py)."""
        from analytics_zoo_tpu.keras import Sequential, policy
        from analytics_zoo_tpu.keras import layers as zl
        with policy.policy_scope("mixed_bfloat16"):
            m = Sequential()
            m.add(zl.Dense(16, activation="relu", input_shape=(8,)))
            m.add(zl.Dense(3))
        m.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy_logits")
        rng = np.random.default_rng(0)
        x = rng.standard_normal((64, 8)).astype(np.float32)
        y = rng.integers(0, 3, 64).astype(np.int32)
        h = m.fit(x, y, batch_size=32, nb_epoch=4)
        assert h["loss"][-1] < h["loss"][0]

    def test_bf16_targets_upcast_in_log_family_losses(self):
        """Regression: _f32 used to upcast only y_pred, so a bf16 TARGET
        inside a log/ratio op (msle's log1p(y_true), mape's 1/|y_true|,
        kld's log(t/p), poisson) evaluated the transcendental at bf16
        precision. Each loss must now match its result on fp32-cast
        targets exactly, and compute in fp32."""
        from analytics_zoo_tpu.learn import losses

        rng = np.random.default_rng(3)
        t32 = (rng.uniform(0.05, 4.0, (8, 5))).astype(np.float32)
        p32 = (rng.uniform(0.05, 4.0, (8, 5))).astype(np.float32)
        t16, p16 = t32.astype(jnp.bfloat16), p32.astype(jnp.bfloat16)
        for name in ("msle", "mape", "kld", "poisson"):
            fn = losses.get(name)
            got = fn(t16, p16)
            assert got.dtype == jnp.float32, name
            # bf16 inputs upcast-then-compute == computing on the fp32
            # casts directly (bitwise — the cast is the ONLY rounding)
            want = fn(np.asarray(t16, np.float32),
                      np.asarray(p16, np.float32))
            np.testing.assert_array_equal(np.asarray(got),
                                          np.asarray(want), err_msg=name)

    @pytest.mark.slow  # ~14s: compiles mobilenet-v2 inference on 1 core
    def test_image_classifier_dtype_arg(self, orca_ctx):
        from analytics_zoo_tpu.models.image.imageclassification import (
            ImageClassifier,
        )
        m = ImageClassifier(class_num=3, model_name="mobilenet-v2",
                            image_size=32, dtype="mixed_bfloat16")
        out = np.asarray(m.predict(
            np.zeros((2, 32, 32, 3), np.float32), distributed=False))
        assert out.shape == (2, 3)
        # softmax probabilities normalized despite bf16 hidden compute
        np.testing.assert_allclose(out.sum(1), 1.0, rtol=2e-2)

    def test_mixed_policy_with_sharded_strategy(self, orca_ctx):
        """bf16 compute composes with mesh sharding: a dp4,tp2 model
        under the policy trains and keeps fp32 params."""
        from analytics_zoo_tpu.keras import Sequential, policy
        from analytics_zoo_tpu.keras import layers as zl
        with policy.policy_scope("mixed_bfloat16"):
            m = Sequential()
            m.add(zl.Dense(32, activation="relu", input_shape=(16,)))
            m.add(zl.Dense(4))
        m.set_strategy("dp4,tp2",
                       param_rules=[(r".*dense.*kernel", (None, "model"))])
        m.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy_logits")
        rng = np.random.default_rng(1)
        x = rng.standard_normal((64, 16)).astype(np.float32)
        y = rng.integers(0, 4, 64).astype(np.int32)
        h = m.fit(x, y, batch_size=32, nb_epoch=3)
        assert h["loss"][-1] < h["loss"][0]
        est = m._ensure_estimator()
        kinds = {np.asarray(p).dtype for p in jax.tree_util.tree_leaves(
            est.adapter.params)
            if np.issubdtype(np.asarray(p).dtype, np.floating)}
        assert kinds == {np.dtype("float32")}, kinds
        # the tp rule must have ACTUALLY applied — otherwise this test
        # passes vacuously with every param on the default layout
        specs = [str(getattr(leaf.sharding, "spec", ""))
                 for leaf in jax.tree_util.tree_leaves(
                     est._state["params"])
                 if getattr(leaf, "ndim", 0) == 2]
        assert any("model" in sp for sp in specs), specs
