"""Step-level decode scheduler: paged KV allocator/cache units, bitwise
interleaved-vs-isolated parity (mid-flight admission, step-boundary
pauses, page recycling across lengths), speculative accept/reject vs the
greedy reference, and pool admission control."""

import numpy as np
import pytest

from analytics_zoo_tpu.common import telemetry
from analytics_zoo_tpu.inference import generation
from analytics_zoo_tpu.inference.decode_scheduler import (
    DecodeScheduler, PagedKVAllocator, PagedKVCache, PagePoolExhausted,
)

DIM = 6


def _step_fn(scale=1.0):
    """Deterministic, strictly causal, row-independent decoder: output at
    position t mixes enc with the cumulative sum of dec[:, :t+1] — the
    properties the interleaving parity claim rests on."""
    w = np.random.default_rng(0).normal(size=(DIM, DIM)).astype(np.float32)

    def fn(enc, dec):
        csum = np.cumsum(np.asarray(dec, np.float32), axis=1)
        return np.tanh(scale * (csum @ w) + np.asarray(
            enc, np.float32)[:, None, :])
    return fn


def _enc(seed, n=1):
    rows = np.random.default_rng(seed).normal(
        size=(n, DIM)).astype(np.float32)
    return rows if n > 1 else rows[0]


def _start():
    s = np.zeros(DIM, np.float32)
    s[0] = 1.0
    return s


def _reference(fn, enc_row, steps, **kw):
    """Isolated whole-loop reference for a single sequence."""
    return generation.decode_loop(
        fn, enc_row[None], _start()[None], steps, ladder=None, **kw)[0]


# ------------------------------------------------------------- allocator

def test_allocator_sizing_and_pages_for():
    alloc = PagedKVAllocator.for_grid(4, 17, DIM, page_size=8)
    assert alloc.n_pages == 4 * 3          # ceil(17/8) per sequence
    assert alloc.pages_for(0) == 0
    assert alloc.pages_for(1) == 1
    assert alloc.pages_for(8) == 1
    assert alloc.pages_for(9) == 2


def test_allocator_zeroes_recycled_pages_and_syncs_gauges():
    alloc = PagedKVAllocator(4, 2, DIM)
    pages = alloc.alloc_pages(2)
    alloc._pool[pages[0]].fill(7.0)
    alloc.free_pages(pages)
    again = alloc.alloc_pages(4)
    assert all(not alloc._pool[p].any() for p in again)
    snap = telemetry.snapshot()
    assert float(snap["zoo_kv_pages_in_use"]) == 4.0
    assert float(snap["zoo_kv_pages_free"]) == 0.0


def test_allocator_exhaustion_vs_growth():
    alloc = PagedKVAllocator(4, 2, DIM)
    held = alloc.alloc_pages(3)
    # contention: another sequence holds the pages -> defer admission
    with pytest.raises(PagePoolExhausted):
        alloc.alloc_pages(2)
    alloc.free_pages(held)
    # a single request larger than the whole pool is capacity planning:
    # the pool grows instead of raising
    big = alloc.alloc_pages(6)
    assert len(big) == 6 and alloc.n_pages == 6


# ----------------------------------------------------------------- cache

def test_cache_append_truncate_gather_close():
    alloc = PagedKVAllocator(8, 2, DIM)
    cache = PagedKVCache(alloc, alloc.alloc_pages(2))
    rows = np.eye(DIM, dtype=np.float32)[:4]
    cache.append_block(rows[:3])
    assert cache.length == 3
    assert cache.token_id(1) == 1
    assert np.array_equal(cache.row(2), rows[2])
    # growth past the admission reservation allocs straight into _pages
    cache.append(rows[3])
    cache.append(rows[0])
    assert cache.length == 5 and cache.capacity == 6
    dst = np.full((8, DIM), 9.0, np.float32)
    dst[:] = 0.0
    cache.gather_into(dst)
    assert np.array_equal(dst[:3], rows[:3])
    assert not dst[5:].any()                 # causal zero tail
    cache.truncate(2)
    assert cache.length == 2
    dst[:] = 0.0
    cache.gather_into(dst)
    assert not dst[2:].any()                 # truncated drafts zeroed
    cache.close()
    cache.close()                            # idempotent
    assert alloc.n_free == alloc.n_pages


# ------------------------------------------------- interleaving parity

def test_scheduler_greedy_matches_isolated_reference_bitwise():
    fn = _step_fn()
    sched = DecodeScheduler(fn, max_batch=4, max_seq=16, page_size=4)
    seqs = [sched.admit(_enc(i), _start(), 5 + i, mode="greedy")
            for i in range(3)]
    sched.drain()
    for i, s in enumerate(seqs):
        ref = _reference(fn, _enc(i), 5 + i, mode="greedy")
        assert np.array_equal(s.result, ref)
    # every page back in the pool after retirement
    assert sched.allocator.n_free == sched.allocator.n_pages


def test_mid_flight_admission_is_invisible_bitwise():
    fn = _step_fn()
    sched = DecodeScheduler(fn, max_batch=4, max_seq=32, page_size=4)
    a = sched.admit(_enc(1), _start(), 10, mode="greedy")
    for _ in range(4):                       # a is mid-generation...
        sched.step()
    b = sched.admit(_enc(2), _start(), 6, mode="greedy")
    sched.drain()
    assert np.array_equal(a.result, _reference(fn, _enc(1), 10,
                                               mode="greedy"))
    assert np.array_equal(b.result, _reference(fn, _enc(2), 6,
                                               mode="greedy"))


def test_step_boundary_pauses_are_invisible_bitwise():
    # the engine preempts between steps — a paused-and-resumed schedule
    # must produce exactly what an uninterrupted drain produces
    fn = _step_fn()
    paused = DecodeScheduler(fn, max_batch=4, max_seq=16, page_size=4)
    straight = DecodeScheduler(fn, max_batch=4, max_seq=16, page_size=4)
    p = [paused.admit(_enc(i), _start(), 7, mode="greedy")
         for i in range(2)]
    s = [straight.admit(_enc(i), _start(), 7, mode="greedy")
         for i in range(2)]
    while paused.live:
        paused.step()                        # "preemption" = caller pause
        # arbitrary interleaved work happens here in the engine
    straight.drain()
    for x, y in zip(p, s):
        assert np.array_equal(x.result, y.result)


def test_page_recycling_across_lengths():
    fn = _step_fn()
    # pool holds exactly two worst-case sequences (6 pages of 4)
    alloc = PagedKVAllocator.for_grid(2, 12, DIM, page_size=4)
    sched = DecodeScheduler(fn, max_batch=2, max_seq=11, page_size=4,
                            allocator=alloc, spec_k=0)
    short = sched.admit(_enc(3), _start(), 2, mode="greedy")
    long = sched.admit(_enc(4), _start(), 11, mode="greedy")
    with pytest.raises(PagePoolExhausted):
        sched.admit(_enc(5), _start(), 11, mode="greedy")
    while not short.done:
        sched.step()
    # the short retirement freed pages mid-flight of the long one
    third = sched.admit(_enc(5), _start(), 4, mode="greedy")
    sched.drain()
    assert np.array_equal(short.result, _reference(fn, _enc(3), 2,
                                                   mode="greedy"))
    assert np.array_equal(long.result, _reference(fn, _enc(4), 11,
                                                  mode="greedy"))
    assert np.array_equal(third.result, _reference(fn, _enc(5), 4,
                                                   mode="greedy"))
    assert alloc.n_free == alloc.n_pages


def test_chunked_prefill_matches_isolated_scheduler():
    fn = _step_fn()
    prefill = np.random.default_rng(8).normal(
        size=(9, DIM)).astype(np.float32)

    def run(extra_load):
        sched = DecodeScheduler(fn, max_batch=4, max_seq=32, page_size=4,
                                prefill_chunk=4)
        if extra_load:
            sched.admit(_enc(6), _start(), 12, mode="greedy")
        seq = sched.admit(_enc(7), prefill, 5, mode="greedy")
        sched.drain()
        return seq.result

    assert np.array_equal(run(True), run(False))


def test_sample_mode_rng_is_per_sequence():
    fn = _step_fn()
    sched = DecodeScheduler(fn, max_batch=4, max_seq=16, page_size=4)
    seqs = [sched.admit(_enc(i), _start(), 6, mode="sample",
                        temperature=0.7, seed=100 + i)
            for i in range(3)]
    sched.drain()
    for i, s in enumerate(seqs):
        ref = _reference(fn, _enc(i), 6, mode="sample", temperature=0.7,
                         seed=100 + i)
        assert np.array_equal(s.result, ref)


# ------------------------------------------------- speculative decoding

def test_speculative_with_perfect_draft_is_bitwise_greedy():
    fn = _step_fn()
    sched = DecodeScheduler(fn, max_batch=4, max_seq=16, page_size=4,
                            draft_fn=fn, spec_k=3)
    seqs = [sched.admit(_enc(i), _start(), 8, mode="greedy")
            for i in range(2)]
    sched.drain()
    for i, s in enumerate(seqs):
        assert np.array_equal(s.result,
                              _reference(fn, _enc(i), 8, mode="greedy"))
    # a perfect draft never mismatches
    assert sched.spec_accept_ratio == 1.0
    # and accepted tokens cost no extra target steps: 8 tokens in
    # ceil(8 / (spec_k + 1)) wide steps, not 8
    assert sched.steps_run == 2
    assert sched.allocator.n_free == sched.allocator.n_pages


def test_speculative_with_adversarial_draft_still_bitwise_greedy():
    fn = _step_fn()
    bad = lambda enc, dec: -fn(enc, dec)     # disagrees everywhere
    sched = DecodeScheduler(fn, max_batch=4, max_seq=16, page_size=4,
                            draft_fn=bad, spec_k=3)
    s = sched.admit(_enc(9), _start(), 8, mode="greedy")
    sched.drain()
    assert np.array_equal(s.result, _reference(fn, _enc(9), 8,
                                               mode="greedy"))
    assert sched.spec_accept_ratio == 0.0
    assert sched.allocator.n_free == sched.allocator.n_pages


def test_speculative_skips_sample_mode_sequences():
    # clean fallback: sampled sequences take the plain one-token step
    # even with a draft configured, and their rng stream is unchanged
    fn = _step_fn()
    sched = DecodeScheduler(fn, max_batch=4, max_seq=16, page_size=4,
                            draft_fn=fn, spec_k=3)
    s = sched.admit(_enc(2), _start(), 6, mode="sample", temperature=0.7,
                    seed=42)
    sched.drain()
    ref = _reference(fn, _enc(2), 6, mode="sample", temperature=0.7,
                     seed=42)
    assert np.array_equal(s.result, ref)
    assert sched.spec_accept_ratio == 0.0    # nothing was proposed


def test_spec_metrics_land_on_the_registry():
    fn = _step_fn()
    sched = DecodeScheduler(fn, max_batch=2, max_seq=16, page_size=4,
                            draft_fn=fn, spec_k=2)
    sched.admit(_enc(1), _start(), 6, mode="greedy")
    sched.drain()
    snap = telemetry.snapshot()
    assert float(snap["zoo_spec_proposed_total"]) > 0
    assert float(snap["zoo_spec_accepted_total"]) > 0
    assert float(snap["zoo_spec_accept_ratio"]) == 1.0


# ------------------------------------------- engine preemption seam

def _preemptions_total():
    fam = telemetry.snapshot().get("zoo_decode_preemptions_total", {})
    if not isinstance(fam, dict):
        return float(fam or 0.0)
    return float(sum(fam.values()))


def test_engine_defers_decode_to_hotter_lane_with_starvation_floor():
    """The engine's per-step preemption: a waiting record on a lane with
    a strictly lower credit/weight ratio defers the decode step (counted
    on zoo_decode_preemptions_total), and the starvation floor forces a
    step through after DECODE_STARVATION_FLOOR consecutive deferrals."""
    from analytics_zoo_tpu.serving.engine import ClusterServing

    eng = ClusterServing(object(), 0, warmup=False)
    sched = DecodeScheduler(_step_fn(), max_batch=2, max_seq=16,
                            page_size=4)
    seq = sched.admit(_enc(1), _start(), 8, mode="greedy")
    eng._decode_sched = sched
    eng._gen_live[seq] = ("u1", ("XACK",), None, "batch", eng._conn_gen)
    # one interactive record waiting in the assembly bucket, its lane
    # ratio (0/4) strictly under the live decode lane's (5/1)
    eng._asm = [(1, "u2", {}, None, "interactive", 0.0, None, None)]
    eng._lane_credit.update({"interactive": 0.0, "batch": 5.0})
    before = _preemptions_total()
    for _ in range(eng.DECODE_STARVATION_FLOOR):
        assert eng._decode_tick(None) == 0
    assert sched.steps_run == 0                  # every tick deferred
    assert _preemptions_total() - before == eng.DECODE_STARVATION_FLOOR
    eng._decode_tick(None)                       # floor reached: step runs
    assert sched.steps_run == 1
    assert _preemptions_total() - before == eng.DECODE_STARVATION_FLOOR
    # with nothing waiting the decode never defers
    eng._asm = []
    eng._decode_tick(None)
    assert sched.steps_run == 2
    sched.abort_all()


# ------------------------------------------- paged step seam (ISSUE 20)

def _paged_fn(fn):
    """Numpy seam with the contract of InferenceModel.paged_decode_step_fn:
    ``(enc, pool, scales, table, lengths) -> [rung, width*page_size, dim]``
    — gather the pages (dequantizing with the exact ``q*scale`` expression
    the allocator's read path uses), zero the causal tail, run the step."""
    def paged(enc, pool, scales, table, lengths):
        pool = np.asarray(pool)
        table = np.asarray(table)
        b, w = table.shape
        ps = pool.shape[1]
        rows = pool[table].astype(np.float32)            # [b, w, ps, d]
        if pool.dtype == np.int8:
            rows = rows * np.asarray(
                scales, np.float32)[table][:, :, None, None]
        dec = rows.reshape(b, w * ps, -1)
        pos = np.arange(w * ps)[None, :, None]
        dec = np.where(pos < np.asarray(lengths)[:, None, None], dec, 0.0)
        return fn(enc, dec)
    return paged


def _counter(name):
    val = telemetry.snapshot().get(name, 0.0)
    return float(val if isinstance(val, (int, float)) else 0.0)


def _paged_pair(fn, paged, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_seq", 16)
    kw.setdefault("page_size", 4)
    return DecodeScheduler(fn, paged_step_fn=_paged_fn(fn), paged=paged,
                           **kw)


def test_paged_force_matches_off_bitwise_with_midflight_admission():
    """The tentpole parity claim: forcing every wide step through the
    paged seam is bitwise invisible — across page-boundary lengths, seq
    rung growth and a mid-flight admission."""
    fn = _step_fn()
    results = {}
    for paged in ("off", "force"):
        sched = _paged_pair(fn, paged)
        a = sched.admit(_enc(1), _start(), 11, mode="greedy")  # 2→3 pages
        for _ in range(5):
            sched.step()
        b = sched.admit(_enc(2), _start(), 4, mode="greedy")   # boundary
        sched.drain()
        results[paged] = (a.result.copy(), b.result.copy())
    assert np.array_equal(results["force"][0], results["off"][0])
    assert np.array_equal(results["force"][1], results["off"][1])
    # and both equal the isolated whole-loop reference
    assert np.array_equal(results["force"][0],
                          _reference(fn, _enc(1), 11, mode="greedy"))


def test_paged_steps_count_and_fallback_counts(monkeypatch):
    fn = _step_fn()
    steps0, fall0 = (_counter("zoo_paged_attn_steps_total"),
                     _counter("zoo_paged_attn_fallback_total"))
    sched = _paged_pair(fn, "force")
    sched.admit(_enc(1), _start(), 4, mode="greedy")
    sched.drain()
    assert _counter("zoo_paged_attn_steps_total") > steps0
    # a seam configured but not dispatched (here: tuning disabled, so
    # "auto" can never see a winning verdict) counts the gather fallback
    monkeypatch.setenv("ZOO_AUTOTUNE", "off")
    sched = _paged_pair(fn, "auto")
    sched.admit(_enc(2), _start(), 4, mode="greedy")
    sched.drain()
    assert _counter("zoo_paged_attn_fallback_total") > fall0


def test_paged_recycling_with_lazy_zero_stays_bitwise():
    """After the first paged step the allocator stops zeroing recycled
    pages (the kernel's length mask is the hygiene): dirty pages flow
    back into new sequences and the outputs still match the reference
    bitwise, while the skip counter advances."""
    fn = _step_fn()
    sched = _paged_pair(fn, "force", max_batch=2, max_seq=11, spec_k=0)
    skip0 = _counter("zoo_kv_page_zeros_skipped_total")
    short = sched.admit(_enc(3), _start(), 2, mode="greedy")
    long = sched.admit(_enc(4), _start(), 11, mode="greedy")
    while not short.done:
        sched.step()
    assert sched.allocator.lazy_zero           # flipped by the first step
    third = sched.admit(_enc(5), _start(), 4, mode="greedy")  # dirty pages
    sched.drain()
    assert np.array_equal(short.result, _reference(fn, _enc(3), 2,
                                                   mode="greedy"))
    assert np.array_equal(long.result, _reference(fn, _enc(4), 11,
                                                  mode="greedy"))
    assert np.array_equal(third.result, _reference(fn, _enc(5), 4,
                                                   mode="greedy"))
    assert sched.allocator.zeros_skipped > 0
    assert _counter("zoo_kv_page_zeros_skipped_total") > skip0


def test_eager_zeroing_stays_default_without_paged_steps():
    # the gather fallback relies on pre-zeroed pages — lazy mode must
    # only ever engage once a kernel-masked step has actually run
    alloc = PagedKVAllocator(4, 2, DIM)
    assert not alloc.lazy_zero
    pages = alloc.alloc_pages(2)
    alloc._pool[pages[0]].fill(7.0)
    alloc.free_pages(pages)
    assert all(not alloc._pool[p].any() for p in alloc.alloc_pages(4))


def test_paged_auto_dispatch_consults_step_verdict(monkeypatch, tmp_path):
    from analytics_zoo_tpu.ops import autotune, paged_attention
    monkeypatch.setenv("ZOO_AUTOTUNE_CACHE", str(tmp_path / "at.json"))
    autotune.reset_tuner()
    try:
        fn = _step_fn()
        sched = _paged_pair(fn, "auto", max_batch=2)
        seq = sched.admit(_enc(1), _start(), 6, mode="greedy")
        alloc = sched.allocator
        # seed a winning verdict for every step shape this drain can hit
        for want in range(1, sched.max_seq + 2):
            key = paged_attention.step_key(
                1, sched._seq_ladder.rung_for(want), sched.page_size,
                alloc.dim, alloc.n_pages, alloc.kv_dtype, seq.enc.shape)
            autotune.get_tuner().record(key, {
                "kernel": "paged_step", "best": "paged",
                "use_kernel": True, "best_ms": 1.0, "reference_ms": 2.0,
                "speedup": 2.0})
        steps0 = _counter("zoo_paged_attn_steps_total")
        sched.drain()
        assert _counter("zoo_paged_attn_steps_total") > steps0
        assert np.array_equal(seq.result,
                              _reference(fn, _enc(1), 6, mode="greedy"))
    finally:
        autotune.reset_tuner()
        autotune._pending.clear()


def test_paged_auto_miss_enqueues_tuning_thunk(monkeypatch, tmp_path):
    from analytics_zoo_tpu.ops import autotune
    monkeypatch.setenv("ZOO_AUTOTUNE_CACHE", str(tmp_path / "at.json"))
    monkeypatch.setenv("ZOO_AUTOTUNE_ITERS", "1")
    autotune.reset_tuner()
    try:
        fn = _step_fn()
        sched = _paged_pair(fn, "auto", max_batch=2)
        seq = sched.admit(_enc(1), _start(), 3, mode="greedy")
        sched.drain()
        # every miss took the gather reference and queued a measurement
        assert np.array_equal(seq.result,
                              _reference(fn, _enc(1), 3, mode="greedy"))
        assert autotune.pending_count() > 0
        assert autotune.tune_pending() > 0       # warmup worker drains it
        assert autotune.pending_count() == 0
    finally:
        autotune.reset_tuner()
        autotune._pending.clear()


def test_tune_paged_records_verdict_at_live_shape(monkeypatch, tmp_path):
    from analytics_zoo_tpu.ops import autotune
    monkeypatch.setenv("ZOO_AUTOTUNE_CACHE", str(tmp_path / "at.json"))
    monkeypatch.setenv("ZOO_AUTOTUNE_ITERS", "1")
    autotune.reset_tuner()
    try:
        fn = _step_fn()
        sched = _paged_pair(fn, "auto")
        sched.admit(_enc(1), _start(), 4, mode="greedy")
        rec = sched.tune_paged()
        assert rec is not None and rec["kernel"] == "paged_step"
        # never-slower invariant holds for the step verdict too
        if rec["use_kernel"]:
            assert rec["best_ms"] < rec["reference_ms"]
        else:
            assert rec["best_ms"] is None or \
                rec["best_ms"] >= rec["reference_ms"]
        sched.abort_all()
    finally:
        autotune.reset_tuner()
        autotune._pending.clear()


# --------------------------------------------------- int8 KV (ISSUE 20)

def test_int8_kv_greedy_is_bitwise_fp32(monkeypatch):
    """The greedy pin: one-hot feedback rows quantize exactly (argmax
    over a dequantized row picks the same token — the per-page scale is
    a single positive scalar), so int8-KV greedy generations equal the
    fp32 run bit for bit, through the paged seam and the gather path."""
    fn = _step_fn()
    fp32 = {}
    for paged in ("off", "force"):
        sched = _paged_pair(fn, paged)
        s = sched.admit(_enc(1), _start(), 9, mode="greedy")
        sched.drain()
        fp32[paged] = s.result.copy()
    monkeypatch.setenv("ZOO_KV_DTYPE", "int8")
    for paged in ("off", "force"):
        sched = _paged_pair(fn, paged)
        seq = sched.admit(_enc(1), _start(), 9, mode="greedy")
        sched.drain()
        assert sched.allocator.quantized
        assert np.array_equal(seq.result, fp32[paged]), (
            f"int8 KV diverged from fp32 under paged={paged}")
    assert np.array_equal(fp32["force"], fp32["off"])


def test_int8_kv_sample_mode_same_seed_matches_fp32(monkeypatch):
    fn = _step_fn()
    def run():
        sched = _paged_pair(fn, "force")
        s = sched.admit(_enc(2), _start(), 7, mode="sample",
                        temperature=0.8, seed=11)
        sched.drain()
        return s.result.copy()
    ref = run()
    monkeypatch.setenv("ZOO_KV_DTYPE", "int8")
    assert np.array_equal(run(), ref)


def test_int8_kv_raw_mode_accuracy_bound(monkeypatch):
    """Raw mode feeds real-valued rows back, so int8 KV genuinely loses
    precision — bounded by the per-page symmetric step (amax/127 per
    element, compounding through tanh's contraction)."""
    fn = _step_fn()
    def run():
        sched = _paged_pair(fn, "force")
        s = sched.admit(_enc(3), _start(), 8, mode="raw")
        sched.drain()
        return s.result.copy()
    ref = run()
    monkeypatch.setenv("ZOO_KV_DTYPE", "int8")
    got = run()
    assert not np.array_equal(got, ref)          # quantization is real
    np.testing.assert_allclose(got, ref, atol=0.05)


def test_int8_kv_doubles_admission_at_fixed_pool_bytes(monkeypatch):
    """The capacity claim: at a FIXED pool byte budget, int8 KV (1 byte
    per element + 8 bytes of scale/amax per page) admits at least twice
    the sequences fp32 does."""
    def admitted(kv_dtype):
        alloc = PagedKVAllocator.for_pool_bytes(
            8192, page_size=4, dim=DIM, kv_dtype=kv_dtype)
        sched = DecodeScheduler(_step_fn(), max_batch=64, max_seq=12,
                                page_size=4, allocator=alloc, spec_k=0)
        n = 0
        try:
            while True:
                sched.admit(_enc(n), _start(), 12, mode="greedy")
                n += 1
        except PagePoolExhausted:
            pass
        sched.abort_all()
        return n
    n_fp32 = admitted("float32")
    n_int8 = admitted("int8")
    assert n_fp32 >= 1
    assert n_int8 >= 2 * n_fp32


def test_int8_requant_on_amax_growth_keeps_rows_faithful():
    """A later, larger row on the same page forces a rescale: existing
    rows requantize to the new scale (counted on
    zoo_kv_quant_requants_total) and read back within one new step."""
    req0 = _counter("zoo_kv_quant_requants_total")
    alloc = PagedKVAllocator(2, 4, DIM, kv_dtype="int8")
    cache = PagedKVCache(alloc, alloc.alloc_pages(1))
    small = np.full(DIM, 0.01, np.float32)
    big = np.full(DIM, 1.27, np.float32)
    cache.append(small)
    cache.append(big)
    assert _counter("zoo_kv_quant_requants_total") > req0
    step = 1.27 / 127.0
    assert np.allclose(cache.row(0), small, atol=step / 2 + 1e-7)
    assert np.allclose(cache.row(1), big, atol=step / 2 + 1e-7)
    dst = np.zeros((4, DIM), np.float32)
    cache.gather_into(dst)
    assert np.allclose(dst[0], small, atol=step / 2 + 1e-7)
    assert not dst[2:].any()


def test_kv_pool_bytes_gauge_tracks_dtype(monkeypatch):
    PagedKVAllocator(4, 4, DIM)
    fp = float(telemetry.snapshot()["zoo_kv_quant_pool_bytes"])
    PagedKVAllocator(4, 4, DIM, kv_dtype="int8")
    q = float(telemetry.snapshot()["zoo_kv_quant_pool_bytes"])
    assert q < fp / 2                            # int8 halves the pool


def test_real_model_paged_seam_is_bitwise_gather(monkeypatch):
    """End to end through InferenceModel: the jitted paged forward
    (``paged_decode_step_fn`` — on-device gather fused under the decode
    step) against the host gather_into path, bitwise, fp32 and int8."""
    from analytics_zoo_tpu.inference import InferenceModel
    from analytics_zoo_tpu.models import Seq2Seq
    m = Seq2Seq(input_dim=4, output_dim=4, hidden_size=8, rnn_type="gru",
                encoder_seq_len=6, decoder_seq_len=4)
    im = InferenceModel().load_zoo(m)
    rng = np.random.default_rng(5)
    enc = rng.standard_normal((2, 6, 4)).astype(np.float32)
    start = np.zeros((2, 4), np.float32)
    start[:, 0] = 1.0
    im.predict((enc, np.zeros((2, 1, 4), np.float32)))

    def run(paged):
        sched = DecodeScheduler(
            im.decode_step_fn(), max_batch=2, max_seq=8, page_size=4,
            spec_k=0, paged_step_fn=im.paged_decode_step_fn(),
            paged=paged)
        seqs = [sched.admit(enc[i], start[i], 6, mode="greedy")
                for i in range(2)]
        sched.drain()
        return [s.result.copy() for s in seqs]

    base = run("off")
    got = run("force")
    for b, g in zip(base, got):
        assert np.array_equal(b, g)
    monkeypatch.setenv("ZOO_KV_DTYPE", "int8")
    for b, g in zip(base, run("force")):
        assert np.array_equal(b, g)              # greedy pin, real model


# ---------------------------------------------------- lifecycle & errors

def test_abort_all_frees_every_page():
    fn = _step_fn()
    sched = DecodeScheduler(fn, max_batch=4, max_seq=16, page_size=4)
    sched.admit(_enc(1), _start(), 8, mode="greedy")
    sched.admit(_enc(2), _start(), 8, mode="greedy")
    sched.step()
    dropped = sched.abort_all()
    assert len(dropped) == 2 and sched.live == 0
    assert sched.allocator.n_free == sched.allocator.n_pages


def test_admit_validates_inputs():
    sched = DecodeScheduler(_step_fn())
    with pytest.raises(ValueError):
        sched.admit(_enc(1), _start(), 0, mode="greedy")
    with pytest.raises(ValueError):
        sched.admit(_enc(1), _start(), 4, mode="beam")
    with pytest.raises(ValueError):
        sched.admit(_enc(1), np.zeros((2, 2, DIM)), 4)
