"""Step-level decode scheduler: paged KV allocator/cache units, bitwise
interleaved-vs-isolated parity (mid-flight admission, step-boundary
pauses, page recycling across lengths), speculative accept/reject vs the
greedy reference, and pool admission control."""

import numpy as np
import pytest

from analytics_zoo_tpu.common import telemetry
from analytics_zoo_tpu.inference import generation
from analytics_zoo_tpu.inference.decode_scheduler import (
    DecodeScheduler, PagedKVAllocator, PagedKVCache, PagePoolExhausted,
)

DIM = 6


def _step_fn(scale=1.0):
    """Deterministic, strictly causal, row-independent decoder: output at
    position t mixes enc with the cumulative sum of dec[:, :t+1] — the
    properties the interleaving parity claim rests on."""
    w = np.random.default_rng(0).normal(size=(DIM, DIM)).astype(np.float32)

    def fn(enc, dec):
        csum = np.cumsum(np.asarray(dec, np.float32), axis=1)
        return np.tanh(scale * (csum @ w) + np.asarray(
            enc, np.float32)[:, None, :])
    return fn


def _enc(seed, n=1):
    rows = np.random.default_rng(seed).normal(
        size=(n, DIM)).astype(np.float32)
    return rows if n > 1 else rows[0]


def _start():
    s = np.zeros(DIM, np.float32)
    s[0] = 1.0
    return s


def _reference(fn, enc_row, steps, **kw):
    """Isolated whole-loop reference for a single sequence."""
    return generation.decode_loop(
        fn, enc_row[None], _start()[None], steps, ladder=None, **kw)[0]


# ------------------------------------------------------------- allocator

def test_allocator_sizing_and_pages_for():
    alloc = PagedKVAllocator.for_grid(4, 17, DIM, page_size=8)
    assert alloc.n_pages == 4 * 3          # ceil(17/8) per sequence
    assert alloc.pages_for(0) == 0
    assert alloc.pages_for(1) == 1
    assert alloc.pages_for(8) == 1
    assert alloc.pages_for(9) == 2


def test_allocator_zeroes_recycled_pages_and_syncs_gauges():
    alloc = PagedKVAllocator(4, 2, DIM)
    pages = alloc.alloc_pages(2)
    alloc._pool[pages[0]].fill(7.0)
    alloc.free_pages(pages)
    again = alloc.alloc_pages(4)
    assert all(not alloc._pool[p].any() for p in again)
    snap = telemetry.snapshot()
    assert float(snap["zoo_kv_pages_in_use"]) == 4.0
    assert float(snap["zoo_kv_pages_free"]) == 0.0


def test_allocator_exhaustion_vs_growth():
    alloc = PagedKVAllocator(4, 2, DIM)
    held = alloc.alloc_pages(3)
    # contention: another sequence holds the pages -> defer admission
    with pytest.raises(PagePoolExhausted):
        alloc.alloc_pages(2)
    alloc.free_pages(held)
    # a single request larger than the whole pool is capacity planning:
    # the pool grows instead of raising
    big = alloc.alloc_pages(6)
    assert len(big) == 6 and alloc.n_pages == 6


# ----------------------------------------------------------------- cache

def test_cache_append_truncate_gather_close():
    alloc = PagedKVAllocator(8, 2, DIM)
    cache = PagedKVCache(alloc, alloc.alloc_pages(2))
    rows = np.eye(DIM, dtype=np.float32)[:4]
    cache.append_block(rows[:3])
    assert cache.length == 3
    assert cache.token_id(1) == 1
    assert np.array_equal(cache.row(2), rows[2])
    # growth past the admission reservation allocs straight into _pages
    cache.append(rows[3])
    cache.append(rows[0])
    assert cache.length == 5 and cache.capacity == 6
    dst = np.full((8, DIM), 9.0, np.float32)
    dst[:] = 0.0
    cache.gather_into(dst)
    assert np.array_equal(dst[:3], rows[:3])
    assert not dst[5:].any()                 # causal zero tail
    cache.truncate(2)
    assert cache.length == 2
    dst[:] = 0.0
    cache.gather_into(dst)
    assert not dst[2:].any()                 # truncated drafts zeroed
    cache.close()
    cache.close()                            # idempotent
    assert alloc.n_free == alloc.n_pages


# ------------------------------------------------- interleaving parity

def test_scheduler_greedy_matches_isolated_reference_bitwise():
    fn = _step_fn()
    sched = DecodeScheduler(fn, max_batch=4, max_seq=16, page_size=4)
    seqs = [sched.admit(_enc(i), _start(), 5 + i, mode="greedy")
            for i in range(3)]
    sched.drain()
    for i, s in enumerate(seqs):
        ref = _reference(fn, _enc(i), 5 + i, mode="greedy")
        assert np.array_equal(s.result, ref)
    # every page back in the pool after retirement
    assert sched.allocator.n_free == sched.allocator.n_pages


def test_mid_flight_admission_is_invisible_bitwise():
    fn = _step_fn()
    sched = DecodeScheduler(fn, max_batch=4, max_seq=32, page_size=4)
    a = sched.admit(_enc(1), _start(), 10, mode="greedy")
    for _ in range(4):                       # a is mid-generation...
        sched.step()
    b = sched.admit(_enc(2), _start(), 6, mode="greedy")
    sched.drain()
    assert np.array_equal(a.result, _reference(fn, _enc(1), 10,
                                               mode="greedy"))
    assert np.array_equal(b.result, _reference(fn, _enc(2), 6,
                                               mode="greedy"))


def test_step_boundary_pauses_are_invisible_bitwise():
    # the engine preempts between steps — a paused-and-resumed schedule
    # must produce exactly what an uninterrupted drain produces
    fn = _step_fn()
    paused = DecodeScheduler(fn, max_batch=4, max_seq=16, page_size=4)
    straight = DecodeScheduler(fn, max_batch=4, max_seq=16, page_size=4)
    p = [paused.admit(_enc(i), _start(), 7, mode="greedy")
         for i in range(2)]
    s = [straight.admit(_enc(i), _start(), 7, mode="greedy")
         for i in range(2)]
    while paused.live:
        paused.step()                        # "preemption" = caller pause
        # arbitrary interleaved work happens here in the engine
    straight.drain()
    for x, y in zip(p, s):
        assert np.array_equal(x.result, y.result)


def test_page_recycling_across_lengths():
    fn = _step_fn()
    # pool holds exactly two worst-case sequences (6 pages of 4)
    alloc = PagedKVAllocator.for_grid(2, 12, DIM, page_size=4)
    sched = DecodeScheduler(fn, max_batch=2, max_seq=11, page_size=4,
                            allocator=alloc, spec_k=0)
    short = sched.admit(_enc(3), _start(), 2, mode="greedy")
    long = sched.admit(_enc(4), _start(), 11, mode="greedy")
    with pytest.raises(PagePoolExhausted):
        sched.admit(_enc(5), _start(), 11, mode="greedy")
    while not short.done:
        sched.step()
    # the short retirement freed pages mid-flight of the long one
    third = sched.admit(_enc(5), _start(), 4, mode="greedy")
    sched.drain()
    assert np.array_equal(short.result, _reference(fn, _enc(3), 2,
                                                   mode="greedy"))
    assert np.array_equal(long.result, _reference(fn, _enc(4), 11,
                                                  mode="greedy"))
    assert np.array_equal(third.result, _reference(fn, _enc(5), 4,
                                                   mode="greedy"))
    assert alloc.n_free == alloc.n_pages


def test_chunked_prefill_matches_isolated_scheduler():
    fn = _step_fn()
    prefill = np.random.default_rng(8).normal(
        size=(9, DIM)).astype(np.float32)

    def run(extra_load):
        sched = DecodeScheduler(fn, max_batch=4, max_seq=32, page_size=4,
                                prefill_chunk=4)
        if extra_load:
            sched.admit(_enc(6), _start(), 12, mode="greedy")
        seq = sched.admit(_enc(7), prefill, 5, mode="greedy")
        sched.drain()
        return seq.result

    assert np.array_equal(run(True), run(False))


def test_sample_mode_rng_is_per_sequence():
    fn = _step_fn()
    sched = DecodeScheduler(fn, max_batch=4, max_seq=16, page_size=4)
    seqs = [sched.admit(_enc(i), _start(), 6, mode="sample",
                        temperature=0.7, seed=100 + i)
            for i in range(3)]
    sched.drain()
    for i, s in enumerate(seqs):
        ref = _reference(fn, _enc(i), 6, mode="sample", temperature=0.7,
                         seed=100 + i)
        assert np.array_equal(s.result, ref)


# ------------------------------------------------- speculative decoding

def test_speculative_with_perfect_draft_is_bitwise_greedy():
    fn = _step_fn()
    sched = DecodeScheduler(fn, max_batch=4, max_seq=16, page_size=4,
                            draft_fn=fn, spec_k=3)
    seqs = [sched.admit(_enc(i), _start(), 8, mode="greedy")
            for i in range(2)]
    sched.drain()
    for i, s in enumerate(seqs):
        assert np.array_equal(s.result,
                              _reference(fn, _enc(i), 8, mode="greedy"))
    # a perfect draft never mismatches
    assert sched.spec_accept_ratio == 1.0
    # and accepted tokens cost no extra target steps: 8 tokens in
    # ceil(8 / (spec_k + 1)) wide steps, not 8
    assert sched.steps_run == 2
    assert sched.allocator.n_free == sched.allocator.n_pages


def test_speculative_with_adversarial_draft_still_bitwise_greedy():
    fn = _step_fn()
    bad = lambda enc, dec: -fn(enc, dec)     # disagrees everywhere
    sched = DecodeScheduler(fn, max_batch=4, max_seq=16, page_size=4,
                            draft_fn=bad, spec_k=3)
    s = sched.admit(_enc(9), _start(), 8, mode="greedy")
    sched.drain()
    assert np.array_equal(s.result, _reference(fn, _enc(9), 8,
                                               mode="greedy"))
    assert sched.spec_accept_ratio == 0.0
    assert sched.allocator.n_free == sched.allocator.n_pages


def test_speculative_skips_sample_mode_sequences():
    # clean fallback: sampled sequences take the plain one-token step
    # even with a draft configured, and their rng stream is unchanged
    fn = _step_fn()
    sched = DecodeScheduler(fn, max_batch=4, max_seq=16, page_size=4,
                            draft_fn=fn, spec_k=3)
    s = sched.admit(_enc(2), _start(), 6, mode="sample", temperature=0.7,
                    seed=42)
    sched.drain()
    ref = _reference(fn, _enc(2), 6, mode="sample", temperature=0.7,
                     seed=42)
    assert np.array_equal(s.result, ref)
    assert sched.spec_accept_ratio == 0.0    # nothing was proposed


def test_spec_metrics_land_on_the_registry():
    fn = _step_fn()
    sched = DecodeScheduler(fn, max_batch=2, max_seq=16, page_size=4,
                            draft_fn=fn, spec_k=2)
    sched.admit(_enc(1), _start(), 6, mode="greedy")
    sched.drain()
    snap = telemetry.snapshot()
    assert float(snap["zoo_spec_proposed_total"]) > 0
    assert float(snap["zoo_spec_accepted_total"]) > 0
    assert float(snap["zoo_spec_accept_ratio"]) == 1.0


# ------------------------------------------- engine preemption seam

def _preemptions_total():
    fam = telemetry.snapshot().get("zoo_decode_preemptions_total", {})
    if not isinstance(fam, dict):
        return float(fam or 0.0)
    return float(sum(fam.values()))


def test_engine_defers_decode_to_hotter_lane_with_starvation_floor():
    """The engine's per-step preemption: a waiting record on a lane with
    a strictly lower credit/weight ratio defers the decode step (counted
    on zoo_decode_preemptions_total), and the starvation floor forces a
    step through after DECODE_STARVATION_FLOOR consecutive deferrals."""
    from analytics_zoo_tpu.serving.engine import ClusterServing

    eng = ClusterServing(object(), 0, warmup=False)
    sched = DecodeScheduler(_step_fn(), max_batch=2, max_seq=16,
                            page_size=4)
    seq = sched.admit(_enc(1), _start(), 8, mode="greedy")
    eng._decode_sched = sched
    eng._gen_live[seq] = ("u1", ("XACK",), None, "batch", eng._conn_gen)
    # one interactive record waiting in the assembly bucket, its lane
    # ratio (0/4) strictly under the live decode lane's (5/1)
    eng._asm = [(1, "u2", {}, None, "interactive", 0.0, None, None)]
    eng._lane_credit.update({"interactive": 0.0, "batch": 5.0})
    before = _preemptions_total()
    for _ in range(eng.DECODE_STARVATION_FLOOR):
        assert eng._decode_tick(None) == 0
    assert sched.steps_run == 0                  # every tick deferred
    assert _preemptions_total() - before == eng.DECODE_STARVATION_FLOOR
    eng._decode_tick(None)                       # floor reached: step runs
    assert sched.steps_run == 1
    assert _preemptions_total() - before == eng.DECODE_STARVATION_FLOOR
    # with nothing waiting the decode never defers
    eng._asm = []
    eng._decode_tick(None)
    assert sched.steps_run == 2
    sched.abort_all()


# ---------------------------------------------------- lifecycle & errors

def test_abort_all_frees_every_page():
    fn = _step_fn()
    sched = DecodeScheduler(fn, max_batch=4, max_seq=16, page_size=4)
    sched.admit(_enc(1), _start(), 8, mode="greedy")
    sched.admit(_enc(2), _start(), 8, mode="greedy")
    sched.step()
    dropped = sched.abort_all()
    assert len(dropped) == 2 and sched.live == 0
    assert sched.allocator.n_free == sched.allocator.n_pages


def test_admit_validates_inputs():
    sched = DecodeScheduler(_step_fn())
    with pytest.raises(ValueError):
        sched.admit(_enc(1), _start(), 0, mode="greedy")
    with pytest.raises(ValueError):
        sched.admit(_enc(1), _start(), 4, mode="beam")
    with pytest.raises(ValueError):
        sched.admit(_enc(1), np.zeros((2, 2, DIM)), 4)
