"""Tests for InferenceModel + Net/TorchNet (mirrors ref
pyzoo/test/zoo/pipeline/inference/ and .../net/test_torch_net.py)."""

import threading

import re

import numpy as np
import pytest

from analytics_zoo_tpu.inference import InferenceModel
from analytics_zoo_tpu.net import Net, TorchNet, torch_to_jax

torch = pytest.importorskip("torch")
import torch.nn as tnn  # noqa: E402


def _mlp():
    torch.manual_seed(0)
    return tnn.Sequential(
        tnn.Linear(4, 16), tnn.ReLU(),
        tnn.Linear(16, 3), tnn.Softmax(dim=-1))


class TestTorchTranslation:
    def test_mlp_matches_torch(self):
        m = _mlp()
        x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
        want = m(torch.from_numpy(x)).detach().numpy()
        got = TorchNet(m).predict(x)
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_conv_bn_pool_matches_torch(self):
        torch.manual_seed(1)
        m = tnn.Sequential(
            tnn.Conv2d(3, 8, 3, stride=1, padding=1),
            tnn.BatchNorm2d(8), tnn.ReLU(),
            tnn.MaxPool2d(2),
            tnn.Flatten(1),
            tnn.Linear(8 * 4 * 4, 5))
        m.eval()
        x = np.random.RandomState(1).randn(2, 3, 8, 8).astype(np.float32)
        want = m(torch.from_numpy(x)).detach().numpy()
        got = TorchNet(m).predict(x)
        np.testing.assert_allclose(got, want, atol=1e-4)

    def test_residual_and_methods(self):
        class Res(tnn.Module):
            def __init__(self):
                super().__init__()
                self.fc = tnn.Linear(6, 6)

            def forward(self, x):
                h = torch.relu(self.fc(x))
                return (x + h).mean(dim=1)

        m = Res().eval()
        x = np.random.RandomState(2).randn(5, 6).astype(np.float32)
        want = m(torch.from_numpy(x)).detach().numpy()
        got = TorchNet(m).predict(x)
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_convtranspose_groupnorm_activations_match_torch(self):
        torch.manual_seed(11)
        m = tnn.Sequential(
            tnn.ConvTranspose2d(3, 5, 3, stride=2, padding=1),
            tnn.GroupNorm(1, 5), tnn.LeakyReLU(0.2),
            tnn.Conv2d(5, 4, 3, padding=1), tnn.GroupNorm(2, 4),
            tnn.ELU(), tnn.SiLU(), tnn.Softplus(), tnn.Hardtanh(-2, 2))
        x = np.random.RandomState(11).randn(2, 3, 6, 6).astype(np.float32)
        want = m(torch.from_numpy(x)).detach().numpy()
        got = TorchNet(m).predict(x)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_unsupported_module_raises(self):
        m = tnn.Sequential(tnn.Linear(4, 4), tnn.PReLU())
        with pytest.raises(NotImplementedError, match="PReLU"):
            torch_to_jax(m)

    def test_pool_padding_matches_torch(self):
        torch.manual_seed(4)
        m = tnn.Sequential(tnn.MaxPool2d(3, stride=2, padding=1),
                           tnn.AvgPool2d(2, padding=1)).eval()
        x = np.random.RandomState(4).randn(1, 2, 8, 8).astype(np.float32)
        want = m(torch.from_numpy(x)).detach().numpy()
        got = TorchNet(m).predict(x)
        assert got.shape == want.shape
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_pool_ceil_mode_raises(self):
        m = tnn.Sequential(tnn.MaxPool2d(2, ceil_mode=True))
        with pytest.raises(NotImplementedError, match="ceil_mode"):
            torch_to_jax(m)

    def test_bn_stats_are_frozen_buffers(self, orca_ctx):
        from analytics_zoo_tpu.learn.estimator import Estimator
        torch.manual_seed(5)
        m = tnn.Sequential(tnn.Linear(4, 8), tnn.BatchNorm1d(8),
                           tnn.ReLU(), tnn.Linear(8, 2))
        # prime the running stats so they are non-trivial
        m.train()
        m(torch.randn(32, 4))
        m.eval()
        _, variables = torch_to_jax(m)
        assert "mean" in variables["buffers"]["1"]
        rng = np.random.RandomState(5)
        x = rng.randn(64, 4).astype(np.float32)
        y = (x.sum(1) > 0).astype(np.int32)
        est = Estimator.from_torch(
            model=m, loss="sparse_categorical_crossentropy",
            optimizer="adam", sample_input=x[:2])
        before = np.array(variables["buffers"]["1"]["mean"])
        h = est.fit((x, y), epochs=3, batch_size=16)
        assert all(np.isfinite(v) for v in h["loss"])
        import jax
        after = jax.device_get(est._state["model_state"]["1"]["mean"])
        np.testing.assert_allclose(after, before, atol=1e-7)

    def test_direct_parameter_is_trained(self, orca_ctx):
        from analytics_zoo_tpu.learn.estimator import Estimator

        class M(tnn.Module):
            def __init__(self):
                super().__init__()
                self.w = tnn.Parameter(torch.zeros(4, 2))

            def forward(self, x):
                return x @ self.w

        m = M()
        apply_fn, variables = torch_to_jax(m)
        assert "attr.w" in variables["params"]
        rng = np.random.RandomState(6)
        x = rng.randn(64, 4).astype(np.float32)
        y = (x.sum(1) > 0).astype(np.int32)
        est = Estimator.from_torch(
            model=m, loss="sparse_categorical_crossentropy_logits",
            optimizer="adam", sample_input=x[:2])
        est.fit((x, y), epochs=2, batch_size=16)
        import jax
        trained = jax.device_get(est._state["params"]["attr.w"])
        assert np.abs(trained).max() > 0, "direct nn.Parameter never trained"

    def test_dropout_is_real_in_train_mode(self, orca_ctx):
        """Regression: Dropout used to silently translate to identity in
        training too."""
        import jax
        torch.manual_seed(7)
        m = tnn.Sequential(tnn.Linear(4, 32), tnn.Dropout(0.5),
                           tnn.Linear(32, 2))
        apply_fn, variables = torch_to_jax(m)
        x = np.random.RandomState(7).randn(16, 4).astype(np.float32)
        # eval mode: identity, matches torch eval forward
        want = m.eval()(torch.from_numpy(x)).detach().numpy()
        np.testing.assert_allclose(
            np.asarray(apply_fn(variables, x)), want, atol=1e-5)
        # train mode without rng is an explicit error, not silent identity
        with pytest.raises(ValueError, match="dropout needs an rng"):
            apply_fn(variables, x, train=True)
        # train mode drops: two rngs give different outputs, both != eval
        r1 = np.asarray(apply_fn(variables, x, train=True,
                                 rng=jax.random.PRNGKey(0)))
        r2 = np.asarray(apply_fn(variables, x, train=True,
                                 rng=jax.random.PRNGKey(1)))
        assert not np.allclose(r1, r2)
        assert not np.allclose(r1, want)

    def test_dropout_trains_through_estimator(self, orca_ctx):
        from analytics_zoo_tpu.learn.estimator import Estimator
        torch.manual_seed(8)
        m = tnn.Sequential(tnn.Linear(4, 16), tnn.ReLU(), tnn.Dropout(0.3),
                           tnn.Linear(16, 2))
        rng = np.random.RandomState(8)
        x = rng.randn(64, 4).astype(np.float32)
        y = (x.sum(1) > 0).astype(np.int32)
        est = Estimator.from_torch(
            model=m, loss="sparse_categorical_crossentropy_logits",
            optimizer="adam", sample_input=x[:2])
        h1 = est.fit((x, y), epochs=1, batch_size=16)
        h8 = est.fit((x, y), epochs=8, batch_size=16)
        assert h8["loss"][-1] < h1["loss"][0]

    def test_bn_train_mode_uses_batch_stats(self, orca_ctx):
        """Train-mode BN must normalize by batch statistics (torch .train()
        semantics), not the frozen running stats."""
        torch.manual_seed(9)
        m = tnn.Sequential(tnn.Linear(4, 8), tnn.BatchNorm1d(8))
        # make running stats very different from any batch's stats
        with torch.no_grad():
            m[1].running_mean.fill_(5.0)
            m[1].running_var.fill_(25.0)
        m.eval()
        apply_fn, variables = torch_to_jax(m)
        x = np.random.RandomState(9).randn(32, 4).astype(np.float32)
        want_train = m.train()(torch.from_numpy(x)).detach().numpy()
        got_train = np.asarray(apply_fn(variables, x, train=True))
        np.testing.assert_allclose(got_train, want_train, atol=1e-4)
        # eval still uses the translated (frozen) running stats
        want_eval_mean = 5.0
        got_eval = np.asarray(apply_fn(variables, x))
        assert not np.allclose(got_eval, got_train)
        assert np.allclose(np.asarray(variables["buffers"]["1"]["mean"]),
                           want_eval_mean)

    def test_estimator_from_torch_trains(self, orca_ctx):
        from analytics_zoo_tpu.learn.estimator import Estimator
        torch.manual_seed(3)
        m = tnn.Sequential(tnn.Linear(4, 8), tnn.Tanh(), tnn.Linear(8, 2))
        rng = np.random.RandomState(3)
        x = rng.randn(64, 4).astype(np.float32)
        y = (x.sum(1) > 0).astype(np.int32)
        est = Estimator.from_torch(
            model=m, loss="sparse_categorical_crossentropy_logits",
            optimizer="adam", sample_input=x[:2])
        h1 = est.fit((x, y), epochs=1, batch_size=16)
        h5 = est.fit((x, y), epochs=5, batch_size=16)
        assert h5["loss"][-1] < h1["loss"][0]
        preds = est.predict(x, batch_size=16)
        assert np.asarray(preds).shape == (64, 2)


class TestNet:
    def test_load_torch_file_roundtrip(self, tmp_path):
        m = _mlp()
        p = str(tmp_path / "m.pt")
        torch.save(m, p)
        net = Net.load_torch_file(p)
        x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
        want = m(torch.from_numpy(x)).detach().numpy()
        np.testing.assert_allclose(net.predict(x), want, atol=1e-5)

    def test_load_torch_file_rejects_state_dict(self, tmp_path):
        p = str(tmp_path / "sd.pt")
        torch.save(_mlp().state_dict(), p)
        with pytest.raises(ValueError, match="state_dict|not a torch module"):
            Net.load_torch_file(p)

    def test_load_zoo_model_dir(self, tmp_path, orca_ctx):
        from analytics_zoo_tpu.models import TextClassifier
        m = TextClassifier(class_num=2, vocab_size=30, token_length=8,
                           sequence_length=12, encoder="cnn",
                           encoder_output_dim=16)
        x = np.random.RandomState(0).randint(1, 31, (4, 12)).astype(np.float32)
        p1 = np.asarray(m.predict(x, distributed=False))
        path = str(tmp_path / "model")
        m.save_model(path)
        m2 = Net.load(path)
        np.testing.assert_allclose(np.asarray(m2.predict(x)), p1, atol=1e-5)


class TestInferenceModel:
    def test_load_zoo_and_predict(self, orca_ctx):
        from analytics_zoo_tpu.models import TextClassifier
        m = TextClassifier(class_num=3, vocab_size=30, token_length=8,
                           sequence_length=12, encoder="cnn",
                           encoder_output_dim=16)
        x = np.random.RandomState(0).randint(1, 31, (10, 12)).astype(np.float32)
        want = np.asarray(m.predict(x, distributed=False))
        im = InferenceModel(concurrent_num=2).load_zoo(m)
        got = im.predict(x)
        np.testing.assert_allclose(got, want, atol=1e-5)
        # tail-batch padding path: batch_size that doesn't divide n
        got2 = im.predict(x, batch_size=4)
        np.testing.assert_allclose(got2, want, atol=1e-5)
        cls = im.predict_classes(x)
        assert cls.shape == (10,) and cls.max() < 3

    def test_load_zoo_snapshots_params(self, orca_ctx):
        """Regression: load_zoo used to alias the estimator's live state;
        the train step donates that state, so a later fit() invalidated the
        inference model's buffers on TPU. The copy must be a distinct buffer
        and predictions must not change when the source model trains on."""
        import jax
        from analytics_zoo_tpu.models import TextClassifier
        m = TextClassifier(class_num=2, vocab_size=30, token_length=8,
                           sequence_length=12, encoder="cnn",
                           encoder_output_dim=16)
        rng = np.random.RandomState(4)
        x = rng.randint(1, 31, (16, 12)).astype(np.float32)
        y = rng.randint(0, 2, 16)
        m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
        m.fit(x, y, batch_size=8, nb_epoch=1)
        im = InferenceModel().load_zoo(m)
        est = m.model.estimator
        # distinct buffers, not aliases of the (donatable) train state
        im_leaves = jax.tree_util.tree_leaves(im._params["params"])
        est_leaves = jax.tree_util.tree_leaves(est._state["params"])
        assert all(a is not b for a, b in zip(im_leaves, est_leaves))
        before = im.predict(x)
        m.fit(x, y, batch_size=8, nb_epoch=2)  # donates + replaces est state
        after = im.predict(x)
        np.testing.assert_allclose(after, before, atol=1e-6)

    def test_load_torch(self):
        m = _mlp()
        x = np.random.RandomState(1).randn(6, 4).astype(np.float32)
        want = m(torch.from_numpy(x)).detach().numpy()
        im = InferenceModel().load_torch(m, x[:1])
        np.testing.assert_allclose(im.predict(x), want, atol=1e-5)

    def test_concurrent_predicts(self, orca_ctx):
        m = _mlp()
        x = np.random.RandomState(2).randn(32, 4).astype(np.float32)
        im = InferenceModel(concurrent_num=4).load_torch(m, x[:1])
        want = im.predict(x)
        results, errors = [None] * 8, []

        def worker(i):
            try:
                results[i] = im.predict(x, batch_size=8)
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        assert not errors
        for r in results:
            np.testing.assert_allclose(r, want, atol=1e-6)

    def test_load_checkpoint(self, tmp_path, orca_ctx):
        from analytics_zoo_tpu.learn.estimator import Estimator
        m = tnn.Sequential(tnn.Linear(4, 8), tnn.Tanh(), tnn.Linear(8, 2))
        rng = np.random.RandomState(3)
        x = rng.randn(32, 4).astype(np.float32)
        y = (x.sum(1) > 0).astype(np.int32)
        est = Estimator.from_torch(
            model=m, loss="sparse_categorical_crossentropy_logits",
            optimizer="adam", sample_input=x[:2])
        est.fit((x, y), epochs=2, batch_size=8)
        ckpt = str(tmp_path / "ckpt")
        est.save(ckpt)
        want = np.asarray(est.predict(x, batch_size=8))

        im = InferenceModel().load_torch(m, x[:1]).load_checkpoint(ckpt)
        np.testing.assert_allclose(im.predict(x, batch_size=8), want,
                                   atol=1e-5)

    def test_quantize_int8(self, orca_ctx):
        """Weight-only int8: ~4x smaller kernels, predictions near-equal,
        top-1 agreement preserved (ref BigDL quantize claims <0.1% drop)."""
        from analytics_zoo_tpu.inference.quantize import tree_nbytes
        from analytics_zoo_tpu.models import TextClassifier
        m = TextClassifier(class_num=3, vocab_size=50, token_length=16,
                           sequence_length=12, encoder="cnn",
                           encoder_output_dim=32)
        x = np.random.RandomState(8).randint(1, 51, (32, 12)).astype(
            np.float32)
        im = InferenceModel().load_zoo(m)
        before = im.predict(x)
        bytes_before = tree_nbytes(im._params)
        im.quantize(min_elems=64)
        after = im.predict(x)
        bytes_after = tree_nbytes(im._params)
        # kernels dominate this model → strong overall shrink
        assert bytes_after < 0.45 * bytes_before, \
            f"{bytes_after} vs {bytes_before}"
        assert (np.argmax(after, -1) == np.argmax(before, -1)).mean() == 1.0
        np.testing.assert_allclose(after, before, atol=0.03)

    def test_quantize_is_idempotent(self, orca_ctx):
        m = _mlp()
        x = np.random.RandomState(9).randn(8, 4).astype(np.float32)
        im = InferenceModel().load_torch(m, x[:1])
        im.quantize(min_elems=4)
        once = im.predict(x)
        im.quantize(min_elems=4)   # second call must be a no-op, not nest
        np.testing.assert_allclose(im.predict(x), once, atol=1e-6)

    def test_quantize_requires_model(self):
        with pytest.raises(RuntimeError, match="load a model"):
            InferenceModel().quantize()

    def test_predict_without_model_raises(self):
        with pytest.raises(RuntimeError, match="no model"):
            InferenceModel().predict(np.zeros((2, 2)))


class TestRecurrentTranslation:
    @pytest.mark.parametrize("batch_first", [True, False])
    def test_lstm_matches_torch(self, batch_first):
        torch.manual_seed(3)
        m = tnn.LSTM(input_size=5, hidden_size=7, num_layers=2,
                     batch_first=batch_first)
        shape = (3, 6, 5) if batch_first else (6, 3, 5)
        x = np.random.RandomState(0).randn(*shape).astype(np.float32)
        apply_fn, variables = torch_to_jax(m)
        out, (h_n, c_n) = apply_fn(variables, x)
        with torch.no_grad():
            want, (wh, wc) = m(torch.from_numpy(x))
        np.testing.assert_allclose(np.asarray(out), want.numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(h_n), wh.numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(c_n), wc.numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_gru_matches_torch(self):
        torch.manual_seed(4)
        m = tnn.GRU(input_size=4, hidden_size=6, num_layers=2,
                    batch_first=True)
        x = np.random.RandomState(1).randn(2, 5, 4).astype(np.float32)
        apply_fn, variables = torch_to_jax(m)
        out, h_n = apply_fn(variables, x)
        with torch.no_grad():
            want, wh = m(torch.from_numpy(x))
        np.testing.assert_allclose(np.asarray(out), want.numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(h_n), wh.numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_lstm_classifier_end_to_end(self, orca_ctx):
        """Embedding → LSTM → last step → Linear, traced through fx and
        served via TorchNet (the sentiment-analysis torch shape)."""
        torch.manual_seed(5)

        class Clf(tnn.Module):
            def __init__(self):
                super().__init__()
                self.emb = tnn.Embedding(50, 8)
                self.lstm = tnn.LSTM(8, 12, batch_first=True)
                self.fc = tnn.Linear(12, 2)

            def forward(self, ids):
                x = self.emb(ids)
                x, _ = self.lstm(x)
                return self.fc(x[:, -1])

        m = Clf()
        ids = np.random.RandomState(2).randint(0, 50, (4, 9))
        tn = TorchNet(m)
        got = np.asarray(tn.predict(ids.astype(np.float32)))
        with torch.no_grad():
            want = m(torch.from_numpy(ids)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_unsupported_rnn_configs_raise(self):
        with pytest.raises(NotImplementedError, match="bidirectional"):
            torch_to_jax(tnn.LSTM(4, 4, bidirectional=True))
        with pytest.raises(NotImplementedError, match="dropout"):
            torch_to_jax(tnn.GRU(4, 4, num_layers=2, dropout=0.5))
        with pytest.raises(NotImplementedError, match="proj_size"):
            torch_to_jax(tnn.LSTM(4, 8, proj_size=3))

    def test_single_layer_dropout_is_noop_like_torch(self):
        # torch documents dropout as a no-op when num_layers == 1
        import warnings as w
        with w.catch_warnings():
            w.simplefilter("ignore")
            m = tnn.LSTM(4, 6, batch_first=True, dropout=0.3)
        x = np.random.RandomState(3).randn(2, 5, 4).astype(np.float32)
        apply_fn, variables = torch_to_jax(m)
        out, _ = apply_fn(variables, x)
        with torch.no_grad():
            want, _ = m(torch.from_numpy(x))
        np.testing.assert_allclose(np.asarray(out), want.numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_explicit_initial_state_rejected(self):
        class WithState(tnn.Module):
            def __init__(self):
                super().__init__()
                self.gru = tnn.GRU(4, 6, batch_first=True)

            def forward(self, x, h0):
                out, _ = self.gru(x, h0)
                return out

        x = np.zeros((2, 5, 4), np.float32)
        h0 = np.zeros((1, 2, 6), np.float32)
        apply_fn, variables = torch_to_jax(WithState())
        with pytest.raises(NotImplementedError, match="initial RNN state"):
            apply_fn(variables, x, h0)
        # keyword spelling lands in the same guard
        apply_fn2, variables2 = torch_to_jax(tnn.GRU(4, 6, batch_first=True))
        with pytest.raises(NotImplementedError, match="initial RNN state"):
            apply_fn2(variables2, x, hx=h0)

    def test_unbatched_rnn_matches_torch(self):
        torch.manual_seed(9)
        m = tnn.LSTM(4, 6, num_layers=2)
        x = np.random.RandomState(4).randn(7, 4).astype(np.float32)
        apply_fn, variables = torch_to_jax(m)
        out, (h_n, c_n) = apply_fn(variables, x)
        with torch.no_grad():
            want, (wh, wc) = m(torch.from_numpy(x))
        assert np.asarray(out).shape == (7, 6)
        np.testing.assert_allclose(np.asarray(out), want.numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(h_n), wh.numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(c_n), wc.numpy(),
                                   rtol=1e-4, atol=1e-5)


class TestAttentionTranslation:
    @pytest.mark.parametrize("batch_first", [True, False])
    def test_self_attention_matches_torch(self, batch_first):
        torch.manual_seed(6)
        m = tnn.MultiheadAttention(embed_dim=8, num_heads=2,
                                   batch_first=batch_first)
        shape = (2, 5, 8) if batch_first else (5, 2, 8)
        x = np.random.RandomState(0).randn(*shape).astype(np.float32)
        apply_fn, variables = torch_to_jax(m)
        out, w = apply_fn(variables, x, x, x)
        with torch.no_grad():
            want, ww = m(*(torch.from_numpy(x),) * 3)
        np.testing.assert_allclose(np.asarray(out), want.numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(w), ww.numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_cross_attention_in_model(self, orca_ctx):
        """A traced block calling attn(q, kv, kv, need_weights=False) —
        exercises call_module kwargs passing."""
        torch.manual_seed(7)

        class Block(tnn.Module):
            def __init__(self):
                super().__init__()
                self.attn = tnn.MultiheadAttention(8, 2, batch_first=True)
                self.fc = tnn.Linear(8, 3)

            def forward(self, q, kv):
                x, _ = self.attn(q, kv, kv, need_weights=False)
                return self.fc(x.mean(1))

        m = Block()
        rng = np.random.RandomState(1)
        q = rng.randn(2, 4, 8).astype(np.float32)
        kv = rng.randn(2, 6, 8).astype(np.float32)
        apply_fn, variables = torch_to_jax(m)
        got = np.asarray(apply_fn(variables, q, kv))
        with torch.no_grad():
            want = m(torch.from_numpy(q), torch.from_numpy(kv)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_unsupported_mha_configs(self):
        with pytest.raises(NotImplementedError, match="embed dims"):
            torch_to_jax(tnn.MultiheadAttention(8, 2, kdim=4, vdim=4))
        with pytest.raises(NotImplementedError, match="add_bias_kv"):
            torch_to_jax(tnn.MultiheadAttention(8, 2, add_bias_kv=True))
        m = tnn.MultiheadAttention(8, 2, batch_first=True)
        apply_fn, variables = torch_to_jax(m)
        x = np.zeros((1, 3, 8), np.float32)
        mask = np.zeros((3, 3), np.float32)
        with pytest.raises(NotImplementedError, match="masks"):
            apply_fn(variables, x, x, x, attn_mask=mask)


class TestTransformerTranslation:
    @pytest.mark.parametrize("norm_first", [False, True])
    def test_encoder_layer_matches_torch(self, norm_first):
        torch.manual_seed(10)
        m = tnn.TransformerEncoderLayer(
            d_model=8, nhead=2, dim_feedforward=16, dropout=0.0,
            batch_first=True, norm_first=norm_first).eval()
        x = np.random.RandomState(0).randn(2, 5, 8).astype(np.float32)
        apply_fn, variables = torch_to_jax(m)
        got = np.asarray(apply_fn(variables, x))
        with torch.no_grad():
            want = m(torch.from_numpy(x)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_encoder_stack_matches_torch(self):
        torch.manual_seed(11)
        layer = tnn.TransformerEncoderLayer(
            d_model=8, nhead=2, dim_feedforward=16, dropout=0.0,
            activation="gelu", batch_first=True)
        m = tnn.TransformerEncoder(layer, num_layers=3,
                                   norm=tnn.LayerNorm(8)).eval()
        x = np.random.RandomState(1).randn(2, 6, 8).astype(np.float32)
        apply_fn, variables = torch_to_jax(m)
        got = np.asarray(apply_fn(variables, x))
        with torch.no_grad():
            want = m(torch.from_numpy(x)).numpy()
        # float32 accumulation drift across 3 stacked layers
        np.testing.assert_allclose(got, want, rtol=1e-3, atol=5e-4)
        # stacked layers have independent (deep-copied) weights in torch
        assert len(variables["params"]["root"]) == 4  # 3 layers + final norm

    def test_transformer_classifier_end_to_end(self, orca_ctx):
        torch.manual_seed(12)

        class Clf(tnn.Module):
            def __init__(self):
                super().__init__()
                self.emb = tnn.Embedding(30, 8)
                layer = tnn.TransformerEncoderLayer(
                    8, 2, dim_feedforward=16, dropout=0.0,
                    batch_first=True)
                self.enc = tnn.TransformerEncoder(layer, 2)
                self.fc = tnn.Linear(8, 2)

            def forward(self, ids):
                x = self.emb(ids)
                x = self.enc(x)
                return self.fc(x.mean(1))

        m = Clf().eval()
        ids = np.random.RandomState(2).randint(0, 30, (4, 7))
        got = np.asarray(TorchNet(m).predict(ids.astype(np.float32)))
        with torch.no_grad():
            want = m(torch.from_numpy(ids)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_unsupported_sub_components_raise_cleanly(self):
        with pytest.raises(NotImplementedError, match="activation.*PReLU"):
            torch_to_jax(tnn.TransformerEncoderLayer(
                8, 2, dropout=0.0, activation=tnn.PReLU(),
                batch_first=True))
        layer = tnn.TransformerEncoderLayer(8, 2, dropout=0.0,
                                            batch_first=True)
        with pytest.raises(NotImplementedError, match="norm.*frozen state"):
            torch_to_jax(tnn.TransformerEncoder(layer, 1,
                                                norm=tnn.BatchNorm1d(8)))


class TestMhaNeedWeightsRewrite:
    """Traced models that discard the attention weights (only getitem[0]
    consumed) are rewritten to need_weights=False, so the full
    (B,H,Tq,Tk) probability matrix is never materialized (ADVICE r3:
    torch defaults need_weights=True)."""

    def _block(self, return_weights):
        class Block(tnn.Module):
            def __init__(self):
                super().__init__()
                self.attn = tnn.MultiheadAttention(8, 2, batch_first=True)

            def forward(self, x):
                out, w = self.attn(x, x, x)   # torch default: weights True
                return (out, w) if return_weights else out

        torch.manual_seed(11)
        return Block()

    def test_discarded_weights_skip_reference_path(self, orca_ctx,
                                                   monkeypatch):
        from analytics_zoo_tpu.ops import attention as attn_mod
        m = self._block(return_weights=False)
        apply_fn, variables = torch_to_jax(m)

        real = attn_mod._reference_attention

        def spy(*a, **k):
            # return_probs=True is the materialize-the-weights path; the
            # plain call is dot_product_attention's small-shape fallback
            assert not k.get("return_probs"), (
                "probability-matrix path ran for a model that discards "
                "the weights")
            return real(*a, **k)

        monkeypatch.setattr(attn_mod, "_reference_attention", spy)
        x = np.random.RandomState(2).randn(2, 4, 8).astype(np.float32)
        got = np.asarray(apply_fn(variables, x))
        with torch.no_grad():
            want = m(torch.from_numpy(x)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_consumed_weights_still_materialize(self, orca_ctx):
        m = self._block(return_weights=True)
        apply_fn, variables = torch_to_jax(m)
        x = np.random.RandomState(3).randn(2, 4, 8).astype(np.float32)
        out, w = apply_fn(variables, x)
        with torch.no_grad():
            t_out, t_w = m(torch.from_numpy(x))
        np.testing.assert_allclose(np.asarray(out), t_out.numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(w), t_w.numpy(),
                                   rtol=1e-4, atol=1e-5)


class TestActivationInt8:
    """Calibrated activation quantization (VERDICT r3 weak #6: the ref's
    MKL int8 path quantizes activations with calibrated ranges; here every
    calibrated nn.Dense runs as an int8 x int8 -> int32 dot_general)."""

    def _model(self, seed=0):
        import flax.linen as nn

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                x = nn.relu(nn.Dense(32, name="d1")(x))
                x = nn.relu(nn.Dense(32, name="d2")(x))
                return nn.Dense(4, name="head")(x)

        rs = np.random.RandomState(seed)
        x = rs.randn(64, 16).astype(np.float32)
        from analytics_zoo_tpu.inference import InferenceModel
        im = InferenceModel().load_flax(Net(), x[:1])
        return im, x

    def test_int8_predictions_match_fp32(self, orca_ctx):
        im, x = self._model()
        ref = im.predict(x)
        im.quantize(mode="int8", calibration_data=x[:32], min_elems=64)
        got = im.predict(x)
        assert got.shape == ref.shape
        # small numeric drift, identical argmax on nearly all rows
        # (the reference claims <0.1% accuracy drop)
        agree = (got.argmax(1) == ref.argmax(1)).mean()
        assert agree >= 0.97, agree
        nrmse = float(np.sqrt(np.mean((got - ref) ** 2)) / ref.std())
        assert nrmse < 0.1, nrmse

    def test_int8_graph_really_uses_int8(self, orca_ctx):
        """The jaxpr of the quantized forward must contain int8 operands
        feeding a dot — proof the MXU int8 path is emitted, not a
        dequantize-then-float matmul."""
        import jax
        im, x = self._model(seed=1)
        im.quantize(mode="int8", calibration_data=x[:16], min_elems=64)
        jaxpr = str(jax.make_jaxpr(
            lambda s, a: im._apply(s, a))(im._params, x[:4]))
        assert "i8[" in jaxpr and "dot_general" in jaxpr
        # int8 inputs with int32 accumulation
        assert "preferred_element_type=int32" in jaxpr

    def test_calibration_required_and_validated(self, orca_ctx):
        im, x = self._model(seed=2)
        with pytest.raises(ValueError, match="calibration_data"):
            im.quantize(mode="int8")
        with pytest.raises(ValueError, match="'weight' or 'int8'"):
            im.quantize(mode="int4")

    def test_torch_translated_model_rejected_with_clear_error(self, orca_ctx):
        """torch-translated graphs have no flax Dense layers — calibration
        must say so instead of silently doing nothing."""
        from analytics_zoo_tpu.inference import InferenceModel
        m = torch.nn.Sequential(torch.nn.Linear(8, 4), torch.nn.ReLU())
        x = np.zeros((4, 8), np.float32)
        im = InferenceModel().load_torch(m, x)
        with pytest.raises(ValueError, match="no flax nn.Dense"):
            im.quantize(mode="int8", calibration_data=x)

    def test_conv_net_int8_matches_fp32(self, orca_ctx):
        """Calibrated activation int8 must cover nn.Conv (ResNet-class
        models are ~99% conv FLOPs — Dense-only coverage left them
        effectively unquantized): argmax agreement + the jaxpr must show
        an int8 convolution with int32 accumulation."""
        import jax
        import flax.linen as nn
        from analytics_zoo_tpu.inference import InferenceModel

        class ConvNet(nn.Module):
            @nn.compact
            def __call__(self, x):
                x = nn.relu(nn.Conv(8, (3, 3), strides=2, name="c1")(x))
                x = nn.relu(nn.Conv(16, (3, 3), padding="VALID",
                                    name="c2")(x))
                x = x.reshape(x.shape[0], -1)
                return nn.Dense(4, name="head")(x)

        rs = np.random.RandomState(7)
        x = rs.randn(32, 12, 12, 3).astype(np.float32)
        im = InferenceModel().load_flax(ConvNet(), x[:1])
        ref = im.predict(x)
        im.quantize(mode="int8", calibration_data=x[:16], min_elems=64)
        got = im.predict(x)
        assert got.shape == ref.shape
        agree = (got.argmax(1) == ref.argmax(1)).mean()
        assert agree >= 0.9, agree
        nrmse = float(np.sqrt(np.mean((got - ref) ** 2)) / ref.std())
        assert nrmse < 0.15, nrmse
        jaxpr = str(jax.make_jaxpr(
            lambda s, a: im._apply(s, a))(im._params, x[:4]))
        assert re.search(
            r"conv_general_dilated\[[^]]*preferred_element_type=int32",
            jaxpr, re.S), "conv did not lower with int32 accumulation"
        assert "i8[" in jaxpr

    def test_conv_raw_int_attrs_quantize_or_fall_back(self, orca_ctx):
        """flax keeps kernel_size/padding raw on the module (nn.Conv(4, 3)
        → kernel_size == 3, padding=1 stays 1): the int8 path must
        canonicalize them, not crash at trace time after a successful
        quantize()."""
        import flax.linen as nn
        from analytics_zoo_tpu.inference import InferenceModel

        class RawAttrNet(nn.Module):
            @nn.compact
            def __call__(self, x):
                x = nn.relu(nn.Conv(8, (3, 3), padding=1, name="c1")(x))
                x = nn.relu(nn.Conv(8, (3, 3), padding=(1, 1),
                                    name="c2")(x))
                x = x.mean(axis=(1, 2))
                return nn.Dense(3, name="head")(x)

        rs = np.random.RandomState(5)
        x = rs.randn(16, 10, 10, 3).astype(np.float32)
        im = InferenceModel().load_flax(RawAttrNet(), x[:1])
        ref = im.predict(x)
        im.quantize(mode="int8", calibration_data=x[:8], min_elems=64)
        got = im.predict(x)           # must not raise
        assert (got.argmax(1) == ref.argmax(1)).mean() >= 0.85

        class Conv1DNet(nn.Module):
            @nn.compact
            def __call__(self, x):
                x = nn.relu(nn.Conv(8, 3, name="c1")(x))   # int kernel_size
                x = x.mean(axis=1)
                return nn.Dense(3, name="head")(x)

        x1 = rs.randn(16, 12, 4).astype(np.float32)
        im1 = InferenceModel().load_flax(Conv1DNet(), x1[:1])
        ref1 = im1.predict(x1)
        im1.quantize(mode="int8", calibration_data=x1[:8], min_elems=32)
        got1 = im1.predict(x1)        # must not raise
        assert (got1.argmax(1) == ref1.argmax(1)).mean() >= 0.85

    def test_depthwise_grouped_conv_int8(self, orca_ctx):
        """feature_group_count (mobilenet depthwise) goes through the int8
        conv path with per-output-channel scales intact."""
        import flax.linen as nn
        from analytics_zoo_tpu.inference import InferenceModel

        class DWNet(nn.Module):
            @nn.compact
            def __call__(self, x):
                x = nn.Conv(12, (1, 1), name="expand")(x)
                x = nn.relu(nn.Conv(12, (3, 3), feature_group_count=12,
                                    name="dw")(x))
                x = x.reshape(x.shape[0], -1)
                return nn.Dense(3, name="head")(x)

        rs = np.random.RandomState(11)
        x = rs.randn(24, 8, 8, 4).astype(np.float32)
        im = InferenceModel().load_flax(DWNet(), x[:1])
        ref = im.predict(x)
        im.quantize(mode="int8", calibration_data=x[:12], min_elems=32)
        got = im.predict(x)
        assert (got.argmax(1) == ref.argmax(1)).mean() >= 0.85

    def test_zoo_keras_model_int8_end_to_end(self, orca_ctx):
        """The zoo-keras GraphModule path: its Dense layers are flax
        nn.Dense submodules, so activation int8 covers zoo models too."""
        from analytics_zoo_tpu.inference import InferenceModel
        from analytics_zoo_tpu.keras import Sequential
        from analytics_zoo_tpu.keras import layers as zl
        m = Sequential()
        m.add(zl.Dense(16, activation="relu", input_shape=(8,)))
        m.add(zl.Dense(3))
        rs = np.random.RandomState(3)
        x = rs.randn(32, 8).astype(np.float32)
        im = InferenceModel().load_zoo(m)
        ref = im.predict(x)
        im.quantize(mode="int8", calibration_data=x[:16], min_elems=32)
        got = im.predict(x)
        assert (got.argmax(1) == ref.argmax(1)).mean() >= 0.9
