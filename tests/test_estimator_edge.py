"""Regression tests for review findings: clipping-after-fit, iteration
checkpoint triggers, small-dataset padding."""

import numpy as np

import flax.linen as nn


class Lin(nn.Module):
    @nn.compact
    def __call__(self, x):
        return nn.Dense(1)(x)


def _data(n=64):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    return x, (x.sum(1, keepdims=True)).astype(np.float32)


def test_clipping_change_after_fit(orca_ctx):
    from analytics_zoo_tpu.learn.estimator import Estimator
    x, y = _data()
    est = Estimator.from_flax(model=Lin(), loss="mse", sample_input=x[:2])
    est.fit((x, y), epochs=1, batch_size=32)
    est.set_l2_norm_gradient_clipping(1.0)  # opt_state must be rebuilt
    est.fit((x, y), epochs=1, batch_size=32)
    est.clear_gradient_clipping()
    hist = est.fit((x, y), epochs=1, batch_size=32)
    assert np.isfinite(hist["loss"][0])


def test_several_iteration_checkpoint(orca_ctx, tmp_path):
    from analytics_zoo_tpu.learn.estimator import Estimator
    from analytics_zoo_tpu.learn.trigger import SeveralIteration
    from analytics_zoo_tpu.learn import checkpoint as ckpt
    x, y = _data(64)  # 8 steps/epoch at batch 8
    mdir = str(tmp_path / "it")
    est = Estimator.from_flax(model=Lin(), loss="mse", sample_input=x[:2],
                              model_dir=mdir)
    est.fit((x, y), epochs=1, batch_size=8,
            checkpoint_trigger=SeveralIteration(3))
    versions = sorted(v for _, v in [ckpt.find_latest_checkpoint(mdir)])
    assert ckpt.find_latest_checkpoint(mdir)[1] >= 6  # fired at 3 and 6


def test_evaluate_smaller_than_batch(orca_ctx):
    from analytics_zoo_tpu.learn.estimator import Estimator
    x, y = _data(20)
    est = Estimator.from_flax(model=Lin(), loss="mse", sample_input=x[:2],
                              metrics=["mae"])
    res = est.evaluate((x, y), batch_size=32)  # 20 rows < batch 32
    assert np.isfinite(res["loss"]) and np.isfinite(res["mae"])
    preds = est.predict(x[:10], batch_size=32)
    assert preds.shape == (10, 1)


def test_multihost_requires_coordinator():
    import pytest
    from analytics_zoo_tpu import init_orca_context
    with pytest.raises(ValueError, match="coordinator_address"):
        init_orca_context(cluster_mode="multihost")


class TestStepsPerLoop:
    def _fit(self, steps_per_loop, seed=0):
        import numpy as np
        from analytics_zoo_tpu.learn.estimator import Estimator
        import flax.linen as nn

        class M(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Dense(2)(nn.tanh(nn.Dense(8)(x)))

        rng = np.random.RandomState(seed)
        x = rng.randn(96, 4).astype(np.float32)
        y = (x.sum(1) > 0).astype(np.int32)
        est = Estimator.from_flax(
            model=M(), loss="sparse_categorical_crossentropy_logits",
            optimizer="sgd", sample_input=x[:2], seed=seed)
        h = est.fit((x, y), epochs=2, batch_size=16, shuffle=False,
                    steps_per_loop=steps_per_loop)
        return est, h

    def test_fused_loop_matches_per_step(self):
        import numpy as np
        import jax
        est1, h1 = self._fit(1)
        est4, h4 = self._fit(4)
        # identical data order + sgd → identical parameters and losses
        np.testing.assert_allclose(h1["loss"], h4["loss"], rtol=1e-5)
        p1 = jax.device_get(est1._state["params"])
        p4 = jax.device_get(est4._state["params"])
        for l1, l4 in zip(jax.tree_util.tree_leaves(p1),
                          jax.tree_util.tree_leaves(p4)):
            np.testing.assert_allclose(l1, l4, rtol=1e-5, atol=1e-6)
        assert est1._py_step == est4._py_step == 12

    def test_tail_group_smaller_than_loop(self):
        # 6 steps/epoch with steps_per_loop=4 → groups of 4 and 2
        est, h = self._fit(4)
        import numpy as np
        assert np.isfinite(h["loss"]).all()
