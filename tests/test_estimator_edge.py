"""Regression tests for review findings: clipping-after-fit, iteration
checkpoint triggers, small-dataset padding."""

import numpy as np

import flax.linen as nn


class Lin(nn.Module):
    @nn.compact
    def __call__(self, x):
        return nn.Dense(1)(x)


def _data(n=64):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    return x, (x.sum(1, keepdims=True)).astype(np.float32)


def test_clipping_change_after_fit(orca_ctx):
    from analytics_zoo_tpu.learn.estimator import Estimator
    x, y = _data()
    est = Estimator.from_flax(model=Lin(), loss="mse", sample_input=x[:2])
    est.fit((x, y), epochs=1, batch_size=32)
    est.set_l2_norm_gradient_clipping(1.0)  # opt_state must be rebuilt
    est.fit((x, y), epochs=1, batch_size=32)
    est.clear_gradient_clipping()
    hist = est.fit((x, y), epochs=1, batch_size=32)
    assert np.isfinite(hist["loss"][0])


def test_several_iteration_checkpoint(orca_ctx, tmp_path):
    from analytics_zoo_tpu.learn.estimator import Estimator
    from analytics_zoo_tpu.learn.trigger import SeveralIteration
    from analytics_zoo_tpu.learn import checkpoint as ckpt
    x, y = _data(64)  # 8 steps/epoch at batch 8
    mdir = str(tmp_path / "it")
    est = Estimator.from_flax(model=Lin(), loss="mse", sample_input=x[:2],
                              model_dir=mdir)
    est.fit((x, y), epochs=1, batch_size=8,
            checkpoint_trigger=SeveralIteration(3))
    versions = sorted(v for _, v in [ckpt.find_latest_checkpoint(mdir)])
    assert ckpt.find_latest_checkpoint(mdir)[1] >= 6  # fired at 3 and 6


def test_evaluate_smaller_than_batch(orca_ctx):
    from analytics_zoo_tpu.learn.estimator import Estimator
    x, y = _data(20)
    est = Estimator.from_flax(model=Lin(), loss="mse", sample_input=x[:2],
                              metrics=["mae"])
    res = est.evaluate((x, y), batch_size=32)  # 20 rows < batch 32
    assert np.isfinite(res["loss"]) and np.isfinite(res["mae"])
    preds = est.predict(x[:10], batch_size=32)
    assert preds.shape == (10, 1)


def test_multihost_requires_coordinator():
    import pytest
    from analytics_zoo_tpu import init_orca_context
    with pytest.raises(ValueError, match="coordinator_address"):
        init_orca_context(cluster_mode="multihost")
