"""Encryption tests (mirrors ref pyzoo/test/zoo/common/test_encryption_utils)
plus encrypted end-to-end serving."""

import numpy as np
import pytest

from analytics_zoo_tpu.common import encryption as enc


class TestAes:
    @pytest.mark.parametrize("mode", ["gcm", "cbc"])
    def test_bytes_roundtrip(self, mode):
        f = {"gcm": (enc.encrypt_bytes_with_aes_gcm,
                     enc.decrypt_bytes_with_aes_gcm),
             "cbc": (enc.encrypt_bytes_with_aes_cbc,
                     enc.decrypt_bytes_with_aes_cbc)}[mode]
        data = bytes(range(256)) * 3
        blob = f[0](data, "s3cret")
        assert blob != data
        assert f[1](blob, "s3cret") == data

    def test_str_roundtrip(self):
        s = "hello analytics zoo é中文"
        assert enc.decrypt_with_aes_gcm(
            enc.encrypt_with_aes_gcm(s, "k"), "k") == s
        assert enc.decrypt_with_aes_cbc(
            enc.encrypt_with_aes_cbc(s, "k"), "k") == s

    def test_wrong_key_fails_gcm(self):
        blob = enc.encrypt_bytes_with_aes_gcm(b"data", "right")
        with pytest.raises(Exception):
            enc.decrypt_bytes_with_aes_gcm(blob, "wrong")

    def test_nondeterministic_ciphertext(self):
        a = enc.encrypt_with_aes_gcm("same", "k")
        b = enc.encrypt_with_aes_gcm("same", "k")
        assert a != b  # fresh salt+nonce each call

    def test_make_cipher_bad_mode(self):
        with pytest.raises(ValueError):
            enc.make_cipher("k", mode="ecb")


class TestEncryptedServing:
    def test_record_encryption_end_to_end(self):
        from analytics_zoo_tpu.serving import (
            Broker, ClusterServing, InputQueue, OutputQueue)
        from analytics_zoo_tpu.serving import schema
        import torch
        import torch.nn as tnn
        from analytics_zoo_tpu.inference import InferenceModel

        cipher = enc.make_cipher("topsecret")
        torch.manual_seed(0)
        m = tnn.Sequential(tnn.Linear(4, 4), tnn.Tanh())
        im = InferenceModel().load_torch(m, np.zeros((1, 4), np.float32))
        x = np.random.RandomState(0).randn(4).astype(np.float32)
        with Broker.launch(backend="python") as broker:
            with ClusterServing(im, broker.port, batch_size=2,
                                cipher=cipher).start():
                in_q = InputQueue(port=broker.port, cipher=cipher)
                out_q = OutputQueue(port=broker.port, cipher=cipher)
                in_q.enqueue("e1", x=x)
                got = out_q.query("e1", timeout=20.0)
                # on-the-wire payload is ciphertext: plain decode fails
                plain_out = OutputQueue(port=broker.port)
                with pytest.raises(Exception):
                    plain_out.query("e1", timeout=0.1)
        want = m(torch.from_numpy(x[None])).detach().numpy()[0]
        np.testing.assert_allclose(got, want, atol=1e-4)
