import numpy as np
import pytest

from analytics_zoo_tpu.models.recommendation import (ColumnFeatureInfo,
                                                     NeuralCF,
                                                     SessionRecommender,
                                                     UserItemFeature,
                                                     WideAndDeep)


def _ml_like(n=400, users=50, items=30, classes=5, seed=0):
    rng = np.random.default_rng(seed)
    u = rng.integers(1, users + 1, n)
    i = rng.integers(1, items + 1, n)
    # rating structured on user/item parity so the model can learn
    y = ((u + i) % classes).astype(np.int32)
    x = np.stack([u, i], 1).astype(np.float32)
    return x, y


def test_ncf_fit_predict(orca_ctx):
    from analytics_zoo_tpu.learn.optimizers import Adam
    x, y = _ml_like()
    ncf = NeuralCF(user_count=50, item_count=30, class_num=5)
    ncf.compile(optimizer=Adam(5e-3), loss="sparse_categorical_crossentropy",
                metrics=["accuracy"])
    hist = ncf.fit(x, y, batch_size=80, nb_epoch=20)
    assert hist["loss"][-1] < hist["loss"][0]
    res = ncf.evaluate(x, y, batch_size=80)
    assert res["accuracy"] > 0.5  # structured signal is learnable
    probs = ncf.predict(x[:8])
    assert probs.shape == (8, 5)
    np.testing.assert_allclose(np.asarray(probs).sum(-1), 1.0, rtol=1e-4)


def test_ncf_no_mf_and_save_load(orca_ctx, tmp_path):
    x, y = _ml_like(n=160)
    ncf = NeuralCF(50, 30, 5, include_mf=False)
    ncf.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    ncf.fit(x, y, batch_size=32, nb_epoch=1)
    path = str(tmp_path / "ncf")
    ncf.save_model(path)
    from analytics_zoo_tpu.models.common import ZooModel
    loaded = ZooModel.load_model(path)
    assert isinstance(loaded, NeuralCF)
    np.testing.assert_allclose(np.asarray(loaded.predict(x[:4])),
                               np.asarray(ncf.predict(x[:4])), rtol=1e-5)


def test_recommender_utilities(orca_ctx):
    x, y = _ml_like(n=80)
    ncf = NeuralCF(50, 30, 5)
    ncf.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    ncf.fit(x, y, batch_size=16, nb_epoch=1)
    feats = [UserItemFeature(int(r[0]), int(r[1]), r) for r in x[:40]]
    preds = ncf.predict_user_item_pair(feats).collect()[0]
    assert len(preds) == 40
    assert all(1 <= p.prediction <= 5 for p in preds)
    recs = ncf.recommend_for_user(feats, max_items=3).collect()
    assert all(len(r) <= 3 for r in recs)
    ritems = ncf.recommend_for_item(feats, max_users=2).collect()
    assert all(len(r) <= 2 for r in ritems)


def test_wide_and_deep_variants(orca_ctx):
    info = ColumnFeatureInfo(
        wide_base_cols=["a", "b"], wide_base_dims=[10, 10],
        wide_cross_cols=["ab"], wide_cross_dims=[20],
        indicator_cols=["c"], indicator_dims=[4],
        embed_cols=["u", "i"], embed_in_dims=[30, 40], embed_out_dims=[8, 8],
        continuous_cols=["age"])
    n = 96
    rng = np.random.default_rng(0)
    wide = np.zeros((n, 40), np.float32)
    wide[np.arange(n), rng.integers(0, 40, n)] = 1.0
    ind = np.zeros((n, 4), np.float32)
    ind[np.arange(n), rng.integers(0, 4, n)] = 1.0
    emb = np.stack([rng.integers(1, 31, n), rng.integers(1, 41, n)], 1).astype(np.float32)
    con = rng.normal(size=(n, 1)).astype(np.float32)
    y = rng.integers(0, 2, n)

    wnd = WideAndDeep(2, info, model_type="wide_n_deep")
    wnd.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                metrics=["accuracy"])
    wnd.fit([wide, ind, emb, con], y, batch_size=32, nb_epoch=2)
    assert wnd.predict([wide, ind, emb, con]).shape == (n, 2)

    wide_only = WideAndDeep(2, info, model_type="wide")
    wide_only.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    wide_only.fit(wide, y, batch_size=32, nb_epoch=1)

    deep = WideAndDeep(2, info, model_type="deep")
    deep.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    deep.fit([ind, emb, con], y, batch_size=32, nb_epoch=1)


def test_session_recommender(orca_ctx):
    rng = np.random.default_rng(0)
    n, sess_len, items = 64, 5, 20
    x = rng.integers(1, items + 1, (n, sess_len)).astype(np.float32)
    y = rng.integers(0, items, n)
    sr = SessionRecommender(item_count=items, item_embed=8,
                            rnn_hidden_layers=[12, 8], session_length=sess_len)
    sr.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    sr.fit(x, y, batch_size=16, nb_epoch=1)
    recs = sr.recommend_for_session(x[:4], max_items=3)
    assert len(recs) == 4 and len(recs[0]) == 3
    with pytest.raises(Exception):
        sr.recommend_for_user(None, 3)


def test_session_recommender_with_history(orca_ctx):
    rng = np.random.default_rng(0)
    n, sess_len, his_len, items = 32, 4, 6, 15
    xs = rng.integers(1, items + 1, (n, sess_len)).astype(np.float32)
    xh = rng.integers(1, items + 1, (n, his_len)).astype(np.float32)
    y = rng.integers(0, items, n)
    sr = SessionRecommender(items, 8, [10], sess_len, include_history=True,
                            mlp_hidden_layers=[10], history_length=his_len)
    sr.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    sr.fit([xs, xh], y, batch_size=16, nb_epoch=1)
    assert sr.predict([xs, xh]).shape == (n, items)


def test_wide_and_deep_tensor_parallel(orca_ctx):
    """W&D trains under dp2,tp4 with its embedding tables model-sharded
    (tp_param_rules — same new capability NCF has)."""
    from analytics_zoo_tpu.parallel import mesh as mesh_lib

    info = ColumnFeatureInfo(
        wide_base_cols=["a"], wide_base_dims=[16],
        embed_cols=["u", "i"], embed_in_dims=[32, 32],
        embed_out_dims=[8, 8], continuous_cols=["age"])
    n = 64
    rng = np.random.default_rng(1)
    wide = np.zeros((n, 16), np.float32)
    wide[np.arange(n), rng.integers(0, 16, n)] = 1.0
    emb = np.stack([rng.integers(1, 33, n), rng.integers(1, 33, n)],
                   1).astype(np.float32)
    con = rng.normal(size=(n, 1)).astype(np.float32)
    y = rng.integers(0, 2, n)

    wnd = WideAndDeep(2, info, model_type="wide_n_deep")
    wnd.model.set_strategy("dp2,tp4",
                           param_rules=WideAndDeep.tp_param_rules())
    wnd.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    h = wnd.fit([wide, emb, con], y, batch_size=32, nb_epoch=2)
    assert all(np.isfinite(v) for v in h["loss"])
    est = wnd.model.estimator
    table = est._state["params"]["embed_0"]["embedding"]
    assert "model" in str(table.sharding.spec), table.sharding.spec
    mesh_lib.set_default_mesh(None)
