"""Metric history & cost attribution (ISSUE 17): the bounded in-process
time-series store (windowed rate/quantile answers from ring samples),
exemplar-linked traces on the serving hot path, fleet history merge
through the snapshot algebra, per-request cost accounting, and the
``/metrics/history`` + ``/query`` HTTP surface."""
import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from analytics_zoo_tpu.common import telemetry, timeseries
from analytics_zoo_tpu.common.telemetry import MetricsRegistry


def _get_json(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


@pytest.fixture(autouse=True)
def _fresh_registry():
    telemetry.reset_for_tests()
    yield
    telemetry.reset_for_tests()


# ------------------------------------------------------ window algebra


def test_counter_rate_and_delta_from_window_edges():
    store = timeseries.TimeSeriesStore(tick_s=5.0, max_points=64)
    c = telemetry.get_registry().counter("zoo_ts_unit_total", "d")
    c.inc(10)
    store.tick(now=0.0)
    c.inc(30)
    store.tick(now=10.0)
    out = store.query("zoo_ts_unit_total", window=10.0, now=10.0)
    assert out["agg"] == "rate"          # counter default
    (pt,) = out["points"]
    assert pt["value"] == pytest.approx(3.0)     # 30 events / 10 s
    assert pt["covered_s"] == pytest.approx(10.0)
    d = store.query("zoo_ts_unit_total", window=10.0, agg="delta",
                    now=10.0)["points"][0]["value"]
    assert d == pytest.approx(30.0)
    # a narrower window excludes the older edge: base falls back to the
    # point at/before the window start, not the series origin
    c.inc(5)
    store.tick(now=20.0)
    r = store.query("zoo_ts_unit_total", window=10.0, agg="rate",
                    now=20.0)["points"][0]["value"]
    assert r == pytest.approx(0.5)               # 5 events / 10 s


def test_gauge_window_aggregates():
    store = timeseries.TimeSeriesStore(tick_s=5.0, max_points=64)
    g = telemetry.get_registry().gauge("zoo_ts_unit_depth", "d")
    for t, v in ((0.0, 2.0), (5.0, 8.0), (10.0, 4.0)):
        g.set(v)
        store.tick(now=t)
    q = lambda agg: store.query("zoo_ts_unit_depth", window=10.0,
                                agg=agg, now=10.0)["points"][0]["value"]
    assert q("last") == 4.0
    assert q("max") == 8.0
    assert q("min") == 2.0
    assert q("avg") == pytest.approx((2.0 + 8.0 + 4.0) / 3)
    with pytest.raises(ValueError):
        store.query("zoo_ts_unit_depth", window=10.0, agg="p99", now=10.0)


def test_windowed_p99_matches_offline_recompute_within_bucket():
    """Acceptance (ISSUE 17): ``p99(window)`` comes from bucket-count
    deltas at the window edges and must agree with an offline
    recomputation from the raw tick samples to within one bucket
    bound — including forgetting out-of-window traffic the cumulative
    reservoir would remember forever."""
    store = timeseries.TimeSeriesStore(tick_s=5.0, max_points=64)
    h = telemetry.get_registry().histogram(
        "zoo_ts_unit_seconds", "d",
        buckets=(0.01, 0.05, 0.1, 0.5, 1.0, 5.0))
    rng = np.random.RandomState(7)
    # era 1 (ages out of the window): fast traffic
    for v in rng.uniform(0.001, 0.02, size=200):
        h.observe(float(v))
    store.tick(now=0.0)
    # era 2 (in-window): slow traffic
    in_window = [float(v) for v in rng.uniform(0.2, 3.0, size=300)]
    for v in in_window:
        h.observe(v)
    store.tick(now=60.0)

    val = store.query("zoo_ts_unit_seconds", window=60.0, agg="p99",
                      now=60.0)["points"][0]["value"]

    # offline recompute from the raw ring samples: subtract the bucket
    # vectors at the window edges, walk the cumulative counts to the
    # bucket containing the 99th percentile
    ser = store._series[("zoo_ts_unit_seconds", "")]
    pts = list(ser.points)
    base, last = pts[0], pts[-1]
    d_counts = [a - b for a, b in zip(last[3], base[3])]
    total = last[1] - base[1]
    assert total == 300
    le = list(ser.le) + [float("inf")]
    acc, lo, hi = 0, 0.0, le[-1]
    for i, c in enumerate(d_counts):
        acc += c
        if acc >= 0.99 * total:
            lo = le[i - 1] if i else 0.0
            hi = le[i]
            break
    assert lo <= val <= hi, (val, lo, hi)
    # and the true empirical p99 of what was observed in-window lands in
    # the same bucket bound
    true_p99 = float(np.percentile(in_window, 99))
    assert lo <= true_p99 <= hi
    # the windowed answer is NOT polluted by era-1 traffic: a cumulative
    # quantile over all 500 samples would sit far below the window's
    assert val >= 0.5


def test_ring_capacity_bounds_points_and_covered_s_reports_partial():
    store = timeseries.TimeSeriesStore(tick_s=5.0, max_points=4)
    c = telemetry.get_registry().counter("zoo_ts_unit_total", "d")
    for t in range(10):
        c.inc(1)
        store.tick(now=float(t * 5))
    assert store.points_held() <= 4 * store.series_held()
    # a 1h window over a ring that only holds 15s of history answers
    # with covered_s == what the data supports, not the asked window
    pt = store.query("zoo_ts_unit_total", window=3600.0, agg="delta",
                     now=45.0)["points"][0]
    assert pt["covered_s"] == pytest.approx(15.0)
    assert pt["value"] == pytest.approx(3.0)     # 3 increments survive


def test_series_born_after_start_reads_implicit_zero_base():
    """A counter/histogram registered AFTER the store began ticking
    genuinely started from zero — the window delta must be the full
    total, not zero (the one-point ring would otherwise make base ==
    last). This is what keeps SLO burn alive for late-registered
    series."""
    store = timeseries.TimeSeriesStore(tick_s=5.0, max_points=64)
    store.tick(now=0.0)                  # store is live, series is not
    c = telemetry.get_registry().counter("zoo_ts_unit_total", "d")
    c.inc(7)
    store.tick(now=5.0)                  # first (and only) point
    d, covered = store.window_scalar_delta("zoo_ts_unit_total",
                                           window=60.0, now=5.0)
    assert d == pytest.approx(7.0)
    assert covered > 0


# ------------------------------------------------------- fleet history


def test_fleet_window_merge_property_rates_add():
    """Property (ISSUE 17 satellite): merging two replicas' windowed
    deltas through ``merge_snapshot`` gives exactly the delta of the
    merged counters — so fleet rate == sum of per-replica rates, and
    histogram bucket deltas add elementwise."""
    rng = np.random.RandomState(3)
    deltas, windows, totals = [], [], []
    for _ in range(2):                   # two simulated replicas
        telemetry.reset_for_tests()
        store = timeseries.TimeSeriesStore(tick_s=5.0, max_points=64)
        reg = telemetry.get_registry()
        c = reg.counter("zoo_ts_prop_total", "d", ("stream",)
                        ).labels("s1")
        h = reg.histogram("zoo_ts_prop_seconds", "d",
                          buckets=(0.1, 1.0))
        base_inc = int(rng.randint(0, 50))
        c.inc(base_inc)
        for v in rng.uniform(0.01, 2.0, size=int(rng.randint(1, 40))):
            h.observe(float(v))
        store.tick(now=0.0)
        t0 = c.value
        inc = int(rng.randint(1, 100))
        c.inc(inc)
        obs = [float(v) for v in rng.uniform(0.01, 2.0,
                                             size=int(rng.randint(1, 40)))]
        for v in obs:
            h.observe(v)
        store.tick(now=60.0)
        deltas.append((inc, len(obs)))
        totals.append((t0, c.value))
        windows.append(store.windows_delta((60.0,), now=60.0)["60s"])

    merged = MetricsRegistry.merge_snapshot(windows[0], windows[1])
    want_delta = deltas[0][0] + deltas[1][0]
    assert merged["zoo_ts_prop_total"]["stream=s1"] == \
        pytest.approx(want_delta)
    # delta of the merged raw counters over the same edges — identical
    fleet_t0 = sum(t[0] for t in totals)
    fleet_t1 = sum(t[1] for t in totals)
    assert fleet_t1 - fleet_t0 == pytest.approx(want_delta)
    # merged windowed rate == sum of per-replica windowed rates
    assert merged["zoo_ts_prop_total"]["stream=s1"] / 60.0 == \
        pytest.approx(sum(w["zoo_ts_prop_total"]["stream=s1"] / 60.0
                          for w in windows))
    mh = merged["zoo_ts_prop_seconds"]
    assert mh["count"] == deltas[0][1] + deltas[1][1]
    assert mh["bucket_counts"] == [
        a + b for a, b in zip(windows[0]["zoo_ts_prop_seconds"]
                              ["bucket_counts"],
                              windows[1]["zoo_ts_prop_seconds"]
                              ["bucket_counts"])]


def test_fleet_history_dead_replica_degrades_to_partial():
    """A registered-but-dead peer lands in ``failed`` and the fleet
    history answer degrades to partial — local retained windows are
    served untouched, never poisoned by the failed scrape."""
    import time

    from analytics_zoo_tpu.common import fleet
    from analytics_zoo_tpu.serving.broker import Broker
    from analytics_zoo_tpu.serving.frontend import scrape_fleet_history

    with Broker.launch(backend="python") as broker:
        reg = fleet.ReplicaRegistry("127.0.0.1", broker.port)
        now = time.time()
        reg.publish(fleet.ReplicaInfo("serving:9:dead", port=1,
                                      started_at=now, last_heartbeat=now))
        c = telemetry.get_registry().counter("zoo_ts_local_total")
        store = timeseries.get_store()
        c.inc(0)                          # series exists at the base tick
        store.tick()
        c.inc(4)
        store.tick()
        merged, meta = scrape_fleet_history("127.0.0.1", broker.port,
                                            windows=(60.0,),
                                            timeout_s=0.5)
        assert meta["failed"] == ["serving:9:dead"]
        assert merged["60s"]["zoo_ts_local_total"] == pytest.approx(4.0)
        snap = telemetry.snapshot()
        assert snap["zoo_fleet_scrape_errors_total"] == \
            {"replica=serving:9:dead": 1.0}
        # local rings survived the failed scrape intact
        again, _ = scrape_fleet_history("127.0.0.1", broker.port,
                                        windows=(60.0,), timeout_s=0.5)
        assert again["60s"]["zoo_ts_local_total"] >= 4.0


# --------------------------------------------------- exemplars & traces


def test_histogram_exemplars_bounded_and_in_prometheus_text():
    reg = telemetry.get_registry()
    h = reg.histogram("zoo_ts_unit_seconds", "d", buckets=(0.1, 1.0))
    h.observe(0.05, exemplar="trace-a")
    h.observe(0.07, exemplar="trace-b")   # same bucket: latest wins
    h.observe(0.5, exemplar="trace-c")
    h.observe(2.0)                        # no exemplar: slot stays empty
    exs = h.labels()._exemplar_state()
    assert len(exs) == 2                  # bounded: one slot per bucket
    assert exs[0][0] == "trace-b"
    assert exs[1][0] == "trace-c"
    text = telemetry.prometheus_text()
    assert '# {trace_id="trace-b"} 0.07' in text
    assert '# {trace_id="trace-c"} 0.5' in text


def test_trace_eviction_counter_counts_lru_drops():
    tr = telemetry.Tracer(capacity=2)
    for i in range(5):
        tr.record(f"uri-{i}", "stage", 0.0, 1.0)
    snap = telemetry.snapshot()
    assert snap["zoo_trace_evictions_total"] == 3.0


def test_query_exemplar_rides_trace_sampling_decision():
    """Exemplars attach only when the record's spans were actually
    recorded, so every exposed trace id resolves on ``/trace``."""
    store = timeseries.TimeSeriesStore(tick_s=5.0, max_points=64)
    h = telemetry.get_registry().histogram(
        "zoo_ts_unit_seconds", "d", buckets=(0.1, 1.0))
    store.tick(now=0.0)
    h.observe(0.5, exemplar="uri-sampled")
    h.observe(0.6)                        # unsampled record: no exemplar
    store.tick(now=5.0)
    out = store.query("zoo_ts_unit_seconds", window=60.0, agg="p99",
                      now=5.0)
    (pt,) = out["points"]
    assert pt["exemplar"]["trace_id"] == "uri-sampled"
    assert pt["exemplar"]["value"] == pytest.approx(0.5)


# --------------------------------------------- HTTP surface, end-to-end


@pytest.mark.slow
def test_history_query_cost_and_healthz_decode_end_to_end():
    """Acceptance drill (ISSUE 17): encode + generate records flow
    through a live engine, then ``/query`` answers a windowed p99 whose
    point carries an exemplar resolvable via ``/trace``;
    ``/metrics/history`` serves the rings; the request-cost histograms
    hold both ``kind="encode"`` and ``kind="generate"`` settlements; and
    ``/healthz`` carries the ``decode`` occupancy block."""
    from analytics_zoo_tpu.models import Seq2Seq
    from analytics_zoo_tpu.serving import (
        Broker, ClusterServing, FrontEnd, InputQueue, OutputQueue,
    )
    from analytics_zoo_tpu.inference import InferenceModel

    m = Seq2Seq(input_dim=3, output_dim=2, hidden_size=8, rnn_type="gru",
                encoder_seq_len=5, decoder_seq_len=4)
    im = InferenceModel().load_zoo(m)
    rng = np.random.RandomState(0)
    enc = rng.randn(5, 3).astype(np.float32)
    start = np.zeros(2, np.float32)

    b = Broker.launch(backend="python")
    eng = ClusterServing(im, b.port, batch_size=4, warmup=False)
    eng.start()
    fe = FrontEnd(b.port, engine=eng).start()
    try:
        in_q = InputQueue(port=b.port)
        out_q = OutputQueue(port=b.port)
        gen_uri = in_q.enqueue("ts_e2e_gen",
                               generate={"max_new_tokens": 8,
                                         "mode": "raw"},
                               x=enc, start=start)
        res = out_q.query(gen_uri, timeout=90.0)
        assert res is not None and res.shape == (8, 2)
        for i in range(4):
            uri = in_q.enqueue(f"ts_e2e_{i}", a_enc=enc,
                               b_dec=np.zeros((4, 2), np.float32))
            assert out_q.query(uri, timeout=60.0) is not None

        base = f"http://127.0.0.1:{fe.port}"
        q = _get_json(base + "/query?name=zoo_serving_latency_seconds"
                             "&window=60&agg=p99")
        assert q["agg"] == "p99" and q["points"], q
        vals = [p["value"] for p in q["points"] if p["value"] is not None]
        assert vals and all(v > 0 for v in vals)
        exs = [p["exemplar"] for p in q["points"] if "exemplar" in p]
        assert exs, q                     # >= 1 point carries an exemplar
        trace_id = exs[0]["trace_id"]
        tr = _get_json(base + f"/trace?uri={trace_id}")
        assert tr.get("traceEvents"), trace_id   # resolvable trace link

        # label filtering: any non-reserved param is an equality filter
        flt = _get_json(base + "/query?name=zoo_serving_latency_seconds"
                               "&window=60&priority=batch")
        assert all(p["labels"].get("priority") == "batch"
                   for p in flt["points"])

        hist = _get_json(base + "/metrics/history"
                                "?name=zoo_serving_lane_depth")
        assert any(s["name"] == "zoo_serving_lane_depth" and s["points"]
                   for s in hist["series"])
        wins = _get_json(base + "/metrics/history?format=windows"
                                "&windows=60")
        assert "zoo_serving_records_total" in wins["windows"]["60s"]

        # cost attribution settled for BOTH kinds
        snap = telemetry.snapshot()
        cost = snap["zoo_request_cost_device_seconds"]
        kinds = {telemetry._parse_label_key(k)[1][
            telemetry._parse_label_key(k)[0].index("kind")]: v
            for k, v in cost.items() if v["count"] > 0}
        assert "encode" in kinds and "generate" in kinds, cost
        assert all(v["sum"] >= 0 for v in cost.values())
        steps = snap["zoo_request_cost_decode_steps"]
        assert any(v["count"] > 0 and v["sum"] >= 8
                   for v in steps.values()), steps
        pages = snap["zoo_request_cost_kv_pages"]
        assert any(v["count"] > 0 and v["sum"] >= 1
                   for v in pages.values()), pages

        # /healthz decode occupancy block (an SLO shed in this tiny run
        # answers 503 but the body is still the full document)
        try:
            with urllib.request.urlopen(base + "/healthz") as r:
                hz = json.loads(r.read())
        except urllib.error.HTTPError as e:
            hz = json.loads(e.read())
        dec = hz.get("decode") or {}
        assert {"live_sequences", "preemptions", "pages_in_use",
                "pages_free"} <= set(dec)
        assert dec["live_sequences"] == 0         # everything retired
        assert dec["pages_in_use"] == 0

        # HTTP error contract
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/query", timeout=10)
        assert ei.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                base + "/query?name=zoo_serving_latency_seconds"
                       "&agg=bogus", timeout=10)
        assert ei.value.code == 400
    finally:
        fe.stop()
        eng.stop()
        b.stop()
