"""Autotuner tests (ops/autotune.py): verdict measurement, cache
persistence, the never-selects-slower invariant, the background tuning
queue, and the flash-attention front end on the CPU interpreter.

All timing-based assertions use grossly mismatched workloads (one matmul
tower vs an add) so they hold on any shared CI box.
"""

import json
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from analytics_zoo_tpu.ops import autotune


@pytest.fixture
def tuner_env(monkeypatch, tmp_path):
    """Point the verdict cache at a tmp file and keep iters tiny."""
    path = str(tmp_path / "autotune.json")
    monkeypatch.setenv("ZOO_AUTOTUNE_CACHE", path)
    monkeypatch.setenv("ZOO_AUTOTUNE_ITERS", "2")
    monkeypatch.delenv("ZOO_AUTOTUNE", raising=False)
    autotune.reset_tuner()
    yield path
    autotune.reset_tuner()
    autotune._pending.clear()


def _heavy(x):
    # ~200 chained matmuls: reliably slower than an add on any host
    for _ in range(200):
        x = x @ x * 0.5
    return x


def _light(x):
    return x + 1.0


X = jnp.ones((64, 64), jnp.float32) * 0.1


# ------------------------------------------------------------ measurement

def test_tune_picks_faster_candidate(tuner_env):
    rec = autotune.get_tuner().tune(
        "demo", "demo|fast", {"light": _light}, _heavy, (X,), iters=2)
    assert rec["best"] == "light"
    assert rec["use_kernel"] is True
    assert rec["best_ms"] < rec["reference_ms"]
    assert rec["speedup"] > 1.0


def test_tune_falls_back_when_reference_wins(tuner_env):
    rec = autotune.get_tuner().tune(
        "demo", "demo|slow", {"heavy": _heavy}, _light, (X,), iters=2)
    assert rec["best"] == "heavy"
    assert rec["use_kernel"] is False
    # the structural invariant: use_kernel is ONLY set when the candidate
    # strictly beat the reference, so dispatch can never pick a loser
    assert rec["best_ms"] >= rec["reference_ms"]


def test_tune_records_candidate_errors(tuner_env):
    def broken(x):
        raise RuntimeError("no such kernel on this backend")

    rec = autotune.get_tuner().tune(
        "demo", "demo|err", {"broken": broken, "light": _light},
        _heavy, (X,), iters=2)
    assert "broken" in rec["errors"]
    assert rec["best"] == "light" and rec["use_kernel"]

    rec2 = autotune.get_tuner().tune(
        "demo", "demo|allerr", {"broken": broken}, _light, (X,), iters=2)
    assert rec2["best"] is None
    assert rec2["use_kernel"] is False
    assert rec2["best_ms"] is None


# ------------------------------------------------------- host-thunk timing

def test_tune_thunks_times_host_callables(tuner_env):
    """tune_thunks measures nullary HOST thunks (the paged-step decision:
    the gather fallback's cost is host-side python a jit harness cannot
    see) with the same verdict contract as tune()."""
    import time

    def slow():
        time.sleep(0.005)
        return np.zeros(4)

    rec = autotune.get_tuner().tune_thunks(
        "paged_step", "step|fast", {"paged": lambda: np.zeros(4)}, slow,
        iters=2)
    assert rec["best"] == "paged" and rec["use_kernel"]
    assert rec["speedup"] > 1.0
    assert autotune.get_tuner().lookup("step|fast") == rec

    rec2 = autotune.get_tuner().tune_thunks(
        "paged_step", "step|slow", {"paged": slow},
        lambda: np.zeros(4), iters=2)
    assert rec2["use_kernel"] is False           # never-selects-slower

    def boom():
        raise RuntimeError("thunk exploded")

    rec3 = autotune.get_tuner().tune_thunks(
        "paged_step", "step|err", {"paged": boom}, lambda: np.zeros(4),
        iters=2)
    assert "paged" in rec3["errors"] and rec3["use_kernel"] is False


# ------------------------------------------------------------ persistence

def test_verdict_persists_across_tuner_instances(tuner_env):
    autotune.get_tuner().tune(
        "demo", "demo|persist", {"light": _light}, _heavy, (X,), iters=2)
    with open(tuner_env) as f:
        on_disk = json.load(f)
    assert on_disk["demo|persist"]["best"] == "light"

    autotune.reset_tuner()                      # fresh process simulation
    rec = autotune.get_tuner().lookup("demo|persist", "demo")
    assert rec is not None and rec["use_kernel"]


def test_corrupt_cache_file_is_ignored(tuner_env):
    with open(tuner_env, "w") as f:
        f.write("{not json")
    assert autotune.get_tuner().lookup("anything") is None
    # and recording over the corrupt file heals it
    autotune.get_tuner().record("k", {"kernel": "demo", "use_kernel": False})
    autotune.reset_tuner()
    assert autotune.get_tuner().lookup("k")["kernel"] == "demo"


# ---------------------------------------------------------- pending queue

def test_pending_queue_dedupes_and_drains(tuner_env):
    ran = []
    autotune.enqueue_tune("q|a", lambda: ran.append("a"))
    autotune.enqueue_tune("q|a", lambda: ran.append("dup"))
    autotune.enqueue_tune("q|b", lambda: ran.append("b"))
    assert autotune.pending_count() == 2
    assert autotune.tune_pending(limit=1) == 1
    assert autotune.pending_count() == 1
    assert autotune.tune_pending() == 1
    assert autotune.pending_count() == 0
    assert sorted(ran) == ["a", "b"]            # the dup never ran


def test_pending_thunk_failure_is_contained(tuner_env):
    def boom():
        raise RuntimeError("tuning exploded")

    autotune.enqueue_tune("q|boom", boom)
    assert autotune.tune_pending() == 1         # no raise
    assert autotune.pending_count() == 0


def test_enqueue_noop_when_off_or_already_cached(tuner_env, monkeypatch):
    autotune.get_tuner().record("q|done", {"use_kernel": False})
    autotune.enqueue_tune("q|done", lambda: None)
    assert autotune.pending_count() == 0

    monkeypatch.setenv("ZOO_AUTOTUNE", "off")
    autotune.enqueue_tune("q|off", lambda: None)
    assert autotune.pending_count() == 0


def test_warm_async_worker_drains_queue(tuner_env):
    """The compile-ahead warmup thread is the queue's consumer: after the
    rungs land it must call tune_pending()."""
    from analytics_zoo_tpu.common import compile_ahead, telemetry

    drained = threading.Event()
    autotune.enqueue_tune("q|warm", drained.set)
    cache = compile_ahead.ExecutableCache(
        jax.jit(lambda x: x * 2.0), name="t_autotune_drain",
        registry=telemetry.MetricsRegistry(), tracer=telemetry.Tracer())
    t = cache.warm_async([(jax.ShapeDtypeStruct((2, 2), np.float32),)])
    t.join(timeout=60)
    assert not t.is_alive()
    assert drained.is_set()
    assert autotune.pending_count() == 0


# ------------------------------------------------- flash attention front

def _attn_args(s_q=64, s_k=64, d=64, dtype=jnp.float32):
    key = jax.random.PRNGKey(7)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (1, s_q, 2, d), dtype)
    k = jax.random.normal(kk, (1, s_k, 2, d), dtype)
    v = jax.random.normal(kv, (1, s_k, 2, d), dtype)
    return q, k, v


def test_attention_candidates_filter():
    # full grid survives at bench shapes; tiny shapes get one clamped cfg
    big = autotune._attention_candidates(2048, 2048)
    assert set(big) == {"128x128", "128x256", "256x256", "256x512",
                        "512x512"}
    tiny = autotune._attention_candidates(64, 64)
    assert tiny == {"64x64": (64, 64)}


def test_tune_attention_on_cpu_interpreter(tuner_env, monkeypatch):
    monkeypatch.setenv("ZOO_PALLAS_INTERPRET", "1")
    rec = autotune.tune_attention(1, 64, 2, 64, dtype=jnp.float32,
                                  causal=True)
    assert rec["best"] is not None, rec["errors"]
    # never-selects-slower, whichever way the measurement went
    if rec["use_kernel"]:
        assert rec["best_ms"] < rec["reference_ms"]
    else:
        assert rec["best_ms"] >= rec["reference_ms"]
    key = autotune.attention_key(1, 64, 64, 2, 64, jnp.float32, True)
    assert autotune.get_tuner().lookup(key) == rec


def test_attention_decision_off_and_unavailable(tuner_env, monkeypatch):
    monkeypatch.setenv("ZOO_AUTOTUNE", "off")
    assert autotune.attention_decision(
        1, 64, 64, 2, 64, jnp.float32, False, True) is None
    # mode on, but CPU without interpret mode: kernels can't run at all
    monkeypatch.delenv("ZOO_AUTOTUNE", raising=False)
    monkeypatch.delenv("ZOO_PALLAS_INTERPRET", raising=False)
    assert autotune.attention_decision(
        1, 64, 64, 2, 64, jnp.float32, False, True) is None
    assert autotune.pending_count() == 0


def test_attention_decision_miss_enqueues_under_trace(tuner_env,
                                                      monkeypatch):
    monkeypatch.setenv("ZOO_PALLAS_INTERPRET", "1")
    assert autotune.attention_decision(
        1, 64, 64, 2, 64, jnp.float32, True, concrete=False) is None
    assert autotune.pending_count() == 1


def test_auto_flash_matches_blockwise_when_off(tuner_env, monkeypatch):
    from analytics_zoo_tpu.ops.flash_attention import blockwise_attention
    monkeypatch.setenv("ZOO_AUTOTUNE", "off")
    q, k, v = _attn_args()
    out = autotune.auto_flash_attention(q, k, v, causal=True)
    np.testing.assert_array_equal(
        np.asarray(out), np.asarray(blockwise_attention(q, k, v,
                                                        causal=True)))


def test_auto_flash_sync_tunes_and_stays_correct(tuner_env, monkeypatch):
    from analytics_zoo_tpu.ops.flash_attention import blockwise_attention
    monkeypatch.setenv("ZOO_PALLAS_INTERPRET", "1")
    monkeypatch.setenv("ZOO_AUTOTUNE", "sync")
    q, k, v = _attn_args()
    out = autotune.auto_flash_attention(q, k, v, causal=True)
    # first concrete call in sync mode tuned on the spot
    key = autotune.attention_key(1, 64, 64, 2, 64, jnp.float32, True)
    assert autotune.get_tuner().lookup(key) is not None
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(blockwise_attention(q, k, v, causal=True)),
        rtol=2e-3, atol=2e-3)


def test_auto_flash_dispatches_tuned_kernel(tuner_env, monkeypatch):
    """A persisted winning verdict routes dispatch through the pallas
    kernel at the recorded block config — numerics must hold there too."""
    from analytics_zoo_tpu.ops.flash_attention import blockwise_attention
    monkeypatch.setenv("ZOO_PALLAS_INTERPRET", "1")
    q, k, v = _attn_args()
    key = autotune.attention_key(1, 64, 64, 2, 64, jnp.float32, False)
    autotune.get_tuner().record(key, {
        "kernel": "flash_attention", "best": "64x64", "use_kernel": True,
        "best_ms": 1.0, "reference_ms": 2.0, "speedup": 2.0})
    out = autotune.auto_flash_attention(q, k, v, causal=False)
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(blockwise_attention(q, k, v, causal=False)),
        rtol=2e-3, atol=2e-3)
