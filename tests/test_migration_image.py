"""Pretrained-weight import goldens for the image model zoo.

Every supported backbone's torch twin (state_dict keys identical to
torchvision's) is imported into the zoo ``ImageClassifier`` and predict
parity is asserted in eval mode — proving a REAL torchvision checkpoint
loaded via ``ImageClassifier(..., pretrained=...)`` reproduces torchvision
outputs (ref ``Net.scala:446`` loadModel semantics; per-model pretrained
configs in ``ImageClassifier.scala``).
"""

import numpy as np
import pytest
import torch

from analytics_zoo_tpu.models.image.imageclassification import (
    ImageClassifier,
)
from analytics_zoo_tpu.models.migration_image import (
    MAKE_TWINS, import_image_classifier_from_torch,
)


def _parity(name, size, class_num=10, batch=2, tol=1e-4):
    twin = MAKE_TWINS[name](class_num).eval()
    clf = ImageClassifier(class_num=class_num, model_name=name,
                          image_size=size)
    import_image_classifier_from_torch(clf, twin)
    x = (np.random.RandomState(0)
         .rand(batch, size, size, 3).astype(np.float32) * 2 - 1)
    with torch.no_grad():
        tout = torch.softmax(
            twin(torch.tensor(x.transpose(0, 3, 1, 2))), -1).numpy()
    zout = np.asarray(clf.predict(x, distributed=False))
    err = float(np.abs(zout - tout).max())
    assert err < tol, (name, err)
    return clf, twin


@pytest.mark.slow  # 30-400s per model: full torchvision import + parity
class TestTorchvisionImportParity:
    """eval-mode predict parity vs the torch twin (GAP backbones run at
    64px to keep single-core CPU time sane; the fixed-flatten ones need
    their native 224)."""

    @pytest.mark.parametrize("name,size", [
        ("resnet-50", 64), ("mobilenet-v2", 64), ("squeezenet", 64),
        ("densenet-121", 64),
    ])
    def test_gap_backbones(self, orca_ctx, name, size):
        _parity(name, size)

    def test_alexnet_224(self, orca_ctx):
        """224 exercises the CHW->HWC flatten permute on classifier.1."""
        _parity("alexnet", 224, batch=1)

    def test_vgg16_224(self, orca_ctx):
        _parity("vgg-16", 224, batch=1)

    def test_pretrained_kwarg_accepts_path_and_dict(self, orca_ctx,
                                                    tmp_path):
        """The ref's one-call loadModel surface: construct with
        ``pretrained=`` (state_dict or torch.save path)."""
        twin = MAKE_TWINS["resnet-50"](7).eval()
        p = str(tmp_path / "resnet50.pt")
        torch.save(twin.state_dict(), p)
        x = np.random.RandomState(1).rand(1, 64, 64, 3).astype(np.float32)
        with torch.no_grad():
            tout = torch.softmax(
                twin(torch.tensor(x.transpose(0, 3, 1, 2))), -1).numpy()
        for pre in (p, twin.state_dict()):
            clf = ImageClassifier(class_num=7, model_name="resnet-50",
                                  image_size=64, pretrained=pre)
            np.testing.assert_allclose(
                np.asarray(clf.predict(x, distributed=False)), tout,
                atol=1e-4)

    def test_real_image_through_preprocessor(self, orca_ctx):
        """End-to-end: checked-in photo -> torchvision preprocessing
        preset -> imported model; top-1 and probabilities match torch."""
        from PIL import Image

        from analytics_zoo_tpu.models.image.imageclassification import (
            image_classifier as ic,
        )
        img = np.asarray(Image.open(
            "tests/fixtures/detection/img0.png").convert("RGB"), np.float32)
        pipe = ic.preprocessor("resnet-50", source="torchvision")
        feat = pipe.transform({"image": img})
        x = feat["image"][None]                       # [1, 224, 224, 3]
        assert x.shape == (1, 224, 224, 3)
        clf, twin = _parity("resnet-50", 224, batch=1)
        with torch.no_grad():
            tout = torch.softmax(
                twin(torch.tensor(x.transpose(0, 3, 1, 2))), -1).numpy()
        zout = np.asarray(clf.predict(x, distributed=False))
        np.testing.assert_allclose(zout, tout, atol=1e-4)
        assert int(zout.argmax()) == int(tout.argmax())

    def test_unsupported_and_shape_errors(self, orca_ctx):
        with pytest.raises(ValueError, match="inception-v1 excluded"):
            clf = ImageClassifier(class_num=5, model_name="inception-v1",
                                  image_size=64)
            import_image_classifier_from_torch(clf, {})
        # class_num mismatch surfaces as a shape error, not silence
        twin = MAKE_TWINS["squeezenet"](10).eval()
        clf = ImageClassifier(class_num=5, model_name="squeezenet",
                              image_size=64)
        with pytest.raises(ValueError, match="shape"):
            import_image_classifier_from_torch(clf, twin)

    def test_bn_running_stats_actually_land(self, orca_ctx):
        """Running mean/var must land in batch_stats — an import that
        only set scale/bias would still 'look right' on centered data."""
        twin = MAKE_TWINS["resnet-50"](4).eval()
        # make running stats distinctive
        sd = twin.state_dict()
        sd["bn1.running_mean"] += 0.7
        twin.load_state_dict(sd)
        clf = ImageClassifier(class_num=4, model_name="resnet-50",
                              image_size=64)
        import_image_classifier_from_torch(clf, twin)
        est = clf.model._ensure_estimator()
        stats = est.adapter.model_state["batch_stats"]
        bn1 = stats["batchnormalization_1"]
        np.testing.assert_allclose(np.asarray(bn1["mean"]),
                                   sd["bn1.running_mean"].numpy(),
                                   rtol=1e-6)


@pytest.mark.slow  # ~6 min: SSD300-VGG import parity on 1 core
class TestSSD300Import:
    """SSD300-VGG weight import (ssd.pytorch-format state_dict — the
    public source of trained SSD300 weights; ref ObjectDetector.scala
    pretrained VGG-SSD entries)."""

    def test_parity_and_anchor_count(self, orca_ctx):
        from analytics_zoo_tpu.models import SSD300VGG
        from analytics_zoo_tpu.models.migration_image import (
            import_ssd300_from_torch, make_torch_ssd300,
        )
        torch.manual_seed(0)
        twin = make_torch_ssd300(class_num=3).eval()
        for p in twin.parameters():          # tame the random deep VGG
            if p.dim() == 4:
                torch.nn.init.normal_(p, std=0.02)
        ssd = SSD300VGG(class_num=3)
        assert ssd.n_anchors == 8732
        import_ssd300_from_torch(ssd, twin)
        x = np.random.RandomState(0).rand(1, 300, 300, 3) \
            .astype(np.float32)
        with torch.no_grad():
            want = twin(torch.tensor(x.transpose(0, 3, 1, 2))).numpy()
        got = np.asarray(ssd.predict(x, distributed=False))
        assert got.shape == want.shape == (1, 8732, 8)
        rel = float(np.abs(got - want).max()) / \
            (float(np.abs(want).max()) + 1e-9)
        assert rel < 1e-3, rel

    def test_detector_pipeline_over_imported_ssd(self, orca_ctx):
        """The imported model drives the full ObjectDetector decode."""
        from analytics_zoo_tpu.models import SSD300VGG
        from analytics_zoo_tpu.models.image.objectdetection. \
            object_detector import ObjectDetector
        from analytics_zoo_tpu.models.migration_image import (
            import_ssd300_from_torch, make_torch_ssd300,
        )
        twin = make_torch_ssd300(class_num=2).eval()
        ssd = SSD300VGG(class_num=2)
        import_ssd300_from_torch(ssd, twin)
        det = ObjectDetector(ssd, conf_threshold=0.05)
        x = np.random.RandomState(1).rand(1, 300, 300, 3) \
            .astype(np.float32)
        boxes = det.predict(x)
        assert len(boxes) == 1
        assert boxes[0].ndim == 2 and boxes[0].shape[1] == 6

    def test_registry_save_load_roundtrip(self, orca_ctx, tmp_path):
        """SSD300VGG must be registry-registered or load_model raises."""
        from analytics_zoo_tpu.models import SSD300VGG
        from analytics_zoo_tpu.models.common import ZooModel
        m = SSD300VGG(class_num=2)
        p = str(tmp_path / "ssd300")
        m.save_model(p)
        m2 = ZooModel.load_model(p)
        assert type(m2).__name__ == "SSD300VGG"
        assert m2.class_num == 2 and m2.n_anchors == 8732
