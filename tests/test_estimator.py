import os

import numpy as np
import pytest

import flax.linen as nn


class MLP(nn.Module):
    hidden: int = 16
    out: int = 1

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Dense(self.hidden)(x)
        x = nn.relu(x)
        x = nn.Dropout(0.1, deterministic=not train)(x)
        return nn.Dense(self.out)(x)


class Classifier(nn.Module):
    classes: int = 3

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(32)(x)
        x = nn.relu(x)
        return nn.softmax(nn.Dense(self.classes)(x))


def _reg_data(n=256, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 4)).astype(np.float32)
    w = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    y = x @ w + 0.1
    return x, y


def _cls_data(n=300, seed=1):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 8)).astype(np.float32)
    y = (np.abs(x).sum(1) > 6.2).astype(np.int32) + (x[:, 0] > 1).astype(np.int32)
    return x, y


def test_fit_regression_converges(orca_ctx, tmp_path):
    from analytics_zoo_tpu.learn.estimator import Estimator
    x, y = _reg_data()
    from analytics_zoo_tpu.learn.optimizers import Adam
    est = Estimator.from_flax(model=MLP(), loss="mse",
                              optimizer=Adam(1e-2),
                              sample_input=x[:2],
                              model_dir=str(tmp_path / "m"))
    hist = est.fit((x, y), epochs=20, batch_size=32)
    assert hist["loss"][0] > hist["loss"][-1]
    assert hist["loss"][-1] < 0.5
    # summaries recorded
    loss_pts = est.get_train_summary("Loss")
    thr_pts = est.get_train_summary("Throughput")
    assert loss_pts and thr_pts
    # events file parseable by pure-python reader
    from analytics_zoo_tpu.common.summary import read_scalars
    import glob
    ev = glob.glob(str(tmp_path / "m" / "train" / "events.out.tfevents.*"))[0]
    scalars = read_scalars(ev)
    assert "Loss" in scalars and len(scalars["Loss"]) == len(loss_pts)


def test_evaluate_and_metrics(orca_ctx):
    from analytics_zoo_tpu.learn.estimator import Estimator
    x, y = _cls_data()
    from analytics_zoo_tpu.learn.optimizers import Adam
    est = Estimator.from_flax(model=Classifier(), sample_input=x[:2],
                              loss="sparse_categorical_crossentropy",
                              optimizer=Adam(1e-2),
                              metrics=["accuracy", "top5"])
    est.fit((x, y), epochs=25, batch_size=40, shuffle=True)
    res = est.evaluate((x, y), batch_size=32)
    assert set(res) == {"loss", "accuracy", "top5_accuracy"}
    assert res["accuracy"] > 0.7
    assert res["top5_accuracy"] == 1.0  # only 3 classes


def test_predict_with_padding(orca_ctx):
    from analytics_zoo_tpu.learn.estimator import Estimator
    x, y = _reg_data(n=45)
    est = Estimator.from_flax(model=MLP(), loss="mse", sample_input=x[:2])
    preds = est.predict(x, batch_size=16)
    assert preds.shape == (45, 1)


def test_predict_xshards_roundtrip(orca_ctx):
    from analytics_zoo_tpu.learn.estimator import Estimator
    from analytics_zoo_tpu.data import XShards
    x, _ = _reg_data(n=40)
    shards = XShards.partition({"x": x}, num_shards=4)
    est = Estimator.from_flax(model=MLP(), loss="mse", sample_input=x[:2])
    out = est.predict(shards, batch_size=16)
    from analytics_zoo_tpu.data import HostXShards
    assert isinstance(out, HostXShards)
    assert out.collect()[0]["prediction"].shape == (40, 1)


def test_checkpoint_resume(orca_ctx, tmp_path):
    from analytics_zoo_tpu.learn.estimator import Estimator
    from analytics_zoo_tpu.learn import checkpoint as ckpt
    x, y = _reg_data()
    mdir = str(tmp_path / "ck")
    est = Estimator.from_flax(model=MLP(), loss="mse", sample_input=x[:2],
                              model_dir=mdir)
    est.fit((x, y), epochs=2, batch_size=32)
    found = ckpt.find_latest_checkpoint(mdir)
    assert found is not None
    path, version = found
    assert version == est._iteration()

    est2 = Estimator.from_flax(model=MLP(), loss="mse", sample_input=x[:2],
                               model_dir=mdir)
    est2.load_orca_checkpoint(path)
    assert est2._iteration() == version
    p1 = est.get_model()
    p2 = est2.get_model()
    import jax
    for a, b in zip(jax.tree_util.tree_leaves(p1), jax.tree_util.tree_leaves(p2)):
        np.testing.assert_allclose(a, b, rtol=1e-6)


def test_retry_from_snapshot_on_injected_failure(orca_ctx, tmp_path):
    """Fault injection for the elastic retry loop (ref Topology.scala:
    1255-1337): a failing train step must trigger reload of the latest
    snapshot and training must complete from there."""
    from analytics_zoo_tpu.learn.estimator import Estimator
    x, y = _reg_data()
    mdir = str(tmp_path / "ck")
    est = Estimator.from_flax(model=MLP(), loss="mse", sample_input=x[:2],
                              model_dir=mdir)
    est.fit((x, y), epochs=1, batch_size=32)  # EveryEpoch snapshot exists
    step_at_ckpt = est._py_step

    real_step = est._train_step
    calls = {"failures": 0}

    def bomb(state, bx, by):
        if calls["failures"] == 0:
            calls["failures"] += 1
            raise RuntimeError("injected chip failure")
        return real_step(state, bx, by)

    est._train_step = bomb
    h = est.fit((x, y), epochs=2, batch_size=32)
    assert calls["failures"] == 1
    assert len(h["loss"]) == 2 and all(np.isfinite(h["loss"]))
    # resumed from the snapshot, then ran 2 full epochs
    assert est._py_step == step_at_ckpt + 2 * (len(x) // 32)
    assert est._epoch == 3


def test_retry_gives_up_after_budget(orca_ctx, tmp_path):
    from analytics_zoo_tpu.learn.estimator import Estimator
    x, y = _reg_data()
    mdir = str(tmp_path / "ck")
    est = Estimator.from_flax(model=MLP(), loss="mse", sample_input=x[:2],
                              model_dir=mdir)
    est.fit((x, y), epochs=1, batch_size=32)
    est.failure_retry_times = 2
    calls = {"failures": 0}

    def always_bomb(state, bx, by):
        calls["failures"] += 1
        raise RuntimeError("persistent failure")

    est._train_step = always_bomb
    with pytest.raises(RuntimeError, match="persistent failure"):
        est.fit((x, y), epochs=1, batch_size=32)
    assert calls["failures"] == est.failure_retry_times + 1


def test_device_cached_epoch_matches_standard(orca_ctx):
    """cache='device' (HBM tier: whole dataset resident, one dispatch per
    epoch, on-device shuffle) must train equivalently to the standard
    host feed — identical losses when shuffle is off."""
    import jax
    from jax.sharding import Mesh
    from analytics_zoo_tpu.learn.estimator import Estimator
    x, y = _reg_data(n=128)
    mesh = Mesh(np.array(jax.devices()[:1]), ("data",))

    def make():
        est = Estimator.from_flax(model=MLP(), loss="mse",
                                  sample_input=x[:2], seed=0)
        est._mesh = mesh
        return est

    a = make()
    ha = a.fit((x, y), epochs=3, batch_size=32, shuffle=False)
    b = make()
    hb = b.fit((x, y), epochs=3, batch_size=32, shuffle=False,
               cache="device")
    np.testing.assert_allclose(hb["loss"], ha["loss"], rtol=1e-5,
                               atol=1e-6)
    assert b._py_step == a._py_step == 12
    # shuffled cached epochs still converge
    c = make()
    hc = c.fit((x, y), epochs=8, batch_size=32, cache="device")
    assert hc["loss"][-1] < hc["loss"][0]


def test_device_cache_rejects_sharded_batch(orca_ctx):
    from analytics_zoo_tpu.learn.estimator import Estimator
    x, y = _reg_data(n=64)
    est = Estimator.from_flax(model=MLP(), loss="mse", sample_input=x[:2])
    with pytest.raises(ValueError, match="unsharded batch"):
        est.fit((x, y), epochs=1, batch_size=32, cache="device")


def test_profile_writes_trace(orca_ctx, tmp_path):
    """fit(profile=True) must produce jax profiler trace artifacts next to
    the tensorboard summaries (SURVEY §5 tracing analog)."""
    from analytics_zoo_tpu.learn.estimator import Estimator
    x, y = _reg_data(n=64)
    est = Estimator.from_flax(model=MLP(), loss="mse", sample_input=x[:2])
    est.set_tensorboard(str(tmp_path), "prof")
    est.fit((x, y), epochs=1, batch_size=32, profile=True)
    trace_root = tmp_path / "prof" / "train"
    found = [p for p in trace_root.rglob("*") if p.is_file()
             and ("trace" in p.name or p.suffix in (".pb", ".gz", ".json"))]
    assert found, f"no profiler trace files under {trace_root}"


def test_gradient_clipping(orca_ctx):
    from analytics_zoo_tpu.learn.estimator import Estimator
    x, y = _reg_data(n=64)
    est = Estimator.from_flax(model=MLP(), loss="mse", sample_input=x[:2])
    est.set_l2_norm_gradient_clipping(1.0)
    h1 = est.fit((x, y), epochs=1, batch_size=32)
    est.set_constant_gradient_clipping(-0.5, 0.5)
    h2 = est.fit((x, y), epochs=1, batch_size=32)
    assert np.isfinite(h1["loss"][0]) and np.isfinite(h2["loss"][0])


def test_fsdp_strategy(orca_ctx):
    from analytics_zoo_tpu.learn.estimator import Estimator
    x, y = _reg_data(n=128)
    est = Estimator.from_flax(model=MLP(hidden=32), loss="mse",
                              sample_input=x[:2], strategy="dp2,fsdp4")
    hist = est.fit((x, y), epochs=3, batch_size=32)
    assert hist["loss"][-1] < hist["loss"][0]
    # params actually sharded over fsdp axis
    import jax
    kernel_sharding = est._state["params"]["Dense_0"]["kernel"].sharding
    assert "fsdp" in str(kernel_sharding.spec)


def test_optimizer_and_schedule_wrappers(orca_ctx):
    from analytics_zoo_tpu.learn.optimizers import SGD, Adam, Poly, Exponential
    import optax
    assert isinstance(SGD(1e-2, momentum=0.9, weightdecay=1e-4,
                          leaningrate_schedule=Poly(2.0, 100)).to_optax(),
                      optax.GradientTransformation)
    assert isinstance(Adam(leaningrate_schedule=Exponential(10, 0.9)).to_optax(),
                      optax.GradientTransformation)


def test_lbfgs_optimizer_trains(orca_ctx):
    """LBFGS (ref optimizers_impl.py:99) runs inside the jitted step and
    beats plain SGD on a deterministic least-squares fit."""
    import flax.linen as nn
    from analytics_zoo_tpu.learn.estimator import Estimator
    from analytics_zoo_tpu.learn.optimizers import LBFGS
    rng = np.random.RandomState(0)
    x = rng.randn(128, 6).astype(np.float32)
    w = rng.randn(6, 1).astype(np.float32)
    y = x @ w

    class Lin(nn.Module):
        @nn.compact
        def __call__(self, inp, train=False):
            return nn.Dense(1, use_bias=False)(inp)

    def final_loss(opt):
        est = Estimator.from_flax(model=Lin(), loss="mse", optimizer=opt,
                                  sample_input=x[:2])
        est.fit((x, y), epochs=12, batch_size=128)
        return est.evaluate((x, y), batch_size=128)["loss"]

    lbfgs_mse = final_loss(LBFGS(learningrate=1.0, ncorrection=10))
    sgd_mse = final_loss("sgd")
    assert np.isfinite(lbfgs_mse) and lbfgs_mse < sgd_mse
    assert lbfgs_mse < 1e-3
    with pytest.raises(ValueError, match="line-search"):
        LBFGS(linesearch=lambda *a: None)


def test_triggers():
    from analytics_zoo_tpu.learn.trigger import (EveryEpoch, SeveralIteration,
                                                 MaxEpoch, MinLoss, TriggerOr)
    t = EveryEpoch()
    assert not t(1, 10, 0.5)  # first observation arms
    assert not t(1, 20, 0.5) and t(2, 30, 0.5) and not t(2, 40, 0.5)
    s = SeveralIteration(5)
    assert s(0, 5, None) and not s(0, 6, None)
    o = TriggerOr(MaxEpoch(3), MinLoss(0.1))
    assert o(3, 0, 1.0) and o(0, 0, 0.05) and not o(1, 0, 1.0)
    from analytics_zoo_tpu.learn.trigger import MaxScore
    ms = MaxScore(0.7)
    assert ms(0, 0, 1.0, score=0.8) and not ms(0, 0, 1.0, score=0.6)
    assert not ms(0, 0, 1.0)  # no validation score yet → never fires
    assert TriggerOr(MaxScore(0.9), MinLoss(0.1))(0, 0, 0.05, score=0.2)


def test_trigger_score_plumbing_and_compat(orca_ctx, tmp_path):
    from analytics_zoo_tpu.learn.estimator import (_fire_trigger,
                                                   _trigger_needs_score)
    from analytics_zoo_tpu.learn.trigger import (MaxScore, MinLoss, Trigger,
                                                 TriggerOr)

    class OldStyle(Trigger):          # pre-score 3-arg user subclass
        def __call__(self, epoch, iteration, loss):
            return loss < 0.5

    assert _fire_trigger(OldStyle(), 1, 1, 0.4, score=0.9)
    assert _fire_trigger(MaxScore(0.5), 1, 1, 0.4, score=0.9)
    assert not _fire_trigger(MaxScore(0.5), 1, 1, 0.4, score=None)
    assert _trigger_needs_score(TriggerOr(MinLoss(0.1), MaxScore(0.5)))
    assert not _trigger_needs_score(MinLoss(0.1))

    # MaxScore without validation_data warns (trigger can never fire)
    import warnings as w
    import flax.linen as nn
    from analytics_zoo_tpu.learn.estimator import Estimator

    class Lin(nn.Module):
        @nn.compact
        def __call__(self, inp, train=False):
            return nn.Dense(1)(inp)

    x = np.random.RandomState(0).randn(32, 4).astype(np.float32)
    y = x.sum(1, keepdims=True).astype(np.float32)
    est = Estimator.from_flax(model=Lin(), loss="mse", optimizer="sgd",
                              sample_input=x[:2],
                              model_dir=str(tmp_path))
    with w.catch_warnings(record=True) as rec:
        w.simplefilter("always")
        est.fit((x, y), epochs=1, batch_size=32,
                checkpoint_trigger=MaxScore(0.9))
    assert any("MaxScore" in str(r.message) for r in rec)


def test_auc_metric(orca_ctx):
    import jax.numpy as jnp
    from analytics_zoo_tpu.learn import metrics
    auc = metrics.get("auc")
    state = auc.init_state()
    y_true = np.array([0, 0, 1, 1], np.float32)
    y_pred = np.array([0.1, 0.4, 0.35, 0.8], np.float32)
    state = auc.update(state, jnp.asarray(y_true), jnp.asarray(y_pred))
    assert abs(auc.result(state) - 0.75) < 0.02


def test_legacy_trigger_nested_in_composites():
    """ADVICE r3: a 3-arg user Trigger subclass works INSIDE TriggerAnd/
    TriggerOr, same as at the top level."""
    from analytics_zoo_tpu.learn.trigger import (MaxScore, Trigger,
                                                 TriggerAnd, TriggerOr)

    class Legacy(Trigger):
        def __call__(self, epoch, iteration, loss):   # old 3-arg form
            return epoch >= 2

    assert TriggerAnd(Legacy(), MaxScore(0.5))(3, 0, 0.1, score=0.9)
    assert not TriggerAnd(Legacy(), MaxScore(0.5))(1, 0, 0.1, score=0.9)
    assert TriggerOr(Legacy(), MaxScore(0.5))(0, 0, 0.1, score=0.9)
    assert not TriggerOr(Legacy(), MaxScore(0.5))(0, 0, 0.1, score=0.2)


def test_maxscore_named_metric_and_error_style_warning():
    """ADVICE r3: MaxScore(metric=...) picks its metric from the val dict;
    unnamed MaxScore warns when the auto-chosen metric is error-style."""
    import warnings
    from analytics_zoo_tpu.learn.trigger import MaxScore

    ms = MaxScore(0.8, metric="accuracy")
    assert ms(1, 1, 0.3, score={"loss": 0.3, "mse": 5.0, "accuracy": 0.9})
    assert not ms(1, 1, 0.3, score={"loss": 0.3, "accuracy": 0.5})
    assert not ms(1, 1, 0.3, score={"loss": 0.3})     # metric absent

    auto = MaxScore(0.8)
    assert auto(1, 1, 0.3, score={"loss": 0.3, "accuracy": 0.95})
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        # a GOOD (low) error metric never exceeds the threshold — the
        # higher-is-better comparison is inverted for mse, hence the warning
        assert not MaxScore(0.8)(1, 1, 0.3, score={"loss": 0.3, "mse": 0.2})
        assert any("error-style" in str(x.message) for x in w)
    # plain float scores keep working (old protocol)
    assert MaxScore(0.5)(1, 1, 0.3, score=0.7)


def test_user_float_score_trigger_still_gets_float():
    """A user trigger written against the float-score protocol receives a
    float even though the estimator now passes the metrics dict."""
    from analytics_zoo_tpu.learn.trigger import fire, Trigger, TriggerOr

    seen = []

    class UserScore(Trigger):
        def __call__(self, epoch, iteration, loss, score=None):
            seen.append(score)
            return score is not None and score > 0.9

    assert fire(UserScore(), 1, 1, 0.2,
                score={"loss": 0.2, "accuracy": 0.95})
    assert seen[-1] == 0.95
    # nested: the composite receives the dict, the leaf gets the float
    assert fire(TriggerOr(UserScore()), 1, 1, 0.2,
                score={"loss": 0.2, "accuracy": 0.95})
    assert seen[-1] == 0.95


def test_maxscore_named_error_metric_warns_at_construction():
    import warnings
    from analytics_zoo_tpu.learn.trigger import MaxScore
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        MaxScore(0.1, metric="mse")
        assert any("WORST" in str(x.message) for x in w)
