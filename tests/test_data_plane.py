"""Parallel data-plane executor + tiered-pipeline tests.

Proves the properties the shard executor claims: ordered results with a
bounded in-flight window (a full ``DISK_n``/``NATIVE_n`` Friesian pipeline
never gathers the table and never holds more than ``workers + 2`` shards in
flight), shard exceptions that carry the failing index, the map-reduce
seam, first()-based metadata, transient zip/column views that don't
re-spill, repartition/partition_by row parity, parquet write modes, and the
streaming prefetch depth knob.
"""

import glob
import os

import numpy as np
import pandas as pd
import pytest

from analytics_zoo_tpu.common.context import OrcaContext
from analytics_zoo_tpu.data import shard as shard_lib
from analytics_zoo_tpu.data.shard import HostXShards, ShardTransformError
from analytics_zoo_tpu.friesian.feature import FeatureTable


@pytest.fixture
def parallel_env(monkeypatch):
    monkeypatch.setenv("ZOO_DATA_WORKERS", "3")
    monkeypatch.setenv("ZOO_DATA_VECTORIZE", "1")


@pytest.fixture
def tier(request):
    old = OrcaContext.train_data_store
    OrcaContext.train_data_store = request.param
    yield request.param
    OrcaContext.train_data_store = old


def _frames(n=8, rows=16):
    rng = np.random.RandomState(7)
    return [pd.DataFrame({
        "user": rng.randint(0, 5, rows),
        "item": rng.randint(0, 9, rows),
        "cat": [["a", "b", "c", "d"][j % 4] for j in range(rows)],
        "hist": [list(range(j % 4)) for j in range(rows)],
    }) for _ in range(n)]


# ------------------------------------------------------------- executor

def test_executor_results_stay_ordered(parallel_env):
    import time as _t
    xs = HostXShards([{"i": i} for i in range(12)])

    def slow_when_early(s):
        _t.sleep(0.02 if s["i"] < 3 else 0)   # early shards finish last
        return {"i": s["i"] * 10}
    out = xs.transform_shard(slow_when_early).collect()
    assert [s["i"] for s in out] == [i * 10 for i in range(12)]
    stats = shard_lib.LAST_RUN_STATS["transform_shard"]
    assert 1 <= stats["in_flight_peak"] <= stats["workers"] + 2


def test_executor_propagates_shard_index(parallel_env):
    xs = HostXShards([{"i": i} for i in range(8)])

    def boom(s):
        if s["i"] == 5:
            raise ValueError("bad shard content")
        return s
    with pytest.raises(ShardTransformError) as ei:
        xs.transform_shard(boom).collect()
    assert ei.value.shard_index == 5
    assert ei.value.op == "transform_shard"
    assert "ValueError" in str(ei.value)
    # the serial path reports the same index
    os.environ["ZOO_DATA_WORKERS"] = "0"
    with pytest.raises(ShardTransformError) as ei:
        xs.transform_shard(boom).collect()
    assert ei.value.shard_index == 5


def test_map_reduce_shard(parallel_env):
    xs = HostXShards(_frames(6))
    total = xs.map_reduce_shard(len, lambda a, b: a + b)
    assert total == sum(len(f) for f in _frames(6))
    with pytest.raises(ShardTransformError):
        xs.map_reduce_shard(lambda d: d["missing"].sum(),
                            lambda a, b: a + b)


def test_first_fetches_only_shard_zero():
    xs = HostXShards(_frames(4))
    fetched = []
    orig = xs._store.get
    xs._store.get = lambda i: (fetched.append(i), orig(i))[1]
    assert len(xs.first()) == 16
    assert fetched == [0]
    with pytest.raises(IndexError):
        HostXShards([]).first()


# --------------------------------------------------- tiered full pipeline

@pytest.mark.parametrize("tier", ["DISK_2", "NATIVE_2"], indirect=True)
def test_full_pipeline_bounded_no_gather(tier, parallel_env, monkeypatch):
    """gen_string_idx fit + encode + pad over a spill tier: completes with
    a bounded in-flight window and no silent whole-table gather."""
    gathers = []
    monkeypatch.setattr(
        HostXShards, "collect",
        lambda self: gathers.append(self) or [
            self._store.get(i) for i in range(self.num_partitions())])

    t = FeatureTable.from_pandas(pd.concat(_frames(8), ignore_index=True), 8)
    assert t.shards.tier.split("_")[0] in ("DISK", "NATIVE")
    [idx] = t.gen_string_idx("cat")
    out = t.encode_string("cat", [idx]).pad("hist", seq_len=4)
    # the only gather so far is the 1-shard StringIndex (to_dict); the
    # 8-shard data table is never materialized
    assert all(g.num_partitions() == 1 for g in gathers)
    for op in ("gen_string_idx", "encode_string", "pad"):
        stats = shard_lib.LAST_RUN_STATS.get(op)
        if stats is not None:
            assert stats["in_flight_peak"] <= stats["workers"] + 2, op
    n_before = len(gathers)
    df = out.to_pandas()          # the one sanctioned data gather, at the end
    assert len(gathers) == n_before + 1
    assert set(df["cat"].unique()) <= {1, 2, 3, 4}
    assert all(len(h) == 4 for h in df["hist"])


def test_zip_and_getitem_are_transient(parallel_env):
    old = OrcaContext.train_data_store
    OrcaContext.train_data_store = "DISK_2"
    try:
        xs = HostXShards([{"x": np.arange(4) + i} for i in range(4)])
        ys = HostXShards([{"y": np.arange(4) * i} for i in range(4)])
        assert xs.tier == "DISK_2"
        zipped = xs.zip(ys)
        # views of already-stored shards: never re-spilled
        assert zipped.tier == "DRAM"
        for i, (a, b) in enumerate(zipped.collect()):
            np.testing.assert_array_equal(a["x"], np.arange(4) + i)
            np.testing.assert_array_equal(b["y"], np.arange(4) * i)
        col = xs["x"]
        assert col.tier == "DRAM"
        np.testing.assert_array_equal(col.collect()[2], np.arange(4) + 2)
    finally:
        OrcaContext.train_data_store = old


def test_zip_rejects_mismatched_partitions():
    xs = HostXShards([{"x": np.arange(4)}] * 2)
    with pytest.raises(AssertionError):
        xs.zip(HostXShards([{"y": np.arange(4)}] * 3))


# -------------------------------------------- repartition / partition_by

@pytest.mark.parametrize("m", [1, 2, 5, 11])
def test_repartition_preserves_rows_dataframes(parallel_env, m):
    frames = _frames(4, rows=10)
    xs = HostXShards([f.copy() for f in frames])
    out = xs.repartition(m)
    assert out.num_partitions() == m
    got = pd.concat(out.collect(), ignore_index=True)
    want = pd.concat(frames, ignore_index=True)
    pd.testing.assert_frame_equal(got, want)


def test_repartition_np_dict_and_records(parallel_env):
    xs = HostXShards([{"x": np.arange(6) + 10 * i,
                       "y": np.ones(6) * i} for i in range(3)])
    out = xs.repartition(2).collect()
    np.testing.assert_array_equal(
        np.concatenate([s["x"] for s in out]),
        np.concatenate([np.arange(6) + 10 * i for i in range(3)]))
    rec = HostXShards([[1, 2, 3], [4, 5], [6]])
    assert [r for s in rec.repartition(2).collect() for r in s] == \
        [1, 2, 3, 4, 5, 6]


def test_partition_by_groups_and_preserves_rows(parallel_env):
    frames = _frames(5)
    xs = HostXShards([f.copy() for f in frames])
    out = xs.partition_by("user", 3)
    assert out.num_partitions() == 3
    shards = out.collect()
    seen = {}
    for i, s in enumerate(shards):
        for u in s["user"].unique():
            assert seen.setdefault(u, i) == i, "user split across shards"
    got = pd.concat(shards).sort_values(["user", "item"]).reset_index(
        drop=True)
    want = pd.concat(frames).sort_values(["user", "item"]).reset_index(
        drop=True)
    pd.testing.assert_frame_equal(got, want)


# ----------------------------------------------------- parquet + metadata

def test_write_parquet_modes(tmp_path):
    t3 = FeatureTable.from_pandas(
        pd.DataFrame({"a": np.arange(9)}), 3)
    p = str(tmp_path / "t")
    t3.write_parquet(p)
    assert len(glob.glob(os.path.join(p, "part-*.parquet"))) == 3
    # overwrite with fewer shards clears the stale third part file
    t2 = FeatureTable.from_pandas(pd.DataFrame({"a": np.arange(4)}), 2)
    t2.write_parquet(p, mode="overwrite")
    assert len(glob.glob(os.path.join(p, "part-*.parquet"))) == 2
    assert FeatureTable.read_parquet(p).size() == 4
    # append continues the numbering instead of clobbering part-00000
    t2.write_parquet(p, mode="append")
    assert len(glob.glob(os.path.join(p, "part-*.parquet"))) == 4
    assert FeatureTable.read_parquet(p).size() == 8
    with pytest.raises(ValueError):
        t2.write_parquet(p, mode="errorifexists")


def test_schema_and_col_names_need_only_first_shard(monkeypatch):
    t = FeatureTable.from_pandas(pd.concat(_frames(4), ignore_index=True), 4)
    monkeypatch.setattr(
        HostXShards, "collect",
        lambda self: pytest.fail("metadata op gathered the table"))
    assert t.col_names() == ["user", "item", "cat", "hist"]
    assert "user" in t.schema.index
    assert t.size() == 64


# ------------------------------------------------------------- prefetch

def test_streaming_prefetch_depth(parallel_env):
    from analytics_zoo_tpu.data.dataset import StreamingShardedDataset
    frames = [pd.DataFrame({"f": np.arange(8) + 8 * i,
                            "label": (np.arange(8) + i) % 2})
              for i in range(6)]

    def batches(depth):
        ds = StreamingShardedDataset(HostXShards([f.copy() for f in frames]),
                                     feature_cols=["f"], label_cols="label")
        assert ds.prefetch(depth) is ds
        assert ds.prefetch_depth == depth
        return [(np.asarray(x).copy(), np.asarray(y).copy())
                for x, y, _ in ds.iter_batches(batch_size=16, shuffle=False)]

    base = batches(1)
    deep = batches(3)
    assert len(base) == len(deep) == 3
    for (x1, y1), (x2, y2) in zip(base, deep):
        np.testing.assert_array_equal(x1, x2)
        np.testing.assert_array_equal(y1, y2)


def test_prefetch_env_default(monkeypatch):
    from analytics_zoo_tpu.data.dataset import StreamingShardedDataset
    monkeypatch.setenv("ZOO_DATA_PREFETCH", "4")
    ds = StreamingShardedDataset(
        HostXShards([pd.DataFrame({"f": [1.0], "label": [0]})]),
        feature_cols=["f"], label_cols="label")
    assert ds.prefetch_depth == 4
