"""MoE / expert-parallel tests."""

import numpy as np
import pytest

from analytics_zoo_tpu.ops.moe import MoEModule, ep_param_rules, top_k_gating
from analytics_zoo_tpu.parallel import mesh as mesh_lib


class TestGating:
    def test_dispatch_slots_are_exclusive(self):
        import jax
        rng = np.random.RandomState(0)
        logits = rng.randn(32, 4).astype(np.float32)
        dispatch, combine, aux = top_k_gating(
            jax.numpy.asarray(logits), k=2, capacity=16)
        d = np.asarray(dispatch)
        # every (expert, slot) holds at most one token
        assert d.sum(axis=0).max() <= 1.0 + 1e-6
        # every token dispatched to at most k experts
        assert d.sum(axis=(1, 2)).max() <= 2.0 + 1e-6
        assert np.isfinite(float(aux))

    def test_combine_weights_match_gates(self):
        import jax
        import jax.numpy as jnp
        rng = np.random.RandomState(1)
        logits = jnp.asarray(rng.randn(16, 4).astype(np.float32))
        probs = np.asarray(jax.nn.softmax(logits, -1))
        dispatch, combine, _ = top_k_gating(logits, k=1, capacity=16)
        c = np.asarray(combine)
        top1 = probs.argmax(-1)
        for n in range(16):
            got = c[n].sum()
            np.testing.assert_allclose(got, probs[n, top1[n]], rtol=1e-5)

    def test_capacity_drops_overflow(self):
        import jax.numpy as jnp
        # all tokens want expert 0; capacity 2 → only 2 dispatched
        logits = jnp.asarray(np.tile([10.0, 0.0], (8, 1)).astype(np.float32))
        dispatch, _, _ = top_k_gating(logits, k=1, capacity=2)
        assert float(np.asarray(dispatch)[:, 0].sum()) == 2.0


class TestMoEModule:
    def test_forward_shapes_and_grad(self):
        import jax
        m = MoEModule(n_experts=4, d_model=8, d_hidden=16, k=2)
        x = np.random.RandomState(0).randn(4, 6, 8).astype(np.float32)
        variables = m.init(jax.random.PRNGKey(0), x)
        out = m.apply(variables, x)
        assert out.shape == x.shape

        def loss(params):
            y = m.apply({"params": params}, x)
            return (y ** 2).mean()

        g = jax.grad(loss)(variables["params"])
        for leaf in jax.tree_util.tree_leaves(g):
            assert np.isfinite(np.asarray(leaf)).all()
        # gating and at least some experts receive signal
        assert np.abs(np.asarray(g["gate"])).max() > 0
        assert np.abs(np.asarray(g["w1"])).max() > 0

    def test_aux_loss_consumed_by_train_step(self, orca_ctx):
        """Regression: the sown load-balance loss used to be dropped — MoE
        trained with zero balancing. The reported loss must include the
        weighted aux term, and model_state must not accumulate it."""
        import flax.linen as nn
        from analytics_zoo_tpu.learn.estimator import Estimator

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x, train: bool = False):
                h = MoEModule(n_experts=4, d_model=8, d_hidden=16,
                              name="moe")(x, train=train)
                return nn.Dense(2)(h)

        rng = np.random.RandomState(1)
        x = rng.randn(64, 8).astype(np.float32)
        y = (x.sum(1) > 0).astype(np.int32)

        def run(aux_w):
            est = Estimator.from_flax(
                model=Net(), loss="sparse_categorical_crossentropy_logits",
                optimizer="sgd", sample_input=x[:2], seed=0,
                aux_loss_weight=aux_w)
            h = est.fit((x, y), epochs=2, batch_size=32, shuffle=False)
            return est, h

        est0, h0 = run(0.0)
        est1, h1 = run(1.0)
        # aux term is positive → the optimized objective differs
        assert h1["loss"][0] > h0["loss"][0]
        # aux_loss never leaks into persistent state (sow would grow it
        # every step otherwise)
        assert "aux_loss" not in est1._state["model_state"]
        assert "aux_loss" not in est1.adapter.model_state
        # with weight, gate gradients include the balance signal → gate
        # params diverge from the aux-free run
        g0 = np.asarray(est0._state["params"]["moe"]["gate"])
        g1 = np.asarray(est1._state["params"]["moe"]["gate"])
        assert not np.allclose(g0, g1)

    def test_ep_train_step_emits_all_to_all(self, orca_ctx):
        """The expert-sharded einsums must lower to cross-device collectives
        (all-to-all resharding tokens batch→expert layout) over the mesh."""
        import flax.linen as nn
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from analytics_zoo_tpu.learn.estimator import Estimator

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x, train: bool = False):
                h = MoEModule(n_experts=8, d_model=8, d_hidden=16,
                              name="moe")(x, train=train)
                return nn.Dense(2)(h)

        rng = np.random.RandomState(2)
        x = rng.randn(32, 8).astype(np.float32)
        y = (x.sum(1) > 0).astype(np.int32)
        est = Estimator.from_flax(
            model=Net(), loss="sparse_categorical_crossentropy_logits",
            optimizer="adam", sample_input=x[:2],
            strategy="dp2,ep4", param_rules=ep_param_rules())
        est._build_train_step()
        mesh = est._ensure_mesh()
        xs = jax.device_put(x, NamedSharding(mesh, P("data")))
        ys = jax.device_put(y, NamedSharding(mesh, P("data")))
        hlo = est._train_step.lower(est._state, xs, ys).compile().as_text()
        assert ("all-to-all" in hlo) or ("all-gather" in hlo), \
            "no cross-device collective for the expert dimension"

    def test_expert_parallel_training(self, orca_ctx):
        """End-to-end ep training: expert weights sharded over 'expert'."""
        import flax.linen as nn
        import jax
        from analytics_zoo_tpu.learn.estimator import Estimator

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x, train: bool = False):
                h = nn.Dense(8)(x)
                h = MoEModule(n_experts=4, d_model=8, d_hidden=16,
                              name="moe")(h, train=train)
                return nn.Dense(2)(h)

        rng = np.random.RandomState(0)
        x = rng.randn(64, 8).astype(np.float32)
        y = (x.sum(1) > 0).astype(np.int32)
        est = Estimator.from_flax(
            model=Net(), loss="sparse_categorical_crossentropy_logits",
            optimizer="adam", sample_input=x[:2],
            strategy="dp2,ep4", param_rules=ep_param_rules())
        h1 = est.fit((x, y), epochs=1, batch_size=16)
        h8 = est.fit((x, y), epochs=8, batch_size=16)
        assert h8["loss"][-1] < h1["loss"][0]
        w1 = est._state["params"]["moe"]["w1"]
        assert "expert" in str(w1.sharding.spec), w1.sharding.spec
