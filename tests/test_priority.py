"""SLO-aware continuous batching (ISSUE 10): priority lanes, deadline
scheduling, and admission control. Covers the schema stamp (priority +
deadline side channel, typed expired results), broker lane partitioning
on BOTH backends (lane-ordered XREADGROUP/XCLAIM, XSHED admission
flags), the client fast-fail on shed, the engine's weighted-deficit lane
schedule with starvation protection, max-wait partial-bucket dispatch,
deadline-slack dispatch, deadline-expiry accounting, the lane/lease
interplay (a dead replica's interactive entries reclaim before its
batch-lane entries — SIGKILL variant slow-marked for the scheduling
lane), the admission-control flip, the frontend's lane state + typed
429/504 answers, and the zero-silent-drops ledger."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from analytics_zoo_tpu.common import resilience, slo, telemetry
from analytics_zoo_tpu.serving import (
    Broker, ClusterServing, FrontEnd, InputQueue, OutputQueue,
)
from analytics_zoo_tpu.serving import schema
from analytics_zoo_tpu.serving.broker import (
    BrokerClient, ShedError, build_native_broker,
)
from analytics_zoo_tpu.serving.engine import _parse_lane_map


BACKENDS = ["python"] + (["native"] if build_native_broker() else [])

STREAM, GROUP = "serving_stream", "serving"
LANES = ",".join(schema.PRIORITIES)


@pytest.fixture(params=BACKENDS)
def broker(request):
    b = Broker.launch(backend=request.param)
    yield b
    b.stop()


@pytest.fixture(autouse=True)
def _fresh_slo_monitor():
    """Every test starts with a fresh lazily-created SLO monitor: burn
    windows baseline at the test's first tick instead of inheriting the
    multi-second latencies earlier tests fed the process-global
    histograms (a stalled-replica drill would otherwise trip admission
    control in whatever test runs after it)."""
    slo.set_monitor(None)
    yield
    slo.set_monitor(None)


def _counter(family, label=None):
    """Current value of a registry counter from the global snapshot (0.0
    when the family has never been touched)."""
    fam = telemetry.snapshot().get(family, {})
    if not isinstance(fam, dict):
        return float(fam or 0.0)
    if label is None:
        return float(next(iter(fam.values()), 0.0))
    return float(fam.get(label, 0.0))


# ------------------------------------------------------- schema side channel

class TestSchema:
    def test_validate_priority(self):
        assert schema.validate_priority(None) == schema.DEFAULT_PRIORITY
        for lane in schema.PRIORITIES:
            assert schema.validate_priority(lane) == lane
        with pytest.raises(ValueError):
            schema.validate_priority("urgent")

    def test_trace_stamp_carries_priority_and_deadline(self):
        trace = {"id": "r1", "t_pc": 1.0, "t_wall": 2.0, "s": 0,
                 "p": "interactive", "d": 250.0}
        payload = schema.encode_record(
            "r1", {"x": np.zeros(3, np.float32)}, None, trace=trace)
        uri, inputs, meta = schema.decode_record_meta(payload)
        assert uri == "r1" and set(inputs) == {"x"}
        assert meta["p"] == "interactive" and meta["d"] == 250.0

    def test_expired_result_is_typed(self):
        exp = schema.encode_error("deadline lapsed", None, code="expired")
        with pytest.raises(schema.DeadlineExpiredError):
            schema.decode_result(exp)
        # DeadlineExpiredError IS a ServingError — callers catching the
        # generic family still see expired records
        assert issubclass(schema.DeadlineExpiredError, schema.ServingError)
        plain = schema.encode_error("model exploded", None)
        with pytest.raises(schema.ServingError) as ei:
            schema.decode_result(plain)
        assert not isinstance(ei.value, schema.DeadlineExpiredError)


# ------------------------------------------- broker lanes, both backends

class TestBrokerLanes:
    def test_lane_ordered_read_and_per_lane_xlen(self, broker):
        c = broker.client()
        # arrival order is the REVERSE of priority order
        c.xadd("s", "YjA=", lane="batch")
        c.xadd("s", "YjE=", lane="batch")
        c.xadd("s", "ZDA=", lane="default")
        c.xadd("s", "aTA=", lane="interactive")
        assert c.xlen("s") == 4
        assert c.xlen("s", "interactive") == 1
        assert c.xlen("s", "default") == 1
        assert c.xlen("s", "batch") == 2
        got = c.xreadgroup("g", "c0", "s", 10, lanes=LANES)
        # 3-tuples, drained in lane-priority order, FIFO within a lane
        assert [(lane, payload) for _, lane, payload in got] == [
            ("interactive", "aTA="), ("default", "ZDA="),
            ("batch", "YjA="), ("batch", "YjE=")]

    def test_laneless_read_is_back_compatible(self, broker):
        c = broker.client()
        c.xadd("s", "YQ==", lane="batch")
        c.xadd("s", "Yg==")                    # legacy laneless enqueue
        got = c.xreadgroup("g", "c0", "s", 10)
        # legacy 2-tuple shape, arrival order across all lanes
        assert got == [(1, "YQ=="), (2, "Yg==")]

    def test_xshed_flag_rejects_xadd_on_that_lane_only(self, broker):
        c = broker.client()
        assert c.xshed("s") == []
        c.xshed_set("s", "batch", True)
        assert c.xshed("s") == ["batch"]
        with pytest.raises(ShedError):
            c.xadd("s", "YQ==", lane="batch")
        # other lanes keep flowing while batch sheds
        c.xadd("s", "Yg==", lane="interactive")
        c.xadd("s", "Yw==", lane="default")
        assert c.xlen("s") == 2
        c.xshed_set("s", "batch", False)
        assert c.xshed("s") == []
        c.xadd("s", "YQ==", lane="batch")
        assert c.xlen("s", "batch") == 1

    def test_xclaim_reclaims_interactive_before_batch(self, broker):
        """The lane/lease interplay at the broker layer: a dead
        consumer's pending entries re-deliver in lane-priority order, not
        arrival order."""
        c = broker.client()
        c.xadd("s", "YjA=", lane="batch")       # arrives FIRST
        c.xadd("s", "YjE=", lane="batch")
        c.xadd("s", "aTA=", lane="interactive")
        c.xadd("s", "aTE=", lane="interactive")
        assert len(c.xreadgroup("g", "dead", "s", 10, lanes=LANES)) == 4
        got = c.xclaim("s", "g", "live", 0, 10, lanes=LANES)
        assert [lane for _, lane, _ in got] == \
            ["interactive", "interactive", "batch", "batch"]
        # FIFO preserved within each lane
        assert [p for _, _, p in got] == ["aTA=", "aTE=", "YjA=", "YjE="]


# ------------------------------------------------------ client fast-fail

class TestClientShedFastFail:
    def test_enqueue_validation(self, broker):
        in_q = InputQueue(port=broker.port)
        try:
            with pytest.raises(ValueError):
                in_q.enqueue("v1", priority="urgent",
                             x=np.zeros(3, np.float32))
            for bad in (0, -5.0):
                with pytest.raises(ValueError):
                    in_q.enqueue("v2", deadline_ms=bad,
                                 x=np.zeros(3, np.float32))
            with pytest.raises(ValueError):
                in_q.enqueue("v3")              # no tensors at all
        finally:
            in_q.close()

    def test_shed_lane_raises_and_counts(self, broker):
        c = broker.client()
        c.xshed_set(STREAM, "batch", True)
        in_q = InputQueue(port=broker.port)
        label = f"stream={STREAM},priority=batch"
        shed0 = _counter("zoo_serving_shed_total", label)
        try:
            with pytest.raises(ShedError):
                in_q.enqueue("s1", priority="batch",
                             x=np.zeros(3, np.float32))
            # fast-fail is typed AND observable: the ledger counted it
            assert _counter("zoo_serving_shed_total", label) == shed0 + 1
            # interactive traffic keeps flowing through the same client
            in_q.enqueue("s2", priority="interactive",
                         x=np.zeros(3, np.float32))
            assert c.xlen(STREAM, "interactive") == 1
            with pytest.raises(ShedError):
                in_q.enqueue_batch(
                    [(f"sb{i}", {"x": np.zeros(3, np.float32)})
                     for i in range(2)], priority="batch")
            assert _counter("zoo_serving_shed_total", label) == shed0 + 2
        finally:
            in_q.close()


# ------------------------------------------------- engine lane scheduling

class _Track:
    """Doubler that records the distinct row markers of every batch it
    sees — the dispatch-order oracle for scheduling tests."""

    def __init__(self, sleep_s=0.0, first_sleep_s=0.0):
        self.sleep_s = sleep_s
        self.first_sleep_s = first_sleep_s
        self.calls = []

    def predict(self, x):
        x = np.asarray(x)
        first = self.first_sleep_s if not self.calls else 0.0
        self.calls.append(sorted(set(float(v) for v in x[:, 0])))
        if first or self.sleep_s:
            time.sleep(first or self.sleep_s)
        return x * 2.0


def _rec(marker):
    return {"x": np.full(3, float(marker), np.float32)}


def test_parse_lane_map():
    d = {lane: 0.0 for lane in schema.PRIORITIES}
    assert _parse_lane_map("", d) == d
    assert _parse_lane_map("250", d) == {k: 250.0 for k in d}
    out = _parse_lane_map("interactive=50, batch=4000", d)
    assert out["interactive"] == 50.0 and out["batch"] == 4000.0
    assert out["default"] == 0.0


def test_weighted_deficit_lane_order():
    with Broker.launch(backend="python") as b:
        eng = ClusterServing(_Track(), b.port, batch_size=4,
                             max_batch_size=4, warmup=False)
        # all credits zero: ties resolve to static priority order
        assert eng._lane_order() == LANES
        # a lane that consumed far more than its weighted share drops to
        # the back of the read order until the others catch up
        eng._lane_credit["interactive"] += 100.0
        assert eng._lane_order().split(",")[-1] == "interactive"
        eng._lane_credit["default"] += 1000.0
        order = eng._lane_order().split(",")
        assert order[0] == "batch" and order[-1] == "default"


def test_starvation_protection_batch_drains_under_interactive_load():
    """Weighted-deficit scheduling: with a deep interactive backlog AND
    queued batch work, the batch lane is served within the first few
    dispatches instead of waiting for the interactive queue to drain
    (strict-priority starvation), and every record still answers."""
    n_int, n_batch = 24, 4
    model = _Track(sleep_s=0.02)
    with Broker.launch(backend="python") as b:
        in_q, out_q = InputQueue(port=b.port), OutputQueue(port=b.port)
        uris = list(in_q.enqueue_batch(
            (f"si{i}", _rec(1 + i)) for i in range(n_int)))
        uris += in_q.enqueue_batch(
            ((f"sb{i}", _rec(100 + i)) for i in range(n_batch)),
            priority="batch")
        with ClusterServing(model, b.port, batch_size=n_batch,
                            max_batch_size=n_batch, pipeline_window=1,
                            warmup=False):
            res = out_q.query_many(uris, timeout=30.0)
        assert all(v is not None for v in res.values())
        batch_markers = {float(100 + i) for i in range(n_batch)}
        hit = [i for i, call in enumerate(model.calls)
               if batch_markers & set(call)]
        # credits: dispatch 0 drains 4 interactive (ratio 1 at weight 4),
        # so the batch lane (ratio 0) leads the very next read — well
        # before the 6 remaining interactive dispatches
        assert hit and hit[0] <= 2, \
            f"batch lane starved: served at dispatches {hit} " \
            f"of {len(model.calls)}"


def test_max_wait_dispatches_partial_bucket(monkeypatch):
    """A partial assembly bucket must dispatch once the oldest record
    has waited out its lane's max-wait — NOT hold out for a full batch
    that may never arrive."""
    monkeypatch.setenv("ZOO_SERVING_MAX_WAIT_MS", "150")
    model = _Track()
    with Broker.launch(backend="python") as b:
        with ClusterServing(model, b.port, batch_size=8, max_batch_size=8,
                            block_ms=20, warmup=False):
            in_q, out_q = InputQueue(port=b.port), OutputQueue(port=b.port)
            t0 = time.monotonic()
            uris = list(in_q.enqueue_batch(
                (f"mw{i}", _rec(1 + i)) for i in range(3)))
            res = out_q.query_many(uris, timeout=30.0)
            dt = time.monotonic() - t0
        assert all(v is not None for v in res.values())
        # one padded dispatch carrying all three records, released by the
        # max-wait trigger: after the wait window, before forever
        assert len(model.calls) == 1, model.calls
        assert set(model.calls[0]) >= {1.0, 2.0, 3.0}
        assert 0.10 <= dt < 5.0, f"dispatch at {dt:.3f}s"


def test_deadline_slack_preempts_max_wait(monkeypatch):
    """A record whose deadline lands inside the max-wait window
    dispatches on its deadline slack — max-wait must never hold a record
    past the moment its result would go stale."""
    monkeypatch.setenv("ZOO_SERVING_MAX_WAIT_MS", "5000")
    model = _Track()
    with Broker.launch(backend="python") as b:
        with ClusterServing(model, b.port, batch_size=8, max_batch_size=8,
                            block_ms=20, warmup=False) as eng:
            in_q, out_q = InputQueue(port=b.port), OutputQueue(port=b.port)
            t0 = time.monotonic()
            uri = in_q.enqueue("ds0", deadline_ms=300.0, **_rec(7))
            res = out_q.query(uri, timeout=30.0)
            dt = time.monotonic() - t0
            assert res is not None          # served, NOT expired
            assert eng.metrics()["records_expired"] == 0
        # released near the 300ms deadline, nowhere near the 5s max-wait
        assert dt < 3.0, f"held {dt:.3f}s despite a 300ms deadline"


@pytest.mark.parametrize("backend", BACKENDS)
def test_deadline_expiry_accounting(backend):
    """An expired record terminates as an EXPLICIT typed result on both
    broker backends: the client's query raises DeadlineExpiredError, the
    per-lane expired counter ticks, the entry is acked (no redelivery
    loop), and it never counts as a record error."""
    b = Broker.launch(backend=backend)
    try:
        in_q, out_q = InputQueue(port=b.port), OutputQueue(port=b.port)
        label = f"stream={STREAM},priority=interactive"
        exp0 = _counter("zoo_serving_expired_total", label)
        err0 = _counter("zoo_serving_record_errors_total",
                        f"stream={STREAM}")
        # enqueue BEFORE the engine exists so the deadline lapses in queue
        dead = in_q.enqueue("exp0", priority="interactive",
                            deadline_ms=30.0, **_rec(1))
        live = in_q.enqueue("ok0", **_rec(2))
        time.sleep(0.1)
        with ClusterServing(_Track(), b.port, batch_size=2,
                            max_batch_size=2, warmup=False) as eng:
            np.testing.assert_allclose(
                out_q.query(live, timeout=30.0), np.full(3, 4.0))
            with pytest.raises(schema.DeadlineExpiredError):
                out_q.query(dead, timeout=30.0)
            assert eng.metrics()["records_expired"] == 1
        assert _counter("zoo_serving_expired_total", label) == exp0 + 1
        # expired ≠ error: availability SLOs must not burn on deadlines
        assert _counter("zoo_serving_record_errors_total",
                        f"stream={STREAM}") == err0
        c = b.client()
        assert c.xpending(STREAM, GROUP) == 0   # acked, not orphaned
    finally:
        b.stop()


# ---------------------------------------------- admission control (engine)

class _FakeMonitor:
    """Stands in for the SLO monitor: `burning` answers a test-set flag
    so the admission tick's broker side effects test deterministically."""

    def __init__(self):
        self.burn = False

    def tick_if_stale(self):
        pass

    def burning(self, name):
        return self.burn

    def stop(self):
        pass


def test_admission_tick_flips_broker_shed_flag():
    fake = _FakeMonitor()
    slo.set_monitor(fake)
    try:
        with Broker.launch(backend="python") as b:
            eng = ClusterServing(_Track(), b.port, batch_size=4,
                                 max_batch_size=4, warmup=False)
            c = b.client()
            eng._admission_tick(c)
            assert not eng.admission_shedding and c.xshed(STREAM) == []
            # burn starts: the BATCH lane sheds at the broker...
            fake.burn = True
            eng._last_admission = 0.0
            eng._admission_tick(c)
            assert eng.admission_shedding
            assert c.xshed(STREAM) == [eng.ADMISSION_LANE] == ["batch"]
            with pytest.raises(ShedError):
                c.xadd(STREAM, "YQ==", lane="batch")
            # ...while interactive admission is untouched
            c.xadd(STREAM, "Yg==", lane="interactive")
            assert _counter("zoo_serving_admission_state",
                            f"stream={STREAM},priority=batch") == 1.0
            # burn ends: the flag clears and batch flows again
            fake.burn = False
            eng._last_admission = 0.0
            eng._admission_tick(c)
            assert not eng.admission_shedding and c.xshed(STREAM) == []
            c.xadd(STREAM, "YQ==", lane="batch")
            assert _counter("zoo_serving_admission_state",
                            f"stream={STREAM},priority=batch") == 0.0
            # lane depth gauges refreshed from the broker on each tick
            assert _counter("zoo_serving_lane_depth",
                            f"stream={STREAM},priority=interactive") == 1.0
    finally:
        slo.set_monitor(None)


# --------------------------------------------------- lane/lease interplay

def test_lease_reclaim_serves_interactive_before_batch():
    """End-to-end lane/lease interplay: replica A takes a mixed
    interactive+batch delivery and stalls past its lease; replica B's
    reclaim sweep re-delivers lane-ordered, so A's interactive records
    are SERVED (not merely claimed) before its batch records."""
    n = 4                                       # per lane
    int_markers = {float(1 + i) for i in range(n)}
    batch_markers = {float(100 + i) for i in range(n)}
    with Broker.launch(backend="python") as b:
        in_q, out_q = InputQueue(port=b.port), OutputQueue(port=b.port)
        # batch-lane records arrive FIRST: arrival order must not win
        uris = list(in_q.enqueue_batch(
            ((f"lb{i}", _rec(100 + i)) for i in range(n)),
            priority="batch"))
        uris += in_q.enqueue_batch(
            ((f"li{i}", _rec(1 + i)) for i in range(n)),
            priority="interactive")
        eng_a = ClusterServing(_Track(first_sleep_s=1.5), b.port,
                               batch_size=2 * n, max_batch_size=2 * n,
                               consumer="repA", claim_min_idle_ms=300,
                               reclaim_interval_s=30.0, warmup=False)
        eng_a.start()
        try:
            c = b.client()
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if c.xpending_detail(STREAM, GROUP).get("repA") == 2 * n:
                    break
                time.sleep(0.02)
            assert c.xpending_detail(STREAM, GROUP) == {"repA": 2 * n}
            model_b = _Track()
            eng_b = ClusterServing(model_b, b.port, batch_size=2,
                                   max_batch_size=2, consumer="repB",
                                   claim_min_idle_ms=300,
                                   reclaim_interval_s=0.1, warmup=False)
            eng_b.start()
            try:
                res = out_q.query_many(uris, timeout=30.0)
                assert all(v is not None for v in res.values())
                # B's dispatch sequence: every interactive marker strictly
                # precedes every batch marker
                order = [set(call) for call in model_b.calls]
                last_int = max(i for i, s in enumerate(order)
                               if s & int_markers)
                first_batch = min(i for i, s in enumerate(order)
                                  if s & batch_markers)
                assert last_int < first_batch, \
                    f"batch served before interactive drained: {order}"
            finally:
                eng_b.stop()
        finally:
            eng_a.stop()


@pytest.mark.slow
def test_sigkill_reclaim_lane_order_drill():
    """Acceptance (ISSUE 10): SIGKILL a replica holding a mixed
    interactive+batch in-flight window (kill@replica fault seam). The
    survivor's lease reclaim must ANSWER the victim's interactive
    records before its batch-lane records, with zero loss."""
    n = 4                                       # per lane
    env = {"ZOO_SERVING_LEASE_MS": "300", "ZOO_SERVING_RECLAIM_S": "0.25",
           "ZOO_FLEET_HEARTBEAT_S": "0.25", "ZOO_FLEET_STALE_S": "1.0"}
    with resilience.fault_drill("kill@replica:1", cpu_fallback=False), \
            Broker.launch(backend="python") as broker:
        in_q = InputQueue(port=broker.port)
        int_uris = list(in_q.enqueue_batch(
            ((f"ki{i}", _rec(1 + i)) for i in range(n)),
            priority="interactive"))
        batch_uris = list(in_q.enqueue_batch(
            ((f"kb{i}", _rec(100 + i)) for i in range(n)),
            priority="batch"))
        victim = resilience.ServingReplicaProc(
            broker.port, batch_size=2 * n, predict_sleep_ms=60_000.0,
            env_extra=env)
        box = {}
        try:
            c = broker.client()
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline and \
                    c.xpending(STREAM, GROUP) < 2 * n:
                time.sleep(0.05)
            assert c.xpending(STREAM, GROUP) == 2 * n
            assert resilience.maybe_kill_replica(victim)
            assert not victim.alive
            # the survivor comes up AFTER the kill: everything it serves
            # arrived through the lane-ordered lease reclaim. Spawned off
            # a thread — its constructor blocks on subprocess imports,
            # and the poll loop must watch the drain LIVE to time the
            # per-lane result arrivals
            spawn = threading.Thread(target=lambda: box.update(
                proc=resilience.ServingReplicaProc(
                    broker.port, batch_size=2, predict_sleep_ms=400.0,
                    env_extra=env)))
            spawn.start()
            arrived = {}
            all_uris = int_uris + batch_uris
            deadline = time.monotonic() + 90.0
            while len(arrived) < 2 * n and time.monotonic() < deadline:
                vals = c.pipeline(("HGET", "result", u) for u in all_uris)
                now = time.monotonic()
                for u, v in zip(all_uris, vals):
                    if v is not None and u not in arrived:
                        arrived[u] = now
                time.sleep(0.005)
            spawn.join(timeout=60.0)
            missing = [u for u in all_uris if u not in arrived]
            assert not missing, f"{len(missing)} records lost after kill"
            # the engine pipelines dispatches, so mid-sequence flushes
            # can tie — but the FIRST record served after the kill must
            # be interactive and the LAST must be batch (the strict
            # per-dispatch order is asserted by the in-process twin,
            # test_lease_reclaim_serves_interactive_before_batch)
            first_int = min(arrived[u] for u in int_uris)
            first_batch = min(arrived[u] for u in batch_uris)
            assert first_int < first_batch, \
                "a batch-lane result was served before any interactive " \
                f"one ({first_int:.3f} vs {first_batch:.3f})"
            assert max(arrived[u] for u in int_uris) <= \
                max(arrived[u] for u in batch_uris)
        finally:
            if box.get("proc") is not None:
                box["proc"].stop()
            victim.stop()


# ---------------------------------------------------------- HTTP frontend

def _get_json(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read().decode())


def _post_predict(port, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read().decode())


def test_frontend_lane_state_and_typed_answers():
    with Broker.launch(backend="python") as b:
        model = _Track(sleep_s=0.02)
        with ClusterServing(model, b.port, batch_size=4, max_batch_size=4,
                            warmup=False) as eng:
            fe = FrontEnd(b.port, engine=eng)
            fe.start()
            c = b.client()
            try:
                # healthy predict rides a lane end to end
                out = _post_predict(fe.port, {
                    "uri": "fe0", "priority": "interactive",
                    "deadline_ms": 30_000.0,
                    "inputs": {"x": schema.encode_tensor(
                        np.full(3, 2.0, np.float32))}})
                assert out["uri"] == "fe0"
                # /healthz and /slo expose the per-lane scheduling state
                hz = _get_json(f"http://127.0.0.1:{fe.port}/healthz")
                assert set(hz["lanes"]) == set(schema.PRIORITIES)
                assert hz["shed_lanes"] == []
                assert hz["admission"]["shedding"] is False
                rep = _get_json(f"http://127.0.0.1:{fe.port}/slo")
                assert set(rep["lanes"]) == set(schema.PRIORITIES)
                assert "admission" in rep
                # a shed lane answers 429 code=shed, instantly
                c.xshed_set(STREAM, "batch", True)
                with pytest.raises(urllib.error.HTTPError) as ei:
                    _post_predict(fe.port, {
                        "priority": "batch",
                        "inputs": {"x": schema.encode_tensor(
                            np.full(3, 1.0, np.float32))}})
                assert ei.value.code == 429
                assert json.loads(ei.value.read())["code"] == "shed"
                hz = _get_json(f"http://127.0.0.1:{fe.port}/healthz")
                assert hz["shed_lanes"] == ["batch"]
                c.xshed_set(STREAM, "batch", False)
                # an expired deadline answers 504 code=expired — occupy
                # the engine so a 1ms deadline deterministically lapses
                in_q = InputQueue(port=b.port)
                in_q.enqueue_batch(
                    (f"fill{i}", _rec(50 + i)) for i in range(8))
                with pytest.raises(urllib.error.HTTPError) as ei:
                    _post_predict(fe.port, {
                        "uri": "fe1", "deadline_ms": 1.0,
                        "inputs": {"x": schema.encode_tensor(
                            np.full(3, 3.0, np.float32))}})
                assert ei.value.code == 504
                body = json.loads(ei.value.read())
                assert body["code"] == "expired" and body["uri"] == "fe1"
                hz = _get_json(f"http://127.0.0.1:{fe.port}/healthz")
                assert hz["admission"]["records_expired"] >= 1
            finally:
                fe.stop()


# ------------------------------------------------- zero-silent-drops ledger

def test_every_enqueue_terminates_result_expired_or_shed():
    """The zero-silent-drops contract (ISSUE 10 acceptance): every
    enqueue attempt lands in exactly ONE terminal state — a result, a
    typed expired result, or a typed shed rejection — and each state is
    observable on a counter."""
    n_good, n_exp, n_shed = 4, 2, 2
    shed_label = f"stream={STREAM},priority=batch"
    exp_label = f"stream={STREAM},priority=default"
    with Broker.launch(backend="python") as b:
        in_q, out_q = InputQueue(port=b.port), OutputQueue(port=b.port)
        shed0 = _counter("zoo_serving_shed_total", shed_label)
        exp0 = _counter("zoo_serving_expired_total", exp_label)
        good = list(in_q.enqueue_batch(
            (f"zg{i}", _rec(1 + i)) for i in range(n_good)))
        expired = [in_q.enqueue(f"ze{i}", deadline_ms=25.0, **_rec(10 + i))
                   for i in range(n_exp)]
        time.sleep(0.1)                 # deadlines lapse in-queue
        c = b.client()
        c.xshed_set(STREAM, "batch", True)
        for i in range(n_shed):
            with pytest.raises(ShedError):
                in_q.enqueue(f"zs{i}", priority="batch", **_rec(20 + i))
        c.xshed_set(STREAM, "batch", False)
        with ClusterServing(_Track(), b.port, batch_size=4,
                            max_batch_size=4, warmup=False) as eng:
            res = out_q.query_many(good, timeout=30.0)
            assert all(v is not None for v in res.values())
            for u in expired:
                with pytest.raises(schema.DeadlineExpiredError):
                    out_q.query(u, timeout=30.0)
            m = eng.metrics()
            # accepted records partition exactly into served + expired
            assert m["records_out"] == n_good
            assert m["records_expired"] == n_exp
        assert _counter("zoo_serving_shed_total", shed_label) == \
            shed0 + n_shed
        assert _counter("zoo_serving_expired_total", exp_label) == \
            exp0 + n_exp
        assert c.xpending(STREAM, GROUP) == 0
        # attempts = terminal outcomes, nothing vanished
        assert n_good + n_exp + n_shed == \
            len(good) + len(expired) + n_shed
