"""Unified telemetry layer tests (ISSUE 2 tentpole): metrics registry,
Prometheus exposition, span tracing, JIT recompile accounting, transfer
byte accounting, StageTimer re-backing, and the buffered SummaryWriter."""

import os
import re
import threading

import numpy as np
import pytest

from analytics_zoo_tpu import observability as obs
from analytics_zoo_tpu.common import summary, telemetry
from analytics_zoo_tpu.common.pipeline_io import StageTimer


@pytest.fixture(autouse=True)
def fresh_telemetry():
    telemetry.reset_for_tests()
    yield
    telemetry.reset_for_tests()


# One sample line: name{labels} value
_PROM_LINE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})? (NaN|[+-]Inf|-?[0-9][0-9.e+-]*)$")


def parse_prometheus(text):
    """Strict parse of the 0.0.4 text format → (types, samples). Asserts
    every line is a HELP/TYPE comment or a well-formed sample."""
    types, samples = {}, {}
    assert text.endswith("\n")
    for line in text.rstrip("\n").split("\n"):
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ")
            assert kind in ("counter", "gauge", "histogram")
            types[name] = kind
        elif line.startswith("#"):
            assert line.startswith("# HELP "), line
        else:
            m = _PROM_LINE.match(line)
            assert m, f"malformed exposition line: {line!r}"
            name, braced, _, val = m.groups()
            samples[(name, braced or "")] = float(val)
    return types, samples


class TestRegistry:
    def test_counter_and_gauge_basics(self):
        reg = telemetry.MetricsRegistry()
        c = reg.counter("zoo_t_total", "help", ("k",))
        c.labels("a").inc()
        c.labels("a").inc(2.5)
        c.labels(k="b").inc()
        assert c.labels("a").value == 3.5
        assert c.labels("b").value == 1.0  # kw and positional hit same child
        with pytest.raises(ValueError, match="only go up"):
            c.labels("a").inc(-1)
        g = reg.gauge("zoo_t_gauge")
        g.set(4)
        g.inc()
        g.dec(2)
        assert g.value == 3.0

    def test_get_or_create_is_idempotent_but_clashes_raise(self):
        reg = telemetry.MetricsRegistry()
        c1 = reg.counter("zoo_x_total", "h", ("a",))
        assert reg.counter("zoo_x_total", labelnames=("a",)) is c1
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("zoo_x_total", labelnames=("a",))  # kind clash
        with pytest.raises(ValueError, match="already registered"):
            reg.counter("zoo_x_total", labelnames=("b",))  # label clash
        with pytest.raises(ValueError, match="bad metric name"):
            reg.counter("0starts_with_digit")
        with pytest.raises(ValueError, match="bad metric name"):
            reg.counter("has-dash")

    def test_histogram_counts_sum_and_quantiles(self):
        reg = telemetry.MetricsRegistry()
        h = reg.histogram("zoo_h_seconds", buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.05, 0.5, 5.0):
            h.observe(v)
        child = h.labels()
        assert child.count == 4
        assert child.sum == pytest.approx(5.555)
        counts, total, _, _ = child._state()
        assert counts == [1, 1, 1, 1] and total == 4
        assert h.quantile(0.5) in (0.05, 0.5)
        assert h.quantile(0.99) == 5.0

    def test_histogram_reservoir_is_bounded(self):
        reg = telemetry.MetricsRegistry()
        h = reg.histogram("zoo_big_seconds", buckets=(1.0,))
        for i in range(5 * telemetry.RESERVOIR_SIZE):
            h.observe(i / 1000.0)
        child = h.labels()
        _, total, _, res = child._state()
        assert total == 5 * telemetry.RESERVOIR_SIZE
        assert len(res) == telemetry.RESERVOIR_SIZE  # bounded forever
        q = h.quantile(0.5)
        assert 0.0 <= q <= 5.12  # sane value drawn from the stream

    def test_snapshot_shapes(self):
        reg = telemetry.MetricsRegistry()
        reg.counter("zoo_c_total", "h", ("s",)).labels("a").inc(3)
        reg.gauge("zoo_g").set(7)
        reg.histogram("zoo_h_seconds").observe(0.2)
        snap = reg.snapshot()
        assert snap["zoo_c_total"] == {"s=a": 3.0}
        assert snap["zoo_g"] == 7.0  # unlabelled family collapses to value
        h = snap["zoo_h_seconds"]
        assert h["count"] == 1 and h["sum"] == pytest.approx(0.2)
        assert h["p50"] == pytest.approx(0.2)
        # the snapshot carries the bucket boundaries + per-bucket counts
        # (ISSUE 6: the histogram JSON is mergeable, not just a summary)
        assert h["le"] == list(telemetry.DEFAULT_BUCKETS)
        assert len(h["bucket_counts"]) == len(h["le"]) + 1  # +Inf bucket
        assert sum(h["bucket_counts"]) == h["count"]
        assert h["reservoir"] == [pytest.approx(0.2)]


class TestPrometheusExposition:
    def test_golden_text(self):
        reg = telemetry.MetricsRegistry()
        reg.counter("zoo_a_total", "A counter", ("s",)).labels(
            'x"y\n').inc(2)
        reg.gauge("zoo_g", "G").set(1.5)
        h = reg.histogram("zoo_h_seconds", "H", buckets=(0.3, 1.0))
        for v in (0.25, 0.5, 4.0):
            h.observe(v)
        want = (
            "# HELP zoo_a_total A counter\n"
            "# TYPE zoo_a_total counter\n"
            'zoo_a_total{s="x\\"y\\n"} 2\n'
            "# HELP zoo_g G\n"
            "# TYPE zoo_g gauge\n"
            "zoo_g 1.5\n"
            "# HELP zoo_h_seconds H\n"
            "# TYPE zoo_h_seconds histogram\n"
            'zoo_h_seconds_bucket{le="0.3"} 1\n'
            'zoo_h_seconds_bucket{le="1"} 2\n'
            'zoo_h_seconds_bucket{le="+Inf"} 3\n'
            "zoo_h_seconds_sum 4.75\n"
            "zoo_h_seconds_count 3\n")
        assert reg.prometheus_text() == want

    def test_exposition_parses_and_buckets_are_cumulative(self):
        reg = telemetry.MetricsRegistry()
        h = reg.histogram("zoo_lat_seconds", "latency", ("stage",))
        for i in range(200):
            h.labels("fetch").observe(i / 100.0)
        reg.counter("zoo_n_total", "n").inc(5)
        types, samples = parse_prometheus(reg.prometheus_text())
        assert types["zoo_lat_seconds"] == "histogram"
        assert types["zoo_n_total"] == "counter"
        buckets = sorted(
            ((float(re.search(r'le="([^"]+)"', lbl).group(1)
                    .replace("+Inf", "inf")), v)
             for (name, lbl), v in samples.items()
             if name == "zoo_lat_seconds_bucket"))
        cum = [v for _, v in buckets]
        assert cum == sorted(cum), "bucket counts must be cumulative"
        assert buckets[-1][0] == float("inf")
        assert buckets[-1][1] == 200  # +Inf bucket == _count
        assert samples[("zoo_lat_seconds_count",
                        '{stage="fetch"}')] == 200
        assert samples[("zoo_n_total", "")] == 5


class TestTracer:
    def test_record_get_and_lru_bound(self):
        tr = telemetry.Tracer(capacity=3)
        for i in range(5):
            tr.record(f"t{i}", "work", 0.0, 1.0)
        assert tr.get("t0") == [] and tr.get("t1") == []
        assert [s.name for s in tr.get("t4")] == ["work"]
        assert tr.get("t4")[0].duration == 1.0
        tr.clear()
        assert tr.get("t4") == []

    def test_span_contextmanager_propagates_trace_and_parent(self):
        tr = telemetry.Tracer()
        with tr.span("root", "tid"):
            assert tr.current_trace_id() == "tid"
            with tr.span("child"):       # inherits tid, parent=root
                pass
        spans = {s.name: s for s in tr.get("tid")}
        assert spans["root"].parent is None
        assert spans["child"].parent == "root"
        assert (spans["root"].start <= spans["child"].start
                <= spans["child"].end <= spans["root"].end)
        with pytest.raises(ValueError, match="needs an enclosing span"):
            with tr.span("orphan"):
                pass

    def test_sampling_is_deterministic_and_exact(self):
        tr = telemetry.Tracer(sample=0.25)
        # the accumulator starts with one sample of credit (the first
        # decision fires), then settles to exactly rate * calls
        hits = sum(tr.should_sample() for _ in range(100))
        assert hits == 26
        hits = sum(tr.should_sample() for _ in range(100))
        assert hits == 25
        tr.set_sampling(0.0)
        assert not any(tr.should_sample() for _ in range(20))
        tr.set_sampling(1.0)
        assert all(tr.should_sample() for _ in range(20))

    def test_global_sampling_helper(self):
        telemetry.set_trace_sampling(0.0)
        assert not telemetry.get_tracer().should_sample()
        telemetry.set_trace_sampling(1.0)
        assert telemetry.get_tracer().should_sample()


class TestJitInstrumentation:
    def test_recompile_counter_increments_then_stays_flat(self):
        """Acceptance: the counter increments on an avals-signature change
        and stays FLAT at steady state."""
        import jax.numpy as jnp
        reg = telemetry.MetricsRegistry()
        jf = telemetry.instrument_jit(lambda x: x * 2, name="f",
                                      registry=reg)
        x8 = jnp.ones(8, jnp.float32)
        for _ in range(5):
            jf(x8)
        assert jf.cache_misses == 1           # one compile
        jf(jnp.ones(16, jnp.float32))         # shape change → recompile
        assert jf.cache_misses == 2
        jf(jnp.ones(8, jnp.int32))            # dtype change → recompile
        assert jf.cache_misses == 3
        for _ in range(10):                   # steady state: flat
            jf(x8)
        assert jf.cache_misses == 3
        calls = reg.counter("zoo_jit_calls_total",
                            labelnames=("fn",)).labels("f").value
        misses = reg.counter("zoo_jit_cache_misses_total",
                             labelnames=("fn",)).labels("f").value
        assert calls == 17 and misses == 3

    def test_python_leaf_value_change_is_a_miss(self):
        import jax.numpy as jnp
        reg = telemetry.MetricsRegistry()
        jf = telemetry.instrument_jit(lambda x, n: x * n, name="g",
                                      registry=reg, static_argnums=1)
        x = jnp.ones(4)
        jf(x, 2)
        jf(x, 2)
        assert jf.cache_misses == 1
        jf(x, 3)  # static value change recompiles for real — counted
        assert jf.cache_misses == 2

    def test_decorator_forms_and_delegation(self):
        import jax.numpy as jnp
        reg = telemetry.MetricsRegistry()

        @telemetry.instrument_jit
        def double(x):
            return x + x

        assert float(double(jnp.float32(2.0))) == 4.0
        jf = telemetry.instrument_jit(name="h", registry=reg)(
            lambda x: x - 1)
        x = jnp.ones(3)
        np.testing.assert_allclose(np.asarray(jf(x)), 0.0)
        # delegation: .lower() reaches the underlying jitted callable
        assert jf.lower(x).compile() is not None


class TestDeviceAccounting:
    def test_transfer_byte_accounting(self):
        x = np.ones((4, 4), np.float32)  # 64 bytes
        dev = telemetry.traced_device_put(x)
        back = telemetry.traced_device_get(dev)
        np.testing.assert_array_equal(back, x)
        snap = telemetry.snapshot()
        assert snap["zoo_device_transfer_bytes_total"]["direction=h2d"] == 64
        assert snap["zoo_device_transfer_bytes_total"]["direction=d2h"] == 64
        assert snap["zoo_device_last_transfer_bytes"]["direction=h2d"] == 64
        # pytrees are billed at the sum of their leaves
        telemetry.traced_device_put({"a": x, "b": np.ones(2, np.float64)})
        snap = telemetry.snapshot()
        assert snap["zoo_device_transfer_bytes_total"]["direction=h2d"] \
            == 64 + 64 + 16
        assert snap["zoo_device_last_transfer_bytes"]["direction=h2d"] == 80

    def test_timed_block_until_ready_records_site(self):
        import jax.numpy as jnp
        out = telemetry.timed_block_until_ready(jnp.ones(8) * 3,
                                                site="test_site")
        np.testing.assert_allclose(np.asarray(out), 3.0)
        snap = telemetry.snapshot()
        entry = snap["zoo_device_block_seconds"]["site=test_site"]
        assert entry["count"] == 1 and entry["sum"] >= 0.0


class TestStageTimer:
    def test_forwards_to_registry_and_keeps_summary_api(self):
        t = StageTimer()
        t.record("fetch", 0.01)
        t.record("fetch", 0.03)
        t.record_value("batch_size", 16)
        # legacy dict API unchanged
        s = t.summary()
        assert s["fetch"]["count"] == 2
        assert s["fetch"]["mean_ms"] == pytest.approx(20.0)
        assert s["batch_size"]["mean"] == 16.0
        # and the same observations landed in the process registry
        snap = telemetry.snapshot()
        assert snap["zoo_stage_seconds"]["stage=fetch"]["count"] == 2
        assert snap["zoo_stage_seconds"]["stage=fetch"]["sum"] \
            == pytest.approx(0.04)
        assert snap["zoo_stage_value"]["stage=batch_size"] == 16.0

    def test_observability_helpers_surface_registry(self):
        t = StageTimer()
        t.record("inference", 0.2)
        assert "zoo_stage_seconds" in obs.scrape()
        assert obs.metrics()["zoo_stage_seconds"]["stage=inference"][
            "count"] == 1
        obs.get_tracer().record("u1", "serve", 0.0, 0.5)
        assert [s.name for s in obs.trace("u1")] == ["serve"]
        assert "serve" in obs.trace_table("u1")
        assert "no trace" in obs.trace_table("nonexistent")


class TestSummaryWriter:
    def test_writes_are_buffered_until_flush(self, tmp_path):
        w = summary.SummaryWriter(str(tmp_path), flush_bytes=1 << 30,
                                  flush_every=1 << 30)
        size0 = os.path.getsize(w._path)  # header record only
        for i in range(50):
            w.add_scalar("Loss", float(i), i)
        assert os.path.getsize(w._path) == size0  # nothing hit disk yet
        assert "Loss" not in summary.read_scalars(w._path)
        w.flush()
        scalars = summary.read_scalars(w._path)
        assert [s for s, _ in scalars["Loss"]] == list(range(50))
        assert w.get_scalar("Loss")[0] == (0, 0.0)
        w.close()

    def test_event_count_threshold_forces_flush(self, tmp_path):
        w = summary.SummaryWriter(str(tmp_path), flush_bytes=1 << 30,
                                  flush_every=8)
        for i in range(7):
            w.add_scalar("x", float(i), i)
        assert "x" not in summary.read_scalars(w._path)
        w.add_scalar("x", 7.0, 7)  # 8th event trips the threshold
        assert len(summary.read_scalars(w._path)["x"]) == 8
        w.close()

    def test_byte_threshold_forces_flush(self, tmp_path):
        w = summary.SummaryWriter(str(tmp_path), flush_bytes=1,
                                  flush_every=1 << 30)
        for i in range(3):
            w.add_scalar("y", float(i), i)
        assert len(summary.read_scalars(w._path)["y"]) == 3
        w.close()

    def test_close_is_idempotent_and_terminal(self, tmp_path):
        w = summary.SummaryWriter(str(tmp_path))
        w.add_scalar("z", 1.0, 0)
        w.close()
        w.close()  # second close: no ValueError on a closed file
        w.flush()  # flush after close: silently ignored
        w.add_scalar("z", 2.0, 1)  # dropped, not crashed
        scalars = summary.read_scalars(w._path)
        assert scalars["z"] == [(0, 1.0)]
        assert w.get_scalar("z") == [(0, 1.0)]  # mirror not polluted either

    def test_concurrent_add_scalar_is_safe(self, tmp_path):
        """4 threads interleave adds through the flush threshold; the
        events file must stay well-framed and lose nothing."""
        w = summary.SummaryWriter(str(tmp_path), flush_every=7)
        n_threads, n_each = 4, 200
        errs = []

        def work(t):
            try:
                for i in range(n_each):
                    w.add_scalar(f"tag{t}", float(i), i)
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=work, args=(t,))
                   for t in range(n_threads)]
        [t.start() for t in threads]
        [t.join() for t in threads]
        w.close()
        assert not errs
        scalars = summary.read_scalars(w._path)
        for t in range(n_threads):
            assert [s for s, _ in scalars[f"tag{t}"]] == list(range(n_each))
            assert len(w.get_scalar(f"tag{t}")) == n_each


class TestEstimatorMirroring:
    def test_fit_mirrors_scalars_into_registry(self, orca_ctx, tmp_path):
        """The fit loop reports step time / throughput / loss / LR into
        BOTH the TF-events writer and the telemetry registry."""
        import flax.linen as nn

        from analytics_zoo_tpu.learn.estimator import Estimator
        from analytics_zoo_tpu.learn.optimizers import Adam

        class Tiny(nn.Module):
            @nn.compact
            def __call__(self, x, train: bool = False):
                return nn.Dense(1)(x)

        rng = np.random.default_rng(0)
        x = rng.normal(size=(64, 4)).astype(np.float32)
        y = (x @ np.ones((4, 1), np.float32))
        est = Estimator.from_flax(model=Tiny(), loss="mse",
                                  optimizer=Adam(1e-2), sample_input=x[:2],
                                  model_dir=str(tmp_path / "m"))
        est.fit((x, y), epochs=3, batch_size=32)
        snap = telemetry.snapshot()
        assert snap["zoo_training_loss"] >= 0.0
        assert snap["zoo_training_throughput_samples_per_sec"] > 0.0
        assert snap["zoo_training_step_seconds"]["count"] >= 1
        assert snap["zoo_training_learning_rate"] == pytest.approx(1e-2)
        # events writer got the same stream (existing surface unchanged)
        assert est.get_train_summary("Loss")
        assert est.get_train_summary("LearningRate")
        # jit instrumentation: compiles counted, steady state flat
        misses = snap["zoo_jit_cache_misses_total"]
        calls = snap["zoo_jit_calls_total"]
        assert sum(misses.values()) >= 1
        assert sum(calls.values()) >= sum(misses.values())
