"""Bucketed autoregressive decode (ISSUE 14).

The load-bearing claim is the parity one: the decoder scan is strictly
causal in time, so a decode buffer padded to the seq-length rung must be
**bitwise identical** to the exact-length unpadded reference — across
tail lengths (live length strictly inside a rung) and rung-growth
boundaries. Everything else (KV-cache rung math, feedback modes, the
decode-steps counter) pins the machinery around that claim.
"""

import numpy as np
import pytest

from analytics_zoo_tpu.common import compile_ahead, telemetry
from analytics_zoo_tpu.inference import generation


def _decode_steps_total() -> float:
    val = telemetry.snapshot().get("zoo_decode_steps_total", 0.0)
    return float(val if isinstance(val, (int, float)) else 0.0)


# ------------------------------------------------------------ ladder

def test_seq_ladder_bounds():
    lad = generation.seq_ladder(33, min_rung=2)
    assert lad.rungs[0] == 2
    assert lad.rungs[-1] >= 33
    # a short generation must not be forced onto a tall bottom rung
    assert generation.seq_ladder(4).rungs[0] <= 4


# ---------------------------------------------------------- KV cache

def test_kv_cache_rung_growth_and_zero_tail():
    lad = compile_ahead.BucketLadder(2, 16)
    c = generation.BucketedKVCache(3, 5, lad)
    assert c.view().shape == (3, 2, 5)
    rungs = []
    for i in range(9):
        c.append(np.full((3, 5), float(i + 1), np.float32))
        rungs.append(c.rung)
    # power-of-two rung growth — never a per-step shape
    assert rungs == [2, 2, 4, 4, 8, 8, 8, 8, 16]
    v = c.view()
    assert v.shape == (3, 16, 5)
    assert np.all(v[:, 9:, :] == 0.0)       # zeros past the live length
    assert np.all(v[:, 8, :] == 9.0)        # last live position intact


def test_kv_cache_without_ladder_is_exact_length():
    c = generation.BucketedKVCache(2, 3)
    for i in range(5):
        c.append(np.zeros((2, 3), np.float32))
        assert c.rung == max(1, i + 1)      # exact shapes: parity baseline


# ------------------------------------------------------------ parity

@pytest.fixture(scope="module")
def s2s():
    from analytics_zoo_tpu.models import Seq2Seq
    return Seq2Seq(input_dim=3, output_dim=2, hidden_size=8,
                   rnn_type="gru", encoder_seq_len=4, decoder_seq_len=4)


@pytest.fixture(scope="module")
def s2s_inputs():
    rng = np.random.RandomState(0)
    enc = rng.randn(2, 4, 3).astype(np.float32)
    start = np.zeros((2, 2), np.float32)
    return enc, start


# 1: single step at the bottom rung; 3/4: tail inside rung 4 and exactly
# full; 5: the 4→8 growth boundary; 9: two growths with a final tail
@pytest.mark.parametrize("steps", [1, 3, 4, 5, 9])
def test_rung_padded_decode_is_bitwise_equal(s2s, s2s_inputs, steps):
    enc, start = s2s_inputs

    def fn(e, d):
        return s2s.predict((e, d))

    lad = generation.seq_ladder(steps + 1, min_rung=2)
    padded = generation.decode_loop(fn, enc, start, steps, ladder=lad)
    exact = generation.decode_loop(fn, enc, start, steps, ladder=None)
    assert padded.shape == (2, steps, 2)
    # bitwise, not allclose: causality means the rung's zero tail cannot
    # perturb a single ulp of the live positions
    assert np.array_equal(padded, exact)


def test_greedy_parity_across_growth_boundary(s2s, s2s_inputs):
    enc, start = s2s_inputs

    def fn(e, d):
        return s2s.predict((e, d))

    lad = generation.seq_ladder(8, min_rung=2)
    padded = generation.decode_loop(fn, enc, start, 6, ladder=lad,
                                    mode="greedy")
    exact = generation.decode_loop(fn, enc, start, 6, ladder=None,
                                   mode="greedy")
    assert np.array_equal(padded, exact)


# ------------------------------------------------------------- modes

def test_greedy_feedback_is_one_hot(s2s, s2s_inputs):
    enc, start = s2s_inputs
    out = generation.decode_loop(
        lambda e, d: s2s.predict((e, d)), enc, start, 4,
        ladder=generation.seq_ladder(5, min_rung=2), mode="greedy")
    flat = out.reshape(-1, out.shape[-1])
    assert np.all(np.isin(flat, (0.0, 1.0)))
    assert np.all(flat.sum(axis=-1) == 1.0)


def test_sample_mode_is_seed_deterministic(s2s, s2s_inputs):
    enc, start = s2s_inputs

    def run(seed):
        return generation.decode_loop(
            lambda e, d: s2s.predict((e, d)), enc, start, 6,
            ladder=generation.seq_ladder(7, min_rung=2), mode="sample",
            temperature=0.7, seed=seed)

    assert np.array_equal(run(5), run(5))


def test_sample_token_ids_gumbel_stream_contract():
    vec = np.random.default_rng(0).normal(size=(4, 6))
    a, b = np.random.default_rng(9), np.random.default_rng(9)
    ids = generation.sample_token_ids(vec, 0.7, a)
    assert ids.shape == (4,)
    # exactly ONE uniform draw of vec.shape per call — the contract the
    # step scheduler's per-sequence rng streams rest on
    b.random(vec.shape)
    assert a.bit_generator.state == b.bit_generator.state
    ids2 = generation.sample_token_ids(vec, 0.7, np.random.default_rng(9))
    assert np.array_equal(ids, ids2)


def test_sample_vectorization_matches_per_row_reference():
    vec = np.random.default_rng(4).normal(size=(5, 7))
    u = np.random.default_rng(11).random(vec.shape)
    u = np.maximum(u, np.finfo(np.float64).tiny)
    want = np.array([np.argmax(vec[i] / 0.7 - np.log(-np.log(u[i])))
                     for i in range(vec.shape[0])])
    got = generation.sample_token_ids(vec, 0.7, np.random.default_rng(11))
    assert np.array_equal(got, want)


def test_sample_low_temperature_collapses_to_argmax():
    vec = np.random.default_rng(1).normal(size=(8, 5))
    ids = generation.sample_token_ids(vec, 1e-9, np.random.default_rng(3))
    assert np.array_equal(ids, np.argmax(vec, axis=-1))


def test_feedback_rows_sample_is_seeded_one_hot():
    vec = np.random.default_rng(2).normal(size=(3, 4)).astype(np.float32)
    r1 = generation.feedback_rows(
        vec, "sample", 0.5, np.random.default_rng(7))
    r2 = generation.feedback_rows(
        vec, "sample", 0.5, np.random.default_rng(7))
    assert np.array_equal(r1, r2)
    assert np.all(np.isin(r1, (0.0, 1.0)))
    assert np.all(r1.sum(axis=-1) == 1.0)


def test_bad_mode_and_steps_raise(s2s_inputs):
    enc, start = s2s_inputs
    fn = lambda e, d: np.zeros((e.shape[0], d.shape[1], 2), np.float32)
    with pytest.raises(ValueError):
        generation.decode_loop(fn, enc, start, 4, mode="beam")
    with pytest.raises(ValueError):
        generation.decode_loop(fn, enc, start, 0)


# ------------------------------------------------- model + telemetry

def test_seq2seq_infer_rides_the_bucketed_loop(s2s, s2s_inputs):
    enc, start = s2s_inputs
    out = s2s.infer(enc, start_sign=start, max_seq_len=6)
    assert out.shape == (2, 5, 2)
    # degenerate request: nothing to generate
    assert s2s.infer(enc, start_sign=start, max_seq_len=1).shape == (2, 0, 2)


def test_decode_steps_counter_and_rung_gauge(s2s, s2s_inputs):
    enc, start = s2s_inputs
    before = _decode_steps_total()
    generation.decode_loop(
        lambda e, d: s2s.predict((e, d)), enc, start, 4,
        ladder=generation.seq_ladder(5, min_rung=2))
    # one increment per generated position per record in the batch
    assert _decode_steps_total() - before == enc.shape[0] * 4
    assert float(telemetry.snapshot().get("zoo_kv_cache_rung", 0.0)) >= 2


def test_decode_spans_land_on_the_trace(s2s, s2s_inputs):
    enc, start = s2s_inputs
    generation.decode_loop(
        lambda e, d: s2s.predict((e, d)), enc, start, 3,
        ladder=generation.seq_ladder(4, min_rung=2),
        trace_ids=("gen-span-test",))
    spans = telemetry.get_tracer().get("gen-span-test")
    names = {s.name for s in spans}
    assert {"decode_step_1", "decode_step_2", "decode_step_3"} <= names
    assert all(s.parent == "device" for s in spans
               if s.name.startswith("decode_step_"))
