"""Cluster Serving tests (mirrors ref pyzoo/test/zoo/serving/ + Scala
serving specs): broker protocol, wire schema, end-to-end stream → inference
→ result, HTTP frontend, config parsing."""

import json
import re
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from analytics_zoo_tpu import observability as obs
from analytics_zoo_tpu.common import telemetry
from analytics_zoo_tpu.serving import (
    Broker, ClusterServing, FrontEnd, InputQueue, OutputQueue, ServingConfig,
)
from analytics_zoo_tpu.serving import schema
from analytics_zoo_tpu.serving.broker import build_native_broker


BACKENDS = ["python"] + (["native"] if build_native_broker() else [])


@pytest.fixture(params=BACKENDS)
def broker(request):
    b = Broker.launch(backend=request.param)
    yield b
    b.stop()


class TestBrokerProtocol:
    def test_ping_xadd_xlen(self, broker):
        c = broker.client()
        assert c.ping()
        assert c.xadd("s", "YWJj") == 1
        assert c.xadd("s", "ZGVm") == 2
        assert c.xlen("s") == 2

    def test_consumer_group_delivery_and_ack(self, broker):
        c = broker.client()
        for i in range(5):
            c.xadd("s", f"cGF5bG9hZA{i}=")
        got = c.xreadgroup("g", "c0", "s", 3)
        assert [e[0] for e in got] == [1, 2, 3]
        # same group continues at cursor; different group restarts
        got2 = c.xreadgroup("g", "c1", "s", 10)
        assert [e[0] for e in got2] == [4, 5]
        other = c.xreadgroup("g2", "c0", "s", 10)
        assert len(other) == 5
        assert c.xpending("s", "g") == 5
        assert c.xack("s", "g", 1) == 1
        assert c.xack("s", "g", 1) == 0  # double-ack
        assert c.xpending("s", "g") == 4

    def test_blocking_read_wakes_on_add(self, broker):
        c_reader = broker.client()
        results = []

        def reader():
            results.extend(c_reader.xreadgroup("g", "c", "s", 1, 3000))

        t = threading.Thread(target=reader)
        t.start()
        c = broker.client()
        c.xadd("s", "aGk=")
        t.join(timeout=5)
        assert not t.is_alive() and results and results[0][0] == 1

    def test_hash_ops(self, broker):
        c = broker.client()
        assert c.hget("h", "k") is None
        c.hset("h", "k", "dg==")
        assert c.hget("h", "k") == "dg=="
        assert sorted(c.hkeys("h")) == ["k"]
        assert c.hdel("h", "k") == 1
        assert c.hdel("h", "k") == 0


class TestSchema:
    def test_tensor_roundtrip(self):
        for arr in (np.random.randn(3, 4).astype(np.float32),
                    np.arange(6, dtype=np.int64).reshape(2, 3),
                    np.array(3.5)):
            got = schema.decode_tensor(schema.encode_tensor(arr))
            np.testing.assert_array_equal(got, arr)
            assert got.dtype == arr.dtype

    def test_record_roundtrip(self):
        x = np.random.randn(2, 5).astype(np.float32)
        y = np.arange(2)
        uri, inputs = schema.decode_record(
            schema.encode_record("r1", {"x": x, "y": y}))
        assert uri == "r1"
        np.testing.assert_array_equal(inputs["x"], x)
        np.testing.assert_array_equal(inputs["y"], y)


def _make_model():
    import torch
    import torch.nn as tnn
    from analytics_zoo_tpu.inference import InferenceModel
    torch.manual_seed(0)
    m = tnn.Sequential(tnn.Linear(4, 8), tnn.ReLU(), tnn.Linear(8, 3),
                       tnn.Softmax(dim=-1))
    return InferenceModel().load_torch(m, np.zeros((1, 4), np.float32)), m


class TestEndToEnd:
    def test_stream_to_result(self, broker):
        im, torch_m = _make_model()
        rng = np.random.RandomState(0)
        xs = {f"u{i}": rng.randn(4).astype(np.float32) for i in range(10)}
        with ClusterServing(im, broker.port, batch_size=4).start() as serving:
            in_q = InputQueue(port=broker.port)
            out_q = OutputQueue(port=broker.port)
            for uri, x in xs.items():
                in_q.enqueue(uri, x=x)
            results = {u: out_q.query(u, timeout=20.0) for u in xs}
        import torch
        for uri, x in xs.items():
            assert results[uri] is not None, f"no result for {uri}"
            want = torch_m(torch.from_numpy(x[None])).detach().numpy()[0]
            np.testing.assert_allclose(results[uri], want, atol=1e-4)
        m = serving.metrics()
        assert m["records_out"] == 10
        assert "inference" in m and m["inference"]["count"] >= 1

    def test_batch_enqueue_and_query_many(self, broker):
        """Pipelined client path: one socket write for N records, pipelined
        HGET polling for the results."""
        im, torch_m = _make_model()
        rng = np.random.RandomState(1)
        xs = [rng.randn(4).astype(np.float32) for _ in range(12)]
        with ClusterServing(im, broker.port, batch_size=4).start():
            in_q = InputQueue(port=broker.port)
            out_q = OutputQueue(port=broker.port)
            uris = in_q.enqueue_batch(
                [(None if i % 2 else f"b{i}", {"x": x})
                 for i, x in enumerate(xs)])
            assert len(uris) == 12 and uris[0] == "b0"
            res = out_q.query_many(uris, timeout=20.0, delete=True)
            import torch
            for uri, x in zip(uris, xs):
                assert res[uri] is not None, f"no result for {uri}"
                want = torch_m(torch.from_numpy(x[None])).detach().numpy()[0]
                np.testing.assert_allclose(res[uri], want, atol=1e-4)
            # delete=True removed the fetched entries
            assert out_q.query(uris[0]) is None

    def test_pipeline_command_interleaving(self, broker):
        """Raw pipeline: many XADDs in one write return in-order ids."""
        from analytics_zoo_tpu.serving.broker import BrokerClient
        c = BrokerClient(port=broker.port)
        ids = c.pipeline(("XADD", "pstream", f"payload{i}")
                         for i in range(50))
        assert [int(v) for v in ids] == list(range(1, 51))
        assert c.xlen("pstream") == 50
        assert c.pipeline([]) == []
        # exceeds one chunk: still ordered and fully applied
        n = c.PIPELINE_CHUNK + 37
        ids = c.pipeline(("XADD", "pstream2", f"p{i}") for i in range(n))
        assert len(ids) == n and c.xlen("pstream2") == n

    def test_pipeline_error_keeps_connection_in_sync(self, broker):
        """A failing command mid-pipeline raises AFTER all replies are
        drained, so later commands on the same client see fresh replies."""
        from analytics_zoo_tpu.serving.broker import BrokerClient
        c = BrokerClient(port=broker.port)
        with pytest.raises(RuntimeError):
            c.pipeline([("XADD", "estream", "a"), ("BOGUSCMD", "x"),
                        ("XADD", "estream", "b")])
        # both valid XADDs applied; the connection is not desynced
        assert c.xlen("estream") == 2
        assert c.ping()

    def test_dequeue_drains(self, broker):
        im, _ = _make_model()
        with ClusterServing(im, broker.port, batch_size=2).start():
            in_q = InputQueue(port=broker.port)
            out_q = OutputQueue(port=broker.port)
            for i in range(4):
                in_q.enqueue(f"d{i}", x=np.zeros(4, np.float32))
            got = {}
            import time
            deadline = time.time() + 20
            while len(got) < 4 and time.time() < deadline:
                got.update(out_q.dequeue())
                time.sleep(0.02)
        assert sorted(got) == [f"d{i}" for i in range(4)]
        # drained: a second dequeue is empty
        assert out_q.dequeue() == {}

    def test_http_frontend(self, broker):
        im, torch_m = _make_model()
        x = np.random.RandomState(1).randn(4).astype(np.float32)
        with ClusterServing(im, broker.port, batch_size=2).start() as eng, \
                FrontEnd(broker.port, engine=eng, timeout=20.0).start() as fe:
            body = json.dumps(
                {"inputs": {"x": schema.encode_tensor(x)}}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{fe.port}/predict", data=body,
                headers={"Content-Type": "application/json"})
            resp = json.loads(urllib.request.urlopen(req, timeout=30).read())
            assert "result" in resp, resp
            got = schema.decode_tensor(resp["result"])
            mreq = urllib.request.urlopen(
                f"http://127.0.0.1:{fe.port}/metrics", timeout=10)
            metrics = json.loads(mreq.read())
        import torch
        want = torch_m(torch.from_numpy(x[None])).detach().numpy()[0]
        np.testing.assert_allclose(got, want, atol=1e-4)
        assert metrics["records_out"] >= 1


class TestResilience:
    def test_bad_uri_rejected(self, broker):
        in_q = InputQueue(port=broker.port)
        for bad in ("has space", "new\nline", "x" * 300):
            with pytest.raises(ValueError, match="bad uri"):
                in_q.enqueue(bad, x=np.zeros(2, np.float32))
        # empty/None uri is not an error — it auto-generates
        assert in_q.enqueue("", x=np.zeros(2, np.float32))

    def test_malformed_record_does_not_kill_engine(self, broker):
        im, _ = _make_model()
        with ClusterServing(im, broker.port, batch_size=2).start():
            c = broker.client()
            # undecodable payload: skipped with a warning, acked
            c.xadd("serving_stream", "bm90anNvbg==")  # not a record
            in_q = InputQueue(port=broker.port)
            out_q = OutputQueue(port=broker.port)
            in_q.enqueue("okshape", x=np.zeros(4, np.float32))
            assert out_q.query("okshape", timeout=20.0) is not None
            # inference-breaking shape (wrong inner dim): the record gets an
            # error result (not silence) and the loop survives
            in_q.enqueue("badshape", x=np.zeros(5, np.float32))
            with pytest.raises(schema.ServingError, match="inference failed"):
                out_q.query("badshape", timeout=20.0)
            # engine still alive for subsequent good records
            in_q.enqueue("after", x=np.ones(4, np.float32))
            assert out_q.query("after", timeout=20.0) is not None

    def test_xclaim_redelivers_dead_consumer_pending(self, broker):
        """Entries delivered to a consumer that dies before XACK must be
        claimable by another consumer (regression: they used to be lost
        forever while XPENDING still counted them)."""
        c = broker.client()
        for _ in range(3):
            c.xadd("s", "ZA==")
        got = c.xreadgroup("g", "c0", "s", 3)   # c0 takes them... and dies
        assert len(got) == 3
        assert c.xpending("s", "g") == 3
        assert c.xreadgroup("g", "c1", "s", 3) == []  # cursor is past them
        # not yet idle long enough → nothing claimable
        assert c.xclaim("s", "g", "c1", 60000, 10) == []
        claimed = c.xclaim("s", "g", "c1", 0, 10)
        assert [e[0] for e in claimed] == [e[0] for e in got]
        assert claimed[0][1] == "ZA=="
        for eid, _ in claimed:
            c.xack("s", "g", eid)
        assert c.xpending("s", "g") == 0
        assert c.xlen("s") == 0  # fully acked → GC'd

    def test_engine_recovers_orphaned_pending(self, broker):
        """A record delivered to a crashed consumer is re-processed by a
        restarted engine via XCLAIM."""
        im, _ = _make_model()
        in_q = InputQueue(port=broker.port)
        in_q.enqueue("orphan", x=np.zeros(4, np.float32))
        ghost = broker.client().xreadgroup("serving", "dead",
                                           "serving_stream", 10)
        assert len(ghost) == 1  # delivered to "dead", never acked
        with ClusterServing(im, broker.port, batch_size=2,
                            claim_min_idle_ms=0).start():
            out_q = OutputQueue(port=broker.port)
            assert out_q.query("orphan", timeout=20.0) is not None

    def test_broker_gc_trims_acked_entries(self, broker):
        c = broker.client()
        for i in range(10):
            c.xadd("s", "ZA==")
        got = c.xreadgroup("g", "c0", "s", 10)
        for eid, _ in got:
            c.xack("s", "g", eid)
        assert c.xlen("s") == 0  # all delivered+acked → trimmed

    def test_frontend_empty_inputs_is_400(self, broker):
        im, _ = _make_model()
        with ClusterServing(im, broker.port, batch_size=2).start() as eng, \
                FrontEnd(broker.port, engine=eng, timeout=5.0).start() as fe:
            body = json.dumps({"inputs": {}}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{fe.port}/predict", data=body)
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            assert ei.value.code == 400


class TestConcurrencyAndRecovery:
    def test_concurrent_producers_no_loss_no_dup(self, broker):
        """4 producer threads x 100 records against one engine: every
        record gets exactly one result (races in the broker's delivery /
        GC path would lose or duplicate)."""
        im, _ = _make_model()
        with ClusterServing(im, broker.port, batch_size=16).start():
            errs = []

            def produce(t):
                try:
                    q = InputQueue(port=broker.port)
                    for i in range(100):
                        q.enqueue(f"p{t}_{i}",
                                  x=np.full(4, t + i / 100, np.float32))
                except Exception as e:  # pragma: no cover
                    errs.append(e)

            threads = [threading.Thread(target=produce, args=(t,))
                       for t in range(4)]
            [t.start() for t in threads]
            [t.join() for t in threads]
            assert not errs
            out_q = OutputQueue(port=broker.port)
            for t in range(4):
                for i in range(100):
                    r = out_q.query(f"p{t}_{i}", timeout=60.0)
                    assert r is not None, f"lost p{t}_{i}"
            # fully drained: no pending deliveries left behind
            c = broker.client()
            assert c.xpending("serving_stream", "serving") == 0

    def test_engine_survives_broker_restart(self):
        """Failure detection (SURVEY §5): the serve loop reconnects when
        the broker dies and a new one comes up on the same port."""
        im, _ = _make_model()
        b1 = Broker.launch(backend="python")
        port = b1.port
        eng = ClusterServing(im, port, batch_size=2).start()
        try:
            in_q = InputQueue(port=port)
            out_q = OutputQueue(port=port)
            in_q.enqueue("before", x=np.zeros(4, np.float32))
            assert out_q.query("before", timeout=30.0) is not None

            b1.stop()          # broker dies mid-service
            b2 = Broker.launch(backend="python", port=port)
            try:
                in_q2 = InputQueue(port=port)
                out_q2 = OutputQueue(port=port)
                in_q2.enqueue("after", x=np.ones(4, np.float32))
                assert out_q2.query("after", timeout=30.0) is not None, \
                    "engine never reconnected to the restarted broker"
            finally:
                eng.stop()
                b2.stop()
        finally:
            eng.stop()


class TestConfig:
    def test_yaml_parse(self, tmp_path):
        p = tmp_path / "config.yaml"
        p.write_text(
            "model:\n  path: /models/ncf\n"
            "data:\n  src: 127.0.0.1:7012\n  record_encrypted: true\n"
            "params:\n  batch_size: 32\n")
        cfg = ServingConfig.load(str(p))
        assert cfg.model_path == "/models/ncf"
        assert cfg.broker_port == 7012
        assert cfg.batch_size == 32
        assert cfg.record_encrypted is True

    def test_defaults(self, tmp_path):
        p = tmp_path / "c.yaml"
        p.write_text("model:\n  path: m\n")
        cfg = ServingConfig.load(str(p))
        assert cfg.batch_size == 8 and cfg.broker_port == 6399


class TestHashTTLAndContention:
    """Broker hardening (VERDICT r3 weak #8): result-hash TTL bounds
    memory when clients never collect, and the broker stays correct under
    multi-client lock contention on both backends."""

    @pytest.mark.parametrize("backend", BACKENDS)
    def test_hash_ttl_evicts_uncollected_results(self, backend):
        b = Broker.launch(backend=backend, hash_ttl_ms=300)
        try:
            c = b.client()
            for i in range(20):
                c.hset("serving_result", f"r{i}", "dmFs")  # b64 "val"
            assert c.hget("serving_result", "r0") == "dmFs"
            time.sleep(0.5)
            # expired: reads return nothing and the key list is empty
            assert c.hget("serving_result", "r0") is None
            assert c.hkeys("serving_result") == []
            # new writes after expiry live again
            c.hset("serving_result", "fresh", "dmFs")
            assert c.hget("serving_result", "fresh") == "dmFs"
        finally:
            b.stop()

    @pytest.mark.parametrize("backend", BACKENDS)
    def test_hash_ttl_zero_disables(self, backend):
        b = Broker.launch(backend=backend, hash_ttl_ms=0)
        try:
            c = b.client()
            c.hset("h", "f", "dmFs")
            time.sleep(0.3)
            assert c.hget("h", "f") == "dmFs"
        finally:
            b.stop()

    @pytest.mark.parametrize("backend", BACKENDS)
    def test_ttl_does_not_race_collection(self, backend):
        """Results collected before the TTL are delivered even while the
        sweeper is active (writer + reader contention with a short TTL)."""
        b = Broker.launch(backend=backend, hash_ttl_ms=2000)
        try:
            c_w = b.client()
            c_r = b.client()
            missing = []

            def write():
                for i in range(200):
                    c_w.hset("res", f"k{i}", "dmFs")

            def read():
                for i in range(200):
                    for _ in range(200):
                        if c_r.hget("res", f"k{i}") is not None:
                            break
                        time.sleep(0.002)
                    else:
                        missing.append(i)

            tw = threading.Thread(target=write)
            tr = threading.Thread(target=read)
            tw.start(); tr.start(); tw.join(); tr.join()
            assert missing == []
        finally:
            b.stop()

    def test_multiclient_contention_stress(self, broker):
        """8 concurrent clients (4 producers, 2 consumers via the engine
        group, 2 hash pollers) hammer one broker: exactly-once results for
        every record, no protocol desync on any connection."""
        im, _ = _make_model()
        n_per, n_prod = 75, 4
        with ClusterServing(im, broker.port, batch_size=8).start():
            errs = []
            polled = {"n": 0}
            stop = threading.Event()

            def produce(t):
                try:
                    q = InputQueue(port=broker.port)
                    for i in range(n_per):
                        q.enqueue(f"s{t}_{i}",
                                  x=np.full(4, t + i / 100, np.float32))
                except Exception as e:
                    errs.append(e)

            def poll_hash():
                # concurrent HKEYS/HGET readers racing the engine's HSETs
                # on the ACTUAL result hash the engine writes
                from analytics_zoo_tpu.serving.client import RESULT_HASH
                try:
                    c = broker.client()
                    while not stop.is_set():
                        for k in c.hkeys(RESULT_HASH)[:10]:
                            c.hget(RESULT_HASH, k)
                        polled["n"] += 1
                except Exception as e:
                    errs.append(e)

            pollers = [threading.Thread(target=poll_hash) for _ in range(2)]
            producers = [threading.Thread(target=produce, args=(t,))
                         for t in range(n_prod)]
            [t.start() for t in pollers + producers]
            [t.join() for t in producers]
            out_q = OutputQueue(port=broker.port)
            for t in range(n_prod):
                for i in range(n_per):
                    assert out_q.query(f"s{t}_{i}", timeout=60.0) \
                        is not None, f"lost s{t}_{i}"
            stop.set()
            [t.join() for t in pollers]
            assert not errs
            assert polled["n"] > 0
            c = broker.client()
            assert c.xpending("serving_stream", "serving") == 0


class TestContainerEntrypoint:
    """docker/cluster-serving/start-serving.py boots broker + engine +
    HTTP frontend from one config.yaml and serves end-to-end (the
    reference's cluster-serving container flow)."""

    @pytest.mark.slow  # ~14s: boots the full container stack in a subprocess
    def test_start_serving_script(self, tmp_path):
        import os
        import signal
        import socket
        import subprocess
        import sys
        import time as _time

        from analytics_zoo_tpu.models import NeuralCF

        # a saved zoo model the entrypoint can InferenceModel().load()
        model_dir = tmp_path / "model"
        NeuralCF(user_count=5, item_count=5, class_num=2, user_embed=4,
                 item_embed=4, hidden_layers=(8,),
                 include_mf=False, mf_embed=0).save_model(str(model_dir))
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            bport = s.getsockname()[1]
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            hport = s.getsockname()[1]
        cfg = tmp_path / "config.yaml"
        cfg.write_text(
            f"model:\n  path: {model_dir}\n"
            f"data:\n  src: 127.0.0.1:{bport}\n"
            f"params:\n  batch_size: 4\n")
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        script = os.path.join(repo, "docker", "cluster-serving",
                              "start-serving.py")
        env = dict(os.environ, HTTP_PORT=str(hport),
                   JAX_PLATFORMS="cpu", PYTHONPATH=repo)
        launcher = ("import jax, runpy, sys; "
                    "jax.config.update('jax_platforms', 'cpu'); "
                    "sys.argv = sys.argv[1:]; "
                    "runpy.run_path(sys.argv[0], run_name='__main__')")
        proc = subprocess.Popen(
            [sys.executable, "-c", launcher, script, str(cfg)],
            env=env, cwd=repo, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        try:
            # readiness wait with a REAL deadline (readline alone would
            # block forever if the entrypoint wedges before printing)
            found = {"line": ""}

            def _wait_ready():
                while True:
                    line = proc.stdout.readline()
                    if not line:
                        return
                    if "serving up" in line:
                        found["line"] = line
                        return

            waiter = threading.Thread(target=_wait_ready, daemon=True)
            waiter.start()
            waiter.join(timeout=300)
            assert "serving up" in found["line"], \
                (found["line"], proc.poll())

            x = np.array([1.0, 2.0], np.float32)
            body = json.dumps(
                {"inputs": {"x": schema.encode_tensor(x)}}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{hport}/predict", data=body,
                headers={"Content-Type": "application/json"})
            resp = json.loads(
                urllib.request.urlopen(req, timeout=120).read())
            assert "result" in resp, resp
            out = schema.decode_tensor(resp["result"])
            assert out.shape[-1] == 2 and np.isfinite(out).all()
        finally:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()


class TestImageInput:
    """Raw-image serving path (ref PreProcessing.scala:36,67-90 +
    client.py:144: the client enqueues encoded image bytes, the SERVER
    decodes and runs the configured preprocessing chain)."""

    def _image_model(self, size=32):
        """Tiny conv classifier taking [b, size, size, 3]."""
        import torch
        import torch.nn as tnn

        from analytics_zoo_tpu.inference import InferenceModel

        torch.manual_seed(0)

        class Net(tnn.Module):
            def __init__(self):
                super().__init__()
                self.conv = tnn.Conv2d(3, 4, 3, 2)
                self.fc = tnn.Linear(4, 3)

            def forward(self, x):          # [b, h, w, 3] channels-last
                y = self.conv(x.permute(0, 3, 1, 2)).mean((2, 3))
                return torch.nn.functional.softmax(self.fc(y), dim=-1)

        m = Net()
        return (InferenceModel().load_torch(
            m, np.zeros((1, size, size, 3), np.float32)), m)

    def test_jpeg_bytes_through_engine(self, broker, tmp_path):
        """JPEG bytes -> broker -> engine decode + preprocess -> result,
        numerically equal to client-side decode + the same chain."""
        import io

        from PIL import Image

        im, torch_m = self._image_model(32)
        rng = np.random.RandomState(0)
        raw = (rng.rand(48, 40, 3) * 255).astype(np.uint8)
        buf = io.BytesIO()
        Image.fromarray(raw).save(buf, format="PNG")  # lossless: exact
        png_bytes = buf.getvalue()

        def pre(arr):                       # resize->crop->scale chain
            from analytics_zoo_tpu.feature.image import (
                ChainedPreprocessing, ImageCenterCrop,
                ImageChannelScaledNormalizer, ImageMatToTensor,
                ImageResize,
            )
            pipe = ChainedPreprocessing([
                ImageResize(36, 36), ImageCenterCrop(32, 32),
                ImageChannelScaledNormalizer(128.0, 128.0, 128.0,
                                             1.0 / 128.0),
                ImageMatToTensor()])
            return pipe.transform({"image": arr})["image"]

        with ClusterServing(im, broker.port, batch_size=2,
                            image_preprocess=pre).start():
            in_q = InputQueue(port=broker.port)
            out_q = OutputQueue(port=broker.port)
            in_q.enqueue("img_bytes", image=png_bytes)
            # path flavor too (ref client enqueues local file uris)
            p = str(tmp_path / "img.png")
            with open(p, "wb") as f:
                f.write(png_bytes)
            in_q.enqueue_image("img_path", p)
            r1 = out_q.query("img_bytes", timeout=60.0)
            r2 = out_q.query("img_path", timeout=60.0)
        assert r1 is not None and r2 is not None
        expect = pre(np.asarray(raw, np.float32))[None]
        import torch
        want = torch_m(torch.tensor(expect)).detach().numpy()[0]
        np.testing.assert_allclose(r1, want, atol=1e-5)
        np.testing.assert_allclose(r2, want, atol=1e-5)

    def test_undecodable_image_gets_error_result(self, broker):
        im, _ = self._image_model(32)
        with ClusterServing(im, broker.port, batch_size=2).start():
            in_q = InputQueue(port=broker.port)
            out_q = OutputQueue(port=broker.port)
            in_q.enqueue("badimg", image=b"not an image at all")
            with pytest.raises(schema.ServingError, match="image decode"):
                out_q.query("badimg", timeout=60.0)

    def test_config_preprocessing_section(self, tmp_path):
        """config.yaml preprocessing: -> a working engine-side chain."""
        p = tmp_path / "config.yaml"
        p.write_text("""
model:
  path: /nonexistent
data:
  src: 127.0.0.1:6399
preprocessing:
  resize: 36
  crop: 32
  mean: "128.0,128.0,128.0"
  scale: 0.0078125
""")
        cfg = ServingConfig.load(str(p))
        assert cfg.image_resize == 36 and cfg.image_crop == 32
        assert cfg.image_mean == (128.0, 128.0, 128.0)
        chain = cfg.build_image_preprocess()
        out = chain(np.full((48, 40, 3), 192.0, np.float32))
        assert out.shape == (32, 32, 3)
        np.testing.assert_allclose(out, (192 - 128) / 128, rtol=1e-5)
        # preset flavor
        p2 = tmp_path / "config2.yaml"
        p2.write_text("""
model:
  path: /nonexistent
preprocessing:
  preset: resnet-50
  source: torchvision
""")
        cfg2 = ServingConfig.load(str(p2))
        chain2 = cfg2.build_image_preprocess()
        out2 = chain2(np.full((300, 300, 3), 128.0, np.float32))
        assert out2.shape == (224, 224, 3)
        # no section -> None
        p3 = tmp_path / "config3.yaml"
        p3.write_text("model:\n  path: /x\n")
        assert ServingConfig.load(str(p3)).build_image_preprocess() is None

    def test_string_tensors_still_roundtrip(self, broker):
        """A str value is a TENSOR, not a file path (a blanket str->open
        would break string inputs and read arbitrary local files)."""
        uri, inputs = schema.decode_record(
            schema.encode_record("r1", {
                "text": InputQueue._coerce("hello world")}))
        assert uri == "r1"
        assert inputs["text"].reshape(-1)[0] == "hello world"


class TestArrowWireFormat:
    """Reference-client Arrow record encoding (ref client.py:149
    data_to_b64 + schema.py get_field_and_data): InputQueue(arrow=True)
    produces it, the engine auto-detects and serves it."""

    def test_arrow_roundtrip_dense(self):
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        payload = schema.encode_record_arrow("r1", {"x": x})
        uri, inputs = schema.decode_record(payload)
        assert uri == "r1"
        np.testing.assert_allclose(inputs["x"], x)

    def test_arrow_image_and_strings(self):
        import io

        from PIL import Image
        buf = io.BytesIO()
        Image.fromarray(np.zeros((4, 4, 3), np.uint8)).save(buf,
                                                            format="PNG")
        payload = schema.encode_record_arrow(
            "r2", {"img": schema.ImageBytes(buf.getvalue()),
                   "words": ["a", "b", "c"]})
        uri, inputs = schema.decode_record(payload)
        assert isinstance(inputs["img"], schema.ImageBytes)
        assert inputs["img"].data == buf.getvalue()
        assert list(inputs["words"]) == ["a", "b", "c"]

    def test_arrow_client_end_to_end(self, broker):
        im, torch_m = _make_model()
        with ClusterServing(im, broker.port, batch_size=4).start():
            in_q = InputQueue(port=broker.port, arrow=True)
            out_q = OutputQueue(port=broker.port)
            x = np.random.RandomState(0).randn(4).astype(np.float32)
            in_q.enqueue("arrow-1", x=x)
            r = out_q.query("arrow-1", timeout=60.0)
        assert r is not None
        import torch
        want = torch_m(torch.from_numpy(x[None])).detach().numpy()[0]
        np.testing.assert_allclose(r, want, atol=1e-5)

    def test_arrow_mixed_image_and_tensor_record(self):
        """Mixed string/image (1-row) and tensor (4-row) columns must
        encode: short columns null-pad to the batch length."""
        import io

        from PIL import Image
        buf = io.BytesIO()
        Image.fromarray(np.zeros((2, 2, 3), np.uint8)).save(buf,
                                                            format="PNG")
        payload = schema.encode_record_arrow(
            "r3", {"img": schema.ImageBytes(buf.getvalue()),
                   "meta": np.arange(4, dtype=np.float32)})
        uri, inputs = schema.decode_record(payload)
        assert isinstance(inputs["img"], schema.ImageBytes)
        np.testing.assert_allclose(inputs["meta"], np.arange(4))

    def test_arrow_b64_looking_string_stays_string(self):
        """A string value that is valid b64 of bytes with a weak magic
        ('BM...') must NOT be misread as an image."""
        payload = schema.encode_record_arrow(
            "r4", {"words": ["Qk1hcmtldA=="]})   # b64("BMarket")
        _, inputs = schema.decode_record(payload)
        assert not isinstance(inputs["words"], schema.ImageBytes)
        assert list(inputs["words"]) == ["Qk1hcmtldA=="]


def _scrape(port: int, accept: str = None, query: str = ""):
    """GET /metrics and return (status, content_type, body_text)."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/metrics{query}",
        headers={"Accept": accept} if accept else {})
    resp = urllib.request.urlopen(req, timeout=10)
    return resp.status, resp.headers.get("Content-Type"), \
        resp.read().decode()


_PROM_SAMPLE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})? (NaN|[+-]Inf|-?[0-9][0-9.e+-]*)$")


def _parse_prometheus(text):
    """(types, samples) from the 0.0.4 text format; asserts every line is
    well-formed (same checks as tests/test_telemetry.py)."""
    types, samples = {}, {}
    for line in text.rstrip("\n").split("\n"):
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ")
            types[name] = kind
        elif not line.startswith("#"):
            m = _PROM_SAMPLE.match(line)
            assert m, f"malformed exposition line: {line!r}"
            name, braced, val = m.groups()
            samples[(name, braced or "")] = float(val)
    return types, samples


class TestTelemetryServing:
    """ISSUE 2 tentpole: Prometheus exposition, /healthz readiness, and
    per-record trace decomposition from a LIVE serve loop."""

    def test_prometheus_scrape_from_live_serve(self, broker):
        telemetry.reset_for_tests()
        im, _ = _make_model()
        rng = np.random.RandomState(0)
        with ClusterServing(im, broker.port, batch_size=4).start() as eng, \
                FrontEnd(broker.port, engine=eng, timeout=20.0).start() as fe:
            in_q = InputQueue(port=broker.port)
            out_q = OutputQueue(port=broker.port)
            for i in range(8):
                in_q.enqueue(f"prom{i}", x=rng.randn(4).astype(np.float32))
            for i in range(8):
                assert out_q.query(f"prom{i}", timeout=20.0) is not None

            # content negotiation: Accept selects Prometheus...
            status, ctype, text = _scrape(fe.port, accept="text/plain")
            assert status == 200
            assert ctype == "text/plain; version=0.0.4; charset=utf-8"
            # ...so does ?format=prometheus with no Accept header
            _, ctype2, text2 = _scrape(fe.port, query="?format=prometheus")
            assert ctype2 == ctype and "zoo_" in text2
            # default stays the JSON engine metrics (existing surface)
            _, jtype, jbody = _scrape(fe.port)
            assert jtype == "application/json"
            assert json.loads(jbody)["records_out"] >= 8

            types, samples = _parse_prometheus(text)
            # a live serve loop populates all three metric kinds
            assert types["zoo_serving_records_total"] == "counter"
            assert types["zoo_serving_batch_bucket"] == "gauge"
            assert types["zoo_stage_seconds"] == "histogram"
            assert samples[("zoo_serving_records_total",
                            '{stream="serving_stream"}')] >= 8
            assert samples[("zoo_serving_batch_bucket",
                            '{stream="serving_stream"}')] == 4
            # stage histogram carries cumulative buckets + sum/count
            assert samples[("zoo_stage_seconds_count",
                            '{stage="inference"}')] >= 1
            infer_buckets = [v for (n, lbl), v in samples.items()
                             if n == "zoo_stage_seconds_bucket"
                             and 'stage="inference"' in lbl]
            assert infer_buckets and max(infer_buckets) >= 1
            # the frontend's own request counter scrapes too (visible from
            # the second scrape on: a response can't count itself)
            _, samples2 = _parse_prometheus(text2)
            assert samples2[("zoo_http_requests_total",
                             '{path="/metrics",code="200"}')] >= 1

    def test_records_counter_is_monotonic_and_never_behind_results(
            self, broker):
        """A client that sees its result and immediately scrapes must find
        the record already counted (count-before-flush ordering), and the
        counter never decreases across scrapes."""
        telemetry.reset_for_tests()
        im, _ = _make_model()
        with ClusterServing(im, broker.port, batch_size=2).start() as eng, \
                FrontEnd(broker.port, engine=eng, timeout=20.0).start() as fe:
            in_q = InputQueue(port=broker.port)
            out_q = OutputQueue(port=broker.port)
            last = 0.0
            for i in range(6):
                in_q.enqueue(f"mono{i}", x=np.zeros(4, np.float32))
                assert out_q.query(f"mono{i}", timeout=20.0) is not None
                _, _, text = _scrape(fe.port, accept="text/plain")
                _, samples = _parse_prometheus(text)
                n = samples[("zoo_serving_records_total",
                             '{stream="serving_stream"}')]
                assert n >= i + 1, "result visible before it was counted"
                assert n >= last
                last = n
                m = json.loads(_scrape(fe.port)[2])
                assert m["records_out"] >= i + 1

    def test_healthz_ready_and_overloaded(self, broker):
        telemetry.reset_for_tests()
        im, _ = _make_model()
        with ClusterServing(im, broker.port, batch_size=2).start() as eng, \
                FrontEnd(broker.port, engine=eng, timeout=20.0).start() as fe:
            resp = urllib.request.urlopen(
                f"http://127.0.0.1:{fe.port}/healthz", timeout=10)
            out = json.loads(resp.read())
            assert resp.status == 200
            assert out["status"] == "ok" and out["broker"] == "up"
            assert out["engine"] is True
            assert "queue_depth" in out and "backlog" in out
        # a drowning replica: deep input queue, no engine draining it
        with FrontEnd(broker.port, engine=None, max_backlog=2).start() as fe:
            in_q = InputQueue(port=broker.port)
            for i in range(5):
                in_q.enqueue(f"over{i}", x=np.zeros(4, np.float32))
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{fe.port}/healthz", timeout=10)
            assert ei.value.code == 503
            body = json.loads(ei.value.read())
            assert body["status"] == "overloaded"
            assert body["queue_depth"] >= 5

    def test_healthz_broker_down_is_503(self):
        import socket
        with socket.socket() as s:          # a port nothing listens on
            s.bind(("127.0.0.1", 0))
            dead_port = s.getsockname()[1]
        with FrontEnd(dead_port).start() as fe:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{fe.port}/healthz", timeout=10)
            assert ei.value.code == 503
            body = json.loads(ei.value.read())
            assert body["status"] == "unavailable"
            assert body["broker"].startswith("down")

    def test_concurrent_scrape_while_serving(self, broker):
        """Scrapers hammer /metrics (both formats) + /healthz while records
        stream through: every response parses, nothing deadlocks."""
        telemetry.reset_for_tests()
        im, _ = _make_model()
        with ClusterServing(im, broker.port, batch_size=4).start() as eng, \
                FrontEnd(broker.port, engine=eng, timeout=20.0).start() as fe:
            errs = []
            stop = threading.Event()

            def scrape_loop():
                try:
                    while not stop.is_set():
                        _, _, text = _scrape(fe.port, accept="text/plain")
                        _parse_prometheus(text)
                        json.loads(_scrape(fe.port)[2])
                        urllib.request.urlopen(
                            f"http://127.0.0.1:{fe.port}/healthz",
                            timeout=10).read()
                except Exception as e:  # pragma: no cover
                    errs.append(e)

            scrapers = [threading.Thread(target=scrape_loop)
                        for _ in range(3)]
            [t.start() for t in scrapers]
            in_q = InputQueue(port=broker.port)
            out_q = OutputQueue(port=broker.port)
            for i in range(40):
                in_q.enqueue(f"c{i}", x=np.full(4, i / 40, np.float32))
            for i in range(40):
                assert out_q.query(f"c{i}", timeout=30.0) is not None
            stop.set()
            [t.join(timeout=15) for t in scrapers]
            assert not errs
            _, samples = _parse_prometheus(
                _scrape(fe.port, accept="text/plain")[2])
            assert samples[("zoo_serving_records_total",
                            '{stream="serving_stream"}')] >= 40

    def test_single_record_trace_decomposes_end_to_end(self, broker):
        """Acceptance: one served record's trace has contiguous stage spans
        whose durations sum (±tolerance) to the root serve span, and the
        root stays within the client-observed latency plus the broker
        block window."""
        telemetry.reset_for_tests()
        im, _ = _make_model()
        with ClusterServing(im, broker.port, batch_size=2).start():
            in_q = InputQueue(port=broker.port)
            out_q = OutputQueue(port=broker.port)
            t_c0 = time.perf_counter()
            in_q.enqueue("traced-1", x=np.ones(4, np.float32))
            assert out_q.query("traced-1", timeout=20.0) is not None
            client_e2e = time.perf_counter() - t_c0

        spans = {s.name: s for s in obs.trace("traced-1")}
        assert set(spans) == {"client_enqueue", "queue_wait", "dequeue",
                              "preprocess", "dispatch", "device",
                              "postprocess", "serve"}
        # the cross-process head of the timeline (ISSUE 6): the client's
        # enqueue span starts the trace, the measured broker queue wait
        # bridges it to the engine's dequeue — strictly before the
        # engine stages on the shared perf_counter clock
        ce, qw = spans["client_enqueue"], spans["queue_wait"]
        assert ce.parent is None and qw.parent is None
        assert ce.start <= qw.start <= qw.end
        assert qw.end <= spans["dequeue"].end + 1e-9
        assert qw.start <= spans["preprocess"].start
        root = spans["serve"]
        children = [spans[n] for n in ("dequeue", "preprocess", "device",
                                       "postprocess")]
        for c in children:
            assert c.parent == "serve"
            assert root.start <= c.start <= c.end <= root.end + 1e-9
        # contiguous stages: the children tile the root span
        child_sum = sum(c.duration for c in children)
        assert child_sum <= root.duration + 1e-9
        assert root.duration - child_sum <= 0.05, \
            f"stage spans leave {root.duration - child_sum:.4f}s unexplained"
        # dispatch is the non-blocking prefix of the device span
        d = spans["dispatch"]
        assert d.parent == "device"
        assert d.start == spans["device"].start
        assert d.end <= spans["device"].end + 1e-9
        # the engine-side latency is bounded by what the client saw plus
        # the blocked broker read the dequeue span includes (block_ms=50)
        assert root.duration <= client_e2e + 0.5
        assert obs.trace_table("traced-1").count("\n") >= 6

    def test_http_predict_trace_joins_engine_trace(self, broker):
        """The frontend's enqueue/wait spans land on the SAME trace as the
        engine's stage spans (the record uri is the trace id)."""
        telemetry.reset_for_tests()
        im, _ = _make_model()
        x = np.ones(4, np.float32)
        with ClusterServing(im, broker.port, batch_size=2).start() as eng, \
                FrontEnd(broker.port, engine=eng, timeout=20.0).start() as fe:
            body = json.dumps({"uri": "http-traced",
                               "inputs": {"x": schema.encode_tensor(x)}}
                              ).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{fe.port}/predict", data=body,
                headers={"Content-Type": "application/json"})
            resp = json.loads(urllib.request.urlopen(req, timeout=30).read())
            assert resp["uri"] == "http-traced"
        spans = {s.name: s for s in obs.trace("http-traced")}
        assert {"http_predict", "enqueue", "wait", "serve"} <= set(spans)
        assert spans["enqueue"].parent == "http_predict"
        assert spans["wait"].parent == "http_predict"
        # the wait span brackets the engine's flush: it can only end after
        # the serve span did
        assert spans["wait"].end >= spans["serve"].end
        assert spans["http_predict"].start <= spans["enqueue"].start
        assert spans["http_predict"].end >= spans["wait"].end

    def test_trace_sampling_zero_records_nothing(self, broker):
        telemetry.reset_for_tests()
        telemetry.set_trace_sampling(0.0)
        try:
            im, _ = _make_model()
            with ClusterServing(im, broker.port, batch_size=2).start():
                in_q = InputQueue(port=broker.port)
                out_q = OutputQueue(port=broker.port)
                in_q.enqueue("unsampled", x=np.zeros(4, np.float32))
                assert out_q.query("unsampled", timeout=20.0) is not None
            assert obs.trace("unsampled") == []
        finally:
            telemetry.set_trace_sampling(1.0)


class TestPostprocessFailure:
    def test_one_bad_postprocess_keeps_rest_of_batch(self, broker):
        """A postprocess exception on one record must produce an error
        result for THAT record only — the rest of the batch still gets
        results and everything is acked (no XCLAIM redelivery loop)."""
        im, torch_m = _make_model()

        rng = np.random.RandomState(3)
        xs = {f"p{i}": rng.randn(4).astype(np.float32) for i in range(8)}
        import torch
        wants = {u: torch_m(torch.from_numpy(x[None])).detach().numpy()[0]
                 for u, x in xs.items()}
        thr = float(np.median([w[0] for w in wants.values()]))

        def post(pred):
            if pred[0] > thr:           # deterministic per-record failure
                raise ValueError("boom")
            return pred

        bad = {u for u, w in wants.items() if w[0] > thr}
        assert bad and len(bad) < len(xs)   # the median splits the batch
        with ClusterServing(im, broker.port, batch_size=4,
                            postprocess=post).start():
            in_q = InputQueue(port=broker.port)
            out_q = OutputQueue(port=broker.port)
            for uri, x in xs.items():
                in_q.enqueue(uri, x=x)
            for uri in xs:
                if uri in bad:
                    with pytest.raises(schema.ServingError, match="postprocess"):
                        out_q.query(uri, timeout=20.0)
                else:
                    got = out_q.query(uri, timeout=20.0)
                    np.testing.assert_allclose(got, wants[uri], atol=1e-4)
        # nothing left pending: the batch was fully acked despite the error
        assert broker.client().xpending("serving_stream", "serving") == 0
