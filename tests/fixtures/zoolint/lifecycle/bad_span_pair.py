"""Seeded span-pairing violations.

Enter/exit call pairs (attach/detach, arm/disarm) a path leaves
unbalanced. Long-lived attaches with no exit call anywhere in the
function are deliberately out of scope — ``install_forever`` is the
negative control for that carve-out, ``traced_guarded`` for the
try/finally fix. Never imported; fixture data for dev/run-tests.sh
zoolint and tests/test_zoolint_dataflow.py.
"""


def traced_submit(tracer, batch):
    # VIOLATION span-pairing: the batch-is-None return skips the detach
    tracer.attach("submit")
    if batch is None:
        return None
    out = list(batch)
    tracer.detach("submit")
    return out


def armed_flush(watchdog, payload):
    # VIOLATION span-pairing: encode() raising skips the disarm
    watchdog.arm(5.0)
    result = payload.encode()
    watchdog.disarm()
    return result


def traced_guarded(tracer, batch):
    """Negative control: the detach sits in a finally."""
    tracer.attach("submit")
    try:
        return list(batch)
    finally:
        tracer.detach("submit")


def install_forever(tracer):
    """Negative control: a process-lifetime hook never detaches — the
    rule requires a matching exit call somewhere in the function."""
    tracer.attach("process-lifetime")
    return tracer
