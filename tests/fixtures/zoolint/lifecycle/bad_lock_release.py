"""Seeded lock-release-path violations.

Bare ``.acquire()`` calls a path never releases: one early return, one
unguarded call between acquire and release (the exception edge leaks
the lock). The try/finally twin is the negative control. Never
imported; fixture data for dev/run-tests.sh zoolint and
tests/test_zoolint_dataflow.py.
"""

import threading

_lock = threading.Lock()


def submit_unbalanced(jobs):
    # VIOLATION lock-release-path: the empty-jobs return leaves it held
    _lock.acquire()
    if not jobs:
        return 0
    n = len(jobs)
    _lock.release()
    return n


def submit_fragile(jobs):
    # VIOLATION lock-release-path: encode() raising skips the release
    _lock.acquire()
    payload = jobs.encode()
    _lock.release()
    return payload


def submit_guarded(jobs):
    """Negative control: released in a finally on every path."""
    _lock.acquire()
    try:
        return len(jobs)
    finally:
        _lock.release()
