"""Seeded cross-thread state races for the fleet lane
(cross-thread-unlocked-state): an unlocked instance-attr write hidden
behind a helper method, an unlocked module global touched from two
roots, and — as the negative control — a helper that is only ever
called with the lock held, which the must-held propagation must keep
quiet. Never imported."""

import threading

BEATS = 0


def record_beat():
    global BEATS
    BEATS += 1  # VIOLATION cross-thread-unlocked-state (module global)


class RacyHeartbeater:
    def __init__(self):
        self._lock = threading.Lock()
        self.last_beat = 0.0
        self.sent = 0
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while True:
            self._bump()
            record_beat()

    def _bump(self):
        self.sent += 1  # VIOLATION cross-thread-unlocked-state (helper)

    def _locked_bump(self):
        # OK: every caller holds self._lock — must-held propagation
        self.last_beat += 1.0

    def beat_now(self):
        with self._lock:
            self._locked_bump()

    def reset(self):
        with self._lock:
            self._locked_bump()
        self.sent = 0  # VIOLATION cross-thread-unlocked-state (main side)
        record_beat()
