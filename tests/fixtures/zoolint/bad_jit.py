"""Seeded recompile-hazard violations (jit-* rules). Never imported."""

import jax


def retrace_per_step(fn, xs):
    out = []
    for x in xs:
        step = jax.jit(fn)  # VIOLATION jit-in-loop
        out.append(step(x))
    return out


def build_and_call(fn, x):
    return jax.jit(fn)(x)  # VIOLATION jit-call-inline


def unhashable_static(fn):
    return jax.jit(fn, static_argnums=[0, 1])  # VIOLATION jit-static-unhashable
