"""Seeded shape-dependent-branch-in-jit violations.

Python branches on traced values inside the jit region: a shape branch
and a value branch in a decorated entry, and a value branch in a helper
the call graph proves is reached from a jitted body. Static arguments
and ``is None`` tests are the negative controls. Never imported;
fixture data for dev/run-tests.sh zoolint and
tests/test_zoolint_dataflow.py.
"""

import functools

import jax


@jax.jit
def scale_clamped(x, limit):
    # VIOLATION shape-dependent-branch-in-jit: one executable compiled
    # per input length
    if x.shape[0] > 8:
        return x[:8]
    # VIOLATION shape-dependent-branch-in-jit: traced-scalar branch
    # raises at trace time
    if limit > 0:
        return x * limit
    return x


def _helper_norm(v, eps):
    # VIOLATION shape-dependent-branch-in-jit: `eps` is fed from a
    # traced caller value — this helper traces inside `normalize`
    if eps > 0:
        return v / eps
    return v


@jax.jit
def normalize(v, eps):
    return _helper_norm(v, eps)


@functools.partial(jax.jit, static_argnums=(1,))
def pad_static(x, block):
    """Negative control: `block` is a static argument."""
    if block > 1:
        return x
    return x


@jax.jit
def with_default(x, bias):
    """Negative control: `is None` is static at trace time."""
    if bias is None:
        return x
    return x + bias
