"""Seeded data-plane violations: row-at-a-time pandas under a ``data/``
path segment. Never imported — exists so the zoolint lane proves
``rowwise-map-in-data-plane`` fires (docs/zoolint.md)."""

import numpy as np


def slow_shard_transform(d, seq_len):
    d = d.copy()
    d["hist"] = d["hist"].map(
        lambda h: list(h)[:seq_len])  # VIOLATION rowwise-map-in-data-plane

    def pad_one(h):
        return list(h) + [0] * (seq_len - len(h))

    d["hist"] = d["hist"].map(pad_one)  # VIOLATION rowwise-map-in-data-plane
    d["total"] = d.apply(
        lambda r: np.sum(r.values),
        axis=1)  # VIOLATION rowwise-map-in-data-plane
    # NOT flagged: vectorized column ops and dict-valued map
    d["ok"] = d["hist"].map({1: 2})
    return d
