"""Seeded thread-lifecycle leak for the chaos lane (thread-leak): a
non-daemon thread that is started but never joined blocks interpreter
shutdown — exactly the hang the chaos drills' kill paths would surface
at the worst time. The daemon spawn below is the negative control.
Never imported."""

import threading


def _pump():
    while True:
        pass


def launch_pump():
    t = threading.Thread(target=_pump)  # VIOLATION thread-leak
    t.start()


class Drainer:
    def __init__(self):
        # OK: daemon threads cannot block shutdown
        self._t = threading.Thread(target=self._drain, daemon=True)
        self._t.start()

    def _drain(self):
        pass
