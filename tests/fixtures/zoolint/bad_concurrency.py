"""Seeded concurrency violations (engine-unlocked-write, lock-order).
Never imported."""

import threading


class LeakyEngine:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._run)
        self._thread.start()

    def _run(self):
        while True:
            self.count += 1  # VIOLATION engine-unlocked-write

    def reset(self):
        self.count = 0  # VIOLATION engine-unlocked-write (caller side)


class AbbaLocks:
    def __init__(self):
        self.lock_a = threading.Lock()
        self.lock_b = threading.Lock()

    def forward(self):
        with self.lock_a:
            with self.lock_b:
                pass

    def backward(self):
        with self.lock_b:
            with self.lock_a:  # VIOLATION lock-order
                pass
