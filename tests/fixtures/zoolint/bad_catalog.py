"""Seeded catalog-drift violations: a zoo_* metric and a ZOO_* env var
that docs/observability.md does not document. Never imported."""

import os


def register_bogus(registry):
    c = registry.counter("zoo_fixture_bogus_total",
                         "not in docs")  # VIOLATION metric-undocumented
    flag = os.getenv("ZOO_FIXTURE_BOGUS")  # VIOLATION envvar-undocumented
    # an autotune-family name the catalog does NOT list: proves the drift
    # check covers newly added zoo_autotune_* metrics, not a stale prefix
    g = registry.gauge("zoo_autotune_bogus_ms",
                       "not in docs")  # VIOLATION metric-undocumented
    knob = os.getenv("ZOO_AUTOTUNE_BOGUS")  # VIOLATION envvar-undocumented
    # a serving-delivery family the catalog does NOT list: the drift
    # check must flag new zoo_serving_* names (the redelivery counters
    # landed with the multi-replica contract; a typo'd sibling like this
    # one must not slide through as "close enough")
    r = registry.counter("zoo_serving_redelivered_bogus_total",
                         "not in docs")  # VIOLATION metric-undocumented
    lease = os.getenv("ZOO_SERVING_BOGUS_MS")  # VIOLATION envvar-undocumented
    # a per-lane scheduling family the catalog does NOT list: the drift
    # check must flag new lane/admission metrics (the priority-lane
    # counters landed with the SLO-aware scheduler; an undeclared
    # sibling must fire, not coast on the zoo_serving_lane_* prefix)
    d = registry.gauge("zoo_serving_lane_depth_bogus",
                       "not in docs")  # VIOLATION metric-undocumented
    wait = os.getenv(
        "ZOO_SERVING_MAX_WAIT_BOGUS_MS")  # VIOLATION envvar-undocumented
    # sharded-executor families the catalog does NOT list: the drift
    # check must flag new per-shard / decode metrics (zoo_shard_hbm_bytes
    # and the decode counters landed with the sharded seam; undeclared
    # siblings must fire, not coast on the prefix)
    s = registry.gauge("zoo_shard_hbm_bogus_bytes", ("shard",),
                      )  # VIOLATION metric-undocumented
    t = registry.counter("zoo_decode_steps_bogus_total",
                         "not in docs")  # VIOLATION metric-undocumented
    seq = os.getenv(
        "ZOO_SERVING_DECODE_BOGUS_SEQ")  # VIOLATION envvar-undocumented
    # history-store families the catalog does NOT list: the drift check
    # must flag new zoo_ts_* self-metrics and ZOO_TS_* knobs (the history
    # store landed with its own catalog rows; an undeclared sibling must
    # fire, not coast on the prefix)
    h = registry.gauge("zoo_ts_points_bogus",
                       "not in docs")  # VIOLATION metric-undocumented
    tick = os.getenv("ZOO_TS_BOGUS_TICK_S")  # VIOLATION envvar-undocumented
    # paged-attention / KV-quantization families the catalog does NOT
    # list: the drift check must flag new zoo_paged_attn_* / zoo_kv_quant_*
    # names and ZOO_KV_* knobs (the paged decode kernel + int8 pool landed
    # with their own rows; undeclared siblings must fire, not coast on the
    # prefix)
    p = registry.counter("zoo_paged_attn_bogus_total",
                         "not in docs")  # VIOLATION metric-undocumented
    q = registry.gauge("zoo_kv_quant_bogus_bytes",
                       "not in docs")  # VIOLATION metric-undocumented
    kvd = os.getenv("ZOO_KV_BOGUS_DTYPE")  # VIOLATION envvar-undocumented
    return c, flag, g, knob, r, lease, d, wait, s, t, seq, h, tick, p, q, kvd
