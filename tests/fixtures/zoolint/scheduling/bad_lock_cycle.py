"""The B->A half of the cross-file lock-order inversion seeded in
locks_shared.py (lock-order-inversion). Never imported."""

from tests.fixtures.zoolint.scheduling.locks_shared import LOCK_ALPHA, LOCK_BETA


def grab_backward():
    with LOCK_BETA:
        with LOCK_ALPHA:  # VIOLATION lock-order-inversion (cross-file)
            pass
