"""Seeded blocking-under-lock for the scheduling lane: the loop thread
sleeps while holding a lock the submit path also needs, so every
submitter stalls for the full sleep — priority lanes and deadlines
can't help a request that is stuck behind a held mutex. Never
imported."""

import threading
import time


class SleepyScheduler:
    def __init__(self):
        self._lock = threading.Lock()
        self.pending = 0
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def _loop(self):
        while True:
            with self._lock:
                time.sleep(0.5)  # VIOLATION blocking-under-lock

    def submit(self, n):
        with self._lock:
            self.pending += n
