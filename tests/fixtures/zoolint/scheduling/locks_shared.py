"""Module-level locks plus the A->B half of a cross-file lock-order
inversion — bad_lock_cycle.py imports these locks and takes them B->A,
which only the whole-program acquisition graph can see. Never
imported."""

import threading

LOCK_ALPHA = threading.Lock()
LOCK_BETA = threading.Lock()


def grab_forward():
    with LOCK_ALPHA:
        with LOCK_BETA:
            pass
