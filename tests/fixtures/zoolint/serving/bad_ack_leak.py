"""Seeded record-ack-leak violations.

Lives under a ``serving/`` path segment so the rule treats it as broker
code. Three shapes of the defect — an exception-free leak (a branch
that finishes the iteration without settling), a double settlement, and
an ack list that is never flushed — with a clean drain as the negative
control. Never imported; fixture data for dev/run-tests.sh zoolint and
tests/test_zoolint_dataflow.py.
"""


def drain_leaky(client, stream, group):
    entries = client.xreadgroup(group, "w0", {stream: ">"}, count=64)
    acks = []
    buckets = []
    # VIOLATION record-ack-leak: the `payload is None` branch continues
    # without an ack or a re-bin — that record's lease leaks forever
    for eid, payload in entries:
        if payload is None:
            continue
        if payload.get("expired"):
            acks.append(("XACK", stream, group, eid))
            continue
        buckets.append((eid, payload))
    if acks:
        client.pipeline(acks)
    return buckets


def drain_double(client, stream, group):
    entries = client.xreadgroup(group, "w0", {stream: ">"})
    acks = []
    buckets = []
    # VIOLATION record-ack-leak: every record is both re-binned and
    # acked — a crash after the flush double-serves or loses the copy
    for eid, payload in entries:
        buckets.append((eid, payload))
        acks.append(("XACK", stream, group, eid))
    client.pipeline(acks)
    return buckets


def drain_unflushed(client, stream, group):
    entries = client.xreadgroup(group, "w0", {stream: ">"})
    acks = []
    for eid, _payload in entries:
        # VIOLATION record-ack-leak: `acks` is never flushed or
        # returned — the XACKs are dropped on the floor
        acks.append(("XACK", stream, group, eid))


def drain_clean(client, stream, group):
    """Negative control: every path settles exactly once and the ack
    list flushes behind a truthiness guard."""
    entries = client.xreadgroup(group, "w0", {stream: ">"})
    acks = []
    buckets = []
    for eid, payload in entries:
        if payload is None:
            acks.append(("XACK", stream, group, eid))
            continue
        buckets.append((eid, payload))
    if acks:
        client.pipeline(acks)
    return buckets
