"""Seeded kv-page-leak violations.

Two shapes of the defect — an early return that strands an allocated
page list, and an unprotected handoff whose exception path leaks — with
clean admission/teardown shapes as the negative controls. Never
imported; fixture data for dev/run-tests.sh zoolint and
tests/test_zoolint_dataflow.py.
"""


def admit_early_return_leak(pool, cache_cls, enc, need, budget):
    # VIOLATION kv-page-leak: the over-budget branch returns without
    # freeing `pages` — they never rejoin the pool's free list
    pages = pool.alloc_pages(need)
    if need > budget:
        return None
    return cache_cls(pool, pages)


def admit_exception_leak(pool, cache_cls, validate, enc, need):
    # VIOLATION kv-page-leak: `validate` raising between the alloc and
    # the handoff propagates out with `pages` still allocated
    pages = pool.alloc_pages(need)
    validate(enc)
    return cache_cls(pool, pages)


def admit_clean(pool, cache_cls, validate, enc, need):
    """Negative control: the handoff is guarded — any exception frees
    the pages before propagating (the scheduler's admission shape)."""
    pages = pool.alloc_pages(need)
    try:
        validate(enc)
        cache = cache_cls(pool, pages)
    except Exception:
        pool.free_pages(pages)
        raise
    return cache


def retire_clean(pool, seqs):
    """Negative control: both branches settle — short sequences free
    their pages directly, the rest hand theirs to the recycle bin."""
    recycled = []
    for seq in seqs:
        pages = pool.alloc_pages(seq.need)
        if seq.short:
            pool.free_pages(pages)
        else:
            recycled.append(pages)
    return recycled
