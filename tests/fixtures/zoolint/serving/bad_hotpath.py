"""Seeded wallclock-hotpath and hotpath-host-sync violations.

Lives under a ``serving/`` path segment so zoolint classifies it as a
hot-path module. Never imported — fixture data for dev/run-tests.sh
zoolint and tests/test_zoolint.py.
"""

import time

import jax
import numpy as np


def dispatch_loop(batches, fences):
    t0 = time.time()  # VIOLATION wallclock-hotpath
    total = 0.0
    for batch in batches:  # VIOLATION hotpath-host-sync (x3 below)
        total += float(batch.loss)
        total += batch.loss.item()
        jax.block_until_ready(fences)
    host = [np.asarray(b) for b in batches]  # VIOLATION hotpath-host-sync
    return total, host, time.time() - t0  # VIOLATION wallclock-hotpath


def dispatch_sampled(batches, sampled):
    """Suppressions and sampling guards must keep this half clean."""
    t0 = time.time()  # zoolint: disable=wallclock-hotpath
    for batch in batches:
        if sampled:
            jax.block_until_ready(batch)  # guarded: not a finding
    return time.time() - t0  # zoolint: disable=wallclock-hotpath
