"""Seeded kv-page-leak violation for the paged-attention table path.

One defect shape: pages allocated for a sequence's page table are
stranded when the admission guard raises before any callee receives
them. The clean shape below hands the pages to the table builder inside
the guard, which settles them. Never imported; fixture data for
dev/run-tests.sh zoolint and tests/test_zoolint_dataflow.py.
"""


def build_table_guard_leak(pool, table_cls, seq, width, max_width):
    # VIOLATION kv-page-leak: the width guard raises with `pages` still
    # allocated — they never reach the table (which would settle them)
    # and never rejoin the pool's free list
    pages = pool.alloc_pages(width)
    if width > max_width:
        raise ValueError("sequence wider than the page-table rung")
    return table_cls(pool, pages, seq)


def build_table_clean(pool, table_cls, seq, width, max_width):
    """Negative control: guard first, allocate after — nothing to leak
    on the raise path, and the table receives the pages directly."""
    if width > max_width:
        raise ValueError("sequence wider than the page-table rung")
    return table_cls(pool, pool.alloc_pages(width), seq)
