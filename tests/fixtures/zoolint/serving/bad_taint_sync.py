"""Seeded tainted-host-sync violations.

Function names deliberately avoid the lexical rule's hot-name tokens
(dispatch/serve/step/...) so every finding here belongs to the taint
rule, not ``hotpath-host-sync`` — that is the point: the dataflow rule
follows the value into helpers the name heuristic misses. Never
imported; fixture data for dev/run-tests.sh zoolint and
tests/test_zoolint_dataflow.py.
"""

import jax
import numpy as np


def _step_impl(params, tok):
    return tok


def autoregress(params, seq, steps):
    step = jax.jit(_step_impl)
    out = seq
    host = None
    for _t in range(steps):
        out = step(params, out)
        # VIOLATION tainted-host-sync: np.asarray on the jit output
        # forces a device->host copy every iteration
        host = np.asarray(out)
        # VIOLATION tainted-host-sync: implicit truthiness on a device
        # value blocks on the transfer each iteration
        if out:
            break
    return host


def accumulate(predict_fn, batches):
    total = 0.0
    for b in batches:
        y = predict_fn(b)
        # VIOLATION tainted-host-sync: float() on the *_fn apply output
        total += float(y)
    return total


def host_math(xs):
    """Negative control: nothing here is device-tainted."""
    total = 0.0
    for x in xs:
        total += float(x)
    return total


def fenced(params, seq, steps):
    """Negative control: the single sync sits outside the loop."""
    step = jax.jit(_step_impl)
    out = seq
    for _t in range(steps):
        out = step(params, out)
    return np.asarray(out)
