"""Seeded jit-compile-in-serve-loop violations.

Hot-path (``serving/`` segment) module whose drain loop builds XLA
executables in-band — the stall the compile-ahead layer forbids. Never
imported; fixture data for dev/run-tests.sh zoolint and
tests/test_zoolint.py.
"""


def serve_drain_loop(jitted, rungs):
    exes = []
    for avals in rungs:
        # VIOLATION jit-compile-in-serve-loop (.lower with args AND the
        # chained .compile both flag)
        exes.append(jitted.lower(*avals).compile())
    return exes


def warm_up(jitted, rungs):
    """Baselined: warm-named functions are the sanctioned AOT path."""
    return [jitted.lower(*avals).compile() for avals in rungs]


def produce_names(rows):
    for r in rows:
        # str.lower() takes no args — never a finding
        yield r.name.lower()
