"""Seeded fixture package: registers ONE documented fleet metric; the
docs also declare ``zoo_fleet_ghost_total`` which nothing registers —
the scan must flag it ``metric-undeclared``."""

from analytics_zoo_tpu.common import telemetry

telemetry.get_registry().counter(
    "zoo_fleet_present_total", "Registered and documented", ("replica",))
