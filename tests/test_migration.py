"""Pretrained-weight migration recipes (VERDICT r3 missing #5): for each
model-zoo entry, torch-twin weights load into the zoo model with predict
parity on a fixture — the honest replacement for the reference's
``Net.load`` artifact formats (ref Net.scala:446)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from analytics_zoo_tpu.models import (  # noqa: E402
    NeuralCF, TextClassifier, WideAndDeep,
)
from analytics_zoo_tpu.models import migration  # noqa: E402
from analytics_zoo_tpu.models.recommendation.wide_and_deep import (  # noqa: E402
    ColumnFeatureInfo,
)


class TestNCFMigration:
    def test_torch_weights_predict_parity(self, orca_ctx):
        torch.manual_seed(0)
        kw = dict(user_count=30, item_count=40, class_num=4, user_embed=6,
                  item_embed=6, hidden_layers=(16, 8), mf_embed=5)
        twin = migration.make_torch_ncf(**kw)
        zoo = NeuralCF(**kw)
        migration.import_ncf_from_torch(zoo, twin)

        rs = np.random.RandomState(0)
        x = np.stack([rs.randint(1, 31, 64), rs.randint(1, 41, 64)],
                     axis=1).astype(np.float32)
        want = twin(torch.from_numpy(x)).detach().numpy()
        got = np.asarray(zoo.predict(x, distributed=False))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_no_mf_variant_and_state_dict_input(self, orca_ctx):
        torch.manual_seed(1)
        kw = dict(user_count=12, item_count=9, class_num=2, user_embed=4,
                  item_embed=4, hidden_layers=(8,), include_mf=False,
                  mf_embed=0)
        twin = migration.make_torch_ncf(**kw)
        zoo = NeuralCF(**kw)
        migration.import_ncf_from_torch(zoo, twin.state_dict())
        x = np.array([[1, 2], [3, 4], [11, 8]], np.float32)
        want = twin(torch.from_numpy(x)).detach().numpy()
        got = np.asarray(zoo.predict(x, distributed=False))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


class TestWideAndDeepMigration:
    def test_torch_weights_predict_parity(self, orca_ctx):
        torch.manual_seed(2)
        info = ColumnFeatureInfo(
            wide_base_cols=["a", "b"], wide_base_dims=[4, 3],
            wide_cross_cols=["c"], wide_cross_dims=[6],
            indicator_cols=["i"], indicator_dims=[3],
            embed_cols=["e1", "e2"], embed_in_dims=[7, 9],
            embed_out_dims=[2, 3], continuous_cols=["x", "y"])
        twin = migration.make_torch_wide_and_deep(2, info,
                                                  hidden_layers=(12, 6))
        zoo = WideAndDeep(class_num=2, column_info=info,
                          hidden_layers=(12, 6))
        migration.import_wide_and_deep_from_torch(zoo, twin)

        rs = np.random.RandomState(3)
        b = 32
        wide = (rs.rand(b, 13) < 0.3).astype(np.float32)
        ind = (rs.rand(b, 3) < 0.5).astype(np.float32)
        emb = np.stack([rs.randint(1, 8, b), rs.randint(1, 10, b)],
                       axis=1).astype(np.float32)
        con = rs.randn(b, 2).astype(np.float32)
        want = twin(*[torch.from_numpy(a) for a in (wide, ind, emb, con)]
                    ).detach().numpy()
        got = np.asarray(zoo.predict([wide, ind, emb, con],
                                     distributed=False))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


class TestTextClassifierMigration:
    def test_torch_weights_predict_parity(self, orca_ctx):
        torch.manual_seed(4)
        kw = dict(class_num=3, vocab_size=60, token_length=8,
                  encoder_output_dim=16)
        twin = migration.make_torch_text_classifier(**kw)
        zoo = TextClassifier(sequence_length=20, encoder="cnn", **kw)
        migration.import_text_classifier_from_torch(zoo, twin)
        rs = np.random.RandomState(5)
        ids = rs.randint(1, 61, (10, 20)).astype(np.float32)
        want = twin(torch.from_numpy(ids)).detach().numpy()
        got = np.asarray(zoo.predict(ids, distributed=False))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_lstm_encoder_rejected(self, orca_ctx):
        zoo = TextClassifier(class_num=2, vocab_size=10, token_length=4,
                             sequence_length=6, encoder="lstm",
                             encoder_output_dim=4)
        with pytest.raises(ValueError, match="cnn encoder"):
            migration.import_text_classifier_from_torch(zoo, {})


class TestAssignLayerParams:
    def test_shape_and_name_validation(self, orca_ctx):
        zoo = NeuralCF(user_count=5, item_count=5, class_num=2,
                       user_embed=3, item_embed=3, hidden_layers=(4,),
                       include_mf=False, mf_embed=0)
        with pytest.raises(KeyError, match="nope"):
            migration.assign_layer_params(zoo.model,
                                          {"nope": {"kernel": np.zeros(1)}})
        with pytest.raises(ValueError, match="shape"):
            migration.assign_layer_params(
                zoo.model, {"dense_1": {"kernel": np.zeros((2, 2))}})

    def test_training_continues_after_import(self, orca_ctx):
        """Imported weights are a valid starting point for further fit
        (fine-tune path a migrating user follows)."""
        torch.manual_seed(6)
        kw = dict(user_count=15, item_count=15, class_num=2, user_embed=4,
                  item_embed=4, hidden_layers=(8,), mf_embed=4)
        twin = migration.make_torch_ncf(**kw)
        zoo = NeuralCF(**kw)
        migration.import_ncf_from_torch(zoo, twin)
        rs = np.random.RandomState(7)
        x = np.stack([rs.randint(1, 16, 64), rs.randint(1, 16, 64)],
                     axis=1).astype(np.float32)
        y = rs.randint(0, 2, 64)
        zoo.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
        h = zoo.fit(x, y, batch_size=32, nb_epoch=2)
        assert np.isfinite(h["loss"]).all()

    def test_patch_after_fit_keeps_trained_weights(self, orca_ctx):
        """assign_layer_params after a fit must sync the TRAINED state
        first — patching one layer leaves the others' trained values."""
        import jax
        torch.manual_seed(8)
        zoo = NeuralCF(user_count=10, item_count=10, class_num=2,
                       user_embed=4, item_embed=4, hidden_layers=(8,),
                       include_mf=False, mf_embed=0)
        rs = np.random.RandomState(9)
        x = np.stack([rs.randint(1, 11, 64), rs.randint(1, 11, 64)],
                     axis=1).astype(np.float32)
        y = rs.randint(0, 2, 64)
        zoo.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
        zoo.fit(x, y, batch_size=32, nb_epoch=2)
        est = zoo.model._ensure_estimator()
        trained_emb = np.asarray(jax.device_get(
            est._state["params"]["mlp_user_embed"]["embedding"]))
        new_head = np.zeros_like(np.asarray(
            jax.device_get(est._state["params"]["dense_2"]["kernel"])))
        migration.assign_layer_params(zoo.model,
                                      {"dense_2": {"kernel": new_head}})
        params = zoo.model._ensure_estimator().adapter.params
        np.testing.assert_allclose(params["mlp_user_embed"]["embedding"],
                                   trained_emb)
        np.testing.assert_allclose(params["dense_2"]["kernel"], new_head)
