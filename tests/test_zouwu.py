"""Tests for Zouwu time-series (mirrors ref pyzoo/test/zoo/zouwu/)."""

import numpy as np
import pandas as pd
import pytest

from analytics_zoo_tpu.zouwu.feature import TimeSequenceFeatureTransformer
from analytics_zoo_tpu.zouwu.model.forecast import (
    LSTMForecaster, MTNetForecaster, Seq2SeqForecaster, TCNForecaster,
)
from analytics_zoo_tpu.zouwu.model.anomaly import (
    AEDetector, DBScanDetector, ThresholdDetector,
)
from analytics_zoo_tpu.zouwu.model.tcmf import TCMFForecaster


def sine_df(n=200, freq="h"):
    t = pd.date_range("2024-01-01", periods=n, freq=freq)
    rng = np.random.RandomState(0)
    v = np.sin(np.arange(n) * 2 * np.pi / 24) + rng.normal(0, 0.05, n)
    return pd.DataFrame({"datetime": t, "value": v})


class TestFeatureTransformer:
    def test_fit_transform_shapes(self):
        tf = TimeSequenceFeatureTransformer(past_seq_len=24, future_seq_len=3)
        x, y = tf.fit_transform(sine_df())
        assert x.shape == (200 - 24 - 3 + 1, 24, tf.n_features)
        assert y.shape == (174, 3)
        assert x.dtype == np.float32

    def test_scaling_and_unscale(self):
        tf = TimeSequenceFeatureTransformer(past_seq_len=10, future_seq_len=1)
        df = sine_df()
        x, y = tf.fit_transform(df)
        assert x[..., 0].min() >= 0.0 and x[..., 0].max() <= 1.0
        back = tf.unscale_y(y)
        lo, hi = df["value"].min(), df["value"].max()
        assert back.min() == pytest.approx(lo, abs=1e-4) or back.min() >= lo - 1e-4

    def test_transform_uses_train_scale(self):
        tf = TimeSequenceFeatureTransformer(past_seq_len=10)
        train, test = sine_df(150), sine_df(60)
        tf.fit_transform(train)
        x, y = tf.transform(test)
        assert x.shape[1] == 10
        x_only = tf.transform(test, with_y=False)
        # without labels the last horizon rows also yield windows
        assert x_only.shape[0] == x.shape[0] + tf.future_seq_len
        assert x_only.shape[1:] == x.shape[1:]

    def test_extra_features_and_no_dt(self):
        df = sine_df()
        df["extra"] = np.arange(len(df), dtype=float)
        tf = TimeSequenceFeatureTransformer(
            past_seq_len=8, extra_features_col=["extra"],
            with_dt_features=False)
        x, y = tf.fit_transform(df)
        assert x.shape[-1] == 2

    def test_save_restore(self, tmp_path):
        tf = TimeSequenceFeatureTransformer(past_seq_len=12, future_seq_len=2)
        tf.fit_transform(sine_df())
        tf.save(str(tmp_path / "tf"))
        tf2 = TimeSequenceFeatureTransformer()
        tf2.restore(str(tmp_path / "tf"))
        assert tf2.past_seq_len == 12 and tf2.future_seq_len == 2
        x, y = tf2.transform(sine_df(80))
        assert x.shape[1] == 12

    def test_selected_features_subset(self):
        df = sine_df()
        df["extra"] = np.arange(len(df), dtype=float)
        full = TimeSequenceFeatureTransformer(
            past_seq_len=8, extra_features_col=["extra"])
        assert full.all_available_features == \
            ["extra", "HOUR", "DAY", "DAYOFWEEK", "MONTH", "IS_WEEKEND"]
        sel = TimeSequenceFeatureTransformer(
            past_seq_len=8, extra_features_col=["extra"],
            selected_features=["HOUR", "IS_WEEKEND"])
        x, y = sel.fit_transform(df)
        # target + 2 selected
        assert x.shape[-1] == 3
        assert sel.feature_names == ["value", "HOUR", "IS_WEEKEND"]
        # selected column values match the full matrix's columns
        xf, _ = full.fit_transform(df)
        hour_full = full.feature_names.index("HOUR")
        np.testing.assert_allclose(x[..., 1], xf[..., hour_full], atol=1e-6)

    def test_selected_features_validation_and_restore(self, tmp_path):
        with pytest.raises(ValueError, match="unknown selected_features"):
            TimeSequenceFeatureTransformer(selected_features=["NOPE"])
        tf = TimeSequenceFeatureTransformer(
            past_seq_len=8, selected_features=["HOUR"])
        tf.fit_transform(sine_df())
        tf.save(str(tmp_path / "tf"))
        tf2 = TimeSequenceFeatureTransformer()
        tf2.restore(str(tmp_path / "tf"))
        assert tf2.selected_features == ["HOUR"]
        assert tf2.transform(sine_df(40), with_y=False).shape[-1] == 2


def _xy(n=96, lookback=16, horizon=2, feats=3):
    rng = np.random.RandomState(0)
    x = rng.normal(size=(n, lookback, feats)).astype(np.float32)
    y = x[:, -horizon:, 0] * 0.5 + 0.1
    return x, y.astype(np.float32)


class TestForecasters:
    def test_lstm_forecaster(self):
        x, y = _xy(horizon=1)
        f = LSTMForecaster(target_dim=1, lstm_units=(8,), dropouts=(0.0,))
        hist = f.fit(x, y[:, :1], epochs=2, batch_size=16)
        assert len(hist["loss"]) == 2
        pred = f.predict(x)
        assert pred.shape == (len(x), 1)
        ev = f.evaluate(x, y[:, :1], metrics=["mse", "mae", "smape"])
        assert set(ev) == {"mse", "mae", "smape"}

    def test_tcn_forecaster_learns(self):
        from analytics_zoo_tpu.learn.optimizers import Adam
        x, y = _xy(n=128, horizon=2)
        f = TCNForecaster(future_seq_len=2, num_channels=(8, 8),
                          kernel_size=3, dropout=0.0,
                          optimizer=Adam(learningrate=0.01))
        f.fit(x, y, epochs=20, batch_size=16)
        final = f.evaluate(x, y)["mse"]
        assert final < 0.05  # learnable linear map

    def test_seq2seq_forecaster(self):
        x, y = _xy(horizon=3)
        f = Seq2SeqForecaster(future_seq_len=3, latent_dim=8, dropout=0.0)
        f.fit(x, y, epochs=2, batch_size=16)
        assert f.predict(x).shape == (len(x), 3)

    def test_mtnet_forecaster(self):
        # seq len must be (n+1)*T = (3+1)*4 = 16
        x, y = _xy(n=64, lookback=16, horizon=1)
        f = MTNetForecaster(future_seq_len=1, long_series_num=3,
                            series_length=4, cnn_hid_size=8, rnn_hid_size=8,
                            ar_window=3)
        f.fit(x, y[:, :1], epochs=2, batch_size=16)
        assert f.predict(x).shape == (len(x), 1)

    def test_save_restore_roundtrip(self, tmp_path):
        x, y = _xy(horizon=1)
        f = TCNForecaster(future_seq_len=1, num_channels=(4,), kernel_size=3)
        f.fit(x, y[:, :1], epochs=1, batch_size=16)
        p1 = f.predict(x)
        f.save(str(tmp_path / "m"))
        g = TCNForecaster(future_seq_len=1, num_channels=(4,), kernel_size=3)
        g.restore(str(tmp_path / "m"), sample_x=x)
        np.testing.assert_allclose(p1, g.predict(x), rtol=1e-5, atol=1e-5)


class TestTCMF:
    def test_fit_predict(self):
        rng = np.random.RandomState(0)
        t = np.arange(120)
        basis = np.stack([np.sin(t * 2 * np.pi / 24),
                          np.cos(t * 2 * np.pi / 24)])
        F = rng.normal(size=(20, 2))
        y = F @ basis + rng.normal(0, 0.01, (20, 120))
        m = TCMFForecaster(k=4, ar_order=24, lr=0.05)
        mse = m.fit(y[:, :96], num_steps=400)
        assert mse < 0.1
        pred = m.predict(horizon=24)
        assert pred.shape == (20, 24)
        # forecast should track the periodic structure reasonably
        assert np.mean((pred - y[:, 96:]) ** 2) < np.mean(y[:, 96:] ** 2)


    def test_fit_incremental_extends_basis(self):
        """New observations update X in closed form with F fixed (ref
        TCMF.fit_incremental) — forecasts then start from the new tail."""
        rng = np.random.RandomState(1)
        t = np.arange(144)
        basis = np.stack([np.sin(t * 2 * np.pi / 24),
                          np.cos(t * 2 * np.pi / 24)])
        F = rng.normal(size=(12, 2))
        y = (F @ basis + rng.normal(0, 0.01, (12, 144))).astype(np.float32)
        m = TCMFForecaster(k=4, ar_order=24, lr=0.05)
        m.fit(y[:, :96], num_steps=400)
        t0 = m.X.shape[1]
        m.fit_incremental(y[:, 96:120])
        assert m.X.shape[1] == t0 + 24
        # the new columns reconstruct the new data well
        recon = m.F @ m.X[:, -24:]
        assert np.mean((recon - y[:, 96:120]) ** 2) < 0.1
        pred = m.predict(horizon=24)
        assert np.mean((pred - y[:, 120:]) ** 2) < np.mean(y[:, 120:] ** 2)
        with pytest.raises(ValueError, match="n_series"):
            m.fit_incremental(np.zeros((5, 4), np.float32))

    def test_hybrid_local_model(self, orca_ctx):
        """use_local=True trains the DeepGLO-style residual TCN and its
        refinement rides on top of the global forecast."""
        rng = np.random.RandomState(2)
        t = np.arange(120)
        basis = np.sin(t * 2 * np.pi / 24)[None]
        F = rng.normal(size=(6, 1))
        y = (F @ basis + 0.02 * rng.standard_normal((6, 120))
             ).astype(np.float32)
        m = TCMFForecaster(k=2, ar_order=24, use_local=True,
                           local_lookback=12)
        m.fit(y[:, :96], num_steps=300)
        assert m._local is not None
        pred = m.predict(horizon=24)
        assert pred.shape == (6, 24)
        assert np.isfinite(pred).all()


class TestAnomaly:
    def test_threshold_detector(self):
        rng = np.random.RandomState(0)
        y = rng.normal(0, 1, 500)
        y[[50, 300]] += 12.0
        det = ThresholdDetector(ratio=4.0).fit(y)
        idx = det.anomaly_indexes(y)
        assert set([50, 300]).issubset(set(idx.tolist()))

    def test_threshold_with_forecast(self):
        y = np.zeros(100)
        y_pred = np.zeros(100)
        y[10] = 5.0
        det = ThresholdDetector(threshold=1.0)
        assert det.anomaly_indexes(y, y_pred).tolist() == [10]

    def test_ae_detector(self):
        rng = np.random.RandomState(0)
        y = np.sin(np.arange(300) * 2 * np.pi / 24) + rng.normal(0, 0.02, 300)
        y[150:153] += 6.0
        det = AEDetector(roll_len=12, hidden=(8, 4), anomaly_ratio=0.03,
                         epochs=4)
        det.fit(y)
        idx = det.anomaly_indexes(y)
        assert any(148 <= i <= 155 for i in idx)

    def test_dbscan_detector(self):
        y = np.concatenate([np.zeros(100), [10.0], np.zeros(100)])
        idx = DBScanDetector(eps=0.5, min_samples=3).anomaly_indexes(y)
        assert 100 in idx
