"""Tests for Zouwu time-series (mirrors ref pyzoo/test/zoo/zouwu/)."""

import numpy as np
import pandas as pd
import pytest

from analytics_zoo_tpu.zouwu.feature import TimeSequenceFeatureTransformer
from analytics_zoo_tpu.zouwu.model.forecast import (
    LSTMForecaster, MTNetForecaster, Seq2SeqForecaster, TCNForecaster,
)
from analytics_zoo_tpu.zouwu.model.anomaly import (
    AEDetector, DBScanDetector, ThresholdDetector,
)
from analytics_zoo_tpu.zouwu.model.tcmf import TCMFForecaster


def sine_df(n=200, freq="h"):
    t = pd.date_range("2024-01-01", periods=n, freq=freq)
    rng = np.random.RandomState(0)
    v = np.sin(np.arange(n) * 2 * np.pi / 24) + rng.normal(0, 0.05, n)
    return pd.DataFrame({"datetime": t, "value": v})


class TestFeatureTransformer:
    def test_fit_transform_shapes(self):
        tf = TimeSequenceFeatureTransformer(past_seq_len=24, future_seq_len=3)
        x, y = tf.fit_transform(sine_df())
        assert x.shape == (200 - 24 - 3 + 1, 24, tf.n_features)
        assert y.shape == (174, 3)
        assert x.dtype == np.float32

    def test_scaling_and_unscale(self):
        tf = TimeSequenceFeatureTransformer(past_seq_len=10, future_seq_len=1)
        df = sine_df()
        x, y = tf.fit_transform(df)
        assert x[..., 0].min() >= 0.0 and x[..., 0].max() <= 1.0
        back = tf.unscale_y(y)
        lo, hi = df["value"].min(), df["value"].max()
        assert back.min() == pytest.approx(lo, abs=1e-4) or back.min() >= lo - 1e-4

    def test_transform_uses_train_scale(self):
        tf = TimeSequenceFeatureTransformer(past_seq_len=10)
        train, test = sine_df(150), sine_df(60)
        tf.fit_transform(train)
        x, y = tf.transform(test)
        assert x.shape[1] == 10
        x_only = tf.transform(test, with_y=False)
        # without labels the last horizon rows also yield windows
        assert x_only.shape[0] == x.shape[0] + tf.future_seq_len
        assert x_only.shape[1:] == x.shape[1:]

    def test_extra_features_and_no_dt(self):
        df = sine_df()
        df["extra"] = np.arange(len(df), dtype=float)
        tf = TimeSequenceFeatureTransformer(
            past_seq_len=8, extra_features_col=["extra"],
            with_dt_features=False)
        x, y = tf.fit_transform(df)
        assert x.shape[-1] == 2

    def test_save_restore(self, tmp_path):
        tf = TimeSequenceFeatureTransformer(past_seq_len=12, future_seq_len=2)
        tf.fit_transform(sine_df())
        tf.save(str(tmp_path / "tf"))
        tf2 = TimeSequenceFeatureTransformer()
        tf2.restore(str(tmp_path / "tf"))
        assert tf2.past_seq_len == 12 and tf2.future_seq_len == 2
        x, y = tf2.transform(sine_df(80))
        assert x.shape[1] == 12

    def test_selected_features_subset(self):
        df = sine_df()
        df["extra"] = np.arange(len(df), dtype=float)
        full = TimeSequenceFeatureTransformer(
            past_seq_len=8, extra_features_col=["extra"])
        assert full.all_available_features == \
            ["extra", "HOUR", "DAY", "DAYOFWEEK", "MONTH", "IS_WEEKEND"]
        sel = TimeSequenceFeatureTransformer(
            past_seq_len=8, extra_features_col=["extra"],
            selected_features=["HOUR", "IS_WEEKEND"])
        x, y = sel.fit_transform(df)
        # target + 2 selected
        assert x.shape[-1] == 3
        assert sel.feature_names == ["value", "HOUR", "IS_WEEKEND"]
        # selected column values match the full matrix's columns
        xf, _ = full.fit_transform(df)
        hour_full = full.feature_names.index("HOUR")
        np.testing.assert_allclose(x[..., 1], xf[..., hour_full], atol=1e-6)

    def test_selected_features_validation_and_restore(self, tmp_path):
        with pytest.raises(ValueError, match="unknown selected_features"):
            TimeSequenceFeatureTransformer(selected_features=["NOPE"])
        tf = TimeSequenceFeatureTransformer(
            past_seq_len=8, selected_features=["HOUR"])
        tf.fit_transform(sine_df())
        tf.save(str(tmp_path / "tf"))
        tf2 = TimeSequenceFeatureTransformer()
        tf2.restore(str(tmp_path / "tf"))
        assert tf2.selected_features == ["HOUR"]
        assert tf2.transform(sine_df(40), with_y=False).shape[-1] == 2


def _xy(n=96, lookback=16, horizon=2, feats=3):
    rng = np.random.RandomState(0)
    x = rng.normal(size=(n, lookback, feats)).astype(np.float32)
    y = x[:, -horizon:, 0] * 0.5 + 0.1
    return x, y.astype(np.float32)


class TestForecasters:
    def test_lstm_forecaster(self):
        x, y = _xy(horizon=1)
        f = LSTMForecaster(target_dim=1, lstm_units=(8,), dropouts=(0.0,))
        hist = f.fit(x, y[:, :1], epochs=2, batch_size=16)
        assert len(hist["loss"]) == 2
        pred = f.predict(x)
        assert pred.shape == (len(x), 1)
        ev = f.evaluate(x, y[:, :1], metrics=["mse", "mae", "smape"])
        assert set(ev) == {"mse", "mae", "smape"}

    def test_tcn_forecaster_learns(self):
        from analytics_zoo_tpu.learn.optimizers import Adam
        x, y = _xy(n=128, horizon=2)
        f = TCNForecaster(future_seq_len=2, num_channels=(8, 8),
                          kernel_size=3, dropout=0.0,
                          optimizer=Adam(learningrate=0.01))
        f.fit(x, y, epochs=20, batch_size=16)
        final = f.evaluate(x, y)["mse"]
        assert final < 0.05  # learnable linear map

    def test_tcn_forecaster_mixed_bfloat16(self):
        """dtype="mixed_bfloat16": bf16 compute, fp32 params, still
        learns the linear map (loss tail is fp32)."""
        import jax
        from analytics_zoo_tpu.learn.optimizers import Adam
        x, y = _xy(n=128, horizon=2)
        f = TCNForecaster(future_seq_len=2, num_channels=(8, 8),
                          kernel_size=3, dropout=0.0,
                          optimizer=Adam(learningrate=0.01),
                          dtype="mixed_bfloat16")
        f.fit(x, y, epochs=20, batch_size=16)
        assert f.evaluate(x, y)["mse"] < 0.08
        import numpy as _np
        kinds = {_np.asarray(p).dtype
                 for p in jax.tree_util.tree_leaves(
                     f._est._state["params"])}
        assert kinds == {_np.dtype("float32")}, kinds

    def test_forecaster_rejects_unknown_dtype(self):
        with pytest.raises(ValueError, match="unknown dtype"):
            LSTMForecaster(dtype="float16")

    def test_mixed_predict_returns_fp32(self):
        """bf16 hidden compute must not leak ml_dtypes.bfloat16 into
        user-facing forecasts (fp32 output head)."""
        x, y = _xy(horizon=1)
        f = LSTMForecaster(target_dim=1, lstm_units=(8,), dropouts=(0.0,),
                           dtype="mixed_bfloat16")
        f.fit(x, y[:, :1], epochs=1, batch_size=16)
        assert f.predict(x).dtype == np.float32

    @pytest.mark.slow  # ~17s: trains MTNet under the bf16 policy
    def test_mtnet_mixed_precision(self):
        """MTNet under mixed_bfloat16: attention-GRU encoders run bf16,
        params stay fp32, forecasts come back fp32, and it still fits."""
        import jax
        x, y = _xy(n=64, lookback=16, horizon=1)
        f = MTNetForecaster(future_seq_len=1, long_num=3, time_step=4,
                            cnn_height=2, ar_window=2,
                            cnn_dropout=0.0, rnn_dropout=0.0,
                            dtype="mixed_bfloat16")
        h = f.fit(x, y, epochs=3, batch_size=16)
        assert h["loss"][-1] < h["loss"][0]
        pred = f.predict(x)
        assert pred.dtype == np.float32 and pred.shape == (len(x), 1)
        kinds = {np.asarray(p).dtype for p in jax.tree_util.tree_leaves(
            f._est._state["params"])}
        assert kinds == {np.dtype("float32")}, kinds

    def test_seq2seq_forecaster(self):
        x, y = _xy(horizon=3)
        f = Seq2SeqForecaster(future_seq_len=3, latent_dim=8, dropout=0.0)
        f.fit(x, y, epochs=2, batch_size=16)
        assert f.predict(x).shape == (len(x), 3)

    @pytest.mark.slow  # ~13s: full MTNet fit/predict cycle
    def test_mtnet_forecaster(self):
        # seq len must be (n+1)*T = (3+1)*4 = 16
        x, y = _xy(n=64, lookback=16, horizon=1)
        f = MTNetForecaster(future_seq_len=1, long_series_num=3,
                            series_length=4, cnn_hid_size=8, rnn_hid_size=8,
                            ar_window=3)
        f.fit(x, y[:, :1], epochs=2, batch_size=16)
        assert f.predict(x).shape == (len(x), 1)

    def test_save_restore_roundtrip(self, tmp_path):
        x, y = _xy(horizon=1)
        f = TCNForecaster(future_seq_len=1, num_channels=(4,), kernel_size=3)
        f.fit(x, y[:, :1], epochs=1, batch_size=16)
        p1 = f.predict(x)
        f.save(str(tmp_path / "m"))
        g = TCNForecaster(future_seq_len=1, num_channels=(4,), kernel_size=3)
        g.restore(str(tmp_path / "m"), sample_x=x)
        np.testing.assert_allclose(p1, g.predict(x), rtol=1e-5, atol=1e-5)


class TestTCMF:
    def test_fit_predict(self):
        rng = np.random.RandomState(0)
        t = np.arange(120)
        basis = np.stack([np.sin(t * 2 * np.pi / 24),
                          np.cos(t * 2 * np.pi / 24)])
        F = rng.normal(size=(20, 2))
        y = F @ basis + rng.normal(0, 0.01, (20, 120))
        m = TCMFForecaster(k=4, ar_order=24, lr=0.05)
        mse = m.fit(y[:, :96], num_steps=400)
        assert mse < 0.1
        pred = m.predict(horizon=24)
        assert pred.shape == (20, 24)
        # forecast should track the periodic structure reasonably
        assert np.mean((pred - y[:, 96:]) ** 2) < np.mean(y[:, 96:] ** 2)


    def test_fit_incremental_extends_basis(self):
        """New observations update X in closed form with F fixed (ref
        TCMF.fit_incremental) — forecasts then start from the new tail."""
        rng = np.random.RandomState(1)
        t = np.arange(144)
        basis = np.stack([np.sin(t * 2 * np.pi / 24),
                          np.cos(t * 2 * np.pi / 24)])
        F = rng.normal(size=(12, 2))
        y = (F @ basis + rng.normal(0, 0.01, (12, 144))).astype(np.float32)
        m = TCMFForecaster(k=4, ar_order=24, lr=0.05)
        m.fit(y[:, :96], num_steps=400)
        t0 = m.X.shape[1]
        m.fit_incremental(y[:, 96:120])
        assert m.X.shape[1] == t0 + 24
        # the new columns reconstruct the new data well
        recon = m.F @ m.X[:, -24:]
        assert np.mean((recon - y[:, 96:120]) ** 2) < 0.1
        pred = m.predict(horizon=24)
        assert np.mean((pred - y[:, 120:]) ** 2) < np.mean(y[:, 120:] ** 2)
        with pytest.raises(ValueError, match="n_series"):
            m.fit_incremental(np.zeros((5, 4), np.float32))

    def test_hybrid_local_model(self, orca_ctx):
        """use_local=True trains the DeepGLO-style residual TCN and its
        refinement rides on top of the global forecast."""
        rng = np.random.RandomState(2)
        t = np.arange(120)
        basis = np.sin(t * 2 * np.pi / 24)[None]
        F = rng.normal(size=(6, 1))
        y = (F @ basis + 0.02 * rng.standard_normal((6, 120))
             ).astype(np.float32)
        m = TCMFForecaster(k=2, ar_order=24, use_local=True,
                           local_lookback=12)
        m.fit(y[:, :96], num_steps=300)
        assert m._local is not None
        pred = m.predict(horizon=24)
        assert pred.shape == (6, 24)
        assert np.isfinite(pred).all()


class TestAnomaly:
    def test_threshold_detector(self):
        rng = np.random.RandomState(0)
        y = rng.normal(0, 1, 500)
        y[[50, 300]] += 12.0
        det = ThresholdDetector(ratio=4.0).fit(y)
        idx = det.anomaly_indexes(y)
        assert set([50, 300]).issubset(set(idx.tolist()))

    def test_threshold_with_forecast(self):
        y = np.zeros(100)
        y_pred = np.zeros(100)
        y[10] = 5.0
        det = ThresholdDetector(threshold=1.0)
        assert det.anomaly_indexes(y, y_pred).tolist() == [10]

    def test_ae_detector(self):
        rng = np.random.RandomState(0)
        y = np.sin(np.arange(300) * 2 * np.pi / 24) + rng.normal(0, 0.02, 300)
        y[150:153] += 6.0
        det = AEDetector(roll_len=12, hidden=(8, 4), anomaly_ratio=0.03,
                         epochs=4)
        det.fit(y)
        idx = det.anomaly_indexes(y)
        assert any(148 <= i <= 155 for i in idx)

    def test_dbscan_detector(self):
        y = np.concatenate([np.zeros(100), [10.0], np.zeros(100)])
        idx = DBScanDetector(eps=0.5, min_samples=3).anomaly_indexes(y)
        assert 100 in idx


class TestTCMFDistributed:
    """TCMF at reference scale (VERDICT r3 missing #3): series sharded over
    the mesh, 10k-series fit, XShards input, rolling evaluation, save/load
    (ref tcmf_forecaster.py + tcmf_model.py XShards/Ray distribution)."""

    @staticmethod
    def _panel(n, t_total, seed=0, k_true=3):
        rng = np.random.RandomState(seed)
        t = np.arange(t_total)
        basis = np.stack([np.sin(t * 2 * np.pi / 24),
                          np.cos(t * 2 * np.pi / 24),
                          0.01 * t])[:k_true]
        F = rng.normal(size=(n, k_true))
        return (F @ basis + rng.normal(0, 0.01, (n, t_total))
                ).astype(np.float32)

    def test_mesh_sharded_10k_series(self, orca_ctx):
        """10,000 series factorize in ONE sharded dispatch over all 8
        devices, and forecast quality matches the in-memory path."""
        y = self._panel(10_000, 120, seed=3)
        m = TCMFForecaster(k=4, ar_order=24, lr=0.05)
        mse = m.fit(y[:, :96], num_steps=300, distributed=True)
        assert m.fit_report["sharded"] is True
        assert m.fit_report["devices_used"] == 8
        assert m.fit_report["n_series"] == 10_000
        assert mse < 0.1
        pred = m.predict(horizon=24)
        assert pred.shape == (10_000, 24)
        future = y[:, 96:]
        assert np.mean((pred - future) ** 2) < np.mean(future ** 2)

        # distributed == single-device math (same seed/init, collectives
        # only change reduction order)
        m1 = TCMFForecaster(k=4, ar_order=24, lr=0.05)
        sub = y[:256]
        m1.fit(sub[:, :96], num_steps=300, distributed=False)
        m2 = TCMFForecaster(k=4, ar_order=24, lr=0.05)
        m2.fit(sub[:, :96], num_steps=300, distributed=True)
        np.testing.assert_allclose(m1.predict(8), m2.predict(8),
                                   rtol=0.05, atol=0.05)

    def test_xshards_input_and_ref_formats(self, orca_ctx):
        """fit accepts {'id','y'} dicts and XShards of them (the reference
        input contract), switching on the sharded path for XShards."""
        from analytics_zoo_tpu.data.shard import HostXShards
        y = self._panel(64, 96, seed=4)
        ids = np.arange(64)
        shards = HostXShards([
            {"id": ids[i:i + 16], "y": y[i:i + 16]}
            for i in range(0, 64, 16)])
        m = TCMFForecaster(k=4, ar_order=24)
        m.fit(shards, num_steps=200)
        assert m.is_xshards_distributed()
        assert m.fit_report["sharded"] is True
        assert m.predict(12).shape == (64, 12)

        m2 = TCMFForecaster(k=4, ar_order=24)
        m2.fit({"id": ids, "y": y}, num_steps=50)
        assert not m2.is_xshards_distributed()

    def test_rolling_evaluate(self, orca_ctx):
        """Rolling-origin evaluation absorbs actuals via fit_incremental
        between origins; the basis grows accordingly."""
        y = self._panel(32, 192, seed=5)
        m = TCMFForecaster(k=4, ar_order=24)
        m.fit(y[:, :96], num_steps=300)
        t0 = m.X.shape[1]
        results = m.rolling_evaluate(y[:, 96:168], horizon=24,
                                     metrics=("mse", "smape"))
        assert [r["origin"] for r in results] == [0, 24, 48]
        assert all(np.isfinite(r["mse"]) for r in results)
        assert m.X.shape[1] == t0 + 72
        naive = np.mean(y[:, 96:168] ** 2)
        assert results[0]["mse"] < naive

    def test_normalize_svd_save_load(self, orca_ctx, tmp_path):
        """normalize + svd init paths (ref DeepGLO.py:521-528 / svd flag),
        save/load round-trip preserves forecasts."""
        y = self._panel(24, 96, seed=6) * 5.0 + 100.0  # offset/scale
        m = TCMFForecaster(k=4, ar_order=24, normalize=True, svd=True)
        mse = m.fit(y[:, :72], num_steps=300)
        assert np.isfinite(mse)
        pred = m.predict(24)
        # forecasts live on the ORIGINAL scale
        assert abs(float(np.mean(pred)) - float(np.mean(y[:, 72:]))) < 20.0
        m.save(str(tmp_path / "tcmf"))
        m2 = TCMFForecaster.load(str(tmp_path / "tcmf"))
        np.testing.assert_allclose(m2.predict(24), pred, rtol=1e-5)
        assert np.mean((pred - y[:, 72:]) ** 2) < np.mean(
            (y[:, 72:] - y[:, 72:].mean()) ** 2) * 2

    def test_seasonal_period_regressor(self, orca_ctx):
        """period= adds a seasonal lag to the basis AR (ref use_time/
        period) — on strongly periodic data it must not hurt."""
        y = self._panel(16, 144, seed=7, k_true=2)
        m = TCMFForecaster(k=4, ar_order=8, period=24)
        m.fit(y[:, :120], num_steps=300)
        pred = m.predict(24)
        future = y[:, 120:]
        assert np.mean((pred - future) ** 2) < np.mean(future ** 2)

    def test_covariates_paths(self, orca_ctx):
        """Covariate-fitted models: fit_incremental demands aligned
        covariates_incr, predict honors known future_covariates."""
        rng = np.random.RandomState(8)
        t_total = 144
        cov = np.sin(np.arange(t_total) * 2 * np.pi / 12)[None]  # [1, T]
        base = self._panel(8, t_total, seed=8, k_true=2)
        y = base + 2.0 * cov  # series strongly driven by the covariate
        m = TCMFForecaster(k=4, ar_order=8)
        m.fit(y[:, :96], num_steps=300, covariates=cov[:, :96])
        with pytest.raises(ValueError, match="covariates_incr"):
            m.fit_incremental(y[:, 96:120])
        m.fit_incremental(y[:, 96:120], covariates_incr=cov[:, 96:120])
        assert m._covariates.shape[1] == 120
        with pytest.raises(ValueError, match="future_covariates"):
            m.predict(24, future_covariates=np.zeros((3, 24)))
        p_known = m.predict(24, future_covariates=cov[:, 120:144])
        p_held = m.predict(24)
        future = y[:, 120:]
        # supplying the true future covariate must not be worse
        assert np.mean((p_known - future) ** 2) <= \
            np.mean((p_held - future) ** 2) * 1.5
        assert p_known.shape == (8, 24)

    def test_use_local_save_load_roundtrip(self, orca_ctx, tmp_path):
        """save/load preserves the DeepGLO local residual TCN — forecasts
        identical after restore."""
        y = self._panel(6, 96, seed=9, k_true=1)
        m = TCMFForecaster(k=2, ar_order=24, use_local=True,
                           local_lookback=12)
        m.fit(y[:, :84], num_steps=200)
        assert m._local is not None
        p1 = m.predict(12)
        m.save(str(tmp_path / "glo"))
        m2 = TCMFForecaster.load(str(tmp_path / "glo"))
        assert m2._local is not None
        np.testing.assert_allclose(m2.predict(12), p1, rtol=1e-4, atol=1e-5)

    def test_ref_epoch_kwargs(self, orca_ctx):
        """init_FX_epoch + alt_iters*max_FX_epoch set the step budget;
        unknown kwargs raise."""
        y = self._panel(8, 64, seed=10)
        m = TCMFForecaster(k=2)
        m.fit(y, init_FX_epoch=20, alt_iters=2, max_FX_epoch=40)
        assert m.fit_report["num_steps"] == 100
        with pytest.raises(TypeError, match="max_FX_epochs"):
            m.fit(y, max_FX_epochs=10)


class TestMTNetFidelity:
    """MTNet at the reference's hyperparameter surface and architecture
    (VERDICT r3 weak #5; ref MTNet_keras.py: three attention-GRU encoders,
    stacked rnn_hid_sizes, valid-padding full-width CNN, all-features AR
    highway)."""

    def _data(self, n=192, long_num=3, time_step=6, feats=2, horizon=2,
              seed=0):
        rng = np.random.RandomState(seed)
        total = (long_num + 1) * time_step
        t = np.arange(n + total + horizon)
        sig = np.stack([np.sin(t * 2 * np.pi / 12),
                        np.cos(t * 2 * np.pi / 12)], -1)[None] \
            + 0.02 * rng.standard_normal((1, len(t), feats))
        xs = np.stack([sig[0, i:i + total] for i in range(n)])
        ys = np.stack([sig[0, i + total:i + total + horizon, 0]
                       for i in range(n)])
        return xs.astype(np.float32), ys.astype(np.float32)

    def test_ref_hyperparameter_surface(self, orca_ctx):
        """Reference names (time_step/long_num/cnn_height/rnn_hid_sizes/
        cnn_dropout/rnn_dropout) build and predict the right shapes,
        including stacked GRU sizes and cnn_height > 1."""
        from analytics_zoo_tpu.zouwu.model.forecast import MTNetForecaster
        xs, ys = self._data()
        f = MTNetForecaster(future_seq_len=2, time_step=6, long_num=3,
                            cnn_height=3, cnn_hid_size=8,
                            rnn_hid_sizes=[4, 8], cnn_dropout=0.1,
                            rnn_dropout=0.1)
        f.fit(xs, ys, epochs=1, batch_size=32)
        assert f.predict(xs[:5]).shape == (5, 2)

    def test_ar_window_zero_disables_linear(self, orca_ctx):
        """ar_window=0 drops the AR highway (ref build(): linear_pred=0)
        — the param tree then has no 'ar' head."""
        import jax
        from analytics_zoo_tpu.zouwu.model.nets import MTNetModule
        m = MTNetModule(output_dim=1, long_num=2, time_step=4,
                        cnn_hid_size=4, rnn_hid_sizes=(4,), cnn_height=2,
                        ar_window=0)
        x = np.zeros((2, 12, 2), np.float32)
        v = m.init({"params": jax.random.PRNGKey(0),
                    "dropout": jax.random.PRNGKey(1)}, x)
        assert "ar" not in v["params"]
        assert m.apply(v, x).shape == (2, 1)

    def test_three_separate_encoders(self, orca_ctx):
        """memory/context/query encoders have DISTINCT weights (the ref
        builds three __encoder instances, not one shared)."""
        import jax
        from analytics_zoo_tpu.zouwu.model.nets import MTNetModule
        m = MTNetModule(output_dim=1, long_num=2, time_step=4,
                        cnn_hid_size=4, rnn_hid_sizes=(4,), cnn_height=2)
        x = np.zeros((2, 12, 2), np.float32)
        v = m.init({"params": jax.random.PRNGKey(0),
                    "dropout": jax.random.PRNGKey(1)}, x)
        names = set(v["params"])
        for enc in ("memory", "context", "query"):
            assert f"{enc}_conv" in names and f"{enc}_attgru" in names
        # attention-GRU carries the wrapper's W1..V weights (W1/b2 feed
        # the precomputed X·W1+b2; the per-step weights live in `steps`)
        ag = v["params"]["memory_attgru"]
        assert {"W1", "b2"} <= set(ag)
        assert {"W2", "W3", "b3", "V", "gru_0"} <= set(ag["steps"])

    def test_convergence_beats_mean_baseline(self, orca_ctx):
        from analytics_zoo_tpu.zouwu.model.forecast import MTNetForecaster
        xs, ys = self._data(n=256)
        f = MTNetForecaster(future_seq_len=2, time_step=6, long_num=3,
                            cnn_height=2, cnn_hid_size=8,
                            rnn_hid_sizes=[8], cnn_dropout=0.0,
                            rnn_dropout=0.0)
        f.fit(xs[:192], ys[:192], epochs=30, batch_size=32)
        pred = f.predict(xs[192:])
        mse = float(np.mean((pred - ys[192:]) ** 2))
        base = float(np.mean((ys[192:] - ys[:192].mean()) ** 2))
        assert mse < base * 0.5, (mse, base)

    def test_old_aliases_still_work(self, orca_ctx):
        from analytics_zoo_tpu.zouwu.model.forecast import MTNetForecaster
        xs, ys = self._data()
        f = MTNetForecaster(future_seq_len=2, long_series_num=3,
                            series_length=6, cnn_hid_size=8,
                            rnn_hid_size=8, cnn_kernel_size=2, dropout=0.1)
        f.fit(xs, ys, epochs=1, batch_size=32)
        assert f.predict(xs[:3]).shape == (3, 2)


def test_tcmf_val_len_holdout_and_covariate_evaluate(orca_ctx):
    """fit(val_len=k) holds the last k columns out of training and scores
    them (fit_report['val_mse']); evaluate forwards target_covariates to
    the forecaster."""
    t_total = 144
    cov = np.sin(np.arange(t_total) * 2 * np.pi / 12)[None]
    y = (TestTCMFDistributed._panel(8, t_total, seed=11, k_true=2)
         + 2.0 * cov).astype(np.float32)
    m = TCMFForecaster(k=4, ar_order=8)
    m.fit(y[:, :120], num_steps=300, covariates=cov[:, :120], val_len=24)
    assert m.X.shape[1] == 96              # holdout removed from training
    assert np.isfinite(m.fit_report["val_mse"])
    ev = m.evaluate(y[:, 96:120], target_covariates=cov[:, 96:120])
    assert np.isfinite(ev["mse"])
    with pytest.raises(ValueError, match="val_len"):
        TCMFForecaster(k=2).fit(y[:, :20], val_len=19)


def test_tcmf_rolling_evaluate_with_covariates(orca_ctx):
    """A covariate-fitted model is usable in rolling_evaluate: each
    origin's covariate window feeds predict(future_covariates=...) and
    fit_incremental(covariates_incr=...); omitting covariates raises."""
    t_total = 168
    cov = np.sin(np.arange(t_total) * 2 * np.pi / 12)[None]
    y = (TestTCMFDistributed._panel(8, t_total, seed=3, k_true=2)
         + 2.0 * cov).astype(np.float32)
    m = TCMFForecaster(k=4, ar_order=8)
    m.fit(y[:, :96], num_steps=300, covariates=cov[:, :96])
    t0 = m.X.shape[1]
    res = m.rolling_evaluate(y[:, 96:144], horizon=24,
                             covariates=cov[:, 96:144])
    assert [r["origin"] for r in res] == [0, 24]
    assert all(np.isfinite(r["mse"]) for r in res)
    assert m.X.shape[1] == t0 + 48
    m2 = TCMFForecaster(k=4, ar_order=8)
    m2.fit(y[:, :96], num_steps=100, covariates=cov[:, :96])
    with pytest.raises(ValueError, match="covariates"):
        m2.rolling_evaluate(y[:, 96:144], horizon=24)


def test_tcmf_datetime_features(orca_ctx, tmp_path):
    """start_date/freq (or dti) derive calendar regressors that improve a
    weekday-pattern panel; predict extends them automatically, and they
    survive save/load and fit_incremental."""
    t_total = 7 * 40                         # 40 weeks daily
    dow = np.arange(t_total) % 7
    pattern = np.where(dow >= 5, 3.0, 0.0)   # weekend lift
    base = TestTCMFDistributed._panel(6, t_total, seed=9, k_true=2)
    y = (base + pattern[None]).astype(np.float32)
    m_dt = TCMFForecaster(k=4, ar_order=3, seed=1)
    m_dt.fit(y[:, :252], num_steps=300, start_date="2020-01-06", freq="D")
    assert m_dt._time_feats is not None and m_dt._time_feats.shape == (4, 252)
    m_plain = TCMFForecaster(k=4, ar_order=3, seed=1)
    m_plain.fit(y[:, :252], num_steps=300)
    target = y[:, 252:280]
    mse_dt = float(np.mean((m_dt.predict(28) - target) ** 2))
    mse_plain = float(np.mean((m_plain.predict(28) - target) ** 2))
    assert mse_dt < mse_plain, (mse_dt, mse_plain)
    # save/load keeps the calendar state; fit_incremental extends it
    p = str(tmp_path / "tcmf_dt")
    m_dt.save(p)
    m2 = TCMFForecaster.load(p)
    np.testing.assert_allclose(m2.predict(28), m_dt.predict(28), rtol=1e-5)
    m2.fit_incremental(y[:, 252:266])
    assert m2._time_feats.shape == (4, 266)
    assert np.isfinite(m2.predict(7)).all()
    # explicit dti path + length validation
    import pandas as pd
    with pytest.raises(ValueError, match="dti length"):
        TCMFForecaster(k=2).fit(
            y[:, :50], num_steps=50,
            dti=pd.date_range("2020-01-06", periods=49, freq="D"))


def test_mtnet_legacy_alias_keeps_single_gru(orca_ctx):
    """Explicit legacy-alias calls default to the pre-round-4 single
    32-unit GRU (param tree unchanged → old checkpoints restore); pure
    ref-name or default calls get the ref's stacked (16, 32)."""
    from analytics_zoo_tpu.zouwu.model.forecast import MTNetForecaster
    legacy = MTNetForecaster(future_seq_len=1, series_length=6,
                             long_series_num=3)
    assert legacy.kw["rnn_hid_sizes"] == (32,)
    ref_style = MTNetForecaster(future_seq_len=1, time_step=6, long_num=3)
    assert ref_style.kw["rnn_hid_sizes"] == (16, 32)
    default = MTNetForecaster(future_seq_len=1)
    assert default.kw["rnn_hid_sizes"] == (16, 32)
    explicit = MTNetForecaster(future_seq_len=1, series_length=6,
                               rnn_hid_size=8)
    assert explicit.kw["rnn_hid_sizes"] == (8,)
