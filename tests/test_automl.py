"""Tests for AutoML (mirrors ref pyzoo/test/zoo/orca/automl/ +
pyzoo/test/zoo/automl/)."""

import numpy as np
import pytest

from analytics_zoo_tpu.automl import (
    AutoEstimator, Evaluator, LocalSearchEngine, hp,
)
from analytics_zoo_tpu.automl.model_builder import FlaxModelBuilder


def linear_data(n=256, d=4, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d).astype(np.float32)
    w = np.arange(1, d + 1, dtype=np.float32)
    y = (x @ w[:, None] + 0.01 * rng.randn(n, 1)).astype(np.float32)
    return x, y


def mlp_creator(config):
    import flax.linen as nn

    class MLP(nn.Module):
        hidden: int

        @nn.compact
        def __call__(self, x, train: bool = False):
            x = nn.relu(nn.Dense(self.hidden)(x))
            return nn.Dense(1)(x)

    return MLP(hidden=int(config.get("hidden", 8)))


class TestHp:
    def test_samplers_in_range(self):
        rng = np.random.default_rng(0)
        for _ in range(50):
            assert hp.choice([1, 2, 3]).sample(rng) in (1, 2, 3)
            assert 0.0 <= hp.uniform(0, 1).sample(rng) <= 1.0
            v = hp.loguniform(1e-4, 1e-1).sample(rng)
            assert 1e-4 <= v <= 1e-1
            assert 2 <= hp.randint(2, 5).sample(rng) < 5
        q = hp.quniform(0, 1, 0.25).sample(rng)
        assert abs(q / 0.25 - round(q / 0.25)) < 1e-9

    def test_grid_cross_product(self):
        space = {"a": hp.grid_search([1, 2]), "b": hp.grid_search([10, 20]),
                 "c": hp.uniform(0, 1)}
        pts = hp.grid_points(space)
        assert len(pts) == 4
        assert {(p["a"], p["b"]) for p in pts} == {(1, 10), (1, 20),
                                                  (2, 10), (2, 20)}
        cfg = hp.sample_config(space, np.random.default_rng(0), pts[0])
        assert cfg["a"] == pts[0]["a"] and 0 <= cfg["c"] <= 1

    def test_fixed_values_pass_through(self):
        cfg = hp.sample_config({"lr": 0.1, "nested": {"k": hp.choice([7])}},
                               np.random.default_rng(0))
        assert cfg["lr"] == 0.1 and cfg["nested"]["k"] == 7


class TestEvaluator:
    def test_metrics(self):
        y = np.array([1.0, 2.0, 3.0])
        p = np.array([1.0, 2.0, 4.0])
        assert Evaluator.evaluate("mse", y, p) == pytest.approx(1 / 3)
        assert Evaluator.evaluate("mae", y, p) == pytest.approx(1 / 3)
        assert Evaluator.evaluate("rmse", y, p) == pytest.approx(
            np.sqrt(1 / 3))
        assert Evaluator.evaluate("r2", y, y) == pytest.approx(1.0)
        assert Evaluator.get_metric_mode("mse") == "min"
        assert Evaluator.get_metric_mode("r2") == "max"
        with pytest.raises(ValueError):
            Evaluator.evaluate("nope", y, p)

    def test_accuracy_handles_logits(self):
        y = np.array([0, 1, 2])
        logits = np.eye(3)
        assert Evaluator.evaluate("accuracy", y, logits) == 1.0


class TestSearchEngine:
    def test_grid_random_counts_and_best(self, tmp_path, orca_ctx):
        x, y = linear_data()
        builder = FlaxModelBuilder(mlp_creator)
        eng = LocalSearchEngine(builder, logs_dir=str(tmp_path), name="t",
                                seed=0)
        space = {"hidden": hp.grid_search([4, 16]), "lr": hp.choice([1e-2]),
                 "batch_size": 64}
        eng.compile((x, y), space, n_sampling=1, epochs=2, metric="mse")
        trials = eng.run()
        assert len(trials) == 2
        assert all(t.status == "done" for t in trials)
        assert all(len(t.metric_history) == 2 for t in trials)
        best = eng.get_best_trial()
        assert best.best_metric == min(t.best_metric for t in trials)
        assert (tmp_path / "t" / "trials.json").exists()

    def test_trial_error_is_captured(self, tmp_path, orca_ctx):
        def bad_creator(config):
            raise RuntimeError("boom")
        eng = LocalSearchEngine(FlaxModelBuilder(bad_creator),
                                logs_dir=str(tmp_path), name="bad")
        x, y = linear_data(32)
        eng.compile((x, y), {"lr": 1e-2}, epochs=1)
        trials = eng.run()
        assert trials[0].status == "error" and "boom" in trials[0].error
        with pytest.raises(RuntimeError):
            eng.get_best_trial()


class TestAutoEstimator:
    def test_fit_search_restores_best(self, tmp_path, orca_ctx):
        x, y = linear_data()
        auto = AutoEstimator.from_flax(model_creator=mlp_creator,
                                       logs_dir=str(tmp_path), name="mlp")
        auto.fit((x, y), validation_data=(x, y),
                 search_space={"hidden": hp.choice([8, 32]),
                               "lr": hp.loguniform(1e-3, 1e-2),
                               "batch_size": 64},
                 n_sampling=2, epochs=3, metric="mse")
        cfg = auto.get_best_config()
        assert cfg["hidden"] in (8, 32)
        model = auto.get_best_model()
        mse = model.evaluate(x, y, metrics=["mse"])["mse"]
        # restored best model must match its recorded search reward
        assert mse == pytest.approx(auto.get_best_trial().best_metric,
                                    rel=0.2)

    def test_from_keras_builder(self, tmp_path, orca_ctx):
        from analytics_zoo_tpu.keras.layers import Dense
        from analytics_zoo_tpu.keras.models import Sequential

        def creator(config):
            m = Sequential()
            m.add(Dense(int(config["hidden"]), activation="relu",
                        input_shape=(4,)))
            m.add(Dense(1))
            m.compile(optimizer="adam", loss="mse")
            return m

        x, y = linear_data(128)
        auto = AutoEstimator.from_keras(model_creator=creator,
                                        logs_dir=str(tmp_path), name="k")
        auto.fit((x, y), search_space={"hidden": hp.choice([8])},
                 n_sampling=1, epochs=2, metric="mse", batch_size=64)
        assert auto.get_best_trial().status == "done"
