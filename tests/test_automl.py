"""Tests for AutoML (mirrors ref pyzoo/test/zoo/orca/automl/ +
pyzoo/test/zoo/automl/)."""

import numpy as np
import pytest

from analytics_zoo_tpu.automl import (
    AutoEstimator, Evaluator, LocalSearchEngine, hp,
)
from analytics_zoo_tpu.automl.model_builder import FlaxModelBuilder


def linear_data(n=256, d=4, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d).astype(np.float32)
    w = np.arange(1, d + 1, dtype=np.float32)
    y = (x @ w[:, None] + 0.01 * rng.randn(n, 1)).astype(np.float32)
    return x, y


def mlp_creator(config):
    import flax.linen as nn

    class MLP(nn.Module):
        hidden: int

        @nn.compact
        def __call__(self, x, train: bool = False):
            x = nn.relu(nn.Dense(self.hidden)(x))
            return nn.Dense(1)(x)

    return MLP(hidden=int(config.get("hidden", 8)))


class TestHp:
    def test_samplers_in_range(self):
        rng = np.random.default_rng(0)
        for _ in range(50):
            assert hp.choice([1, 2, 3]).sample(rng) in (1, 2, 3)
            assert 0.0 <= hp.uniform(0, 1).sample(rng) <= 1.0
            v = hp.loguniform(1e-4, 1e-1).sample(rng)
            assert 1e-4 <= v <= 1e-1
            assert 2 <= hp.randint(2, 5).sample(rng) < 5
        q = hp.quniform(0, 1, 0.25).sample(rng)
        assert abs(q / 0.25 - round(q / 0.25)) < 1e-9

    def test_grid_cross_product(self):
        space = {"a": hp.grid_search([1, 2]), "b": hp.grid_search([10, 20]),
                 "c": hp.uniform(0, 1)}
        pts = hp.grid_points(space)
        assert len(pts) == 4
        assert {(p["a"], p["b"]) for p in pts} == {(1, 10), (1, 20),
                                                  (2, 10), (2, 20)}
        cfg = hp.sample_config(space, np.random.default_rng(0), pts[0])
        assert cfg["a"] == pts[0]["a"] and 0 <= cfg["c"] <= 1

    def test_fixed_values_pass_through(self):
        cfg = hp.sample_config({"lr": 0.1, "nested": {"k": hp.choice([7])}},
                               np.random.default_rng(0))
        assert cfg["lr"] == 0.1 and cfg["nested"]["k"] == 7

    def test_subset_sampler(self):
        rng = np.random.default_rng(0)
        items = ["a", "b", "c", "d"]
        for _ in range(30):
            s = hp.subset(items).sample(rng)
            assert 1 <= len(s) <= 4
            assert s == [it for it in items if it in s]  # order preserved
            assert len(set(s)) == len(s)
        assert len(hp.subset(items, min_items=3).sample(rng)) >= 3
        with pytest.raises(ValueError):
            hp.subset(["a"], min_items=2)


class TestEvaluator:
    def test_metrics(self):
        y = np.array([1.0, 2.0, 3.0])
        p = np.array([1.0, 2.0, 4.0])
        assert Evaluator.evaluate("mse", y, p) == pytest.approx(1 / 3)
        assert Evaluator.evaluate("mae", y, p) == pytest.approx(1 / 3)
        assert Evaluator.evaluate("rmse", y, p) == pytest.approx(
            np.sqrt(1 / 3))
        assert Evaluator.evaluate("r2", y, y) == pytest.approx(1.0)
        assert Evaluator.get_metric_mode("mse") == "min"
        assert Evaluator.get_metric_mode("r2") == "max"
        with pytest.raises(ValueError):
            Evaluator.evaluate("nope", y, p)

    def test_accuracy_handles_logits(self):
        y = np.array([0, 1, 2])
        logits = np.eye(3)
        assert Evaluator.evaluate("accuracy", y, logits) == 1.0

    def test_auc(self):
        y = np.array([0, 0, 1, 1])
        assert Evaluator.evaluate("auc", y,
                                  np.array([0.1, 0.2, 0.8, 0.9])) == 1.0
        assert Evaluator.evaluate("auc", y,
                                  np.array([0.9, 0.8, 0.2, 0.1])) == 0.0
        # ties average to 0.5 credit
        assert Evaluator.evaluate(
            "auc", y, np.array([0.5, 0.5, 0.5, 0.5])) == pytest.approx(0.5)
        # 2-column probabilities use column 1
        probs = np.stack([1 - np.array([0.1, 0.2, 0.8, 0.9]),
                          np.array([0.1, 0.2, 0.8, 0.9])], 1)
        assert Evaluator.evaluate("auc", y, probs) == 1.0
        assert Evaluator.get_metric_mode("auc") == "max"
        with pytest.raises(ValueError, match="both classes"):
            Evaluator.evaluate("auc", np.zeros(4), np.arange(4.0))


class TestSearchEngine:
    def test_grid_random_counts_and_best(self, tmp_path, orca_ctx):
        x, y = linear_data()
        builder = FlaxModelBuilder(mlp_creator)
        eng = LocalSearchEngine(builder, logs_dir=str(tmp_path), name="t",
                                seed=0)
        space = {"hidden": hp.grid_search([4, 16]), "lr": hp.choice([1e-2]),
                 "batch_size": 64}
        eng.compile((x, y), space, n_sampling=1, epochs=2, metric="mse")
        trials = eng.run()
        assert len(trials) == 2
        assert all(t.status == "done" for t in trials)
        assert all(len(t.metric_history) == 2 for t in trials)
        best = eng.get_best_trial()
        assert best.best_metric == min(t.best_metric for t in trials)
        assert (tmp_path / "t" / "trials.json").exists()

    def test_trial_error_is_captured(self, tmp_path, orca_ctx):
        def bad_creator(config):
            raise RuntimeError("boom")
        eng = LocalSearchEngine(FlaxModelBuilder(bad_creator),
                                logs_dir=str(tmp_path), name="bad")
        x, y = linear_data(32)
        eng.compile((x, y), {"lr": 1e-2}, epochs=1)
        trials = eng.run()
        assert trials[0].status == "error" and "boom" in trials[0].error
        with pytest.raises(RuntimeError):
            eng.get_best_trial()


class _AnalyticBuilder:
    """Fake builder: fit_eval returns a known function of the config and
    epoch — lets scheduler/searcher logic be tested deterministically and
    fast (no training)."""

    def __init__(self, fn):
        self.fn = fn

    def build(self, config):
        builder = self

        class _M:
            def __init__(self):
                self.epoch = 0

            def fit_eval(self, data, validation_data=None, epochs=1,
                         metric="mse", batch_size=None):
                self.epoch += epochs
                return builder.fn(dict(config), self.epoch)

            def save(self, path):
                pass

        return _M()


class TestBayesSearch:
    def test_bayes_concentrates_near_optimum(self, tmp_path, orca_ctx):
        """After the startup phase, TPE-style proposals must beat pure
        random sampling on a sharp 1-d objective."""
        target = 3e-3

        def objective(cfg, epoch):
            return abs(np.log10(cfg["lr"]) - np.log10(target))

        space = {"lr": hp.loguniform(1e-5, 1e-1)}
        eng = LocalSearchEngine(_AnalyticBuilder(objective),
                                logs_dir=str(tmp_path), name="bayes", seed=7)
        eng.compile((None, None), space, n_sampling=30, epochs=1,
                    metric="mse", mode="min", search_alg="bayes")
        trials = eng.run()
        assert len(trials) == 30 and all(t.status == "done" for t in trials)
        late = [t.best_metric for t in trials[15:]]
        eng2 = LocalSearchEngine(_AnalyticBuilder(objective),
                                 logs_dir=str(tmp_path), name="rand", seed=7)
        eng2.compile((None, None), space, n_sampling=30, epochs=1,
                     metric="mse", mode="min")
        rand = [t.best_metric for t in eng2.run()]
        # bayes late-phase proposals average closer to the optimum than
        # random draws (log distance, optimum within a 4-decade space)
        assert np.mean(late) < np.mean(rand)
        assert eng.get_best_trial().best_metric < 0.3

    def test_bayes_survives_poisoned_configs(self, tmp_path, orca_ctx):
        def objective(cfg, epoch):
            if cfg["lr"] > 1e-2:
                raise RuntimeError("diverged")
            return float(cfg["lr"])

        eng = LocalSearchEngine(_AnalyticBuilder(objective),
                                logs_dir=str(tmp_path), name="poison")
        eng.compile((None, None), {"lr": hp.loguniform(1e-4, 1.0)},
                    n_sampling=20, epochs=1, metric="mse", mode="min",
                    search_alg="bayes")
        trials = eng.run()
        assert any(t.status == "error" for t in trials)
        assert eng.get_best_trial().best_metric is not None


class TestHyperband:
    def test_successive_halving_prunes_and_keeps_best(self, tmp_path,
                                                      orca_ctx):
        # metric improves at a config-specific rate; the best rate must
        # survive all rungs, most trials must stop early
        def objective(cfg, epoch):
            return 10.0 / (1.0 + cfg["rate"] * epoch)

        space = {"rate": hp.grid_search([0.1, 0.2, 0.5, 1.0, 2.0, 5.0,
                                         10.0, 20.0, 50.0])}
        eng = LocalSearchEngine(_AnalyticBuilder(objective),
                                logs_dir=str(tmp_path), name="hb")
        eng.compile((None, None), space, n_sampling=1, epochs=9,
                    metric="mse", mode="min", scheduler="hyperband")
        trials = eng.run()
        stopped = [t for t in trials if t.status == "stopped"]
        done = [t for t in trials if t.status == "done"]
        assert len(stopped) >= 5, "halving never pruned"
        assert all(len(t.metric_history) < 9 for t in stopped)
        assert any(t.config["rate"] == 50.0 for t in done)
        best = eng.get_best_trial()
        assert best.config["rate"] == 50.0
        # pruned trials spent less epoch budget than survivors
        total = sum(len(t.metric_history) for t in trials)
        assert total < 9 * len(trials) * 0.7

    def test_device_packed_parallel_trials(self, tmp_path, orca_ctx):
        """n_parallel='auto' packs trials round-robin over the 8 virtual
        devices; every trial completes with correct results."""
        x, y = linear_data(128)
        eng = LocalSearchEngine(FlaxModelBuilder(mlp_creator),
                                logs_dir=str(tmp_path), name="pack",
                                n_parallel="auto")
        eng.compile((x, y), {"hidden": hp.grid_search([4, 8, 16, 32]),
                             "lr": 1e-2, "batch_size": 64},
                    n_sampling=1, epochs=1, metric="mse")
        trials = eng.run()
        assert len(trials) == 4
        assert all(t.status == "done" for t in trials)
        assert all(np.isfinite(t.best_metric) for t in trials)


class TestPopulationSearch:
    @pytest.mark.slow  # ~14s: trains the full population twice (vmap+serial)
    def test_vmapped_population_matches_and_beats_serial(self, tmp_path,
                                                         orca_ctx):
        """The fused vmap population must (a) train every member for real,
        (b) rank learning rates sensibly, (c) beat the serial per-trial
        loop on wall clock (compile + dispatch amortized K-fold — the
        SURVEY §7.6 trial-packing claim)."""
        import time
        from analytics_zoo_tpu.automl import PopulationSearchEngine

        x, y = linear_data(256)
        K, E = 32, 6
        space = {"lr": hp.loguniform(1e-4, 3e-2)}

        eng = PopulationSearchEngine(mlp_creator, loss="mse",
                                     logs_dir=str(tmp_path), seed=3)
        eng.compile((x, y), space, n_sampling=K, epochs=E, metric="mse",
                    batch_size=64)
        t0 = time.time()
        trials = eng.run()
        pop_wall = time.time() - t0
        assert len(trials) == K
        assert all(t.status == "done" for t in trials)
        assert all(len(t.metric_history) == E for t in trials)
        metrics = np.array([t.best_metric for t in trials])
        assert np.isfinite(metrics).all()
        assert len(set(np.round(metrics, 6))) > 1, "members identical"
        # the best member actually learned the linear map
        assert eng.get_best_trial().best_metric < np.var(y)
        params = eng.get_best_params()
        assert params is not None

        # serial baseline: same creator, same trial count, same epochs
        serial = LocalSearchEngine(FlaxModelBuilder(mlp_creator),
                                   logs_dir=str(tmp_path), name="serial",
                                   seed=3)
        serial.compile((x, y), {"lr": hp.loguniform(1e-4, 3e-2),
                                "batch_size": 64},
                       n_sampling=K, epochs=E, metric="mse")
        t0 = time.time()
        serial.run()
        serial_wall = time.time() - t0
        speedup = serial_wall / max(pop_wall, 1e-9)
        # measured ~5x on an idle single-core host (population cost is
        # nearly flat in K — one compile, one dispatch per epoch). The
        # assert is a loose sanity floor so machine load can't flake it;
        # the real perf evidence lives in the measured number above.
        print(f"population packing speedup: {speedup:.2f}x")
        assert speedup > 1.2, \
            f"population packing only {speedup:.1f}x vs serial"


class TestAutoEstimator:
    def test_fit_search_restores_best(self, tmp_path, orca_ctx):
        x, y = linear_data()
        auto = AutoEstimator.from_flax(model_creator=mlp_creator,
                                       logs_dir=str(tmp_path), name="mlp")
        auto.fit((x, y), validation_data=(x, y),
                 search_space={"hidden": hp.choice([8, 32]),
                               "lr": hp.loguniform(1e-3, 1e-2),
                               "batch_size": 64},
                 n_sampling=2, epochs=3, metric="mse")
        cfg = auto.get_best_config()
        assert cfg["hidden"] in (8, 32)
        model = auto.get_best_model()
        mse = model.evaluate(x, y, metrics=["mse"])["mse"]
        # restored best model must match its recorded search reward
        assert mse == pytest.approx(auto.get_best_trial().best_metric,
                                    rel=0.2)

    def test_from_keras_builder(self, tmp_path, orca_ctx):
        from analytics_zoo_tpu.keras.layers import Dense
        from analytics_zoo_tpu.keras.models import Sequential

        def creator(config):
            m = Sequential()
            m.add(Dense(int(config["hidden"]), activation="relu",
                        input_shape=(4,)))
            m.add(Dense(1))
            m.compile(optimizer="adam", loss="mse")
            return m

        x, y = linear_data(128)
        auto = AutoEstimator.from_keras(model_creator=creator,
                                        logs_dir=str(tmp_path), name="k")
        auto.fit((x, y), search_space={"hidden": hp.choice([8])},
                 n_sampling=1, epochs=2, metric="mse", batch_size=64)
        assert auto.get_best_trial().status == "done"


class TestXGBoost:
    """Native GBDT backend + AutoXGBoost (ref orca/automl/xgboost)."""

    def test_regressor_learns_nonlinear(self, orca_ctx):
        from analytics_zoo_tpu.automl import XGBRegressor
        rng = np.random.RandomState(0)
        x = rng.rand(400, 3).astype(np.float32)
        y = (np.sin(4 * x[:, 0]) + (x[:, 1] > 0.5) * 2 + x[:, 2] ** 2)
        m = XGBRegressor(n_estimators=60, max_depth=4, learning_rate=0.2)
        m.fit(x[:300], y[:300])
        mse = m.evaluate(x[300:], y[300:], metrics=["mse"])["mse"]
        # trees must beat predicting the mean by a wide margin
        assert mse < 0.1 * np.var(y[300:])

    def test_classifier_and_proba(self, orca_ctx):
        from analytics_zoo_tpu.automl import XGBClassifier
        rng = np.random.RandomState(1)
        x = rng.rand(400, 4).astype(np.float32)
        y = ((x[:, 0] > 0.5) ^ (x[:, 1] > 0.5)).astype(np.int64)  # XOR
        m = XGBClassifier(n_estimators=60, max_depth=4, learning_rate=0.3)
        m.fit(x[:300], y[:300])
        acc = (m.predict(x[300:]) == y[300:]).mean()
        assert acc > 0.9, f"GBDT failed XOR: acc {acc}"
        proba = m.predict_proba(x[300:])
        assert proba.shape == (100, 2)
        np.testing.assert_allclose(proba.sum(1), 1.0, atol=1e-6)

    def test_auto_xgb_search(self, tmp_path, orca_ctx):
        from analytics_zoo_tpu.automl import AutoXGBRegressor
        rng = np.random.RandomState(2)
        x = rng.rand(256, 3).astype(np.float32)
        y = x[:, 0] * 3 + (x[:, 1] > 0.3)
        auto = AutoXGBRegressor(logs_dir=str(tmp_path), name="axgb",
                                n_estimators=30)
        auto.fit((x[:192], y[:192]), validation_data=(x[192:], y[192:]),
                 search_space={"max_depth": hp.grid_search([2, 4]),
                               "learning_rate": hp.choice([0.1, 0.3])},
                 n_sampling=1, metric="mse")
        cfg = auto.get_best_config()
        assert cfg["max_depth"] in (2, 4)
        best = auto.get_best_model()
        pred = best.predict(x[192:])
        assert np.mean((pred - y[192:]) ** 2) < 0.1 * np.var(y)
