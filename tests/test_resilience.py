"""Wedge-resilient elastic execution (ISSUE 7): the deterministic fault
injector, the backend supervisor state machine, the dump_once latch,
checkpoint validation/fallback, ``fit(auto_resume=True)`` bitwise resume,
and the full serving wedge→failover→recover→swap-back cycle (in-process
and as a subprocess replica polled over HTTP)."""

import json
import os
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_injector():
    from analytics_zoo_tpu.common import profiling, resilience
    resilience.install_plan(None)
    yield
    resilience.install_plan(None)
    resilience._drop_supervisor()
    # drop the flight-recorder singleton so its dump_once latch cannot
    # leak a "backend-wedged-1" trigger into the next test's episode
    profiling.reset_for_tests()


# ---------------------------------------------------------------- injector

class TestFaultInjector:
    def test_plan_grammar_windows(self):
        from analytics_zoo_tpu.common.resilience import FaultInjector
        inj = FaultInjector("wedge@dispatch:3+1,oom@step:2,wedge@probe")
        assert set(inj.sites()) == {"dispatch", "step", "probe"}
        # dispatch: arrivals 3 and 4 only
        fired = [inj.check("dispatch") is not None for _ in range(6)]
        assert fired == [False, False, True, True, False, False]
        # step: exactly arrival 2
        assert [inj.check("step") is not None for _ in range(3)] == \
            [False, True, False]
        # probe with no :start fires every call
        assert all(inj.check("probe") is not None for _ in range(4))
        assert inj.counts() == {"dispatch": 6, "step": 3, "probe": 4}

    def test_fault_carries_plan_detail(self):
        from analytics_zoo_tpu.common.resilience import FaultInjector
        f = FaultInjector("wedge@dispatch:1").check("dispatch")
        assert (f.kind, f.site, f.index) == ("wedge", "dispatch", 1)
        assert "ZOO_FAULT_PLAN" in str(f)

    def test_malformed_plan_raises(self):
        from analytics_zoo_tpu.common.resilience import FaultInjector
        with pytest.raises(ValueError, match="ZOO_FAULT_PLAN"):
            FaultInjector("wedge-dispatch-3")

    def test_malformed_env_plan_is_ignored(self, monkeypatch):
        from analytics_zoo_tpu.common import resilience
        monkeypatch.setenv("ZOO_FAULT_PLAN", "not a plan")
        resilience._INJ_LOADED = False
        resilience._INJECTOR = None
        assert resilience.get_injector() is None
        assert not resilience.fault_plan_active()

    def test_maybe_fault_raises_at_planned_arrival(self):
        from analytics_zoo_tpu.common import resilience
        resilience.install_plan("wedge@dispatch:2")
        resilience.maybe_fault("dispatch")
        with pytest.raises(resilience.InjectedFault):
            resilience.maybe_fault("dispatch")
        resilience.maybe_fault("dispatch")       # window passed

    def test_fault_scope_suppresses_nested_same_site(self):
        from analytics_zoo_tpu.common import resilience
        resilience.install_plan("wedge@dispatch:2")
        with resilience.fault_scope("dispatch"):
            # nested seam: must NOT count as arrival 2
            resilience.maybe_fault("dispatch")
            resilience.maybe_fault("dispatch")
        with pytest.raises(resilience.InjectedFault):
            with resilience.fault_scope("dispatch"):
                pass

    def test_probe_fault_is_non_raising(self):
        from analytics_zoo_tpu.common import resilience
        resilience.install_plan("wedge@probe:1")
        assert resilience.probe_fault() == "wedge"
        assert resilience.probe_fault() is None

    def test_is_backend_loss(self):
        from analytics_zoo_tpu.common import resilience

        class XlaRuntimeError(Exception):
            pass

        assert resilience.is_backend_loss(
            resilience.InjectedFault("wedge", "dispatch", 1))
        assert resilience.is_backend_loss(XlaRuntimeError("boom"))
        assert resilience.is_backend_loss(RuntimeError("device lost"))
        assert not resilience.is_backend_loss(ValueError("bad shape"))
        assert not resilience.is_backend_loss(None)

    def test_probe_seam_reaches_backend_state(self):
        from analytics_zoo_tpu.common import profiling, resilience
        resilience.install_plan("wedge@probe:1")
        st = profiling.backend_state(timeout_s=1.0)
        assert st["status"] == "wedged" and st["injected"] == "wedge"
        # plan exhausted: the next probe is a real (healthy) one
        st2 = profiling.backend_state(timeout_s=1.0)
        assert st2["status"] != "wedged"


# -------------------------------------------------------------- supervisor

def _scripted_supervisor(statuses, **kw):
    """Supervisor fed a canned probe sequence on a private registry."""
    from analytics_zoo_tpu.common import resilience, telemetry
    seq = iter(statuses)
    reg = telemetry.MetricsRegistry()
    sup = resilience.BackendSupervisor(
        probe=lambda: {"status": next(seq)}, registry=reg, **kw)
    return sup, reg


class TestBackendSupervisor:
    def test_full_cycle_and_metrics(self, tmp_path, monkeypatch):
        monkeypatch.setenv("ZOO_FLIGHT_RECORDER_DIR", str(tmp_path))
        from analytics_zoo_tpu.common.resilience import BackendSupervisor
        sup, reg = _scripted_supervisor(
            ["error", "error", "ok", "ok", "ok"], recover_probes=2)
        states = []
        for _ in range(5):
            sup.probe_once()
            states.append(sup.state)
        # the probe that flips wedged→recovering starts the healthy
        # streak, so recover_probes=2 lands ok on the next healthy probe
        assert states == ["suspect", "wedged", "recovering", "ok", "ok"]
        assert sup.episodes == 1
        snap = reg.snapshot()
        assert snap["zoo_backend_state"] == \
            BackendSupervisor.STATE_CODES["ok"]
        assert snap["zoo_backend_failovers_total"] == 1
        dumps = [p for p in os.listdir(tmp_path) if p.startswith("flightrec")]
        assert len(dumps) == 1          # one postmortem for the episode

    def test_relapse_is_same_episode_no_second_dump(self, tmp_path,
                                                    monkeypatch):
        monkeypatch.setenv("ZOO_FLIGHT_RECORDER_DIR", str(tmp_path))
        sup, _ = _scripted_supervisor(
            ["error", "error", "ok", "error", "ok", "ok"])
        states = [sup.probe_once() and sup.state for _ in range(6)]
        assert states[1] == "wedged"
        assert states[3] == "wedged"    # relapse from recovering
        assert states[-1] == "ok"
        assert sup.episodes == 1        # not a new episode
        dumps = [p for p in os.listdir(tmp_path) if p.startswith("flightrec")]
        assert len(dumps) == 1          # dump_once latch held

    def test_report_failure_and_force_wedged(self):
        sup, reg = _scripted_supervisor([])
        sup.report_failure(RuntimeError("device lost"))
        assert sup.state == "suspect"
        sup.report_failure(RuntimeError("device lost"))
        assert sup.state == "wedged" and sup.episodes == 1
        sup2, _ = _scripted_supervisor([])
        sup2.force_wedged("init hang")
        assert sup2.state == "wedged" and sup2.episodes == 1

    def test_probe_loop_recovers(self):
        """The daemon loop drives wedged→ok on its own once probes heal."""
        sup, _ = _scripted_supervisor([], interval_s=0.02,
                                      backoff_max_s=0.05)
        sup.force_wedged("drill")
        healthy = {"status": "ok"}
        sup._probe = lambda: healthy
        sup.ensure_started()
        try:
            deadline = time.monotonic() + 5.0
            while sup.state != "ok" and time.monotonic() < deadline:
                time.sleep(0.02)
            assert sup.state == "ok"
        finally:
            sup.stop()


class TestDumpOnce:
    def test_latch_keyed_by_trigger(self, tmp_path, monkeypatch):
        monkeypatch.setenv("ZOO_FLIGHT_RECORDER_DIR", str(tmp_path))
        from analytics_zoo_tpu.common.profiling import FlightRecorder
        fr = FlightRecorder()
        fr.note("evidence")
        p1 = fr.dump_once(trigger="backend-wedged-1", reason="backend-wedged")
        p2 = fr.dump_once(trigger="backend-wedged-1", reason="backend-wedged")
        assert p1 == p2                 # latched: same artifact back
        p3 = fr.dump_once(trigger="signal-SIGTERM", reason="sigterm")
        assert p3 != p1                 # distinct event, distinct artifact
        dumps = [p for p in os.listdir(tmp_path) if p.startswith("flightrec")]
        assert len(dumps) == 2

    def test_arm_twice_does_not_self_chain(self):
        import signal
        from analytics_zoo_tpu.common.profiling import FlightRecorder
        fr = FlightRecorder()
        if not fr.arm():
            pytest.skip("not in main thread")
        try:
            fr.arm()                    # second arm: no re-store
            prev = fr._prev_handlers.get(signal.SIGTERM)
            assert prev is not fr._handler
        finally:
            fr.disarm()


# ------------------------------------------------------------- checkpoints

class TestCheckpointValidation:
    def _state(self, scale=1.0, shape=(3, 2)):
        return {"params": {"w": np.full(shape, scale, np.float32),
                           "b": np.zeros((shape[1],), np.float32)},
                "step": np.int32(0)}

    def test_validate_state_mismatches(self):
        from analytics_zoo_tpu.learn import checkpoint as ckpt
        good = self._state()
        ckpt.validate_state(good, self._state())
        with pytest.raises(ValueError, match="shape"):
            ckpt.validate_state(self._state(shape=(4, 2)), good)
        with pytest.raises(ValueError, match="structure"):
            bad = dict(good)
            bad.pop("step")
            ckpt.validate_state(bad, good)

    def test_torn_file_falls_back_to_previous_version(self, tmp_path):
        from analytics_zoo_tpu.learn import checkpoint as ckpt
        d = str(tmp_path)
        ckpt.save_checkpoint(d, self._state(1.0), iteration=4, epoch=1)
        ckpt.save_checkpoint(d, self._state(2.0), iteration=8, epoch=2)
        # tear the newest state file in half — a crash mid-write after the
        # rename would look like this
        torn = os.path.join(d, "ckpt-8", "state.msgpack")
        blob = open(torn, "rb").read()
        with open(torn, "wb") as fh:
            fh.write(blob[:len(blob) // 2])
        got = ckpt.load_latest_checkpoint(d, self._state())
        assert got is not None
        state, meta, path = got
        assert path.endswith("ckpt-4") and meta["iteration"] == 4
        assert float(state["params"]["w"][0, 0]) == 1.0

    def test_wrong_model_checkpoint_is_skipped(self, tmp_path):
        from analytics_zoo_tpu.learn import checkpoint as ckpt
        d = str(tmp_path)
        ckpt.save_checkpoint(d, self._state(), iteration=2, epoch=1)
        ckpt.save_checkpoint(d, self._state(shape=(5, 4)), iteration=6,
                             epoch=2)
        got = ckpt.load_latest_checkpoint(d, self._state())
        assert got is not None and got[2].endswith("ckpt-2")

    def test_no_survivor_returns_none(self, tmp_path):
        from analytics_zoo_tpu.learn import checkpoint as ckpt
        assert ckpt.load_latest_checkpoint(str(tmp_path),
                                           self._state()) is None


# ------------------------------------------------------------- auto-resume

def _fit_mlp():
    import flax.linen as nn

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = False):
            x = nn.Dense(16)(x)
            x = nn.relu(x)
            return nn.Dense(1)(x)
    return MLP()


def test_fit_auto_resume_bitwise_identical(orca_ctx, tmp_path):
    """Acceptance (ISSUE 7): an injected backend loss mid-epoch-3 must
    resume from the epoch-2 checkpoint at the exact step and converge to
    a BITWISE-identical final loss and params vs an unfaulted run."""
    from analytics_zoo_tpu.common import resilience
    from analytics_zoo_tpu.learn.estimator import Estimator
    from analytics_zoo_tpu.learn.trigger import EveryEpoch

    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 4)).astype(np.float32)
    y = (x @ np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)) + 0.1

    def run(faulted, mdir):
        # 4 steps/epoch × 3 epochs; step arrival 10 = epoch 3, step 2 —
        # past the epoch-2 checkpoint, so resume must reload it
        resilience.install_plan("wedge@step:10" if faulted else None)
        est = Estimator.from_flax(model=_fit_mlp(), loss="mse",
                                  sample_input=x[:2], model_dir=mdir)
        hist = est.fit((x, y), epochs=3, batch_size=16,
                       checkpoint_trigger=EveryEpoch(),
                       auto_resume=faulted)
        resilience.install_plan(None)
        return est, hist

    est_a, hist_a = run(False, str(tmp_path / "a"))
    est_b, hist_b = run(True, str(tmp_path / "b"))
    assert est_a._py_step == est_b._py_step == 12
    assert hist_a["loss"][-1] == hist_b["loss"][-1]
    import jax
    for a, b in zip(jax.tree_util.tree_leaves(est_a.get_model()),
                    jax.tree_util.tree_leaves(est_b.get_model())):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_fit_auto_resume_bounded_by_env(orca_ctx, tmp_path, monkeypatch):
    """ZOO_FIT_MAX_RESUMES=0 turns auto-resume off: the injected loss
    propagates instead of retrying forever."""
    from analytics_zoo_tpu.common import resilience
    from analytics_zoo_tpu.learn.estimator import Estimator
    from analytics_zoo_tpu.learn.trigger import EveryEpoch

    monkeypatch.setenv("ZOO_FIT_MAX_RESUMES", "0")
    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 4)).astype(np.float32)
    y = x[:, :1].copy()
    resilience.install_plan("wedge@step:3")
    est = Estimator.from_flax(model=_fit_mlp(), loss="mse",
                              sample_input=x[:2],
                              model_dir=str(tmp_path / "m"))
    with pytest.raises(resilience.InjectedFault):
        est.fit((x, y), epochs=2, batch_size=16,
                checkpoint_trigger=EveryEpoch(), auto_resume=True)


# ------------------------------------------------------- serving failover

def _tiny_inference_model():
    import flax.linen as nn
    from analytics_zoo_tpu.inference import InferenceModel

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(3)(nn.relu(nn.Dense(16)(x)))

    return InferenceModel().load_flax(Net(), np.zeros((4, 5), np.float32))


def test_serving_wedge_failover_recover_swap_back(orca_ctx):
    """Acceptance (ISSUE 7): full in-process cycle — wedge mid-stream,
    drain to the pre-built CPU rungs with ZERO dropped records, recover
    when probes heal, swap dispatch back to the device."""
    from analytics_zoo_tpu.common import resilience
    from analytics_zoo_tpu.serving import (
        Broker, ClusterServing, InputQueue, OutputQueue,
    )

    im = _tiny_inference_model()
    n = 48
    rng = np.random.default_rng(5)
    payloads = rng.standard_normal((n, 5)).astype(np.float32)
    with resilience.fault_drill("wedge@dispatch:6+2,wedge@probe:1+2"), \
            Broker.launch() as broker:
        eng = ClusterServing(im, broker.port, batch_size=4,
                             max_batch_size=4, pipeline_window=2)
        with eng.start():
            eng.wait_warm(timeout=120.0)
            in_q = InputQueue(port=broker.port)
            out_q = OutputQueue(port=broker.port)
            uris = in_q.enqueue_batch(
                (f"r{i}", {"x": payloads[i]}) for i in range(n))
            res = out_q.query_many(uris, timeout=90.0)
            assert all(v is not None for v in res.values()), \
                f"{sum(v is None for v in res.values())} records dropped"
            # drain→first-CPU-result latency was measured
            assert eng.failover_seconds and eng.failover_seconds[0] >= 0
            sup = eng._supervisor
            assert sup is not None and sup.episodes == 1
            # probes heal after the plan window: supervisor returns to ok
            # and the engine swaps dispatch back off the CPU rungs
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline and \
                    (eng.failover_active or sup.state != "ok"):
                time.sleep(0.1)
            assert sup.state == "ok"
            assert not eng.failover_active


_REPLICA_SCRIPT = """
import sys
import numpy as np
import flax.linen as nn
from analytics_zoo_tpu.inference import InferenceModel
from analytics_zoo_tpu.serving.engine import ClusterServing
from analytics_zoo_tpu.serving.frontend import FrontEnd

class Net(nn.Module):
    @nn.compact
    def __call__(self, x):
        return nn.Dense(3)(nn.relu(nn.Dense(16)(x)))

port = int(sys.argv[1])
im = InferenceModel().load_flax(Net(), np.zeros((4, 5), np.float32))
eng = ClusterServing(im, port, batch_size=4, max_batch_size=4,
                     pipeline_window=2)
fe = FrontEnd(port, engine=eng)
eng.start()
eng.wait_warm(timeout=120.0)
fe.start()
print("READY", fe.port, flush=True)
sys.stdin.readline()                    # parent closes stdin to stop us
eng.stop()
fe.stop()
print("DONE", flush=True)
"""


def _get_json(url, timeout=10.0):
    import urllib.error
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def test_serving_failover_subprocess_healthz_never_503(orca_ctx):
    """Acceptance (ISSUE 7): a subprocess replica armed purely through the
    environment (``ZOO_FAULT_PLAN`` + ``ZOO_CPU_FALLBACK=1``) wedges
    mid-stream, completes EVERY record via CPU failover, keeps ``/healthz``
    degraded-but-200 (never 503), and its ``records_out`` only grows."""
    from analytics_zoo_tpu.serving.broker import Broker
    from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue

    env = dict(os.environ, JAX_PLATFORMS="cpu", ZOO_CPU_FALLBACK="1",
               ZOO_FAULT_PLAN="wedge@dispatch:6+2,wedge@probe:1+2")
    n = 48
    rng = np.random.default_rng(9)
    payloads = rng.standard_normal((n, 5)).astype(np.float32)
    with Broker.launch() as broker:
        proc = subprocess.Popen(
            [sys.executable, "-c", _REPLICA_SCRIPT, str(broker.port)],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            text=True, cwd=REPO, env=env)
        try:
            ready = proc.stdout.readline().split()
            assert ready and ready[0] == "READY", ready
            http = int(ready[1])
            in_q = InputQueue(port=broker.port)
            out_q = OutputQueue(port=broker.port)
            uris = in_q.enqueue_batch(
                (f"w{i}", {"x": payloads[i]}) for i in range(n))
            codes, records_seen = [], []
            saw_failover = False
            deadline = time.monotonic() + 90.0
            res = {}
            while time.monotonic() < deadline:
                code, health = _get_json(
                    f"http://127.0.0.1:{http}/healthz")
                codes.append(code)
                saw_failover = saw_failover or \
                    health.get("failover") == "cpu-fallback" or \
                    health.get("status") == "degraded"
                _, m = _get_json(f"http://127.0.0.1:{http}/metrics")
                records_seen.append(int(m.get("records_out", 0)))
                res = out_q.query_many(uris, timeout=2.0)
                if all(v is not None for v in res.values()):
                    break
            missing = [u for u, v in res.items() if v is None]
            assert not missing, f"{len(missing)} records dropped"
            # /healthz stayed serving through the wedge — degraded, not down
            assert codes and all(c == 200 for c in codes), codes
            assert saw_failover, "wedge never surfaced on /healthz"
            # records_total is monotone and accounts for every record
            assert records_seen == sorted(records_seen)
            _, m = _get_json(f"http://127.0.0.1:{http}/metrics")
            assert int(m.get("records_out", 0)) == n
            # the supervisor verdict is visible from the probe endpoint
            _, health = _get_json(f"http://127.0.0.1:{http}/healthz")
            sup = health.get("backend_supervisor") or {}
            assert sup.get("episodes", 0) >= 1
        finally:
            try:
                proc.stdin.close()
            except OSError:
                pass
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)
