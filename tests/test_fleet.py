"""Fleet observability (ISSUE 6): mergeable-snapshot algebra, the
replica registry, the burn-rate SLO monitor, and the two-replica
federation smoke (subprocess engines, one broker, one merged
``/metrics?scope=fleet`` view)."""

import json
import os
import random
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from analytics_zoo_tpu.common import fleet, slo, telemetry
from analytics_zoo_tpu.common.telemetry import MetricsRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _hist_pair(name, streams, seed):
    """Two registries observing disjoint random streams + the union
    stream observed into a third — the ground truth for merge algebra."""
    rng = random.Random(seed)
    regs = [MetricsRegistry() for _ in range(len(streams) + 1)]
    union = regs[-1]
    for reg, n in zip(regs, streams):
        h = reg.histogram(name, "d", ("s",)).labels("x")
        hu = union.histogram(name, "d", ("s",)).labels("x")
        for _ in range(n):
            v = rng.expovariate(2.0)
            h.observe(v)
            hu.observe(v)
    return regs


class TestMergeAlgebra:
    def test_merge_equals_union_stream(self):
        """Property: merge(A, B) has exactly the bucket counts, count,
        and sum of the union stream, and its quantile estimates sit
        within one bucket width of the union registry's."""
        a, b, union = _hist_pair("zoo_t_seconds", (500, 1500), seed=7)
        merged = MetricsRegistry.merge_snapshot(a.snapshot(), b.snapshot())
        want = union.snapshot()["zoo_t_seconds"]["s=x"]
        got = merged["zoo_t_seconds"]["s=x"]
        assert got["count"] == want["count"] == 2000
        assert got["sum"] == pytest.approx(want["sum"])
        assert got["le"] == want["le"]
        assert got["bucket_counts"] == want["bucket_counts"]
        # quantiles: merged values are bucket-derived (upper edge), so
        # they can differ from the union's reservoir quantile by at most
        # the width of the bucket that holds the rank
        le = got["le"]
        for q in ("p50", "p99"):
            edge_i = next(i for i, e in enumerate(le) if got[q] <= e)
            lo = 0.0 if edge_i == 0 else le[edge_i - 1]
            assert lo <= want[q] <= le[edge_i] + 1e-12, \
                f"{q}: merged {got[q]} vs union {want[q]}"
        # reservoir stays bounded and sorted
        r = got["reservoir"]
        assert len(r) <= telemetry.SNAPSHOT_RESERVOIR and r == sorted(r)

    def test_merge_is_commutative_and_leaves_inputs_alone(self):
        a, b, _ = _hist_pair("zoo_t_seconds", (64, 256), seed=3)
        sa, sb = a.snapshot(), b.snapshot()
        sa0 = json.loads(json.dumps(sa))
        ab = MetricsRegistry.merge_snapshot(sa, sb)
        ba = MetricsRegistry.merge_snapshot(sb, sa)
        assert ab["zoo_t_seconds"]["s=x"]["bucket_counts"] == \
            ba["zoo_t_seconds"]["s=x"]["bucket_counts"]
        assert sa == sa0, "merge mutated its input snapshot"

    def test_counters_gauges_and_disjoint_families_sum(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        a.counter("zoo_n_total", "d", ("s",)).labels("x").inc(3)
        b.counter("zoo_n_total", "d", ("s",)).labels("x").inc(4)
        b.counter("zoo_n_total", "d", ("s",)).labels("y").inc(5)
        a.gauge("zoo_depth").set(2)
        b.gauge("zoo_depth").set(7)
        a.counter("zoo_only_a_total").inc(1)
        m = MetricsRegistry.merge_snapshot(a.snapshot(), b.snapshot())
        assert m["zoo_n_total"] == {"s=x": 7.0, "s=y": 5.0}
        assert m["zoo_depth"] == 9.0       # gauges sum (fleet totals)
        assert m["zoo_only_a_total"] == 1.0

    def test_mismatched_buckets_raise(self):
        a, b = MetricsRegistry(), MetricsRegistry()
        a.histogram("zoo_h_seconds", "d", buckets=(0.1, 1.0)).observe(0.2)
        b.histogram("zoo_h_seconds", "d", buckets=(0.5, 2.0)).observe(0.2)
        with pytest.raises(ValueError, match="bucket"):
            MetricsRegistry.merge_snapshot(a.snapshot(), b.snapshot())

    def test_from_snapshot_round_trips_to_prometheus(self):
        a, b, _ = _hist_pair("zoo_t_seconds", (10, 20), seed=1)
        a.counter("zoo_n_total").inc(2)
        b.counter("zoo_n_total").inc(3)
        merged = MetricsRegistry.merge_snapshot(a.snapshot(), b.snapshot())
        text = MetricsRegistry.from_snapshot(merged).prometheus_text()
        assert 'zoo_t_seconds_count{s="x"} 30' in text
        assert "zoo_n_total 5" in text
        # and the rebuilt registry snapshots back to the same counts
        again = MetricsRegistry.from_snapshot(merged).snapshot()
        assert again["zoo_t_seconds"]["s=x"]["bucket_counts"] == \
            merged["zoo_t_seconds"]["s=x"]["bucket_counts"]


class TestReplicaRegistry:
    @pytest.fixture()
    def broker(self):
        from analytics_zoo_tpu.serving.broker import Broker
        with Broker.launch(backend="python") as b:
            yield b

    def test_publish_list_partition_remove(self, broker):
        telemetry.reset_for_tests()
        reg = fleet.ReplicaRegistry("127.0.0.1", broker.port)
        now = time.time()
        fresh = fleet.ReplicaInfo("serving:1:aaa", port=81,
                                  started_at=now, last_heartbeat=now,
                                  records_total=5)
        old = fleet.ReplicaInfo("serving:2:bbb", port=82,
                                started_at=now - 600,
                                last_heartbeat=now - 600)
        reg.publish(fresh)
        reg.publish(old)
        live, stale = reg.partition()
        assert [r.replica_id for r in live] == ["serving:1:aaa"]
        assert [r.replica_id for r in stale] == ["serving:2:bbb"]
        assert live[0].records_total == 5 and live[0].port == 81
        snap = telemetry.snapshot()
        assert snap["zoo_fleet_replicas"] == {"state=live": 1.0,
                                              "state=stale": 1.0}
        reg.remove("serving:1:aaa")
        live, stale = reg.partition()
        assert live == [] and len(stale) == 1

    def test_heartbeater_counts_failures_without_raising(self):
        telemetry.reset_for_tests()
        # port 1: nothing listens — every beat must fail quietly
        reg = fleet.ReplicaRegistry("127.0.0.1", 1)
        info = fleet.ReplicaInfo("serving:3:ccc")
        hb = fleet.Heartbeater(reg, lambda: info, interval_s=60)
        assert hb.beat_once() is False
        fam = telemetry.snapshot()["zoo_fleet_heartbeat_errors_total"]
        assert fam == {"replica=serving:3:ccc": 1.0}
        hb.stop()   # deregister against a dead broker must not raise

    def test_env_knobs(self, monkeypatch):
        monkeypatch.setenv("ZOO_FLEET_HEARTBEAT_S", "0.5")
        assert fleet.heartbeat_interval_s() == 0.5
        assert fleet.stale_after_s() == 5.0  # 5 × max(interval, 1)
        monkeypatch.setenv("ZOO_FLEET_STALE_S", "42")
        assert fleet.stale_after_s() == 42.0


class TestSLOMonitor:
    def _setup(self):
        telemetry.reset_for_tests()
        reg = telemetry.get_registry()
        return (reg.histogram("zoo_serving_latency_seconds", "d",
                              ("stream", "priority")).labels("s", "default"),
                reg.counter("zoo_serving_records_total", "d",
                            ("stream",)).labels("s"),
                reg.counter("zoo_serving_record_errors_total", "d",
                            ("stream",)).labels("s"))

    def test_latency_burn_math(self):
        h, good, _ = self._setup()
        mon = slo.SLOMonitor(windows=(10.0,), shed_burn=2.0, tick_s=1.0)
        mon.tick(now=0.0)
        # 90 fast + 10 slow: bad fraction 0.10 against a 0.99 objective
        # → burn = 0.10 / 0.01 = 10
        for _ in range(90):
            h.observe(0.01)
        for _ in range(10):
            h.observe(5.0)
        mon.tick(now=5.0)
        assert mon.burn_rates()["serving_p99_latency"]["10s"] == \
            pytest.approx(10.0)
        assert mon.overloaded()
        snap = telemetry.snapshot()
        assert snap["zoo_slo_burn_rate"][
            "slo=serving_p99_latency,window=10s"] == pytest.approx(10.0)
        assert snap["zoo_slo_shedding"] == 1.0

    def test_availability_burn_math(self):
        _, good, bad = self._setup()
        mon = slo.SLOMonitor(windows=(10.0,), shed_burn=2.0, tick_s=1.0)
        mon.tick(now=0.0)
        good.inc(999)
        bad.inc(1)
        mon.tick(now=5.0)
        # bad fraction 1/1000 at objective 0.999 → burn exactly 1.0:
        # spending the budget at precisely the sustainable rate
        assert mon.burn_rates()["serving_availability"]["10s"] == \
            pytest.approx(1.0)
        assert not mon.overloaded()

    def test_multi_window_guard_blocks_blip_shedding(self):
        h, _, _ = self._setup()
        mon = slo.SLOMonitor(windows=(5.0, 60.0), shed_burn=2.0,
                             tick_s=1.0)
        mon.tick(now=0.0)
        for _ in range(2000):
            h.observe(0.01)
        mon.tick(now=50.0)
        for _ in range(20):
            h.observe(5.0)          # a late burst
        mon.tick(now=55.0)
        br = mon.burn_rates()["serving_p99_latency"]
        # short window sees only the burst (100% bad → burn 100), long
        # window dilutes it below the budget (20/2020 bad ≈ burn 0.99)
        # — multi-window agreement must NOT shed on the blip
        assert br["5s"] > 2.0 > br["60s"]
        assert not mon.overloaded()

    def test_no_traffic_means_no_burn(self):
        self._setup()
        mon = slo.SLOMonitor(windows=(10.0,))
        mon.tick(now=0.0)
        mon.tick(now=5.0)
        assert all(v == 0.0
                   for per in mon.burn_rates().values()
                   for v in per.values())
        assert not mon.overloaded()
        assert mon.report()["shedding"] is False

    def test_registry_reset_reads_as_empty_window(self):
        h, _, _ = self._setup()
        mon = slo.SLOMonitor(windows=(10.0,))
        for _ in range(10):
            h.observe(9.0)
        mon.tick(now=0.0)
        telemetry.reset_for_tests()     # cumulative series drops to zero
        mon.tick(now=5.0)
        assert not mon.overloaded()     # clamped, never negative/stuck

    def test_env_defaults(self, monkeypatch):
        monkeypatch.setenv("ZOO_SLO_P99_MS", "250")
        monkeypatch.setenv("ZOO_SLO_AVAILABILITY", "0.99")
        monkeypatch.setenv("ZOO_SLO_WINDOWS", "30,120")
        monkeypatch.setenv("ZOO_SLO_SHED_BURN", "3.5")
        mon = slo.SLOMonitor()
        lat = next(s for s in mon.slos if s.kind == "latency")
        avail = next(s for s in mon.slos if s.kind == "availability")
        assert lat.threshold_s == pytest.approx(0.25)
        assert avail.objective == 0.99
        assert mon.windows == (30.0, 120.0) and mon.shed_burn == 3.5


# --------------------------------------------------------------- federation

_REPLICA_SCRIPT = """
import sys
import numpy as np
from analytics_zoo_tpu.serving.engine import ClusterServing
from analytics_zoo_tpu.serving.frontend import FrontEnd

class Duck:
    def predict(self, x):
        return np.asarray(x) * 2.0

port, consumer = int(sys.argv[1]), sys.argv[2]
eng = ClusterServing(Duck(), port, batch_size=4, consumer=consumer)
fe = FrontEnd(port, engine=eng)
eng.start()
fe.start()
print("READY", fe.port, eng.replica_id, flush=True)
sys.stdin.readline()                    # parent closes stdin to stop us
eng.stop()
fe.stop()
print("DONE", flush=True)
"""


def _get_json(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


def test_two_replica_federation_smoke():
    """Acceptance (ISSUE 6): two live subprocess replicas on one broker;
    ``GET /metrics?scope=fleet`` from either serves merged counters and
    histograms whose ``records_total`` equals the sum over replicas, and
    ``/healthz`` reports both replicas live."""
    from analytics_zoo_tpu.serving.broker import Broker
    from analytics_zoo_tpu.serving.client import InputQueue, OutputQueue

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               ZOO_FLEET_HEARTBEAT_S="0.25")
    n_records = 20
    with Broker.launch(backend="python") as broker:
        procs = [subprocess.Popen(
            [sys.executable, "-c", _REPLICA_SCRIPT,
             str(broker.port), f"c{i}"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            text=True, cwd=REPO, env=env) for i in range(2)]
        try:
            ready = [p.stdout.readline().split() for p in procs]
            assert all(r and r[0] == "READY" for r in ready), ready
            ports = [int(r[1]) for r in ready]
            replica_ids = {r[2] for r in ready}

            in_q = InputQueue(port=broker.port)
            out_q = OutputQueue(port=broker.port)
            uris = in_q.enqueue_batch(
                (f"fed{i}", {"x": np.full(3, i, np.float32)})
                for i in range(n_records))
            res = out_q.query_many(uris, timeout=60.0)
            assert all(v is not None for v in res.values()), \
                [u for u, v in res.items() if v is None]

            # wait until BOTH replicas' heartbeats carry the final
            # records_total (heartbeat period 0.25s)
            reg = fleet.ReplicaRegistry("127.0.0.1", broker.port)
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                live, _ = reg.partition()
                if len(live) == 2 and \
                        sum(r.records_total for r in live) == n_records:
                    break
                time.sleep(0.2)
            live, _ = reg.partition()
            assert {r.replica_id for r in live} == replica_ids
            assert sum(r.records_total for r in live) == n_records
            # both replicas took work (group fan-out, 2 consumers)
            assert all(r.records_total > 0 for r in live), \
                [(r.replica_id, r.records_total) for r in live]

            # the merged fleet view from replica 0 equals the sum
            flt = _get_json(
                f"http://127.0.0.1:{ports[0]}/metrics?scope=fleet")
            assert flt["scope"] == "fleet" and flt["partial"] is False, \
                flt["replicas"]
            assert sorted(flt["replicas"]["scraped"]) == \
                sorted(replica_ids)
            m = flt["metrics"]
            assert m["zoo_serving_records_total"][
                "stream=serving_stream"] == n_records
            # histograms merged too: fleet-wide latency distribution
            # carries every record and its bucket boundaries
            lat = m["zoo_serving_latency_seconds"][
                "stream=serving_stream,priority=default"]
            assert lat["count"] == n_records
            assert sum(lat["bucket_counts"]) == n_records
            assert lat["le"] == list(telemetry.DEFAULT_BUCKETS)
            # per-replica snapshots really do sum to the fleet view
            parts = [_get_json(f"http://127.0.0.1:{p}/metrics"
                               f"?format=snapshot") for p in ports]
            by_replica = [
                part.get("zoo_serving_records_total", {})
                .get("stream=serving_stream", 0.0) for part in parts]
            assert sum(by_replica) == n_records

            # healthz sees the whole fleet
            hz = _get_json(f"http://127.0.0.1:{ports[1]}/healthz")
            assert hz["fleet"]["replicas"] == 2, hz["fleet"]
            assert hz["status"] == "ok"

            # prometheus flavor of the merged view
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{ports[0]}/metrics?scope=fleet"
                    f"&format=prometheus", timeout=10) as resp:
                text = resp.read().decode()
            assert (f'zoo_serving_records_total{{stream="serving_stream"}}'
                    f" {n_records}") in text
        finally:
            for p in procs:
                try:
                    p.stdin.close()
                except OSError:
                    pass
            for p in procs:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait(timeout=10)


def test_dead_replica_degrades_fleet_view_to_partial():
    """A registered replica that cannot be scraped lands in ``failed``
    (+``zoo_fleet_scrape_errors_total``) — the view degrades, the
    request still answers."""
    from analytics_zoo_tpu.serving.broker import Broker
    from analytics_zoo_tpu.serving.frontend import scrape_fleet

    telemetry.reset_for_tests()
    with Broker.launch(backend="python") as broker:
        reg = fleet.ReplicaRegistry("127.0.0.1", broker.port)
        now = time.time()
        # port 1: nothing listens there
        reg.publish(fleet.ReplicaInfo("serving:9:dead", port=1,
                                      started_at=now, last_heartbeat=now))
        telemetry.get_registry().counter(
            "zoo_local_records_total").inc(4)
        merged, meta = scrape_fleet("127.0.0.1", broker.port,
                                    timeout_s=0.5)
        assert meta["failed"] == ["serving:9:dead"]
        assert merged["zoo_local_records_total"] == 4.0  # local survives
        snap = telemetry.snapshot()
        assert snap["zoo_fleet_scrape_errors_total"] == \
            {"replica=serving:9:dead": 1.0}


def test_healthz_sheds_on_slo_burn_not_backlog():
    """Acceptance (ISSUE 6): /healthz flips 503 under a synthetic p99
    burn while the raw queue depth stays far below ``max_backlog`` —
    overload is now the measured signal, not the coarse backlog."""
    from analytics_zoo_tpu.serving.broker import Broker
    from analytics_zoo_tpu.serving.frontend import FrontEnd

    telemetry.reset_for_tests()
    with Broker.launch(backend="python") as broker:
        fe = FrontEnd(broker.port, engine=None, max_backlog=10000)
        mon = slo.SLOMonitor(windows=(10.0,), shed_burn=2.0, tick_s=0.01)
        slo.set_monitor(mon)
        try:
            fe.start()
            mon.tick()
            hz = _get_json(f"http://127.0.0.1:{fe.port}/healthz")
            assert hz["status"] == "ok" and hz["slo"]["shedding"] is False

            h = telemetry.get_registry().histogram(
                "zoo_serving_latency_seconds", "d",
                ("stream", "priority")).labels("serving_stream", "default")
            for _ in range(50):
                h.observe(9.0)          # every record blows the 1s p99
            time.sleep(0.05)            # tick_if_stale refires on read
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{fe.port}/healthz", timeout=10)
            assert ei.value.code == 503
            body = json.loads(ei.value.read())
            assert body["status"] == "overloaded"
            assert body["reason"] == "slo-burn"
            assert body["queue_depth"] == 0     # backlog is NOT the cause
            assert body["slo"]["shedding"] is True

            rep = _get_json(f"http://127.0.0.1:{fe.port}/slo")
            assert rep["shedding"] is True
            burn = rep["slos"][0]["windows"]["10s"]["burn"]
            assert burn > 2.0
        finally:
            fe.stop()
            slo.set_monitor(None)
            telemetry.reset_for_tests()
