"""GANEstimator test (mirrors ref pyzoo/test/zoo/tfpark/test_gan.py
spirit): learn a shifted 2D Gaussian."""

import flax.linen as nn
import numpy as np
import pytest

from analytics_zoo_tpu.learn.gan import GANEstimator


class Gen(nn.Module):
    @nn.compact
    def __call__(self, z):
        h = nn.relu(nn.Dense(16)(z))
        return nn.Dense(2)(h)


class Disc(nn.Module):
    @nn.compact
    def __call__(self, x):
        h = nn.relu(nn.Dense(16)(x))
        return nn.Dense(1)(h)[:, 0]


@pytest.mark.parametrize("loss", ["minimax", "lsgan"])
def test_gan_learns_gaussian_mean(loss, orca_ctx):
    rng = np.random.RandomState(0)
    data = rng.randn(512, 2).astype(np.float32) * 0.3 + np.array(
        [2.0, -1.0], np.float32)
    gan = GANEstimator(Gen(), Disc(), noise_dim=4,
                       loss=loss, seed=0)
    before = gan.fit(data, epochs=1, batch_size=64)
    samples0 = gan.generate(256)
    hist = gan.fit(data, epochs=40, batch_size=64)
    samples = gan.generate(256)
    assert all(np.isfinite(v) for v in hist["d_loss"] + hist["g_loss"])
    err0 = np.abs(samples0.mean(0) - [2.0, -1.0]).max()
    err = np.abs(samples.mean(0) - [2.0, -1.0]).max()
    assert err < err0, (err0, err)
    # adversarial training oscillates around the target; a loose bound is
    # the honest check
    assert err < 0.8, f"generator mean off by {err}"


def test_too_small_dataset_raises():
    gan = GANEstimator(Gen(), Disc(), noise_dim=4)
    with pytest.raises(ValueError, match="batch_size"):
        gan.fit(np.zeros((8, 2), np.float32), batch_size=32)


def test_bad_loss_raises():
    with pytest.raises(ValueError, match="minimax"):
        GANEstimator(Gen(), Disc(), noise_dim=4, loss="wgan")


def test_generate_before_fit_raises():
    with pytest.raises(RuntimeError):
        GANEstimator(Gen(), Disc(), noise_dim=4).generate(4)
