"""Tests for the image/text feature pipelines (mirrors ref test layout
pyzoo/test/zoo/feature/)."""

import numpy as np
import pytest

from analytics_zoo_tpu.feature.image import (
    ImageSet, ImageResize, ImageCenterCrop, ImageRandomCrop, ImageHFlip,
    ImageChannelNormalize, ImageBrightness, ImageAspectScale,
    ImageColorJitter, ImageExpand, ImageSetToSample, ChainedPreprocessing,
    ImageMatToTensor, ImageRandomPreprocessing, ImageMirror,
    ImageChannelOrder, PerImageNormalize,
)
from analytics_zoo_tpu.feature.text import TextSet


def _imgs(n=6, h=24, w=32):
    rng = np.random.RandomState(0)
    return [rng.randint(0, 255, (h, w, 3), dtype=np.uint8) for _ in range(n)]


class TestImageSet:
    def test_resize_crop_normalize_chain(self):
        iset = ImageSet.from_arrays(_imgs(), labels=list(range(6)))
        pipeline = ChainedPreprocessing([
            ImageResize(16, 16),
            ImageCenterCrop(8, 8),
            ImageChannelNormalize(123, 117, 104, 58, 57, 57),
            ImageMatToTensor(),
            ImageSetToSample(),
        ])
        out = iset.transform(pipeline)
        imgs = out.get_image()
        assert all(im.shape == (8, 8, 3) for im in imgs)
        assert all(im.dtype == np.float32 for im in imgs)
        ds = out.to_dataset()
        batch = ds.collect()[0]
        assert batch["x"].ndim == 4 and batch["x"].shape[1:] == (8, 8, 3)
        assert "y" in batch

    def test_hflip_is_involution(self):
        img = _imgs(1)[0]
        flipped = ImageHFlip().apply_image(ImageHFlip().apply_image(img))
        assert np.array_equal(flipped, img)

    def test_aspect_scale_short_edge(self):
        img = _imgs(1, 40, 80)[0]
        out = ImageAspectScale(min_size=20, max_size=1000).apply_image(img)
        assert min(out.shape[:2]) == 20
        assert out.shape[1] / out.shape[0] == pytest.approx(2.0, abs=0.1)

    def test_random_crop_and_jitter_shapes(self):
        img = _imgs(1)[0]
        out = ImageRandomCrop(10, 12).apply_image(img)
        assert out.shape == (10, 12, 3)
        out = ImageColorJitter().apply_image(img)
        assert out.shape == img.shape

    def test_expand_canvas(self):
        img = _imgs(1, 10, 10)[0]
        out = ImageExpand(min_expand_ratio=2.0, max_expand_ratio=2.0).apply_image(img)
        assert out.shape == (20, 20, 3)

    def test_random_preprocessing_prob0(self):
        img = _imgs(1)[0]
        f = {"image": img}
        out = ImageRandomPreprocessing(ImageResize(4, 4), prob=0.0).transform(f)
        assert out["image"].shape == img.shape

    def test_brightness_delta(self):
        img = np.zeros((4, 4, 3), np.float32)
        out = ImageBrightness(10, 10).apply_image(img)
        assert np.allclose(out, 10.0)

    def test_mirror_and_channel_order(self):
        img = _imgs(1)[0]
        assert np.array_equal(ImageMirror().apply_image(img), img[:, ::-1])
        bgr = ImageChannelOrder().apply_image(img)
        assert np.array_equal(bgr[..., 0], img[..., 2])
        assert np.array_equal(
            ImageChannelOrder().apply_image(bgr), img)

    def test_per_image_normalize(self):
        img = _imgs(1)[0]
        out = PerImageNormalize(0.0, 1.0).apply_image(img)
        assert out.min() == pytest.approx(0.0) and out.max() == pytest.approx(1.0)
        flat = PerImageNormalize(0.5, 1.0).apply_image(
            np.full((4, 4, 3), 7, np.uint8))
        assert np.allclose(flat, 0.5)

    def test_read_from_disk_with_label(self, tmp_path):
        from PIL import Image
        for cls in ("cat", "dog"):
            d = tmp_path / cls
            d.mkdir()
            for i in range(2):
                Image.fromarray(_imgs(1)[0]).save(d / f"{i}.png")
        iset = ImageSet.read(str(tmp_path), with_label=True)
        labels = sorted(iset.get_label())
        assert labels == [0, 0, 1, 1]


class TestTextSet:
    TEXTS = [
        "The quick brown fox jumps over the lazy dog",
        "A quick movie about a lazy dog",
        "the worst movie ever made, truly awful",
        "an awful film about an awful dog",
    ]

    def test_full_pipeline(self):
        ts = (TextSet.from_texts(self.TEXTS, labels=[0, 0, 1, 1])
              .tokenize().normalize().word2idx().shape_sequence(6)
              .generate_sample())
        vocab = ts.get_word_index()
        assert vocab and min(vocab.values()) == 1
        samples = ts.get_samples()
        assert all(s["x"].shape == (6,) for s in samples)
        batch = ts.to_dataset().collect()[0]
        assert batch["x"].dtype == np.int32
        assert batch["x"].shape[1] == 6

    def test_word2idx_options(self):
        ts = TextSet.from_texts(self.TEXTS).tokenize().normalize()
        v_all = ts.word2idx().get_word_index()
        v_cap = ts.word2idx(max_words_num=3).get_word_index()
        assert len(v_cap) == 3
        # remove_topN drops the most frequent words
        top_word = min(v_all, key=lambda w: v_all[w])
        v_drop = ts.word2idx(remove_topN=1).get_word_index()
        assert top_word not in v_drop

    def test_existing_map_and_oov(self):
        ts = (TextSet.from_texts(["hello unknownword"])
              .tokenize().normalize()
              .word2idx(existing_map={"hello": 1}))
        feats = ts._features()
        assert feats[0]["indexed_tokens"] == [1, 0]

    def test_shape_trunc_modes(self):
        ts = TextSet.from_texts(["a b c d e"]).tokenize().word2idx()
        pre = ts.shape_sequence(3, "pre")._features()[0]["indexed_tokens"]
        post = ts.shape_sequence(3, "post")._features()[0]["indexed_tokens"]
        assert len(pre) == 3 and len(post) == 3 and pre != post

    def test_read_folder(self, tmp_path):
        for cls, txt in (("neg", "bad terrible"), ("pos", "good great")):
            d = tmp_path / cls
            d.mkdir()
            (d / "a.txt").write_text(txt)
        ts = TextSet.read(str(tmp_path))
        assert sorted(ts.get_labels()) == [0, 1]

    def test_load_glove(self, tmp_path):
        from analytics_zoo_tpu.feature.text.textset import load_glove
        p = tmp_path / "glove.txt"
        p.write_text("hello 1.0 2.0\nworld 3.0 4.0\n")
        emb = load_glove(str(p), {"hello": 1, "world": 2}, dim=2)
        assert emb.shape == (3, 2)
        assert np.allclose(emb[1], [1.0, 2.0])
        assert np.allclose(emb[0], 0.0)
