"""Tests for the image/text feature pipelines (mirrors ref test layout
pyzoo/test/zoo/feature/)."""

import numpy as np
import pytest

from analytics_zoo_tpu.feature.image import (
    ImageSet, ImageResize, ImageCenterCrop, ImageRandomCrop, ImageHFlip,
    ImageChannelNormalize, ImageBrightness, ImageAspectScale,
    ImageColorJitter, ImageExpand, ImageSetToSample, ChainedPreprocessing,
    ImageMatToTensor, ImageRandomPreprocessing, ImageMirror,
    ImageChannelOrder, PerImageNormalize,
)
from analytics_zoo_tpu.feature.text import Relation, Relations, TextSet


def _imgs(n=6, h=24, w=32):
    rng = np.random.RandomState(0)
    return [rng.randint(0, 255, (h, w, 3), dtype=np.uint8) for _ in range(n)]


class TestImageSet:
    def test_resize_crop_normalize_chain(self):
        iset = ImageSet.from_arrays(_imgs(), labels=list(range(6)))
        pipeline = ChainedPreprocessing([
            ImageResize(16, 16),
            ImageCenterCrop(8, 8),
            ImageChannelNormalize(123, 117, 104, 58, 57, 57),
            ImageMatToTensor(),
            ImageSetToSample(),
        ])
        out = iset.transform(pipeline)
        imgs = out.get_image()
        assert all(im.shape == (8, 8, 3) for im in imgs)
        assert all(im.dtype == np.float32 for im in imgs)
        ds = out.to_dataset()
        batch = ds.collect()[0]
        assert batch["x"].ndim == 4 and batch["x"].shape[1:] == (8, 8, 3)
        assert "y" in batch

    def test_hflip_is_involution(self):
        img = _imgs(1)[0]
        flipped = ImageHFlip().apply_image(ImageHFlip().apply_image(img))
        assert np.array_equal(flipped, img)

    def test_aspect_scale_short_edge(self):
        img = _imgs(1, 40, 80)[0]
        out = ImageAspectScale(min_size=20, max_size=1000).apply_image(img)
        assert min(out.shape[:2]) == 20
        assert out.shape[1] / out.shape[0] == pytest.approx(2.0, abs=0.1)

    def test_random_crop_and_jitter_shapes(self):
        img = _imgs(1)[0]
        out = ImageRandomCrop(10, 12).apply_image(img)
        assert out.shape == (10, 12, 3)
        out = ImageColorJitter().apply_image(img)
        assert out.shape == img.shape

    def test_expand_canvas(self):
        img = _imgs(1, 10, 10)[0]
        out = ImageExpand(min_expand_ratio=2.0, max_expand_ratio=2.0).apply_image(img)
        assert out.shape == (20, 20, 3)

    def test_random_preprocessing_prob0(self):
        img = _imgs(1)[0]
        f = {"image": img}
        out = ImageRandomPreprocessing(ImageResize(4, 4), prob=0.0).transform(f)
        assert out["image"].shape == img.shape

    def test_brightness_delta(self):
        img = np.zeros((4, 4, 3), np.float32)
        out = ImageBrightness(10, 10).apply_image(img)
        assert np.allclose(out, 10.0)

    def test_mirror_and_channel_order(self):
        img = _imgs(1)[0]
        assert np.array_equal(ImageMirror().apply_image(img), img[:, ::-1])
        bgr = ImageChannelOrder().apply_image(img)
        assert np.array_equal(bgr[..., 0], img[..., 2])
        assert np.array_equal(
            ImageChannelOrder().apply_image(bgr), img)

    def test_per_image_normalize(self):
        img = _imgs(1)[0]
        out = PerImageNormalize(0.0, 1.0).apply_image(img)
        assert out.min() == pytest.approx(0.0) and out.max() == pytest.approx(1.0)
        flat = PerImageNormalize(0.5, 1.0).apply_image(
            np.full((4, 4, 3), 7, np.uint8))
        assert np.allclose(flat, 0.5)

    def test_read_from_disk_with_label(self, tmp_path):
        from PIL import Image
        for cls in ("cat", "dog"):
            d = tmp_path / cls
            d.mkdir()
            for i in range(2):
                Image.fromarray(_imgs(1)[0]).save(d / f"{i}.png")
        iset = ImageSet.read(str(tmp_path), with_label=True)
        labels = sorted(iset.get_label())
        assert labels == [0, 0, 1, 1]


class TestTextSet:
    TEXTS = [
        "The quick brown fox jumps over the lazy dog",
        "A quick movie about a lazy dog",
        "the worst movie ever made, truly awful",
        "an awful film about an awful dog",
    ]

    def test_full_pipeline(self):
        ts = (TextSet.from_texts(self.TEXTS, labels=[0, 0, 1, 1])
              .tokenize().normalize().word2idx().shape_sequence(6)
              .generate_sample())
        vocab = ts.get_word_index()
        assert vocab and min(vocab.values()) == 1
        samples = ts.get_samples()
        assert all(s["x"].shape == (6,) for s in samples)
        batch = ts.to_dataset().collect()[0]
        assert batch["x"].dtype == np.int32
        assert batch["x"].shape[1] == 6

    def test_word2idx_options(self):
        ts = TextSet.from_texts(self.TEXTS).tokenize().normalize()
        v_all = ts.word2idx().get_word_index()
        v_cap = ts.word2idx(max_words_num=3).get_word_index()
        assert len(v_cap) == 3
        # remove_topN drops the most frequent words
        top_word = min(v_all, key=lambda w: v_all[w])
        v_drop = ts.word2idx(remove_topN=1).get_word_index()
        assert top_word not in v_drop

    def test_existing_map_and_oov(self):
        ts = (TextSet.from_texts(["hello unknownword"])
              .tokenize().normalize()
              .word2idx(existing_map={"hello": 1}))
        feats = ts._features()
        assert feats[0]["indexed_tokens"] == [1, 0]

    def test_shape_trunc_modes(self):
        ts = TextSet.from_texts(["a b c d e"]).tokenize().word2idx()
        pre = ts.shape_sequence(3, "pre")._features()[0]["indexed_tokens"]
        post = ts.shape_sequence(3, "post")._features()[0]["indexed_tokens"]
        assert len(pre) == 3 and len(post) == 3 and pre != post

    def test_read_folder(self, tmp_path):
        for cls, txt in (("neg", "bad terrible"), ("pos", "good great")):
            d = tmp_path / cls
            d.mkdir()
            (d / "a.txt").write_text(txt)
        ts = TextSet.read(str(tmp_path))
        assert sorted(ts.get_labels()) == [0, 1]

    def test_load_glove(self, tmp_path):
        from analytics_zoo_tpu.feature.text.textset import load_glove
        p = tmp_path / "glove.txt"
        p.write_text("hello 1.0 2.0\nworld 3.0 4.0\n")
        emb = load_glove(str(p), {"hello": 1, "world": 2}, dim=2)
        assert emb.shape == (3, 2)
        assert np.allclose(emb[1], [1.0, 2.0])
        assert np.allclose(emb[0], 0.0)


class TestRelations:
    def _corpora(self):
        q = TextSet.from_texts(["what is tpu", "how fast is light"],
                               ids=["q1", "q2"])
        a = TextSet.from_texts(
            ["a tensor processing unit", "a kind of pasta",
             "three hundred thousand km per second", "a type of bird"],
            ids=["a1", "a2", "a3", "a4"])
        q = q.tokenize().normalize().word2idx().shape_sequence(4)
        a = (a.tokenize().normalize()
             .word2idx(existing_map=q.get_word_index())
             .shape_sequence(6))
        # extend vocab for answer words not in questions
        return q, a

    def test_relation_read_roundtrip(self, tmp_path):
        p = tmp_path / "rel.csv"
        p.write_text("q1,a1,1\nq1,a2,0\nq2,a3,1\n")
        rels = Relations.read(str(p))
        assert rels[0] == Relation("q1", "a1", 1)
        assert [r.label for r in rels] == [1, 0, 1]

    def test_relation_read_parquet(self, tmp_path):
        import pandas as pd
        df = pd.DataFrame({"id1": ["q1"], "id2": ["a2"], "label": [0]})
        df.to_parquet(tmp_path / "rel.parquet")
        rels = Relations.read_parquet(str(tmp_path / "rel.parquet"))
        assert rels == [Relation("q1", "a2", 0)]

    def test_from_relation_pairs_shapes_and_join(self):
        q, a = self._corpora()
        rels = [Relation("q1", "a1", 1), Relation("q1", "a2", 0),
                Relation("q2", "a3", 1), Relation("q2", "a4", 0),
                Relation("q2", "a2", 0)]
        ts = TextSet.from_relation_pairs(rels, q, a)
        samples = ts.get_samples()
        # q1: 1 pos x 1 neg; q2: 1 pos x 2 neg → 3 pairs
        assert len(samples) == 3
        for s in samples:
            assert s["x"].shape == (2, 10)
            np.testing.assert_array_equal(s["y"], [[1.0], [0.0]])
        # the positive row must embed the positive answer's ids
        a_index = {f["id"]: f["indexed_tokens"] for f in a._features()}
        q_index = {f["id"]: f["indexed_tokens"] for f in q._features()}
        np.testing.assert_array_equal(
            samples[0]["x"][0], np.concatenate([q_index["q1"],
                                                a_index["a1"]]))

    def test_from_relation_lists_shapes(self):
        q, a = self._corpora()
        rels = [("q1", "a1", 1), ("q1", "a2", 0), ("q1", "a4", 0),
                ("q2", "a3", 1)]
        ts = TextSet.from_relation_lists(rels, q, a)
        samples = ts.get_samples()
        assert samples[0]["x"].shape == (3, 10)
        assert samples[0]["y"].tolist() == [[1.0], [0.0], [0.0]]
        assert samples[1]["x"].shape == (1, 10)

    def test_missing_id_raises(self):
        q, a = self._corpora()
        with pytest.raises(KeyError):
            TextSet.from_relation_pairs([("qX", "a1", 1), ("qX", "a2", 0)],
                                        q, a)
        bare = TextSet.from_texts(["no ids"]).tokenize().word2idx()
        with pytest.raises(ValueError):
            TextSet.from_relation_pairs([("q1", "a1", 1)], bare, a)

    def test_knrm_trains_on_relation_pairs(self, orca_ctx):
        from analytics_zoo_tpu.models.textmatching import KNRM
        q, a = self._corpora()
        rng = np.random.RandomState(0)
        rels = []
        for qi in ("q1", "q2"):
            for ai in ("a1", "a2", "a3", "a4"):
                rels.append(Relation(qi, ai, int(rng.rand() > 0.5)))
        # ensure at least one pos+neg per query
        rels += [Relation("q1", "a1", 1), Relation("q1", "a2", 0)]
        ts = TextSet.from_relation_pairs(rels, q, a)
        xs = np.concatenate([s["x"] for s in ts.get_samples()])  # flatten pairs
        ys = np.concatenate([s["y"] for s in ts.get_samples()])
        vocab = max(max(f["indexed_tokens"]) for f in a._features())
        m = KNRM(text1_length=4, text2_length=6, vocab_size=vocab + 1,
                 embed_dim=8, kernel_num=5)
        m.compile(optimizer="adam", loss="binary_crossentropy")
        m.fit(xs.astype(np.float32), ys, batch_size=8, nb_epoch=1)
        scores = np.asarray(m.predict(xs.astype(np.float32)))
        assert scores.shape == (len(xs), 1)
        from analytics_zoo_tpu.models.textmatching.knrm import (
            evaluate_map, evaluate_ndcg)
        assert 0.0 <= evaluate_ndcg(ys[:, 0], scores[:, 0], k=3) <= 1.0
        assert 0.0 <= evaluate_map(ys[:, 0], scores[:, 0]) <= 1.0


class TestRefImageSpellingParity:
    """Every class in the reference's imagePreprocessing.py has a spelling
    here (completing §2.2's 'handful of ref ops still absent')."""

    REF_CLASSES = [
        "ImagePreprocessing", "ImageBytesToMat", "ImagePixelBytesToMat",
        "ImageResize", "ImageBrightness", "ImageChannelNormalize",
        "PerImageNormalize", "ImageMatToTensor", "ImageSetToSample",
        "ImageHue", "ImageSaturation", "ImageChannelOrder",
        "ImageColorJitter", "ImageAspectScale", "ImageRandomAspectScale",
        "ImagePixelNormalize", "ImageRandomCrop", "ImageCenterCrop",
        "ImageFixedCrop", "ImageExpand", "ImageFiller", "ImageHFlip",
        "ImageMirror", "ImageFeatureToTensor", "ImageFeatureToSample",
        "RowToImageFeature", "ImageRandomPreprocessing",
    ]

    def test_all_ref_classes_importable(self):
        from analytics_zoo_tpu.feature import image as zimg
        for name in self.REF_CLASSES:
            assert hasattr(zimg, name), f"missing image op {name}"

    def test_pixel_bytes_to_mat(self):
        from analytics_zoo_tpu.feature.image import ImagePixelBytesToMat
        raw = np.arange(2 * 3 * 3, dtype=np.uint8)
        f = ImagePixelBytesToMat(shape=(2, 3, 3)).transform(
            {"bytes": raw.tobytes()})
        np.testing.assert_array_equal(f["image"], raw.reshape(2, 3, 3))
        # shape from the feature itself
        f = ImagePixelBytesToMat().transform(
            {"bytes": raw.tobytes(), "shape": (2, 3, 3)})
        assert f["image"].shape == (2, 3, 3)
        with pytest.raises(ValueError, match="shape"):
            ImagePixelBytesToMat().transform({"bytes": raw.tobytes()})

    def test_pixel_normalize_flat_means(self):
        from analytics_zoo_tpu.feature.image import ImagePixelNormalize
        img = np.ones((2, 2, 3), np.float32) * 10
        means = np.arange(12, dtype=np.float32)
        out = ImagePixelNormalize(means).transform({"image": img})["image"]
        np.testing.assert_allclose(out, 10 - means.reshape(2, 2, 3))

    def test_feature_to_tensor_and_sample(self):
        from analytics_zoo_tpu.feature.image import (
            ImageFeatureToSample, ImageFeatureToTensor,
        )
        img = np.ones((4, 4, 3), np.uint8)
        t = ImageFeatureToTensor().transform({"image": img})
        assert t.dtype == np.float32 and t.shape == (4, 4, 3)
        s = ImageFeatureToSample().transform({"image": img, "label": 2})
        assert s["x"].shape == (4, 4, 3) and int(s["y"]) == 2

    def test_row_to_image_feature_pipeline(self):
        """Row (bytes) → feature → decode → sample, end to end (the
        reference's DataFrame image-pipeline entry)."""
        import io
        from PIL import Image
        from analytics_zoo_tpu.feature.image import (
            ChainedPreprocessing, ImageBytesToMat, ImageFeatureToSample,
            ImageResize, RowToImageFeature,
        )
        buf = io.BytesIO()
        Image.fromarray(np.zeros((8, 6, 3), np.uint8)).save(buf, "PNG")
        row = {"image": buf.getvalue(), "uri": "a.png", "label": 1}
        pipe = ChainedPreprocessing([
            RowToImageFeature(), ImageBytesToMat(), ImageResize(4, 4),
            ImageFeatureToSample()])
        s = pipe.transform(row)
        assert s["x"].shape == (4, 4, 3) and int(s["y"]) == 1
