"""Tests for Friesian FeatureTable (mirrors ref
pyzoo/test/zoo/friesian/feature/test_table.py)."""

import numpy as np
import pandas as pd
import pytest

from analytics_zoo_tpu.friesian.feature import FeatureTable, StringIndex, Table


def ratings_df():
    return pd.DataFrame({
        "user": [1, 1, 1, 2, 2, 3],
        "item": [10, 11, 12, 10, 13, 11],
        "time": [1, 2, 3, 1, 2, 1],
        "price": [1.0, np.nan, 3.0, 4.0, 5.0, np.nan],
        "cat": ["a", "b", "a", "c", "a", None],
    })


class TestTableBasics:
    def test_size_select_drop_rename(self):
        t = FeatureTable.from_pandas(ratings_df(), 2)
        assert t.size() == 6
        assert t.select("user", "item").col_names() == ["user", "item"]
        assert "price" not in t.drop("price").col_names()
        assert "u" in t.rename({"user": "u"}).col_names()

    def test_fillna_dropna_fill_median(self):
        t = FeatureTable.from_pandas(ratings_df(), 2)
        assert t.fillna(0.0, ["price"]).to_pandas()["price"].isna().sum() == 0
        assert t.dropna(["price"]).size() == 4
        filled = t.fill_median("price").to_pandas()["price"]
        assert filled.isna().sum() == 0
        assert filled[1] == pytest.approx(3.5)  # median of 1,3,4,5

    def test_clip_log_normalize(self):
        t = FeatureTable.from_pandas(ratings_df(), 2).fillna(0.0, ["price"])
        clipped = t.clip(["price"], min=2.0, max=4.0).to_pandas()["price"]
        assert clipped.min() >= 2.0 and clipped.max() <= 4.0
        logged = t.log(["price"]).to_pandas()["price"]
        assert logged.max() == pytest.approx(np.log1p(5.0))
        normed = t.normalize(["price"]).to_pandas()["price"]
        assert normed.min() == 0.0 and normed.max() == 1.0

    def test_filter_distinct_join(self):
        t = FeatureTable.from_pandas(ratings_df(), 2)
        assert t.filter("user == 1").size() == 3
        assert t.filter(lambda d: d["user"] == 2).size() == 2
        dup = FeatureTable.from_pandas(
            pd.concat([ratings_df(), ratings_df()], ignore_index=True), 3)
        assert dup.distinct().size() == 6
        side = Table.from_pandas(pd.DataFrame({"user": [1, 2, 3],
                                               "age": [20, 30, 40]}), 1)
        joined = t.join(side, on="user").to_pandas()
        assert "age" in joined.columns and len(joined) == 6

    def test_merge_cols_and_udf(self):
        t = FeatureTable.from_pandas(ratings_df(), 1).fillna(0, ["price"])
        merged = t.merge_cols(["user", "item"], "ui").to_pandas()
        assert merged["ui"][0] == [1, 10]
        out = t.transform_python_udf("user", "user2", lambda u: u * 2)
        assert out.to_pandas()["user2"].tolist() == [2, 2, 2, 4, 4, 6]

    def test_parquet_roundtrip(self, tmp_path):
        t = FeatureTable.from_pandas(ratings_df().drop(columns=["cat"]), 2)
        t.write_parquet(str(tmp_path / "t"))
        back = FeatureTable.read_parquet(str(tmp_path / "t"))
        assert back.size() == 6


class TestCategorical:
    def test_gen_string_idx_and_encode(self):
        t = FeatureTable.from_pandas(ratings_df(), 2)
        [idx] = t.gen_string_idx("cat", freq_limit=None)
        m = idx.to_dict()
        assert m["a"] == 1  # most frequent gets id 1
        assert set(m.values()) == {1, 2, 3}
        enc = t.encode_string("cat", [idx]).to_pandas()
        assert enc["cat"].tolist()[0] == 1
        assert enc["cat"].tolist()[5] == 0  # None -> 0
        assert enc["cat"].dtype == np.int64

    def test_freq_limit(self):
        t = FeatureTable.from_pandas(ratings_df(), 1)
        [idx] = t.gen_string_idx("cat", freq_limit=2)
        assert set(idx.to_dict().keys()) == {"a"}

    def test_string_index_parquet_roundtrip(self, tmp_path):
        t = FeatureTable.from_pandas(ratings_df(), 1)
        [idx] = t.gen_string_idx("cat")
        idx.write_parquet(str(tmp_path / "idx"))
        back = StringIndex.read_parquet(str(tmp_path / "idx"))
        assert back.col_name == "cat"
        assert back.to_dict() == idx.to_dict()

    def test_cross_columns(self):
        t = FeatureTable.from_pandas(ratings_df(), 2)
        crossed = t.cross_columns([["user", "item"]], [100]).to_pandas()
        assert "user_item" in crossed.columns
        assert crossed["user_item"].between(0, 99).all()
        # deterministic
        again = t.cross_columns([["user", "item"]], [100]).to_pandas()
        assert crossed["user_item"].tolist() == again["user_item"].tolist()


class TestSequenceFeatures:
    def test_add_negative_samples(self):
        t = FeatureTable.from_pandas(
            pd.DataFrame({"user": [1, 2], "item": [3, 4]}), 1)
        out = t.add_negative_samples(item_size=10, neg_num=2).to_pandas()
        assert len(out) == 6
        pos = out[out["label"] == 1]
        neg = out[out["label"] == 0]
        assert len(pos) == 2 and len(neg) == 4
        # negatives never collide with the positive item of their row
        for _, r in neg.iterrows():
            orig = {1: 3, 2: 4}[r["user"]]
            assert r["item"] != orig
            assert 1 <= r["item"] <= 10

    def test_add_hist_seq(self):
        t = FeatureTable.from_pandas(ratings_df(), 2)
        out = t.add_hist_seq("user", ["item"], sort_col="time",
                             min_len=1, max_len=2)
        df = out.to_pandas()
        # user1 has rows at i=1,2; user2 at i=1; user3 none
        assert len(df) == 3
        u1 = df[df["user"] == 1].sort_values("time")
        assert u1["item_hist_seq"].tolist() == [[10], [10, 11]]

    def test_neg_hist_pad_mask_length(self):
        t = FeatureTable.from_pandas(ratings_df(), 1)
        out = t.add_hist_seq("user", ["item"], min_len=1, max_len=5)
        out = out.add_neg_hist_seq(20, "item_hist_seq", neg_num=2)
        df = out.to_pandas()
        assert all(len(n) == 2 for n in df["neg_item_hist_seq"])
        assert all(len(n[0]) == len(h) for n, h in
                   zip(df["neg_item_hist_seq"], df["item_hist_seq"]))
        out = out.add_length("item_hist_seq")
        out = out.mask_pad(padding_cols=["item_hist_seq"],
                           mask_cols=["item_hist_seq"], seq_len=4)
        df = out.to_pandas()
        assert all(len(h) == 4 for h in df["item_hist_seq"])
        assert all(len(m) == 4 for m in df["item_hist_seq_mask"])
        assert df["item_hist_seq_length"].tolist() == [1, 2, 1]

    def test_add_feature(self):
        t = FeatureTable.from_pandas(
            pd.DataFrame({"item": [1, 2], "hist": [[1, 2], [2, 9]]}), 1)
        lookup = FeatureTable.from_pandas(
            pd.DataFrame({"item": [1, 2], "cat": [7, 8]}), 1)
        out = t.add_feature(["item", "hist"], lookup, default_value=0)
        df = out.to_pandas()
        assert df["item_feature"].tolist() == [7, 8]
        assert df["hist_feature"].tolist() == [[7, 8], [8, 0]]

    def test_to_sharded_arrays(self):
        t = FeatureTable.from_pandas(
            pd.DataFrame({"user": [1, 2, 3, 4], "item": [5, 6, 7, 8],
                          "label": [1, 0, 1, 0]}), 2)
        ds = t.to_sharded_arrays(["user", "item"], "label")
        batch = ds.collect()[0]
        assert isinstance(batch["x"], list) and len(batch["x"]) == 2
        assert batch["y"].shape == (2,)
