"""ARIMA / Prophet-style forecaster tests (ref zouwu test_arima /
test_prophet shapes on synthetic series)."""

import numpy as np
import pandas as pd
import pytest

from analytics_zoo_tpu.zouwu.model import ARIMAForecaster, ProphetForecaster


def _ar_series(n=400, phi=0.7, seed=0):
    rng = np.random.RandomState(seed)
    y = np.zeros(n)
    for i in range(1, n):
        y[i] = phi * y[i - 1] + rng.randn() * 0.3
    return y


class TestARIMA:
    def test_ar1_coefficient_recovered(self):
        f = ARIMAForecaster(p=1, d=0, q=0)
        f.fit(_ar_series())
        phi_hat = f._coef[1]
        assert abs(phi_hat - 0.7) < 0.12, phi_hat

    def test_forecast_decays_to_mean(self):
        y = _ar_series()
        f = ARIMAForecaster(p=1, d=0, q=0).fit(y)
        pred = f.predict(horizon=50)
        assert pred.shape == (50,)
        assert abs(pred[-1]) < abs(pred[0]) + 0.1  # AR(1) reverts to mean

    def test_trend_with_differencing(self):
        t = np.arange(300, dtype=float)
        y = 2.0 * t + _ar_series(300, phi=0.3, seed=1)
        f = ARIMAForecaster(p=1, d=1, q=1).fit(y)
        pred = f.predict(horizon=10)
        # slope ~2/step must carry into the forecast
        assert pred[-1] > y[-1] + 10, (y[-1], pred[-1])
        assert abs((pred[-1] - pred[0]) / 9 - 2.0) < 0.5

    def test_double_differencing_quadratic(self):
        """d=2 on y = t^2: second difference is constant 2, so the forecast
        must continue the quadratic."""
        t = np.arange(200, dtype=float)
        y = t ** 2
        f = ARIMAForecaster(p=1, d=2, q=0).fit(y)
        pred = f.predict(horizon=5)
        want = (np.arange(200, 205, dtype=float)) ** 2
        rel = np.abs(pred - want) / want
        assert rel.max() < 0.02, (pred, want)

    def test_save_restore(self, tmp_path):
        f = ARIMAForecaster(p=2, d=0, q=1).fit(_ar_series())
        p1 = f.predict(5)
        f.save(str(tmp_path))
        g = ARIMAForecaster().restore(str(tmp_path))
        np.testing.assert_allclose(g.predict(5), p1)

    def test_too_short_raises(self):
        with pytest.raises(ValueError, match="too short"):
            ARIMAForecaster(p=2, d=0, q=2).fit(np.ones(8))

    def test_bad_order_raises(self):
        with pytest.raises(ValueError):
            ARIMAForecaster(p=0, d=0, q=0)


def _seasonal_df(n_days=120, seed=0):
    rng = np.random.RandomState(seed)
    ds = pd.date_range("2025-01-01", periods=n_days, freq="D")
    t = np.arange(n_days, dtype=float)
    y = 0.5 * t + 5 * np.sin(2 * np.pi * t / 7) + rng.randn(n_days) * 0.3
    return pd.DataFrame({"ds": ds, "y": y})


class TestProphet:
    def test_learns_trend_and_weekly_cycle(self):
        df = _seasonal_df()
        f = ProphetForecaster(daily_seasonality=False).fit(df)
        out = f.predict(horizon=14, freq="D")
        assert list(out.columns) == ["ds", "yhat"]
        t_future = np.arange(120, 134, dtype=float)
        want = 0.5 * t_future + 5 * np.sin(2 * np.pi * t_future / 7)
        err = np.abs(out["yhat"].to_numpy() - want).max()
        assert err < 1.5, err

    def test_evaluate_in_sample(self):
        df = _seasonal_df()
        f = ProphetForecaster(daily_seasonality=False).fit(df)
        scores = f.evaluate(df, metrics=("mse", "mae"))
        assert scores["mse"] < 0.5

    def test_save_restore(self, tmp_path):
        df = _seasonal_df()
        f = ProphetForecaster(daily_seasonality=False).fit(df)
        p1 = f.predict(7)["yhat"].to_numpy()
        f.save(str(tmp_path))
        g = ProphetForecaster().restore(str(tmp_path))
        np.testing.assert_allclose(g.predict(7)["yhat"].to_numpy(), p1)

    def test_monthly_frequency(self):
        """Calendar frequencies must work (ref Prophet supports monthly)."""
        df = _seasonal_df(200)
        f = ProphetForecaster(daily_seasonality=False,
                              weekly_seasonality=False).fit(df)
        out = f.predict(horizon=3, freq="MS")
        assert len(out) == 3
        assert out["ds"].dt.day.tolist() == [1, 1, 1]

    def test_predict_before_fit_raises(self):
        with pytest.raises(RuntimeError):
            ProphetForecaster().predict(3)
