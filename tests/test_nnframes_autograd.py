"""Tests for NNFrames (ref pyzoo/test/zoo/pipeline/nnframes/) and autograd
(ref pyzoo/test/zoo/pipeline/autograd/test_autograd.py)."""

import numpy as np
import pandas as pd
import pytest

from analytics_zoo_tpu.keras import autograd as A
from analytics_zoo_tpu.keras.autograd import CustomLoss, Lambda
from analytics_zoo_tpu.nnframes import (
    NNClassifier, NNEstimator, NNImageReader, NNModel,
)


class TestAutogradMath:
    def _eval(self, build, *arrays):
        """build(*vars) -> output node; evaluated on arrays."""
        vs = [A.Variable(input_shape=a.shape[1:]) for a in arrays]
        out = build(*vs)
        fn = A.to_function(vs, out)
        import jax
        return np.asarray(jax.device_get(fn(*arrays)))

    def test_elementwise_ops_match_numpy(self):
        x = np.random.RandomState(0).rand(4, 3).astype(np.float32) + 0.5
        np.testing.assert_allclose(
            self._eval(lambda v: A.abs(v * -2.0), x), np.abs(x * -2),
            rtol=1e-6)
        np.testing.assert_allclose(
            self._eval(A.exp, x), np.exp(x), rtol=1e-5)
        np.testing.assert_allclose(
            self._eval(A.log, x), np.log(x), rtol=1e-5)
        np.testing.assert_allclose(
            self._eval(A.sqrt, x), np.sqrt(x), rtol=1e-6)
        np.testing.assert_allclose(
            self._eval(lambda v: A.clip(v, 0.6, 1.0), x),
            np.clip(x, 0.6, 1.0), rtol=1e-6)
        np.testing.assert_allclose(
            self._eval(lambda v: A.pow(v, 3.0), x), x ** 3, rtol=1e-5)
        np.testing.assert_allclose(
            self._eval(A.softsign, x), x / (1 + np.abs(x)), rtol=1e-6)

    def test_operator_sugar(self):
        x = np.random.RandomState(1).randn(4, 3).astype(np.float32)
        y = np.random.RandomState(2).randn(4, 3).astype(np.float32)
        got = self._eval(lambda a, b: (a - b) * 2.0 + 1.0, x, y)
        np.testing.assert_allclose(got, (x - y) * 2 + 1, rtol=1e-6)
        got = self._eval(lambda a, b: a / (b * b + 4.0), x, y)
        np.testing.assert_allclose(got, x / (y * y + 4), rtol=1e-5)

    def test_reductions_axis_counts_batch(self):
        x = np.random.RandomState(3).randn(4, 3, 2).astype(np.float32)
        np.testing.assert_allclose(
            self._eval(lambda v: A.mean(v, axis=1), x), x.mean(1), rtol=1e-6)
        np.testing.assert_allclose(
            self._eval(lambda v: A.sum(v, axis=2), x), x.sum(2), rtol=1e-5)
        np.testing.assert_allclose(
            self._eval(lambda v: A.max(v, axis=1), x), x.max(1), rtol=1e-6)

    def test_batch_dot_and_l2_normalize(self):
        a = np.random.RandomState(4).randn(3, 2, 4).astype(np.float32)
        b = np.random.RandomState(5).randn(3, 4, 5).astype(np.float32)
        got = self._eval(lambda u, v: A.batch_dot(u, v), a, b)
        np.testing.assert_allclose(got, np.einsum("bij,bjk->bik", a, b),
                                   rtol=1e-5)
        x = np.random.RandomState(6).randn(4, 3).astype(np.float32)
        got = self._eval(lambda v: A.l2_normalize(v, axis=1), x)
        np.testing.assert_allclose(
            got, x / np.linalg.norm(x, axis=1, keepdims=True), rtol=1e-5)

    def test_shape_ops(self):
        x = np.random.RandomState(7).randn(4, 3).astype(np.float32)
        got = self._eval(lambda v: A.expand_dims(v, 1), x)
        assert got.shape == (4, 1, 3)
        got = self._eval(lambda v: A.squeeze(A.expand_dims(v, 2), 2), x)
        np.testing.assert_allclose(got, x)

    def test_to_function_rejects_parameterized(self):
        from analytics_zoo_tpu.keras.layers import Dense
        v = A.Variable(input_shape=(3,))
        out = Dense(2)(v)
        with pytest.raises(ValueError, match="parameterized"):
            A.to_function([v], out)

    def test_custom_loss_in_training(self, orca_ctx):
        from analytics_zoo_tpu.keras.models import Sequential
        from analytics_zoo_tpu.keras.layers import Dense

        loss = CustomLoss(
            lambda yt, yp: A.mean(A.square(yt - yp)), y_shape=(1,))
        # spot check (ref CustomLoss.forward)
        val = loss.forward(np.zeros((2, 1)), np.ones((2, 1)))
        np.testing.assert_allclose(val, 1.0, rtol=1e-6)

        m = Sequential()
        m.add(Dense(8, input_shape=(4,), activation="relu"))
        m.add(Dense(1))
        m.compile(optimizer="adam", loss=loss)
        rng = np.random.RandomState(0)
        x = rng.randn(64, 4).astype(np.float32)
        y = x.sum(1, keepdims=True).astype(np.float32)
        h = m.fit(x, y, batch_size=16, nb_epoch=5)
        assert h["loss"][-1] < h["loss"][0]

    def test_lambda_layer_in_model(self, orca_ctx):
        from analytics_zoo_tpu.keras.models import Model
        from analytics_zoo_tpu.keras.layers import Dense
        from analytics_zoo_tpu.keras.engine import Input

        inp = Input(shape=(4,))
        h = Dense(6)(inp)
        out = Lambda(lambda a: a * 2.0 + 1.0)(h)
        m = Model(inp, out)
        x = np.random.RandomState(0).randn(8, 4).astype(np.float32)
        base = np.asarray(Model(inp, h).predict(x, distributed=False))
        got = np.asarray(m.predict(x, distributed=False))
        np.testing.assert_allclose(got, base * 2 + 1, rtol=1e-5)


def _toy_df(n=64, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 4).astype(np.float32)
    y = (x.sum(1) > 0).astype(np.int64)
    return pd.DataFrame({
        "features": [row for row in x],
        "label": y,
        "f0": x[:, 0], "f1": x[:, 1], "f2": x[:, 2], "f3": x[:, 3],
    })


def _mlp(num_out=2, activation="softmax"):
    from analytics_zoo_tpu.keras.models import Sequential
    from analytics_zoo_tpu.keras.layers import Dense
    m = Sequential()
    m.add(Dense(8, input_shape=(4,), activation="relu"))
    m.add(Dense(num_out, activation=activation))
    return m


class TestNNFrames:
    def test_nnestimator_fit_transform(self, orca_ctx):
        df = _toy_df()
        est = (NNEstimator(_mlp(), "sparse_categorical_crossentropy")
               .setBatchSize(16).setMaxEpoch(3)
               .set_features_col("features").set_label_col("label"))
        model = est.fit(df)
        assert isinstance(model, NNModel)
        out = model.transform(df)
        assert "prediction" in out.columns
        probs = np.stack(out["prediction"].tolist())
        assert probs.shape == (64, 2)
        np.testing.assert_allclose(probs.sum(1), 1.0, atol=1e-4)

    def test_scalar_feature_cols(self, orca_ctx):
        df = _toy_df()
        est = (NNEstimator(_mlp(), "sparse_categorical_crossentropy")
               .set_features_col(["f0", "f1", "f2", "f3"])
               .set_label_col("label").setMaxEpoch(2).setBatchSize(16))
        model = est.fit(df)
        out = model.transform(df)
        assert len(out["prediction"]) == 64

    def test_nnclassifier_argmax(self, orca_ctx):
        df = _toy_df()
        clf = (NNClassifier(_mlp(), "sparse_categorical_crossentropy")
               .setBatchSize(16).setMaxEpoch(30)
               .set_features_col("features").set_label_col("label"))
        model = clf.fit(df)
        out = model.transform(df)
        preds = out["prediction"].to_numpy()
        assert set(np.unique(preds)) <= {0.0, 1.0}
        acc = (preds == df["label"].to_numpy()).mean()
        assert acc > 0.7, f"classifier barely better than chance: {acc}"

    def test_model_save_load(self, orca_ctx, tmp_path):
        df = _toy_df()
        est = (NNEstimator(_mlp(), "sparse_categorical_crossentropy")
               .setBatchSize(16).setMaxEpoch(1)
               .set_features_col("features").set_label_col("label"))
        model = est.fit(df)
        p1 = np.stack(model.transform(df)["prediction"].tolist())
        path = str(tmp_path / "nnmodel")
        model.save(path)
        est2 = (NNEstimator(_mlp(), "sparse_categorical_crossentropy")
                .setBatchSize(8)
                .set_features_col("features").set_label_col("label"))
        model2 = est2.fit(df.head(8))  # build params, then overwrite
        model2.load(path)
        p2 = np.stack(model2.transform(df)["prediction"].tolist())
        np.testing.assert_allclose(p2, p1, atol=1e-5)

    def test_image_reader(self, tmp_path, orca_ctx):
        from PIL import Image
        d = tmp_path / "imgs"
        d.mkdir()
        rng = np.random.RandomState(0)
        for i in range(3):
            Image.fromarray(
                rng.randint(0, 255, (10, 12, 3), dtype=np.uint8)).save(
                d / f"im{i}.png")
        df = NNImageReader.read_images(str(d), resize_h=8, resize_w=8)
        assert len(df) == 3
        assert df["image"][0].shape == (8, 8, 3)
        assert all(df["origin"].str.endswith(".png"))
