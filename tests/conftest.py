"""Test bootstrap: fake an 8-chip TPU slice with virtual CPU devices.

Mirrors the reference's test strategy (SURVEY.md §4): Spark ``local[n]``
simulated multi-node; here ``--xla_force_host_platform_device_count=8``
simulates an 8-device mesh so every sharding/collective path runs for real.
Must run before jax is imported anywhere.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

# The repo root must be importable when tests run from a subdir.
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# In the axon environment a sitecustomize imports jax before conftest runs,
# so the env vars above are too late for the already-imported module — force
# the platform through the config API as well.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_context():
    yield
    from analytics_zoo_tpu.common import context as ctx
    ctx.stop_orca_context()


@pytest.fixture
def orca_ctx():
    from analytics_zoo_tpu import init_orca_context
    return init_orca_context(cluster_mode="local")
