"""Tests for 3D image transforms (ref pyzoo/test/zoo/feature/image3d) and
the parquet image dataset (ref pyzoo/test/zoo/orca/data/test_parquet_*)."""

import os

import numpy as np
import pytest

from analytics_zoo_tpu.feature.image3d import (
    AffineTransform3D, CenterCrop3D, Crop3D, RandomCrop3D, Rotate3D,
    Warp3D, rotation_matrix,
)
from analytics_zoo_tpu.data.image import (
    Image, NDarray, ParquetDataset, Scalar, write_from_directory,
    write_mnist, write_ndarrays,
)


def _volume(d=8, h=10, w=12, seed=0):
    return np.random.RandomState(seed).rand(d, h, w).astype(np.float32)


class TestCrop3D:
    def test_fixed_crop(self):
        v = _volume()
        out = Crop3D(start=[1, 2, 3], patch_size=[4, 5, 6]).apply_image(v)
        np.testing.assert_array_equal(out, v[1:5, 2:7, 3:9])

    def test_fixed_crop_out_of_range_raises(self):
        with pytest.raises(ValueError, match="exceeds"):
            Crop3D([6, 0, 0], [4, 4, 4]).apply_image(_volume())

    def test_center_crop(self):
        v = _volume()
        out = CenterCrop3D(4, 4, 4).apply_image(v)
        np.testing.assert_array_equal(out, v[2:6, 3:7, 4:8])

    def test_random_crop_shape_and_content(self):
        v = _volume()
        out = RandomCrop3D(4, 5, 6).apply_image(v)
        assert out.shape == (4, 5, 6)
        # the patch exists somewhere in the volume
        found = any(
            np.array_equal(v[z:z + 4, y:y + 5, x:x + 6], out)
            for z in range(5) for y in range(6) for x in range(7))
        assert found

    def test_feature_dict_and_chaining(self):
        v = _volume()
        pipeline = Crop3D([0, 0, 0], [6, 6, 6]) > CenterCrop3D(4, 4, 4)
        out = pipeline({"image": v})
        assert out["image"].shape == (4, 4, 4)


class TestAffine3D:
    def test_identity_is_noop(self):
        v = _volume()
        out = AffineTransform3D(np.eye(3)).apply_image(v)
        np.testing.assert_allclose(out, v, atol=1e-5)

    def test_translation_shifts(self):
        v = _volume()
        # dst(z) = src(z + 1): shift content up by one plane
        out = AffineTransform3D(np.eye(3),
                                translation=[1, 0, 0]).apply_image(v)
        np.testing.assert_allclose(out[:-1], v[1:], atol=1e-5)

    def test_padding_mode(self):
        v = np.ones((4, 4, 4), np.float32)
        out = AffineTransform3D(np.eye(3), translation=[10, 0, 0],
                                clamp_mode="padding",
                                pad_val=-3.0).apply_image(v)
        np.testing.assert_allclose(out, -3.0)

    def test_clamp_vs_padding_validation(self):
        with pytest.raises(ValueError, match="pad_val"):
            AffineTransform3D(np.eye(3), clamp_mode="clamp", pad_val=1.0)
        with pytest.raises(ValueError, match="clamp_mode"):
            AffineTransform3D(np.eye(3), clamp_mode="weird")

    def test_channels_last_volume(self):
        v = np.random.RandomState(1).rand(5, 6, 7, 2).astype(np.float32)
        out = AffineTransform3D(np.eye(3)).apply_image(v)
        assert out.shape == v.shape
        np.testing.assert_allclose(out, v, atol=1e-5)


class TestWarp3D:
    def test_zero_offset_flow_is_noop(self):
        v = _volume()
        flow = np.zeros((3,) + v.shape, np.float64)
        np.testing.assert_allclose(Warp3D(flow).apply_image(v), v,
                                   atol=1e-5)

    def test_absolute_flow_gathers(self):
        v = _volume(4, 4, 4)
        # every dst voxel reads src[1, 2, 3]
        flow = np.zeros((3, 2, 2, 2), np.float64)
        flow[0], flow[1], flow[2] = 1, 2, 3
        out = Warp3D(flow, offset=False).apply_image(v)
        assert out.shape == (2, 2, 2)
        np.testing.assert_allclose(out, v[1, 2, 3], atol=1e-6)

    def test_offset_flow_shifts(self):
        v = _volume()
        flow = np.zeros((3,) + v.shape, np.float64)
        flow[0] = 1.0                      # dst(z) = src(z + 1)
        out = Warp3D(flow).apply_image(v)
        np.testing.assert_allclose(out[:-1], v[1:], atol=1e-5)

    def test_padding_mode_marks_off_volume(self):
        v = np.ones((4, 4, 4), np.float32)
        flow = np.full((3, 4, 4, 4), 99.0)
        out = Warp3D(flow, offset=False, clamp_mode="padding",
                     pad_val=-7.0).apply_image(v)
        np.testing.assert_allclose(out, -7.0)
        # clamp mode instead clamps to the far corner value
        out = Warp3D(flow, offset=False).apply_image(v)
        np.testing.assert_allclose(out, 1.0)

    def test_flow_shape_validation(self):
        with pytest.raises(ValueError, match="flow_field"):
            Warp3D(np.zeros((2, 4, 4, 4)))


class TestRotate3D:
    def test_quarter_yaw_matches_numpy_rot(self):
        """A 90° rotation about z equals an axis transpose+flip of the
        (z, y, x) volume — exact up to interpolation at the grid points."""
        v = _volume(6, 8, 8, seed=2)
        out = Rotate3D([np.pi / 2, 0.0, 0.0]).apply_image(v)
        # rotation about z mixes the (y, x) plane; compare against numpy
        want = np.stack([np.rot90(v[z], k=1) for z in range(v.shape[0])])
        np.testing.assert_allclose(out, want, atol=1e-4)

    def test_full_turn_is_identity(self):
        v = _volume(6, 6, 6, seed=3)
        out = Rotate3D([2 * np.pi, 0, 0]).apply_image(v)
        np.testing.assert_allclose(out, v, atol=1e-4)

    def test_rotation_matrix_orthonormal(self):
        m = rotation_matrix(0.3, -0.7, 1.1)
        np.testing.assert_allclose(m @ m.T, np.eye(3), atol=1e-12)
        assert np.linalg.det(m) == pytest.approx(1.0)


class TestParquetDataset:
    def _write_images(self, tmp_path, n=6):
        from PIL import Image as PILImage
        img_dir = tmp_path / "imgs"
        for cls in ("cat", "dog"):
            os.makedirs(img_dir / cls, exist_ok=True)
        rng = np.random.RandomState(0)
        for i in range(n):
            cls = "cat" if i % 2 == 0 else "dog"
            arr = rng.randint(0, 255, (8, 8, 3), dtype=np.uint8)
            PILImage.fromarray(arr).save(img_dir / cls / f"{i}.png")
        return str(img_dir)

    def test_write_read_roundtrip_all_field_kinds(self, tmp_path, orca_ctx):
        from PIL import Image as PILImage
        img_path = str(tmp_path / "one.png")
        arr = np.arange(48, dtype=np.uint8).reshape(4, 4, 3)
        PILImage.fromarray(arr).save(img_path)

        schema = {"id": Scalar("int64"), "feat": NDarray("float32"),
                  "img": Image()}
        rng = np.random.RandomState(1)
        feats = rng.rand(5, 3).astype(np.float32)

        def gen():
            for i in range(5):
                yield {"id": i, "feat": feats[i], "img": img_path}

        out = str(tmp_path / "pq")
        ParquetDataset.write(out, gen(), schema, block_size=2)
        shards = ParquetDataset.read_as_xshards(out)
        assert shards.num_partitions() == 3  # 2+2+1
        data = shards.collect()
        np.testing.assert_array_equal(
            np.concatenate([d["id"] for d in data]), np.arange(5))
        np.testing.assert_allclose(
            np.concatenate([d["feat"] for d in data]), feats)
        # image decoded losslessly (png)
        np.testing.assert_array_equal(data[0]["img"][0], arr)

    def test_write_mode_guard(self, tmp_path, orca_ctx):
        out = str(tmp_path / "pq")
        schema = {"id": Scalar("int64")}
        ParquetDataset.write(out, iter([{"id": 1}]), schema)
        with pytest.raises(FileExistsError):
            ParquetDataset.write(out, iter([{"id": 2}]), schema,
                                 write_mode="errorifexists")
        ParquetDataset.write(out, iter([{"id": 3}]), schema)  # overwrite
        data = ParquetDataset.read_as_xshards(out).collect()
        assert list(data[0]["id"]) == [3]

    def test_write_from_directory_and_train(self, tmp_path, orca_ctx):
        """Image-tree → parquet → ShardedDataset → one Estimator epoch:
        the reference's dataset-creation use case end-to-end."""
        import flax.linen as nn
        from analytics_zoo_tpu.learn.estimator import Estimator

        img_dir = self._write_images(tmp_path, n=8)
        out = str(tmp_path / "pq")
        write_from_directory(img_dir, {"cat": 0, "dog": 1}, out,
                             block_size=4)
        ds = ParquetDataset.read_as_dataset(out, "image", "label")
        assert ds.n == 8

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                x = x.astype(np.float32) / 255.0
                return nn.Dense(2)(x.reshape(x.shape[0], -1))

        est = Estimator.from_flax(
            model=Net(), loss="sparse_categorical_crossentropy_logits",
            optimizer="adam",
            sample_input=np.zeros((2, 8, 8, 3), np.float32))
        h = est.fit(ds, epochs=1, batch_size=8)
        assert np.isfinite(h["loss"][0])

    def test_row_iterator(self, tmp_path, orca_ctx):
        out = str(tmp_path / "pq")
        write_ndarrays(np.arange(12, dtype=np.float32).reshape(6, 2),
                       np.arange(6, dtype=np.int64), out, block_size=4)
        rows = list(ParquetDataset.read_as_torch(out)())
        assert len(rows) == 6
        np.testing.assert_allclose(rows[3]["image"], [6.0, 7.0])
        assert rows[3]["label"] == 3

    def test_write_mnist(self, tmp_path, orca_ctx):
        # craft tiny IDX files
        n, r, c = 4, 3, 3
        images = np.arange(n * r * c, dtype=np.uint8).reshape(n, r, c)
        labels = np.array([0, 1, 2, 3], np.uint8)
        img_f, lbl_f = str(tmp_path / "img"), str(tmp_path / "lbl")
        with open(img_f, "wb") as f:
            for v in (2051, n, r, c):
                f.write(int(v).to_bytes(4, "big"))
            f.write(images.tobytes())
        with open(lbl_f, "wb") as f:
            for v in (2049, n):
                f.write(int(v).to_bytes(4, "big"))
            f.write(labels.tobytes())
        out = str(tmp_path / "mnist")
        write_mnist(img_f, lbl_f, out)
        data = ParquetDataset.read_as_xshards(out).collect()
        np.testing.assert_array_equal(data[0]["image"], images)
        np.testing.assert_array_equal(data[0]["label"], labels)
