"""Paged decode kernel tests (ops/paged_attention.py): BITWISE
gather-vs-reference parity (the pallas kernel and the pure-jax take
dequantize with the same expression and zero the same causal tail, so
equality is exact), stale-garbage masking on recycled pages, the
online-softmax attention kernel against a dense softmax reference
(page-table indexing, page-boundary / mid-page / zero lengths, int8
per-page dequant), and the autotune verdict dispatch.

Kernel paths run on the CPU pallas interpreter via ZOO_PALLAS_INTERPRET;
``use_kernel=True/False`` pins dispatch except in the dispatch tests.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from analytics_zoo_tpu.ops import autotune
from analytics_zoo_tpu.ops import paged_attention as pa

N_PAGES, PS, DIM = 7, 4, 8


@pytest.fixture(autouse=True)
def _interp(monkeypatch, tmp_path):
    monkeypatch.setenv("ZOO_PALLAS_INTERPRET", "1")
    monkeypatch.setenv("ZOO_AUTOTUNE_CACHE",
                       str(tmp_path / "autotune.json"))
    monkeypatch.setenv("ZOO_AUTOTUNE_ITERS", "2")
    autotune.reset_tuner()
    yield
    autotune.reset_tuner()
    autotune._pending.clear()


def _pool(dtype="float32", seed=0):
    rng = np.random.default_rng(seed)
    if dtype == "int8":
        pool = rng.integers(-127, 128, (N_PAGES, PS, DIM)).astype(np.int8)
        scales = rng.uniform(0.005, 0.05, N_PAGES).astype(np.float32)
    else:
        pool = rng.standard_normal((N_PAGES, PS, DIM)).astype(np.float32)
        scales = np.ones(N_PAGES, np.float32)
    return pool, scales


def _host_gather(pool, table, lengths, scales):
    """Numpy host loop — the gather_into semantics the kernel replaces."""
    b, w = table.shape
    out = np.zeros((b, w * PS, DIM), np.float32)
    for i in range(b):
        for j in range(w):
            rows = pool[table[i, j]].astype(np.float32)
            if pool.dtype == np.int8:
                rows = rows * np.float32(scales[table[i, j]])
            out[i, j * PS:(j + 1) * PS] = rows
        out[i, lengths[i]:] = 0.0
    return out


TABLE = np.array([[3, 1], [0, 6], [5, 5]], np.int32)   # dup page reused
LENGTHS = np.array([8, 5, 0], np.int32)                # full / mid / empty


# --------------------------------------------------------- paged gather

@pytest.mark.parametrize("dtype", ["float32", "int8"])
def test_gather_ref_matches_host_loop_bitwise(dtype):
    pool, scales = _pool(dtype)
    got = pa.paged_gather_ref(pool, TABLE, LENGTHS, scales=scales)
    np.testing.assert_array_equal(
        np.asarray(got), _host_gather(pool, TABLE, LENGTHS, scales))


@pytest.mark.parametrize("dtype", ["float32", "int8"])
def test_gather_kernel_matches_ref_bitwise(dtype):
    pool, scales = _pool(dtype)
    got = pa.paged_gather(pool, TABLE, LENGTHS, scales=scales,
                          use_kernel=True)
    want = pa.paged_gather_ref(pool, TABLE, LENGTHS, scales=scales)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("use_kernel", [True, False])
def test_gather_masks_stale_garbage(use_kernel):
    """The length mask IS the recycle hygiene: a page full of stale rows
    from a retired sequence reads back as exact zeros past the live
    length, so alloc never needs to memset it."""
    pool, scales = _pool()
    pool[6] = 1e30                               # recycled, never zeroed
    pool[2, 3] = 1e30                            # stale tail of a live page
    table = np.array([[2, 6]], np.int32)         # stale page in-table
    out = np.asarray(pa.paged_gather(pool, table, np.array([3], np.int32),
                                     scales=scales, use_kernel=use_kernel))
    assert np.array_equal(out[0, :3], pool[2, :3])
    assert not out[0, 3:].any()                  # exact zeros, not tiny
    # length 0: the whole row is zeros even with every page stale
    out0 = np.asarray(pa.paged_gather(
        np.full_like(pool, 127 if pool.dtype == np.int8 else 1e30),
        table, np.array([0], np.int32), scales=scales,
        use_kernel=use_kernel))
    assert not out0.any()


def test_gather_out_len_trims_to_seq_rung():
    pool, scales = _pool()
    full = pa.paged_gather_ref(pool, TABLE, LENGTHS, scales=scales)
    trim = pa.paged_gather_ref(pool, TABLE, LENGTHS, scales=scales,
                               out_len=6)
    assert trim.shape == (3, 6, DIM)
    np.testing.assert_array_equal(np.asarray(trim),
                                  np.asarray(full)[:, :6])


def test_gather_clamps_out_of_range_table_entries():
    # index_map DMAs the page before the mask applies — entries must be
    # clamped into the pool, and the mask makes the row invisible anyway
    pool, scales = _pool()
    table = np.array([[0, 99]], np.int32)
    out = np.asarray(pa.paged_gather(pool, table, np.array([4], np.int32),
                                     scales=scales, use_kernel=True))
    assert np.isfinite(out).all()
    assert not out[0, 4:].any()


# ------------------------------------------------- paged decode attention

def _dense_attention(q, k, v, lengths):
    """Straight-line fp32 softmax over the gathered dense rows — the
    ground truth the online-softmax accumulation must reproduce."""
    s = (q[:, None, :] * k).sum(-1) / np.sqrt(DIM)
    out = np.zeros_like(q)
    for i in range(q.shape[0]):
        n = lengths[i]
        if n == 0:
            continue
        w = np.exp(s[i, :n] - s[i, :n].max())
        out[i] = (w[:, None] * v[i, :n]).sum(0) / w.sum()
    return out


@pytest.mark.parametrize("dtype", ["float32", "int8"])
@pytest.mark.parametrize("use_kernel", [True, False])
def test_attention_matches_dense_reference(dtype, use_kernel):
    """Page-table indexing + masking + online softmax vs the dense
    einsum, across a full page, a mid-page length, a page-boundary
    length and an empty row, fp32 and int8 pools."""
    k_pool, k_scales = _pool(dtype, seed=1)
    v_pool, v_scales = _pool(dtype, seed=2)
    table = np.array([[3, 1], [0, 6], [5, 2], [4, 4]], np.int32)
    lengths = np.array([8, 5, 4, 0], np.int32)   # boundary at 4 = PS
    q = np.random.default_rng(3).standard_normal((4, DIM)).astype(
        np.float32)
    got = np.asarray(pa.paged_attention(
        q, k_pool, v_pool, table, lengths, k_scales=k_scales,
        v_scales=v_scales, use_kernel=use_kernel))
    k = _host_gather(k_pool, table, lengths, k_scales)
    v = _host_gather(v_pool, table, lengths, v_scales)
    np.testing.assert_allclose(got, _dense_attention(q, k, v, lengths),
                               rtol=2e-5, atol=2e-6)
    assert not got[3].any()                      # empty row: exact zeros


def test_attention_kernel_matches_ref_path():
    k_pool, k_scales = _pool(seed=4)
    v_pool, v_scales = _pool(seed=5)
    table = np.array([[6, 0], [2, 2]], np.int32)
    lengths = np.array([7, 6], np.int32)
    q = np.random.default_rng(6).standard_normal((2, DIM)).astype(
        np.float32)
    kern = np.asarray(pa.paged_attention(q, k_pool, v_pool, table,
                                         lengths, use_kernel=True))
    ref = np.asarray(pa.paged_attention_ref(q, k_pool, v_pool, table,
                                            lengths))
    np.testing.assert_allclose(kern, ref, rtol=2e-5, atol=2e-6)


def test_attention_ignores_stale_rows_on_recycled_pages():
    k_pool, _ = _pool(seed=7)
    v_pool, _ = _pool(seed=8)
    table = np.array([[1, 5]], np.int32)
    lengths = np.array([4], np.int32)            # second page fully dead
    q = np.ones((1, DIM), np.float32)
    base = np.asarray(pa.paged_attention(q, k_pool, v_pool, table,
                                         lengths, use_kernel=True))
    k_pool[5] = 1e3                              # poison the dead page
    v_pool[5] = -1e3
    poisoned = np.asarray(pa.paged_attention(q, k_pool, v_pool, table,
                                             lengths, use_kernel=True))
    np.testing.assert_array_equal(base, poisoned)


# ----------------------------------------------------- verdict dispatch

def test_tune_persists_verdict_and_auto_dispatch_stays_correct():
    pool, scales = _pool()
    rec = pa.tune_paged_gather(3, 2, PS, DIM, N_PAGES)
    key = pa.gather_key(3, 2, PS, DIM, N_PAGES, jnp.float32)
    assert autotune.get_tuner().lookup(key) == rec
    # never-selects-slower, whichever way the measurement went — and the
    # auto path must match the reference bitwise on either verdict
    if rec["use_kernel"]:
        assert rec["best_ms"] < rec["reference_ms"]
    out = np.asarray(pa.paged_gather(pool, TABLE, LENGTHS, scales=scales))
    np.testing.assert_array_equal(
        out, np.asarray(pa.paged_gather_ref(pool, TABLE, LENGTHS,
                                            scales=scales)))


def test_auto_dispatch_off_mode_takes_reference(monkeypatch):
    monkeypatch.setenv("ZOO_AUTOTUNE", "off")
    pool, scales = _pool()
    out = pa.paged_gather(pool, TABLE, LENGTHS, scales=scales)
    np.testing.assert_array_equal(
        np.asarray(out), _host_gather(pool, TABLE, LENGTHS, scales))
    assert autotune.pending_count() == 0


def test_auto_dispatch_miss_enqueues_for_warmup_worker():
    pool, scales = _pool()
    pa.paged_attention(np.zeros((3, DIM), np.float32), pool, pool,
                       TABLE, LENGTHS)
    assert autotune.pending_count() == 1
    assert autotune.tune_pending() == 1          # worker drains → verdict
    key = pa.attn_key(3, 2, PS, DIM, N_PAGES, jnp.float32)
    assert autotune.get_tuner().lookup(key) is not None


def test_seeded_winning_verdict_routes_through_kernel(monkeypatch):
    calls = []
    orig = pa._gather_pallas

    def spy(*a, **kw):
        calls.append(1)
        return orig(*a, **kw)

    monkeypatch.setattr(pa, "_gather_pallas", spy)
    key = pa.gather_key(3, 2, PS, DIM, N_PAGES, jnp.float32)
    autotune.get_tuner().record(key, {
        "kernel": "paged_gather", "best": "pallas", "use_kernel": True,
        "best_ms": 1.0, "reference_ms": 2.0, "speedup": 2.0})
    pool, scales = _pool()
    out = pa.paged_gather(pool, TABLE, LENGTHS, scales=scales)
    assert calls, "winning verdict did not dispatch the kernel"
    np.testing.assert_array_equal(
        np.asarray(out), _host_gather(pool, TABLE, LENGTHS, scales))


def test_step_key_spells_shape_pool_and_kv_dtype():
    key = pa.step_key(4, 16, 8, 32, 12, np.int8, (5, 7))
    assert "b4s16p8d32n12" in key and "enc5x7" in key
    assert key.endswith("int8") and key.startswith("paged_step|")
