"""Smoke coverage for the driver contracts: bench.py must emit its one
JSON line and __graft_entry__.entry() must stay jittable — a breakage in
either costs the round's BENCH/MULTICHIP artifacts."""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@pytest.fixture(autouse=True)
def _flight_dumps_to_tmp(monkeypatch, tmp_path):
    # wedge-path tests dump flight-recorder postmortems; keep them out
    # of the repo's zoo_tpu_logs/
    monkeypatch.setenv("ZOO_FLIGHT_RECORDER_DIR", str(tmp_path))


@pytest.fixture
def tiny_bench(monkeypatch):
    import bench
    monkeypatch.setattr(bench, "N_ROWS", 4000)
    monkeypatch.setattr(bench, "BATCH", 512)
    monkeypatch.setattr(bench, "WARMUP_STEPS", 2)
    monkeypatch.setattr(bench, "MEASURE_STEPS", 4)
    monkeypatch.setattr(bench, "STEPS_PER_LOOP", 2)
    return bench


def test_measure_ncf_both_paths(tiny_bench, orca_ctx):
    res = tiny_bench.measure_ncf()
    assert res["staged"] > 0
    assert res["best"] >= res["staged"]
    # 8 virtual devices → no single-device cached measurement
    if res["cached"] is not None:
        assert res["cached"] > 0


@pytest.mark.slow  # ~11s: trains the TCN bench model on 1 core
def test_measure_tcn(tiny_bench, orca_ctx):
    out = tiny_bench.measure_tcn()
    assert out["tcn_steps_per_sec"] > 0


def test_measure_serving(tiny_bench, orca_ctx, monkeypatch):
    monkeypatch.setattr(tiny_bench, "SERVE_N", 96)
    monkeypatch.setattr(tiny_bench, "SERVE_BATCH", 16)
    monkeypatch.setattr(tiny_bench, "SERVE_HIDDEN", 32)
    monkeypatch.setattr(tiny_bench, "SERVE_WINDOW", 2)
    monkeypatch.setattr(tiny_bench, "SERVE_REPS", 1)
    out = tiny_bench.measure_serving()
    # the sync-vs-pipelined pair is the ISSUE 1 artifact; the headline
    # key stays for dashboard continuity (== the pipelined number)
    assert out["serving_sync_records_per_sec"] > 0
    assert out["serving_pipelined_records_per_sec"] > 0
    assert (out["serving_records_per_sec"]
            == out["serving_pipelined_records_per_sec"])
    assert out["serving_pipeline_speedup"] > 0
    assert out["serving_broker"] in ("native", "python")


def test_step_flops_helper(tiny_bench, orca_ctx):
    """cost_analysis plumbing (the MFU numerator) works on this backend."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(a, b):
        return a @ b

    flops = None
    try:
        compiled = f.lower(jnp.ones((64, 64)), jnp.ones((64, 64))).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        flops = float(ca.get("flops", 0.0))
    except Exception:
        pytest.skip("cost_analysis unavailable on this backend")
    assert flops and flops >= 2 * 64 * 64 * 64 * 0.5


def test_entry_is_jittable(orca_ctx):
    import jax
    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    assert jax.tree_util.tree_leaves(out)[0].shape[0] == 8


@pytest.mark.slow  # ~29s: compiles the BERT step across the batch sweep
def test_measure_bert_sweep(tiny_bench, orca_ctx, monkeypatch):
    """measure_bert emits the canonical-batch detail plus the MFU sweep
    (tiny model/batches so the smoke stays fast on CPU)."""
    monkeypatch.setattr(tiny_bench, "BERT_SEQ", 16)
    monkeypatch.setattr(tiny_bench, "BERT_BATCHES", (8, 16))
    monkeypatch.setattr(tiny_bench, "BERT_SCAN_STEPS", 2)
    monkeypatch.setattr(tiny_bench, "BERT_CFG_KW",
                        dict(vocab=100, hidden_size=32, n_block=2,
                             n_head=2, intermediate_size=64,
                             max_position_len=32))
    out = tiny_bench.measure_bert()
    assert out["bert_step_ms"] > 0
    assert out["bert_scan_step_ms"] > 0
    assert set(out["bert_mfu_sweep"]) == {"8", "16"}
    # no peak table entry for the CPU device → MFU fields None or absent
    if out.get("bert_base_mfu") is not None:
        assert 0 < out["bert_base_mfu"] <= 1.5


def test_measure_flash_attention(tiny_bench, orca_ctx, monkeypatch):
    bench = tiny_bench
    monkeypatch.setattr(bench, "FA_BATCH", 1)
    monkeypatch.setattr(bench, "FA_SEQ", 128)
    monkeypatch.setattr(bench, "FA_HEADS", 2)
    monkeypatch.setattr(bench, "FA_DIM", 32)
    monkeypatch.setattr(bench, "FA_ITERS", 2)
    out = bench.measure_flash_attention()
    assert out["blockwise_attn_seq_ms"] > 0
    # on the CPU mesh pallas is unavailable: the fn must still return the
    # blockwise number plus the reason (on chip this key is the speedup)
    assert "flash_vs_blockwise_speedup" in out or "flash_attn_error" in out


def test_measure_int8_predict(tiny_bench, orca_ctx, monkeypatch):
    bench = tiny_bench
    monkeypatch.setattr(bench, "INT8_MODEL", "resnet-lite")
    monkeypatch.setattr(bench, "INT8_IMAGE", 32)
    monkeypatch.setattr(bench, "INT8_BATCH", 4)
    monkeypatch.setattr(bench, "INT8_CLASSES", 5)
    monkeypatch.setattr(bench, "INT8_ITERS", 2)
    out = bench.measure_int8_predict()
    assert out["resnet50_fp32_ms_per_batch32"] > 0
    assert out["resnet50_int8_speedup"] > 0
    assert out["ncf_int8_speedup"] > 0


def test_run_with_deadline_emits_partial_on_stall(tiny_bench, monkeypatch,
                                                  capsys, tmp_path):
    """A tunnel wedge MID-run must still produce the one JSON line with
    every already-measured field and the name of the stalled part."""
    import threading

    bench = tiny_bench
    monkeypatch.setattr(
        bench, "measure_ncf",
        lambda: {"best": 7.0, "staged": 7.0, "cached": None})
    # a slow cold jit in the real sanity probe must not outlast the tight
    # test deadline and misroute into the early-fallback branch
    monkeypatch.setattr(bench, "_device_sanity", lambda out: None)
    exited = {}

    def fake_exit(code):
        exited["code"] = code
        raise SystemExit(code)

    monkeypatch.setattr(bench.os, "_exit", fake_exit)

    release = threading.Event()

    def fast():
        return {"fast_ok": 1}

    def stall():
        release.wait(30)          # simulated blocked recv; freed at exit
        return {}

    out = {"metric": "x", "device": "test"}
    with pytest.raises(SystemExit):
        bench._run_with_deadline(out, (fast, stall), deadline_s=1.0)
    release.set()
    assert exited["code"] == 4
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["fast_ok"] == 1
    assert rec["value"] == 7.0
    assert "stall" in rec["error"]
    # the simulated wedge left a flight-recorder postmortem, and the
    # record points at it
    assert os.path.isfile(rec["flight_recorder"])
    with open(rec["flight_recorder"]) as fh:
        dump = json.load(fh)
    assert dump["kind"] == "zoo_flight_recorder"
    assert dump["reason"] == "bench-deadline"
    assert any("deadline" in n for n in dump["notes"])


def test_smoke_mode_embeds_telemetry_snapshot(tiny_bench, monkeypatch,
                                              capsys):
    """``bench.py --smoke`` must print the one-line JSON record with the
    telemetry snapshot riding along (ISSUE 2: the BENCH line is
    self-describing — recompiles, transfer bytes, stage times)."""
    from analytics_zoo_tpu.common import telemetry

    bench = tiny_bench
    telemetry.reset_for_tests()

    def fake_ncf():
        # what the real measures do: report through the registry
        telemetry.get_registry().counter(
            "zoo_jit_cache_misses_total", labelnames=("fn",)).labels(
            "bench_stub").inc(3)
        return {"best": 9.0, "staged": 9.0, "cached": None}

    def fake_serving():
        telemetry.get_tracer().record("bench-uri", "serve", 0.0, 0.01)
        return {"serving_records_per_sec": 5.0}

    # SERVE_*/RECSYS_* restored by monkeypatch even though _smoke assigns
    # globals
    for k in ("SERVE_N", "SERVE_BATCH", "SERVE_HIDDEN", "SERVE_WINDOW",
              "SERVE_REPS", "RECSYS_ROWS", "RECSYS_SHARDS", "RECSYS_USERS",
              "RECSYS_ITEMS", "RECSYS_BATCH"):
        monkeypatch.setattr(bench, k, getattr(bench, k))
    monkeypatch.setattr(bench, "measure_ncf", fake_ncf)
    monkeypatch.setattr(bench, "measure_serving", fake_serving)
    # the replica drills spawn subprocess fleets — covered by
    # test_multi_replica.py and the chaos lane, stubbed out here; the
    # recsys pipeline measure has its own focused test below
    for heavy in ("measure_serving_failover", "measure_serving_multi_replica",
                  "measure_replica_kill_failover",
                  "measure_recsys_pipeline"):
        monkeypatch.setattr(bench, heavy, lambda: {})
    bench._smoke()
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["mode"] == "smoke"
    assert rec["value"] == 9.0
    assert rec["serving_records_per_sec"] == 5.0
    snap = rec["telemetry"]
    assert snap["zoo_jit_cache_misses_total"]["fn=bench_stub"] == 3
    assert snap["trace_ids_held"] >= 1
    json.dumps(snap)  # the whole snapshot stays JSON-able


def test_assemble_record_reports_telemetry_failure_softly(tiny_bench,
                                                          monkeypatch):
    """A broken snapshot must not kill the BENCH line (one failure, one
    error field)."""
    from analytics_zoo_tpu.common import telemetry
    bench = tiny_bench
    monkeypatch.setattr(
        bench, "measure_ncf",
        lambda: {"best": 1.0, "staged": 1.0, "cached": None})
    monkeypatch.setattr(telemetry, "bench_snapshot",
                        lambda: (_ for _ in ()).throw(RuntimeError("boom")))
    rec = bench._assemble_record({"metric": "x"}, ())
    assert "telemetry" not in rec
    assert "boom" in rec["telemetry_error"]
    assert rec["value"] == 1.0


def test_run_with_deadline_completes_normally(tiny_bench, monkeypatch,
                                              capsys):
    bench = tiny_bench
    monkeypatch.setattr(
        bench, "measure_ncf",
        lambda: {"best": 7.0, "staged": 7.0, "cached": None})
    monkeypatch.setattr(bench, "_device_sanity", lambda out: None)
    out = {"metric": "x", "device": "test"}
    bench._run_with_deadline(out, (lambda: {"a": 1},), deadline_s=30.0)
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["a"] == 1 and "error" not in rec


def test_measure_resnet50_train(tiny_bench, orca_ctx, monkeypatch):
    bench = tiny_bench
    monkeypatch.setattr(bench, "RN50_MODEL", "resnet-lite")
    monkeypatch.setattr(bench, "RN50_IMAGE", 32)
    monkeypatch.setattr(bench, "RN50_BATCH", 8)
    monkeypatch.setattr(bench, "RN50_ITERS", 2)
    out = bench.measure_resnet50_train()
    assert out["resnet50_train_samples_per_sec"] > 0
    assert out["resnet50_train_step_ms"] > 0


def test_measure_widedeep_train(tiny_bench, orca_ctx, monkeypatch):
    bench = tiny_bench
    monkeypatch.setattr(bench, "WND_BATCH", 16)
    monkeypatch.setattr(bench, "WND_ITERS", 2)
    monkeypatch.setattr(bench, "WND_DIMS", dict(
        wide_base=(4, 6), wide_cross=(10,), indicator=(3, 2),
        embed_in=(5, 7), embed_out=(3, 4), n_continuous=2))
    out = bench.measure_widedeep_train()
    assert out["widedeep_train_samples_per_sec"] > 0


def test_measure_recsys_pipeline(tiny_bench, orca_ctx, monkeypatch):
    """ISSUE 12 gate: full Friesian data plane → streaming feed → NCF fit,
    data time included, with the never-slower transform dispatch."""
    bench = tiny_bench
    monkeypatch.setattr(bench, "RECSYS_ROWS", 1200)
    monkeypatch.setattr(bench, "RECSYS_SHARDS", 4)
    monkeypatch.setattr(bench, "RECSYS_USERS", 50)
    monkeypatch.setattr(bench, "RECSYS_ITEMS", 40)
    monkeypatch.setattr(bench, "RECSYS_BATCH", 128)
    out = bench.measure_recsys_pipeline()
    assert out["recsys_pipeline_samples_per_sec"] > 0
    assert out["recsys_pipeline_rows"] > 0
    # never-slower dispatch: the higher-better *_speedup gate metric can
    # never sit below par — the pipeline runs whichever mode measured
    # faster
    assert out["friesian_transform_speedup"] >= 1.0
    assert out["recsys_transform_mode"] in ("vectorized-parallel",
                                            "legacy-serial")


def test_run_with_deadline_early_cpu_fallback_when_sanity_stalls(
        tiny_bench, monkeypatch, capsys):
    """Wedged-after-init mode: if even the sanity dispatch never returns,
    bench must emit the labeled CPU-fallback line quickly (exit 3)."""
    import threading

    bench = tiny_bench
    release = threading.Event()

    def fake_assemble(out, parts, current=None):
        current["part"] = "device_sanity"
        release.wait(30)

    monkeypatch.setattr(bench, "_assemble_record", fake_assemble)
    monkeypatch.setattr(
        bench, "_cpu_fallback_line",
        lambda note, timeout_s=2400.0: (
            json.dumps({"metric": "x", "cpu_fallback": 1,
                        "error": note}), None))
    exited = {}

    def fake_exit(code):
        exited["code"] = code
        raise SystemExit(code)

    monkeypatch.setattr(bench.os, "_exit", fake_exit)
    with pytest.raises(SystemExit):
        bench._run_with_deadline({"metric": "x"}, (), deadline_s=1.0)
    release.set()
    assert exited["code"] == 3
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["cpu_fallback"] == 1
    assert "wedged post-init" in rec["error"]
