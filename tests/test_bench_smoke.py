"""Smoke coverage for the driver contracts: bench.py must emit its one
JSON line and __graft_entry__.entry() must stay jittable — a breakage in
either costs the round's BENCH/MULTICHIP artifacts."""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@pytest.fixture
def tiny_bench(monkeypatch):
    import bench
    monkeypatch.setattr(bench, "N_ROWS", 4000)
    monkeypatch.setattr(bench, "BATCH", 512)
    monkeypatch.setattr(bench, "WARMUP_STEPS", 2)
    monkeypatch.setattr(bench, "MEASURE_STEPS", 4)
    monkeypatch.setattr(bench, "STEPS_PER_LOOP", 2)
    return bench


def test_measure_ncf_both_paths(tiny_bench, orca_ctx):
    res = tiny_bench.measure_ncf()
    assert res["staged"] > 0
    assert res["best"] >= res["staged"]
    # 8 virtual devices → no single-device cached measurement
    if res["cached"] is not None:
        assert res["cached"] > 0


def test_measure_tcn(tiny_bench, orca_ctx):
    out = tiny_bench.measure_tcn()
    assert out["tcn_steps_per_sec"] > 0


def test_measure_serving(tiny_bench, orca_ctx):
    out = tiny_bench.measure_serving()
    assert out["serving_records_per_sec"] > 0
    assert out["serving_broker"] in ("native", "python")


def test_step_flops_helper(tiny_bench, orca_ctx):
    """cost_analysis plumbing (the MFU numerator) works on this backend."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(a, b):
        return a @ b

    flops = None
    try:
        compiled = f.lower(jnp.ones((64, 64)), jnp.ones((64, 64))).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        flops = float(ca.get("flops", 0.0))
    except Exception:
        pytest.skip("cost_analysis unavailable on this backend")
    assert flops and flops >= 2 * 64 * 64 * 64 * 0.5


def test_entry_is_jittable(orca_ctx):
    import jax
    import __graft_entry__

    fn, args = __graft_entry__.entry()
    out = jax.jit(fn)(*args)
    assert jax.tree_util.tree_leaves(out)[0].shape[0] == 8
