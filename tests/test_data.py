import os

import numpy as np
import pandas as pd
import pytest

from analytics_zoo_tpu import init_orca_context, OrcaContext
from analytics_zoo_tpu.data import XShards, HostXShards
from analytics_zoo_tpu.data.dataset import ShardedDataset, to_sharded_dataset
import analytics_zoo_tpu.data.pandas as zoo_pandas


@pytest.fixture
def csv_dir(tmp_path):
    for i in range(3):
        df = pd.DataFrame({"a": np.arange(10) + i * 10, "b": np.arange(10) * 2.0,
                           "label": (np.arange(10) % 2)})
        df.to_csv(tmp_path / f"f{i}.csv", index=False)
    return str(tmp_path)


def test_partition_and_transform(orca_ctx):
    x = {"x": np.arange(40).reshape(40, 1).astype(np.float32),
         "y": np.arange(40).astype(np.int32)}
    shards = XShards.partition(x, num_shards=4)
    assert shards.num_partitions() == 4
    assert len(shards) == 40
    doubled = shards.transform_shard(lambda d: {"x": d["x"] * 2, "y": d["y"]})
    got = np.concatenate([s["x"] for s in doubled.collect()])
    np.testing.assert_allclose(got[:, 0], np.arange(40) * 2)


def test_read_csv_repartition_partition_by(orca_ctx, csv_dir):
    shards = zoo_pandas.read_csv(csv_dir)
    assert shards.num_partitions() == 3
    assert len(shards) == 30
    rep = shards.repartition(5)
    assert rep.num_partitions() == 5
    assert len(rep) == 30
    byp = shards.partition_by("label", num_partitions=2)
    for df in byp.collect():
        assert df["label"].nunique() <= 1 or set(df["label"].unique()) <= {0, 1}
    assert sum(len(d) for d in byp.collect()) == 30
    uniq = shards["label"].unique()
    assert set(uniq.tolist()) == {0, 1}


def test_shard_size_knob(orca_ctx, csv_dir):
    OrcaContext.shard_size = 7
    try:
        shards = zoo_pandas.read_csv(csv_dir)
        assert shards.num_partitions() == 5  # ceil(30/7)
    finally:
        OrcaContext.shard_size = None


def test_save_load_pickle(orca_ctx, tmp_path, csv_dir):
    shards = zoo_pandas.read_csv(csv_dir)
    shards.save_pickle(str(tmp_path / "saved"), batchSize=2)
    loaded = XShards.load_pickle(str(tmp_path / "saved"))
    assert len(loaded) == 30


def test_disk_tier(orca_ctx):
    OrcaContext.train_data_store = "DISK_2"
    try:
        x = {"x": np.ones((16, 2), np.float32), "y": np.zeros(16, np.int32)}
        shards = XShards.partition(x, num_shards=4)
        assert shards.tier == "DISK_2"
        assert len(shards) == 16
        total = sum(len(s["y"]) for s in shards.collect())
        assert total == 16
    finally:
        OrcaContext.train_data_store = "DRAM"


def test_native_store_oserror_falls_back_to_disk(orca_ctx, monkeypatch):
    """Regression: NativeShardStore raises IOError/OSError on spill failure;
    the NATIVE_n tier must degrade to the python DISK_n spill, not crash."""
    import analytics_zoo_tpu.data.native_store as native_store
    from analytics_zoo_tpu.data import shard as shard_lib

    class Boom:
        def __init__(self, *a, **k):
            raise IOError("disk full while spilling shard")

    monkeypatch.setattr(native_store, "NativeShardStore", Boom)
    store = shard_lib._make_store(
        [{"a": np.arange(4)}, {"a": np.arange(4, 8)}], "NATIVE_2")
    assert isinstance(store, shard_lib._ShardStore)
    assert store.tier == "DISK_2"
    np.testing.assert_array_equal(store.get(1)["a"], np.arange(4, 8))


def test_streaming_dataset_covers_all_rows_bounded(orca_ctx):
    """Out-of-core feed (ref DiskFeatureSet, FeatureSet.scala:556): under a
    DISK_4 tier the training iterator must stream windows, see every row
    exactly once per epoch, and never materialize the full dataset."""
    from analytics_zoo_tpu.common.context import OrcaContext
    from analytics_zoo_tpu.data import StreamingShardedDataset
    from analytics_zoo_tpu.data.dataset import to_sharded_dataset
    from analytics_zoo_tpu.data.shard import HostXShards

    OrcaContext.train_data_store = "DISK_4"
    try:
        # 8 shards x 32 rows; row id rides in column 0
        shards = HostXShards([
            {"x": np.stack([np.arange(i * 32, (i + 1) * 32),
                            np.ones(32)], 1).astype(np.float32),
             "y": np.zeros(32, np.int32)}
            for i in range(8)])
        ds = to_sharded_dataset(shards)
        assert isinstance(ds, StreamingShardedDataset)
        assert ds.n == 256
        for epoch in (0, 1):
            got = [x for x, y, m in
                   ds.iter_batches(16, shuffle=True, seed=3, epoch=epoch)]
            ids = np.concatenate([g[:, 0] for g in got])
            assert len(ids) == 256
            assert sorted(ids.tolist()) == list(range(256))
        # residency: window = ceil(8/4)=2 shards (64 rows) + carry < 16
        assert ds.peak_window_rows <= 64 + 16
        # padded tail path (drop_remainder=False with batch 48)
        got = list(ds.iter_batches(48, drop_remainder=False))
        assert got[-1][2] is not None  # mask on the padded tail
        assert sum(int(m.sum()) if m is not None else len(x)
                   for x, y, m in got) == 256
    finally:
        OrcaContext.train_data_store = "DRAM"


def test_fit_streams_from_tiered_store(orca_ctx):
    """Training end-to-end from a DISK_2 store: loss decreases and the feed
    stays windowed (the tier is not defeated by fit())."""
    import flax.linen as nn
    from analytics_zoo_tpu.common.context import OrcaContext
    from analytics_zoo_tpu.data import StreamingShardedDataset
    from analytics_zoo_tpu.data.dataset import to_sharded_dataset
    from analytics_zoo_tpu.data.shard import HostXShards
    from analytics_zoo_tpu.learn.estimator import Estimator

    OrcaContext.train_data_store = "DISK_2"
    try:
        rng = np.random.RandomState(0)
        shards = []
        for i in range(8):
            x = rng.randn(64, 4).astype(np.float32)
            shards.append({"x": x, "y": (x.sum(1) > 0).astype(np.int32)})
        xsh = HostXShards(shards)
        ds = to_sharded_dataset(xsh)
        assert isinstance(ds, StreamingShardedDataset)

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Dense(2)(nn.tanh(nn.Dense(16)(x)))

        est = Estimator.from_flax(
            model=Net(), loss="sparse_categorical_crossentropy_logits",
            optimizer="adam", sample_input=np.zeros((2, 4), np.float32))
        h = est.fit(ds, epochs=4, batch_size=32)
        assert h["loss"][-1] < h["loss"][0]
        # bounded: window = ceil(8/2) = 4 shards = 256 rows (+carry), not 512
        assert 0 < ds.peak_window_rows <= 256 + 32
    finally:
        OrcaContext.train_data_store = "DRAM"


def test_zip_split(orca_ctx):
    a = HostXShards([np.arange(4), np.arange(4, 8)])
    b = HostXShards([np.arange(4) * 10, np.arange(4, 8) * 10])
    z = a.zip(b)
    parts = z.split()
    assert len(parts) == 2
    np.testing.assert_array_equal(parts[1].collect()[0], np.arange(4) * 10)


def test_sharded_dataset_batching(orca_ctx):
    n = 35
    ds = ShardedDataset.from_ndarrays(
        {"u": np.arange(n, dtype=np.float32)}, np.arange(n, dtype=np.int32))
    batches = list(ds.iter_batches(8, shuffle=True, seed=1, drop_remainder=True))
    assert len(batches) == 4
    assert all(b[0]["u"].shape == (8,) for b in batches)
    # padded eval path
    batches = list(ds.iter_batches(8, drop_remainder=False))
    assert len(batches) == 5
    x, y, mask = batches[-1]
    assert x["u"].shape == (8,) and mask.sum() == 3
    # epochs shuffle differently but cover all
    e0 = np.concatenate([b[1] for b in ds.iter_batches(5, shuffle=True, epoch=0)])
    e1 = np.concatenate([b[1] for b in ds.iter_batches(5, shuffle=True, epoch=1)])
    assert not np.array_equal(e0, e1)
    assert set(e0.tolist()) == set(range(35))


def test_device_iterator_sharding(orca_ctx):
    from analytics_zoo_tpu.parallel.strategy import ShardingStrategy
    s = ShardingStrategy.parse("dp")
    mesh = s.build_mesh()
    ds = ShardedDataset.from_ndarrays(np.ones((64, 3), np.float32),
                                      np.zeros(64, np.int32))
    out = list(ds.device_iterator(mesh, s, batch_size=16))
    assert len(out) == 4
    x, y, mask = out[0]
    assert x.shape == (16, 3)
    assert "data" in str(x.sharding.spec)


def test_from_dataframe_cols(orca_ctx):
    df = pd.DataFrame({"f1": np.arange(10.0), "f2": np.arange(10.0) * 2,
                       "y": np.arange(10)})
    ds = to_sharded_dataset(df, feature_cols=["f1", "f2"], label_cols="y")
    assert isinstance(ds.x, tuple) and len(ds.x) == 2
    assert ds.n == 10


def test_streaming_dataset_scan_iterator(orca_ctx):
    """steps_per_loop fusion must compose with the out-of-core feed
    (device_scan_iterator drives iter_batches through the window logic)."""
    import flax.linen as nn
    from analytics_zoo_tpu.common.context import OrcaContext
    from analytics_zoo_tpu.data import StreamingShardedDataset
    from analytics_zoo_tpu.data.dataset import to_sharded_dataset
    from analytics_zoo_tpu.data.shard import HostXShards
    from analytics_zoo_tpu.learn.estimator import Estimator

    OrcaContext.train_data_store = "DISK_2"
    try:
        rng = np.random.RandomState(3)
        shards = []
        for _ in range(4):
            x = rng.randn(64, 4).astype(np.float32)
            shards.append({"x": x, "y": (x.sum(1) > 0).astype(np.int32)})
        ds = to_sharded_dataset(HostXShards(shards))
        assert isinstance(ds, StreamingShardedDataset)

        class Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Dense(2)(nn.tanh(nn.Dense(8)(x)))

        est = Estimator.from_flax(
            model=Net(), loss="sparse_categorical_crossentropy_logits",
            optimizer="adam", sample_input=np.zeros((2, 4), np.float32))
        h = est.fit(ds, epochs=3, batch_size=32, steps_per_loop=4)
        assert len(h["loss"]) == 3 and all(np.isfinite(h["loss"]))
        # 256 rows / 32 per batch = 8 steps/epoch x 3 epochs
        assert est._py_step == 24
        assert ds.peak_window_rows <= 128 + 32
    finally:
        OrcaContext.train_data_store = "DRAM"
