"""Tests for AutoTS (mirrors ref pyzoo/test/zoo/zouwu/autots/)."""

import numpy as np
import pandas as pd
import pytest

from analytics_zoo_tpu.zouwu.autots import AutoTSTrainer, TSPipeline
from analytics_zoo_tpu.zouwu.config import (
    BayesRecipe, GridRandomRecipe, LSTMGridRandomRecipe,
    LSTMSeq2SeqRandomRecipe, MTNetGridRandomRecipe, MTNetSmokeRecipe,
    PastSeqParamHandler, RandomRecipe, Seq2SeqRandomRecipe, SmokeRecipe,
    TCNGridRandomRecipe, TCNSmokeRecipe, XgbRegressorGridRandomRecipe,
    XgbRegressorSkOptRecipe,
)


def sine_df(n=240):
    t = pd.date_range("2024-01-01", periods=n, freq="h")
    rng = np.random.RandomState(0)
    v = np.sin(np.arange(n) * 2 * np.pi / 24) + rng.normal(0, 0.05, n)
    return pd.DataFrame({"datetime": t, "value": v})


class TestRecipes:
    def test_search_spaces_materialize(self):
        from analytics_zoo_tpu.automl import hp
        rng = np.random.default_rng(0)
        for recipe in [SmokeRecipe(), MTNetSmokeRecipe(), TCNSmokeRecipe(),
                       GridRandomRecipe(), LSTMGridRandomRecipe(),
                       LSTMSeq2SeqRandomRecipe(), TCNGridRandomRecipe(),
                       Seq2SeqRandomRecipe(), MTNetGridRandomRecipe(),
                       RandomRecipe(), BayesRecipe()]:
            space = recipe.search_space()
            for gp in hp.grid_points(space):
                cfg = hp.sample_config(space, rng, gp)
                assert "model" in cfg
            rt = recipe.runtime_params()
            assert rt["n_sampling"] >= 1 and rt["epochs"] >= 1
        for recipe in [XgbRegressorGridRandomRecipe(),
                       XgbRegressorSkOptRecipe()]:
            space = recipe.search_space()
            for gp in hp.grid_points(space):
                cfg = hp.sample_config(space, rng, gp)
                assert "n_estimators" in cfg and "max_depth" in cfg

    def test_bayes_recipe_declares_search_alg(self):
        assert BayesRecipe().runtime_params()["search_alg"] == "bayes"
        assert XgbRegressorSkOptRecipe().runtime_params()["search_alg"] == "bayes"

    def test_look_back_range(self):
        r = LSTMGridRandomRecipe(look_back=(10, 20))
        s = r.search_space()
        rng = np.random.default_rng(0)
        for _ in range(20):
            v = s["past_seq_len"].sample(rng)
            assert 10 <= v <= 20
        with pytest.raises(ValueError):
            PastSeqParamHandler.get_past_seq_config((20, 10))


class TestAutoTS:
    def test_smoke_fit_predict_evaluate(self, tmp_path, orca_ctx):
        df = sine_df()
        train, val = df.iloc[:200], df.iloc[180:]
        trainer = AutoTSTrainer(dt_col="datetime", target_col="value",
                                horizon=3, logs_dir=str(tmp_path))
        ts = trainer.fit(train, val, recipe=SmokeRecipe(), metric="mse")
        assert isinstance(ts, TSPipeline)
        pred = ts.predict(val)
        assert pred.ndim == 2 and pred.shape[1] == 3
        res = ts.evaluate(val, metrics=["mse", "smape"])
        assert set(res) == {"mse", "smape"} and np.isfinite(res["mse"])

    def test_pipeline_save_load_roundtrip(self, tmp_path, orca_ctx):
        df = sine_df()
        train, val = df.iloc[:200], df.iloc[180:]
        trainer = AutoTSTrainer(horizon=2, logs_dir=str(tmp_path / "logs"))
        ts = trainer.fit(train, val, recipe=SmokeRecipe())
        p1 = ts.predict(val)
        ts.save(str(tmp_path / "pipe"))
        ts2 = TSPipeline.load(str(tmp_path / "pipe"))
        p2 = ts2.predict(val)
        np.testing.assert_allclose(p1, p2, rtol=1e-5, atol=1e-5)
        assert ts2.config["model"] == "VanillaLSTM"

    def test_pipeline_incremental_fit(self, tmp_path, orca_ctx):
        df = sine_df()
        train, val = df.iloc[:200], df.iloc[180:]
        trainer = AutoTSTrainer(horizon=2, logs_dir=str(tmp_path))
        ts = trainer.fit(train, val, recipe=SmokeRecipe())
        before = ts.evaluate(val, metrics=["mse"])["mse"]
        ts.fit(train, epochs=3)
        after = ts.evaluate(val, metrics=["mse"])["mse"]
        assert np.isfinite(after)
        assert after <= before * 2.0   # training continued without blowup

    def test_tcn_recipe_search(self, tmp_path, orca_ctx):
        df = sine_df(160)
        train, val = df.iloc[:120], df.iloc[100:]
        trainer = AutoTSTrainer(horizon=2, logs_dir=str(tmp_path))
        recipe = TCNGridRandomRecipe(num_rand_samples=1, epochs=1,
                                     look_back=12)
        ts = trainer.fit(train, val, recipe=recipe)
        assert ts.config["model"] == "TCN"
        assert ts.predict(val).shape[1] == 2

    def test_feature_selection_axis(self, tmp_path, orca_ctx):
        """selected_features flows recipe → trial transformer → pipeline
        save/load (ref recipes' RandomSample(all_available_features))."""
        df = sine_df(160)
        train, val = df.iloc[:120], df.iloc[100:]
        trainer = AutoTSTrainer(horizon=2, logs_dir=str(tmp_path))
        recipe = TCNGridRandomRecipe(num_rand_samples=1, epochs=1,
                                     look_back=12)
        space = recipe.search_space(["HOUR", "DAY", "IS_WEEKEND"])
        assert "selected_features" in space
        ts = trainer.fit(train, val, recipe=recipe)
        sel = ts.config.get("selected_features")
        assert sel and set(sel) <= {"HOUR", "DAY", "DAYOFWEEK", "MONTH",
                                    "IS_WEEKEND"}
        assert ts.predict(val).shape[1] == 2
        ts.save(str(tmp_path / "pipe"))
        ts2 = TSPipeline.load(str(tmp_path / "pipe"))
        np.testing.assert_allclose(ts.predict(val), ts2.predict(val),
                                   rtol=1e-5, atol=1e-5)

    def test_bayes_recipe_search(self, tmp_path, orca_ctx):
        df = sine_df(160)
        train, val = df.iloc[:120], df.iloc[100:]
        trainer = AutoTSTrainer(horizon=2, logs_dir=str(tmp_path))
        recipe = BayesRecipe(num_samples=2, epochs=1, look_back=12)
        ts = trainer.fit(train, val, recipe=recipe)
        assert ts.config["model"] == "TCN"
        assert ts.predict(val).shape[1] == 2


class TestTimeSequencePredictor:
    def test_fit_predict_evaluate(self, tmp_path, orca_ctx):
        """(ref regression/time_sequence_predictor.py:23 — same surface
        over the local engine)"""
        from analytics_zoo_tpu.zouwu.regression import TimeSequencePredictor
        df = sine_df(200)
        df.loc[5, "value"] = np.nan          # drop_missing path
        train, val = df.iloc[:160], df.iloc[140:].dropna()
        tsp = TimeSequencePredictor(logs_dir=str(tmp_path),
                                    future_seq_len=2,
                                    target_col=["value"])
        pipe = tsp.fit(train, val, recipe=SmokeRecipe())
        assert isinstance(pipe, TSPipeline)
        pred = tsp.predict(val)
        assert pred.shape[1] == 2
        res = tsp.evaluate(val, metric=["mse", "smape"])
        assert set(res) == {"mse", "smape"}
        with pytest.raises(ValueError, match="single target_col"):
            TimeSequencePredictor(target_col=["a", "b"])

    def test_predict_before_fit_raises(self, tmp_path):
        from analytics_zoo_tpu.zouwu.regression import TimeSequencePredictor
        with pytest.raises(RuntimeError, match="fit first"):
            TimeSequencePredictor(logs_dir=str(tmp_path)).predict(sine_df(40))

    def test_search_alg_override_does_not_mutate_recipe(self, tmp_path):
        from analytics_zoo_tpu.zouwu.regression import TimeSequencePredictor
        recipe = SmokeRecipe()
        tsp = TimeSequencePredictor(logs_dir=str(tmp_path),
                                    search_alg="bayes")
        tsp.fit(sine_df(120), recipe=recipe)
        assert recipe.search_alg is None  # caller's object untouched


def test_time_sequence_pipeline_alias(tmp_path, orca_ctx):
    """(ref zouwu/pipeline/time_sequence.py:27,211 import-path parity)"""
    from analytics_zoo_tpu.zouwu.pipeline import (TimeSequencePipeline,
                                                  load_ts_pipeline)
    assert TimeSequencePipeline is TSPipeline
    trainer = AutoTSTrainer(horizon=1, logs_dir=str(tmp_path))
    df = sine_df(120)
    ts = trainer.fit(df.iloc[:100], df.iloc[90:], recipe=SmokeRecipe())
    ts.save(str(tmp_path / "p"))
    restored = load_ts_pipeline(str(tmp_path / "p"))
    np.testing.assert_allclose(ts.predict(df.iloc[90:]),
                               restored.predict(df.iloc[90:]),
                               rtol=1e-5, atol=1e-5)
