"""Embedding-bag kernel tests (ops/embedding_bag.py): BITWISE fused-vs-
unfused parity (the kernels accumulate in the same order and precision as
their references, so equality is exact, not approximate), empty-bag
semantics, ragged tail shards, gradients through the custom VJPs, and the
keras FusedEmbeddings / pooled-Embedding wiring.

Kernel paths run on the CPU pallas interpreter via ZOO_PALLAS_INTERPRET;
``use_kernel=True/False`` pins the dispatch so no autotune verdict is
consulted.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from analytics_zoo_tpu.ops import embedding_bag as eb


@pytest.fixture(autouse=True)
def _interp(monkeypatch, tmp_path):
    monkeypatch.setenv("ZOO_PALLAS_INTERPRET", "1")
    monkeypatch.setenv("ZOO_AUTOTUNE_CACHE",
                       str(tmp_path / "autotune.json"))
    from analytics_zoo_tpu.ops import autotune
    autotune.reset_tuner()
    yield
    autotune.reset_tuner()


def _tables(widths, vocab=13, dtype=jnp.float32, seed=0):
    key = jax.random.PRNGKey(seed)
    return tuple(
        jax.random.normal(jax.random.fold_in(key, i), (vocab + i, d), dtype)
        for i, d in enumerate(widths))


def _ids(tables, batch=9, seed=1):
    key = jax.random.PRNGKey(seed)
    return jnp.stack([
        jax.random.randint(jax.random.fold_in(key, i), (batch,), 0,
                           t.shape[0])
        for i, t in enumerate(tables)], axis=1)


# --------------------------------------------------- fused lookup parity

def test_fused_concat_mixed_widths_bitwise():
    tables = _tables([8, 16, 4])                  # mixed dims: concat only
    ids = _ids(tables)
    got = eb.fused_embedding_lookup(tables, ids, "concat", use_kernel=True)
    want = eb._fused_ref(tables, ids, "concat")
    assert got.shape == (9, 28)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.parametrize("combine", ["sum", "mean", "mul"])
def test_fused_pooled_combines_bitwise(combine):
    tables = _tables([8, 8, 8])
    ids = _ids(tables)
    got = eb.fused_embedding_lookup(tables, ids, combine, use_kernel=True)
    want = eb._fused_ref(tables, ids, combine)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_fused_bf16_tables_bitwise():
    tables = _tables([8, 8], dtype=jnp.bfloat16)
    ids = _ids(tables)
    got = eb.fused_embedding_lookup(tables, ids, "sum", use_kernel=True)
    want = eb._fused_ref(tables, ids, "sum")
    assert got.dtype == jnp.bfloat16
    np.testing.assert_array_equal(np.asarray(got.astype(jnp.float32)),
                                  np.asarray(want.astype(jnp.float32)))


def test_fused_reference_path_matches_unfused_gathers():
    # the reference itself must equal N independent gathers (what the
    # pre-fused keras graph computed)
    tables = _tables([8, 4])
    ids = _ids(tables)
    out = eb.fused_embedding_lookup(tables, ids, "concat", use_kernel=False)
    want = jnp.concatenate(
        [tables[0][ids[:, 0]], tables[1][ids[:, 1]]], axis=-1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(want))


# --------------------------------------------------------- bag pooling

@pytest.mark.parametrize("mode", ["sum", "mean"])
def test_bag_kernel_bitwise(mode):
    key = jax.random.PRNGKey(3)
    table = jax.random.normal(key, (11, 8), jnp.float32)
    ids = jax.random.randint(jax.random.fold_in(key, 1), (6, 5), 0, 11)
    lengths = jnp.array([5, 3, 0, 1, 5, 2], jnp.int32)   # one EMPTY bag
    got = eb.embedding_bag(table, ids, lengths, mode, use_kernel=True)
    want = eb._bag_ref(table, ids, lengths, mode == "mean")
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    # empty bag: exact zeros, no NaN even under mean's divide
    np.testing.assert_array_equal(np.asarray(got[2]), np.zeros(8))
    assert not np.isnan(np.asarray(got)).any()


def test_bag_default_lengths_full():
    key = jax.random.PRNGKey(4)
    table = jax.random.normal(key, (7, 4), jnp.float32)
    ids = jax.random.randint(jax.random.fold_in(key, 1), (3, 2), 0, 7)
    got = eb.embedding_bag(table, ids, None, "sum", use_kernel=True)
    want = (table[ids[:, 0]].astype(jnp.float32)
            + table[ids[:, 1]].astype(jnp.float32)).astype(table.dtype)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_bag_masked_slots_never_read():
    # out-of-range ids past the valid length must not poison the result
    table = jnp.arange(12, dtype=jnp.float32).reshape(6, 2)
    ids = jnp.array([[1, 999], [2, 3]], jnp.int32)
    lengths = jnp.array([1, 2], jnp.int32)
    got = eb.embedding_bag(table, ids, lengths, "sum", use_kernel=True)
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray([table[1], table[2] + table[3]]))


def test_bag_ragged_tail_shard():
    """Offsets-form bags incl. an empty bag and a tail shard running to
    the end of flat_ids — the uneven-last-shard case the ISSUE calls out."""
    table = jax.random.normal(jax.random.PRNGKey(5), (9, 4), jnp.float32)
    flat = jnp.array([0, 1, 2, 3, 4, 5, 6, 7, 8], jnp.int32)
    offsets = jnp.array([0, 3, 3, 5, 9], jnp.int32)      # bag 1 empty
    got = eb.embedding_bag_ragged(table, flat, offsets, "sum")
    f32 = table.astype(jnp.float32)
    want = jnp.stack([f32[:3].sum(0), jnp.zeros(4), f32[3:5].sum(0),
                      f32[5:9].sum(0)]).astype(table.dtype)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)
    mean = eb.embedding_bag_ragged(table, flat, offsets, "mean")
    assert not np.isnan(np.asarray(mean)).any()
    np.testing.assert_array_equal(np.asarray(mean[1]), np.zeros(4))


# ------------------------------------------------------------- gradients

def test_fused_grads_match_reference():
    tables = _tables([8, 8])
    ids = _ids(tables, batch=6)
    g_out = jax.random.normal(jax.random.PRNGKey(9), (6, 8))

    def loss(ts, use_kernel):
        out = eb.fused_embedding_lookup(ts, ids, "mul",
                                        use_kernel=use_kernel)
        return jnp.sum(out.astype(jnp.float32) * g_out)

    gk = jax.grad(lambda ts: loss(ts, True))(tables)
    gr = jax.grad(lambda ts: loss(ts, False))(tables)
    for a, b in zip(gk, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_bag_grads_match_reference():
    table = jax.random.normal(jax.random.PRNGKey(10), (9, 4), jnp.float32)
    ids = jax.random.randint(jax.random.PRNGKey(11), (5, 3), 0, 9)
    lengths = jnp.array([3, 0, 2, 3, 1], jnp.int32)

    def loss(t, use_kernel):
        out = eb.embedding_bag(t, ids, lengths, "mean",
                               use_kernel=use_kernel)
        return jnp.sum(out ** 2)

    gk = jax.grad(lambda t: loss(t, True))(table)
    gr = jax.grad(lambda t: loss(t, False))(table)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(gr),
                               rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------- keras wiring

def test_keras_fused_embeddings_param_tree(orca_ctx):
    from analytics_zoo_tpu.keras import Input, Model
    from analytics_zoo_tpu.keras import layers as zl

    inp = Input(shape=(2,))
    out = zl.FusedEmbeddings([("user_embed", 10, 6), ("item_embed", 8, 6)],
                             combine="concat", zero_based_id=False,
                             name="bag")(inp)
    m = Model(input=inp, output=out)
    mod = m.to_flax()
    params = mod.init(jax.random.PRNGKey(0),
                      jnp.zeros((3, 2), jnp.float32))["params"]
    # each spec owns a top-level table named for param_rules to match
    assert params["user_embed"]["embedding"].shape == (10, 6)
    assert params["item_embed"]["embedding"].shape == (8, 6)
    y = mod.apply({"params": params}, jnp.zeros((3, 2), jnp.float32))
    assert y.shape == (3, 12)


def test_keras_pooled_embedding_matches_bag(orca_ctx):
    from analytics_zoo_tpu.keras import Input, Model
    from analytics_zoo_tpu.keras import layers as zl

    inp = Input(shape=(4,))
    out = zl.Embedding(9, 5, pooling="mean", name="bagged")(inp)
    m = Model(input=inp, output=out)
    mod = m.to_flax()
    x = jnp.array([[1, 2, 3, 4], [5, 5, 6, 7]], jnp.float32)
    variables = mod.init(jax.random.PRNGKey(0), x)
    y = mod.apply(variables, x)
    table = variables["params"]["bagged"]["embedding"]
    want = eb.embedding_bag(table, x.astype(jnp.int32), mode="mean",
                            use_kernel=False)
    assert y.shape == (2, 5)
    np.testing.assert_allclose(np.asarray(y), np.asarray(want), rtol=1e-6)


def test_ncf_param_tree_keeps_embed_names(orca_ctx):
    """NCF's fused bags must land parameters exactly where the per-column
    nn.Embed layers used to — tp_param_rules and checkpoints depend on the
    mlp_*/mf_* table names."""
    from analytics_zoo_tpu.models.recommendation import NeuralCF

    ncf = NeuralCF(user_count=12, item_count=7, class_num=3,
                   user_embed=6, item_embed=4, mf_embed=5)
    mod = ncf.model.to_flax()
    params = mod.init(jax.random.PRNGKey(0),
                      jnp.ones((2, 2), jnp.float32))["params"]
    shapes = {k: params[k]["embedding"].shape
              for k in params if k.endswith("_embed")}
    assert shapes == {
        "mlp_user_embed": (13, 6), "mlp_item_embed": (8, 4),
        "mf_user_embed": (13, 5), "mf_item_embed": (8, 5)}
