"""Tests for Transformer/BERT modules, keras layers and task estimators
(mirrors ref pyzoo/test/zoo/tfpark/test_text_estimators.py +
layers/TransformerLayerSpec.scala / BERTSpec.scala)."""

import numpy as np
import pytest

from analytics_zoo_tpu.text import (
    BERTClassifier, BERTNER, BERTSQuAD, BertConfig, BertModule,
    TransformerModule,
)

CFG = BertConfig(vocab=50, hidden_size=16, n_block=2, n_head=2,
                 intermediate_size=32, max_position_len=32,
                 hidden_drop=0.0, attn_drop=0.0)


def _toy_batch(b=8, L=12, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(1, 50, (b, L)).astype(np.int32)
    seg = np.zeros((b, L), np.int32)
    mask = np.ones((b, L), np.int32)
    mask[:, L - 3:] = 0  # padded tail
    return ids, seg, mask


class TestModules:
    def test_bert_shapes(self):
        import jax
        ids, seg, mask = _toy_batch()
        m = BertModule(CFG)
        variables = m.init(jax.random.PRNGKey(0), ids, seg, mask)
        seq, pooled = m.apply(variables, ids, seg, mask)
        assert seq.shape == (8, 12, 16)
        assert pooled.shape == (8, 16)

    def test_padding_mask_blocks_attention(self):
        """Changing a masked-out token must not change unmasked positions'
        representations (ref BERT attention-mask semantics)."""
        import jax
        ids, seg, mask = _toy_batch()
        m = BertModule(CFG)
        variables = m.init(jax.random.PRNGKey(0), ids, seg, mask)
        seq1, _ = m.apply(variables, ids, seg, mask)
        ids2 = ids.copy()
        ids2[:, -1] = (ids2[:, -1] % 49) + 1  # mutate a masked position
        seq2, _ = m.apply(variables, ids2, seg, mask)
        np.testing.assert_allclose(np.asarray(seq1[:, :9]),
                                   np.asarray(seq2[:, :9]), atol=1e-5)

    def test_transformer_causality(self):
        """Causal stack: mutating a future token must not change past
        positions (ref TransformerLayer causal masking)."""
        import jax
        rng = np.random.RandomState(1)
        ids = rng.randint(1, 50, (4, 10)).astype(np.int32)
        m = TransformerModule(vocab=50, hidden_size=16, n_block=2, n_head=2,
                              hidden_drop=0.0, max_position_len=16)
        variables = m.init(jax.random.PRNGKey(0), ids)
        out1 = m.apply(variables, ids)
        ids2 = ids.copy()
        ids2[:, -1] = (ids2[:, -1] % 49) + 1
        out2 = m.apply(variables, ids2)
        np.testing.assert_allclose(np.asarray(out1[:, :-1]),
                                   np.asarray(out2[:, :-1]), atol=1e-5)
        assert np.abs(np.asarray(out1[:, -1]) -
                      np.asarray(out2[:, -1])).max() > 1e-4


class TestKerasLayers:
    def test_bert_layer_in_model(self, orca_ctx):
        from analytics_zoo_tpu.keras.engine import Input
        from analytics_zoo_tpu.keras.layers import BERT, Dense
        from analytics_zoo_tpu.keras.models import Model

        inp = Input(shape=(12,))
        pooled = BERT(vocab=50, hidden_size=16, n_block=1, n_head=2,
                      intermediate_size=32, max_position_len=32,
                      hidden_drop=0.0, attn_drop=0.0)(inp)
        out = Dense(3, activation="softmax")(pooled)
        m = Model(inp, out)
        ids = np.random.RandomState(0).randint(1, 50, (4, 12)).astype(
            np.float32)
        probs = np.asarray(m.predict(ids, distributed=False))
        assert probs.shape == (4, 3)
        np.testing.assert_allclose(probs.sum(-1), 1.0, atol=1e-4)

    def test_transformer_layer_shape(self, orca_ctx):
        from analytics_zoo_tpu.keras.engine import Input
        from analytics_zoo_tpu.keras.layers import TransformerLayer
        from analytics_zoo_tpu.keras.models import Model

        inp = Input(shape=(10,))
        seq = TransformerLayer(vocab=50, hidden_size=16, n_block=1,
                               n_head=2, seq_len=16, hidden_drop=0.0)(inp)
        m = Model(inp, seq)
        ids = np.random.RandomState(0).randint(1, 50, (4, 10)).astype(
            np.float32)
        assert np.asarray(m.predict(ids, distributed=False)).shape \
            == (4, 10, 16)


class TestEstimators:
    def test_classifier_learns(self, orca_ctx):
        ids, seg, mask = _toy_batch(b=64, L=12)
        # learnable signal: class = whether token 7 appears early
        labels = (ids[:, :4] == 7).any(1).astype(np.int32)
        est = BERTClassifier(num_classes=2, config=CFG, seq_len=12)
        h1 = est.fit(ids, labels, token_type_ids=seg, input_mask=mask,
                     epochs=1, batch_size=16)
        h2 = est.fit(ids, labels, token_type_ids=seg, input_mask=mask,
                     epochs=8, batch_size=16)
        assert h2["loss"][-1] < h1["loss"][0]
        probs = np.asarray(est.predict(ids, seg, mask, batch_size=16))
        assert probs.shape == (64, 2)

    def test_sequence_longer_than_positions_raises(self):
        import jax
        ids = np.zeros((2, 40), np.int32)
        m = BertModule(CFG)  # max_position_len=32
        with pytest.raises(ValueError, match="max_position_len"):
            m.init(jax.random.PRNGKey(0), ids)

    def test_ner_loss_ignores_padding(self):
        """Mutating labels at masked positions must not change the loss."""
        from analytics_zoo_tpu.text.estimators import _ner_loss
        rng = np.random.RandomState(0)
        logits = rng.randn(4, 8, 3).astype(np.float32)
        labels = rng.randint(0, 3, (4, 8))
        labels_masked = labels.copy()
        labels_masked[:, 6:] = -1
        l1 = np.asarray(_ner_loss(labels_masked, logits))
        garbage = labels.copy()
        garbage[:, 6:] = -7  # different negative marker, same mask
        l2 = np.asarray(_ner_loss(garbage, logits))
        np.testing.assert_allclose(l1, l2)
        # and differs from the unmasked loss
        l3 = np.asarray(_ner_loss(labels, logits))
        assert np.abs(l1 - l3).max() > 1e-6

    def test_ner_shapes_and_training(self, orca_ctx):
        ids, seg, mask = _toy_batch(b=32, L=12)
        tags = (ids % 3).astype(np.int32)  # learnable per-token tags
        est = BERTNER(num_entities=3, config=CFG, seq_len=12)
        h = est.fit(ids, tags, input_mask=mask, epochs=6, batch_size=16)
        assert h["loss"][-1] < h["loss"][0]
        out = np.asarray(est.predict(ids, seg, mask, batch_size=16))
        assert out.shape == (32, 12, 3)

    def test_squad_start_end(self, orca_ctx):
        ids, seg, mask = _toy_batch(b=32, L=12)
        labels = np.stack([np.full(32, 2), np.full(32, 5)], 1).astype(
            np.int32)
        est = BERTSQuAD(config=CFG, seq_len=12)
        h = est.fit(ids, labels, epochs=6, batch_size=16)
        assert h["loss"][-1] < h["loss"][0]
        start, end = est.predict(ids, seg, mask, batch_size=16)
        assert np.asarray(start).shape == (32, 12)
        assert np.asarray(end).shape == (32, 12)

    def test_save_load_roundtrip(self, orca_ctx, tmp_path):
        ids, seg, mask = _toy_batch(b=16, L=12)
        est = BERTClassifier(num_classes=2, config=CFG, seq_len=12)
        est.fit(ids, (ids[:, 0] % 2).astype(np.int32), epochs=1,
                batch_size=8)
        p1 = np.asarray(est.predict(ids, seg, mask, batch_size=8))
        path = str(tmp_path / "bert")
        est.save(path)
        est2 = BERTClassifier(num_classes=2, config=CFG, seq_len=12)
        est2.load(path)
        p2 = np.asarray(est2.predict(ids, seg, mask, batch_size=8))
        np.testing.assert_allclose(p2, p1, atol=1e-5)

    def test_tensor_parallel_bert(self, orca_ctx):
        """BERT under dp2,tp2 on the virtual 8-dev mesh: params really
        shard over the model axis (new capability vs reference)."""
        ids, seg, mask = _toy_batch(b=16, L=12)
        labels = (ids[:, 0] % 2).astype(np.int32)
        est = BERTClassifier(num_classes=2, config=CFG, seq_len=12,
                             strategy="dp,tp2")
        h = est.fit(ids, labels, epochs=1, batch_size=16)
        assert np.isfinite(h["loss"][0])
        state = est.estimator._state
        qk = state["params"]["bert"]["block_0"]["attention"]["query"]["kernel"]
        assert "model" in str(qk.sharding.spec), qk.sharding.spec


def test_remat_forward_and_grad_equivalence(orca_ctx):
    """BertConfig(remat=True) recomputes activations in backward without
    changing forward outputs or gradients (docs/BERT_MFU.md)."""
    import jax
    import jax.numpy as jnp
    from analytics_zoo_tpu.text.bert import BertConfig, BertModule

    kw = dict(vocab=100, hidden_size=32, n_block=2, n_head=2,
              intermediate_size=64, max_position_len=16,
              hidden_drop=0.0, attn_drop=0.0)
    ids = np.random.RandomState(0).randint(0, 100, (2, 16)).astype(np.int32)
    plain = BertModule(BertConfig(**kw))
    remat = BertModule(BertConfig(**kw, remat=True))
    variables = plain.init({"params": jax.random.PRNGKey(0),
                            "dropout": jax.random.PRNGKey(1)}, ids)
    np.testing.assert_allclose(
        np.asarray(plain.apply(variables, ids)[1]),
        np.asarray(remat.apply(variables, ids)[1]), atol=1e-6)

    def loss(module):
        return lambda v: jnp.sum(module.apply(v, ids)[1] ** 2)

    g1 = jax.grad(loss(plain))(variables)
    g2 = jax.grad(loss(remat))(variables)
    for a, b in zip(jax.tree_util.tree_leaves(g1),
                    jax.tree_util.tree_leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
