"""Profiling & flight recorder (ISSUE 3): chrome-trace export golden
structure, StepProfiler MFU/FLOPs/HBM gauges, SIGTERM postmortem dumps,
backend probe, and bench.py's regression gate."""

import json
import os
import signal
import sys

import numpy as np
import pytest

from analytics_zoo_tpu.common import profiling, telemetry


@pytest.fixture(autouse=True)
def fresh_telemetry():
    telemetry.reset_for_tests()
    yield
    telemetry.reset_for_tests()


def _record_serving_style_trace(tracer, uri="rec-0", t0=100.0):
    """A serving record's stage decomposition, deterministic timings."""
    tracer.record(uri, "total", t0, t0 + 0.010)
    tracer.record(uri, "dequeue", t0, t0 + 0.001, parent="total")
    tracer.record(uri, "preprocess", t0 + 0.001, t0 + 0.003, parent="total")
    tracer.record(uri, "device", t0 + 0.003, t0 + 0.009, parent="total")
    tracer.record(uri, "postprocess", t0 + 0.009, t0 + 0.010, parent="total")


class TestChromeTrace:
    def test_golden_structure(self):
        """The export is a Chrome Trace Event JSON object: 'M' metadata
        events naming the process and one track per trace id, 'X'
        complete events with µs timestamps relative to the earliest span
        — the exact shape Perfetto/chrome://tracing loads."""
        tracer = telemetry.get_tracer()
        _record_serving_style_trace(tracer, "rec-0", t0=100.0)
        obj = profiling.chrome_trace()
        assert obj["displayTimeUnit"] == "ms"
        ev = obj["traceEvents"]
        # round-trips through JSON (the /trace and dump_trace payload)
        assert json.loads(json.dumps(obj)) == obj

        meta = [e for e in ev if e["ph"] == "M"]
        assert {"pid", "tid", "name", "args"} <= set(meta[0])
        assert meta[0]["name"] == "process_name"
        assert meta[0]["args"]["name"] == "analytics_zoo_tpu"
        assert meta[0]["pid"] == os.getpid()
        assert [m["args"]["name"] for m in meta[1:]] == ["rec-0"]

        xs = {e["name"]: e for e in ev if e["ph"] == "X"}
        assert set(xs) == {"total", "dequeue", "preprocess", "device",
                           "postprocess"}
        for e in xs.values():
            assert {"name", "cat", "ph", "ts", "dur", "pid", "tid",
                    "args"} <= set(e)
            assert e["cat"] == "zoo" and e["tid"] == meta[1]["tid"]
            assert e["args"]["trace_id"] == "rec-0"
        # timestamps are µs relative to the earliest span (trace opens
        # at t=0), durations µs — exact for these synthetic inputs
        assert xs["total"]["ts"] == 0.0
        assert xs["total"]["dur"] == pytest.approx(10_000.0)
        assert xs["dequeue"]["ts"] == 0.0
        assert xs["dequeue"]["dur"] == pytest.approx(1_000.0)
        assert xs["preprocess"]["ts"] == pytest.approx(1_000.0)
        assert xs["device"]["ts"] == pytest.approx(3_000.0)
        assert xs["device"]["dur"] == pytest.approx(6_000.0)
        assert xs["postprocess"]["ts"] == pytest.approx(9_000.0)
        assert xs["dequeue"]["args"]["parent"] == "total"

    def test_trace_id_filter_and_multi_track(self):
        tracer = telemetry.get_tracer()
        _record_serving_style_trace(tracer, "rec-a", t0=10.0)
        _record_serving_style_trace(tracer, "rec-b", t0=20.0)
        both = profiling.chrome_trace()
        tids = {e["tid"] for e in both["traceEvents"] if e["ph"] == "X"}
        assert len(tids) == 2, "one track (tid) per trace id"
        only = profiling.chrome_trace("rec-b")
        names = {e["args"]["name"] for e in only["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "thread_name"}
        assert names == {"rec-b"}

    def test_dump_trace_roundtrip_and_telemetry_delegate(self, tmp_path):
        tracer = telemetry.get_tracer()
        _record_serving_style_trace(tracer)
        p = telemetry.dump_trace(str(tmp_path / "sub" / "trace.json"))
        with open(p) as fh:
            obj = json.load(fh)
        assert obj["displayTimeUnit"] == "ms"
        assert any(e["ph"] == "X" and e["name"] == "device"
                   for e in obj["traceEvents"])

    def test_empty_tracer_is_still_valid(self):
        obj = profiling.chrome_trace()
        assert obj["traceEvents"][0]["ph"] == "M"
        assert [e for e in obj["traceEvents"] if e["ph"] == "X"] == []


class TestStepProfiler:
    def test_mfu_is_exact_for_known_inputs(self):
        """MFU = flops x n_steps / fenced device seconds / chip peak —
        checked against hand-computed values, no hardware involved."""
        prof = profiling.StepProfiler(name="t", sample_every=1,
                                      peak_flops=1e10)
        prof.set_flops(1e9)
        prof.observe_step(0, t_start=0.0, data_wait_s=0.01,
                          dispatch_s=0.001, device_s=0.5)
        snap = telemetry.snapshot()
        assert snap["zoo_step_flops"] == 1e9
        assert snap["zoo_mfu"] == pytest.approx(1e9 / 0.5 / 1e10)
        # fused scan: flops per compiled call cover n optimizer steps
        prof2 = profiling.StepProfiler(name="t2", sample_every=1,
                                       peak_flops=1e10)
        prof2.set_flops(4e9, per_steps=4)
        prof2.observe_step(0, 0.0, 0.01, 0.001, device_s=0.5, n_steps=4)
        assert telemetry.snapshot()["zoo_mfu"] == pytest.approx(
            4 * 1e9 / 0.5 / 1e10)

    def test_compiled_flops_match_hand_computed_matmul(self):
        """cost_analysis() agrees with the textbook 2mnk FLOPs of a
        matmul — the MFU numerator is real, not a heuristic."""
        import jax
        import jax.numpy as jnp

        f = jax.jit(lambda a, b: a @ b)
        a = jnp.zeros((8, 16), jnp.float32)
        b = jnp.zeros((16, 4), jnp.float32)
        flops = profiling.compiled_step_flops(f, a, b)
        assert flops == pytest.approx(2 * 8 * 16 * 4)

    def test_no_peak_means_no_mfu(self):
        """Unknown chip (CPU): MFU is never published from a made-up
        peak; flops and phases still are."""
        prof = profiling.StepProfiler(name="t", sample_every=1,
                                      peak_flops=None)
        assert prof.peak_flops is None   # CPU: not in the table, no env
        prof.set_flops(1e9)
        prof.observe_step(0, 0.0, 0.01, 0.001, device_s=0.5)
        snap = telemetry.snapshot()
        assert snap["zoo_step_flops"] == 1e9
        assert "zoo_mfu" not in snap

    def test_env_peak_override(self, monkeypatch):
        monkeypatch.setenv("BENCH_PEAK_FLOPS", "2.5e12")
        assert profiling.device_peak_flops() == 2.5e12
        prof = profiling.StepProfiler(sample_every=1)
        assert prof.peak_flops == 2.5e12

    def test_phase_histogram_and_sampling(self):
        prof = profiling.StepProfiler(name="t", sample_every=4)
        assert [prof.should_sample(s) for s in range(5)] == \
            [True, False, False, False, True]
        for step in range(8):
            dev = 0.2 if prof.should_sample(step) else None
            prof.observe_step(step, 0.0, 0.01, 0.001, device_s=dev,
                              callback_s=0.002)
        snap = telemetry.snapshot()
        h = snap["zoo_train_phase_seconds"]
        assert h["phase=data_wait"]["count"] == 8
        assert h["phase=dispatch"]["count"] == 8
        assert h["phase=callback"]["count"] == 8
        # device time only exists on fenced (sampled) steps
        assert h["phase=device"]["count"] == 2

    def test_sampled_step_trace_decomposition(self):
        """Sampled steps land in the tracer as a step span with
        contiguous data_wait/dispatch/device/callback children — the
        training analogue of the serving trace, chrome-exportable."""
        prof = profiling.StepProfiler(name="train", sample_every=1)
        prof.observe_step(7, t_start=50.0, data_wait_s=0.010,
                          dispatch_s=0.002, device_s=0.100,
                          callback_s=0.005)
        spans = {s.name: s for s in
                 telemetry.get_tracer().get("train/step-7")}
        assert set(spans) == {"step", "data_wait", "dispatch", "device",
                              "callback"}
        assert spans["data_wait"].start == pytest.approx(50.0)
        assert spans["data_wait"].end == pytest.approx(50.010)
        assert spans["device"].start == pytest.approx(50.010)
        assert spans["device"].end == pytest.approx(50.110)
        assert spans["callback"].end == spans["step"].end
        for name in ("data_wait", "dispatch", "device", "callback"):
            assert spans[name].parent == "step"
        xs = [e for e in profiling.chrome_trace("train/step-7")
              ["traceEvents"] if e["ph"] == "X"]
        assert {e["name"] for e in xs} == set(spans)

    def test_hbm_gauge_from_live_arrays_on_cpu(self):
        """CPU exposes no memory_stats(); the gauge falls back to summed
        live-array bytes and labels the source accordingly."""
        import jax.numpy as jnp

        keep = jnp.zeros((128, 128), jnp.float32)  # noqa: F841
        n, src = profiling.hbm_bytes()
        assert src in ("live_arrays", "memory_stats")
        assert n is not None and n >= keep.nbytes
        prof = profiling.StepProfiler(sample_every=1)
        prof.observe_step(0, 0.0, 0.01, 0.001, device_s=0.1)
        hbm = telemetry.snapshot()["zoo_hbm_bytes"]
        assert hbm[f"source={src}"] >= keep.nbytes


class TestFitPublishesProfileMetrics:
    def test_fit_publishes_flops_mfu_hbm(self, orca_ctx, tmp_path,
                                         monkeypatch):
        """End to end through the estimator: fit() publishes
        zoo_step_flops (from the compiled step's cost_analysis), zoo_mfu
        (peak injected via env — CPU has none), zoo_hbm_bytes, and the
        phase histogram, all visible in the Prometheus exposition."""
        import flax.linen as nn

        from analytics_zoo_tpu.learn.estimator import Estimator
        from analytics_zoo_tpu.learn.optimizers import Adam

        monkeypatch.setenv("BENCH_PEAK_FLOPS", "1e12")

        class Tiny(nn.Module):
            @nn.compact
            def __call__(self, x, train=False):
                return nn.Dense(1)(x)

        rng = np.random.default_rng(0)
        x = rng.normal(size=(64, 4)).astype(np.float32)
        y = x @ np.ones((4, 1), np.float32)
        est = Estimator.from_flax(model=Tiny(), loss="mse",
                                  optimizer=Adam(1e-2), sample_input=x[:2],
                                  model_dir=str(tmp_path / "m"))
        est.fit((x, y), epochs=2, batch_size=32)
        snap = telemetry.snapshot()
        # XLA's optimized-HLO count for one fwd+bwd+adam step of this
        # tiny Dense; exact hand-computed checks are in TestStepProfiler
        assert 0 < snap["zoo_step_flops"] < 1e6
        assert 0 < snap["zoo_mfu"] < 1.0
        assert snap["zoo_train_phase_seconds"]["phase=device"]["count"] >= 1
        hbm = snap["zoo_hbm_bytes"]
        assert sum(hbm.values()) > 0
        text = telemetry.prometheus_text()
        assert "zoo_mfu " in text and "zoo_step_flops " in text
        assert 'zoo_hbm_bytes{source="' in text
        # sampled training steps produced chrome-exportable traces
        xs = [e for e in profiling.chrome_trace()["traceEvents"]
              if e["ph"] == "X"]
        assert any(e["args"]["trace_id"].startswith("train/step-")
                   and e["name"] == "device" for e in xs)


class TestFlightRecorder:
    def test_ring_is_fed_by_tracer_and_bounded(self):
        fr = profiling.FlightRecorder(capacity=8).attach()
        tracer = telemetry.get_tracer()
        for i in range(20):
            tracer.record(f"t{i}", "stage", 0.0, 1.0)
        snap = fr.snapshot(reason="unit")
        assert len(snap["spans"]) == 8
        assert snap["spans"][-1]["trace_id"] == "t19"
        assert snap["kind"] == "zoo_flight_recorder"
        assert snap["reason"] == "unit" and snap["pid"] == os.getpid()
        fr.detach()
        tracer.record("after", "stage", 0.0, 1.0)
        assert len(fr.snapshot()["spans"]) == 8, "detach stops feeding"

    def test_dump_contents(self, tmp_path, monkeypatch):
        monkeypatch.setenv("ZOO_DUMMY_FOR_TEST", "42")
        fr = profiling.FlightRecorder(
            capacity=4, dump_dir=str(tmp_path)).attach()
        telemetry.get_registry().counter("zoo_fr_test_total").inc(3)
        telemetry.get_tracer().record("u", "device", 1.0, 2.5)
        fr.note("part: ncf_train")
        path = fr.dump(reason="unit-test")
        assert os.path.basename(path).startswith("flightrec_")
        with open(path) as fh:
            d = json.load(fh)
        assert d["reason"] == "unit-test"
        assert d["notes"] == ["part: ncf_train"]
        assert d["env"]["ZOO_DUMMY_FOR_TEST"] == "42"
        assert d["metrics"]["zoo_fr_test_total"] == 3
        assert d["backend"]["status"] in ("ok", "jax-not-imported")
        (span,) = d["spans"]
        assert span["name"] == "device"
        assert span["duration_ms"] == pytest.approx(1500.0)

    def test_sigterm_leaves_a_dump_and_chains_handler(
            self, tmp_path, monkeypatch):
        """A simulated external kill: the armed recorder writes its
        postmortem, then chains to the previously installed handler (so
        arming never swallows someone else's SIGTERM logic)."""
        hits = []

        def prior_handler(s, f):
            hits.append(s)

        prev = signal.signal(signal.SIGTERM, prior_handler)
        try:
            monkeypatch.setenv("ZOO_FLIGHT_RECORDER", "1")
            monkeypatch.setenv("ZOO_FLIGHT_RECORDER_DIR", str(tmp_path))
            fr = profiling.maybe_arm_from_env()
            assert fr is not None
            telemetry.get_tracer().record("wedge", "device", 0.0, 9.9)
            os.kill(os.getpid(), signal.SIGTERM)
            dumps = [p for p in os.listdir(tmp_path)
                     if p.startswith("flightrec_")]
            assert len(dumps) == 1
            with open(tmp_path / dumps[0]) as fh:
                d = json.load(fh)
            assert d["reason"] == "signal-SIGTERM"
            assert [s["trace_id"] for s in d["spans"]] == ["wedge"]
            assert hits == [signal.SIGTERM], "previous handler chained"
            fr.disarm()
            # disarm restores what was in place when arm() ran
            assert signal.getsignal(signal.SIGTERM) is prior_handler
        finally:
            signal.signal(signal.SIGTERM, prev)

    def test_arm_off_main_thread_is_refused(self):
        import threading

        out = {}
        t = threading.Thread(target=lambda: out.update(
            armed=profiling.FlightRecorder().arm()))
        t.start()
        t.join()
        assert out["armed"] is False

    def test_env_gate_off_by_default(self, monkeypatch):
        monkeypatch.delenv("ZOO_FLIGHT_RECORDER", raising=False)
        assert profiling.maybe_arm_from_env() is None

    def test_dump_never_raises(self, tmp_path):
        fr = profiling.FlightRecorder(
            dump_dir=str(tmp_path / "f" / "\0bad"))
        assert fr.dump(reason="x") == ""


class TestBackendProbe:
    def test_probe_reports_cpu_backend(self):
        st = profiling.backend_state()
        assert st["status"] == "ok"
        assert st["platform"] == "cpu"
        assert st["device_count"] == 8   # conftest's virtual slice
        # second call hits the cache (still a fresh dict)
        st2 = profiling.backend_state()
        st2["status"] = "mutated"
        assert profiling.backend_state()["status"] == "ok"


class TestBenchRegressionGate:
    PREV = {"metric": "ncf_train_samples_per_sec", "value": 1000.0,
            "device": "TPU v4", "n": 3, "rc": 0, "bert_step_ms": 50.0,
            "serving_p50_ms": 8.0, "mfu": 0.4, "ready": True}

    def _gate(self):
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        import bench
        return bench

    def test_flags_throughput_drop_and_latency_rise(self):
        bench = self._gate()
        cur = dict(self.PREV, value=800.0, bert_step_ms=60.0, mfu=0.41)
        out = bench.compare_bench_records(self.PREV, cur, threshold=0.10)
        assert out["comparable"] is True
        # value: higher-better, -20% -> regression
        assert out["deltas"]["value"] == {
            "prev": 1000.0, "cur": 800.0, "delta_pct": -20.0,
            "regression": True}
        # *_ms: lower-better, +20% -> regression
        assert out["deltas"]["bert_step_ms"]["regression"] is True
        assert out["deltas"]["bert_step_ms"]["delta_pct"] == 20.0
        # within threshold -> delta recorded, not flagged
        assert out["deltas"]["mfu"]["regression"] is False
        assert sorted(out["regressions"]) == ["bert_step_ms", "value"]

    def test_improvements_and_bookkeeping_are_not_flagged(self):
        bench = self._gate()
        cur = dict(self.PREV, value=2000.0, bert_step_ms=25.0, n=99,
                   rc=4)
        out = bench.compare_bench_records(self.PREV, cur, threshold=0.10)
        assert out["regressions"] == []
        assert "n" not in out["deltas"] and "rc" not in out["deltas"]
        assert "ready" not in out["deltas"], "bools are not metrics"
        assert "device" not in out["deltas"]

    def test_device_mismatch_is_incomparable(self):
        """A cpu-fallback round vs a chip round is a backend change, not
        a perf regression — deltas ride along unflagged."""
        bench = self._gate()
        cur = dict(self.PREV, value=10.0, device="cpu-fallback")
        out = bench.compare_bench_records(self.PREV, cur, threshold=0.10)
        assert out["comparable"] is False
        assert out["regressions"] == []
        assert out["deltas"]["value"]["delta_pct"] == -99.0

    def test_find_previous_record_unwraps_driver_wrapper(self, tmp_path):
        bench = self._gate()
        (tmp_path / "BENCH_r03.json").write_text(json.dumps(
            {"n": 3, "cmd": "x", "rc": 0, "tail": "",
             "parsed": {"metric": "m", "value": 3.0, "device": "cpu"}}))
        (tmp_path / "BENCH_r07.json").write_text(json.dumps(
            {"n": 7, "cmd": "x", "rc": 0,
             "tail": 'noise\n{"metric": "m", "value": 7.0}\n'}))
        name, rec = bench._find_previous_bench_record(str(tmp_path))
        assert name == "BENCH_r07.json"
        assert rec == {"metric": "m", "value": 7.0}

    def test_no_baseline_means_empty_gate(self, tmp_path):
        bench = self._gate()
        assert bench._find_previous_bench_record(str(tmp_path)) == \
            (None, None)


class TestServingTraceEndpoint:
    def test_trace_and_healthz_backend_over_http(self):
        """GET /trace serves the chrome trace (optionally filtered) and
        /healthz now reports the backend probe — no broker needed for
        either."""
        import socket
        import urllib.error
        import urllib.request

        from analytics_zoo_tpu.serving.frontend import FrontEnd

        _record_serving_style_trace(telemetry.get_tracer(), "uri-1")
        with socket.socket() as s:           # a port nothing listens on
            s.bind(("127.0.0.1", 0))
            dead_port = s.getsockname()[1]
        with FrontEnd(dead_port).start() as fe:
            resp = urllib.request.urlopen(
                f"http://127.0.0.1:{fe.port}/trace", timeout=10)
            obj = json.loads(resp.read())
            assert resp.status == 200
            assert obj["displayTimeUnit"] == "ms"
            names = {e["name"] for e in obj["traceEvents"]
                     if e["ph"] == "X"}
            assert {"dequeue", "preprocess", "device",
                    "postprocess"} <= names
            resp2 = urllib.request.urlopen(
                f"http://127.0.0.1:{fe.port}/trace?trace_id=nope",
                timeout=10)
            obj2 = json.loads(resp2.read())
            assert [e for e in obj2["traceEvents"]
                    if e["ph"] == "X"] == []
            # healthz: broker down -> 503, but the backend probe rides
            # along and shows a live (cpu) jax backend
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{fe.port}/healthz", timeout=10)
            body = json.loads(ei.value.read())
            assert body["backend"]["status"] == "ok"
            assert body["backend"]["platform"] == "cpu"
