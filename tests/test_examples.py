"""Smoke-run the examples (the reference runs its example scripts in CI,
pyzoo/zoo/examples/run-example-test*.sh — same idea)."""

import os
import subprocess
import sys

import pytest

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples")

# distributed_training sets its own virtual-device env; the others inherit
# the test env (CPU platform via conftest env vars)
ALL = ["recommendation_ncf.py", "anomaly_detection.py",
       "autots_forecast.py", "cluster_serving.py", "torch_migration.py",
       "distributed_training.py", "dogs_vs_cats_transfer.py",
       "sentiment_analysis.py", "vae.py", "fraud_detection.py",
       "image_similarity.py", "wide_and_deep.py", "object_detection.py",
       "image_augmentation.py", "model_inference.py",
       "automl_hp_search.py", "qa_ranker.py", "multihost_launch.py",
       "image_classification_serving.py"]

# the heavyweight end-to-end examples (multi-process launches, real
# training loops: 10-25s each on 1 core) run in the examples lane only
_SLOW = {"distributed_training.py", "autots_forecast.py",
         "object_detection.py", "multihost_launch.py"}


@pytest.mark.parametrize(
    "script", [pytest.param(s, marks=pytest.mark.slow) if s in _SLOW else s
               for s in ALL])
def test_example_runs(script):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    # a sitecustomize may initialize a real accelerator backend regardless
    # of JAX_PLATFORMS (same failure mode as __graft_entry__): force the
    # CPU platform through the config API before the example runs
    launcher = (
        "import jax, runpy, sys; "
        "jax.config.update('jax_platforms', 'cpu'); "
        "sys.argv = [sys.argv[1]]; "  # argparse-using examples see no args
        "runpy.run_path(sys.argv[0], run_name='__main__')")
    proc = subprocess.run(
        [sys.executable, "-c", launcher, os.path.join(EXAMPLES, script)],
        capture_output=True, text=True, timeout=900, env=env)
    assert proc.returncode == 0, (
        f"{script} failed:\nstdout:\n{proc.stdout[-2000:]}\n"
        f"stderr:\n{proc.stderr[-2000:]}")
