"""EsTable tests against a fake in-process Elasticsearch REST server
(ref pyzoo orca/data/elastic_search.py surface; no real ES in this
environment, so the test speaks the same scroll/_bulk wire protocol)."""

import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import numpy as np
import pandas as pd
import pytest

from analytics_zoo_tpu.data.elastic_search import EsTable


class _FakeES(BaseHTTPRequestHandler):
    store = {}          # index -> list of {"_id", "_source"}
    scrolls = {}        # scroll_id -> (index, cursor, size)
    deleted_scrolls = []
    bulk_calls = 0

    def log_message(self, *a):
        pass

    def do_DELETE(self):
        length = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(length).decode()
        type(self).deleted_scrolls.append(json.loads(raw)["scroll_id"])
        self._json(200, {"succeeded": True})

    def _json(self, code, payload):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        length = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(length).decode()
        cls = type(self)
        if self.path.endswith("/_bulk"):
            cls.bulk_calls += 1
            index = self.path.split("/")[1]
            lines = [ln for ln in raw.splitlines() if ln.strip()]
            items = []
            docs = cls.store.setdefault(index, [])
            for i in range(0, len(lines), 2):
                action = json.loads(lines[i])["index"]
                doc = json.loads(lines[i + 1])
                _id = action.get("_id", str(len(docs)))
                docs.append({"_id": _id, "_source": doc})
                items.append({"index": {"_id": _id, "status": 201}})
            self._json(200, {"errors": False, "items": items})
            return
        if "/_search/scroll" in self.path:
            sid = json.loads(raw)["scroll_id"]
            index, cursor, size = cls.scrolls[sid]
            docs = cls.store.get(index, [])
            page = docs[cursor:cursor + size]
            cls.scrolls[sid] = (index, cursor + size, size)
            self._json(200, {"_scroll_id": sid,
                             "hits": {"hits": page}})
            return
        if "/_search" in self.path:
            index = self.path.split("/")[1]
            body = json.loads(raw or "{}")
            size = int(body.get("size", 10))
            docs = cls.store.get(index, [])
            if "query" in body:
                term = body["query"].get("term", {})
                for field, val in term.items():
                    docs = [d for d in docs
                            if d["_source"].get(field) == val]
            sid = f"scroll-{index}-{len(cls.scrolls)}"
            cls.scrolls[sid] = (index, size, size)
            self._json(200, {"_scroll_id": sid,
                             "hits": {"hits": docs[:size]}})
            return
        self._json(404, {"error": "unknown endpoint"})


@pytest.fixture
def fake_es():
    _FakeES.store = {}
    _FakeES.scrolls = {}
    _FakeES.deleted_scrolls = []
    _FakeES.bulk_calls = 0
    server = HTTPServer(("127.0.0.1", 0), _FakeES)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    cfg = {"host": "127.0.0.1", "port": server.server_address[1]}
    yield cfg
    server.shutdown()
    server.server_close()


class TestEsTable:
    def test_write_then_scroll_read(self, fake_es, orca_ctx):
        df = pd.DataFrame({"user": [1, 2, 3, 4, 5],
                           "score": [0.1, 0.2, 0.3, 0.4, 0.5]})
        n = EsTable.write_df(fake_es, "ratings", df)
        assert n == 5
        shards = EsTable.read_df(fake_es, "ratings", batch_size=2)
        big = shards.to_pandas()
        assert len(big) == 5  # scrolled through 3 pages
        np.testing.assert_array_equal(np.sort(big["user"].to_numpy()),
                                      [1, 2, 3, 4, 5])

    def test_scroll_context_released(self, fake_es, orca_ctx):
        EsTable.write_df(fake_es, "r", pd.DataFrame({"x": [1, 2, 3]}))
        EsTable.read_df(fake_es, "r", batch_size=1)
        assert _FakeES.deleted_scrolls, "scroll context never deleted"

    def test_write_preserves_dtypes_and_nan(self, fake_es, orca_ctx):
        """Mixed int/float frames must keep ints as ints on the wire
        (iterrows would upcast), and NaN must serialize as null."""
        df = pd.DataFrame({"user": [1, 2], "score": [0.5, np.nan]})
        EsTable.write_df(fake_es, "mixed", df)
        docs = [d["_source"] for d in _FakeES.store["mixed"]]
        assert docs[0]["user"] == 1 and isinstance(docs[0]["user"], int)
        assert docs[1]["score"] is None

    def test_write_chunks_bulk_requests(self, fake_es, orca_ctx):
        df = pd.DataFrame({"i": list(range(25))})
        n = EsTable.write_df(fake_es, "chunky", df, chunk_size=10)
        assert n == 25
        assert _FakeES.bulk_calls == 3  # 10 + 10 + 5
        assert len(_FakeES.store["chunky"]) == 25

    def test_query_filter(self, fake_es, orca_ctx):
        df = pd.DataFrame({"cls": ["a", "a", "b"], "v": [1, 2, 3]})
        EsTable.write_df(fake_es, "docs", df)
        got = EsTable.read_df(fake_es, "docs",
                              query={"term": {"cls": "a"}}).to_pandas()
        assert sorted(got["v"].tolist()) == [1, 2]

    def test_read_rdd_records(self, fake_es, orca_ctx):
        EsTable.write_df(fake_es, "r", pd.DataFrame({"x": [7]}))
        recs = EsTable.read_rdd(fake_es, "r").collect()[0]
        assert recs[0]["x"] == 7

    def test_flatten_df(self):
        df = pd.DataFrame({
            "plain": [1, 2],
            "nested": [{"a": 1, "b": 2}, {"a": 3}],
        })
        flat = EsTable.flatten_df(df)
        assert sorted(flat.columns) == ["nested.a", "nested.b", "plain"]
        assert flat["nested.a"].tolist() == [1, 3]
        assert pd.isna(flat["nested.b"][1])

    def test_num_shards_repartition(self, fake_es, orca_ctx):
        EsTable.write_df(fake_es, "big",
                         pd.DataFrame({"i": list(range(10))}))
        shards = EsTable.read_df(fake_es, "big", num_shards=4)
        assert shards.num_partitions() == 4
        assert len(shards.to_pandas()) == 10
