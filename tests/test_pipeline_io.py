"""Device-dispatch pipeline tests (common/pipeline_io.py + its three
consumers): window-bound backpressure, FIFO ordering under out-of-order
device completion, error propagation, drain-on-close, and bit-exact
equivalence of the pipelined predict paths with their synchronous cadence
(ISSUE 1 acceptance criteria)."""

import threading
import time

import numpy as np
import pytest

from analytics_zoo_tpu.common.pipeline_io import (
    Completed,
    DevicePipeline,
    StageTimer,
)


# ------------------------------------------------------------ unit: window
class Recorder:
    """submit/fetch pair instrumented to count batches in flight —
    a stand-in for the device: submit is non-blocking, fetch blocks."""

    def __init__(self, fetch_delay=None):
        self.outstanding = 0
        self.max_outstanding = 0
        self.submitted = []
        self.fetched = []
        self.fetch_delay = fetch_delay or (lambda b: 0.0)

    def submit(self, batch):
        self.outstanding += 1
        self.max_outstanding = max(self.max_outstanding, self.outstanding)
        self.submitted.append(batch)
        return batch

    def fetch(self, pending):
        d = self.fetch_delay(pending)
        if d:
            time.sleep(d)
        self.outstanding -= 1
        self.fetched.append(pending)
        return pending * 10


def test_window_validation():
    with pytest.raises(ValueError):
        DevicePipeline(lambda b: b, window=0)


def test_backpressure_never_exceeds_window():
    """THE acceptance assertion: at most K batches in flight, ever —
    dispatch and retrieval are decoupled but bounded."""
    for k in (1, 2, 4):
        rec = Recorder()
        pipe = DevicePipeline(rec.submit, window=k, fetch_fn=rec.fetch)
        for i in range(20):
            pipe.submit(i)
            assert pipe.in_flight <= k
            assert rec.outstanding <= k
        pipe.drain()
        assert rec.max_outstanding == k        # the window actually fills
        assert pipe.in_flight == 0
        assert rec.fetched == list(range(20))


def test_submit_returns_nothing_until_window_fills():
    rec = Recorder()
    pipe = DevicePipeline(rec.submit, window=3, fetch_fn=rec.fetch)
    assert pipe.submit(0) == []
    assert pipe.submit(1) == []
    assert pipe.submit(2) == []
    done = pipe.submit(3)                      # overflow retires the oldest
    assert [c.result for c in done] == [0]
    assert pipe.in_flight == 3
    assert [c.result for c in pipe.drain()] == [10, 20, 30]


def test_ordering_under_out_of_order_completion():
    """Batches 'complete' on the fake device in reverse order (early
    batches are the slowest to fetch); retirement must still be FIFO in
    submission order."""
    rec = Recorder(fetch_delay=lambda b: 0.02 if b < 3 else 0.0)
    done = {}

    def complete_async(batch):
        # out-of-order completion: a background thread finishes later
        # batches first; fetch then waits on the per-batch event
        ev = threading.Event()
        done[batch] = ev
        threading.Timer(0.03 if batch < 3 else 0.001, ev.set).start()
        return batch

    def fetch(batch):
        done[batch].wait(timeout=5)
        return rec.fetch(batch)

    pipe = DevicePipeline(complete_async, window=2, fetch_fn=fetch)
    out = list(pipe.map(range(6)))
    assert out == [0, 10, 20, 30, 40, 50]      # submission order, always


def test_map_reraises_failed_batch_in_order():
    def submit(b):
        if b == 3:
            raise RuntimeError("bad batch 3")
        return b

    pipe = DevicePipeline(submit, window=2, fetch_fn=lambda p: p)
    got = []
    with pytest.raises(RuntimeError, match="bad batch 3"):
        for r in pipe.map(range(6)):
            got.append(r)
    # everything BEFORE the failed batch was yielded first
    assert got == [0, 1, 2]


def test_dispatch_error_rides_window_in_order():
    """A failed dispatch retires as an error Completed at its FIFO
    position; neighbours are unaffected (the serving engine depends on
    this to emit per-record error results without tearing down)."""
    def submit(b):
        if b == 1:
            raise ValueError("boom")
        return b

    pipe = DevicePipeline(submit, window=4, fetch_fn=lambda p: p)
    for i in range(3):
        pipe.submit(i, ctx=f"ctx{i}")
    comps = pipe.drain()
    assert [c.ctx for c in comps] == ["ctx0", "ctx1", "ctx2"]
    assert comps[0].error is None and comps[0].result == 0
    assert isinstance(comps[1].error, ValueError)
    assert comps[1].result is None
    assert comps[2].error is None and comps[2].result == 2


def test_fetch_error_is_captured_not_raised():
    def fetch(p):
        if p == 1:
            raise OSError("device pull failed")
        return p

    pipe = DevicePipeline(lambda b: b, window=4, fetch_fn=fetch)
    for i in range(3):
        pipe.submit(i)
    comps = pipe.drain()
    assert comps[0].error is None
    assert isinstance(comps[1].error, OSError)
    assert comps[2].error is None


def test_drain_on_close():
    rec = Recorder()
    with DevicePipeline(rec.submit, window=8, fetch_fn=rec.fetch) as pipe:
        for i in range(5):
            pipe.submit(i)
        assert pipe.in_flight == 5
    # __exit__ retired everything — no device work left dangling
    assert pipe.in_flight == 0
    assert rec.fetched == list(range(5))


def test_drain_max_n():
    pipe = DevicePipeline(lambda b: b, window=8, fetch_fn=lambda p: p)
    for i in range(5):
        pipe.submit(i)
    assert [c.result for c in pipe.drain(max_n=2)] == [0, 1]
    assert pipe.in_flight == 3
    assert [c.result for c in pipe.drain()] == [2, 3, 4]


def test_timer_gauges_recorded():
    t = StageTimer()
    pipe = DevicePipeline(lambda b: b, window=2, fetch_fn=lambda p: p,
                          timer=t)
    list(pipe.map(range(4)))
    s = t.summary()
    assert s["dispatch"]["count"] == 4 and s["fetch"]["count"] == 4
    assert s["window_depth"]["count"] == 4
    assert 1.0 <= s["window_depth"]["p99"] <= 2.0
    assert s["overlap_ratio"]["count"] == 4
    assert all(0.0 <= v <= 1.0 for v in t.values["overlap_ratio"])


# ------------------------------------------- consumers: bit-exact equality
class _Net:
    pass


def _flax_im(seed=0, n_in=6, n_out=4):
    import flax.linen as nn
    from analytics_zoo_tpu.inference import InferenceModel

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(n_out)(nn.relu(nn.Dense(16)(x)))

    return InferenceModel().load_flax(
        Net(), np.zeros((1, n_in), np.float32))


def test_inference_model_pipelined_matches_sync(orca_ctx):
    im = _flax_im()
    x = np.random.default_rng(0).standard_normal((37, 6)).astype(np.float32)
    sync = im.predict(x, batch_size=8, pipeline_window=1)
    for w in (2, 4):
        piped = im.predict(x, batch_size=8, pipeline_window=w)
        np.testing.assert_array_equal(sync, piped)   # bitwise
    # generator input streams through the same window, same bits
    gen = (x[i:i + 8] for i in range(0, len(x), 8))
    streamed = im.predict(gen, pipeline_window=3)
    np.testing.assert_array_equal(sync, streamed)


def test_inference_model_async_hooks_match_predict(orca_ctx):
    im = _flax_im(seed=1)
    x = np.random.default_rng(1).standard_normal((8, 6)).astype(np.float32)
    pending = im.predict_async(x)
    got = np.asarray(im.predict_fetch(pending))
    np.testing.assert_array_equal(got, im.predict(x))


def test_estimator_predict_pipelined_matches_sync(orca_ctx):
    import flax.linen as nn
    from analytics_zoo_tpu.learn.estimator import Estimator
    from analytics_zoo_tpu.learn.optimizers import Adam

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = False):
            return nn.Dense(1)(nn.relu(nn.Dense(16)(x)))

    x = np.random.default_rng(2).standard_normal((70, 4)).astype(np.float32)
    est = Estimator.from_flax(model=MLP(), loss="mse", optimizer=Adam(1e-2),
                              sample_input=x[:2])
    sync = est.predict(x, batch_size=16, pipeline_window=1)
    for w in (2, 4):
        piped = est.predict(x, batch_size=16, pipeline_window=w)
        np.testing.assert_array_equal(sync, piped)   # bitwise


# ------------------------------------------------------- engine: behavior
class _CountingModel:
    """Duck-typed serving model: counts concurrently in-flight dispatched
    batches via the predict_async/predict_fetch hooks the engine uses."""

    def __init__(self):
        self.outstanding = 0
        self.max_outstanding = 0
        self.lock = threading.Lock()

    def predict_async(self, x):
        with self.lock:
            self.outstanding += 1
            self.max_outstanding = max(self.max_outstanding,
                                       self.outstanding)
        return np.asarray(x)

    def predict_fetch(self, pending):
        with self.lock:
            self.outstanding -= 1
        return pending * 2.0


def _serve(model, n, batch_size, **kw):
    from analytics_zoo_tpu.serving import (
        Broker, ClusterServing, InputQueue, OutputQueue,
    )
    rng = np.random.default_rng(7)
    xs = {f"u{i}": rng.standard_normal(3).astype(np.float32)
          for i in range(n)}
    with Broker.launch() as broker, \
            ClusterServing(model, broker.port, batch_size=batch_size,
                           **kw).start() as eng:
        in_q = InputQueue(port=broker.port)
        out_q = OutputQueue(port=broker.port)
        uris = in_q.enqueue_batch((u, {"x": v}) for u, v in xs.items())
        res = out_q.query_many(uris, timeout=30.0)
    assert all(v is not None for v in res.values())
    return xs, res, eng


def test_engine_backpressure_bounded_by_window(orca_ctx):
    """The serve loop keeps dispatch and retrieval decoupled, but never
    exceeds pipeline_window batches in flight on the model."""
    model = _CountingModel()
    xs, res, eng = _serve(model, n=48, batch_size=4, pipeline_window=2,
                          max_batch_size=4)
    assert model.max_outstanding <= 2
    for u, x in xs.items():
        np.testing.assert_allclose(res[u], x * 2.0, rtol=1e-6)
    m = eng.metrics()
    assert m["records_out"] == 48
    assert "window_depth" in m and m["window_depth"]["p99"] <= 2.0


def test_engine_pipelined_matches_sync_results(orca_ctx):
    im = _flax_im(n_in=3, n_out=2)
    xs0, res0, _ = _serve(im, n=20, batch_size=4, pipeline_window=0,
                          max_batch_size=4)
    xs1, res1, _ = _serve(im, n=20, batch_size=4, pipeline_window=3,
                          max_batch_size=4)
    for u in xs0:
        np.testing.assert_array_equal(res0[u], res1[u])   # bitwise


def test_engine_adaptive_batch_growth(orca_ctx):
    """Sustained backlog (every dequeue full) doubles the batch bucket up
    to max_batch_size; the growth is visible as the batch_size gauge."""
    model = _CountingModel()
    # one pipelined write lands 96 records at once -> dequeues at bucket 2
    # come back full until the stream drains, far past the
    # BACKLOG_GROW_AFTER=8 streak
    xs, res, eng = _serve(model, n=96, batch_size=2, pipeline_window=2,
                          max_batch_size=8)
    assert eng.batch_size > 2
    assert eng.batch_size <= 8
    m = eng.metrics()
    assert "batch_size" in m and m["batch_size"]["count"] >= 1
    for u, x in xs.items():
        np.testing.assert_allclose(res[u], x * 2.0, rtol=1e-6)


def test_engine_growth_pinned_when_capped(orca_ctx):
    model = _CountingModel()
    _, _, eng = _serve(model, n=40, batch_size=4, pipeline_window=2,
                       max_batch_size=4)
    assert eng.batch_size == 4                  # pinned: cap == initial
