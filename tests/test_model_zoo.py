"""Tests for text/seq2seq/anomaly/image model zoo entries (mirrors ref
pyzoo/test/zoo/models/)."""

import os

import numpy as np
import pytest

from analytics_zoo_tpu.models import (
    AnomalyDetector, ImageClassifier, KNRM, ObjectDetector, SSDLite,
    Seq2Seq, TextClassifier, ZooModel,
)
from analytics_zoo_tpu.models.image.objectdetection import (
    bbox_util, MultiBoxLoss,
)
from analytics_zoo_tpu.models.textmatching.knrm import (
    evaluate_map, evaluate_ndcg,
)


class TestTextClassifier:
    def test_fit_predict(self, orca_ctx):
        m = TextClassifier(class_num=3, vocab_size=50, token_length=16,
                           sequence_length=20, encoder="cnn",
                           encoder_output_dim=32)
        m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
        rng = np.random.RandomState(0)
        x = rng.randint(1, 51, (64, 20)).astype(np.float32)
        y = rng.randint(0, 3, 64).astype(np.int32)
        m.fit(x, y, batch_size=16, nb_epoch=1)
        probs = np.asarray(m.predict(x))
        assert probs.shape == (64, 3)
        np.testing.assert_allclose(probs.sum(-1), 1.0, atol=1e-4)

    @pytest.mark.parametrize("encoder", ["lstm", "gru"])
    def test_rnn_encoders(self, encoder, orca_ctx):
        m = TextClassifier(class_num=2, vocab_size=30, token_length=8,
                           sequence_length=12, encoder=encoder,
                           encoder_output_dim=16)
        x = np.random.RandomState(0).randint(1, 31, (8, 12)).astype(np.float32)
        assert np.asarray(m.predict(x, distributed=False)).shape == (8, 2)

    def test_bad_encoder_raises(self):
        with pytest.raises(ValueError):
            TextClassifier(2, 10, encoder="transformer")

    def test_save_load_roundtrip(self, tmp_path, orca_ctx):
        m = TextClassifier(class_num=2, vocab_size=30, token_length=8,
                           sequence_length=12, encoder="cnn",
                           encoder_output_dim=16)
        x = np.random.RandomState(0).randint(1, 31, (4, 12)).astype(np.float32)
        p1 = np.asarray(m.predict(x, distributed=False))
        m.save_model(str(tmp_path / "tc"))
        m2 = ZooModel.load_model(str(tmp_path / "tc"))
        p2 = np.asarray(m2.predict(x, distributed=False))
        np.testing.assert_allclose(p1, p2, atol=1e-5)


class TestKNRM:
    def test_forward_shapes_ranking(self, orca_ctx):
        m = KNRM(text1_length=5, text2_length=10, vocab_size=40,
                 embed_dim=16, kernel_num=11)
        x = np.random.RandomState(0).randint(1, 41, (6, 15)).astype(np.float32)
        out = np.asarray(m.predict(x, distributed=False))
        assert out.shape == (6, 1)
        assert (out >= 0).all() and (out <= 1).all()

    def test_classification_mode_and_fit(self, orca_ctx):
        m = KNRM(text1_length=4, text2_length=6, vocab_size=30, embed_dim=8,
                 kernel_num=5, target_mode="classification")
        m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
        rng = np.random.RandomState(0)
        x = rng.randint(1, 31, (32, 10)).astype(np.float32)
        y = rng.randint(0, 2, 32).astype(np.int32)
        m.fit(x, y, batch_size=16, nb_epoch=1)
        assert np.asarray(m.predict(x)).shape == (32, 2)

    def test_ranking_metrics(self):
        y_true = [1, 0, 0, 1]
        perfect = [0.9, 0.1, 0.2, 0.8]
        assert evaluate_map(y_true, perfect) == 1.0
        assert evaluate_ndcg(y_true, perfect, k=4) == pytest.approx(1.0)
        worst = [0.1, 0.9, 0.8, 0.2]
        assert evaluate_map(y_true, worst) < 1.0


class TestSeq2Seq:
    def test_teacher_forced_fit_and_infer(self, orca_ctx):
        m = Seq2Seq(input_dim=3, output_dim=2, hidden_size=16,
                    num_layers=1, encoder_seq_len=6, decoder_seq_len=4)
        m.compile(optimizer="adam", loss="mse")
        rng = np.random.RandomState(0)
        enc = rng.randn(32, 6, 3).astype(np.float32)
        dec = rng.randn(32, 4, 2).astype(np.float32)
        tgt = rng.randn(32, 4, 2).astype(np.float32)
        m.fit([enc, dec], tgt, batch_size=16, nb_epoch=1)
        out = np.asarray(m.predict([enc, dec]))
        assert out.shape == (32, 4, 2)
        gen = m.infer(enc[:2], start_sign=np.zeros(2, np.float32),
                      max_seq_len=4)
        assert gen.shape == (2, 3, 2)

    def test_gru_and_bad_rnn(self, orca_ctx):
        m = Seq2Seq(input_dim=2, output_dim=1, hidden_size=8,
                    rnn_type="gru", encoder_seq_len=5, decoder_seq_len=3)
        enc = np.zeros((2, 5, 2), np.float32)
        dec = np.zeros((2, 3, 1), np.float32)
        assert np.asarray(m.predict([enc, dec],
                                    distributed=False)).shape == (2, 3, 1)
        with pytest.raises(ValueError):
            Seq2Seq(2, 1, rnn_type="cnn")


class TestAnomalyDetector:
    def test_unroll_and_detect(self):
        data = np.arange(20, dtype=np.float32)
        x, y = AnomalyDetector.unroll(data, unroll_length=5)
        assert x.shape == (15, 5, 1)
        np.testing.assert_array_equal(y, np.arange(5, 20, dtype=np.float32))
        y_pred = y.copy()
        y_pred[3] += 100.0
        idx = AnomalyDetector.detect_anomalies(y, y_pred, anomaly_size=1)
        assert idx.tolist() == [3]

    def test_fit_predict(self, orca_ctx):
        m = AnomalyDetector(feature_shape=(8, 1), hidden_layers=(8, 8),
                            dropouts=(0.1, 0.1))
        m.compile(optimizer="adam", loss="mse")
        series = np.sin(np.arange(120) / 5).astype(np.float32)
        x, y = AnomalyDetector.unroll(series, 8)
        m.fit(x, y, batch_size=32, nb_epoch=2)
        pred = np.asarray(m.predict(x))
        assert pred.shape == (len(x), 1)

    def test_mismatched_config_raises(self):
        with pytest.raises(ValueError):
            AnomalyDetector((8, 1), hidden_layers=(8, 8), dropouts=(0.1,))


class TestImageClassifier:
    @pytest.mark.parametrize("arch", ["lenet", "vgg-lite", "mobilenet",
                                      "resnet-lite"])
    def test_forward(self, arch, orca_ctx):
        m = ImageClassifier(class_num=4, model_name=arch, image_size=32)
        x = np.random.RandomState(0).rand(4, 32, 32, 3).astype(np.float32)
        probs = np.asarray(m.predict(x, distributed=False))
        assert probs.shape == (4, 4)
        np.testing.assert_allclose(probs.sum(-1), 1.0, atol=1e-4)
        assert m.predict_classes(x).shape == (4,)

    def test_fit(self, orca_ctx):
        m = ImageClassifier(class_num=2, model_name="lenet", image_size=16)
        m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
        rng = np.random.RandomState(0)
        x = rng.rand(32, 16, 16, 3).astype(np.float32)
        y = rng.randint(0, 2, 32).astype(np.int32)
        m.fit(x, y, batch_size=16, nb_epoch=1)


class TestBboxUtil:
    def test_anchor_count_and_range(self):
        anchors = bbox_util.generate_anchors([4, 2], [0.2, 0.4, 0.8])
        assert anchors.shape == ((16 + 4) * 4, 4)
        assert (anchors >= 0).all() and (anchors <= 1).all()
        assert (anchors[:, 2] >= anchors[:, 0]).all()

    def test_iou_identity(self):
        b = np.array([[0.1, 0.1, 0.5, 0.5], [0.6, 0.6, 0.9, 0.9]])
        iou = bbox_util.iou_matrix(b, b)
        np.testing.assert_allclose(np.diag(iou), 1.0, atol=1e-6)
        assert iou[0, 1] == 0.0

    def test_encode_decode_roundtrip(self):
        anchors = bbox_util.generate_anchors([4], [0.3, 0.6])
        gt = np.array([[0.2, 0.2, 0.55, 0.55]], np.float32)
        targets = bbox_util.encode_targets(gt, np.array([2]), anchors)
        pos = targets[:, 4] > 0
        assert pos.any()
        decoded = bbox_util.decode_boxes(targets[:, :4], anchors)
        # every positive anchor should decode back to the gt box
        np.testing.assert_allclose(decoded[pos], np.tile(gt, (pos.sum(), 1)),
                                   atol=1e-4)

    def test_empty_gt(self):
        anchors = bbox_util.generate_anchors([2], [0.3, 0.6])
        t = bbox_util.encode_targets(np.zeros((0, 4)), np.zeros(0), anchors)
        assert (t == 0).all()

    def test_nms_suppresses_overlaps(self):
        boxes = np.array([[0.1, 0.1, 0.5, 0.5],
                          [0.12, 0.12, 0.52, 0.52],
                          [0.6, 0.6, 0.9, 0.9]], np.float32)
        keep = bbox_util.nms(boxes, np.array([0.9, 0.8, 0.7]), 0.45)
        assert keep.tolist() == [0, 2]


class TestSSD:
    def test_forward_and_loss_step(self, orca_ctx):
        ssd = SSDLite(class_num=2, image_size=32)
        A = ssd.n_anchors
        x = np.random.RandomState(0).rand(8, 32, 32, 3).astype(np.float32)
        out = np.asarray(ssd.predict(x, distributed=False))
        assert out.shape == (8, A, 4 + 3)

        gt_boxes = [np.array([[0.1, 0.1, 0.6, 0.6]], np.float32),
                    np.array([[0.3, 0.3, 0.8, 0.8],
                              [0.0, 0.0, 0.2, 0.2]], np.float32)] * 4
        gt_labels = [np.array([1]), np.array([2, 1])] * 4
        y = ssd.encode_ground_truth(gt_boxes, gt_labels)
        assert y.shape == (8, A, 5)

        ssd.compile(optimizer="adam", loss=ssd.loss())
        ssd.fit(x, y, batch_size=8, nb_epoch=1)

    def test_detector_output_format(self, orca_ctx):
        ssd = SSDLite(class_num=2, image_size=32)
        det = ObjectDetector(ssd, conf_threshold=0.05)
        x = np.random.RandomState(0).rand(2, 32, 32, 3).astype(np.float32)
        results = det.predict(x)
        assert len(results) == 2
        for r in results:
            assert r.ndim == 2 and (r.shape[1] == 6 or r.shape[0] == 0)
            if len(r):
                assert set(np.unique(r[:, 0])) <= {1.0, 2.0}

    def test_multibox_loss_positive_sensitivity(self):
        import jax.numpy as jnp
        loss = MultiBoxLoss(n_classes=2)
        A = 20
        y_true = np.zeros((1, A, 5), np.float32)
        y_true[0, 0, 4] = 1           # one positive anchor
        good = np.zeros((1, A, 4 + 3), np.float32)
        good[0, :, 4] = 5.0           # confident background...
        good[0, 0, 4] = 0.0
        good[0, 0, 5] = 5.0           # ...but class-1 at the positive
        bad = np.zeros((1, A, 4 + 3), np.float32)
        bad[0, 0, 4] = 5.0            # background at the positive anchor
        assert float(loss(jnp.asarray(y_true), jnp.asarray(good))) < \
            float(loss(jnp.asarray(y_true), jnp.asarray(bad)))


class TestDetectionEvaluation:
    """mAP + visualizer (ref MeanAveragePrecision validation +
    Visualizer.scala)."""

    def test_average_precision_known_curve(self):
        from analytics_zoo_tpu.models.image.objectdetection import (
            average_precision,
        )
        rec = np.array([0.5, 1.0])
        prec = np.array([1.0, 0.5])
        # area metric: 0.5*1.0 + 0.5*0.5 = 0.75
        assert average_precision(rec, prec) == pytest.approx(0.75)
        # 11-point: p(0..0.5)=1.0 (6 pts), p(0.6..1.0)=0.5 (5 pts)
        ap07 = average_precision(rec, prec, use_07_metric=True)
        assert ap07 == pytest.approx((6 * 1.0 + 5 * 0.5) / 11.0)

    def test_map_perfect_and_missed(self):
        from analytics_zoo_tpu.models.image.objectdetection import (
            mean_average_precision,
        )
        gt_b = [np.array([[0.1, 0.1, 0.4, 0.4], [0.6, 0.6, 0.9, 0.9]])]
        gt_l = [np.array([1, 2])]
        perfect = [np.array([[1, 0.9, 0.1, 0.1, 0.4, 0.4],
                             [2, 0.8, 0.6, 0.6, 0.9, 0.9]])]
        res = mean_average_precision(perfect, gt_b, gt_l, n_classes=2)
        assert res["mAP"] == pytest.approx(1.0)

        # class-2 detection in the wrong place: its AP drops to 0
        wrong = [np.array([[1, 0.9, 0.1, 0.1, 0.4, 0.4],
                           [2, 0.8, 0.0, 0.0, 0.1, 0.1]])]
        res = mean_average_precision(wrong, gt_b, gt_l, n_classes=2)
        assert res["ap_per_class"][1] == pytest.approx(1.0)
        assert res["ap_per_class"][2] == pytest.approx(0.0)
        assert res["mAP"] == pytest.approx(0.5)

    def test_map_duplicate_detections_are_fp(self):
        from analytics_zoo_tpu.models.image.objectdetection import (
            mean_average_precision,
        )
        gt_b = [np.array([[0.1, 0.1, 0.5, 0.5]])]
        gt_l = [np.array([1])]
        # two hits on the same gt: second is a false positive
        dets = [np.array([[1, 0.9, 0.1, 0.1, 0.5, 0.5],
                          [1, 0.8, 0.12, 0.1, 0.5, 0.5]])]
        res = mean_average_precision(dets, gt_b, gt_l, n_classes=1)
        # precision at rank2 = 0.5 but recall already 1.0 at rank1 → AP 1.0
        assert res["mAP"] == pytest.approx(1.0)
        # reversed scores: the duplicate outranks the hit → AP 0.5 (area)
        dets = [np.array([[1, 0.8, 0.1, 0.1, 0.5, 0.5],
                          [1, 0.9, 0.55, 0.1, 0.9, 0.5]])]
        res = mean_average_precision(dets, gt_b, gt_l, n_classes=1)
        assert res["mAP"] == pytest.approx(0.5)

    def test_visualizer_draws(self, tmp_path):
        from analytics_zoo_tpu.models.image.objectdetection import (
            Visualizer,
        )
        img = np.zeros((64, 64, 3), np.uint8)
        dets = np.array([[1, 0.9, 0.25, 0.25, 0.75, 0.75]])
        vis = Visualizer(label_map={1: "cat"})
        out = vis.draw(img, dets)
        assert out.shape == img.shape
        assert out.sum() > 0  # something was drawn
        p = vis.save(str(tmp_path / "det.png"), img, dets)
        assert (tmp_path / "det.png").exists() and p.endswith("det.png")


class TestSSDFidelity:
    """VERDICT r3 missing #4: anchor pyramid configs, hard-negative mining
    vs a naive reference implementation, NMS parity on hand-computed boxes,
    and the full detect path on checked-in image fixtures
    (ref BboxUtil.scala:1033 / MultiBoxLoss.scala:622 / VOC samples in
    zoo/src/test/resources)."""

    def test_ssd300_anchor_pyramid_count(self):
        """The ssd300_vgg preset reproduces the canonical 8,732-anchor
        pyramid (4+6+6+6+4+4 anchors/cell over 38/19/10/5/3/1 maps)."""
        anchors = bbox_util.anchors_from_config("ssd300_vgg")
        assert anchors.shape == (8732, 4)
        a512 = bbox_util.anchors_from_config("ssd512_vgg")
        assert a512.shape == (4 * 64 ** 2 + 6 * (32 ** 2 + 16 ** 2 + 8 ** 2
                              + 4 ** 2) + 4 * (2 ** 2 + 1), 4)
        with pytest.raises(ValueError, match="unknown anchor config"):
            bbox_util.anchors_from_config("nope")

    def test_per_layer_aspect_ratios_model(self, orca_ctx):
        """SSDLite accepts per-layer ratio lists (ref per-prior-box-layer
        configs); head widths and the anchor count follow per layer."""
        ratios = [(1.0, 2.0), (1.0, 2.0, 0.5), (1.0,)]
        ssd = SSDLite(class_num=1, image_size=32, aspect_ratios=ratios)
        fm = [4, 2, 1]
        expect = sum(f * f * (len(r) + 1) for f, r in zip(fm, ratios))
        assert ssd.n_anchors == expect
        x = np.zeros((2, 32, 32, 3), np.float32)
        out = np.asarray(ssd.predict(x, distributed=False))
        assert out.shape == (2, expect, 4 + 2)
        with pytest.raises(ValueError, match="per-layer"):
            bbox_util.generate_anchors([4, 2], [0.2, 0.4, 0.8],
                                       [(1.0,), (1.0,), (1.0,)])

    def test_hard_negative_mining_matches_naive(self):
        """The rank-mask mining in MultiBoxLoss equals a naive numpy
        top-k-by-CE selection (ref MultiBoxLoss.scala:622 sorts conf
        losses and keeps negPosRatio * numPos negatives)."""
        import jax.numpy as jnp
        rs = np.random.RandomState(0)
        b, A, C = 3, 40, 2
        y_true = np.zeros((b, A, 5), np.float32)
        for i in range(b):
            pos_idx = rs.choice(A, size=2 + i, replace=False)
            y_true[i, pos_idx, 4] = rs.randint(1, C + 1, size=len(pos_idx))
        y_pred = rs.randn(b, A, 4 + C + 1).astype(np.float32)

        ratio = 3.0
        loss = MultiBoxLoss(n_classes=C, neg_pos_ratio=ratio)
        got = float(loss(jnp.asarray(y_true), jnp.asarray(y_pred)))

        # naive reference
        labels = y_true[..., 4].astype(int)
        logits = y_pred[..., 4:]
        logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
        ce = -np.take_along_axis(logp, labels[..., None], -1)[..., 0]
        total = 0.0
        for i in range(b):
            pos = labels[i] > 0
            n_pos = int(pos.sum())
            diff = y_pred[i, :, :4] - y_true[i, :, :4]
            ad = np.abs(diff)
            sl1 = np.where(ad < 1, 0.5 * diff ** 2, ad - 0.5).sum(-1)
            loc = sl1[pos].sum()
            k = int(max(ratio * n_pos, 1))
            neg_ce = np.sort(ce[i][~pos])[::-1][:k]
            conf = ce[i][pos].sum() + neg_ce.sum()
            total += (loc + conf) / max(n_pos, 1)
        np.testing.assert_allclose(got, total / b, rtol=1e-5)

    def test_mining_ratio_bounds_negatives(self):
        """Raising neg_pos_ratio strictly grows the mined-negative set's
        contribution (exercises the ratio end-to-end)."""
        import jax.numpy as jnp
        rs = np.random.RandomState(1)
        y_true = np.zeros((1, 30, 5), np.float32)
        y_true[0, 0, 4] = 1
        y_pred = rs.randn(1, 30, 4 + 2).astype(np.float32)
        vals = [float(MultiBoxLoss(1, neg_pos_ratio=r)(
            jnp.asarray(y_true), jnp.asarray(y_pred)))
            for r in (1.0, 3.0, 10.0)]
        assert vals[0] < vals[1] < vals[2]

    def test_nms_hand_computed(self):
        """NMS parity against hand-worked boxes (ref BboxUtil.nms).
        Hand-computed IoUs: iou(b1,b2)=0.75, iou(b1,b3)=0.5,
        iou(b4,b5)=0.95, all cross pairs 0."""
        boxes = np.array([
            [0.0, 0.0, 0.4, 0.4],      # b1 score .9 -> kept (highest)
            [0.1, 0.0, 0.4, 0.4],      # b2: iou(b1)=0.75 -> suppressed
            [0.0, 0.0, 0.2, 0.4],      # b3: iou(b1)=0.5 -> threshold-dep.
            [0.5, 0.5, 0.9, 0.9],      # b4: disjoint from b1 -> kept
            [0.5, 0.5, 0.88, 0.9],     # b5: iou(b4)=0.95 -> suppressed
        ], np.float32)
        scores = np.array([0.9, 0.8, 0.7, 0.6, 0.5], np.float32)
        keep = bbox_util.nms(boxes, scores, iou_threshold=0.45)
        assert list(keep) == [0, 3]
        # with a looser threshold b3 (IoU 0.5 with b1) survives
        keep = bbox_util.nms(boxes, scores, iou_threshold=0.55)
        assert list(keep) == [0, 2, 3]
        # top_k truncates before suppression
        keep = bbox_util.nms(boxes, scores, iou_threshold=0.45, top_k=1)
        assert list(keep) == [0]

    def test_encode_decode_roundtrip_exact(self):
        """decode(encode(gt)) reproduces the gt boxes for matched anchors
        (ref BboxUtil encode/decodeBoxes with variances)."""
        anchors = bbox_util.generate_anchors([4, 2], [0.3, 0.5, 0.9])
        gt = np.array([[0.12, 0.2, 0.55, 0.7]], np.float32)
        t = bbox_util.encode_targets(gt, np.array([1]), anchors)
        pos = t[:, 4] > 0
        assert pos.any()
        dec = bbox_util.decode_boxes(t[:, :4], anchors)
        np.testing.assert_allclose(dec[pos], np.repeat(gt, pos.sum(), 0),
                                   atol=1e-5)


@pytest.mark.slow  # ~75s: trains the SSD overfit fixture end-to-end
class TestSSDImageFixture:
    """Full detect path on checked-in image fixtures (the reference keeps
    VOC sample images in zoo/src/test/resources for exactly this)."""

    FIX = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "fixtures", "detection")

    def _load(self):
        import json
        from PIL import Image
        with open(os.path.join(self.FIX, "ground_truth.json")) as f:
            gt = json.load(f)
        names = sorted(gt)
        imgs = np.stack([np.asarray(Image.open(os.path.join(self.FIX, n)))
                         for n in names]).astype(np.float32) / 255.0
        gtb = [np.array([g["box"] for g in gt[n]], np.float32)
               for n in names]
        gtl = [np.array([g["label"] for g in gt[n]]) for n in names]
        return imgs, gtb, gtl

    def test_overfit_fixture_reaches_full_map(self, orca_ctx):
        """Train the small SSD on the two fixture images until it detects
        the ground-truth boxes: mAP@0.5 == 1.0 end-to-end through
        ImageSet-style arrays -> fit -> ObjectDetector -> mAP."""
        from analytics_zoo_tpu.learn.optimizers import Adam
        from analytics_zoo_tpu.models.image.objectdetection import (
            mean_average_precision,
        )
        imgs, gtb, gtl = self._load()
        ssd = SSDLite(class_num=1, image_size=64)
        y = ssd.encode_ground_truth(gtb, gtl)
        assert (y[..., 4] > 0).sum(axis=1).min() >= 1  # every image matched
        ssd.compile(optimizer=Adam(learningrate=3e-3), loss=ssd.loss())
        h = ssd.fit(np.repeat(imgs, 8, axis=0), np.repeat(y, 8, axis=0),
                    batch_size=16, nb_epoch=400, shuffle=False,
                    steps_per_loop=8)
        assert h["loss"][-1] < 0.05
        det = ObjectDetector(ssd, conf_threshold=0.5)
        res = det.predict(imgs)
        assert sum(len(r) for r in res) >= 3  # 3 gt objects total
        scores = mean_average_precision(res, gtb, gtl, n_classes=1)
        assert scores["mAP"] >= 0.99


class TestFullBackbones:
    """The reference's full image-classification model set
    (ref ImageClassificationConfig.scala:33-51: alexnet, inception-v1,
    resnet-50, vgg-16/19, densenet-161, squeezenet, mobilenet(-v2); the
    -quantize/-int8 variants are the same graphs executed int8 —
    InferenceModel.quantize here)."""

    NAMES = ["alexnet", "vgg-16", "resnet-50", "inception-v1",
             "squeezenet", "densenet-121", "mobilenet-v2"]
    # the three >10s compiles stay out of the fast lanes (the set is
    # inlined: comprehensions cannot read class-body names)
    @pytest.mark.parametrize(
        "name", [pytest.param(n, marks=pytest.mark.slow)
                 if n in {"vgg-16", "resnet-50", "inception-v1"}
                 else n for n in NAMES])
    def test_builds_and_forwards(self, orca_ctx, name):
        m = ImageClassifier(class_num=7, model_name=name, image_size=64)
        out = np.asarray(m.predict(np.zeros((2, 64, 64, 3), np.float32),
                                   distributed=False))
        assert out.shape == (2, 7)
        np.testing.assert_allclose(out.sum(1), 1.0, rtol=1e-4)

    def test_resnet50_parameter_count(self, orca_ctx):
        """Structural sanity: ResNet-50's backbone parameter count is a
        known quantity (~23.5M + head); a mis-built stage would miss it
        by millions."""
        import jax
        m = ImageClassifier(class_num=10, model_name="resnet-50",
                            image_size=64)
        est = m.model._ensure_estimator()
        n = sum(int(np.prod(np.shape(p)))
                for p in jax.tree_util.tree_leaves(est.adapter.params))
        assert 23_000_000 < n < 26_000_000, n

    def test_mobilenet_v2_parameter_count(self, orca_ctx):
        """The inverted-residual blocks (expand-relu6 -> dw-BN-relu6 ->
        linear 1x1) must reproduce the canonical ~2.22M backbone params —
        a fused/activation-less depthwise would miss by hundreds of
        thousands."""
        import jax
        m = ImageClassifier(class_num=5, model_name="mobilenet-v2",
                            image_size=64)
        est = m.model._ensure_estimator()
        n = sum(int(np.prod(np.shape(p)))
                for p in jax.tree_util.tree_leaves(est.adapter.params))
        assert 2_100_000 < n < 2_500_000, n

    def test_vgg19_deeper_than_vgg16(self, orca_ctx):
        import jax

        def count(name):
            m = ImageClassifier(class_num=5, model_name=name, image_size=64)
            est = m.model._ensure_estimator()
            return sum(int(np.prod(np.shape(p)))
                       for p in jax.tree_util.tree_leaves(est.adapter.params))

        assert count("vgg-19") > count("vgg-16")

    def test_densenet_161_listed(self):
        from analytics_zoo_tpu.models.image.imageclassification import (
            image_classifier,
        )
        for name in ("densenet-161", "vgg-19"):
            assert name in image_classifier._ARCHS
        with pytest.raises(ValueError, match="unknown model_name"):
            ImageClassifier(class_num=2, model_name="nope")

    def test_save_load_roundtrip_full_arch(self, orca_ctx, tmp_path):
        m = ImageClassifier(class_num=3, model_name="squeezenet",
                            image_size=64)
        x = np.random.RandomState(0).rand(2, 64, 64, 3).astype(np.float32)
        p1 = np.asarray(m.predict(x, distributed=False))
        m.save_model(str(tmp_path / "m"))
        m2 = ZooModel.load_model(str(tmp_path / "m"))
        np.testing.assert_allclose(
            np.asarray(m2.predict(x, distributed=False)), p1,
            rtol=1e-5, atol=1e-6)


class TestLabelOutputAndPreprocess:
    """Per-model preprocessing presets + labeled output (ref
    ImagenetConfig:62-160 + LabelOutput.scala)."""

    def test_preprocessor_pipeline(self):
        from analytics_zoo_tpu.models.image.imageclassification import (
            image_classifier as ic,
        )
        pipe = ic.preprocessor("resnet-50")
        img = (np.random.RandomState(0).rand(300, 280, 3) * 255
               ).astype(np.uint8)
        out = pipe.transform({"image": img})["image"]
        assert out.shape == (224, 224, 3)
        assert out.dtype == np.float32
        # mean-subtracted: values centered near zero, not 0..255
        assert abs(float(out.mean())) < 40.0
        with pytest.raises(ValueError, match="no preprocessing preset"):
            ic.preprocessor("lenet")
        # alexnet/squeezenet use the 227 crop (ref Consts)
        assert ic.preprocessor("alexnet").transform(
            {"image": img})["image"].shape == (227, 227, 3)
        # scaled presets MULTIPLY by scale ((x-mean)*0.017 lands ~[-3, 3];
        # dividing by the scale would be thousands of times larger)
        dense = ic.preprocessor("densenet-121").transform(
            {"image": img})["image"]
        assert float(np.abs(dense).max()) < 5.0
        iv3 = ic.preprocessor("inception-v3").transform(
            {"image": img})["image"]
        assert iv3.shape == (299, 299, 3)
        assert float(np.abs(iv3).max()) <= 1.01

    def test_label_output_sorting_and_softmax(self):
        from analytics_zoo_tpu.models.image.imageclassification import (
            image_classifier as ic,
        )
        label_map = {0: "cat", 1: "dog", 2: "fish"}
        lo = ic.LabelOutput(label_map)
        res = lo(np.array([[0.2, 0.7, 0.1]]))
        assert res[0]["classes"] == ["dog", "cat", "fish"]
        np.testing.assert_allclose(res[0]["probs"], [0.7, 0.2, 0.1])
        # logits path applies softmax first
        lo2 = ic.LabelOutput(label_map, prob_as_output=False)
        res2 = lo2(np.array([[1.0, 3.0, 0.0]]), top_k=2)
        assert res2[0]["classes"][0] == "dog"
        assert len(res2[0]["probs"]) == 2
        assert float(np.sum(lo2(np.array([[1.0, 3.0, 0.0]]))[0]["probs"])) \
            == pytest.approx(1.0)
