"""zoolint unit tests — golden per-rule fixtures, suppression and
baseline round-trips, JSON schema stability, and the self-scan invariant
(the shipped tree is clean modulo dev/zoolint-baseline.json)."""

import json
import os
import textwrap

import pytest

from analytics_zoo_tpu.analysis import (
    all_rules, analyze_paths, analyze_source,
)
from analytics_zoo_tpu.analysis import baseline as baseline_lib
from analytics_zoo_tpu.analysis import report

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "fixtures", "zoolint")


def _scan(source, relpath="serving/mod.py"):
    return analyze_source(textwrap.dedent(source), relpath)


def _rules_of(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------------------ rule catalog

def test_rule_registry_complete():
    rules = all_rules()
    assert set(rules) == {
        "wallclock-hotpath", "hotpath-host-sync",
        "jit-in-loop", "jit-call-inline", "jit-static-unhashable",
        "jit-compile-in-serve-loop",
        "engine-unlocked-write", "lock-order",
        "cross-thread-unlocked-state", "lock-order-inversion",
        "blocking-under-lock", "thread-leak",
        "metric-undocumented", "metric-undeclared", "envvar-undocumented",
        "rowwise-map-in-data-plane",
        "record-ack-leak", "lock-release-path", "span-pairing",
        "tainted-host-sync", "shape-dependent-branch-in-jit",
        "kv-page-leak",
    }
    for rid, rule in rules.items():
        assert rule.id == rid
        assert rule.scope in ("file", "project")
        assert rule.description


# --------------------------------------------------------------- wallclock

def test_wallclock_flagged_in_hot_path():
    src = """
    import time
    def stamp():
        return time.time()
    """
    (f,) = _scan(src, "analytics_zoo_tpu/serving/mod.py")
    assert f.rule == "wallclock-hotpath"
    assert f.line == 4


def test_wallclock_alias_and_datetime_resolved():
    src = """
    import time as clock
    import datetime
    def stamp():
        return clock.time(), datetime.datetime.now()
    """
    fs = _scan(src, "learn/mod.py")
    assert [f.rule for f in fs] == ["wallclock-hotpath"] * 2


def test_wallclock_ignored_outside_hot_path():
    src = """
    import time
    def stamp():
        return time.time()
    """
    assert _scan(src, "analytics_zoo_tpu/zouwu/mod.py") == []
    # perf_counter/monotonic are the sanctioned clocks
    ok = """
    import time
    def span():
        return time.perf_counter() - time.monotonic()
    """
    assert _scan(ok, "serving/mod.py") == []


# ----------------------------------------------------------- hotpath sync

def test_host_sync_in_dispatch_loop():
    src = """
    import jax
    import numpy as np
    def dispatch(batches):
        out = 0.0
        for b in batches:
            out += float(b.loss)
            out += b.loss.item()
            jax.block_until_ready(b)
            np.asarray(b)
        return out
    """
    fs = _scan(src)
    assert [f.rule for f in fs] == ["hotpath-host-sync"] * 4
    labels = "\n".join(f.message for f in fs)
    for needle in ("float(<non-literal>)", ".item()",
                   "jax.block_until_ready()", "numpy.asarray()"):
        assert needle in labels


def test_host_sync_requires_hot_function_and_loop():
    # same syncs, but the function name has no dispatch/drain/... token
    src = """
    import jax
    def summarize(batches):
        for b in batches:
            jax.block_until_ready(b)
    """
    assert _scan(src) == []
    # hot name but no loop: a single fence at the end is the sane pattern
    src = """
    import jax
    def drain(pending):
        jax.block_until_ready(pending)
    """
    assert _scan(src) == []


def test_host_sync_sampling_guard_exempts():
    src = """
    import jax
    def run_epoch(steps, profiler):
        for s in steps:
            if profiler.should_sample():
                jax.block_until_ready(s)
    """
    assert _scan(src) == []


def test_host_sync_float_of_literal_ok():
    src = """
    def step_loop(xs):
        acc = 0.0
        for x in xs:
            acc += float("1.5")
        return acc
    """
    assert _scan(src) == []


# ------------------------------------------------------------------- jit

def test_jit_in_loop():
    src = """
    import jax
    def build(fns):
        return [jax.jit(f) for f in fns]
    """
    # comprehensions are not For/While — only statement loops re-trace
    # per *iteration* in the way this rule targets
    src = """
    import jax
    def build(fns, xs):
        out = []
        for f in fns:
            out.append(jax.jit(f))
        return out
    """
    (f,) = _scan(src, "mod.py")
    assert f.rule == "jit-in-loop"


def test_jit_call_inline_and_from_import():
    src = """
    from jax import jit
    def apply(f, x):
        return jit(f)(x)
    """
    fs = _scan(src, "mod.py")
    assert "jit-call-inline" in _rules_of(fs)


def test_jit_static_unhashable_list_vs_tuple():
    src = """
    import jax
    bad = jax.jit(lambda a, b: a, static_argnums=[0])
    good = jax.jit(lambda a, b: a, static_argnums=(0,))
    named = jax.jit(lambda a, b: a, static_argnames=["b"])
    """
    fs = _scan(src, "mod.py")
    assert [f.rule for f in fs] == ["jit-static-unhashable"] * 2
    assert [f.line for f in fs] == [3, 5]


def test_local_helper_named_jit_not_flagged():
    src = """
    def jit(f):
        return f
    def apply(f, x):
        return jit(f)(x)
    """
    assert _scan(src, "mod.py") == []


# -------------------------------------------------- compile-in-serve-loop

def test_compile_in_serve_loop_flagged():
    src = """
    def serve_drain(jitted, rungs):
        out = []
        for avals in rungs:
            out.append(jitted.lower(*avals).compile())
        return out
    """
    fs = _scan(src)
    assert _rules_of(fs) == ["jit-compile-in-serve-loop"]
    assert len(fs) == 2   # .lower(*avals) AND the chained .compile()


def test_compile_in_serve_loop_baselines():
    # warm-named functions are the sanctioned AOT path; re.compile and
    # zero-arg str.lower() are not XLA builds; non-hot packages exempt
    src = """
    import re
    def warm_serve_loop(jitted, rungs):
        return [jitted.lower(*a).compile() for a in rungs]
    def produce(rows):
        for r in rows:
            if re.compile(r.pat):
                yield r.name.lower()
    """
    assert _scan(src) == []
    hot_elsewhere = """
    def serve_drain(jitted, rungs):
        out = []
        for avals in rungs:
            out.append(jitted.lower(*avals).compile())
        return out
    """
    assert _scan(hot_elsewhere, "analytics_zoo_tpu/zouwu/mod.py") == []


def test_compile_outside_loop_not_flagged():
    # one build at function entry (the ExecutableCache miss path) is fine
    src = """
    def predict(jitted, avals, x):
        exe = jitted.lower(*avals).compile()
        return exe(x)
    """
    assert _scan(src) == []


# ----------------------------------------------------------- concurrency

def test_unlocked_write_across_thread_boundary():
    src = """
    import threading
    class Engine:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0
        def start(self):
            threading.Thread(target=self._run).start()
        def _run(self):
            self.n += 1
        def read(self):
            self.n = 0
    """
    fs = _scan(src, "mod.py")
    assert [f.rule for f in fs] == ["engine-unlocked-write"] * 2


def test_locked_write_is_clean():
    src = """
    import threading
    class Engine:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0
        def start(self):
            threading.Thread(target=self._run).start()
        def _run(self):
            with self._lock:
                self.n += 1
        def read(self):
            with self._lock:
                return self.n
    """
    assert _scan(src, "mod.py") == []


def test_thread_confined_attr_is_clean():
    # only the thread side touches _streak: no sharing, no finding
    src = """
    import threading
    class Engine:
        def __init__(self):
            self._streak = 0
        def start(self):
            threading.Thread(target=self._run).start()
        def _run(self):
            self._streak += 1
    """
    assert _scan(src, "mod.py") == []


def test_lock_order_inversion():
    src = """
    class M:
        def fwd(self):
            with self.a_lock:
                with self.b_lock:
                    pass
        def bwd(self):
            with self.b_lock:
                with self.a_lock:
                    pass
    """
    fs = _scan(src, "mod.py")
    assert _rules_of(fs) == ["lock-order"]
    src_consistent = """
    class M:
        def fwd(self):
            with self.a_lock:
                with self.b_lock:
                    pass
        def also_fwd(self):
            with self.a_lock:
                with self.b_lock:
                    pass
    """
    assert _scan(src_consistent, "mod.py") == []


# --------------------------------------------------- rowwise in data plane

def test_rowwise_map_flagged_in_data_plane():
    src = """
    def pad(d, seq_len):
        d["h"] = d["h"].map(lambda h: list(h)[:seq_len])
        return d
    """
    (f,) = _scan(src, "analytics_zoo_tpu/data/mod.py")
    assert f.rule == "rowwise-map-in-data-plane"
    assert f.line == 3
    # friesian/ is the other data-plane tree
    (f,) = _scan(src, "analytics_zoo_tpu/friesian/feature/mod.py")
    assert f.rule == "rowwise-map-in-data-plane"


def test_rowwise_nested_def_and_apply_axis1_flagged():
    src = """
    def xform(d):
        def pad_one(h):
            return list(h) + [0]
        d["h"] = d["h"].map(pad_one)
        d["t"] = d.apply(lambda r: sum(r.values), axis=1)
        d["u"] = d.apply(lambda r: sum(r.values), axis="columns")
        return d
    """
    fs = _scan(src, "analytics_zoo_tpu/data/mod.py")
    assert [f.rule for f in fs] == ["rowwise-map-in-data-plane"] * 3


def test_rowwise_dict_param_and_axis0_not_flagged():
    src = """
    def xform(d, func, mapping):
        d["e"] = d["e"].map(mapping)       # param: udf seam, caller's call
        d["f"] = d["f"].map({1: 2})        # dict map: vectorized lookup
        d["g"] = d["g"].map(len)           # builtin, not a nested def
        d["s"] = d.apply(sum)              # column-wise apply
        return d
    """
    assert _scan(src, "analytics_zoo_tpu/data/mod.py") == []


def test_rowwise_silent_outside_data_plane():
    src = """
    def pad(d, seq_len):
        d["h"] = d["h"].map(lambda h: list(h)[:seq_len])
        return d
    """
    assert _scan(src, "analytics_zoo_tpu/zouwu/mod.py") == []
    assert _scan(src, "analytics_zoo_tpu/serving/mod.py") == []


def test_rowwise_inline_suppression():
    src = """
    def pad(d, seq_len):
        d["h"] = d["h"].map(  # zoolint: disable=rowwise-map-in-data-plane
            lambda h: list(h))
        return d
    """
    assert _scan(src, "analytics_zoo_tpu/data/mod.py") == []


# ---------------------------------------------------------- suppressions

def test_line_suppression_bare_and_named():
    src = """
    import time
    def stamp():
        a = time.time()  # zoolint: disable
        b = time.time()  # zoolint: disable=wallclock-hotpath
        c = time.time()  # zoolint: disable=jit-in-loop
        return a, b, c
    """
    fs = _scan(src)
    assert len(fs) == 1 and fs[0].line == 6


def test_file_suppression():
    src = """
    # zoolint: disable-file=wallclock-hotpath
    import time
    def stamp():
        return time.time()
    """
    assert _scan(src) == []


# -------------------------------------------------------------- baseline

def test_baseline_round_trip(tmp_path):
    mod = tmp_path / "serving" / "mod.py"
    mod.parent.mkdir()
    mod.write_text("import time\n\n\ndef stamp():\n"
                   "    return time.time()\n")
    findings = analyze_paths([str(mod)], root=str(tmp_path))
    assert _rules_of(findings) == ["wallclock-hotpath"]

    bl = tmp_path / "baseline.json"
    n = baseline_lib.save(str(bl), findings, str(tmp_path),
                          justifications=None)
    assert n == 1
    entries = baseline_lib.load(str(bl))
    left, stale = baseline_lib.apply(findings, entries, str(tmp_path))
    assert left == [] and stale == []

    # fingerprints key on line *text*, not line number: shifting the
    # offending line down must not invalidate the baseline ...
    mod.write_text("import time\n\n# a new comment\n\n\ndef stamp():\n"
                   "    return time.time()\n")
    findings2 = analyze_paths([str(mod)], root=str(tmp_path))
    left, stale = baseline_lib.apply(findings2, entries, str(tmp_path))
    assert left == [] and stale == []

    # ... while editing the line itself retires the entry (stale) and
    # resurfaces the finding
    mod.write_text("import time\n\n\ndef stamp():\n"
                   "    return time.time() + 0\n")
    findings3 = analyze_paths([str(mod)], root=str(tmp_path))
    left, stale = baseline_lib.apply(findings3, entries, str(tmp_path))
    assert len(left) == 1 and len(stale) == 1


def test_baseline_preserves_justifications(tmp_path):
    mod = tmp_path / "common" / "mod.py"
    mod.parent.mkdir()
    mod.write_text("import time\nT = time.time()\n")
    findings = analyze_paths([str(mod)], root=str(tmp_path))
    bl = str(tmp_path / "baseline.json")
    baseline_lib.save(bl, findings, str(tmp_path))
    entries = baseline_lib.load(bl)
    fp = next(iter(entries))
    entries[fp]["justification"] = "module-load timestamp, not a loop"
    with open(bl, "w") as fh:
        json.dump({"version": baseline_lib.BASELINE_VERSION,
                   "entries": list(entries.values())}, fh)
    baseline_lib.save(bl, findings, str(tmp_path))
    again = baseline_lib.load(bl)
    assert again[fp]["justification"] == \
        "module-load timestamp, not a loop"


def test_baseline_rejects_unknown_version(tmp_path):
    bl = tmp_path / "baseline.json"
    bl.write_text('{"version": 99, "entries": []}')
    with pytest.raises(ValueError):
        baseline_lib.load(str(bl))


# ---------------------------------------------------------- JSON schema

def test_json_report_schema(tmp_path):
    mod = tmp_path / "learn" / "mod.py"
    mod.parent.mkdir()
    mod.write_text("import time\nT = time.time()\n")
    findings = analyze_paths([str(mod)], root=str(tmp_path))
    obj = json.loads(report.json_report(
        findings, [{"fingerprint": "deadbeefdeadbeef"}], str(tmp_path)))
    assert obj["version"] == report.JSON_SCHEMA_VERSION == 1
    assert set(obj) == {"version", "findings", "stale_baseline", "summary"}
    (f,) = obj["findings"]
    assert set(f) == {"rule", "path", "line", "col", "message",
                      "fingerprint"}
    assert f["path"] == "learn/mod.py"
    assert obj["stale_baseline"] == ["deadbeefdeadbeef"]
    assert obj["summary"] == {"total": 1,
                              "by_rule": {"wallclock-hotpath": 1}}


# ----------------------------------------------------- tree + fixture scan

def test_shipped_tree_clean_modulo_baseline():
    findings = analyze_paths([os.path.join(REPO, "analytics_zoo_tpu")],
                             root=REPO)
    entries = baseline_lib.load(
        os.path.join(REPO, baseline_lib.DEFAULT_BASELINE))
    left, _stale = baseline_lib.apply(findings, entries, REPO)
    assert left == [], "\n".join(f.format() for f in left)
    for e in entries.values():
        assert e["justification"].strip() and \
            not e["justification"].startswith("TODO"), e


def test_seeded_fixture_trips_every_family():
    findings = analyze_paths([FIXTURE], root=REPO)
    got = set(_rules_of(findings))
    # metric-undeclared can't fire here by design: the fixture scan does
    # not cover analytics_zoo_tpu/, so doc-side rows are not checked
    assert got == {
        "wallclock-hotpath", "hotpath-host-sync",
        "jit-in-loop", "jit-call-inline", "jit-static-unhashable",
        "jit-compile-in-serve-loop",
        "engine-unlocked-write", "lock-order",
        "cross-thread-unlocked-state", "lock-order-inversion",
        "blocking-under-lock", "thread-leak",
        "metric-undocumented", "envvar-undocumented",
        "rowwise-map-in-data-plane",
        "record-ack-leak", "lock-release-path", "span-pairing",
        "tainted-host-sync", "shape-dependent-branch-in-jit",
        "kv-page-leak",
    }
    # and the suppressed half of the fixture stays quiet
    sup = [f for f in findings
           if f.path.endswith("bad_hotpath.py") and f.line >= 25]
    assert sup == []


def test_metric_undeclared_requires_full_package_scan(tmp_path):
    # a doc row with no registration fires on a whole-package scan ...
    pkg = tmp_path / "analytics_zoo_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "serving").mkdir()
    (pkg / "serving" / "mod.py").write_text("X = 1\n")
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "observability.md").write_text(
        "| `zoo_ghost_total` | counter |\n")
    fs = analyze_paths([str(pkg)], root=str(tmp_path))
    assert [f.rule for f in fs] == ["metric-undeclared"]
    # ... but a subtree scan must not flag metrics registered elsewhere
    fs = analyze_paths([str(pkg / "serving")], root=str(tmp_path))
    assert fs == []


def test_fleet_fixture_trips_metric_undeclared():
    """The on-disk seeded fixture for the catalog rule the main fixture
    can't fire (ISSUE 6): a documented ``zoo_fleet_*`` metric that no
    code registers must read ``metric-undeclared`` on a full-package
    scan of the fixture root."""
    root = os.path.join(REPO, "tests", "fixtures", "zoolint_fleet")
    fs = analyze_paths([os.path.join(root, "analytics_zoo_tpu")],
                       root=root)
    undeclared = [f for f in fs if f.rule == "metric-undeclared"]
    assert len(undeclared) == 1, [f.format() for f in fs]
    assert "zoo_fleet_ghost_total" in undeclared[0].message
    # the registered-and-documented twin stays clean
    assert not any("zoo_fleet_present_total" in f.message for f in fs)


def test_cli_partial_scan_keeps_baseline_quiet(monkeypatch, capsys):
    # gan.py's baselined findings are out of scope when scanning
    # serving/ only — neither surfaced nor reported stale
    from analytics_zoo_tpu.analysis import cli
    monkeypatch.chdir(REPO)
    rc = cli.main(["analytics_zoo_tpu/serving"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "stale" not in out


def _cli_tree(tmp_path):
    """A minimal anchored checkout with one wallclock finding."""
    (tmp_path / ".git").mkdir()
    mod = tmp_path / "serving" / "mod.py"
    mod.parent.mkdir()
    mod.write_text("import time\n\n\ndef stamp():\n"
                   "    return time.time()\n")
    return mod


def test_cli_github_format(tmp_path, capsys):
    from analytics_zoo_tpu.analysis import cli
    mod = _cli_tree(tmp_path)
    rc = cli.main(["--no-baseline", "--format=github", str(mod)])
    out = capsys.readouterr().out
    assert rc == 1
    line = out.strip().splitlines()[0]
    assert line.startswith("::error file=serving/mod.py,line=5,")
    assert "title=zoolint wallclock-hotpath" in line
    # clean scans emit a notice, not silence
    (tmp_path / "clean.py").write_text("X = 1\n")
    rc = cli.main(["--no-baseline", "--format=github",
                   str(tmp_path / "clean.py")])
    out = capsys.readouterr().out
    assert rc == 0 and "::notice" in out


def test_cli_exit_codes_distinguish_usage_and_crash(monkeypatch, capsys):
    from analytics_zoo_tpu.analysis import cli
    # usage error: 2
    assert cli.main(["/no/such/path.py"]) == 2
    assert cli.main(["--rules", "bogus-rule", "."]) == 2
    # internal crash: 3 (so CI can tell findings from linter bugs)
    def boom(*a, **k):
        raise RuntimeError("linter bug")
    monkeypatch.setattr(cli, "analyze_paths", boom)
    assert cli.main(["--no-baseline", "."]) == 3
    err = capsys.readouterr().err
    assert "internal error" in err and "RuntimeError" in err


def test_cli_jobs_parallel_matches_serial(capsys):
    from analytics_zoo_tpu.analysis import cli
    args = ["--no-baseline", "--format=json", FIXTURE]
    rc1 = cli.main(["--jobs", "1"] + args)
    out1 = capsys.readouterr().out
    rc4 = cli.main(["--jobs", "4"] + args)
    out4 = capsys.readouterr().out
    assert rc1 == rc4 == 1
    assert json.loads(out1) == json.loads(out4)


def test_cli_migrate_baseline_v1_to_v2(tmp_path, capsys):
    from analytics_zoo_tpu.analysis import cli
    mod = _cli_tree(tmp_path)
    findings = analyze_paths([str(mod)], root=str(tmp_path))
    (f, fp1), = baseline_lib.fingerprints(findings, str(tmp_path),
                                          version=1)
    bl = tmp_path / "dev" / "zoolint-baseline.json"
    bl.parent.mkdir()
    bl.write_text(json.dumps({"version": 1, "entries": [{
        "fingerprint": fp1, "rule": f.rule, "path": f.path,
        "line": f.line, "message": f.message,
        "justification": "known wallclock, kept on purpose"}]}))
    # a normal run refuses the v1 file with a pointer at the migration
    assert cli.main([str(mod)]) == 2
    assert "--migrate-baseline" in capsys.readouterr().err
    # one-shot migration preserves the justification ...
    assert cli.main(["--migrate-baseline", str(mod)]) == 0
    assert "migrated" in capsys.readouterr().out
    entries = baseline_lib.load(str(bl))
    (entry,) = entries.values()
    assert entry["justification"] == "known wallclock, kept on purpose"
    # ... and the migrated baseline keeps the tree quiet across a rewrap
    assert cli.main([str(mod)]) == 0
    capsys.readouterr()
    mod.write_text("import time\n\n\ndef stamp():\n"
                   "    return max(time.time(),\n               0 * 1)\n")
    findings = analyze_paths([str(mod)], root=str(tmp_path))
    bl.write_text(json.dumps({"version": 2, "entries": [
        dict(e, fingerprint=fp) for (_f, fp), e in
        zip(baseline_lib.fingerprints(findings, str(tmp_path)),
            entries.values())]}))
    mod.write_text("import time\n\n\ndef stamp():\n"
                   "    return max(time.time(), 0 * 1)\n")
    findings2 = analyze_paths([str(mod)], root=str(tmp_path))
    left, stale = baseline_lib.apply(
        findings2, baseline_lib.load(str(bl)), str(tmp_path))
    assert left == [] and stale == []


def test_cli_ownership_report(tmp_path, capsys):
    from analytics_zoo_tpu.analysis import cli
    _cli_tree(tmp_path)
    out_md = tmp_path / "docs" / "concurrency.md"
    rc = cli.main(["--ownership-report", str(out_md),
                   str(tmp_path / "serving")])
    assert rc == 0
    assert "ownership report written" in capsys.readouterr().out
    assert out_md.is_file()
    js = json.loads((tmp_path / "docs" / "concurrency.json").read_text())
    assert [r["root"] for r in js["roots"]][0] == "main"


def test_syntax_error_is_a_finding(tmp_path):
    mod = tmp_path / "broken.py"
    mod.write_text("def broken(:\n")
    findings = analyze_paths([str(mod)], root=str(tmp_path))
    assert [f.rule for f in findings] == ["syntax-error"]
