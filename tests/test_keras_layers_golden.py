"""Golden-value tests for the keras layer library.

Mirrors the reference's per-layer Spec tests (SURVEY.md §4 "Model
correctness tests compare zoo layer outputs vs Keras/BigDL references",
e.g. zoo/src/test/.../keras/layers/*Spec.scala): every layer family gets a
numeric check against an independent implementation — torch for convs,
pooling, LRN and resize; closed-form numpy for elementwise, locally
connected, highway, maxout and the rest.
"""

import numpy as np
import pytest

from analytics_zoo_tpu.keras import Input, Model
from analytics_zoo_tpu.keras import layers as zl

torch = pytest.importorskip("torch")
import torch.nn.functional as F  # noqa: E402


def run_layer(layer, *xs, train=False, rng_seed=0):
    """Build Input→layer→Model, init and run; returns (output, params)."""
    import jax
    inputs = [Input(shape=x.shape[1:]) for x in xs]
    out = layer(inputs if len(inputs) > 1 else inputs[0])
    m = Model(input=inputs if len(inputs) > 1 else inputs[0], output=out)
    module = m.to_flax()
    variables = module.init(
        {"params": jax.random.PRNGKey(rng_seed),
         "dropout": jax.random.PRNGKey(rng_seed + 1)}, *xs, train=train)
    y = module.apply(variables, *xs, train=train,
                     rngs={"dropout": jax.random.PRNGKey(rng_seed + 2)})
    return np.asarray(y), variables.get("params", {})


def _x(shape, seed=0):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


# ---------------------------------------------------------- elementwise

ELEMENTWISE_CASES = [
    (zl.Identity(), lambda x: x),
    (zl.Exp(), np.exp),
    (zl.Log(), lambda x: np.log(np.abs(x) + 1.0)),  # input made positive
    (zl.Sqrt(), lambda x: np.sqrt(np.abs(x) + 1.0)),
    (zl.Square(), np.square),
    (zl.Negative(), np.negative),
    (zl.AddConstant(2.5), lambda x: x + 2.5),
    (zl.MulConstant(-3.0), lambda x: x * -3.0),
    (zl.Power(2.0, scale=2.0, shift=1.0), lambda x: (1.0 + 2.0 * x) ** 2),
    (zl.HardTanh(-0.5, 0.5), lambda x: np.clip(x, -0.5, 0.5)),
    (zl.HardShrink(0.5), lambda x: np.where(np.abs(x) > 0.5, x, 0.0)),
    (zl.SoftShrink(0.5), lambda x: np.where(
        x > 0.5, x - 0.5, np.where(x < -0.5, x + 0.5, 0.0))),
    (zl.Threshold(0.2, -7.0), lambda x: np.where(x > 0.2, x, -7.0)),
    (zl.BinaryThreshold(0.0), lambda x: (x > 0.0).astype(np.float32)),
    (zl.LeakyReLU(0.1), lambda x: np.where(x >= 0, x, 0.1 * x)),
    (zl.ELU(1.5), lambda x: np.where(x >= 0, x, 1.5 * (np.exp(x) - 1))),
    (zl.ThresholdedReLU(0.7), lambda x: np.where(x > 0.7, x, 0.0)),
]


@pytest.mark.parametrize("layer,ref", ELEMENTWISE_CASES,
                         ids=[type(c[0]).__name__ for c in ELEMENTWISE_CASES])
def test_elementwise_golden(orca_ctx, layer, ref):
    x = _x((4, 6))
    if type(layer).__name__ in ("Log", "Sqrt"):
        x = np.abs(x) + 1.0
        got, _ = run_layer(layer, x)
        np.testing.assert_allclose(got, ref(np.sign(x) * (np.abs(x) - 1.0)),
                                   rtol=1e-5)
        return
    got, _ = run_layer(layer, x)
    np.testing.assert_allclose(got, ref(x), rtol=1e-5, atol=1e-6)


def test_max_select_table(orca_ctx):
    x = _x((3, 5, 4))
    got, _ = run_layer(zl.Max(dim=1), x)
    np.testing.assert_allclose(got, x.max(1), rtol=1e-6)
    a, b = _x((3, 4), 1), _x((3, 4), 2)
    got, _ = run_layer(zl.SelectTable(1), a, b)
    np.testing.assert_allclose(got, b)


# ---------------------------------------------------------- scale/shift

def test_cadd_cmul_scale_mul(orca_ctx):
    x = _x((4, 6))
    got, p = run_layer(zl.CAdd((6,), name="ca"), x)
    np.testing.assert_allclose(got, x + np.asarray(p["ca"]["bias"]),
                               rtol=1e-6)
    got, p = run_layer(zl.CMul((6,), name="cm"), x)
    np.testing.assert_allclose(got, x * np.asarray(p["cm"]["weight"]),
                               rtol=1e-6)
    got, p = run_layer(zl.Scale((6,), name="sc"), x)
    np.testing.assert_allclose(
        got, x * np.asarray(p["sc"]["weight"]) + np.asarray(p["sc"]["bias"]),
        rtol=1e-6)
    got, p = run_layer(zl.Mul(name="mu"), x)
    np.testing.assert_allclose(got, x * float(np.asarray(p["mu"]["weight"])),
                               rtol=1e-6)


def test_prelu_srelu_rrelu(orca_ctx):
    x = _x((4, 6))
    got, p = run_layer(zl.PReLU(name="pr"), x)
    a = np.asarray(p["pr"]["alpha"])
    np.testing.assert_allclose(got, np.where(x >= 0, x, a * x), rtol=1e-6)

    got, p = run_layer(zl.SReLU(name="sr"), x)
    tl, al = np.asarray(p["sr"]["t_left"]), np.asarray(p["sr"]["a_left"])
    tr, ar = np.asarray(p["sr"]["t_right"]), np.asarray(p["sr"]["a_right"])
    want = np.where(x >= tr, tr + ar * (x - tr), x)
    want = np.where(x <= tl, tl + al * (x - tl), want)
    np.testing.assert_allclose(got, want, rtol=1e-6)

    # eval-mode RReLU is deterministic mean-slope leaky relu
    got, _ = run_layer(zl.RReLU(0.1, 0.3), x, train=False)
    np.testing.assert_allclose(got, np.where(x >= 0, x, 0.2 * x), rtol=1e-6)
    # train mode randomizes within [lower, upper]
    got_t, _ = run_layer(zl.RReLU(0.1, 0.3), x, train=True)
    neg = x < 0
    slopes = got_t[neg] / x[neg]
    assert (slopes >= 0.1 - 1e-6).all() and (slopes <= 0.3 + 1e-6).all()
    assert slopes.std() > 0.01


# ---------------------------------------------------------- convolutions

def test_conv3d_matches_torch(orca_ctx):
    x = _x((2, 5, 6, 7, 3))
    got, p = run_layer(zl.Conv3D(4, 2, 3, 3, name="c3"), x)
    w = np.asarray(p["c3"]["kernel"])          # [2,3,3,in,out]
    b = np.asarray(p["c3"]["bias"])
    tw = torch.from_numpy(w.transpose(4, 3, 0, 1, 2))  # [out,in,2,3,3]
    tx = torch.from_numpy(x.transpose(0, 4, 1, 2, 3))
    want = F.conv3d(tx, tw, torch.from_numpy(b)).numpy()
    np.testing.assert_allclose(got, want.transpose(0, 2, 3, 4, 1),
                               rtol=1e-4, atol=1e-4)


def test_separable_conv2d_matches_torch(orca_ctx):
    """Depthwise (groups=in, depth_multiplier=2) + pointwise 1x1 vs the
    same composition in torch (ref convolutional.py:313)."""
    x = _x((2, 9, 10, 3))
    got, p = run_layer(
        zl.SeparableConvolution2D(5, 3, 3, depth_multiplier=2, name="sep"),
        x)
    dw = np.asarray(p["sep"]["depthwise"]["kernel"])   # [3,3,1,6]
    db = np.asarray(p["sep"]["depthwise"]["bias"])
    pw = np.asarray(p["sep"]["pointwise"]["kernel"])   # [1,1,6,5]
    pb = np.asarray(p["sep"]["pointwise"]["bias"])
    tx = torch.from_numpy(x.transpose(0, 3, 1, 2))
    mid = F.conv2d(tx, torch.from_numpy(dw.transpose(3, 2, 0, 1).copy()),
                   torch.from_numpy(db), groups=3)
    want = F.conv2d(mid, torch.from_numpy(pw.transpose(3, 2, 0, 1).copy()),
                    torch.from_numpy(pb)).numpy()
    np.testing.assert_allclose(got, want.transpose(0, 2, 3, 1),
                               rtol=1e-4, atol=1e-4)
    assert got.shape == (2, 7, 8, 5)


def test_atrous_conv_matches_torch(orca_ctx):
    x = _x((2, 12, 3))
    got, p = run_layer(zl.AtrousConvolution1D(5, 3, atrous_rate=2,
                                              name="a1"), x)
    w = np.asarray(p["a1"]["kernel"])          # [k,in,out]
    b = np.asarray(p["a1"]["bias"])
    want = F.conv1d(torch.from_numpy(x.transpose(0, 2, 1)),
                    torch.from_numpy(w.transpose(2, 1, 0)),
                    torch.from_numpy(b), dilation=2).numpy()
    np.testing.assert_allclose(got, want.transpose(0, 2, 1),
                               rtol=1e-4, atol=1e-4)

    x2 = _x((2, 10, 10, 3))
    got, p = run_layer(zl.AtrousConvolution2D(4, 3, 3, atrous_rate=(2, 2),
                                              name="a2"), x2)
    w = np.asarray(p["a2"]["kernel"])
    b = np.asarray(p["a2"]["bias"])
    want = F.conv2d(torch.from_numpy(x2.transpose(0, 3, 1, 2)),
                    torch.from_numpy(w.transpose(3, 2, 0, 1)),
                    torch.from_numpy(b), dilation=2).numpy()
    np.testing.assert_allclose(got, want.transpose(0, 2, 3, 1),
                               rtol=1e-4, atol=1e-4)


def test_deconv2d_matches_torch(orca_ctx):
    x = _x((2, 5, 5, 3))
    got, p = run_layer(zl.Deconvolution2D(4, 3, 3, subsample=(2, 2),
                                          name="d2"), x)
    w = np.asarray(p["d2"]["kernel"])          # [kh,kw,in,out]
    b = np.asarray(p["d2"]["bias"])
    # torch wants [in, out, kh, kw] and flips spatial dims vs XLA's
    # transposed conv (which correlates, not convolves)
    tw = torch.from_numpy(w[::-1, ::-1].transpose(2, 3, 0, 1).copy())
    want = F.conv_transpose2d(torch.from_numpy(x.transpose(0, 3, 1, 2)),
                              tw, torch.from_numpy(b), stride=2).numpy()
    np.testing.assert_allclose(got, want.transpose(0, 2, 3, 1),
                               rtol=1e-4, atol=1e-4)


def test_locally_connected_1d_golden(orca_ctx):
    x = _x((2, 8, 3))
    got, p = run_layer(zl.LocallyConnected1D(4, 3, name="lc"), x)
    w = np.asarray(p["lc"]["kernel"])          # [L', k*c, f]
    b = np.asarray(p["lc"]["bias"])
    want = np.zeros((2, 6, 4), np.float32)
    for pos in range(6):
        patch = x[:, pos:pos + 3, :].reshape(2, -1)
        want[:, pos, :] = patch @ w[pos] + b[pos]
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_locally_connected_2d_golden(orca_ctx):
    x = _x((2, 6, 5, 3))
    got, p = run_layer(zl.LocallyConnected2D(4, 3, 2, name="lc2"), x)
    w = np.asarray(p["lc2"]["kernel"])         # [oh, ow, kh*kw*c, f]
    b = np.asarray(p["lc2"]["bias"])
    oh, ow = 4, 4
    want = np.zeros((2, oh, ow, 4), np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = x[:, i:i + 3, j:j + 2, :].reshape(2, -1)
            want[:, i, j, :] = patch @ w[i, j] + b[i, j]
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_share_conv_is_conv(orca_ctx):
    x = _x((2, 6, 6, 2))
    got, p = run_layer(zl.ShareConvolution2D(3, 3, 3, name="s"), x)
    assert got.shape == (2, 4, 4, 3)


def test_conv_lstm_2d(orca_ctx):
    """ConvLSTM2D: the RNN wrapper must equal a manual step-by-step unroll
    of the same cell."""
    import jax
    import flax.linen as nn
    x = _x((2, 4, 6, 6, 3))
    layer = zl.ConvLSTM2D(5, 3, return_sequences=True, name="cl")
    got, p = run_layer(layer, x)
    assert got.shape == (2, 4, 6, 6, 5)

    cell = nn.ConvLSTMCell(features=5, kernel_size=(3, 3))
    key = next(k for k in p if "ConvLSTMCell" in k)
    carry = cell.initialize_carry(jax.random.PRNGKey(0), x[:, 0].shape)
    outs = []
    for t in range(4):
        carry, y = cell.apply({"params": p[key]}, carry, x[:, t])
        outs.append(np.asarray(y))
    want = np.stack(outs, axis=1)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    last, _ = run_layer(zl.ConvLSTM2D(5, 3, name="cl_last"), x)
    assert last.shape == (2, 6, 6, 5)


def test_conv_lstm_3d_shapes(orca_ctx):
    x = _x((1, 3, 4, 4, 4, 2))
    got, _ = run_layer(zl.ConvLSTM3D(3, 3, return_sequences=True), x)
    assert got.shape == (1, 3, 4, 4, 4, 3)


def test_lrn2d_matches_torch(orca_ctx):
    x = np.abs(_x((2, 5, 5, 7))) + 0.1
    got, _ = run_layer(zl.LRN2D(alpha=1e-2, k=1.2, beta=0.6, n=3), x)
    lrn = torch.nn.LocalResponseNorm(3, alpha=1e-2, beta=0.6, k=1.2)
    want = lrn(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    np.testing.assert_allclose(got, want.transpose(0, 2, 3, 1),
                               rtol=1e-4, atol=1e-5)


def test_resize_bilinear_matches_torch(orca_ctx):
    x = _x((2, 5, 7, 3))
    for align in (False, True):
        got, _ = run_layer(zl.ResizeBilinear(10, 14, align_corners=align), x)
        want = F.interpolate(torch.from_numpy(x.transpose(0, 3, 1, 2)),
                             size=(10, 14), mode="bilinear",
                             align_corners=align).numpy()
        np.testing.assert_allclose(got, want.transpose(0, 2, 3, 1),
                                   rtol=1e-3, atol=1e-3)


# ---------------------------------------------------------- 3D pool/pad

def test_pool3d_matches_torch(orca_ctx):
    x = _x((2, 6, 6, 6, 3))
    tx = torch.from_numpy(x.transpose(0, 4, 1, 2, 3))
    got, _ = run_layer(zl.MaxPooling3D((2, 2, 2)), x)
    want = F.max_pool3d(tx, 2).numpy().transpose(0, 2, 3, 4, 1)
    np.testing.assert_allclose(got, want, rtol=1e-5)
    got, _ = run_layer(zl.AveragePooling3D((2, 2, 2)), x)
    want = F.avg_pool3d(tx, 2).numpy().transpose(0, 2, 3, 4, 1)
    np.testing.assert_allclose(got, want, rtol=1e-5)
    got, _ = run_layer(zl.GlobalMaxPooling3D(), x)
    np.testing.assert_allclose(got, x.max((1, 2, 3)), rtol=1e-6)
    got, _ = run_layer(zl.GlobalAveragePooling3D(), x)
    np.testing.assert_allclose(got, x.mean((1, 2, 3)), rtol=1e-5)


def test_pad_crop_upsample(orca_ctx):
    x = _x((2, 4, 5, 6, 3))
    got, _ = run_layer(zl.ZeroPadding3D((1, 2, 3)), x)
    assert got.shape == (2, 6, 9, 12, 3)
    np.testing.assert_allclose(got[:, 1:5, 2:7, 3:9, :], x)

    x1 = _x((2, 10, 3))
    got, _ = run_layer(zl.Cropping1D((2, 3)), x1)
    np.testing.assert_allclose(got, x1[:, 2:7, :])

    x2 = _x((2, 8, 9, 3))
    got, _ = run_layer(zl.Cropping2D(((1, 2), (3, 0))), x2)
    np.testing.assert_allclose(got, x2[:, 1:6, 3:, :])

    got, _ = run_layer(zl.Cropping3D(((1, 1), (0, 2), (1, 0))), x)
    np.testing.assert_allclose(got, x[:, 1:3, 0:3, 1:, :])

    x1u = _x((2, 4, 3))
    got, _ = run_layer(zl.UpSampling1D(3), x1u)
    np.testing.assert_allclose(got, np.repeat(x1u, 3, axis=1))

    got, _ = run_layer(zl.UpSampling3D((2, 1, 2)), x)
    want = np.repeat(np.repeat(x, 2, axis=1), 2, axis=3)
    np.testing.assert_allclose(got, want)


# ---------------------------------------------------------- dense variants

def test_highway_golden(orca_ctx):
    x = _x((4, 6))
    got, p = run_layer(zl.Highway(activation="tanh", name="hw"), x)
    pt = p["hw"]["transform"]
    ph = p["hw"]["h"]
    t = 1 / (1 + np.exp(-(x @ np.asarray(pt["kernel"])
                          + np.asarray(pt["bias"]))))
    h = np.tanh(x @ np.asarray(ph["kernel"]) + np.asarray(ph["bias"]))
    np.testing.assert_allclose(got, t * h + (1 - t) * x, rtol=1e-4,
                               atol=1e-5)


def test_maxout_dense_golden(orca_ctx):
    x = _x((4, 6))
    got, p = run_layer(zl.MaxoutDense(3, nb_feature=4, name="mo"), x)
    dense = list(p["mo"].values())[0]
    y = x @ np.asarray(dense["kernel"]) + np.asarray(dense["bias"])
    want = y.reshape(4, 4, 3).max(1)
    np.testing.assert_allclose(got, want, rtol=1e-5)
    assert got.shape == (4, 3)


def test_sparse_variants(orca_ctx):
    x = _x((4, 6))
    got, p = run_layer(zl.SparseDense(5, name="sd"), x)
    d = p["sd"]
    np.testing.assert_allclose(
        got, x @ np.asarray(d["kernel"]) + np.asarray(d["bias"]), rtol=1e-5)
    ids = np.array([[1, 2], [0, 3]], np.float32)
    got, p = run_layer(zl.SparseEmbedding(5, 4, name="se"), ids)
    emb = np.asarray(p["se"]["embedding"])
    np.testing.assert_allclose(got, emb[ids.astype(int)], rtol=1e-6)


def test_word_embedding(orca_ctx):
    table = _x((10, 4))
    ids = np.array([[1, 3, 5], [2, 0, 9]], np.float32)
    # frozen: no params, exact lookup
    got, p = run_layer(zl.WordEmbedding(table, trainable=False,
                                        zero_based_id=True), ids)
    assert p == {}
    np.testing.assert_allclose(got, table[ids.astype(int)], rtol=1e-6)
    # 1-based ids shift down
    got, _ = run_layer(zl.WordEmbedding(table, zero_based_id=False),
                       ids + 1)
    np.testing.assert_allclose(got, table[ids.astype(int)], rtol=1e-6)
    # trainable: params hold the pretrained table
    got, p = run_layer(zl.WordEmbedding(table, trainable=True, name="we"),
                       ids)
    np.testing.assert_allclose(np.asarray(p["we"]["embedding"]), table,
                               rtol=1e-6)
    np.testing.assert_allclose(got, table[ids.astype(int)], rtol=1e-6)


def test_word_embedding_from_glove(orca_ctx, tmp_path):
    p = tmp_path / "glove.txt"
    p.write_text("hello 1.0 2.0\nworld 3.0 4.0\nskip 9.0\n")
    we = zl.WordEmbedding.from_glove(str(p), {"hello": 1, "world": 2}, 2)
    np.testing.assert_allclose(we.weights[1], [1.0, 2.0])
    np.testing.assert_allclose(we.weights[2], [3.0, 4.0])
    # lookups are DIRECT: id 1 → hello's vector, id 0 → the pad row
    # (regression: a 1-based shift here read the previous word's vector)
    got, _ = run_layer(we, np.array([[1, 2, 0]], np.float32))
    np.testing.assert_allclose(got[0], [[1.0, 2.0], [3.0, 4.0], [0.0, 0.0]])


# ---------------------------------------------------------- noise

def test_gaussian_noise_and_dropout(orca_ctx):
    x = np.ones((64, 64), np.float32)
    gn = zl.GaussianNoise(0.5)
    eval_out, _ = run_layer(gn, x, train=False)
    np.testing.assert_allclose(eval_out, x)
    train_out, _ = run_layer(gn, x, train=True)
    noise = train_out - x
    assert 0.4 < noise.std() < 0.6 and abs(noise.mean()) < 0.05

    gd = zl.GaussianDropout(0.5)
    eval_out, _ = run_layer(gd, x, train=False)
    np.testing.assert_allclose(eval_out, x)
    train_out, _ = run_layer(gd, x, train=True)
    # multiplicative noise: mean ~1, std ~sqrt(p/(1-p))=1
    assert abs(train_out.mean() - 1.0) < 0.05
    assert 0.9 < train_out.std() < 1.1


def test_spatial_dropout(orca_ctx):
    x = np.ones((8, 16, 32), np.float32)
    sd = zl.SpatialDropout1D(0.5)
    eval_out, _ = run_layer(sd, x, train=False)
    np.testing.assert_allclose(eval_out, x)
    out, _ = run_layer(sd, x, train=True)
    # whole channels are dropped: each (sample, channel) column is all-0
    # or all-scaled
    col = out[0, :, :]
    is_zero = (col == 0).all(axis=0)
    is_scaled = np.isclose(col, 2.0).all(axis=0)
    assert (is_zero | is_scaled).all()
    assert is_zero.any() and is_scaled.any()

    x2 = np.ones((4, 5, 6, 8), np.float32)
    out, _ = run_layer(zl.SpatialDropout2D(0.5), x2, train=True)
    flat = out.reshape(4, -1, 8)
    per_map = (flat == 0).all(axis=1) | np.isclose(flat, 2.0).all(axis=1)
    assert per_map.all()

    x3 = np.ones((2, 3, 4, 5, 6), np.float32)
    out, _ = run_layer(zl.SpatialDropout3D(0.5), x3, train=True)
    flat = out.reshape(2, -1, 6)
    per_map = (flat == 0).all(axis=1) | np.isclose(flat, 2.0).all(axis=1)
    assert per_map.all()


def test_gaussian_sampler(orca_ctx):
    mean = np.full((2048, 4), 3.0, np.float32)
    logv = np.full((2048, 4), np.log(0.25), np.float32)
    got, _ = run_layer(zl.GaussianSampler(), mean, logv, train=True)
    assert abs(got.mean() - 3.0) < 0.05
    assert abs(got.std() - 0.5) < 0.05
    # eval is deterministic (predict/evaluate pass no rng): returns mean
    ev, _ = run_layer(zl.GaussianSampler(), mean, logv, train=False)
    np.testing.assert_allclose(ev, mean)


def test_torch_reused_dropout_draws_independent_masks(orca_ctx):
    """A Dropout module applied twice in forward() must drop different
    positions at each call site (regression: per-module rng keying gave
    both sites the same mask)."""
    import torch as _t
    import torch.nn as tnn
    import jax
    from analytics_zoo_tpu.net.torch_net import torch_to_jax

    class M(tnn.Module):
        def __init__(self):
            super().__init__()
            self.drop = tnn.Dropout(0.5)

        def forward(self, x):
            return self.drop(x), self.drop(x)

    apply_fn, variables = torch_to_jax(M())
    x = np.ones((4, 256), np.float32)
    a, b = apply_fn(variables, x, train=True, rng=jax.random.PRNGKey(0))
    a, b = np.asarray(a), np.asarray(b)
    assert (a == 0).any() and (b == 0).any()
    assert not np.array_equal(a == 0, b == 0), \
        "both call sites dropped identical positions"
