import numpy as np
import pytest

from analytics_zoo_tpu.keras import Input, Model, Sequential
from analytics_zoo_tpu.keras import layers as zl


def test_sequential_mlp_fit(orca_ctx):
    x = np.random.default_rng(0).normal(size=(256, 8)).astype(np.float32)
    y = (x.sum(1) > 0).astype(np.float32)[:, None]
    m = Sequential()
    m.add(zl.Dense(16, activation="relu", input_shape=(8,)))
    m.add(zl.Dropout(0.1))
    m.add(zl.Dense(1, activation="sigmoid"))
    from analytics_zoo_tpu.learn.optimizers import Adam
    m.compile(optimizer=Adam(1e-2), loss="binary_crossentropy",
              metrics=["accuracy"])
    m.fit(x, y, batch_size=32, nb_epoch=15)
    res = m.evaluate(x, y, batch_size=32)
    assert res["accuracy"] > 0.8
    preds = m.predict(x[:10])
    assert preds.shape == (10, 1)


def test_functional_two_tower(orca_ctx):
    a = Input(shape=(4,))
    b = Input(shape=(4,))
    ha = zl.Dense(8, activation="relu")(a)
    hb = zl.Dense(8, activation="relu")(b)
    merged = zl.merge([ha, hb], mode="concat")
    out = zl.Dense(1)(merged)
    m = Model(input=[a, b], output=out)
    m.compile(optimizer="adam", loss="mse")
    xa = np.random.default_rng(1).normal(size=(64, 4)).astype(np.float32)
    xb = np.random.default_rng(2).normal(size=(64, 4)).astype(np.float32)
    y = (xa - xb).sum(1, keepdims=True).astype(np.float32)
    hist = m.fit([xa, xb], y, batch_size=16, nb_epoch=5)
    assert hist["loss"][-1] < hist["loss"][0]
    assert m.predict([xa, xb]).shape == (64, 1)


def test_weight_sharing(orca_ctx):
    import jax
    inp1 = Input(shape=(4,))
    inp2 = Input(shape=(4,))
    shared = zl.Dense(3, name="shared_dense")
    o = zl.merge([shared(inp1), shared(inp2)], mode="sum")
    m = Model(input=[inp1, inp2], output=o)
    module = m.to_flax()
    variables = module.init(jax.random.PRNGKey(0),
                            np.zeros((2, 4), np.float32),
                            np.zeros((2, 4), np.float32))
    # one copy of shared params
    assert list(variables["params"].keys()) == ["shared_dense"]


def test_cnn_layers(orca_ctx):
    m = Sequential()
    m.add(zl.Conv2D(4, 3, 3, activation="relu", input_shape=(8, 8, 1)))
    m.add(zl.MaxPooling2D())
    m.add(zl.Flatten())
    m.add(zl.Dense(10, activation="softmax"))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    x = np.random.default_rng(0).normal(size=(64, 8, 8, 1)).astype(np.float32)
    y = np.random.default_rng(0).integers(0, 10, size=64)
    m.fit(x, y, batch_size=16, nb_epoch=1)
    assert m.predict(x[:4]).shape == (4, 10)
    cls = m.predict_classes(x[:4])
    assert cls.shape == (4,) and cls.dtype.kind == "i"


def test_lstm_gru(orca_ctx):
    for Layer in (zl.LSTM, zl.GRU, zl.SimpleRNN):
        m = Sequential()
        m.add(Layer(6, input_shape=(5, 3)))
        m.add(zl.Dense(1))
        m.compile(optimizer="adam", loss="mse")
        x = np.random.default_rng(0).normal(size=(32, 5, 3)).astype(np.float32)
        y = x.mean((1, 2), keepdims=False)[:, None]
        m.fit(x, y, batch_size=16, nb_epoch=1)
        assert m.predict(x[:3]).shape == (3, 1)


def test_lstm_return_sequences_and_bidirectional(orca_ctx):
    import jax
    m = Sequential()
    m.add(zl.Bidirectional(zl.LSTM(4, return_sequences=True),
                           merge_mode="concat"))
    m.layers[0].layer.input_shape = None
    # Bidirectional needs explicit input_shape on the wrapper path
    seq = Sequential()
    bi = zl.Bidirectional(zl.LSTM(4, return_sequences=True))
    bi.input_shape = (6, 3)
    seq.add(bi)
    seq.add(zl.TimeDistributed(zl.Dense(2)))
    module = seq.to_flax()
    x = np.zeros((2, 6, 3), np.float32)
    variables = module.init(jax.random.PRNGKey(0), x)
    out = module.apply(variables, x)
    assert out.shape == (2, 6, 2)


def test_embedding_and_batchnorm(orca_ctx):
    m = Sequential()
    m.add(zl.Embedding(100, 8, input_shape=(4,)))
    m.add(zl.Flatten())
    m.add(zl.BatchNormalization())
    m.add(zl.Dense(1))
    m.compile(optimizer="adam", loss="mse")
    x = np.random.default_rng(0).integers(0, 100, size=(64, 4)).astype(np.float32)
    y = np.zeros((64, 1), np.float32)
    m.fit(x, y, batch_size=16, nb_epoch=1)
    # batch_stats updated during training
    est = m.estimator
    assert "batch_stats" in est._state["model_state"]


def test_attention_layer(orca_ctx):
    seq = Sequential()
    att = zl.MultiHeadAttention(num_heads=2, head_dim=4)
    att.input_shape = (6, 8)
    seq.add(att)
    seq.add(zl.GlobalAveragePooling1D())
    seq.add(zl.Dense(1))
    seq.compile(optimizer="adam", loss="mse")
    x = np.random.default_rng(0).normal(size=(16, 6, 8)).astype(np.float32)
    y = np.zeros((16, 1), np.float32)
    seq.fit(x, y, batch_size=8, nb_epoch=1)


def test_summary(orca_ctx, capsys):
    m = Sequential()
    m.add(zl.Dense(4, input_shape=(3,), name="d1"))
    m.add(zl.Dense(2, name="d2"))
    text = m.summary()
    assert "d1" in text and "Total params: 26" in text  # 3*4+4 + 4*2+2


def test_node_arith_ops(orca_ctx):
    import jax
    a = Input(shape=(3,))
    out = (a * 2.0) + 1.0
    m = Model(input=a, output=out)
    module = m.to_flax()
    x = np.ones((2, 3), np.float32)
    variables = module.init(jax.random.PRNGKey(0), x)
    np.testing.assert_allclose(module.apply(variables, x), 3.0 * np.ones((2, 3)))


def test_duplicate_user_layer_name_rejected(orca_ctx):
    import pytest
    m = Sequential()
    m.add(zl.Dense(4, input_shape=(3,), name="d"))
    m.add(zl.Dense(2, name="d"))
    with pytest.raises(ValueError, match="duplicate layer name"):
        m.to_flax()


def test_auto_name_avoids_user_collision(orca_ctx):
    import jax
    m = Sequential()
    m.add(zl.Dense(5, input_shape=(3,), name="dense_1"))
    m.add(zl.Dense(7))  # auto-named; must NOT collide with user 'dense_1'
    module = m.to_flax()
    x = np.zeros((2, 3), np.float32)
    variables = module.init(jax.random.PRNGKey(0), x)
    out = module.apply(variables, x)
    assert out.shape == (2, 7)
    assert set(variables["params"].keys()) == {"dense_1", "dense_2"}


def test_rnn_activation_respected(orca_ctx):
    import jax
    m = Sequential()
    m.add(zl.SimpleRNN(4, activation="relu", input_shape=(5, 3)))
    module = m.to_flax()
    x = np.abs(np.random.default_rng(0).normal(size=(2, 5, 3))).astype(np.float32)
    variables = module.init(jax.random.PRNGKey(0), x)
    out = np.asarray(module.apply(variables, x))
    assert (out >= 0).all()  # relu cell output is non-negative; tanh would dip <0


def test_node_reflected_ops(orca_ctx):
    import jax
    a = Input(shape=(3,))
    out = 1.0 - a / 2.0
    m = Model(input=a, output=out)
    module = m.to_flax()
    x = np.full((2, 3), 4.0, np.float32)
    variables = module.init(jax.random.PRNGKey(0), x)
    np.testing.assert_allclose(module.apply(variables, x), -1.0)


def test_time_distributed_checkpoint_stable(orca_ctx, tmp_path):
    def build():
        s = Sequential()
        lstm = zl.LSTM(4, return_sequences=True)
        lstm.input_shape = (6, 3)
        s.add(lstm)
        s.add(zl.TimeDistributed(zl.Dense(2)))
        return s
    m1 = build()
    # burn some global name counters to ensure determinism doesn't depend on them
    for _ in range(3):
        zl.Dense(1)
    m2 = build()
    m1.save_weights(str(tmp_path / "w"))
    m2.load_weights(str(tmp_path / "w"))  # must not raise key mismatch


def test_full_model_save_load_roundtrip(orca_ctx, tmp_path):
    """Model.save/load persists TOPOLOGY + weights in one artifact (ref
    Topology.scala saveModule) — no rebuilding code needed at load."""
    from analytics_zoo_tpu.keras.models import KerasNet

    m = Sequential()
    m.add(zl.Dense(16, activation="relu", input_shape=(6,)))
    m.add(zl.Dropout(0.1))
    m.add(zl.Dense(3))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    rng = np.random.RandomState(0)
    x = rng.randn(64, 6).astype(np.float32)
    y = rng.randint(0, 3, 64).astype(np.int32)
    m.fit(x, y, batch_size=16, nb_epoch=2)
    want = np.asarray(m.predict(x[:8]))

    p = str(tmp_path / "full_model")
    m.save(p)
    loaded = KerasNet.load(p)
    np.testing.assert_allclose(np.asarray(loaded.predict(x[:8])), want,
                               atol=1e-5)
    # the loaded model is trainable (compile config survived)
    h = loaded.fit(x, y, batch_size=16, nb_epoch=1)
    assert np.isfinite(h["loss"][0])


def test_functional_model_save_load(orca_ctx, tmp_path):
    from analytics_zoo_tpu.keras.models import KerasNet

    a = Input(shape=(4,))
    b = Input(shape=(4,))
    out = zl.Dense(2)(zl.merge([zl.Dense(8)(a), zl.Dense(8)(b)],
                               mode="concat"))
    m = Model(input=[a, b], output=out)
    m.compile(optimizer="adam", loss="mse")
    xa = np.random.RandomState(1).randn(16, 4).astype(np.float32)
    xb = np.random.RandomState(2).randn(16, 4).astype(np.float32)
    m.fit([xa, xb], xa[:, :2], batch_size=8, nb_epoch=1)
    want = np.asarray(m.predict([xa, xb]))
    p = str(tmp_path / "func_model")
    m.save(p)
    loaded = KerasNet.load(p)
    np.testing.assert_allclose(np.asarray(loaded.predict([xa, xb])), want,
                               atol=1e-5)


def test_keras_layer_wrapper(orca_ctx):
    """KerasLayerWrapper adopts an arbitrary flax module into the keras
    graph; its params train with the rest (ref wrappers.py:86)."""
    import flax.linen as nn
    from analytics_zoo_tpu.keras.layers import Dense, KerasLayerWrapper
    from analytics_zoo_tpu.keras.models import Sequential

    class Block(nn.Module):
        feats: int = 8

        @nn.compact
        def __call__(self, x, train=False):
            x = nn.Dense(self.feats)(x)
            x = nn.Dropout(0.5, deterministic=not train)(x)
            return nn.relu(x)

    m = Sequential()
    m.add(KerasLayerWrapper(Block(), call_with_train=True,
                            input_shape=(4,), name="blk"))
    m.add(Dense(2))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    rng = np.random.RandomState(0)
    x = rng.randn(64, 4).astype(np.float32)
    y = (x.sum(1) > 0).astype(np.int32)
    import jax
    before = jax.tree_util.tree_map(np.array, m.get_weights())
    h = m.fit(x, y, batch_size=32, nb_epoch=3)
    assert np.isfinite(h["loss"][-1])
    # wrapped params exist under the layer's name AND were trained
    after = m.get_weights()
    assert "blk" in after, f"wrapped params missing: {list(after)}"
    changed = jax.tree_util.tree_map(
        lambda a, b: not np.allclose(a, b), before["blk"], after["blk"])
    assert any(jax.tree_util.tree_leaves(changed)), \
        "wrapped module params did not update"
    probs = np.asarray(m.predict(x[:4]))
    assert probs.shape == (4, 2)
    # dropout inside the wrapped module is inert at predict time
    np.testing.assert_allclose(probs, np.asarray(m.predict(x[:4])),
                               atol=1e-6)


def test_separable_convolution2d_alias():
    from analytics_zoo_tpu.keras.layers import (SeparableConv2D,
                                                SeparableConvolution2D)
    assert SeparableConvolution2D is SeparableConv2D


def test_diverse_layer_save_load_roundtrip(orca_ctx, tmp_path):
    """Serialization round-trip across one of each major layer family
    (ref per-layer serialization Specs, SURVEY §4): conv, norm, pooling,
    separable conv, noise-free dropout, flatten, dense, activations."""
    from analytics_zoo_tpu.keras.models import KerasNet

    m = Sequential()
    m.add(zl.Convolution2D(6, 3, 3, border_mode="same",
                           input_shape=(12, 12, 3)))
    m.add(zl.BatchNormalization())
    m.add(zl.Activation("relu"))
    m.add(zl.SeparableConvolution2D(8, 3, 3, depth_multiplier=2))
    m.add(zl.MaxPooling2D())
    m.add(zl.Dropout(0.2))
    m.add(zl.Flatten())
    m.add(zl.Dense(16, activation="tanh"))
    m.add(zl.Highway())
    m.add(zl.Dense(4, activation="softmax"))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
    rng = np.random.RandomState(1)
    x = rng.rand(32, 12, 12, 3).astype(np.float32)
    y = rng.randint(0, 4, 32).astype(np.int32)
    m.fit(x, y, batch_size=16, nb_epoch=1)
    want = np.asarray(m.predict(x[:8]))

    p = str(tmp_path / "diverse")
    m.save(p)
    loaded = KerasNet.load(p)
    got = np.asarray(loaded.predict(x[:8]))
    np.testing.assert_allclose(got, want, atol=1e-5)
