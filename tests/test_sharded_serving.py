"""Sharded model-executor seam (ISSUE 14).

conftest forces ``--xla_force_host_platform_device_count=8``, so every
test here runs against a real 8-device mesh: ShardedExecutable dispatch
must be numerically equivalent to the unsharded apply, the per-shard HBM
accounting must prove no single device holds the whole model, a warmed
sharded `InferenceModel` must dispatch every rung with ZERO recompiles
(the sharded-aval fix), the fleet metrics merge must NOT sum shard-
labeled resource gauges, and one end-to-end generate request must flow
client → lanes → assembly → sharded prefill → decode loop → typed
result with decode spans on ``GET /trace``.
"""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import flax.linen as nn

from analytics_zoo_tpu.common import compile_ahead, telemetry
from analytics_zoo_tpu.inference import InferenceModel
from analytics_zoo_tpu.parallel.sharded_executable import ShardedExecutable

# tensor-parallel rules: Dense kernels split on the output-feature axis,
# biases (no match) replicate
RULES = [(r"kernel", (None, "model"))]


class _Net(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Dense(32)(x))
        return nn.Dense(8)(x)


def _jit_misses() -> float:
    fam = telemetry.snapshot().get("zoo_jit_cache_misses_total", {})
    if not isinstance(fam, dict):
        return float(fam or 0.0)
    return float(fam.get("fn=inference_model", 0.0))


def _net_and_params():
    net = _Net()
    params = net.init(jax.random.PRNGKey(0),
                      np.zeros((1, 16), np.float32))
    return net, params


# ------------------------------------------------- ShardedExecutable

def test_mesh_is_eight_devices():
    assert len(jax.devices()) == 8     # the whole file depends on this


def test_sharded_dispatch_matches_unsharded():
    net, params = _net_and_params()
    ex = ShardedExecutable(lambda p, x: net.apply(p, x), params,
                           "tp8", param_rules=RULES)
    assert ex.n_shards == 8
    xb = np.random.RandomState(1).randn(4, 16).astype(np.float32)
    ref = np.asarray(net.apply(params, xb))
    np.testing.assert_allclose(np.asarray(ex(xb)), ref,
                               rtol=1e-5, atol=1e-5)


def test_shard_hbm_proves_no_device_holds_whole_model():
    net, params = _net_and_params()
    ex = ShardedExecutable(lambda p, x: net.apply(p, x), params,
                           "tp8", param_rules=RULES)
    hbm = ex.shard_hbm_bytes()
    total = ex.total_param_bytes()
    assert len(hbm) == 8 and total > 0
    # kernels are split 8-way: the largest shard is a fraction of the
    # model, while replicated biases keep the sum at or above the total
    assert max(hbm.values()) < total
    assert sum(hbm.values()) >= total
    fam = telemetry.snapshot().get("zoo_shard_hbm_bytes", {})
    assert isinstance(fam, dict)
    assert any(k.startswith("shard=") for k in fam)


def test_replicated_params_without_rules():
    net, params = _net_and_params()
    ex = ShardedExecutable(lambda p, x: net.apply(p, x), params, "tp8")
    hbm = ex.shard_hbm_bytes(publish=False)
    # no rules matched → every shard holds the full model (the failure
    # mode the max_shard_fraction bench gate exists to catch)
    assert max(hbm.values()) == ex.total_param_bytes()


def test_warm_rungs_dispatch_without_recompile():
    net, params = _net_and_params()
    ex = ShardedExecutable(lambda p, x: net.apply(p, x), params,
                           "tp8", param_rules=RULES, name="warm_rung_test")
    spec = (((16,), np.dtype(np.float32)),)
    ex.warm(spec, (2, 4, 8), block=True)
    for rung in (2, 4, 8):
        out = ex(np.zeros((rung, 16), np.float32))
        assert np.asarray(out).shape == (rung, 8)


# --------------------------------------------- InferenceModel seam

def test_inference_model_shard_matches_unsharded():
    net, params = _net_and_params()
    x0 = np.zeros((1, 16), np.float32)
    plain = InferenceModel().load_flax(net, x0, params=params)
    sharded = InferenceModel().load_flax(net, x0, params=params)
    sharded.shard("tp8", param_rules=RULES)
    info = sharded.shard_info()
    assert info["n_shards"] == 8
    assert max(info["shard_hbm_bytes"].values()) \
        < info["total_param_bytes"]
    xb = np.random.RandomState(3).randn(5, 16).astype(np.float32)
    np.testing.assert_allclose(np.asarray(sharded.predict(xb)),
                               np.asarray(plain.predict(xb)),
                               rtol=1e-5, atol=1e-5)


def test_sharded_warm_ladder_dispatches_recompile_flat():
    """Satellite pin: warmup builds every rung from SHARDED avals, so
    plain numpy batches (tail lengths included) hit the AOT executables
    and ``zoo_jit_cache_misses_total{fn=inference_model}`` stays flat."""
    net, params = _net_and_params()
    im = InferenceModel().load_flax(net, np.zeros((1, 16), np.float32),
                                    params=params)
    im.shard("tp8", param_rules=RULES)
    im.set_ladder(compile_ahead.BucketLadder(2, 8))
    im.warm_up(block=True)
    base = _jit_misses()
    rng = np.random.RandomState(2)
    for n in (2, 3, 4, 5, 8):           # tails pad up to warmed rungs
        out = im.predict(rng.randn(n, 16).astype(np.float32))
        assert np.asarray(out).shape == (n, 8)
    assert _jit_misses() == base


# ------------------------------------------------------ fleet merge

def test_fleet_merge_does_not_sum_shard_gauges():
    """Satellite pin: identically-labeled ``zoo_shard_hbm_bytes`` series
    from different replicas describe the SAME resident parameters — the
    fleet scope must merge them by max, never sum, while counters keep
    adding."""
    a = {"zoo_shard_hbm_bytes": {"shard=0": 100.0, "shard=1": 80.0},
         "zoo_serving_requests_total": 5.0}
    b = {"zoo_shard_hbm_bytes": {"shard=0": 100.0, "shard=1": 90.0},
         "zoo_serving_requests_total": 7.0}
    merged = telemetry.MetricsRegistry.merge_snapshot(a, b)
    assert merged["zoo_shard_hbm_bytes"]["shard=0"] == 100.0
    assert merged["zoo_shard_hbm_bytes"]["shard=1"] == 90.0
    assert merged["zoo_serving_requests_total"] == 12.0
    # the unlabeled KV-rung gauge is non-additive too: two replicas at
    # rung 16 and 8 are a fleet at rung 16, not a fleet at rung 24
    assert telemetry.MetricsRegistry.merge_snapshot(
        {"zoo_kv_cache_rung": 16.0},
        {"zoo_kv_cache_rung": 8.0})["zoo_kv_cache_rung"] == 16.0


# -------------------------------------------------- end-to-end flow

@pytest.mark.parametrize("steps", [16])
def test_serving_generate_end_to_end(steps):
    """Acceptance drill: a generate request (prefill + >= 16 decode
    steps) flows client → lanes → assembly → sharded prefill → decode
    loop → typed ``[steps, dim]`` result, with decode-step spans on
    ``GET /trace`` and the sharding block on ``/healthz``."""
    from analytics_zoo_tpu.models import Seq2Seq
    from analytics_zoo_tpu.serving import (
        Broker, ClusterServing, FrontEnd, InputQueue, OutputQueue,
    )

    m = Seq2Seq(input_dim=3, output_dim=2, hidden_size=8, rnn_type="gru",
                encoder_seq_len=5, decoder_seq_len=4)
    im = InferenceModel().load_zoo(m)
    im.shard("tp2")                     # dp4 x tp2 over the 8 devices
    rng = np.random.RandomState(0)
    enc = rng.randn(5, 3).astype(np.float32)
    start = np.zeros(2, np.float32)

    b = Broker.launch(backend="python")
    eng = ClusterServing(im, b.port, batch_size=4, warmup=False)
    eng.start()
    fe = FrontEnd(b.port, engine=eng).start()
    try:
        in_q = InputQueue(port=b.port)
        out_q = OutputQueue(port=b.port)
        uri = in_q.enqueue("e2e_gen",
                           generate={"max_new_tokens": steps,
                                     "mode": "raw"},
                           x=enc, start=start)
        res = out_q.query(uri, timeout=90.0)
        assert res is not None and res.shape == (steps, 2)
        ref = im.generate(enc[None], start[None], steps, mode="raw")
        np.testing.assert_allclose(res, ref[0], rtol=1e-5, atol=1e-5)

        # a plain predict record runs alongside unharmed
        uri2 = in_q.enqueue("e2e_plain", a_enc=enc,
                            b_dec=np.zeros((4, 2), np.float32))
        res2 = out_q.query(uri2, timeout=60.0)
        assert res2 is not None and res2.shape == (4, 2)

        # decode-step spans visible on the trace endpoint
        with urllib.request.urlopen(
                f"http://127.0.0.1:{fe.port}/trace?uri=e2e_gen") as r:
            tr = json.loads(r.read())
        names = [str(e.get("name", "")) for e in tr.get("traceEvents", [])]
        n_spans = sum(1 for n in names if n.startswith("decode_step_"))
        assert n_spans >= steps, names

        # /healthz carries the per-shard HBM block (an SLO shed in this
        # tiny run answers 503 but the body is still the full document)
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{fe.port}/healthz") as r:
                hz = json.loads(r.read())
        except urllib.error.HTTPError as e:
            hz = json.loads(e.read())
        sharding = hz.get("sharding") or {}
        assert sharding.get("n_shards") == 8
        assert sharding.get("shard_hbm_bytes")
    finally:
        fe.stop()
        eng.stop()
        b.stop()


def test_generate_request_validation():
    from analytics_zoo_tpu.serving import schema
    assert schema.validate_generate(None) is None
    assert schema.validate_generate({}) == {"n": 16}
    g = schema.validate_generate({"max_new_tokens": 8, "mode": "sample",
                                  "temperature": 0.5, "seed": 3})
    assert g == {"n": 8, "m": "sample", "t": 0.5, "s": 3}
    with pytest.raises(ValueError):
        schema.validate_generate({"mode": "beam"})
    with pytest.raises(ValueError):
        schema.validate_generate({"max_new_tokens": 0})
    with pytest.raises(ValueError):
        schema.validate_generate({"bogus": 1})
    with pytest.raises(ValueError):
        schema.validate_generate("greedy")


def test_arrow_wire_format_rejects_generate():
    from analytics_zoo_tpu.serving.client import InputQueue
    # no broker needed: validation happens before any socket write
    q = InputQueue.__new__(InputQueue)
    q.arrow, q.cipher, q.stream = True, None, "s"
    q._tracer = telemetry.get_tracer()
    with pytest.raises(ValueError):
        q._encode("u1", {"x": np.zeros(3, np.float32)},
                  generate={"max_new_tokens": 4})
