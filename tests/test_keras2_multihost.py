"""keras2 API subset (ref pyzoo/zoo/pipeline/api/keras2/layers) + the
multi-host bootstrap wiring (ref SURVEY §2.1 NNContext launchers /
jax.distributed path) + golden checks for core keras-1 conv/rnn layers
vs torch (VERDICT weak #10)."""

import numpy as np
import pytest

from analytics_zoo_tpu.keras import Input, Model, Sequential
from analytics_zoo_tpu.keras2 import layers as k2

torch = pytest.importorskip("torch")
import torch.nn.functional as F  # noqa: E402

from tests.test_keras_layers_golden import run_layer  # noqa: E402


class TestKeras2:
    def test_dense_conv_signatures(self, orca_ctx):
        """keras2 spellings (units/filters/kernel_size/strides/padding)
        build and run through the same engine."""
        m = Sequential()
        m.add(k2.Conv1D(8, kernel_size=3, strides=1, padding="same",
                        activation="relu", input_shape=(16, 4)))
        m.add(k2.MaxPooling1D(pool_size=2))
        m.add(k2.Flatten())
        m.add(k2.Dense(units=2))
        m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
        x = np.random.RandomState(0).randn(32, 16, 4).astype(np.float32)
        y = (x.sum((1, 2)) > 0).astype(np.int32)
        h = m.fit(x, y, batch_size=16, nb_epoch=2)
        assert np.isfinite(h["loss"][-1])
        assert m.predict(x[:4]).shape == (4, 2)

    def test_cropping_and_global_pooling(self, orca_ctx):
        """keras2 aliases for Cropping1D and Global*Pooling compute the
        obvious numpy reductions."""
        x = np.random.RandomState(4).randn(3, 10, 5).astype(np.float32)
        got, _ = run_layer(k2.Cropping1D(cropping=(2, 3)), x)
        np.testing.assert_allclose(got, x[:, 2:-3], atol=1e-6)
        got, _ = run_layer(k2.GlobalMaxPooling1D(), x)
        np.testing.assert_allclose(got, x.max(1), atol=1e-6)
        got, _ = run_layer(k2.GlobalAveragePooling1D(), x)
        np.testing.assert_allclose(got, x.mean(1), atol=1e-5)
        img = np.random.RandomState(5).randn(2, 6, 7, 3).astype(np.float32)
        got, _ = run_layer(k2.GlobalAveragePooling2D(), img)
        np.testing.assert_allclose(got, img.mean((1, 2)), atol=1e-5)

    def test_conv2d_matches_torch(self, orca_ctx):
        x = np.random.RandomState(1).randn(2, 8, 8, 3).astype(np.float32)
        got, p = run_layer(k2.Conv2D(4, kernel_size=3, name="c2"), x)
        w = np.asarray(p["c2"]["kernel"])          # [kh,kw,in,out]
        b = np.asarray(p["c2"]["bias"])
        want = F.conv2d(torch.from_numpy(x.transpose(0, 3, 1, 2)),
                        torch.from_numpy(w.transpose(3, 2, 0, 1).copy()),
                        torch.from_numpy(b)).numpy()
        np.testing.assert_allclose(got, want.transpose(0, 2, 3, 1),
                                   rtol=1e-4, atol=1e-4)

    def test_merge_layers(self, orca_ctx):
        a = np.random.RandomState(2).randn(4, 6).astype(np.float32)
        b = np.random.RandomState(3).randn(4, 6).astype(np.float32)
        got, _ = run_layer(k2.Average(), a, b)
        np.testing.assert_allclose(got, (a + b) / 2, rtol=1e-6)
        got, _ = run_layer(k2.Maximum(), a, b)
        np.testing.assert_allclose(got, np.maximum(a, b), rtol=1e-6)
        got, _ = run_layer(k2.Minimum(), a, b)
        np.testing.assert_allclose(got, np.minimum(a, b), rtol=1e-6)


class TestCoreLayerGoldens:
    """Golden checks vs torch for the ORIGINAL keras-1 conv/rnn layers
    (their earlier coverage was end-to-end convergence only)."""

    def test_conv1d_matches_torch(self, orca_ctx):
        from analytics_zoo_tpu.keras import layers as k1
        x = np.random.RandomState(4).randn(2, 12, 3).astype(np.float32)
        got, p = run_layer(k1.Conv1D(5, 3, name="c1"), x)
        w = np.asarray(p["c1"]["kernel"])          # [k,in,out]
        b = np.asarray(p["c1"]["bias"])
        want = F.conv1d(torch.from_numpy(x.transpose(0, 2, 1)),
                        torch.from_numpy(w.transpose(2, 1, 0).copy()),
                        torch.from_numpy(b)).numpy()
        np.testing.assert_allclose(got, want.transpose(0, 2, 1),
                                   rtol=1e-4, atol=1e-4)

    def test_separable_conv2d_matches_torch(self, orca_ctx):
        from analytics_zoo_tpu.keras import layers as k1
        x = np.random.RandomState(5).randn(2, 8, 8, 3).astype(np.float32)
        got, p = run_layer(k1.SeparableConv2D(6, 3, 3, name="sc"), x)
        dw = np.asarray(p["sc"]["depthwise"]["kernel"])   # [kh,kw,1,c]
        db = np.asarray(p["sc"]["depthwise"]["bias"])
        pw = np.asarray(p["sc"]["pointwise"]["kernel"])   # [1,1,c,out]
        pb = np.asarray(p["sc"]["pointwise"]["bias"])
        tx = torch.from_numpy(x.transpose(0, 3, 1, 2))
        tdw = torch.from_numpy(dw.transpose(3, 2, 0, 1).copy())  # [c,1,k,k]
        t = F.conv2d(tx, tdw, torch.from_numpy(db), groups=3)
        tpw = torch.from_numpy(pw.transpose(3, 2, 0, 1).copy())
        want = F.conv2d(t, tpw, torch.from_numpy(pb)).numpy()
        np.testing.assert_allclose(got, want.transpose(0, 2, 3, 1),
                                   rtol=1e-4, atol=1e-4)

    def test_gru_matches_torch(self, orca_ctx):
        """flax GRUCell uses the torch/cudnn reset-gate formulation, so the
        recurrence can be checked weight-for-weight against torch.GRU."""
        import jax
        import flax.linen as nn
        x = np.random.RandomState(6).randn(2, 5, 3).astype(np.float32)
        H = 4
        cell = nn.GRUCell(features=H)
        variables = cell.init(jax.random.PRNGKey(0),
                              np.zeros((2, H), np.float32), x[:, 0])
        p = variables["params"]

        tg = torch.nn.GRU(3, H, batch_first=True)
        # flax: ir/rz/rn (input) and hr/hz/hn (hidden); torch packs W_ir|iz|in
        wi = np.concatenate([np.asarray(p["ir"]["kernel"]).T,
                             np.asarray(p["iz"]["kernel"]).T,
                             np.asarray(p["in"]["kernel"]).T])
        wh = np.concatenate([np.asarray(p["hr"]["kernel"]).T,
                             np.asarray(p["hz"]["kernel"]).T,
                             np.asarray(p["hn"]["kernel"]).T])
        bi = np.concatenate([np.asarray(p["ir"]["bias"]),
                             np.asarray(p["iz"]["bias"]),
                             np.zeros(H, np.float32)])
        bh = np.concatenate([np.zeros(H, np.float32),
                             np.zeros(H, np.float32),
                             np.asarray(p["hn"]["bias"])])
        with torch.no_grad():
            tg.weight_ih_l0.copy_(torch.from_numpy(wi))
            tg.weight_hh_l0.copy_(torch.from_numpy(wh))
            tg.bias_ih_l0.copy_(torch.from_numpy(bi))
            tg.bias_hh_l0.copy_(torch.from_numpy(bh))
            want, _ = tg(torch.from_numpy(x))
        want = want.detach()

        carry = np.zeros((2, H), np.float32)
        outs = []
        for t in range(x.shape[1]):
            carry, y = cell.apply(variables, carry, x[:, t])
            outs.append(np.asarray(y))
        got = np.stack(outs, 1)
        np.testing.assert_allclose(got, want.numpy(), rtol=1e-4, atol=1e-4)


    def test_lstm_matches_torch(self, orca_ctx):
        """flax OptimizedLSTMCell vs torch.nn.LSTM weight-for-weight
        (torch packs gates as i|f|g|o; flax names them ii/if/ig/io +
        hi/hf/hg/ho with biases on the h-side)."""
        import jax
        import flax.linen as nn
        x = np.random.RandomState(7).randn(2, 5, 3).astype(np.float32)
        H = 4
        cell = nn.OptimizedLSTMCell(features=H)
        carry0 = (np.zeros((2, H), np.float32), np.zeros((2, H), np.float32))
        variables = cell.init(jax.random.PRNGKey(0), carry0, x[:, 0])
        p = variables["params"]

        tl = torch.nn.LSTM(3, H, batch_first=True)
        wi = np.concatenate([np.asarray(p[f"i{g}"]["kernel"]).T
                             for g in "ifgo"])
        wh = np.concatenate([np.asarray(p[f"h{g}"]["kernel"]).T
                             for g in "ifgo"])
        bh = np.concatenate([np.asarray(p[f"h{g}"]["bias"])
                             for g in "ifgo"])
        with torch.no_grad():
            tl.weight_ih_l0.copy_(torch.from_numpy(wi))
            tl.weight_hh_l0.copy_(torch.from_numpy(wh))
            tl.bias_ih_l0.copy_(torch.from_numpy(np.zeros(4 * H, np.float32)))
            tl.bias_hh_l0.copy_(torch.from_numpy(bh))
            want, _ = tl(torch.from_numpy(x))
        want = want.detach().numpy()

        carry = carry0
        outs = []
        for t in range(x.shape[1]):
            carry, y = cell.apply(variables, carry, x[:, t])
            outs.append(np.asarray(y))
        got = np.stack(outs, 1)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


class TestMultihostBootstrap:
    """The jax.distributed init path (ref SURVEY §2.1 launchers; VERDICT
    weak #5: 'code exists, never exercised') — wiring verified with a
    monkeypatched jax.distributed."""

    def test_multihost_calls_distributed_initialize(self, monkeypatch):
        import jax
        from analytics_zoo_tpu.common import context as ctx

        calls = {}

        def fake_init(coordinator_address=None, num_processes=None,
                      process_id=None, **kw):
            calls.update(coordinator=coordinator_address,
                         num=num_processes, pid=process_id)

        monkeypatch.setattr(jax.distributed, "initialize", fake_init)
        ctx.stop_orca_context()
        try:
            ctx.init_orca_context(cluster_mode="multihost",
                                  coordinator_address="10.0.0.1:1234",
                                  num_processes=4, process_id=2)
            assert calls == {"coordinator": "10.0.0.1:1234", "num": 4,
                             "pid": 2}
        finally:
            ctx.stop_orca_context()

    def test_multihost_requires_coordinator(self):
        from analytics_zoo_tpu.common import context as ctx
        ctx.stop_orca_context()
        with pytest.raises((ValueError, TypeError)):
            ctx.init_orca_context(cluster_mode="multihost")
        ctx.stop_orca_context()


class TestKeras2Complete:
    """Full reference keras2 surface (VERDICT r3 missing #2): every class in
    ref pyzoo/zoo/pipeline/api/keras2/layers/*.py has a spelling here with a
    golden or shape test. The ref's other eight keras2 modules are
    license-header stubs with no classes."""

    REF_CLASSES = ["Dense", "Activation", "Dropout", "Flatten",
                   "Conv1D", "Conv2D", "Cropping1D",
                   "MaxPooling1D", "AveragePooling1D",
                   "GlobalAveragePooling1D", "GlobalMaxPooling1D",
                   "GlobalAveragePooling2D",
                   "Maximum", "Minimum", "Average",
                   "LocallyConnected1D"]
    REF_FUNCTIONS = ["maximum", "minimum", "average"]

    def test_class_name_parity(self):
        for name in self.REF_CLASSES:
            assert hasattr(k2, name), f"keras2 missing class {name}"
            assert isinstance(getattr(k2, name), type)
        for name in self.REF_FUNCTIONS:
            assert callable(getattr(k2, name)), f"keras2 missing fn {name}"

    def test_activation_goldens(self, orca_ctx):
        """incl. the keras2-docstring extra spellings tanh_shrink /
        softmin / log_sigmoid (ref keras2/layers/core.py:73)."""
        x = np.random.RandomState(7).randn(4, 6).astype(np.float32)
        got, _ = run_layer(k2.Activation("tanh_shrink"), x)
        np.testing.assert_allclose(got, x - np.tanh(x), atol=1e-6)
        got, _ = run_layer(k2.Activation("softmin"), x)
        e = np.exp(-x - (-x).max(-1, keepdims=True))
        np.testing.assert_allclose(got, e / e.sum(-1, keepdims=True),
                                   atol=1e-6)
        got, _ = run_layer(k2.Activation("log_sigmoid"), x)
        np.testing.assert_allclose(got, -np.log1p(np.exp(-x)), atol=1e-5)

    def test_dropout_train_vs_eval(self, orca_ctx):
        x = np.ones((8, 100), np.float32)
        got_eval, _ = run_layer(k2.Dropout(0.5), x)
        np.testing.assert_allclose(got_eval, x)  # identity at inference
        got_train, _ = run_layer(k2.Dropout(0.5), x, train=True)
        zeros = (got_train == 0).mean()
        assert 0.3 < zeros < 0.7  # ~half dropped
        kept = got_train[got_train != 0]
        np.testing.assert_allclose(kept, 2.0, atol=1e-6)  # inverted scaling

    def test_average_pooling1d_golden(self, orca_ctx):
        x = np.random.RandomState(8).randn(2, 10, 3).astype(np.float32)
        got, _ = run_layer(k2.AveragePooling1D(pool_size=2, strides=2), x)
        want = x.reshape(2, 5, 2, 3).mean(2)
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_locally_connected1d(self, orca_ctx):
        x = np.random.RandomState(9).randn(2, 8, 3).astype(np.float32)
        got, p = run_layer(k2.LocallyConnected1D(4, 3, name="lc"), x)
        assert got.shape == (2, 6, 4)
        w = np.asarray(p["lc"]["kernel"])  # [L', k*c, f]
        want = np.einsum("blk,lkf->blf",
                         np.stack([x[:, i:i + 3, :].reshape(2, 9)
                                   for i in range(6)], 1), w) \
            + np.asarray(p["lc"]["bias"])
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
        with pytest.raises(ValueError, match="valid"):
            k2.LocallyConnected1D(4, 3, padding="same")

    def test_functional_merge(self, orca_ctx):
        from analytics_zoo_tpu.keras import Input, Model
        a, b = Input(shape=(5,)), Input(shape=(5,))
        out = k2.maximum([a, b])
        m = Model(input=[a, b], output=out)
        xa = np.random.RandomState(10).randn(3, 5).astype(np.float32)
        xb = np.random.RandomState(11).randn(3, 5).astype(np.float32)
        np.testing.assert_allclose(m.predict([xa, xb]), np.maximum(xa, xb),
                                   rtol=1e-6)

    def test_dense_input_dim(self, orca_ctx):
        m = Sequential()
        m.add(k2.Dense(3, input_dim=7))
        assert m.predict(np.zeros((2, 7), np.float32)).shape == (2, 3)

    def test_l2_regularizer_decays_weights(self, orca_ctx):
        """Exact weight-decay check: zero inputs + no bias make the data
        gradient vanish, so one SGD step is w' = (1 - 2*l2*lr) * w."""
        import jax
        from analytics_zoo_tpu.keras.regularizers import l2
        from analytics_zoo_tpu.learn.optimizers import SGD

        m = Sequential()
        m.add(k2.Dense(4, use_bias=False, kernel_regularizer=l2(0.05),
                       input_shape=(3,), name="d1"))
        m.compile(optimizer=SGD(learningrate=0.5), loss="mse")
        w0 = np.asarray(m.estimator.adapter.params["d1"]["kernel"]).copy()
        x = np.zeros((16, 3), np.float32)
        y = np.zeros((16, 4), np.float32)
        h = m.fit(x, y, batch_size=16, nb_epoch=1, shuffle=False)
        w1 = np.asarray(jax.device_get(
            m.estimator._state["params"]["d1"]["kernel"]))
        np.testing.assert_allclose(w1, (1 - 2 * 0.05 * 0.5) * w0,
                                   rtol=1e-5, atol=1e-6)
        # reported loss includes the penalty: l2 * sum(w0^2)
        np.testing.assert_allclose(h["loss"][0], 0.05 * (w0 ** 2).sum(),
                                   rtol=1e-4)

    def test_l1_regularizer_changes_training(self, orca_ctx):
        """A conv with l1 on the kernel trains to a smaller weight norm
        than the same model without it (end-to-end through fit)."""
        from analytics_zoo_tpu.keras.regularizers import l1
        import jax
        rs = np.random.RandomState(3)
        x = rs.randn(64, 8, 2).astype(np.float32)
        y = rs.randn(64, 1).astype(np.float32)

        def norm_after(reg):
            m = Sequential()
            m.add(k2.Conv1D(4, 3, kernel_regularizer=reg,
                            input_shape=(8, 2), name="c"))
            m.add(k2.GlobalAveragePooling1D())
            m.add(k2.Dense(1, name="d"))
            m.compile(optimizer="adam", loss="mse")
            m.fit(x, y, batch_size=32, nb_epoch=3, shuffle=False)
            k = jax.device_get(m.estimator._state["params"]["c"]["kernel"])
            return float(np.abs(np.asarray(k)).sum())

        assert norm_after(l1(0.5)) < norm_after(None)
