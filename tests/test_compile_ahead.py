"""Compile-ahead execution tests (ISSUE 5): bucket-ladder math, the AOT
executable cache (hit/miss/fallback, zero jit recompiles on warm
dispatch), the persistent compile-cache latch, bitwise equality of
padded-to-rung vs unpadded outputs for ``InferenceModel.predict`` and
the serving drain path, and the warmup integration invariant — traffic
crossing a bucket-growth boundary with a flat recompile counter and no
serve-thread span overlapping a compile span."""

import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from analytics_zoo_tpu.common import compile_ahead, telemetry
from analytics_zoo_tpu.common.compile_ahead import (
    WARMUP_TRACE_ID,
    BucketLadder,
    ExecutableCache,
    batch_avals,
    configure_persistent_cache,
    pad_to_rung,
)


# ------------------------------------------------------------------ ladder
def test_ladder_power_of_two_rungs():
    assert BucketLadder(4, 32).rungs == (4, 8, 16, 32)
    assert BucketLadder(2, 2).rungs == (2,)
    assert BucketLadder(3).rungs == (3,)
    # a max that is not a doubling of min clamps the top rung
    assert BucketLadder(4, 24).rungs == (4, 8, 16, 24)


def test_ladder_selection_and_stepping():
    lad = BucketLadder(4, 32)
    assert lad.min == 4 and lad.max == 32
    assert lad.rung_for(1) == 4
    assert lad.rung_for(4) == 4
    assert lad.rung_for(5) == 8
    assert lad.rung_for(9) == 16
    assert lad.rung_for(1000) == 32          # clamps to the top
    assert lad.up(4) == 8 and lad.up(32) == 32
    assert lad.down(32) == 16 and lad.down(4) == 4
    assert 8 in lad and 6 not in lad
    assert list(lad) == [4, 8, 16, 32] and len(lad) == 4


def test_ladder_validation():
    with pytest.raises(ValueError):
        BucketLadder(0)
    with pytest.raises(ValueError):
        BucketLadder(8, 4)


# ----------------------------------------------------------------- padding
def test_pad_to_rung_repeats_last_row_and_observes_fraction():
    a = np.arange(6, dtype=np.float32).reshape(3, 2)
    b = np.arange(3, dtype=np.int32)
    (pa, pb) = pad_to_rung((a, b), 4, site="t_pad_unit")
    assert pa.shape == (4, 2) and pb.shape == (4,)
    np.testing.assert_array_equal(pa[:3], a)
    np.testing.assert_array_equal(pa[3], a[-1])      # repeated last row
    assert pb[3] == b[-1]
    # full batches observe 0 so the histogram mean is the true waste rate
    (same,) = pad_to_rung((a,), 3, site="t_pad_unit")
    assert same is a
    with pytest.raises(ValueError):
        pad_to_rung((a,), 2, site="t_pad_unit")
    h = telemetry.snapshot()["zoo_bucket_pad_fraction"]["site=t_pad_unit"]
    assert h["count"] == 2
    assert h["sum"] == pytest.approx(0.25)           # (4-3)/4 then 0


def test_batch_avals():
    spec = [((3,), np.dtype(np.float32)), ((2, 2), np.dtype(np.int32))]
    avals = batch_avals(spec, 8)
    assert [tuple(a.shape) for a in avals] == [(8, 3), (8, 2, 2)]
    assert [a.dtype for a in avals] == [np.float32, np.int32]


# -------------------------------------------------- persistent cache latch
def test_persistent_cache_latch_and_disable(tmp_path):
    import jax
    old = getattr(jax.config, "jax_compilation_cache_dir", None)
    try:
        jax.config.update("jax_compilation_cache_dir", None)
        compile_ahead._reset_cache_config_for_tests()
        target = str(tmp_path / "xla_cache")
        got = configure_persistent_cache(target)
        assert got == target and os.path.isdir(target)
        # latched: a second call with a different path is a no-op
        assert configure_persistent_cache(str(tmp_path / "other")) == target
        assert getattr(jax.config, "jax_compilation_cache_dir") == target

        compile_ahead._reset_cache_config_for_tests()
        jax.config.update("jax_compilation_cache_dir", None)
        assert configure_persistent_cache("off") is None
        assert getattr(jax.config, "jax_compilation_cache_dir", None) is None
    finally:
        jax.config.update("jax_compilation_cache_dir", old)
        compile_ahead._reset_cache_config_for_tests()
        if old:
            configure_persistent_cache(old)


def test_persistent_cache_respects_existing_config(tmp_path):
    import jax
    old = getattr(jax.config, "jax_compilation_cache_dir", None)
    try:
        mine = str(tmp_path / "user_cache")
        jax.config.update("jax_compilation_cache_dir", mine)
        compile_ahead._reset_cache_config_for_tests()
        # a user-configured directory is adopted, never overwritten
        assert configure_persistent_cache(str(tmp_path / "zoo")) == mine
    finally:
        jax.config.update("jax_compilation_cache_dir", old)
        compile_ahead._reset_cache_config_for_tests()
        if old:
            configure_persistent_cache(old)


# --------------------------------------------------------- executable cache
def _fresh_cache(fn, name):
    import jax
    reg = telemetry.MetricsRegistry()
    tracer = telemetry.Tracer()
    return ExecutableCache(jax.jit(fn), name=name, registry=reg,
                           tracer=tracer), reg, tracer


def _counter(reg, metric, name):
    return reg.snapshot().get(metric, {}).get(f"fn={name}", 0.0)


def test_cache_warm_then_hit(orca_ctx):
    import jax
    cache, reg, tracer = _fresh_cache(lambda x: x * 2.0 + 1.0, "t_warm")
    aval = jax.ShapeDtypeStruct((4, 3), np.float32)
    assert not cache.ready(aval)
    assert cache.warm(aval)
    assert cache.ready(aval) and len(cache) == 1
    assert cache.warm(aval)                          # idempotent
    x = np.ones((4, 3), np.float32)
    np.testing.assert_array_equal(np.asarray(cache(x)), x * 2.0 + 1.0)
    assert _counter(reg, "zoo_compile_cache_hits_total", "t_warm") == 1
    assert _counter(reg, "zoo_compile_cache_misses_total", "t_warm") == 0
    # exactly one timed compile, recorded as a span on the warmup trace
    hist = reg.snapshot()["zoo_compile_seconds"]["fn=t_warm"]
    assert hist["count"] == 1
    spans = tracer.get(WARMUP_TRACE_ID)
    assert [s.name for s in spans] == ["compile"]


def test_cache_miss_compiles_then_hits(orca_ctx):
    cache, reg, _ = _fresh_cache(lambda x: x - 3.0, "t_miss")
    x = np.full((2, 2), 5.0, np.float32)
    np.testing.assert_array_equal(np.asarray(cache(x)), x - 3.0)
    assert _counter(reg, "zoo_compile_cache_misses_total", "t_miss") == 1
    np.testing.assert_array_equal(np.asarray(cache(x)), x - 3.0)
    assert _counter(reg, "zoo_compile_cache_hits_total", "t_miss") == 1
    # a different shape is its own signature
    y = np.zeros((3, 2), np.float32)
    cache(y)
    assert _counter(reg, "zoo_compile_cache_misses_total", "t_miss") == 2
    assert len(cache) == 2


def test_cache_falls_back_to_callable_without_lower(orca_ctx):
    # a plain callable has no .lower — the AOT path fails, the call still
    # returns through the wrapped function and warm() reports failure
    reg = telemetry.MetricsRegistry()
    cache = ExecutableCache(lambda x: x * 4.0, name="t_fallback",
                            registry=reg, tracer=telemetry.Tracer())
    x = np.ones(3, np.float32)
    np.testing.assert_array_equal(cache(x), x * 4.0)
    assert _counter(reg, "zoo_compile_cache_misses_total", "t_fallback") == 1
    import jax
    assert not cache.warm(jax.ShapeDtypeStruct((3,), np.float32))
    assert len(cache) == 0


def test_process_exits_cleanly_during_warmup():
    """A short-lived process must not abort while a background ladder
    warmup is mid-compile: a daemon thread killed inside an XLA compile
    takes the interpreter down from C++ ('terminate called without an
    active exception'). The atexit drain in compile_ahead cancels the
    remaining rungs and joins the in-flight build."""
    src = (
        "import jax, numpy as np\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from analytics_zoo_tpu.common import compile_ahead, telemetry\n"
        "cache = compile_ahead.ExecutableCache(\n"
        "    jax.jit(lambda x: (x @ x.T).sum(-1)), name='t_exit',\n"
        "    registry=telemetry.MetricsRegistry(),\n"
        "    tracer=telemetry.Tracer())\n"
        "cache.warm_async([(jax.ShapeDtypeStruct((r, 64), np.float32),)\n"
        "                  for r in (8, 16, 32, 64, 128)])\n"
        # exit immediately, compiles still in flight
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run([sys.executable, "-c", src], capture_output=True,
                          text=True, timeout=300, cwd=repo)
    assert proc.returncode == 0, \
        f"exit during warmup crashed ({proc.returncode}):\n{proc.stderr[-2000:]}"


def test_cache_warm_async_builds_all_rungs(orca_ctx):
    import jax
    cache, _, _ = _fresh_cache(lambda x: x.sum(axis=-1), "t_async")
    sets = [(jax.ShapeDtypeStruct((r, 3), np.float32),) for r in (2, 4, 8)]
    t = cache.warm_async(sets)
    assert isinstance(t, threading.Thread)
    t.join(60)
    assert len(cache) == 3
    for (aval,) in sets:
        assert cache.ready(aval)


def test_warm_dispatch_leaves_jit_counters_flat(orca_ctx):
    """The tentpole invariant at unit scale: an AOT-warmed signature
    dispatches through the stored executable, so the instrument_jit
    recompile counter cannot move."""
    import jax
    reg = telemetry.MetricsRegistry()
    jitted = telemetry.instrument_jit(lambda x: x @ x.T, name="t_flat",
                                      registry=reg)
    cache = ExecutableCache(jitted, name="t_flat", registry=reg,
                            tracer=telemetry.Tracer())
    aval = jax.ShapeDtypeStruct((4, 2), np.float32)
    assert cache.warm(aval)
    x = np.ones((4, 2), np.float32)
    for _ in range(3):
        cache(x)
    assert jitted.cache_misses == 0
    assert _counter(reg, "zoo_jit_calls_total", "t_flat") == 0
    assert _counter(reg, "zoo_compile_cache_hits_total", "t_flat") == 3


# --------------------------------------------- bitwise: padded vs unpadded
def _flax_im(n_in=6, n_out=4):
    import flax.linen as nn
    from analytics_zoo_tpu.inference import InferenceModel

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(n_out)(nn.relu(nn.Dense(16)(x)))

    return InferenceModel().load_flax(
        Net(), np.zeros((1, n_in), np.float32))


def _two_input_im():
    import flax.linen as nn
    import jax.numpy as jnp
    from analytics_zoo_tpu.inference import InferenceModel

    class TwoIn(nn.Module):
        @nn.compact
        def __call__(self, a, b):
            h = jnp.concatenate([a, b], axis=-1)
            return nn.Dense(3)(nn.relu(nn.Dense(8)(h)))

    sample = (np.zeros((1, 4), np.float32), np.zeros((1, 2), np.float32))
    return InferenceModel().load_flax(TwoIn(), sample)


def test_predict_padded_tail_bitwise(orca_ctx):
    """Tail chunk that doesn't divide the rung: 10 rows at batch_size=4
    pads the final 2-row chunk to rung 4 — outputs must be bitwise
    identical to the unpadded single-chunk predict."""
    im = _flax_im()
    x = np.random.default_rng(3).standard_normal((10, 6)).astype(np.float32)
    base = im.predict(x)                      # one unpadded chunk of 10
    im.set_ladder(4, 8)
    im.warm_up(block=True)
    padded = im.predict(x, batch_size=4)      # chunks 4, 4, 2->pad 4
    np.testing.assert_array_equal(base, padded)


def test_predict_padded_multi_input_bitwise(orca_ctx):
    im = _two_input_im()
    rng = np.random.default_rng(4)
    a = rng.standard_normal((11, 4)).astype(np.float32)
    b = rng.standard_normal((11, 2)).astype(np.float32)
    base = im.predict((a, b))
    im.set_ladder(4, 8)
    im.warm_up(block=True)
    padded = im.predict((a, b), batch_size=8)  # chunks 8, 3->pad rung 4
    np.testing.assert_array_equal(base, padded)


def test_serving_drain_path_padded_bitwise(orca_ctx):
    """The engine pads every drained batch to a ladder rung; results per
    record must be bitwise identical to an unpadded direct predict."""
    from analytics_zoo_tpu.serving import (
        Broker, ClusterServing, InputQueue, OutputQueue,
    )
    im = _flax_im(n_in=3, n_out=2)
    rng = np.random.default_rng(5)
    xs = {f"u{i}": rng.standard_normal(3).astype(np.float32)
          for i in range(6)}
    stacked = np.stack(list(xs.values()))
    base = np.asarray(im.predict(stacked))    # one unpadded chunk of 6
    with Broker.launch() as broker, \
            ClusterServing(im, broker.port, batch_size=8,
                           min_batch_size=8, max_batch_size=8,
                           pipeline_window=2).start() as eng:
        in_q = InputQueue(port=broker.port)
        out_q = OutputQueue(port=broker.port)
        uris = in_q.enqueue_batch((u, {"x": v}) for u, v in xs.items())
        res = out_q.query_many(uris, timeout=30.0)
        eng.wait_warm(timeout=120)   # don't leak a warm thread to the next test
    assert all(v is not None for v in res.values())
    for i, u in enumerate(xs):
        np.testing.assert_array_equal(res[u], base[i])


# -------------------------------------------------- warmup integration
def test_serving_warmup_growth_no_recompiles_no_overlap(orca_ctx):
    """ISSUE 5 acceptance at test scale: after the background ladder
    warmup, a burst that crosses at least one bucket-growth boundary
    leaves ``zoo_jit_cache_misses_total{fn=inference_model}`` flat, and
    no serve-thread span overlaps any compile span."""
    from analytics_zoo_tpu.serving import (
        Broker, ClusterServing, InputQueue, OutputQueue,
    )

    def jit_misses():
        return telemetry.snapshot().get(
            "zoo_jit_cache_misses_total", {}).get("fn=inference_model", 0.0)

    # hermetic span window: drain warmup threads other tests left behind,
    # then only consider compile spans that START inside this test
    for t in threading.enumerate():
        if t.name.startswith("zoo-warmup"):
            t.join(120)
    from time import perf_counter
    t0 = perf_counter()

    im = _flax_im(n_in=3, n_out=2)
    rng = np.random.default_rng(6)
    xs = {f"w{i}": rng.standard_normal(3).astype(np.float32)
          for i in range(96)}
    with Broker.launch() as broker, \
            ClusterServing(im, broker.port, batch_size=2,
                           min_batch_size=2, max_batch_size=8,
                           pipeline_window=2).start() as eng:
        assert eng.wait_warm(timeout=120) is eng
        for rung in eng.ladder.rungs:
            assert im.rung_ready(rung), f"rung {rung} not warm"
        # the serve loop's idle dequeue poll (<= block_ms) may already be
        # in flight while the last background compile tails off — that
        # blocked broker read is not serve work. Let one poll cycle pass
        # so every burst span starts strictly after the compiles end.
        import time
        time.sleep(0.25)
        base = jit_misses()
        in_q = InputQueue(port=broker.port)
        out_q = OutputQueue(port=broker.port)
        uris = in_q.enqueue_batch((u, {"x": v}) for u, v in xs.items())
        res = out_q.query_many(uris, timeout=60.0)
        peak = eng.batch_size
    assert all(v is not None for v in res.values())
    assert peak > 2, "burst never crossed a bucket-growth boundary"
    assert jit_misses() == base, "serve path recompiled after warmup"

    # every compile span must end before any serve-thread span of this
    # burst starts (stall-free: the serve thread never builds an exe)
    tracer = telemetry.get_tracer()
    compiles = [(s.start, s.end) for s in tracer.get(WARMUP_TRACE_ID)
                if s.start >= t0]
    assert compiles, "warmup recorded no compile spans"
    serve_spans = [s for u in xs for s in tracer.get(u)]
    assert serve_spans, "burst recorded no serving spans"
    for s in serve_spans:
        for c0, c1 in compiles:
            assert s.end <= c0 or c1 <= s.start, \
                f"serve span {s.name} overlaps a compile span"


def test_engine_idle_shrink_records_bucket(orca_ctx):
    """Satellite: sustained idle steps the bucket DOWN one rung and the
    transition lands on the batch_size timer + serving gauge."""
    from analytics_zoo_tpu.serving import ClusterServing

    class Duck:
        def predict_async(self, x):
            return np.asarray(x)

        def predict_fetch(self, pending):
            return pending

    eng = ClusterServing(Duck(), broker_port=0, batch_size=8,
                         min_batch_size=2, max_batch_size=8,
                         stream="t_shrink")
    assert eng.batch_size == 8
    for _ in range(eng.IDLE_SHRINK_AFTER):
        eng._grow_batch_on_backlog(0)         # empty polls count as idle
    assert eng.batch_size == 4                # one rung down, not a crash
    m = eng.metrics()
    assert m["batch_size"]["count"] >= 1
    snap = telemetry.snapshot()
    assert snap["zoo_serving_batch_bucket"]["stream=t_shrink"] == 4
    # shrink floors at min_batch_size
    for _ in range(2 * eng.IDLE_SHRINK_AFTER):
        eng._grow_batch_on_backlog(0)
    assert eng.batch_size == 2
    for _ in range(2 * eng.IDLE_SHRINK_AFTER):
        eng._grow_batch_on_backlog(0)
    assert eng.batch_size == 2
