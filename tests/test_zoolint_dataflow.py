"""zoolint v3: CFG construction, the worklist solver, the five
path-sensitive rules (positive and negative per rule), the CFG cache,
the CLI surface (--timing, --prune-baseline, --jobs), and the
acceptance demo — a hand-introduced exception-edge ack drop in
serving/engine.py that record-ack-leak must catch."""

import ast
import json
import os
import subprocess
import sys
import textwrap

import pytest

from analytics_zoo_tpu.analysis import analyze_paths, analyze_source
from analytics_zoo_tpu.analysis.core import (
    CFG, CFG_STATS, dataflow, parse_file,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(REPO, "tests", "fixtures", "zoolint")
ENGINE = os.path.join(REPO, "analytics_zoo_tpu", "serving", "engine.py")


def _cfg(src):
    tree = ast.parse(textwrap.dedent(src))
    fn = tree.body[0]
    return CFG(fn), fn


def _scan(src, relpath="serving/mod.py"):
    return analyze_source(textwrap.dedent(src), relpath)


def _rules_of(findings):
    return {f.rule for f in findings}


def _cli(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, "-m", "analytics_zoo_tpu.analysis", *args],
        cwd=cwd, capture_output=True, text=True)


# ------------------------------------------------------------ golden CFGs

def test_cfg_loop_break_continue_edges():
    g, fn = _cfg("""
    def f(xs):
        t = 0
        for x in xs:
            if x < 0:
                continue
            if x > 9:
                break
            t = t + x
        return t
    """)
    kinds = g.edge_kinds()
    assert {"true", "false", "back", "break", "continue",
            "return"} <= kinds
    loop = fn.body[1]
    head = g.blocks_of(loop)[0]
    # the back edge and the continue edge both target the loop head
    back_srcs = [b.idx for b in g.blocks
                 for d, k in b.succs if d == head and k == "back"]
    cont_srcs = [b.idx for b in g.blocks
                 for d, k in b.succs if d == head and k == "continue"]
    assert back_srcs and cont_srcs
    # break leaves the loop without touching the head
    brk = [d for b in g.blocks for d, k in b.succs if k == "break"]
    assert brk and head not in brk


def test_cfg_try_finally_duplicates_finally_body():
    g, fn = _cfg("""
    def f(x):
        try:
            return g(x)
        finally:
            done()
    """)
    fin = fn.body[0].finalbody[0]
    copies = g.blocks_of(fin)
    # one copy per way of reaching it: normal fallthrough, exception,
    # and the inline copy the return crosses
    assert len(copies) == 3
    # the return's copy continues to the function exit with kind return
    assert any((g.exit, "return") in g.block(b).succs for b in copies)
    # the exceptional copy re-raises: it reaches the raise exit
    assert any((g.raise_exit, "exc") in g.block(b).succs for b in copies)


def test_cfg_exception_edges_route_to_handler():
    g, fn = _cfg("""
    def f(x):
        try:
            y = decode(x)
        except ValueError:
            y = None
        return y
    """)
    risky = fn.body[0].body[0]
    handler = fn.body[0].handlers[0].body[0]
    rb = g.blocks_of(risky)[0]
    hb = g.blocks_of(handler)[0]
    # the call statement's exception edge lands at the handler entry,
    # whose block chain reaches the handler body — not the raise exit
    reach, seen = [rb], set()
    hit = False
    while reach:
        cur = reach.pop()
        if cur in seen:
            continue
        seen.add(cur)
        if cur == hb:
            hit = True
        reach.extend(d for d, _k in g.block(cur).succs)
    assert hit
    # a non-catch-all handler still lets the exception escape
    assert g.raise_exit in seen


def test_cfg_with_desugaring():
    g, fn = _cfg("""
    def f(p):
        with open(p) as fh:
            data = fh.read()
        return data
    """)
    w = fn.body[0]
    wb = g.blocks_of(w)
    assert len(wb) == 1 and g.block(wb[0]).label == "with"
    # the context expression can raise; the body flows through the
    # with-exit back to the function tail
    assert (g.raise_exit, "exc") in g.block(wb[0]).succs
    body = g.blocks_of(w.body[0])[0]
    exits = [d for d, _k in g.block(body).succs
             if g.block(d).label == "with-exit"]
    assert exits


def test_dataflow_forward_join_over_branches_and_loops():
    g, fn = _cfg("""
    def f(a, xs):
        if a:
            x = 1
        else:
            x = 2
        n = 0
        for v in xs:
            n = n + 1
        return x + n
    """)

    def transfer(block, fact):
        s = block.stmt
        if isinstance(s, ast.Assign):
            return fact | {t.id for t in s.targets
                           if isinstance(t, ast.Name)}
        if block.label == "loop-head" and isinstance(s, ast.For):
            return fact | {s.target.id}
        return fact

    facts = dataflow(g, transfer, init=frozenset(), bottom=frozenset(),
                     join=lambda a, b: a | b)
    assert {"x", "n", "v"} <= facts[g.exit]


def test_dataflow_backward_reach_avoid():
    g, fn = _cfg("""
    def f(a):
        if a:
            return 1
        return 2
    """)
    ret1 = g.blocks_of(fn.body[0].body[0])[0]

    def transfer(block, fact):
        return False if block.idx == ret1 else fact

    facts = dataflow(g, transfer, init=True, bottom=False,
                     join=lambda a, b: a or b, backward=True)
    # from the entry, the `return 2` path reaches exit without ret1
    assert facts[g.entry] is True


# ------------------------------------------------------- record-ack-leak

_LEAK = """
def drain(client, stream, group):
    entries = client.xreadgroup(group, "w", {stream: ">"})
    acks = []
    for eid, payload in entries:
        if payload is None:
            continue
        acks.append(("XACK", stream, group, eid))
    client.pipeline(acks)
"""

_CLEAN = """
def drain(client, stream, group):
    entries = client.xreadgroup(group, "w", {stream: ">"})
    acks = []
    buckets = []
    for eid, payload in entries:
        if payload is None:
            acks.append(("XACK", stream, group, eid))
            continue
        buckets.append((eid, payload))
    if acks:
        client.pipeline(acks)
    return buckets
"""


def test_ack_leak_positive_and_negative():
    assert "record-ack-leak" in _rules_of(_scan(_LEAK))
    assert "record-ack-leak" not in _rules_of(_scan(_CLEAN))


def test_ack_leak_needs_serving_path():
    assert "record-ack-leak" not in _rules_of(_scan(_LEAK, "data/mod.py"))


def test_ack_leak_escaping_exception_is_not_a_leak():
    # the lease/redelivery contract covers exceptions that propagate
    # out of the function — only *handled-and-continued* paths leak
    src = """
    def drain(client, stream, group):
        entries = client.xreadgroup(group, "w", {stream: ">"})
        acks = []
        for eid, payload in entries:
            decode(payload)
            acks.append(("XACK", stream, group, eid))
        client.pipeline(acks)
    """
    assert "record-ack-leak" not in _rules_of(_scan(src))


def test_ack_leak_double_settlement():
    src = """
    def drain(client, stream, group):
        entries = client.xreadgroup(group, "w", {stream: ">"})
        acks = []
        buckets = []
        for eid, payload in entries:
            buckets.append((eid, payload))
            acks.append(("XACK", stream, group, eid))
        client.pipeline(acks)
    """
    f = [x for x in _scan(src) if x.rule == "record-ack-leak"]
    assert f and "more than once" in f[0].message


def test_ack_flush_positive_negative_and_guard():
    unflushed = """
    def drain(client, stream, group):
        entries = client.xreadgroup(group, "w", {stream: ">"})
        acks = []
        for eid, p in entries:
            acks.append(("XACK", stream, group, eid))
    """
    f = [x for x in _scan(unflushed) if x.rule == "record-ack-leak"]
    assert f and "without being flushed" in f[0].message
    # an `if acks:` truthiness guard proves the unflushed path is empty
    assert "record-ack-leak" not in _rules_of(_scan(_CLEAN))


def test_ack_flush_in_finally_counts_on_every_path():
    src = """
    def drain(client, stream, group):
        entries = client.xreadgroup(group, "w", {stream: ">"})
        acks = []
        try:
            for eid, p in entries:
                acks.append(("XACK", stream, group, eid))
        finally:
            client.pipeline(acks)
    """
    assert "record-ack-leak" not in _rules_of(_scan(src))


# ----------------------------------------------------- lock-release-path

def test_lock_release_positive_and_negative():
    bad = """
    def submit(lock, jobs):
        lock.acquire()
        if not jobs:
            return 0
        n = len(jobs)
        lock.release()
        return n
    """
    good = """
    def submit(lock, jobs):
        lock.acquire()
        try:
            return len(jobs)
        finally:
            lock.release()
    """
    assert "lock-release-path" in _rules_of(_scan(bad))
    assert "lock-release-path" not in _rules_of(_scan(good))


def test_lock_release_tested_acquire_skipped():
    src = """
    def submit(lock, jobs):
        got = lock.acquire(timeout=1.0)
        if not got:
            return 0
        return len(jobs)
    """
    assert "lock-release-path" not in _rules_of(_scan(src))


def test_lock_release_exception_edge_counts():
    src = """
    def submit(lock, jobs):
        lock.acquire()
        payload = jobs.encode()
        lock.release()
        return payload
    """
    assert "lock-release-path" in _rules_of(_scan(src))


# --------------------------------------------------------- span-pairing

def test_span_pairing_positive_negative_and_carveout():
    bad = """
    def traced(tracer, batch):
        tracer.attach("s")
        if batch is None:
            return None
        out = list(batch)
        tracer.detach("s")
        return out
    """
    good = """
    def traced(tracer, batch):
        tracer.attach("s")
        try:
            return list(batch)
        finally:
            tracer.detach("s")
    """
    forever = """
    def install(tracer):
        tracer.attach("process-lifetime")
        return tracer
    """
    assert "span-pairing" in _rules_of(_scan(bad))
    assert "span-pairing" not in _rules_of(_scan(good))
    assert "span-pairing" not in _rules_of(_scan(forever))


# ----------------------------------------------------- tainted-host-sync

def test_taint_sync_positive_branch_and_negative():
    bad = """
    import jax
    import numpy as np

    def autoregress(params, seq, steps):
        step = jax.jit(seq)
        out = seq
        for _t in range(steps):
            out = step(params, out)
            host = np.asarray(out)
            if out:
                break
        return host
    """
    findings = [f for f in _scan(bad) if f.rule == "tainted-host-sync"]
    assert len(findings) == 2            # the asarray and the branch
    clean = """
    import jax
    import numpy as np

    def fenced(params, seq, steps):
        step = jax.jit(seq)
        out = seq
        for _t in range(steps):
            out = step(params, out)
        return np.asarray(out)
    """
    assert "tainted-host-sync" not in _rules_of(_scan(clean))


def test_taint_killed_by_reassignment():
    src = """
    import jax

    def gen(params, xs):
        step = jax.jit(xs)
        y = step(params, xs)
        y = 0
        total = 0
        for x in xs:
            total = total + float(y)
        return total
    """
    assert "tainted-host-sync" not in _rules_of(_scan(src))


def test_taint_fn_parameter_convention():
    src = """
    def accumulate(predict_fn, batches):
        total = 0.0
        for b in batches:
            y = predict_fn(b)
            total = total + float(y)
        return total
    """
    assert "tainted-host-sync" in _rules_of(_scan(src))
    # inference/ is in scope too (the decode loop lives there)
    assert "tainted-host-sync" in _rules_of(_scan(src, "inference/gen.py"))
    # ...but a cold package is not
    assert "tainted-host-sync" not in _rules_of(_scan(src, "automl/gen.py"))


# ------------------------------------- shape-dependent-branch-in-jit

def test_jit_branch_fixture_lines():
    path = os.path.join(FIXTURE, "bad_jit_branch.py")
    findings = [f for f in analyze_paths([path], root=REPO)
                if f.rule == "shape-dependent-branch-in-jit"]
    by_kind = {(f.line, "shape" in f.message) for f in findings}
    src = open(path).read().splitlines()
    shape_line = next(i for i, l in enumerate(src, 1)
                      if "x.shape[0] > 8" in l)
    value_line = next(i for i, l in enumerate(src, 1) if "limit > 0" in l)
    helper_line = next(i for i, l in enumerate(src, 1) if "eps > 0" in l)
    assert (shape_line, True) in by_kind
    assert (value_line, False) in by_kind
    assert (helper_line, False) in by_kind     # reached via call graph
    # static_argnums and `is None` negative controls stay quiet
    assert len(findings) == 3


# ---------------------------------------------------------- kv-page-leak

def test_kv_page_leak_early_return_and_guarded_handoff():
    leak = """
    def admit(pool, cache_cls, enc, need, budget):
        pages = pool.alloc_pages(need)
        if need > budget:
            return None
        return cache_cls(pool, pages)
    """
    clean = """
    def admit(pool, cache_cls, validate, enc, need):
        pages = pool.alloc_pages(need)
        try:
            validate(enc)
            cache = cache_cls(pool, pages)
        except Exception:
            pool.free_pages(pages)
            raise
        return cache
    """
    assert "kv-page-leak" in _rules_of(_scan(leak))
    assert "kv-page-leak" not in _rules_of(_scan(clean))


def test_kv_page_leak_counts_the_raise_exit():
    # unlike record-ack-leak (lease redelivery covers escaping
    # exceptions), stranded pages never rejoin the pool — an unprotected
    # call between the alloc and the handoff is itself a finding
    src = """
    def admit(pool, cache_cls, validate, enc, need):
        pages = pool.alloc_pages(need)
        validate(enc)
        return cache_cls(pool, pages)
    """
    f = [x for x in _scan(src) if x.rule == "kv-page-leak"]
    assert f and "without being freed or handed off" in f[0].message


def test_kv_page_leak_loop_settlement_forms():
    # free on one branch, handoff into a collection on the other — both
    # settle ownership, the per-iteration alloc is clean
    src = """
    def retire(pool, seqs):
        recycled = []
        for seq in seqs:
            pages = pool.alloc_pages(seq.need)
            if seq.short:
                pool.free_pages(pages)
            else:
                recycled.append(pages)
        return recycled
    """
    assert "kv-page-leak" not in _rules_of(_scan(src))


def test_kv_page_leak_fixture_lines():
    path = os.path.join(FIXTURE, "serving", "bad_kv_page_leak.py")
    findings = [f for f in analyze_paths([path], root=REPO)
                if f.rule == "kv-page-leak"]
    tree = ast.parse(open(path).read())
    expected = set()
    for fn in tree.body:
        if isinstance(fn, ast.FunctionDef) and fn.name.endswith("_leak"):
            expected.add(min(n.lineno for n in ast.walk(fn)
                             if isinstance(n, ast.Assign)))
    # exactly the two VIOLATION allocs; the clean shapes stay quiet
    assert {f.line for f in findings} == expected
    assert len(findings) == 2


def test_kv_page_leak_clean_on_real_scheduler():
    sched = os.path.join(REPO, "analytics_zoo_tpu", "inference",
                         "decode_scheduler.py")
    findings = [f for f in analyze_paths([sched, ENGINE], root=REPO)
                if f.rule == "kv-page-leak"]
    assert findings == []


# ------------------------------------------------------------ CFG cache

def test_cfg_cache_hits_and_rebuild():
    ctx, err = parse_file(ENGINE, REPO)
    assert err is None
    fn = next(n for n in ctx.walk()
              if isinstance(n, ast.FunctionDef) and n.name == "_produce")
    CFG_STATS["built"] = CFG_STATS["hits"] = 0
    g1 = ctx.cfg(fn)
    g2 = ctx.cfg(fn)
    assert g1 is g2
    assert CFG_STATS == {"built": 1, "hits": 1}
    # the cache key is the v2 normalized-statement hash, so two parses
    # of identical source agree on it
    ctx2, _ = parse_file(ENGINE, REPO)
    fn2 = next(n for n in ctx2.walk()
               if isinstance(n, ast.FunctionDef) and n.name == "_produce")
    assert ctx.func_hash(fn) == ctx2.func_hash(fn2)


# ------------------------------------------------ acceptance: engine demo

def test_hand_introduced_ack_drop_is_caught():
    """Delete the undecodable-record handler's ack (the PR 9/10 suites
    never exercise a corrupt record racing an exception there) and the
    path-sensitive rule must catch the exception-edge drop."""
    src = open(ENGINE, encoding="utf-8").read()
    lines = src.splitlines(keepends=True)
    idx = next(i for i, l in enumerate(lines)
               if "dropping undecodable record" in l)
    assert "term_acks.append(ack)" in lines[idx + 1]
    broken = "".join(lines[:idx + 1] + lines[idx + 2:])
    rel = "analytics_zoo_tpu/serving/engine.py"

    before = [f for f in analyze_source(src, rel)
              if f.rule == "record-ack-leak"]
    after = [f for f in analyze_source(broken, rel)
             if f.rule == "record-ack-leak"]
    new = {f.line for f in after} - {f.line for f in before}
    assert len(new) == 1                  # exactly the intake loop
    intake_line = max(i for i, l in enumerate(lines, 1)
                      if "for eid, lane, payload in entries:" in l
                      and i <= idx)
    assert new == {intake_line}


# -------------------------------------------------------------- CLI

@pytest.mark.slow
def test_cli_fixture_fails_and_jobs_agree():
    r1 = _cli("--no-baseline", "--format=json", "--jobs", "1",
              "tests/fixtures/zoolint")
    r4 = _cli("--no-baseline", "--format=json", "--jobs", "4",
              "tests/fixtures/zoolint")
    assert r1.returncode == 1 and r4.returncode == 1
    f1 = json.loads(r1.stdout)["findings"]
    f4 = json.loads(r4.stdout)["findings"]
    assert f1 == f4
    assert {"record-ack-leak", "lock-release-path", "span-pairing",
            "tainted-host-sync", "shape-dependent-branch-in-jit"} <= \
        {f["rule"] for f in f1}


@pytest.mark.slow
def test_cli_timing_prints_cfg_stats():
    r = _cli("--timing", "--no-baseline", "analytics_zoo_tpu/analysis")
    assert r.returncode in (0, 1)
    assert "CFGs built=" in r.stderr and "cache-hits=" in r.stderr


@pytest.mark.slow
def test_cli_prune_baseline_report_and_fix(tmp_path):
    (tmp_path / ".git").mkdir()
    mod = tmp_path / "mod.py"
    mod.write_text("X = 1\n")
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps({"version": 2, "entries": [
        {"fingerprint": "deadbeefdeadbeef", "rule": "wallclock-hotpath",
         "path": "mod.py", "line": 1, "message": "gone",
         "justification": "was justified once"}]}))
    r = _cli(str(mod), "--baseline", str(bl), "--prune-baseline")
    assert r.returncode == 0
    assert "deadbeefdeadbeef" in r.stdout and "stale" in r.stdout
    # report form does not touch the file
    assert len(json.loads(bl.read_text())["entries"]) == 1
    r = _cli(str(mod), "--baseline", str(bl), "--prune-baseline=fix")
    assert r.returncode == 0
    assert json.loads(bl.read_text())["entries"] == []
    # an out-of-scope entry is never judged by a partial scan
    bl.write_text(json.dumps({"version": 2, "entries": [
        {"fingerprint": "cafecafecafecafe", "rule": "wallclock-hotpath",
         "path": "elsewhere.py", "line": 1, "message": "gone",
         "justification": "x"}]}))
    r = _cli(str(mod), "--baseline", str(bl), "--prune-baseline=fix")
    assert r.returncode == 0
    assert len(json.loads(bl.read_text())["entries"]) == 1


def test_shipped_tree_has_no_new_rule_findings():
    """The five new rules are clean on the shipped tree modulo the two
    justified baseline entries (engine dedupe loop, decode feedback)."""
    findings = [f for f in analyze_paths(
        [os.path.join(REPO, "analytics_zoo_tpu")], root=REPO)
        if f.rule in ("record-ack-leak", "lock-release-path",
                      "span-pairing", "tainted-host-sync",
                      "shape-dependent-branch-in-jit")]
    where = {(f.rule, f.path) for f in findings}
    assert where == {
        ("record-ack-leak", "analytics_zoo_tpu/serving/engine.py"),
        ("tainted-host-sync", "analytics_zoo_tpu/inference/generation.py"),
    }
