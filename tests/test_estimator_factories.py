"""Estimator.from_keras / from_graph factory tests
(ref pyzoo/test/zoo/orca/learn/test_estimator_*)."""

import numpy as np
import pytest

from analytics_zoo_tpu.learn.estimator import Estimator


def _data():
    rng = np.random.RandomState(0)
    x = rng.randn(64, 4).astype(np.float32)
    y = (x.sum(1) > 0).astype(np.int32)
    return x, y


class TestFromKeras:
    def test_fit_predict(self, orca_ctx):
        from analytics_zoo_tpu.keras.models import Sequential
        from analytics_zoo_tpu.keras.layers import Dense

        m = Sequential()
        m.add(Dense(8, input_shape=(4,), activation="relu"))
        m.add(Dense(2, activation="softmax"))
        x, y = _data()
        est = Estimator.from_keras(
            keras_model=m, loss="sparse_categorical_crossentropy",
            optimizer="adam")
        h = est.fit((x, y), epochs=5, batch_size=16)
        assert h["loss"][-1] < h["loss"][0]
        assert np.asarray(est.predict(x, batch_size=16)).shape == (64, 2)

    def test_compiled_defaults_are_used(self, orca_ctx):
        from analytics_zoo_tpu.keras.models import Sequential
        from analytics_zoo_tpu.keras.layers import Dense
        from analytics_zoo_tpu.learn.optimizers import Optimizer

        m = Sequential()
        m.add(Dense(2, input_shape=(4,), activation="softmax"))
        m.compile(optimizer="sgd", loss="sparse_categorical_crossentropy")
        est = Estimator.from_keras(keras_model=m)
        # the compiled optimizer wins over the factory default
        assert type(est.optimizer) is type(Optimizer.get("sgd"))
        x, y = _data()
        est.fit((x, y), epochs=1, batch_size=16)

    def test_prior_strategy_is_kept(self, orca_ctx):
        from analytics_zoo_tpu.keras.models import Sequential
        from analytics_zoo_tpu.keras.layers import Dense

        m = Sequential()
        m.add(Dense(4, input_shape=(4,), activation="relu"))
        m.add(Dense(2, activation="softmax"))
        m.set_strategy("dp2,tp4",
                       param_rules=[(r"kernel", (None, "model"))])
        est = Estimator.from_keras(
            keras_model=m, loss="sparse_categorical_crossentropy")
        assert str(est.strategy) == "dp2,tp4"
        assert est.strategy.param_rules

    def test_rejects_non_keras(self):
        with pytest.raises(TypeError, match="zoo keras"):
            Estimator.from_keras(keras_model=object(), loss="mse")


class TestFromGraph:
    def test_symbolic_graph_trains(self, orca_ctx):
        from analytics_zoo_tpu.keras.engine import Input
        from analytics_zoo_tpu.keras.layers import Dense

        inp = Input(shape=(4,))
        out = Dense(2, activation="softmax")(Dense(8, activation="relu")(inp))
        x, y = _data()
        est = Estimator.from_graph(
            inputs=inp, outputs=out,
            loss="sparse_categorical_crossentropy")
        h = est.fit((x, y), epochs=5, batch_size=16)
        assert h["loss"][-1] < h["loss"][0]


class TestStrategyPreservesWeights:
    def test_set_strategy_keeps_params(self, orca_ctx):
        import numpy as np
        from analytics_zoo_tpu.keras.models import Sequential
        from analytics_zoo_tpu.keras.layers import Dense

        m = Sequential()
        m.add(Dense(8, input_shape=(4,), activation="relu"))
        m.add(Dense(2, activation="softmax"))
        m.compile(optimizer="adam", loss="sparse_categorical_crossentropy")
        x, y = _data()
        m.fit(x, y, batch_size=16, nb_epoch=2)
        before = np.asarray(m.predict(x, distributed=False))
        # re-strategize through the factory: weights must survive
        est = Estimator.from_keras(
            keras_model=m, loss="sparse_categorical_crossentropy",
            strategy="dp,tp2",
            param_rules=[(r"kernel", (None, "model"))])
        after = np.asarray(est.predict(x, batch_size=16))
        np.testing.assert_allclose(after, before, atol=1e-5)

    def test_strategy_only_keeps_rules(self, orca_ctx):
        from analytics_zoo_tpu.keras.models import Sequential
        from analytics_zoo_tpu.keras.layers import Dense

        m = Sequential()
        m.add(Dense(2, input_shape=(4,), activation="softmax"))
        m.set_strategy("dp", param_rules=[(r"kernel", (None, "model"))])
        m.set_strategy("dp2,tp2")  # no rules given → keep the old ones
        assert m._param_rules

    def test_missing_loss_raises(self, orca_ctx):
        from analytics_zoo_tpu.keras.models import Sequential
        from analytics_zoo_tpu.keras.layers import Dense
        m = Sequential()
        m.add(Dense(2, input_shape=(4,), activation="softmax"))
        with pytest.raises(ValueError, match="no loss"):
            Estimator.from_keras(keras_model=m)
