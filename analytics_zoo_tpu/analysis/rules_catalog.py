"""Catalog-drift rules — the code and docs/observability.md must agree.

docs/observability.md declares every ``zoo_*`` metric name **stable**
("tests and dashboards key on them") and documents the ``ZOO_*`` env
knobs. Drift in either direction is a real bug: an undocumented metric is
invisible to dashboard authors, a documented-but-unregistered metric is a
dashboard keyed on nothing. These are project-scope rules — they see
every scanned file at once — and the same check is exposed as a plain
pytest via :func:`catalog_drift` (tests/test_docs.py) so tier-1 catches
drift even without the zoolint lane.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

from analytics_zoo_tpu.analysis.core import (
    Finding, ProjectContext, Rule, analyze_paths, find_repo_root, register,
)

_REGISTRY_METHODS = frozenset({"counter", "gauge", "histogram"})
_METRIC_PREFIX = "zoo_"
_ENV_PREFIX = "ZOO_"

#: catalog table rows: ``| `zoo_name` | kind | ...``
_DOC_METRIC_ROW = re.compile(r"^\|\s*`(zoo_[a-z0-9_]+)`", re.M)
#: any backticked/bare mention counts as "documented"
_DOC_METRIC_ANY = re.compile(r"\b(zoo_[a-z0-9_]+)\b")
_DOC_ENV_ANY = re.compile(r"\b(ZOO_[A-Z0-9_]+)\b")


def _docs_path(root: Optional[str]) -> Optional[str]:
    if root is None:
        return None
    p = os.path.join(root, "docs", "observability.md")
    return p if os.path.isfile(p) else None


def _read_docs(root: Optional[str]) -> Optional[str]:
    p = _docs_path(root)
    if p is None:
        return None
    with open(p, "r", encoding="utf-8") as fh:
        return fh.read()


def _registered_metrics(pctx: ProjectContext) -> List[
        Tuple[str, str, int, int]]:
    """Every ``reg.counter/gauge/histogram("zoo_...")`` registration in
    the scanned files: (metric, path, line, col)."""
    out = []
    for ctx in pctx.files:
        for node in ctx.walk():
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _REGISTRY_METHODS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and node.args[0].value.startswith(_METRIC_PREFIX)):
                continue
            out.append((node.args[0].value, ctx.path,
                        node.lineno, node.col_offset))
    return out


def _env_reads(pctx: ProjectContext) -> List[Tuple[str, str, int, int]]:
    """Every ``ZOO_*`` env read: os.environ.get/[], os.getenv,
    environ.get — (var, path, line, col)."""
    out = []
    for ctx in pctx.files:
        for node in ctx.walk():
            var = None
            if isinstance(node, ast.Call):
                name = ctx.imports.resolve(node.func)
                tail = name.split(".")[-1] if name else ""
                if (name == "os.getenv"
                        or (tail == "get" and "environ" in name)) \
                        and node.args \
                        and isinstance(node.args[0], ast.Constant):
                    var = node.args[0].value
            elif isinstance(node, ast.Subscript):
                base = node.value
                if isinstance(base, ast.Attribute) \
                        and base.attr == "environ":
                    sl = node.slice
                    if isinstance(sl, ast.Constant):
                        var = sl.value
            if isinstance(var, str) and var.startswith(_ENV_PREFIX):
                out.append((var, ctx.path, node.lineno, node.col_offset))
    return out


def _scan_covers_package(pctx: ProjectContext) -> bool:
    """Doc→code drift only makes sense when the scan includes the WHOLE
    package tree — a fixture-only or subtree scan registers few/no
    metrics and would flag every documented one. Scanning the package
    root always pulls in its __init__.py, so that file is the witness."""
    return any(c.path == "analytics_zoo_tpu/__init__.py"
               for c in pctx.files)


@register
class MetricUndocumented(Rule):
    """A ``zoo_*`` metric registered in code but absent from the
    docs/observability.md catalog."""

    id = "metric-undocumented"
    scope = "project"
    description = "registered zoo_* metric missing from the docs catalog"

    def check_project(self, pctx: ProjectContext) -> Iterable[Finding]:
        docs = _read_docs(pctx.root)
        if docs is None:
            return
        documented = set(_DOC_METRIC_ANY.findall(docs))
        for metric, path, line, col in _registered_metrics(pctx):
            if metric not in documented:
                yield Finding(
                    self.id, path, line, col,
                    f"metric {metric!r} is registered here but missing "
                    "from docs/observability.md — add a catalog row "
                    "(metric names are a stable interface)")


@register
class MetricUndeclared(Rule):
    """A catalog row in docs/observability.md whose metric no scanned
    code registers — a dashboard keyed on nothing."""

    id = "metric-undeclared"
    scope = "project"
    description = "docs catalog row with no registration in code"

    def check_project(self, pctx: ProjectContext) -> Iterable[Finding]:
        docs = _read_docs(pctx.root)
        if docs is None or not _scan_covers_package(pctx):
            return
        registered = {m for m, *_ in _registered_metrics(pctx)}
        doc_rel = "docs/observability.md"
        for m in _DOC_METRIC_ROW.finditer(docs):
            metric = m.group(1)
            if metric not in registered:
                line = docs.count("\n", 0, m.start()) + 1
                yield Finding(
                    self.id, doc_rel, line, 0,
                    f"catalog documents {metric!r} but nothing in the "
                    "scanned tree registers it — remove the row or "
                    "restore the metric")


@register
class EnvvarUndocumented(Rule):
    """A ``ZOO_*`` env var read in code but never mentioned in
    docs/observability.md."""

    id = "envvar-undocumented"
    scope = "project"
    description = "ZOO_* env var read but undocumented"

    def check_project(self, pctx: ProjectContext) -> Iterable[Finding]:
        docs = _read_docs(pctx.root)
        if docs is None:
            return
        documented = set(_DOC_ENV_ANY.findall(docs))
        for var, path, line, col in _env_reads(pctx):
            if var not in documented:
                yield Finding(
                    self.id, path, line, col,
                    f"env var {var!r} is read here but undocumented — "
                    "mention it in docs/observability.md")


def catalog_drift(root: Optional[str] = None) -> List[Finding]:
    """The catalog checks as a plain function: scan the repo's
    ``analytics_zoo_tpu`` package with only the three catalog rules.
    tests/test_docs.py asserts this returns [] so tier-1 fails on drift
    even when the zoolint lane is skipped."""
    if root is None:
        root = find_repo_root(os.path.dirname(os.path.abspath(__file__)))
    if root is None:
        raise RuntimeError("repo root not found")
    rules = {r.id: r for r in (
        MetricUndocumented(), MetricUndeclared(), EnvvarUndocumented())}
    return analyze_paths([os.path.join(root, "analytics_zoo_tpu")],
                         rules=rules, root=root)
