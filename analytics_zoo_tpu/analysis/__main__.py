import sys

from analytics_zoo_tpu.analysis.cli import main

try:
    rc = main()
    sys.stdout.flush()
except BrokenPipeError:
    # reader went away (e.g. `... | head`) — not a lint failure
    rc = 0
sys.exit(rc)
