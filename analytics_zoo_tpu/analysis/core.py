"""zoolint core — per-file AST rule engine with inline suppressions.

The invariants the last three PRs rest on (no wall-clock in hot paths, no
implicit host syncs inside dispatch loops, no per-call jit construction,
locked engine shared state, a docs catalog that matches the registry) were
enforced by code review plus one brittle grep. This package turns them
into first-class static analysis: every rule is an AST visitor with a
stable id, findings carry ``path:line:col``, and any finding can be
silenced in place (``# zoolint: disable=RULE``) or grandfathered in the
committed baseline (see baseline.py) — so the clean-tree invariant is
``exit 0`` in CI, not tribal knowledge.

Two rule scopes:

- **file** rules see one parsed module at a time (``check_file``);
- **project** rules see every scanned file at once plus the repo root
  (``check_project``) — the catalog-drift checks that compare code
  against docs/observability.md live there.
"""

from __future__ import annotations

import ast
import hashlib
import os
import re
from collections import deque
from dataclasses import dataclass, field
from typing import (Any, Callable, Dict, Iterable, List, Optional, Sequence,
                    Set, Tuple)

#: path segments whose files count as hot-path (the serve/dispatch/train
#: inner loops) — hot-path-only rules look at these trees exclusively
HOT_PATH_SEGMENTS = frozenset({"serving", "common", "learn"})

_DISABLE_LINE = re.compile(
    r"#\s*zoolint:\s*disable(?:=(?P<rules>[\w,\- ]+))?")
_DISABLE_FILE = re.compile(
    r"#\s*zoolint:\s*disable-file=(?P<rules>[\w,\- ]+)")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location. ``path`` is repo-relative
    posix so findings (and baseline fingerprints) are machine-portable."""

    rule: str
    path: str
    line: int
    col: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: " \
               f"{self.message}"


class _ParentAnnotator(ast.NodeVisitor):
    """Stamp ``_zl_parent`` on every node — rules walk ancestor chains
    (enclosing loop / function / ``with`` / ``if``) constantly."""

    def generic_visit(self, node):
        for child in ast.iter_child_nodes(node):
            child._zl_parent = node  # type: ignore[attr-defined]
        super().generic_visit(node)


def ancestors(node: ast.AST) -> Iterable[ast.AST]:
    cur = getattr(node, "_zl_parent", None)
    while cur is not None:
        yield cur
        cur = getattr(cur, "_zl_parent", None)


class ImportMap:
    """Local name -> qualified dotted name, from a module's imports.

    ``resolve(call.func)`` turns an AST callee into its dotted origin
    (``np.asarray`` -> ``numpy.asarray``, bare ``jit`` after ``from jax
    import jit`` -> ``jax.jit``) so rules match on canonical names, not on
    whatever alias a file picked."""

    def __init__(self, tree: ast.AST):
        self.names: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.names[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    self.names[a.asname or a.name] = \
                        f"{node.module}.{a.name}"

    def resolve(self, func: ast.AST) -> str:
        """Dotted name of a callee ('' when it isn't a plain name chain)."""
        parts: List[str] = []
        cur = func
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return ""
        root = self.names.get(cur.id, cur.id)
        return ".".join([root] + list(reversed(parts)))


@dataclass
class FileContext:
    """Everything a file rule sees: parsed AST (parent-annotated), source
    lines, repo-relative path, import resolution, and hot-path flag."""

    path: str                    # repo-relative, posix separators
    source: str
    tree: ast.AST
    lines: List[str] = field(default_factory=list)
    imports: ImportMap = None  # type: ignore[assignment]
    _order: Optional[List[ast.AST]] = None
    _span: Optional[Dict[int, Tuple[int, int]]] = None
    _cfg_cache: Optional[Dict[int, Tuple[str, "CFG"]]] = None

    def __post_init__(self):
        self.lines = self.source.splitlines()
        if self.imports is None:
            self.imports = ImportMap(self.tree)

    def _index(self):
        """DFS pre-order of every node plus each node's subtree extent —
        built once, so repeated tree walks (model build + every file
        rule) are list iterations, not fresh ast.walk() traversals."""
        order: List[ast.AST] = []
        span: Dict[int, Tuple[int, int]] = {}
        stack: List[Tuple[ast.AST, bool]] = [(self.tree, False)]
        while stack:
            node, done = stack.pop()
            if done:
                start = span[id(node)][0]
                span[id(node)] = (start, len(order))
                continue
            span[id(node)] = (len(order), 0)
            order.append(node)
            stack.append((node, True))
            for child in reversed(list(ast.iter_child_nodes(node))):
                stack.append((child, False))
        self._order, self._span = order, span

    def walk(self, node: Optional[ast.AST] = None) -> List[ast.AST]:
        """All nodes under ``node`` (default: the whole module), node
        itself first. Equivalent node set to ``ast.walk`` (pre-order
        rather than breadth-first), served from the cached index."""
        if self._order is None:
            self._index()
        if node is None or node is self.tree:
            return self._order
        ext = self._span.get(id(node))
        if ext is None:                # node not from this tree
            return list(ast.walk(node))
        return self._order[ext[0]:ext[1]]

    @property
    def is_hot_path(self) -> bool:
        return bool(HOT_PATH_SEGMENTS
                    & set(self.path.split("/")[:-1]))

    def line_text(self, line: int) -> str:
        return self.lines[line - 1] if 0 < line <= len(self.lines) else ""

    def func_hash(self, func: ast.AST) -> str:
        """v2 normalized-statement hash of a function's source extent —
        the CFG cache validator. Same normalization as the baseline v2
        fingerprints (comments stripped, whitespace collapsed), so a
        comment/formatting edit does not invalidate a cached CFG."""
        from analytics_zoo_tpu.analysis import baseline as _baseline
        lo = getattr(func, "lineno", 1)
        hi = getattr(func, "end_lineno", lo) or lo
        parts = []
        for ln in range(lo, hi + 1):
            text = " ".join(_baseline._strip_comment(
                self.line_text(ln)).split())
            if text:
                parts.append(text)
        digest = hashlib.sha256("\n".join(parts).encode("utf-8"))
        return digest.hexdigest()[:16]

    def cfg(self, func: ast.AST) -> "CFG":
        """The control-flow graph of ``func``, memoized per file and
        keyed by the v2 normalized-statement hash: every path-sensitive
        rule scanning this file shares one build per function body."""
        if self._cfg_cache is None:
            self._cfg_cache = {}
        fhash = self.func_hash(func)
        hit = self._cfg_cache.get(id(func))
        if hit is not None and hit[0] == fhash:
            CFG_STATS["hits"] += 1
            return hit[1]
        CFG_STATS["built"] += 1
        graph = CFG(func)
        self._cfg_cache[id(func)] = (fhash, graph)
        return graph


@dataclass
class ProjectContext:
    """What project rules see: every FileContext plus the repo root (for
    docs/ lookups). ``root`` may be None when no repo root was found —
    root-dependent rules then skip themselves."""

    files: List[FileContext]
    root: Optional[str]
    _model: Optional["ProjectModel"] = None

    def model(self) -> "ProjectModel":
        """The whole-program model (symbol table, call graph, thread
        roots, lock discipline) — built once per scan, shared by every
        interprocedural rule and the ownership report."""
        if self._model is None:
            self._model = ProjectModel(self.files)
        return self._model


class Rule:
    """Base rule. Subclasses set ``id`` (the stable suppression/baseline
    key), ``scope`` ('file' | 'project'), and override the matching
    ``check_*``. Rule ids are kebab-case and documented in
    docs/zoolint.md."""

    id: str = ""
    scope: str = "file"
    description: str = ""

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def check_project(self, pctx: ProjectContext) -> Iterable[Finding]:
        return ()


_RULES: "Dict[str, Rule]" = {}


def register(rule_cls):
    """Class decorator: instantiate and add to the global rule registry
    (import-time, like pytest plugins — rules_*.py modules just need to
    be imported)."""
    rule = rule_cls()
    if not rule.id:
        raise ValueError(f"{rule_cls.__name__} has no id")
    if rule.id in _RULES:
        raise ValueError(f"duplicate rule id {rule.id!r}")
    _RULES[rule.id] = rule
    return rule_cls


def all_rules() -> Dict[str, Rule]:
    from analytics_zoo_tpu.analysis import (  # noqa: F401
        rules_catalog, rules_compile, rules_concurrency, rules_dataplane,
        rules_hotpath, rules_jit, rules_lifecycle, rules_locks,
        rules_ownership, rules_taint,
    )
    return dict(_RULES)


# ------------------------------------------------------------ suppressions

def _parse_rule_list(raw: Optional[str]) -> Optional[frozenset]:
    """None = bare disable (all rules)."""
    if raw is None:
        return None
    return frozenset(r.strip() for r in raw.split(",") if r.strip())


def suppressed(ctx: FileContext, finding: Finding) -> bool:
    """True when the finding's source line carries ``# zoolint: disable``
    (bare = everything, ``=a,b`` = those rules) or the file carries a
    matching ``# zoolint: disable-file=a,b`` anywhere."""
    m = _DISABLE_LINE.search(ctx.line_text(finding.line))
    if m:
        rules = _parse_rule_list(m.group("rules"))
        if rules is None or finding.rule in rules:
            return True
    for line in ctx.lines:
        fm = _DISABLE_FILE.search(line)
        if fm and finding.rule in _parse_rule_list(fm.group("rules")):
            return True
    return False


# ------------------------------------------------- control-flow graphs
#
# Per-function CFGs power the path-sensitive rule families
# (rules_lifecycle, rules_taint). One statement per block keeps exception
# edges precise: a statement that may raise mid-block would otherwise
# leak the block-exit fact onto the handler edge. Synthetic (stmt=None)
# blocks mark structure: entry/exit/raise, branch joins, loop exits,
# finally copies, with-exit.

#: built/hit counters for the shared per-file CFG cache — reset by the
#: CLI per scan, printed by ``--timing`` and the zoolint CI lane.
CFG_STATS: Dict[str, int] = {"built": 0, "hits": 0}


class CFGBlock:
    """One CFG node. ``stmt`` holds at most one AST statement (None for
    synthetic blocks); ``label`` says what the block *means* — for
    ``branch``/``loop-head`` blocks the semantics cover only the test /
    iterator of the carried If/While/For node, never its body."""

    __slots__ = ("idx", "stmt", "label", "succs", "preds")

    def __init__(self, idx: int, stmt: Optional[ast.AST], label: str):
        self.idx = idx
        self.stmt = stmt
        self.label = label
        self.succs: List[Tuple[int, str]] = []   # (block idx, edge kind)
        self.preds: List[Tuple[int, str]] = []

    def __repr__(self):  # pragma: no cover - debugging aid
        at = getattr(self.stmt, "lineno", "-")
        return f"<B{self.idx} {self.label} L{at}>"


class CFG:
    """Control-flow graph of one function body.

    Edge kinds: ``normal`` (fallthrough), ``true``/``false`` (branch and
    loop test outcomes), ``back`` (loop back-edge), ``break``,
    ``continue``, ``return``, ``exc`` (exception edge). Exception edges
    are *optimistic by construction*: only statements that contain a
    call, an ``assert``, or a ``raise`` get them, routed through the
    enclosing handler/finally chain (``finally`` bodies are built twice —
    a shared normal copy and a shared exceptional copy — plus fresh
    inline copies for each abrupt ``return``/``break``/``continue`` that
    crosses them). Analyses that want pessimism simply include the
    ``raise`` exit in their checked exits; optimistic ones ignore it."""

    def __init__(self, func: ast.AST):
        self.func = func
        self.blocks: List[CFGBlock] = []
        self.entry = 0
        self.exit = 0
        self.raise_exit = 0
        self._stmt_blocks: Dict[int, List[int]] = {}
        _CFGBuilder(self).build(func)

    def block(self, idx: int) -> CFGBlock:
        return self.blocks[idx]

    def blocks_of(self, stmt: ast.AST) -> List[int]:
        """Every block carrying ``stmt`` — 2+ for finally-body and
        abrupt-exit duplication, else 0 or 1."""
        return list(self._stmt_blocks.get(id(stmt), ()))

    def edge_kinds(self) -> Set[str]:
        return {k for b in self.blocks for _, k in b.succs}


def _has_call(node: Optional[ast.AST]) -> bool:
    if node is None:
        return False
    return any(isinstance(n, ast.Call) for n in ast.walk(node))


_NO_RAISE_STMTS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                   ast.Pass, ast.Global, ast.Nonlocal, ast.Break,
                   ast.Continue, ast.Import, ast.ImportFrom)


def _may_raise(stmt: ast.AST) -> bool:
    if isinstance(stmt, (ast.Raise, ast.Assert)):
        return True
    if isinstance(stmt, _NO_RAISE_STMTS):
        return False
    return _has_call(stmt)


class _TryFrame:
    __slots__ = ("handler_entries", "catch_all", "fin_exc_entry")

    def __init__(self, handler_entries, catch_all, fin_exc_entry):
        self.handler_entries = handler_entries
        self.catch_all = catch_all
        self.fin_exc_entry = fin_exc_entry


def _is_catch_all(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
    for n in names:
        tail = n.attr if isinstance(n, ast.Attribute) else \
            n.id if isinstance(n, ast.Name) else ""
        if tail in ("Exception", "BaseException"):
            return True
    return False


class _CFGBuilder:
    def __init__(self, cfg: CFG):
        self.cfg = cfg
        self.frames: List[_TryFrame] = []       # enclosing try frames
        self.fin_pending: List[list] = []       # finalbodys abrupt exits cross
        self.loops: List[Tuple[int, int, int]] = []  # (head, after, fin depth)

    # -------------------------------------------------------- plumbing
    def _new(self, stmt: Optional[ast.AST], label: str) -> int:
        b = CFGBlock(len(self.cfg.blocks), stmt, label)
        self.cfg.blocks.append(b)
        if stmt is not None:
            self.cfg._stmt_blocks.setdefault(id(stmt), []).append(b.idx)
        return b.idx

    def _edge(self, src: Optional[int], dst: int, kind: str):
        if src is None:
            return
        self.cfg.blocks[src].succs.append((dst, kind))
        self.cfg.blocks[dst].preds.append((src, kind))

    def _exc_edges(self, b: int, frames: Optional[List[_TryFrame]] = None):
        """Route an exception raised at block ``b`` through the handler/
        finally chain: innermost handlers first; a catch-all stops the
        walk; a finally (exceptional copy) absorbs the escape — its tail
        continues outward with the frames outside it."""
        frames = self.frames if frames is None else frames
        for fr in reversed(frames):
            for h in fr.handler_entries:
                self._edge(b, h, "exc")
            if fr.catch_all:
                return
            if fr.fin_exc_entry is not None:
                self._edge(b, fr.fin_exc_entry, "exc")
                return
        self._edge(b, self.cfg.raise_exit, "exc")

    def _inline_finallys(self, cur: int, upto: int) -> int:
        """Fresh copies of every pending finally body from innermost down
        to depth ``upto`` — the path a return/break/continue actually
        executes on its way out. Each copy is built with only the
        *outer* finallys pending, so a return inside a finally body
        inlines outward instead of recursing into itself."""
        saved = self.fin_pending
        idx = len(saved)
        while idx > upto and cur is not None:
            idx -= 1
            self.fin_pending = saved[:idx]
            cur = self._seq(saved[idx], cur, "normal")
        self.fin_pending = saved
        return cur

    # ------------------------------------------------------- dispatch
    def build(self, func: ast.AST):
        self.cfg.entry = self._new(None, "entry")
        self.cfg.exit = self._new(None, "exit")
        self.cfg.raise_exit = self._new(None, "raise")
        cur = self._seq(getattr(func, "body", []), self.cfg.entry, "normal")
        self._edge(cur, self.cfg.exit, "normal")

    def _seq(self, stmts, cur: Optional[int], kind: str) -> Optional[int]:
        first = True
        for s in stmts:
            if cur is None:                 # unreachable tail: still built
                cur = self._new(None, "unreachable")
                first = False
            cur = self._stmt(s, cur, kind if first else "normal")
            first = False
        return cur

    def _stmt(self, node, cur, kind) -> Optional[int]:
        if isinstance(node, ast.If):
            return self._branch(node, cur, kind)
        if isinstance(node, (ast.While, ast.For, ast.AsyncFor)):
            return self._loop(node, cur, kind)
        if isinstance(node, ast.Try):
            return self._try(node, cur, kind)
        if isinstance(node, (ast.With, ast.AsyncWith)):
            return self._with(node, cur, kind)
        if isinstance(node, ast.Match):
            return self._match(node, cur, kind)
        b = self._new(node, type(node).__name__.lower())
        self._edge(cur, b, kind)
        if isinstance(node, ast.Return):
            if _may_raise(node):
                self._exc_edges(b)
            end = self._inline_finallys(b, 0)
            self._edge(end, self.cfg.exit, "return")
            return None
        if isinstance(node, ast.Raise):
            self._exc_edges(b)
            return None
        if isinstance(node, (ast.Break, ast.Continue)):
            if self.loops:
                head, after, depth = self.loops[-1]
                end = self._inline_finallys(b, depth)
                if isinstance(node, ast.Break):
                    self._edge(end, after, "break")
                else:
                    self._edge(end, head, "continue")
            return None
        if _may_raise(node):
            self._exc_edges(b)
        return b

    def _branch(self, node: ast.If, cur, kind) -> Optional[int]:
        b = self._new(node, "branch")
        self._edge(cur, b, kind)
        if _has_call(node.test):
            self._exc_edges(b)
        join = self._new(None, "join")
        tcur = self._seq(node.body, b, "true")
        self._edge(tcur, join, "normal")
        if node.orelse:
            ecur = self._seq(node.orelse, b, "false")
            self._edge(ecur, join, "normal")
        else:
            self._edge(b, join, "false")
        return join if self.cfg.blocks[join].preds else None

    def _loop(self, node, cur, kind) -> int:
        head = self._new(node, "loop-head")
        self._edge(cur, head, kind)
        test = node.test if isinstance(node, ast.While) else node.iter
        if _has_call(test):
            self._exc_edges(head)
        after = self._new(None, "loop-exit")
        self.loops.append((head, after, len(self.fin_pending)))
        bcur = self._seq(node.body, head, "true")
        self._edge(bcur, head, "back")
        self.loops.pop()
        if node.orelse:     # runs on normal exhaustion only (no break)
            ocur = self._seq(node.orelse, head, "false")
            self._edge(ocur, after, "normal")
        else:
            self._edge(head, after, "false")
        return after

    def _with(self, node, cur, kind) -> Optional[int]:
        b = self._new(node, "with")     # context exprs + __enter__
        self._edge(cur, b, kind)
        self._exc_edges(b)
        wcur = self._seq(node.body, b, "normal")
        if wcur is None:
            return None
        wx = self._new(None, "with-exit")   # __exit__ on the normal path
        self._edge(wcur, wx, "normal")
        return wx

    def _match(self, node: ast.Match, cur, kind) -> Optional[int]:
        head = self._new(node, "branch")
        self._edge(cur, head, kind)
        if _has_call(node.subject):
            self._exc_edges(head)
        join = self._new(None, "join")
        for case in node.cases:
            ccur = self._seq(case.body, head, "true")
            self._edge(ccur, join, "normal")
        self._edge(head, join, "false")     # no case matched
        return join

    def _try(self, node: ast.Try, cur, kind) -> Optional[int]:
        after = self._new(None, "join")
        handler_entries = [self._new(h, "except") for h in node.handlers]
        catch_all = any(_is_catch_all(h) for h in node.handlers)
        fin_exc = self._new(None, "finally-exc") if node.finalbody else None
        outer_frames = list(self.frames)

        # body + orelse raise into THIS frame's handlers/finally
        self.frames.append(_TryFrame(handler_entries, catch_all, fin_exc))
        if node.finalbody:
            self.fin_pending.append(node.finalbody)
        bcur = self._seq(node.body, cur, kind)
        if node.orelse and bcur is not None:
            bcur = self._seq(node.orelse, bcur, "normal")
        self.frames.pop()

        # handler bodies: an exception inside a handler escapes outward,
        # but still runs this try's finally on the way
        self.frames.append(_TryFrame([], False, fin_exc))
        hends = []
        for hb in handler_entries:
            hends.append(self._seq(self.cfg.blocks[hb].stmt.body,
                                   hb, "normal"))
        self.frames.pop()
        if node.finalbody:
            self.fin_pending.pop()

        if node.finalbody:
            # shared normal copy: body/orelse + handler completions
            fin_n = self._new(None, "finally")
            for e in [bcur] + hends:
                self._edge(e, fin_n, "normal")
            fcur = self._seq(node.finalbody, fin_n, "normal")
            self._edge(fcur, after, "normal")
            # shared exceptional copy: tail re-raises outward
            fe_cur = self._seq(node.finalbody, fin_exc, "normal")
            if fe_cur is not None:
                self._exc_edges(fe_cur, outer_frames)
        else:
            for e in [bcur] + hends:
                self._edge(e, after, "normal")
        return after if self.cfg.blocks[after].preds else None


def dataflow(cfg: CFG, transfer: Callable[[CFGBlock, Any], Any], *,
             init: Any, bottom: Any, join: Callable[[Any, Any], Any],
             backward: bool = False,
             edge_fn: Optional[Callable[[CFGBlock, str, Any], Any]] = None,
             ) -> Dict[int, Any]:
    """Generic worklist gen/kill solve over a CFG.

    Returns the fixpoint fact per block at its *entry* (forward) or
    *exit* (backward). ``transfer(block, fact)`` crosses the block in
    the analysis direction; ``edge_fn(src_block, kind, fact)`` may
    refine the fact per outgoing edge kind (``None`` = edge contributes
    nothing) — ``src_block`` is always the edge's source in CFG
    direction, i.e. the branch that owns the ``true``/``false`` kind.
    Facts must support ``==``; ``join`` must be monotone.

    Blocks carry one statement, so in forward mode an ``exc`` edge
    propagates the block's *entry* fact: a statement that raises did not
    complete its effect (an ``append`` that blew up appended nothing)."""
    facts: Dict[int, Any] = {b.idx: bottom for b in cfg.blocks}
    if backward:
        for s in (cfg.exit, cfg.raise_exit):
            facts[s] = init
    else:
        facts[cfg.entry] = init
    work = deque(range(len(cfg.blocks)))
    guard = 0
    limit = 64 * len(cfg.blocks) + 256
    while work and guard < limit:
        guard += 1
        i = work.popleft()
        crossed = transfer(cfg.blocks[i], facts[i])
        edges = cfg.blocks[i].preds if backward else cfg.blocks[i].succs
        for j, kind in edges:
            src = cfg.blocks[j] if backward else cfg.blocks[i]
            base = facts[i] if (kind == "exc" and not backward) else crossed
            f = base if edge_fn is None else edge_fn(src, kind, base)
            if f is None:
                continue
            merged = join(facts[j], f)
            if merged != facts[j]:
                facts[j] = merged
                work.append(j)
    return facts


# ------------------------------------------------------------------ engine

def find_repo_root(start: str) -> Optional[str]:
    """Walk up from ``start`` to the checkout root (the dir holding
    pyproject.toml / .git / docs/observability.md) — anchors the baseline
    path and the catalog rules' docs lookup."""
    cur = os.path.abspath(start)
    if os.path.isfile(cur):
        cur = os.path.dirname(cur)
    while True:
        if (os.path.exists(os.path.join(cur, "pyproject.toml"))
                or os.path.isdir(os.path.join(cur, ".git"))
                or os.path.isfile(
                    os.path.join(cur, "docs", "observability.md"))):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return None
        cur = parent


def relpath(path: str, root: Optional[str]) -> str:
    """Repo-relative posix path — the form Finding.path and baseline
    entries use."""
    ap = os.path.abspath(path)
    if root and ap.startswith(os.path.abspath(root) + os.sep):
        ap = os.path.relpath(ap, root)
    return ap.replace(os.sep, "/")


_relpath = relpath


def parse_file(path: str, root: Optional[str]) -> Tuple[
        Optional[FileContext], Optional[Finding]]:
    """Parse one file into a FileContext, or a ``syntax-error`` finding —
    an unparseable file must fail the lint loudly, not crash the linter."""
    with open(path, "r", encoding="utf-8", errors="replace") as fh:
        source = fh.read()
    rel = _relpath(path, root)
    try:
        tree = ast.parse(source, filename=rel)
    except SyntaxError as e:
        return None, Finding("syntax-error", rel, e.lineno or 1,
                             (e.offset or 1) - 1,
                             f"file does not parse: {e.msg}")
    _ParentAnnotator().visit(tree)
    return FileContext(path=rel, source=source, tree=tree), None


def analyze_source(source: str, relpath: str,
                   rules: Optional[Sequence[Rule]] = None,
                   root: Optional[str] = None) -> List[Finding]:
    """Run file-scope rules over in-memory source — the unit-test entry
    point (project rules need a tree on disk; see ``analyze_paths``)."""
    try:
        tree = ast.parse(source, filename=relpath)
    except SyntaxError as e:
        return [Finding("syntax-error", relpath, e.lineno or 1,
                        (e.offset or 1) - 1,
                        f"file does not parse: {e.msg}")]
    _ParentAnnotator().visit(tree)
    ctx = FileContext(path=relpath.replace(os.sep, "/"), source=source,
                      tree=tree)
    use = [r for r in (rules if rules is not None
                       else all_rules().values()) if r.scope == "file"]
    out: List[Finding] = []
    for rule in use:
        for f in rule.check_file(ctx):
            if not suppressed(ctx, f):
                out.append(f)
    return sorted(out, key=lambda f: (f.path, f.line, f.col, f.rule))


def iter_python_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git",
                                            "build", ".eggs")]
                out.extend(os.path.join(dirpath, f)
                           for f in sorted(filenames) if f.endswith(".py"))
    return sorted(set(out))


def analyze_paths(paths: Sequence[str],
                  rules: Optional[Dict[str, Rule]] = None,
                  root: Optional[str] = None,
                  jobs: int = 1) -> List[Finding]:
    """Scan files/dirs with every registered rule (file + project scope),
    inline suppressions applied. Baseline filtering is the CLI's job —
    library callers (the pytest catalog cross-check) see raw findings.
    ``jobs`` > 1 parses files on a thread pool (output is identical —
    findings are sorted, and rules run after every parse lands)."""
    rules = rules if rules is not None else all_rules()
    if root is None and paths:
        root = find_repo_root(paths[0])
    files = iter_python_files(paths)
    if jobs and jobs > 1 and len(files) > 1:
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=jobs) as ex:
            parsed = list(ex.map(lambda p: parse_file(p, root), files))
    else:
        parsed = [parse_file(p, root) for p in files]
    contexts: List[FileContext] = []
    findings: List[Finding] = []
    for ctx, err in parsed:
        if err is not None:
            findings.append(err)
            continue
        contexts.append(ctx)
        for rule in rules.values():
            if rule.scope != "file":
                continue
            for f in rule.check_file(ctx):
                if not suppressed(ctx, f):
                    findings.append(f)
    pctx = ProjectContext(files=contexts, root=root)
    by_path = {c.path: c for c in contexts}
    for rule in rules.values():
        if rule.scope != "project":
            continue
        for f in rule.check_project(pctx):
            ctx = by_path.get(f.path)
            if ctx is None or not suppressed(ctx, f):
                findings.append(f)
    return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))


# ===================================================== whole-program model
#
# Everything below this line is the interprocedural half of zoolint: a
# project-wide symbol table + call graph, thread-root inference, a
# "runs-on" propagation pass, and lock/state bookkeeping. The four
# cross-file concurrency rules (rules_ownership.py, rules_locks.py) and
# the --ownership-report artifact (ownership.py) consume this model; the
# per-file rules never touch it.

_FUNC_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)

#: attribute/variable names that denote a synchronization object — same
#: heuristic the per-file concurrency rules use
_LOCKISH_NAMES = ("lock", "cv", "cond", "mutex", "sem")

#: types whose instances are internally synchronized — method calls on
#: them are not shared-state touches
THREAD_SAFE_TYPES = frozenset({
    "threading.Lock", "threading.RLock", "threading.Event",
    "threading.Condition", "threading.Semaphore",
    "threading.BoundedSemaphore", "threading.Barrier", "threading.local",
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue",
    "concurrent.futures.ThreadPoolExecutor",
    "concurrent.futures.ProcessPoolExecutor",
    "concurrent.futures.thread.ThreadPoolExecutor",
})

#: container methods that mutate their receiver — ``self._q.append(x)``
#: is a *write* to ``_q`` for ownership purposes
MUTATOR_METHODS = frozenset({
    "append", "appendleft", "extend", "extendleft", "add", "insert",
    "remove", "discard", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "sort", "reverse",
})

#: construction-time methods — writes here are pre-publication, not races
_INIT_METHODS = frozenset({"__init__", "__new__", "__post_init__",
                           "__init_subclass__", "__set_name__"})

#: stdlib request-handler bases: every do_*/handle method on a subclass
#: is invoked by the (threading) server on its own thread
_HANDLER_BASES = ("BaseHTTPRequestHandler", "SimpleHTTPRequestHandler",
                  "StreamRequestHandler", "DatagramRequestHandler",
                  "BaseRequestHandler")

#: class docstring markers that declare thread-confinement by contract
#: ("Not thread-safe: one pipeline belongs to one producer thread") —
#: the JVM @NotThreadSafe equivalent. Instances are single-owner, so the
#: cross-thread rule does not flag their attributes; the ownership report
#: lists the class as confined-by-contract instead.
CONFINEMENT_MARKERS = ("not thread-safe", "not threadsafe",
                       "thread-confined", "single-threaded",
                       "thread-compatible")

#: method names too generic for the unique-name fallback resolution —
#: resolving ``d.get(...)`` to the one project class defining ``get``
#: would wire dict lookups into the call graph
_GENERIC_METHODS = frozenset({
    "get", "set", "put", "pop", "items", "keys", "values", "update",
    "append", "extend", "add", "remove", "clear", "copy", "join",
    "start", "run", "stop", "close", "read", "write", "open", "send",
    "recv", "result", "submit", "wait", "acquire", "release", "format",
    "strip", "split", "encode", "decode", "sort", "index", "count",
    "insert", "next", "flush", "seek", "tell", "info", "debug",
    "warning", "error", "exception", "observe", "inc", "dec", "labels",
    "record", "item", "mean", "sum", "min", "max", "reshape", "astype",
    "tolist", "numpy", "map", "filter", "reduce", "merge", "head",
    "apply", "groupby", "name", "all", "any", "size", "fields", "done",
    "cancel", "shutdown", "to_dict", "save", "load", "reset", "build",
    "call", "first",
})


def module_name(path: str) -> str:
    """Dotted module name from a repo-relative posix path."""
    mod = path[:-3] if path.endswith(".py") else path
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod.lstrip(".")


def _lockish_name(name: str) -> bool:
    low = name.lower()
    return any(t in low for t in _LOCKISH_NAMES)


def _is_lockish_expr(expr: ast.AST) -> bool:
    if isinstance(expr, ast.Attribute):
        return _lockish_name(expr.attr)
    if isinstance(expr, ast.Name):
        return _lockish_name(expr.id)
    return False


def _qualpath(node: ast.AST) -> str:
    parts = [node.name]  # type: ignore[attr-defined]
    for a in ancestors(node):
        if isinstance(a, _FUNC_DEFS + (ast.ClassDef,)):
            parts.append(a.name)
    return ".".join(reversed(parts))


def _owner_defs(node: ast.AST):
    """(nearest enclosing function def, nearest enclosing class def)."""
    fn = cl = None
    for a in ancestors(node):
        if fn is None and isinstance(a, _FUNC_DEFS):
            fn = a
        if cl is None and isinstance(a, ast.ClassDef):
            cl = a
        if fn is not None and cl is not None:
            break
    return fn, cl


def _const_kwarg(call: ast.Call, name: str):
    for kw in call.keywords:
        if kw.arg == name and isinstance(kw.value, ast.Constant):
            return kw.value.value
    return None


def _kwarg(call: ast.Call, name: str):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


@dataclass
class FuncNode:
    """One function/method (or the per-module pseudo-function for
    module-level statements) in the project symbol table."""

    qual: str                     # <module dotted>.<qualpath>
    name: str
    module: str
    ctx: FileContext
    node: Optional[ast.AST]       # None for the <module> pseudo-function
    cls: Optional["ClassNode"] = None
    nested_in: Optional[str] = None
    local_types: Dict[str, str] = field(default_factory=dict)
    declared_globals: frozenset = frozenset()
    local_names: frozenset = frozenset()

    @property
    def qualpath(self) -> str:
        return self.qual[len(self.module) + 1:]

    @property
    def line(self) -> int:
        return getattr(self.node, "lineno", 1)

    @property
    def display(self) -> str:
        return f"{self.ctx.path}:{self.qualpath}"

    @property
    def is_test(self) -> bool:
        base = self.ctx.path.rsplit("/", 1)[-1]
        return (base.startswith("test_") or base == "conftest.py"
                or self.name.startswith("test_"))


@dataclass
class ClassNode:
    qual: str
    name: str
    module: str
    ctx: FileContext
    node: ast.ClassDef
    bases: List[str] = field(default_factory=list)
    methods: Dict[str, FuncNode] = field(default_factory=dict)
    attr_types: Dict[str, str] = field(default_factory=dict)
    confined_by_contract: bool = False


@dataclass
class ThreadSpawn:
    """One ``Thread(...)`` / ``pool.submit(...)`` / handler-registration
    site — the raw material for thread roots and the thread-leak rule."""

    func: FuncNode
    node: ast.Call
    kind: str                     # thread | executor | atexit | signal
    target: Optional[str]         # entry FuncNode qual when resolvable
    daemon: bool
    name_hint: Optional[str]
    started: bool
    joined: bool
    escapes: bool


@dataclass
class Root:
    """A thread root: an execution entry the scheduler (or the runtime)
    can start independently. ``main`` is the implicit root owning every
    externally-callable function."""

    rid: str
    kind: str                     # main|thread|executor|atexit|signal|handler
    entries: List[str]
    site: Optional[Tuple[str, int]] = None   # (path, line) of the spawn


@dataclass
class StateAccess:
    """One read/write of a shared-state key (``module.Class.attr`` or
    ``module.GLOBAL``). ``locks`` are the locks held *syntactically* (via
    ``with`` ancestors) at the access; callers add ``must_held`` of the
    enclosing function for the helper-method case."""

    state: str
    func: str
    node: ast.AST
    write: bool
    locks: frozenset


class ProjectModel:
    """Whole-program model over a set of parsed files.

    Build order: symbols -> attribute/local typing -> body scan (call
    edges, spawns, lock acquisitions, state accesses) -> roots ->
    runs-on propagation -> held-lock fixpoints -> lock graph. All
    consumers (rules, ownership report) read the finished fields."""

    def __init__(self, files: Sequence[FileContext]):
        self.files = list(files)
        self.functions: Dict[str, FuncNode] = {}
        self.classes: Dict[str, ClassNode] = {}
        self.globals: Dict[str, set] = {}
        self.aliases: Dict[str, str] = {}
        self.edges: Dict[str, set] = {}
        self.incoming: Dict[str, set] = {}
        self.call_sites: List[Tuple[str, str, Optional[ast.AST],
                                    frozenset]] = []
        self.calls_in: Dict[str, List[ast.Call]] = {}
        self.spawns: List[ThreadSpawn] = []
        self.roots: Dict[str, Root] = {}
        self.runs_on: Dict[str, frozenset] = {}
        self.must_held: Dict[str, frozenset] = {}
        self.may_held: Dict[str, frozenset] = {}
        #: raw lock acquisitions: (lock, func qual, With node, locks held
        #: via enclosing ``with`` blocks at that node)
        self.acquisitions: List[Tuple[str, str, ast.AST, frozenset]] = []
        #: (outer, inner) -> (path, line, interprocedural-only)
        self.lock_edges: Dict[Tuple[str, str], Tuple[str, int, bool]] = {}
        self.lock_roots: Dict[str, set] = {}
        self.state: Dict[str, List[StateAccess]] = {}
        self._mod_funcs: Dict[str, FuncNode] = {}
        self._method_index: Dict[str, List[FuncNode]] = {}
        self._build()

    # ------------------------------------------------------------ build
    def _build(self):
        for ctx in self.files:
            self._collect_symbols(ctx)
        self._infer_attr_types()
        for fn in self.functions.values():
            self._infer_local_types(fn)
        self._attr_types_from_locals()
        for ctx in self.files:
            self._scan_bodies(ctx)
        self._finish_roots()
        self._propagate_runs_on()
        self._propagate_held()
        self._build_lock_graph()

    # -------------------------------------------------------- symbols
    def _collect_symbols(self, ctx: FileContext):
        mod = module_name(ctx.path)
        pseudo = FuncNode(qual=f"{mod}.<module>", name="<module>",
                          module=mod, ctx=ctx, node=None)
        self._mod_funcs[ctx.path] = pseudo
        self.functions[pseudo.qual] = pseudo
        for node in ctx.walk():
            if isinstance(node, ast.ClassDef):
                cn = ClassNode(qual=f"{mod}.{_qualpath(node)}",
                               name=node.name, module=mod, ctx=ctx,
                               node=node)
                doc = (ast.get_docstring(node) or "").lower()
                cn.confined_by_contract = any(
                    m in doc for m in CONFINEMENT_MARKERS)
                for b in node.bases:
                    d = ctx.imports.resolve(b)
                    if d:
                        cn.bases.append(d)
                self.classes[cn.qual] = cn
        for node in ctx.walk():
            if isinstance(node, _FUNC_DEFS):
                encl_fn, encl_cls = _owner_defs(node)
                fn = FuncNode(qual=f"{mod}.{_qualpath(node)}",
                              name=node.name, module=mod, ctx=ctx,
                              node=node)
                if encl_cls is not None:
                    fn.cls = self.classes.get(
                        f"{mod}.{_qualpath(encl_cls)}")
                if encl_fn is not None:
                    fn.nested_in = f"{mod}.{_qualpath(encl_fn)}"
                decl, assigned = set(), set()
                for sub in ctx.walk(node):
                    if isinstance(sub, ast.Global):
                        decl.update(sub.names)
                    elif isinstance(sub, ast.Name) and isinstance(
                            sub.ctx, ast.Store):
                        assigned.add(sub.id)
                a = node.args
                params = [p.arg for p in
                          (a.posonlyargs + a.args + a.kwonlyargs)]
                if a.vararg:
                    params.append(a.vararg.arg)
                if a.kwarg:
                    params.append(a.kwarg.arg)
                fn.declared_globals = frozenset(decl)
                fn.local_names = (frozenset(assigned)
                                  | frozenset(params)) - fn.declared_globals
                self.functions[fn.qual] = fn
                if fn.cls is not None and \
                        getattr(node, "_zl_parent", None) is encl_cls:
                    fn.cls.methods[fn.name] = fn
                    self._method_index.setdefault(fn.name, []).append(fn)
        g = self.globals.setdefault(mod, set())
        for node in ctx.walk():
            if not isinstance(node, (ast.Assign, ast.AnnAssign,
                                     ast.AugAssign)):
                continue
            if _owner_defs(node) != (None, None):
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Name):
                    g.add(t.id)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    g.update(e.id for e in t.elts
                             if isinstance(e, ast.Name))
            value = getattr(node, "value", None)
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(value, (ast.Name, ast.Attribute)):
                d = ctx.imports.resolve(value)
                if d:
                    self.aliases[f"{mod}.{node.targets[0].id}"] = d

    # -------------------------------------------------------- resolution
    def _lookup_method(self, cls: ClassNode, name: str,
                       _depth: int = 0) -> Optional[FuncNode]:
        if name in cls.methods:
            return cls.methods[name]
        if _depth >= 4:
            return None
        for b in cls.bases:
            r = self.resolve_dotted(b, cls.module)
            if r and r[0] == "class" and r[1] is not cls:
                m = self._lookup_method(r[1], name, _depth + 1)
                if m is not None:
                    return m
        return None

    def resolve_dotted(self, dotted: str, mod: str = ""):
        """('func', FuncNode) | ('class', ClassNode) | None for a
        canonical dotted name, chasing module-level aliases."""
        for _ in range(4):
            if not dotted:
                return None
            cands = [dotted]
            if mod and "." not in dotted:
                cands.append(f"{mod}.{dotted}")
            for cand in cands:
                if cand in self.functions:
                    return ("func", self.functions[cand])
                if cand in self.classes:
                    return ("class", self.classes[cand])
            head, _, tail = dotted.rpartition(".")
            if head and tail:
                for cand in ([head, f"{mod}.{head}"]
                             if mod and "." not in head else [head]):
                    if cand in self.classes:
                        m = self._lookup_method(self.classes[cand], tail)
                        if m is not None:
                            return ("func", m)
            nxt = self.aliases.get(dotted)
            if nxt is None and mod and "." not in dotted:
                nxt = self.aliases.get(f"{mod}.{dotted}")
            if nxt is None:
                return None
            dotted = nxt
        return None

    def reachable(self, seeds: Iterable[str]) -> Set[str]:
        """Call-graph closure: every function qual reachable from
        ``seeds`` over ``edges`` — the interprocedural summary the
        path-sensitive rules piggyback on (e.g. the jit-region closure
        of rules_taint)."""
        seen: Set[str] = set()
        stack = [q for q in seeds if q in self.functions]
        while stack:
            q = stack.pop()
            if q in seen:
                continue
            seen.add(q)
            stack.extend(c for c in self.edges.get(q, ())
                         if c in self.functions and c not in seen)
        return seen

    # ------------------------------------------------------------ typing
    def _resolve_type(self, expr, ctx: FileContext,
                      mod: str) -> Optional[str]:
        if expr is None:
            return None
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            name = expr.value.split("[")[0].strip().strip('"\'')
            r = self.resolve_dotted(name, mod)
            return r[1].qual if r and r[0] == "class" else None
        if isinstance(expr, ast.Subscript):
            base = ctx.imports.resolve(expr.value)
            if base.rsplit(".", 1)[-1] == "Optional":
                return self._resolve_type(expr.slice, ctx, mod)
            return None
        if isinstance(expr, ast.BinOp):
            return (self._resolve_type(expr.left, ctx, mod)
                    or self._resolve_type(expr.right, ctx, mod))
        if isinstance(expr, (ast.Name, ast.Attribute)):
            d = ctx.imports.resolve(expr)
            if not d:
                return None
            r = self.resolve_dotted(d, mod)
            if r and r[0] == "class":
                return r[1].qual
            return d
        return None

    def _attr_type(self, cls: ClassNode, attr: str,
                   _depth: int = 0) -> Optional[str]:
        if attr in cls.attr_types:
            return cls.attr_types[attr]
        if _depth >= 4:
            return None
        for b in cls.bases:
            r = self.resolve_dotted(b, cls.module)
            if r and r[0] == "class" and r[1] is not cls:
                t = self._attr_type(r[1], attr, _depth + 1)
                if t is not None:
                    return t
        return None

    def _type_of_value(self, value, fn: FuncNode) -> Optional[str]:
        ctx, mod = fn.ctx, fn.module
        if isinstance(value, ast.Call):
            d = ctx.imports.resolve(value.func)
            if d:
                r = self.resolve_dotted(d, mod)
                if r and r[0] == "class":
                    return r[1].qual
                if r and r[0] == "func" and r[1].node is not None:
                    return self._resolve_type(
                        getattr(r[1].node, "returns", None),
                        r[1].ctx, r[1].module)
                if d in THREAD_SAFE_TYPES:
                    return d
            f = value.func
            if isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and \
                    f.value.id == "self" and fn.cls is not None:
                m = self._lookup_method(fn.cls, f.attr)
                if m is not None and m.node is not None:
                    return self._resolve_type(
                        getattr(m.node, "returns", None), m.ctx, m.module)
            return None
        if isinstance(value, ast.Name):
            return fn.local_types.get(value.id)
        if isinstance(value, ast.Attribute) and \
                isinstance(value.value, ast.Name) and \
                value.value.id == "self" and fn.cls is not None:
            return self._attr_type(fn.cls, value.attr)
        return None

    def _param_types(self, fn: FuncNode) -> Dict[str, str]:
        out: Dict[str, str] = {}
        if fn.node is None:
            return out
        a = fn.node.args
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            if p.annotation is not None:
                t = self._resolve_type(p.annotation, fn.ctx, fn.module)
                if t:
                    out[p.arg] = t
        return out

    def _infer_attr_types(self):
        for cls in self.classes.values():
            for m in cls.methods.values():
                params = self._param_types(m)
                for sub in m.ctx.walk(m.node):
                    tgt = None
                    if isinstance(sub, ast.Assign) and \
                            len(sub.targets) == 1:
                        tgt, val = sub.targets[0], sub.value
                    elif isinstance(sub, ast.AnnAssign):
                        tgt, val = sub.target, sub.value
                    else:
                        continue
                    if not (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        continue
                    t = None
                    if isinstance(sub, ast.AnnAssign):
                        t = self._resolve_type(sub.annotation, m.ctx,
                                               m.module)
                    if t is None and isinstance(val, ast.Call):
                        d = m.ctx.imports.resolve(val.func)
                        if d:
                            r = self.resolve_dotted(d, m.module)
                            if r and r[0] == "class":
                                t = r[1].qual
                            elif d in THREAD_SAFE_TYPES:
                                t = d
                    if t is None and isinstance(val, ast.Name):
                        t = params.get(val.id)
                    if t and tgt.attr not in cls.attr_types:
                        cls.attr_types[tgt.attr] = t

    def _infer_local_types(self, fn: FuncNode):
        if fn.node is None:
            return
        fn.local_types.update(self._param_types(fn))
        for sub in fn.ctx.walk(fn.node):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name):
                t = self._type_of_value(sub.value, fn)
                if t and sub.targets[0].id not in fn.local_types:
                    fn.local_types[sub.targets[0].id] = t
            elif isinstance(sub, ast.AnnAssign) and \
                    isinstance(sub.target, ast.Name):
                t = self._resolve_type(sub.annotation, fn.ctx, fn.module)
                if t and sub.target.id not in fn.local_types:
                    fn.local_types[sub.target.id] = t

    def _attr_types_from_locals(self):
        for cls in self.classes.values():
            for m in cls.methods.values():
                for sub in m.ctx.walk(m.node):
                    if not (isinstance(sub, ast.Assign)
                            and len(sub.targets) == 1):
                        continue
                    tgt = sub.targets[0]
                    if isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == "self" and \
                            isinstance(sub.value, ast.Name):
                        t = m.local_types.get(sub.value.id)
                        if t and tgt.attr not in cls.attr_types:
                            cls.attr_types[tgt.attr] = t

    # --------------------------------------------------------- body scan
    def _owner_func(self, node: ast.AST, mod: str,
                    pseudo: FuncNode) -> FuncNode:
        fn, _ = _owner_defs(node)
        if fn is None:
            return pseudo
        return self.functions.get(f"{mod}.{_qualpath(fn)}", pseudo)

    def _held_at(self, node: ast.AST, owner: FuncNode,
                 exclude: Optional[ast.AST] = None) -> frozenset:
        """Locks acquired by enclosing ``with`` blocks at ``node``."""
        held = set()
        for a in ancestors(node):
            if isinstance(a, (ast.With, ast.AsyncWith)) and a is not exclude:
                for item in a.items:
                    if _is_lockish_expr(item.context_expr):
                        held.add(self._lock_id(item.context_expr, owner))
        return frozenset(held)

    def _lock_id(self, expr: ast.AST, owner: FuncNode) -> str:
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name) and base.id == "self" \
                    and owner.cls is not None:
                return f"{owner.cls.qual}.{expr.attr}"
            if isinstance(base, ast.Name):
                t = owner.local_types.get(base.id)
                if t and t in self.classes:
                    return f"{t}.{expr.attr}"
            d = owner.ctx.imports.resolve(expr)
            if d:
                return d
            return f"{owner.qual}.<{expr.attr}>"
        if isinstance(expr, ast.Name):
            if expr.id in self.globals.get(owner.module, ()) and \
                    expr.id not in owner.local_names:
                return f"{owner.module}.{expr.id}"
            if expr.id not in owner.local_names:
                # an imported module-level lock keeps its home identity,
                # so cross-file acquisitions of the same lock line up
                d = owner.ctx.imports.resolve(expr)
                if d and d != expr.id:
                    mod, _, name = d.rpartition(".")
                    if name in self.globals.get(mod, ()):
                        return d
            return f"{owner.qual}.{expr.id}"
        return f"{owner.qual}.<lock@{getattr(expr, 'lineno', 0)}>"

    def _state_key(self, expr: ast.AST,
                   owner: FuncNode) -> Optional[Tuple[str, ClassNode]]:
        """Shared-state key for an expression, or None. Returns the
        owning ClassNode for attribute state (None for globals)."""
        if isinstance(expr, ast.Attribute):
            base = expr.value
            cls = None
            if isinstance(base, ast.Name) and base.id == "self":
                cls = owner.cls
            elif isinstance(base, ast.Name):
                t = owner.local_types.get(base.id)
                cls = self.classes.get(t) if t else None
            elif isinstance(base, ast.Attribute) and \
                    isinstance(base.value, ast.Name) and \
                    base.value.id == "self" and owner.cls is not None:
                t = self._attr_type(owner.cls, base.attr)
                cls = self.classes.get(t) if t else None
            if cls is not None:
                attr = expr.attr
                if _lockish_name(attr) or attr in cls.methods:
                    return None
                t = self._attr_type(cls, attr)
                if t in THREAD_SAFE_TYPES:
                    return None
                return f"{cls.qual}.{attr}", cls
            d = owner.ctx.imports.resolve(expr)
            if d:
                head, _, tail = d.rpartition(".")
                if tail and not _lockish_name(tail) and \
                        tail in self.globals.get(head, ()):
                    return f"{d}", None
            return None
        if isinstance(expr, ast.Name):
            if expr.id in self.globals.get(owner.module, ()) and \
                    expr.id not in owner.local_names and \
                    not _lockish_name(expr.id):
                return f"{owner.module}.{expr.id}", None
        return None

    def _record_state(self, key, cls, owner: FuncNode, node: ast.AST,
                      write: bool):
        if owner.name in _INIT_METHODS or owner.node is None:
            return
        self.state.setdefault(key, []).append(StateAccess(
            state=key, func=owner.qual, node=node, write=write,
            locks=self._held_at(node, owner)))

    def _scan_bodies(self, ctx: FileContext):
        mod = module_name(ctx.path)
        pseudo = self._mod_funcs[ctx.path]
        order = ctx.walk()
        # owner per node, computed in one pass over the DFS pre-order:
        # a def claims its subtree slice; nested defs are visited later
        # and overwrite their sub-slice. The def node itself (incl. its
        # decorators/defaults, evaluated in the enclosing scope) keeps
        # the enclosing owner — same attribution _owner_func derives by
        # walking ancestors, minus the per-node ancestor walk.
        owners = [pseudo] * len(order)
        span = ctx._span
        for i, node in enumerate(order):
            if isinstance(node, _FUNC_DEFS):
                fn = self.functions.get(f"{mod}.{_qualpath(node)}")
                if fn is not None:
                    end = span[id(node)][1]
                    owners[i + 1:end] = [fn] * (end - i - 1)
        for i, node in enumerate(order):
            owner = owners[i]
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if _is_lockish_expr(item.context_expr):
                        self.acquisitions.append((
                            self._lock_id(item.context_expr, owner),
                            owner.qual, node,
                            self._held_at(node, owner, exclude=node)))
            elif isinstance(node, ast.Call):
                self._handle_call(owner, node, ctx, mod)
            elif isinstance(node, ast.Attribute) and \
                    isinstance(node.ctx, (ast.Store, ast.Del)):
                ks = self._state_key(node, owner)
                if ks is not None:
                    self._record_state(ks[0], ks[1], owner, node, True)
            elif isinstance(node, ast.Attribute) and \
                    isinstance(node.ctx, ast.Load):
                par = getattr(node, "_zl_parent", None)
                if isinstance(par, ast.Call) and par.func is node:
                    continue  # callee position — an edge, not state
                if isinstance(par, ast.Attribute) or \
                        isinstance(par, ast.Subscript) and par.value is node:
                    continue  # handled at the outer node
                ks = self._state_key(node, owner)
                if ks is not None:
                    self._record_state(ks[0], ks[1], owner, node, False)
            elif isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, (ast.Store, ast.Del)):
                ks = self._state_key(node.value, owner)
                if ks is not None:
                    self._record_state(ks[0], ks[1], owner, node, True)
            elif isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load):
                par = getattr(node, "_zl_parent", None)
                if isinstance(par, (ast.Attribute, ast.Call)):
                    continue
                ks = self._state_key(node, owner)
                if ks is not None:
                    self._record_state(ks[0], ks[1], owner, node, False)
            elif isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Store):
                if node.id in owner.declared_globals:
                    self._record_state(f"{owner.module}.{node.id}", None,
                                       owner, node, True)

    # ----------------------------------------------------------- calls
    def _add_edge(self, caller: str, callee: str,
                  node: Optional[ast.AST], held: frozenset):
        self.edges.setdefault(caller, set()).add(callee)
        self.incoming.setdefault(callee, set()).add(caller)
        self.call_sites.append((caller, callee, node, held))

    def _resolve_callable(self, expr: ast.AST, owner: FuncNode):
        """('func', FuncNode) | ('class', ClassNode) | None for a callee
        or callback-reference expression."""
        if isinstance(expr, ast.Name):
            scope = owner
            while scope is not None:
                cand = f"{scope.qual}.{expr.id}"
                if cand in self.functions:
                    return ("func", self.functions[cand])
                scope = self.functions.get(scope.nested_in) \
                    if scope.nested_in else None
            d = owner.ctx.imports.resolve(expr)
            return self.resolve_dotted(d or expr.id, owner.module)
        if not isinstance(expr, ast.Attribute):
            return None
        base = expr.value
        if isinstance(base, ast.Name) and base.id == "self" \
                and owner.cls is not None:
            m = self._lookup_method(owner.cls, expr.attr)
            return ("func", m) if m is not None else None
        recv_t = None
        if isinstance(base, ast.Name):
            recv_t = owner.local_types.get(base.id)
        elif isinstance(base, ast.Attribute) and \
                isinstance(base.value, ast.Name) and \
                base.value.id == "self" and owner.cls is not None:
            recv_t = self._attr_type(owner.cls, base.attr)
        if recv_t and recv_t in self.classes:
            m = self._lookup_method(self.classes[recv_t], expr.attr)
            return ("func", m) if m is not None else None
        d = owner.ctx.imports.resolve(expr)
        if d:
            r = self.resolve_dotted(d, owner.module)
            if r is not None:
                return r
        # unique-method-name fallback: exactly one project class defines
        # this (non-generic) method — resolve to it
        if expr.attr not in _GENERIC_METHODS:
            cands = self._method_index.get(expr.attr, ())
            if len(cands) == 1:
                return ("func", cands[0])
        return None

    def _spawn_bookkeeping(self, owner: FuncNode, node: ast.Call):
        """started/joined/escapes/daemon facts for one Thread(...) call."""
        par = getattr(node, "_zl_parent", None)
        var = attr = None
        started = joined = escapes = False
        daemon = _const_kwarg(node, "daemon") is True
        if isinstance(par, ast.Attribute) and par.attr == "start":
            started = True
        elif isinstance(par, ast.Assign) and len(par.targets) == 1:
            tgt = par.targets[0]
            if isinstance(tgt, ast.Name):
                var = tgt.id
            elif isinstance(tgt, ast.Attribute) and \
                    isinstance(tgt.value, ast.Name) and \
                    tgt.value.id == "self":
                attr = tgt.attr
        elif isinstance(par, (ast.Return, ast.Yield)) or \
                isinstance(par, ast.Call):
            escapes = True
        scope = owner.node if owner.node is not None else owner.ctx.tree
        if var is not None:
            for sub in owner.ctx.walk(scope):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute) and \
                        isinstance(sub.func.value, ast.Name) and \
                        sub.func.value.id == var:
                    if sub.func.attr == "start":
                        started = True
                    elif sub.func.attr == "join":
                        joined = True
                elif isinstance(sub, ast.Call) and any(
                        isinstance(a, ast.Name) and a.id == var
                        for a in sub.args):
                    escapes = True
                elif isinstance(sub, (ast.Return, ast.Yield)) and \
                        isinstance(getattr(sub, "value", None), ast.Name) \
                        and sub.value.id == var:
                    escapes = True
                elif isinstance(sub, ast.Assign) and \
                        isinstance(sub.value, ast.Name) and \
                        sub.value.id == var:
                    escapes = True
                elif isinstance(sub, ast.Assign) and \
                        isinstance(sub.targets[0], ast.Attribute) and \
                        isinstance(sub.targets[0].value, ast.Name) and \
                        sub.targets[0].value.id == var and \
                        sub.targets[0].attr == "daemon" and \
                        isinstance(sub.value, ast.Constant) and \
                        sub.value.value is True:
                    daemon = True
        if attr is not None:
            started = True  # published on the instance; assume managed
            search = owner.cls.node if owner.cls is not None else scope
            for sub in owner.ctx.walk(search):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute) and \
                        sub.func.attr == "join":
                    joined = True
        return daemon, started, joined, escapes

    def _handle_call(self, owner: FuncNode, node: ast.Call,
                     ctx: FileContext, mod: str):
        self.calls_in.setdefault(owner.qual, []).append(node)
        # container mutation through a method call is a *write* to the
        # receiver state (self._q.append(x), GLOBAL.update(...))
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in MUTATOR_METHODS:
            ks = self._state_key(node.func.value, owner)
            if ks is not None:
                self._record_state(ks[0], ks[1], owner, node, True)
        d = ctx.imports.resolve(node.func)
        held = None  # computed lazily

        def site_held():
            nonlocal held
            if held is None:
                held = self._held_at(node, owner)
            return held

        # ---- thread/executor/handler registration sites become roots
        if d == "threading.Thread":
            target = _kwarg(node, "target")
            tq = None
            if target is not None and not isinstance(target, ast.Lambda):
                r = self._resolve_callable(target, owner)
                if r is not None and r[0] == "func":
                    tq = r[1].qual
            daemon, started, joined, escapes = \
                self._spawn_bookkeeping(owner, node)
            name = _const_kwarg(node, "name")
            self.spawns.append(ThreadSpawn(
                func=owner, node=node, kind="thread", target=tq,
                daemon=daemon, name_hint=name if isinstance(name, str)
                else None, started=started, joined=joined,
                escapes=escapes))
            return
        if d in ("atexit.register", "signal.signal") and node.args:
            arg = node.args[0] if d == "atexit.register" else (
                node.args[1] if len(node.args) > 1 else None)
            tq = None
            if arg is not None and not isinstance(arg, ast.Lambda):
                r = self._resolve_callable(arg, owner)
                if r is not None and r[0] == "func":
                    tq = r[1].qual
            self.spawns.append(ThreadSpawn(
                func=owner, node=node,
                kind="atexit" if d == "atexit.register" else "signal",
                target=tq, daemon=True, name_hint=None, started=True,
                joined=True, escapes=True))
            return

        # ---- ordinary call edge (typed receivers, imports, self.*)
        r = self._resolve_callable(node.func, owner)
        if r is None and isinstance(node.func, ast.Attribute) and \
                node.func.attr == "submit" and node.args:
            # untyped-receiver .submit(fn, ...): an executor dispatch —
            # the submitted callable becomes a pool root
            tq = None
            if not isinstance(node.args[0], ast.Lambda):
                rr = self._resolve_callable(node.args[0], owner)
                if rr is not None and rr[0] == "func":
                    tq = rr[1].qual
            self.spawns.append(ThreadSpawn(
                func=owner, node=node, kind="executor", target=tq,
                daemon=True, name_hint=None, started=True, joined=True,
                escapes=True))
            return
        callee_cls = None
        if r is not None and r[0] == "func":
            self._add_edge(owner.qual, r[1].qual, node, site_held())
        elif r is not None and r[0] == "class":
            callee_cls = r[1]
            init = self._lookup_method(callee_cls, "__init__")
            if init is not None:
                self._add_edge(owner.qual, init.qual, node, site_held())

        # ---- callback arguments: a project-function reference passed
        # into a call may be invoked by the receiver later
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if not isinstance(arg, (ast.Name, ast.Attribute)):
                continue
            cb = self._resolve_callable(arg, owner)
            if cb is None or cb[0] != "func":
                continue
            if callee_cls is not None:
                # constructor capture: any method of the class may call it
                for m in callee_cls.methods.values():
                    self._add_edge(m.qual, cb[1].qual, None, frozenset())
            elif r is not None and r[0] == "func":
                self._add_edge(r[1].qual, cb[1].qual, None, frozenset())
            else:
                self._add_edge(owner.qual, cb[1].qual, node, site_held())

    # ----------------------------------------------------------- roots
    def _finish_roots(self):
        def add_root(rid, kind, entries, site):
            rid0, n = rid, 1
            while rid in self.roots:
                if self.roots[rid].kind == kind and \
                        set(self.roots[rid].entries) == set(entries):
                    return
                n += 1
                rid = f"{rid0}#{n}"
            self.roots[rid] = Root(rid=rid, kind=kind,
                                   entries=sorted(entries), site=site)

        for sp in self.spawns:
            if sp.func.is_test:
                continue
            site = (sp.func.ctx.path, sp.node.lineno)
            rid = sp.name_hint or (
                sp.target if sp.target is not None
                else f"{sp.kind}@{sp.func.qual}")
            add_root(rid, sp.kind, [sp.target] if sp.target else [], site)
        for cls in self.classes.values():
            if any(f.startswith("test_") or f == "conftest.py"
                   for f in (cls.ctx.path.rsplit("/", 1)[-1],)):
                continue
            chain = self._base_chain(cls)
            if any(b.rsplit(".", 1)[-1] in _HANDLER_BASES for b in chain):
                for name, m in cls.methods.items():
                    if name.startswith("do_") or name == "handle":
                        add_root(f"{cls.qual}.{name}", "handler",
                                 [m.qual], (cls.ctx.path, m.line))
            if any(b == "threading.Thread" for b in chain) and \
                    "run" in cls.methods:
                add_root(f"{cls.qual}.run", "thread",
                         [cls.methods["run"].qual],
                         (cls.ctx.path, cls.methods["run"].line))
        entries = set()
        for root in self.roots.values():
            entries.update(root.entries)
        main = []
        for fn in self.functions.values():
            if fn.node is None:
                main.append(fn.qual)   # module import runs on main
            elif fn.qual not in entries and fn.nested_in is None and \
                    not self.incoming.get(fn.qual) and \
                    not fn.name.startswith("do_"):
                main.append(fn.qual)
        self.roots["main"] = Root(rid="main", kind="main",
                                  entries=sorted(main), site=None)

    def _base_chain(self, cls: ClassNode, _depth: int = 0) -> List[str]:
        out = list(cls.bases)
        if _depth >= 4:
            return out
        for b in cls.bases:
            r = self.resolve_dotted(b, cls.module)
            if r and r[0] == "class" and r[1] is not cls:
                out.extend(self._base_chain(r[1], _depth + 1))
        return out

    # ----------------------------------------------------- propagation
    def _propagate_runs_on(self):
        on: Dict[str, set] = {}
        for root in self.roots.values():
            # atexit handlers execute ON the main thread (sequentially,
            # at shutdown) — they are listed as roots for the ownership
            # report but attribute their reachability to main, so
            # main-only state is not miscounted as cross-thread
            rid = "main" if root.kind == "atexit" else root.rid
            seen = set()
            stack = [e for e in root.entries if e in self.functions]
            while stack:
                q = stack.pop()
                if q in seen:
                    continue
                seen.add(q)
                stack.extend(self.edges.get(q, ()))
            for q in seen:
                on.setdefault(q, set()).add(rid)
        self.runs_on = {q: frozenset(s) for q, s in on.items()}

    def _propagate_held(self):
        """must_held = locks guaranteed held on *every* path into a
        function (intersection over call sites — the helper-method lock
        tracking); may_held = locks held on *some* path (union — feeds
        the lock-order graph and blocking-under-lock)."""
        sites: Dict[str, List[Tuple[str, frozenset]]] = {}
        for caller, callee, node, held in self.call_sites:
            sites.setdefault(callee, []).append((caller, held))
        # a root entry (or an externally-callable function — no project
        # callers) starts lock-free; its must-set is pinned at empty
        pinned = {e for r in self.roots.values() for e in r.entries}
        pinned.update(q for q in self.functions
                      if not self.incoming.get(q))
        must: Dict[str, Optional[frozenset]] = \
            {q: (frozenset() if q in pinned else None)
             for q in self.functions}      # None = no information yet
        may: Dict[str, frozenset] = \
            {q: frozenset() for q in self.functions}
        for _ in range(24):
            changed = False
            for callee, ss in sites.items():
                if callee not in must:
                    continue
                macc = set(may[callee])
                acc: Optional[frozenset] = None
                for caller, held in ss:
                    macc |= may.get(caller, frozenset()) | held
                    cm = must.get(caller)
                    if cm is None:
                        continue   # caller unreached so far: no info
                    inc = cm | held
                    acc = inc if acc is None else (acc & inc)
                if callee not in pinned and acc is not None \
                        and acc != must[callee]:
                    cur = must[callee]
                    must[callee] = acc if cur is None else (cur & acc)
                    if must[callee] != cur:
                        changed = True
                if macc != may[callee]:
                    may[callee] = frozenset(macc)
                    changed = True
            if not changed:
                break
        self.must_held = {q: (v or frozenset()) for q, v in must.items()}
        self.may_held = may

    def _build_lock_graph(self):
        for lock, funcq, node, anc in self.acquisitions:
            held_before = anc | self.may_held.get(funcq, frozenset())
            path = self.functions[funcq].ctx.path
            line = getattr(node, "lineno", 1)
            for h in held_before:
                if h == lock:
                    continue
                interproc = h not in anc
                prev = self.lock_edges.get((h, lock))
                if prev is None or (prev[2] and not interproc):
                    self.lock_edges[(h, lock)] = (path, line, interproc)
            self.lock_roots.setdefault(lock, set()).update(
                self.runs_on.get(funcq, frozenset()))

    # -------------------------------------------------------- queries
    def effective_locked(self, acc: StateAccess) -> bool:
        """Locked directly (``with`` ancestor) or via a helper method
        that is only ever called with a lock held."""
        return bool(acc.locks) or \
            bool(self.must_held.get(acc.func, frozenset()))

    def state_roots(self, key: str) -> frozenset:
        roots = set()
        for acc in self.state.get(key, ()):
            roots |= self.runs_on.get(acc.func, frozenset())
        return frozenset(roots)


def build_project(sources: Dict[str, str]) -> ProjectModel:
    """Whole-program model from in-memory sources (unit-test entry).
    Keys are repo-relative posix paths."""
    ctxs = []
    for rel, src in sorted(sources.items()):
        tree = ast.parse(src, filename=rel)
        _ParentAnnotator().visit(tree)
        ctxs.append(FileContext(path=rel.replace(os.sep, "/"),
                                source=src, tree=tree))
    return ProjectModel(ctxs)


def build_model_for_paths(paths: Sequence[str], root: Optional[str] = None,
                          jobs: int = 1) -> ProjectModel:
    """Parse ``paths`` and build the whole-program model (the
    --ownership-report path; findings are not computed)."""
    if root is None and paths:
        root = find_repo_root(paths[0])
    files = iter_python_files(paths)
    if jobs and jobs > 1 and len(files) > 1:
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=jobs) as ex:
            parsed = list(ex.map(lambda p: parse_file(p, root), files))
    else:
        parsed = [parse_file(p, root) for p in files]
    return ProjectModel([ctx for ctx, err in parsed if ctx is not None])
