"""zoolint core — per-file AST rule engine with inline suppressions.

The invariants the last three PRs rest on (no wall-clock in hot paths, no
implicit host syncs inside dispatch loops, no per-call jit construction,
locked engine shared state, a docs catalog that matches the registry) were
enforced by code review plus one brittle grep. This package turns them
into first-class static analysis: every rule is an AST visitor with a
stable id, findings carry ``path:line:col``, and any finding can be
silenced in place (``# zoolint: disable=RULE``) or grandfathered in the
committed baseline (see baseline.py) — so the clean-tree invariant is
``exit 0`` in CI, not tribal knowledge.

Two rule scopes:

- **file** rules see one parsed module at a time (``check_file``);
- **project** rules see every scanned file at once plus the repo root
  (``check_project``) — the catalog-drift checks that compare code
  against docs/observability.md live there.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: path segments whose files count as hot-path (the serve/dispatch/train
#: inner loops) — hot-path-only rules look at these trees exclusively
HOT_PATH_SEGMENTS = frozenset({"serving", "common", "learn"})

_DISABLE_LINE = re.compile(
    r"#\s*zoolint:\s*disable(?:=(?P<rules>[\w,\- ]+))?")
_DISABLE_FILE = re.compile(
    r"#\s*zoolint:\s*disable-file=(?P<rules>[\w,\- ]+)")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location. ``path`` is repo-relative
    posix so findings (and baseline fingerprints) are machine-portable."""

    rule: str
    path: str
    line: int
    col: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: " \
               f"{self.message}"


class _ParentAnnotator(ast.NodeVisitor):
    """Stamp ``_zl_parent`` on every node — rules walk ancestor chains
    (enclosing loop / function / ``with`` / ``if``) constantly."""

    def generic_visit(self, node):
        for child in ast.iter_child_nodes(node):
            child._zl_parent = node  # type: ignore[attr-defined]
        super().generic_visit(node)


def ancestors(node: ast.AST) -> Iterable[ast.AST]:
    cur = getattr(node, "_zl_parent", None)
    while cur is not None:
        yield cur
        cur = getattr(cur, "_zl_parent", None)


class ImportMap:
    """Local name -> qualified dotted name, from a module's imports.

    ``resolve(call.func)`` turns an AST callee into its dotted origin
    (``np.asarray`` -> ``numpy.asarray``, bare ``jit`` after ``from jax
    import jit`` -> ``jax.jit``) so rules match on canonical names, not on
    whatever alias a file picked."""

    def __init__(self, tree: ast.AST):
        self.names: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.names[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for a in node.names:
                    self.names[a.asname or a.name] = \
                        f"{node.module}.{a.name}"

    def resolve(self, func: ast.AST) -> str:
        """Dotted name of a callee ('' when it isn't a plain name chain)."""
        parts: List[str] = []
        cur = func
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return ""
        root = self.names.get(cur.id, cur.id)
        return ".".join([root] + list(reversed(parts)))


@dataclass
class FileContext:
    """Everything a file rule sees: parsed AST (parent-annotated), source
    lines, repo-relative path, import resolution, and hot-path flag."""

    path: str                    # repo-relative, posix separators
    source: str
    tree: ast.AST
    lines: List[str] = field(default_factory=list)
    imports: ImportMap = None  # type: ignore[assignment]

    def __post_init__(self):
        self.lines = self.source.splitlines()
        if self.imports is None:
            self.imports = ImportMap(self.tree)

    @property
    def is_hot_path(self) -> bool:
        return bool(HOT_PATH_SEGMENTS
                    & set(self.path.split("/")[:-1]))

    def line_text(self, line: int) -> str:
        return self.lines[line - 1] if 0 < line <= len(self.lines) else ""


@dataclass
class ProjectContext:
    """What project rules see: every FileContext plus the repo root (for
    docs/ lookups). ``root`` may be None when no repo root was found —
    root-dependent rules then skip themselves."""

    files: List[FileContext]
    root: Optional[str]


class Rule:
    """Base rule. Subclasses set ``id`` (the stable suppression/baseline
    key), ``scope`` ('file' | 'project'), and override the matching
    ``check_*``. Rule ids are kebab-case and documented in
    docs/zoolint.md."""

    id: str = ""
    scope: str = "file"
    description: str = ""

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def check_project(self, pctx: ProjectContext) -> Iterable[Finding]:
        return ()


_RULES: "Dict[str, Rule]" = {}


def register(rule_cls):
    """Class decorator: instantiate and add to the global rule registry
    (import-time, like pytest plugins — rules_*.py modules just need to
    be imported)."""
    rule = rule_cls()
    if not rule.id:
        raise ValueError(f"{rule_cls.__name__} has no id")
    if rule.id in _RULES:
        raise ValueError(f"duplicate rule id {rule.id!r}")
    _RULES[rule.id] = rule
    return rule_cls


def all_rules() -> Dict[str, Rule]:
    from analytics_zoo_tpu.analysis import (  # noqa: F401
        rules_catalog, rules_compile, rules_concurrency, rules_dataplane,
        rules_hotpath, rules_jit,
    )
    return dict(_RULES)


# ------------------------------------------------------------ suppressions

def _parse_rule_list(raw: Optional[str]) -> Optional[frozenset]:
    """None = bare disable (all rules)."""
    if raw is None:
        return None
    return frozenset(r.strip() for r in raw.split(",") if r.strip())


def suppressed(ctx: FileContext, finding: Finding) -> bool:
    """True when the finding's source line carries ``# zoolint: disable``
    (bare = everything, ``=a,b`` = those rules) or the file carries a
    matching ``# zoolint: disable-file=a,b`` anywhere."""
    m = _DISABLE_LINE.search(ctx.line_text(finding.line))
    if m:
        rules = _parse_rule_list(m.group("rules"))
        if rules is None or finding.rule in rules:
            return True
    for line in ctx.lines:
        fm = _DISABLE_FILE.search(line)
        if fm and finding.rule in _parse_rule_list(fm.group("rules")):
            return True
    return False


# ------------------------------------------------------------------ engine

def find_repo_root(start: str) -> Optional[str]:
    """Walk up from ``start`` to the checkout root (the dir holding
    pyproject.toml / .git / docs/observability.md) — anchors the baseline
    path and the catalog rules' docs lookup."""
    cur = os.path.abspath(start)
    if os.path.isfile(cur):
        cur = os.path.dirname(cur)
    while True:
        if (os.path.exists(os.path.join(cur, "pyproject.toml"))
                or os.path.isdir(os.path.join(cur, ".git"))
                or os.path.isfile(
                    os.path.join(cur, "docs", "observability.md"))):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return None
        cur = parent


def relpath(path: str, root: Optional[str]) -> str:
    """Repo-relative posix path — the form Finding.path and baseline
    entries use."""
    ap = os.path.abspath(path)
    if root and ap.startswith(os.path.abspath(root) + os.sep):
        ap = os.path.relpath(ap, root)
    return ap.replace(os.sep, "/")


_relpath = relpath


def parse_file(path: str, root: Optional[str]) -> Tuple[
        Optional[FileContext], Optional[Finding]]:
    """Parse one file into a FileContext, or a ``syntax-error`` finding —
    an unparseable file must fail the lint loudly, not crash the linter."""
    with open(path, "r", encoding="utf-8", errors="replace") as fh:
        source = fh.read()
    rel = _relpath(path, root)
    try:
        tree = ast.parse(source, filename=rel)
    except SyntaxError as e:
        return None, Finding("syntax-error", rel, e.lineno or 1,
                             (e.offset or 1) - 1,
                             f"file does not parse: {e.msg}")
    _ParentAnnotator().visit(tree)
    return FileContext(path=rel, source=source, tree=tree), None


def analyze_source(source: str, relpath: str,
                   rules: Optional[Sequence[Rule]] = None,
                   root: Optional[str] = None) -> List[Finding]:
    """Run file-scope rules over in-memory source — the unit-test entry
    point (project rules need a tree on disk; see ``analyze_paths``)."""
    try:
        tree = ast.parse(source, filename=relpath)
    except SyntaxError as e:
        return [Finding("syntax-error", relpath, e.lineno or 1,
                        (e.offset or 1) - 1,
                        f"file does not parse: {e.msg}")]
    _ParentAnnotator().visit(tree)
    ctx = FileContext(path=relpath.replace(os.sep, "/"), source=source,
                      tree=tree)
    use = [r for r in (rules if rules is not None
                       else all_rules().values()) if r.scope == "file"]
    out: List[Finding] = []
    for rule in use:
        for f in rule.check_file(ctx):
            if not suppressed(ctx, f):
                out.append(f)
    return sorted(out, key=lambda f: (f.path, f.line, f.col, f.rule))


def iter_python_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git",
                                            "build", ".eggs")]
                out.extend(os.path.join(dirpath, f)
                           for f in sorted(filenames) if f.endswith(".py"))
    return sorted(set(out))


def analyze_paths(paths: Sequence[str],
                  rules: Optional[Dict[str, Rule]] = None,
                  root: Optional[str] = None) -> List[Finding]:
    """Scan files/dirs with every registered rule (file + project scope),
    inline suppressions applied. Baseline filtering is the CLI's job —
    library callers (the pytest catalog cross-check) see raw findings."""
    rules = rules if rules is not None else all_rules()
    if root is None and paths:
        root = find_repo_root(paths[0])
    contexts: List[FileContext] = []
    findings: List[Finding] = []
    for path in iter_python_files(paths):
        ctx, err = parse_file(path, root)
        if err is not None:
            findings.append(err)
            continue
        contexts.append(ctx)
        for rule in rules.values():
            if rule.scope != "file":
                continue
            for f in rule.check_file(ctx):
                if not suppressed(ctx, f):
                    findings.append(f)
    pctx = ProjectContext(files=contexts, root=root)
    by_path = {c.path: c for c in contexts}
    for rule in rules.values():
        if rule.scope != "project":
            continue
        for f in rule.check_project(pctx):
            ctx = by_path.get(f.path)
            if ctx is None or not suppressed(ctx, f):
                findings.append(f)
    return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule))
