"""Hot-path sync rules — wall-clock timing and implicit host↔device
synchronization in the serve/dispatch/train inner loops.

These replace dev/run-tests.sh's ``lint_wallclock`` grep and extend it to
the bug class the Gemma-on-TPU comparison (PAPERS.md) blames for most
GPU→TPU regressions: a single accidental host round-trip (``.item()``,
``float(device_val)``, ``np.asarray``, an unguarded ``block_until_ready``)
inside a dispatch loop serializes the host against the device and erases
the overlap the pipeline PRs bought.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from analytics_zoo_tpu.analysis.core import (
    FileContext, Finding, Rule, ancestors, register,
)

#: wall-clock constructors banned from hot-path packages (stage stats and
#: deadlines must ride perf_counter/monotonic — NTP slew corrupts both)
_WALLCLOCK = frozenset({
    "time.time", "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.datetime.today", "datetime.date.today",
})

#: function-name tokens that mark a dispatch/drain/step loop owner — the
#: loops inside these are the latency-critical inner loops
HOT_FN_TOKENS = frozenset({
    "dispatch", "drain", "step", "serve", "retire", "submit", "produce",
    "finish", "fetch", "run", "predict", "fit", "loop",
})

#: callee final components that force a host sync wherever they resolve
#: from (jax.device_get, telemetry.traced_device_get, bare imports...)
_SYNC_TAILS = frozenset({
    "block_until_ready", "device_get", "traced_device_get",
})
#: fully-resolved names that force a host copy of their argument
_SYNC_CALLS = frozenset({"numpy.asarray", "numpy.array"})

_LOOPS = (ast.For, ast.While, ast.AsyncFor, ast.ListComp, ast.SetComp,
          ast.DictComp, ast.GeneratorExp)
_FUNCS = (ast.FunctionDef, ast.AsyncFunctionDef)

#: identifiers in an ``if`` test that mark a deliberate, rate-limited
#: fence (the profiler's sampled steps) — sampled syncs are the design
_SAMPLING_MARKERS = ("sample", "prof")


def _fn_tokens(name: str) -> set:
    return set(t for t in name.lower().split("_") if t)


def _enclosing(node: ast.AST, kinds) -> List[ast.AST]:
    return [a for a in ancestors(node) if isinstance(a, kinds)]


def _nearest_function(node: ast.AST):
    for a in ancestors(node):
        if isinstance(a, _FUNCS):
            return a
    return None


def _test_identifiers(test: ast.AST) -> Iterable[str]:
    for n in ast.walk(test):
        if isinstance(n, ast.Name):
            yield n.id
        elif isinstance(n, ast.Attribute):
            yield n.attr


def _sampling_guarded(node: ast.AST, stop_at: ast.AST) -> bool:
    """True when an ``if`` between ``node`` and its function mentions a
    sampling/profiling identifier — the fence is intentional and bounded
    (StepProfiler.should_sample, tracer.should_sample...)."""
    for a in ancestors(node):
        if a is stop_at:
            return False
        if isinstance(a, ast.If) and any(
                any(m in ident.lower() for m in _SAMPLING_MARKERS)
                for ident in _test_identifiers(a.test)):
            return True
    return False


@register
class WallclockHotpath(Rule):
    """``time.time()`` / ``datetime.now()`` in serving/, common/, learn/.

    Wall-clock stamps there corrupt stage stats, deadlines and rate
    limiters under NTP slew — use ``time.perf_counter()`` (intervals) or
    ``time.monotonic()`` (deadlines). Legitimate wall-clock uses (event
    timestamps, dump filenames, checkpoint metadata) carry
    ``# zoolint: disable=wallclock-hotpath``."""

    id = "wallclock-hotpath"
    description = "wall-clock timing in a hot-path package"

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.is_hot_path:
            return
        for node in ctx.walk():
            if not isinstance(node, ast.Call):
                continue
            name = ctx.imports.resolve(node.func)
            if name in _WALLCLOCK:
                yield Finding(
                    self.id, ctx.path, node.lineno, node.col_offset,
                    f"{name}() in a hot-path package — use "
                    "time.perf_counter() for intervals or "
                    "time.monotonic() for deadlines")


@register
class HotpathHostSync(Rule):
    """Implicit host↔device sync inside a dispatch/drain/step loop.

    Flags ``.item()``, ``float(x)``, ``np.asarray``/``np.array``,
    ``device_get`` and un-sampled ``block_until_ready`` calls that sit
    lexically inside a loop of a hot-named function
    (dispatch/drain/serve/produce/finish/fetch/run/predict/fit/...)
    in a hot-path package. Each one forces the host to wait for the
    device per iteration — exactly what the bounded in-flight window
    exists to avoid. Fence off-loop, fetch via the pipeline's drain, or
    guard with a sampling predicate (an ``if`` mentioning
    ``*sample*``/``*prof*`` is recognized)."""

    id = "hotpath-host-sync"
    description = "implicit device sync inside a hot dispatch loop"

    def check_file(self, ctx: FileContext) -> Iterable[Finding]:
        if not ctx.is_hot_path:
            return
        for node in ctx.walk():
            if not isinstance(node, ast.Call):
                continue
            label = self._sync_label(ctx, node)
            if label is None:
                continue
            fn = _nearest_function(node)
            if fn is None or not (_fn_tokens(fn.name) & HOT_FN_TOKENS):
                continue
            loops = [lp for lp in _enclosing(node, _LOOPS)
                     if _nearest_function(lp) is fn]
            if not loops:
                continue
            if _sampling_guarded(node, fn):
                continue
            yield Finding(
                self.id, ctx.path, node.lineno, node.col_offset,
                f"{label} inside the `{fn.name}` loop forces a host sync "
                "per iteration — hoist it out of the loop, use the "
                "pipeline drain, or guard it with a sampling predicate")

    @staticmethod
    def _sync_label(ctx: FileContext, node: ast.Call):
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr == "item" \
                and not node.args and not node.keywords:
            return ".item()"
        name = ctx.imports.resolve(func)
        if name and (name.split(".")[-1] in _SYNC_TAILS
                     or name in _SYNC_CALLS):
            return f"{name}()"
        if name == "float" and len(node.args) == 1 \
                and not isinstance(node.args[0], ast.Constant):
            return "float(<non-literal>)"
        return None
