"""Interprocedural thread-ownership rules (zoolint v2).

Built on :class:`core.ProjectModel` — the project-wide call graph with
thread-root inference and runs-on propagation — so unlike the per-file
``engine-unlocked-write`` rule these see races that span modules: a
heartbeat thread in ``common/fleet.py`` reading an attribute the main
thread writes in ``serving/engine.py``, a module global mutated from the
shard pool, a non-daemon thread nobody joins.

A class may declare thread-confinement by contract in its docstring
("Not thread-safe", "thread-confined", "single-threaded"); its instance
attributes are then single-owner by design and never flagged — the
ownership report lists the class as confined-by-contract instead.
"""

from __future__ import annotations

from typing import Iterable

from analytics_zoo_tpu.analysis.core import (
    Finding, ProjectContext, Rule, register,
)


def _short(key: str) -> str:
    """module.Class.attr -> Class.attr, module.GLOBAL -> GLOBAL."""
    parts = key.split(".")
    return ".".join(parts[-2:]) if len(parts) >= 2 else key


@register
class CrossThreadUnlockedState(Rule):
    id = "cross-thread-unlocked-state"
    scope = "project"
    description = ("instance attr / module global written without a lock "
                   "while reachable from >=2 thread roots (interprocedural)")

    def check_project(self, pctx: ProjectContext) -> Iterable[Finding]:
        model = pctx.model()
        for key in sorted(model.state):
            owner_cls = model.classes.get(key.rsplit(".", 1)[0])
            if owner_cls is not None and owner_cls.confined_by_contract:
                continue
            roots = model.state_roots(key)
            if len(roots) < 2:
                continue
            kind = "instance attr" if owner_cls is not None \
                else "module global"
            for acc in model.state.get(key, ()):
                if not acc.write or model.effective_locked(acc):
                    continue
                if not model.runs_on.get(acc.func):
                    continue   # dead code — no root reaches the writer
                fn = model.functions[acc.func]
                yield Finding(
                    self.id, fn.ctx.path, acc.node.lineno,
                    acc.node.col_offset,
                    f"{kind} '{_short(key)}' is written here without a "
                    f"lock but is reachable from {len(roots)} thread "
                    f"roots ({', '.join(sorted(roots))}) — guard the "
                    f"write with a lock or confine the state to one "
                    f"thread")


@register
class ThreadLeak(Rule):
    id = "thread-leak"
    scope = "project"
    description = ("Thread.start() with neither daemon=True nor a "
                   "reachable join() — leaks on shutdown")

    def check_project(self, pctx: ProjectContext) -> Iterable[Finding]:
        model = pctx.model()
        for sp in model.spawns:
            if sp.kind != "thread" or sp.func.is_test:
                continue
            if sp.daemon or not sp.started or sp.joined or sp.escapes:
                continue
            what = sp.target.rsplit(".", 1)[-1] if sp.target else "target"
            yield Finding(
                self.id, sp.func.ctx.path, sp.node.lineno,
                sp.node.col_offset,
                f"thread running '{what}' is started with neither "
                f"daemon=True nor a reachable join() — it outlives its "
                f"owner and blocks interpreter shutdown; mark it daemon "
                f"or join it on the stop path")
